"""Time-domain tracing plane: structured host spans.

The repo can already tell you *what* happened (metrics vector,
histograms, conformance ledger, flight ring) but not *when* or where
wall time went: the ~17 ms/launch dispatch tax behind the ROADMAP's
streaming-serve-loop item exists only as hand-run PROFILE.md
experiments (findings 17-18).  This module is the instrument that
prices every round-trip continuously -- a thread-safe, ns-resolution
structured span tracer for **host-side** events:

- spans nest (per-thread stacks), carry one of the fixed
  :data:`CATEGORIES`, and record wall ``ts``/``dur`` from
  ``perf_counter_ns`` plus **self time** (duration minus child spans),
  so category sums attribute wall time without double counting;
- storage is a bounded in-memory ring (past the cap the oldest rows
  drop, counted) with per-(name, category) aggregates that survive the
  ring wrapping -- ``dispatch_ms_per_launch`` stays exact over a
  million-launch bench;
- export: JSONL (one row per span), Chrome trace-event / Perfetto JSON
  via ``obs.trace_export`` (loadable in ``chrome://tracing``), and an
  epoch-boundary ``drain_jsonl`` the supervisor flushes alongside its
  rotation checkpoints so the span stream survives a SIGKILL restart.

**Spans are host-side only, never in-graph**: a tracer observes wall
time around device launches; it cannot perturb a decision.  The
tracing-off path is a single ``None`` check per call site
(:func:`span` returns a shared no-op context manager), gated in CI to
bit-identical decisions and ~zero overhead.  See
``docs/OBSERVABILITY.md`` ("Tracing plane") for the schema and
category taxonomy.
"""

from __future__ import annotations

import json
import threading
import time as _walltime
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

# The fixed category taxonomy (docs/OBSERVABILITY.md).  Every span
# carries exactly one; the exporter validates against this set so a
# typo'd category fails in CI instead of silently fragmenting the
# attribution tables.  "compile" is the capacity plane's time axis
# (obs.compile_plane): one span per jit-cache lower+compile, so
# compile storms land on the same timeline as the launches they delay.
CATEGORIES = ("ingest", "host_prep", "dispatch", "device_compute",
              "fetch", "drain", "checkpoint", "retry", "compile")

# JSONL row schema (docs/OBSERVABILITY.md): ts/dur/self in ns from
# perf_counter_ns (monotonic within a process -- NOT comparable across
# restarts; the supervisor's drained stream is per-incarnation).
ROW_FIELDS = ("name", "cat", "ts", "dur", "self", "tid", "depth",
              "args")


class _NullSpan:
    """Shared no-op context manager: the entire tracing-off cost."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(tracer: Optional["SpanTracer"], name: str, cat: str, **args):
    """``with span(tracer, name, cat):`` -- a no-op when ``tracer`` is
    None, so call sites need no branching and the off path costs one
    function call + a None test."""
    if tracer is None:
        return _NULL_SPAN
    return tracer.span(name, cat, **args)


def instant(tracer: Optional["SpanTracer"], name: str, cat: str,
            **args) -> None:
    """Zero-duration event (a retry, a ladder step) -- no-op when
    ``tracer`` is None."""
    if tracer is not None:
        tracer.instant(name, cat, **args)


class _Span:
    """One open span; the context manager ``SpanTracer.span`` returns.
    Mutable slots only -- allocation per span is the on-path cost, and
    it is a few hundred ns."""

    __slots__ = ("_tr", "name", "cat", "args", "t0", "child_ns",
                 "depth")

    def __init__(self, tracer, name, cat, args):
        self._tr = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0
        self.child_ns = 0
        self.depth = 0

    def __enter__(self):
        self._tr._push(self)
        return self

    def __exit__(self, *exc):
        self._tr._pop(self)
        return False


class SpanTracer:
    """Thread-safe ns-resolution structured span tracer.

    ``limit`` bounds the in-memory ring (rows past it drop oldest
    first, counted in ``spans_dropped``); the per-(name, cat)
    aggregates and per-category self-time totals are unbounded and
    exact regardless of ring wrap.  ``clock_ns`` is injectable for
    deterministic tests.
    """

    def __init__(self, limit: int = 200_000,
                 clock_ns: Callable[[], int] =
                 _walltime.perf_counter_ns):
        self.limit = int(limit)
        self._clock = clock_ns
        self._mtx = threading.Lock()
        self._ring: deque = deque(maxlen=self.limit)
        self._local = threading.local()
        self.spans_recorded = 0
        self.spans_dropped = 0
        # spans lost to broken enter/exit discipline (a child left
        # open when its parent exited, a double __exit__): their rows
        # and time are NOT recorded, so the loss must at least be
        # countable
        self.spans_leaked = 0
        # per-category SELF time + span count: parents never double
        # count their children, so summing categories attributes wall
        # time exactly (the >=95%-of-wall acceptance gate's currency)
        self._cat_self: Dict[str, int] = {c: 0 for c in CATEGORIES}
        self._cat_count: Dict[str, int] = {c: 0 for c in CATEGORIES}
        # (name, cat) -> [count, total_ns, self_ns]
        self._agg: Dict[Tuple[str, str], List[int]] = {}
        # cat -> last span-end timestamp (watchdog stall detection)
        self._last_end: Dict[str, int] = {}
        # tid -> that thread's open-span stack, for cross-thread
        # in-flight reads (open_categories); registered once per
        # thread, so the hot path stays lock-free
        self._all_stacks: Dict[int, list] = {}

    # -- recording -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
            with self._mtx:
                self._all_stacks[threading.get_ident()] = st
        return st

    def span(self, name: str, cat: str, **args) -> _Span:
        # a real raise, not an assert: under PYTHONOPTIMIZE an assert
        # strips and a typo'd category would silently fragment the
        # attribution tables (the ProfileTimer double-start lesson)
        if cat not in CATEGORIES:
            raise ValueError(f"unknown span category {cat!r} "
                             f"(taxonomy: {CATEGORIES})")
        return _Span(self, name, cat, args or None)

    def _push(self, sp: _Span) -> None:
        st = self._stack()
        sp.depth = len(st)
        # t0 BEFORE the append: the cross-thread readers
        # (oldest_open_ns / open_categories) walk the stack lock-free,
        # and a span visible with t0 still 0 would read as infinitely
        # old -- defeating the watchdog's in-flight stall suppression
        # it exists to serve
        sp.t0 = self._clock()
        st.append(sp)

    def _pop(self, sp: _Span) -> None:
        end = self._clock()
        st = self._stack()
        if sp not in st:
            # double __exit__, or a child exiting after its parent
            # already popped through it: recording again would
            # duplicate (or fabricate) a row -- count the discipline
            # break instead of corrupting the stack
            with self._mtx:
                self.spans_leaked += 1
            return
        # tolerate exits out of order (a caller leaking an open child
        # while the parent exits): pop through to this span, counting
        # each leaked child -- their rows are lost, not silent
        leaked = 0
        while st[-1] is not sp:
            st.pop()
            leaked += 1
        st.pop()
        if leaked:
            with self._mtx:
                self.spans_leaked += leaked
        dur = end - sp.t0
        if st:
            st[-1].child_ns += dur
        self._record(sp.name, sp.cat, sp.t0, dur,
                     dur - sp.child_ns, sp.depth, sp.args)

    def instant(self, name: str, cat: str, **args) -> None:
        if cat not in CATEGORIES:
            raise ValueError(f"unknown span category {cat!r} "
                             f"(taxonomy: {CATEGORIES})")
        self._record(name, cat, self._clock(), 0, 0,
                     len(self._stack()), args or None)

    def _record(self, name, cat, ts, dur, self_ns, depth, args) -> None:
        row = {"name": name, "cat": cat, "ts": ts, "dur": dur,
               "self": self_ns, "tid": threading.get_ident(),
               "depth": depth, "args": args}
        with self._mtx:
            if len(self._ring) == self.limit:
                self.spans_dropped += 1
            self._ring.append(row)
            self.spans_recorded += 1
            self._cat_self[cat] = self._cat_self.get(cat, 0) + self_ns
            self._cat_count[cat] = self._cat_count.get(cat, 0) + 1
            a = self._agg.get((name, cat))
            if a is None:
                self._agg[(name, cat)] = [1, dur, self_ns]
            else:
                a[0] += 1
                a[1] += dur
                a[2] += self_ns
            self._last_end[cat] = ts + dur

    # -- reading -------------------------------------------------------
    def rows(self) -> List[dict]:
        """Snapshot of the ring (oldest first), without clearing."""
        with self._mtx:
            return list(self._ring)

    def drain(self) -> List[dict]:
        """Take everything currently in the ring and clear it -- the
        epoch-boundary flush primitive (aggregates are untouched)."""
        with self._mtx:
            rows = list(self._ring)
            self._ring.clear()
            return rows

    def category_totals(self) -> Dict[str, int]:
        """cat -> accumulated SELF time ns (copy)."""
        with self._mtx:
            return dict(self._cat_self)

    def category_counts(self) -> Dict[str, int]:
        with self._mtx:
            return dict(self._cat_count)

    def last_end_ns(self, cat: str) -> Optional[int]:
        """End timestamp of the most recent span in ``cat`` (watchdog
        stall detection); None before the first one closes."""
        with self._mtx:
            return self._last_end.get(cat)

    def _live_stacks(self):
        """Snapshot (tid, stack) pairs for LIVE threads, pruning dead
        threads' stacks as a side effect.  A thread that exited with
        spans still open is a discipline break: its orphans are folded
        into ``spans_leaked`` and its registry entry dropped, so they
        neither report as in-flight work forever (which would
        permanently blind the watchdog's stall check) nor pin the
        registry's memory under thread churn.  Best-effort snapshot:
        the stacks mutate lock-free on their owning threads, so a span
        entered/exited mid-walk may be missed or double-seen for one
        poll -- fine for a sampler."""
        with self._mtx:
            items = list(self._all_stacks.items())
        alive = {t.ident for t in threading.enumerate()}
        live = []
        dead = []
        for tid, st in items:
            if tid not in alive:
                dead.append((tid, len(tuple(st))))
            else:
                live.append((tid, st))
        if dead:
            with self._mtx:
                # ONE fresh alive snapshot under the lock (the
                # recording hot path contends on this mutex, so the
                # critical section must stay O(threads), not
                # O(dead x threads)): CPython reuses thread idents,
                # and a new thread may have re-registered a dead key
                # since the first snapshot
                alive2 = {t.ident for t in threading.enumerate()}
                for tid, leaked in dead:
                    if tid in self._all_stacks and tid not in alive2:
                        self._all_stacks.pop(tid)
                        self.spans_leaked += leaked
        return live

    def open_categories(self) -> Dict[str, int]:
        """cat -> number of spans currently OPEN across all threads --
        the watchdog's in-flight-dispatch awareness: a fused stream
        launch legitimately runs for seconds with no dispatch span
        COMPLETING, but the blocked ``device_wait`` span is open the
        whole time, and an open launch is not a stalled cadence."""
        out: Dict[str, int] = {}
        for _tid, st in self._live_stacks():
            for sp in tuple(st):
                out[sp.cat] = out.get(sp.cat, 0) + 1
        return out

    def oldest_open_ns(self, cats=("dispatch", "device_compute")
                       ) -> Optional[int]:
        """Start timestamp of the OLDEST currently-open span in
        ``cats`` across live threads (None when nothing is open) --
        what bounds the watchdog's in-flight stall suppression: an
        open launch suppresses the stall warning only while it is
        younger than the wedge threshold, so a launch the runtime
        wedged INSIDE still surfaces."""
        oldest = None
        for _tid, st in self._live_stacks():
            for sp in tuple(st):
                if sp.cat in cats and \
                        (oldest is None or sp.t0 < oldest):
                    oldest = sp.t0
        return oldest

    def name_stats(self) -> Dict[Tuple[str, str], Tuple[int, int, int]]:
        """(name, cat) -> (count, total_ns, self_ns); exact past ring
        wrap."""
        with self._mtx:
            return {k: tuple(v) for k, v in self._agg.items()}

    def summary(self) -> dict:
        """JSON-able rollup (what bench.py embeds per workload)."""
        with self._mtx:
            return {
                "spans": self.spans_recorded,
                "dropped": self.spans_dropped,
                "leaked": self.spans_leaked,
                "categories": {
                    c: {"count": self._cat_count.get(c, 0),
                        "self_ns": self._cat_self.get(c, 0)}
                    for c in CATEGORIES if self._cat_count.get(c, 0)},
                "by_name": {
                    f"{name}|{cat}": {"count": v[0], "total_ns": v[1],
                                      "self_ns": v[2]}
                    for (name, cat), v in self._agg.items()},
            }

    # -- export --------------------------------------------------------
    def export_jsonl(self, path: str) -> int:
        """Write every ring row as JSONL (the raw-span interchange
        format ``scripts/trace_report.py`` and ``trace_export``
        consume).  Returns the row count."""
        rows = self.rows()
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r, separators=(",", ":")) + "\n")
        return len(rows)

    def drain_jsonl(self, path: str) -> int:
        """APPEND the un-flushed rows to ``path`` and clear the ring --
        the supervisor calls this at every checkpoint boundary, so the
        span stream survives a SIGKILL restart with at most one
        epoch's spans lost (the same durability window as the PR-5
        rotation checkpoints)."""
        rows = self.drain()
        if not rows:
            return 0
        with open(path, "a") as fh:
            for r in rows:
                fh.write(json.dumps(r, separators=(",", ":")) + "\n")
            fh.flush()
        return len(rows)


def load_jsonl(path: str) -> List[dict]:
    """Read a span JSONL stream back (skips blank lines; raises
    ``ValueError`` on a malformed row)."""
    rows = []
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}")
            if not isinstance(row, dict) or "name" not in row \
                    or "ts" not in row:
                raise ValueError(f"{path}:{i}: not a span row")
            rows.append(row)
    return rows
