"""HBM flight recorder: the last R decision records, written in-graph.

A microsecond-scale scheduler cannot afford a host-side decision trace
in the data path (``obs.trace`` costs a JSONL row per decision on the
host), but postmortems need to know WHAT the engine was committing
right before a crash.  The flight recorder is the middle ground: a
fixed-size ring of the most recent R commit records living in device
memory, written by the epoch scans with dense scatter rows (no host
involvement), and drained by the host ONLY at epoch/checkpoint
boundaries -- ``jax.device_get`` stays off the hot path, and the ring
rides in the supervisor's rotation checkpoints so a SIGKILLed run's
resume replays it bit-identically (crash equivalence extends to
telemetry; ``robust.supervisor``).

Record granularity follows each engine's commit unit (the engines emit
sets, not per-decision streams):

- prefix epoch: one record per DECISION (client, phase-class, unified
  entry key, cost);
- chain epoch: one record per UNIT (cost column = the unit's decision
  count);
- calendar epoch: one record per CLIENT per BATCH (cost column = the
  client's committed decisions that batch).

Columns (int64): ``seq`` (monotone global record number -- drain
orders by it and wraparound is visible as a seq gap), ``batch`` (the
recording batch's global index), ``client`` (slot), ``cls`` (unified
class: 0 reservation / 1 weight / 2 limit-break), ``tag`` (unified
entry key), ``cost``, and -- since the provenance plane
(``obs.provenance``) -- ``margin`` (the record's winner margin over
the runner-up candidate, ns; -1 = no runner-up existed) and ``gate``
(how many clients sat queued but limit-blocked at the recording
batch's entry).  Unwritten rows carry seq -1.  With the three
provenance columns the ring is a true black box: each drained record
says not just WHAT committed but how contested the choice was and how
much demand the limit gate was holding back at that instant.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

FLIGHT_FIELDS = ("seq", "batch", "client", "cls", "tag", "cost",
                 "margin", "gate")
FLIGHT_COLS = len(FLIGHT_FIELDS)


class FlightState(NamedTuple):
    """The device-resident ring + its monotone cursors.  ``seq`` is
    the count of records ever written (the next record's number);
    ``batch`` counts live batches recorded.  The ring slot of record
    ``s`` is ``s % R``, so the buffer always holds the newest
    ``min(seq, R)`` records."""

    buf: jnp.ndarray    # int64[R, FLIGHT_COLS]; seq column -1 = empty
    seq: jnp.ndarray    # int64 scalar
    batch: jnp.ndarray  # int64 scalar


def flight_init(records: int) -> FlightState:
    """Fresh ring of ``records`` rows (the R knob; ~48 bytes/row)."""
    assert records >= 1, "the flight ring needs at least one row"
    buf = jnp.full((records, FLIGHT_COLS), jnp.int64(-1))
    return FlightState(buf=buf, seq=jnp.int64(0), batch=jnp.int64(0))


def flight_record(fl: FlightState, slot, cls, tag, cost,
                  live=True, margin=None, gate=None) -> FlightState:
    """Append one batch's commit records in-graph.

    ``slot`` (int32[k], -1 = no record) selects the valid rows --
    callers pass the engines' already-masked outputs, so a gated
    (tag32-dead) batch whose slots are all -1 writes nothing.
    Validity need not be a contiguous prefix (the calendar engine's
    dense per-client mask is scattered); ranks come from a cumsum.
    When one batch carries more than R records only the NEWEST R are
    materialized (deterministically -- duplicate ring indices never
    reach the scatter), but ``seq`` still advances by the full count,
    so the drop is visible as a seq gap.

    ``margin`` (int64[k]; -1 = no runner-up) and ``gate`` (scalar:
    limit-gated client count at batch entry) are the provenance
    columns (``obs.provenance``); callers without them write -1 / 0."""
    r = fl.buf.shape[0]
    slot = jnp.asarray(slot)
    live = jnp.asarray(live, dtype=bool)
    mask = (slot >= 0) & live
    rank = jnp.cumsum(mask.astype(jnp.int64)) - 1
    total = jnp.sum(mask.astype(jnp.int64))
    keep = mask & (rank >= total - r)
    idx = jnp.where(keep, (fl.seq + rank) % r, r).astype(jnp.int32)
    margin = jnp.full(slot.shape, jnp.int64(-1)) if margin is None \
        else jnp.asarray(margin, dtype=jnp.int64)
    gate = jnp.int64(0) if gate is None \
        else jnp.asarray(gate, dtype=jnp.int64)
    rows = jnp.stack([
        fl.seq + rank,
        jnp.broadcast_to(fl.batch, slot.shape),
        slot.astype(jnp.int64),
        jnp.asarray(cls, dtype=jnp.int64),
        jnp.asarray(tag, dtype=jnp.int64),
        jnp.asarray(cost, dtype=jnp.int64),
        jnp.broadcast_to(margin, slot.shape),
        jnp.broadcast_to(gate, slot.shape),
    ], axis=1)
    buf = fl.buf.at[idx].set(rows, mode="drop")
    return FlightState(buf=buf, seq=fl.seq + total,
                       batch=fl.batch + live.astype(jnp.int64))


def _ring_rows(buf2d: np.ndarray) -> np.ndarray:
    """ONE ring's valid rows in seq order (oldest -> newest) -- the
    single drain selection every entry point (single drain, stacked
    merge, stacked dump) builds on, so the validity sentinel / order
    rule cannot drift between them."""
    buf2d = np.asarray(buf2d, dtype=np.int64)
    rows = buf2d[buf2d[:, 0] >= 0]
    return rows[np.argsort(rows[:, 0], kind="stable")]


def _write_jsonl(records: list, path: str) -> int:
    import json

    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    return len(records)


def flight_drain(fl: FlightState) -> list:
    """Host drain: ONE ``device_get`` of the ring, decoded into dict
    records ordered oldest -> newest.  Call only at epoch/checkpoint
    boundaries -- this is the async seam that keeps the recorder off
    the hot path."""
    import jax

    buf = jax.device_get(fl.buf)
    return [dict(zip(FLIGHT_FIELDS, (int(x) for x in row)))
            for row in _ring_rows(buf)]


def flight_dump(fl: FlightState, path: str) -> int:
    """Drain the ring to a JSONL file (the supervisor's --flight-dump
    crash hook); returns the record count.  Telemetry must never kill
    what it observes -- callers wrap this in a best-effort guard."""
    return _write_jsonl(flight_drain(fl), path)


def flight_merge_stacked(fl: FlightState):
    """Deterministic SHARD-ORDER merge of a mesh job's stacked
    per-shard rings (``buf`` int64[S, R, COLS], ``seq`` int64[S]):
    each shard's valid rows ordered by its own seq column, shards
    concatenated 0..S-1.  Returns ``(rows int64[V, COLS], total_seq
    int)``.  Per-shard seq counters are independent (each ring is its
    own black box); the shard-major order is the one deterministic
    interleave that needs no cross-shard clock, which is what lets
    the crash-equivalence gate compare merged rings bit-for-bit."""
    import jax

    buf = np.asarray(jax.device_get(fl.buf), dtype=np.int64)
    seq = np.asarray(jax.device_get(fl.seq), dtype=np.int64)
    assert buf.ndim == 3, f"expected stacked [S, R, COLS], {buf.shape}"
    parts = [_ring_rows(buf[s]) for s in range(buf.shape[0])]
    merged = np.concatenate(parts, axis=0) if parts else \
        np.zeros((0, FLIGHT_COLS), dtype=np.int64)
    return merged, int(seq.sum())


def flight_drain_stacked(fl: FlightState) -> list:
    """Host drain of a stacked per-shard ring: dict records with a
    ``shard`` key added, in the :func:`flight_merge_stacked` order --
    the mesh job's ``--flight-dump`` crash-hook format."""
    import jax

    buf = np.asarray(jax.device_get(fl.buf), dtype=np.int64)
    out = []
    for s in range(buf.shape[0]):
        for row in _ring_rows(buf[s]):
            rec = dict(zip(FLIGHT_FIELDS, (int(x) for x in row)))
            rec["shard"] = s
            out.append(rec)
    return out


def flight_dump_any(fl: FlightState, path: str) -> int:
    """:func:`flight_dump` that accepts single OR stacked rings (the
    supervisor's one crash-hook entry point)."""
    import jax

    if np.asarray(jax.device_get(fl.buf)).ndim == 3:
        return _write_jsonl(flight_drain_stacked(fl), path)
    return flight_dump(fl, path)


def flight_from_arrays(buf, seq, batch) -> FlightState:
    """Rebuild a FlightState from checkpointed numpy leaves
    (``robust.supervisor`` payload round-trip)."""
    return FlightState(buf=jnp.asarray(buf, dtype=jnp.int64),
                       seq=jnp.asarray(seq, dtype=jnp.int64),
                       batch=jnp.asarray(batch, dtype=jnp.int64))
