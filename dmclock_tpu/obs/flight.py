"""HBM flight recorder: the last R decision records, written in-graph.

A microsecond-scale scheduler cannot afford a host-side decision trace
in the data path (``obs.trace`` costs a JSONL row per decision on the
host), but postmortems need to know WHAT the engine was committing
right before a crash.  The flight recorder is the middle ground: a
fixed-size ring of the most recent R commit records living in device
memory, written by the epoch scans with dense scatter rows (no host
involvement), and drained by the host ONLY at epoch/checkpoint
boundaries -- ``jax.device_get`` stays off the hot path, and the ring
rides in the supervisor's rotation checkpoints so a SIGKILLed run's
resume replays it bit-identically (crash equivalence extends to
telemetry; ``robust.supervisor``).

Record granularity follows each engine's commit unit (the engines emit
sets, not per-decision streams):

- prefix epoch: one record per DECISION (client, phase-class, unified
  entry key, cost);
- chain epoch: one record per UNIT (cost column = the unit's decision
  count);
- calendar epoch: one record per CLIENT per BATCH (cost column = the
  client's committed decisions that batch).

Columns (int64): ``seq`` (monotone global record number -- drain
orders by it and wraparound is visible as a seq gap), ``batch`` (the
recording batch's global index), ``client`` (slot), ``cls`` (unified
class: 0 reservation / 1 weight / 2 limit-break), ``tag`` (unified
entry key), ``cost``, and -- since the provenance plane
(``obs.provenance``) -- ``margin`` (the record's winner margin over
the runner-up candidate, ns; -1 = no runner-up existed) and ``gate``
(how many clients sat queued but limit-blocked at the recording
batch's entry).  Unwritten rows carry seq -1.  With the three
provenance columns the ring is a true black box: each drained record
says not just WHAT committed but how contested the choice was and how
much demand the limit gate was holding back at that instant.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

FLIGHT_FIELDS = ("seq", "batch", "client", "cls", "tag", "cost",
                 "margin", "gate")
FLIGHT_COLS = len(FLIGHT_FIELDS)


class FlightState(NamedTuple):
    """The device-resident ring + its monotone cursors.  ``seq`` is
    the count of records ever written (the next record's number);
    ``batch`` counts live batches recorded.  The ring slot of record
    ``s`` is ``s % R``, so the buffer always holds the newest
    ``min(seq, R)`` records."""

    buf: jnp.ndarray    # int64[R, FLIGHT_COLS]; seq column -1 = empty
    seq: jnp.ndarray    # int64 scalar
    batch: jnp.ndarray  # int64 scalar


def flight_init(records: int) -> FlightState:
    """Fresh ring of ``records`` rows (the R knob; ~48 bytes/row)."""
    assert records >= 1, "the flight ring needs at least one row"
    buf = jnp.full((records, FLIGHT_COLS), jnp.int64(-1))
    return FlightState(buf=buf, seq=jnp.int64(0), batch=jnp.int64(0))


def flight_record(fl: FlightState, slot, cls, tag, cost,
                  live=True, margin=None, gate=None) -> FlightState:
    """Append one batch's commit records in-graph.

    ``slot`` (int32[k], -1 = no record) selects the valid rows --
    callers pass the engines' already-masked outputs, so a gated
    (tag32-dead) batch whose slots are all -1 writes nothing.
    Validity need not be a contiguous prefix (the calendar engine's
    dense per-client mask is scattered); ranks come from a cumsum.
    When one batch carries more than R records only the NEWEST R are
    materialized (deterministically -- duplicate ring indices never
    reach the scatter), but ``seq`` still advances by the full count,
    so the drop is visible as a seq gap.

    ``margin`` (int64[k]; -1 = no runner-up) and ``gate`` (scalar:
    limit-gated client count at batch entry) are the provenance
    columns (``obs.provenance``); callers without them write -1 / 0."""
    r = fl.buf.shape[0]
    slot = jnp.asarray(slot)
    live = jnp.asarray(live, dtype=bool)
    mask = (slot >= 0) & live
    rank = jnp.cumsum(mask.astype(jnp.int64)) - 1
    total = jnp.sum(mask.astype(jnp.int64))
    keep = mask & (rank >= total - r)
    idx = jnp.where(keep, (fl.seq + rank) % r, r).astype(jnp.int32)
    margin = jnp.full(slot.shape, jnp.int64(-1)) if margin is None \
        else jnp.asarray(margin, dtype=jnp.int64)
    gate = jnp.int64(0) if gate is None \
        else jnp.asarray(gate, dtype=jnp.int64)
    rows = jnp.stack([
        fl.seq + rank,
        jnp.broadcast_to(fl.batch, slot.shape),
        slot.astype(jnp.int64),
        jnp.asarray(cls, dtype=jnp.int64),
        jnp.asarray(tag, dtype=jnp.int64),
        jnp.asarray(cost, dtype=jnp.int64),
        jnp.broadcast_to(margin, slot.shape),
        jnp.broadcast_to(gate, slot.shape),
    ], axis=1)
    buf = fl.buf.at[idx].set(rows, mode="drop")
    return FlightState(buf=buf, seq=fl.seq + total,
                       batch=fl.batch + live.astype(jnp.int64))


def flight_drain(fl: FlightState) -> list:
    """Host drain: ONE ``device_get`` of the ring, decoded into dict
    records ordered oldest -> newest.  Call only at epoch/checkpoint
    boundaries -- this is the async seam that keeps the recorder off
    the hot path."""
    import jax

    buf, seq = jax.device_get((fl.buf, fl.seq))
    buf = np.asarray(buf, dtype=np.int64)
    valid = buf[:, 0] >= 0
    rows = buf[valid]
    rows = rows[np.argsort(rows[:, 0], kind="stable")]
    out = [dict(zip(FLIGHT_FIELDS, (int(x) for x in row)))
           for row in rows]
    return out


def flight_dump(fl: FlightState, path: str) -> int:
    """Drain the ring to a JSONL file (the supervisor's --flight-dump
    crash hook); returns the record count.  Telemetry must never kill
    what it observes -- callers wrap this in a best-effort guard."""
    import json

    records = flight_drain(fl)
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    return len(records)


def flight_from_arrays(buf, seq, batch) -> FlightState:
    """Rebuild a FlightState from checkpointed numpy leaves
    (``robust.supervisor`` payload round-trip)."""
    return FlightState(buf=jnp.asarray(buf, dtype=jnp.int64),
                       seq=jnp.asarray(seq, dtype=jnp.int64),
                       batch=jnp.asarray(batch, dtype=jnp.int64))
