"""Compile/retrace observatory: the capacity plane's time axis.

Every hot launch path in the repo routes through a MODULE-LEVEL jit
cache (the ``engine/queue.py`` ``_JIT_CACHE`` convention), because a
re-trace costs seconds of host time and a re-compile on the remote
Mosaic compiler has been measured north of 15 minutes (PROFILE.md) --
a retrace STORM is a silicon-session-killing failure mode that today
is invisible until the wall clock is already gone.  This module makes
every one of those caches observable:

- :func:`instrumented_jit` wraps ``jax.jit`` for a cache entry.  It
  keeps a per-argument-signature map of AOT-compiled executables
  (``fn.lower(...).compile()``), so the FIRST call for each signature
  is where lowering and compilation happen -- timed separately,
  recorded per entry, and attributed: a second signature arriving at
  an existing entry is a **retrace**, recorded together with the
  leaf-level arg-signature diff that caused it.
- Each compile also captures ``Compiled.cost_analysis()`` (flops /
  bytes accessed -- the roofline attributor's numerator) and
  ``Compiled.memory_analysis()`` (argument / output / temp /
  generated-code HBM bytes -- what the static ledger in
  ``obs.capacity`` is validated against).  Both are advisory on
  XLA:CPU (PROFILE.md); the TPU session is the real record.
- Records export three ways: ``plane().snapshot()`` (JSON-able),
  ``publish_compile_metrics`` (``dmclock_compile_*`` Prometheus
  families), and -- when a tracer is attached via ``set_tracer`` --
  one ``compile``-category span per lower+compile into the PR-7 span
  stream, so compile time lands on the same timeline as the launches
  it delays and rides the supervisor's ``span_log`` checkpoint-
  boundary flush (the rotation checkpoints' durability window).

**The plane cannot perturb a decision**: the wrapped executable is the
exact program ``jax.jit`` would have dispatched (same trace, same
donation), and with the plane disabled (``enable(False)`` or
``DMCLOCK_COMPILE_PLANE=0``) calls route through the plain ``jax.jit``
path untouched.  Decisions are bit-identical either way (ci.sh
capacity smoke).  If a compiled executable rejects a call our
signature considered equal (an aval aspect the signature cannot see,
e.g. an exotic sharding), the wrapper permanently routes that
signature through the plain jit path and counts the miss -- telemetry
must never kill the launch it observes.
"""

from __future__ import annotations

import os
import threading
import time as _walltime
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from .spans import span as _span

# every live InstrumentedJit, so clear_compiled() can drop the held
# executables alongside jax.clear_caches() (the test suite's
# between-modules compile-state relief must reach them too)
_ALL_WRAPPERS: "weakref.WeakSet" = weakref.WeakSet()


def clear_compiled() -> None:
    """Drop every wrapper's held AOT executables (records are kept).
    Call next to ``jax.clear_caches()`` when shedding compile state --
    the next call per signature re-lowers and re-compiles, recorded as
    a retrace."""
    for w in list(_ALL_WRAPPERS):
        w.clear_compiled()

# one retrace event ring entry per (re)trace, what the watchdog's
# retrace-storm check windows over
_RETRACE_RING = 1024
# how many leaf-level diffs a retrace record keeps (arg pytrees can
# have hundreds of leaves; the first few changed ones name the cause)
_DIFF_LIMIT = 8
_ENTRY_STR_LIMIT = 160


def _entry_str(entry: Any) -> str:
    s = repr(entry)
    return s if len(s) <= _ENTRY_STR_LIMIT else \
        s[:_ENTRY_STR_LIMIT - 3] + "..."


_PY_SCALARS = (bool, int, float, complex)


def _leaf_spec(leaf):
    """Hashable per-leaf signature matching jax's retrace rule closely
    enough: arrays key by (shape, dtype, weak_type) -- values never
    retrace; python scalars key by TYPE only (jax traces them weakly,
    so 3 and 4 share one executable); anything else by repr.  Dtype
    OBJECTS, not strings -- str(dtype) per leaf per call was the
    dominant per-call cost."""
    if isinstance(leaf, _PY_SCALARS):
        return type(leaf)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), dtype,
                getattr(leaf, "weak_type", False))
    return ("obj", repr(leaf))


def _leaf_spec_readable(leaf) -> tuple:
    """The human-facing form for retrace diffs (compile-time only)."""
    if isinstance(leaf, _PY_SCALARS):
        return ("py", type(leaf).__name__)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype),
                bool(getattr(leaf, "weak_type", False)))
    return ("obj", repr(leaf))


def _signature(args, kwargs) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_spec(x) for x in leaves))


def _signature_or_none(args, kwargs):
    """One pass over the flattened args: the hashable signature, or
    None when a leaf is a tracer (this jit is inlining inside an outer
    trace -- route to the plain jit path)."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    tr = jax.core.Tracer
    specs = []
    for leaf in leaves:
        if isinstance(leaf, tr):
            return None
        specs.append(_leaf_spec(leaf))
    return (treedef, tuple(specs))


def _path_specs(args, kwargs) -> Dict[str, tuple]:
    """Leaf path -> spec, for the retrace diff (computed only when a
    compile actually happens -- never on the per-call hot path)."""
    out = {}
    try:
        flat, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
        for path, leaf in flat:
            out[jax.tree_util.keystr(path)] = \
                _leaf_spec_readable(leaf)
    except Exception:      # ancient jax without path flattening
        leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
        for i, leaf in enumerate(leaves):
            out[f"[{i}]"] = _leaf_spec_readable(leaf)
    return out


def _sig_diff(old: Dict[str, tuple], new: Dict[str, tuple]
              ) -> List[str]:
    """Human-readable leaf diffs between two path-spec maps: exactly
    what changed shape/dtype/type to cause the retrace."""
    diffs = []
    for path in new:
        if path not in old:
            diffs.append(f"{path}: added {new[path]}")
        elif old[path] != new[path]:
            diffs.append(f"{path}: {old[path]} -> {new[path]}")
    for path in old:
        if path not in new:
            diffs.append(f"{path}: removed (was {old[path]})")
    return diffs[:_DIFF_LIMIT]


def normalize_cost_analysis(ca) -> Dict[str, float]:
    """ONE normalization of a raw ``cost_analysis()`` value
    (list-of-dicts on some backends, dict on others) -- shared by the
    plane's records and ``bench.epoch_cost_analysis`` so the bench row
    and the compile record can never disagree on the same program."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out = {}
    for key in ("flops", "bytes accessed", "transcendentals"):
        if key in (ca or {}):
            out[key.replace(" ", "_")] = float(ca[key])
    return out


def cost_analysis_dict(compiled) -> Dict[str, float]:
    """Normalized flops/bytes from ``Compiled.cost_analysis()`` --
    degrade to empty, never raise (callers that want the error note
    catch around ``compiled.cost_analysis()`` themselves and
    normalize with :func:`normalize_cost_analysis`)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    return normalize_cost_analysis(ca)


def memory_analysis_dict(compiled) -> Dict[str, int]:
    """The HBM footprint breakdown from
    ``Compiled.memory_analysis()``: argument / output / temp /
    generated-code / aliased bytes.  ``total_bytes`` is the resident
    peak estimate (alias overlap -- donated outputs sharing argument
    buffers -- subtracted once).  Empty when the backend cannot
    report."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for name, key in (("argument_size_in_bytes", "argument_bytes"),
                      ("output_size_in_bytes", "output_bytes"),
                      ("temp_size_in_bytes", "temp_bytes"),
                      ("generated_code_size_in_bytes", "code_bytes"),
                      ("alias_size_in_bytes", "alias_bytes")):
        v = getattr(ma, name, None)
        if v is not None:
            out[key] = int(v)
    if out:
        out["total_bytes"] = (out.get("argument_bytes", 0)
                              + out.get("output_bytes", 0)
                              + out.get("temp_bytes", 0)
                              + out.get("code_bytes", 0)
                              - out.get("alias_bytes", 0))
    return out


class _EntryStats:
    """Aggregate compile record of ONE cache entry (one static
    configuration): how many times it lowered+compiled, how long that
    took, what the latest executable's cost/memory analyses said, and
    the signature diff behind the most recent retrace."""

    __slots__ = ("cache", "entry", "compiles", "retraces",
                 "lower_ns", "compile_ns", "cost", "hbm",
                 "path_specs", "last_diff", "dispatch_fallbacks")

    def __init__(self, cache: str, entry: str):
        self.cache = cache
        self.entry = entry
        self.compiles = 0
        self.retraces = 0
        self.lower_ns = 0
        self.compile_ns = 0
        self.cost: Dict[str, float] = {}
        self.hbm: Dict[str, int] = {}
        self.path_specs: Optional[Dict[str, tuple]] = None
        self.last_diff: List[str] = []
        self.dispatch_fallbacks = 0

    def to_dict(self) -> dict:
        return {"cache": self.cache, "entry": self.entry,
                "compiles": self.compiles, "retraces": self.retraces,
                "lower_ms": self.lower_ns / 1e6,
                "compile_ms": self.compile_ns / 1e6,
                "cost_analysis": dict(self.cost),
                "memory_analysis": dict(self.hbm),
                "last_retrace_diff": list(self.last_diff),
                "dispatch_fallbacks": self.dispatch_fallbacks}


class CompilePlane:
    """Process-wide compile/retrace ledger.  ``clock_ns`` is
    injectable for deterministic watchdog tests (same clock domain as
    the watchdog's)."""

    def __init__(self, clock_ns: Callable[[], int] =
                 _walltime.perf_counter_ns):
        self._mtx = threading.Lock()
        self.clock_ns = clock_ns
        self.enabled = os.environ.get(
            "DMCLOCK_COMPILE_PLANE", "1").lower() not in (
                "0", "off", "false")
        self._tracer_ref = None     # weakref to a SpanTracer, or None
        self._entries: Dict[Tuple[str, str], _EntryStats] = {}
        self._retraces: deque = deque(maxlen=_RETRACE_RING)

    # -- control -------------------------------------------------------
    def enable(self, on: bool) -> "CompilePlane":
        self.enabled = bool(on)
        return self

    def set_tracer(self, tracer) -> None:
        """Route future compiles into ``tracer`` as ``compile``-category
        spans (the PR-7 span stream; None detaches).  Held WEAKLY: the
        plane is process-global while tracers are per-incarnation
        (supervisor) or per-run (bench), and a strong reference would
        pin a dead job's tracer -- and its span ring -- forever, with
        later compiles appended to a stream nobody drains."""
        self._tracer_ref = None if tracer is None \
            else weakref.ref(tracer)

    @property
    def tracer(self):
        if self._tracer_ref is None:
            return None
        return self._tracer_ref()   # None once the owner dropped it

    def reset(self) -> None:
        with self._mtx:
            self._entries.clear()
            self._retraces.clear()

    # -- recording -----------------------------------------------------
    def _entry(self, cache: str, entry: str) -> _EntryStats:
        key = (cache, entry)
        e = self._entries.get(key)
        if e is None:
            e = self._entries[key] = _EntryStats(cache, entry)
        return e

    def record_compile(self, cache: str, entry: str, *,
                       lower_ns: int, compile_ns: int,
                       cost: Dict[str, float], hbm: Dict[str, int],
                       path_specs: Optional[Dict[str, tuple]] = None
                       ) -> dict:
        """Fold one lower+compile into the entry's record; returns the
        span-args payload (retrace flag + diff included) so the caller
        can attach it to the compile span it just closed."""
        with self._mtx:
            e = self._entry(cache, entry)
            retrace = e.compiles > 0
            diff: List[str] = []
            if retrace:
                e.retraces += 1
                if e.path_specs is not None and path_specs is not None:
                    diff = _sig_diff(e.path_specs, path_specs)
                e.last_diff = diff
                self._retraces.append((self.clock_ns(),
                                       f"{cache}:{entry}"))
            e.compiles += 1
            e.lower_ns += int(lower_ns)
            e.compile_ns += int(compile_ns)
            if cost:
                e.cost = dict(cost)
            if hbm:
                e.hbm = dict(hbm)
            if path_specs is not None:
                e.path_specs = path_specs
        out = {"cache": cache, "entry": entry, "retrace": retrace,
               "lower_ms": lower_ns / 1e6, "compile_ms": compile_ns / 1e6}
        if cost.get("flops") is not None:
            out["flops"] = cost["flops"]
        if cost.get("bytes_accessed") is not None:
            out["bytes_accessed"] = cost["bytes_accessed"]
        if hbm.get("total_bytes") is not None:
            out["hbm_total_bytes"] = hbm["total_bytes"]
        if diff:
            out["sig_diff"] = diff
        return out

    def note_dispatch_fallback(self, cache: str, entry: str) -> None:
        with self._mtx:
            self._entry(cache, entry).dispatch_fallbacks += 1

    # -- reading -------------------------------------------------------
    def entries(self) -> List[dict]:
        with self._mtx:
            return [e.to_dict() for e in self._entries.values()]

    def totals(self) -> dict:
        with self._mtx:
            es = list(self._entries.values())
            return {
                "entries": len(es),
                "compiles": sum(e.compiles for e in es),
                "retraces": sum(e.retraces for e in es),
                "lower_ms_total": sum(e.lower_ns for e in es) / 1e6,
                "compile_ms_total":
                    sum(e.compile_ns for e in es) / 1e6,
                "dispatch_fallbacks":
                    sum(e.dispatch_fallbacks for e in es),
            }

    def snapshot(self) -> dict:
        """JSON-able full record (what ``scripts/capacity_report.py``
        and the bench JSON line derive from)."""
        return {"totals": self.totals(), "entries": self.entries()}

    def retrace_events(self) -> List[Tuple[int, str]]:
        """(clock_ns, "cache:entry") per retrace, newest-bounded --
        the watchdog's retrace-storm feed."""
        with self._mtx:
            return list(self._retraces)


_PLANE = CompilePlane()


def plane() -> CompilePlane:
    """The process-wide compile plane (module caches all record
    here)."""
    return _PLANE


def set_tracer(tracer) -> None:
    _PLANE.set_tracer(tracer)


def _timed_compile(pl: CompilePlane, cache: str, entry: str,
                   jitted, args, kwargs):
    """One timed lower+compile with full attribution: the shared leg
    of :class:`InstrumentedJit` and :func:`aot_record`."""
    with _span(pl.tracer, f"compile.{cache}", "compile"):
        t0 = pl.clock_ns()
        lowered = jitted.lower(*args, **kwargs)
        t1 = pl.clock_ns()
        compiled = lowered.compile()
        t2 = pl.clock_ns()
    rec = pl.record_compile(
        cache, entry, lower_ns=t1 - t0, compile_ns=t2 - t1,
        cost=cost_analysis_dict(compiled),
        hbm=memory_analysis_dict(compiled),
        path_specs=_path_specs(args, kwargs))
    if pl.tracer is not None:
        # one instant carrying the full record payload next to the
        # span (spans close before the record exists; the instant IS
        # the compile record on the timeline)
        pl.tracer.instant(f"compile.{cache}.record", "compile", **rec)
    return compiled


# sentinel: signatures whose AOT executable rejected a call route
# through the plain jit dispatch path forever after
_DISPATCH = object()


class InstrumentedJit:
    """``jax.jit(fn)`` plus the compile observatory.  Drop-in for the
    module jit caches: calling it dispatches the identical compiled
    program; the first call per argument signature is where lowering
    and compilation happen (timed + recorded), and a second signature
    on the same entry is recorded as a retrace with its diff."""

    __slots__ = ("_fn", "_cache", "_entry", "_jit", "_compiled",
                 "_mtx", "__weakref__")

    def __init__(self, fn, *, cache: str, entry: Any, **jit_kwargs):
        self._fn = fn
        self._cache = cache
        self._entry = _entry_str(entry)
        self._jit = jax.jit(fn, **jit_kwargs)
        self._compiled: Dict[tuple, Any] = {}
        self._mtx = threading.Lock()
        _ALL_WRAPPERS.add(self)

    def clear_compiled(self) -> None:
        with self._mtx:
            self._compiled.clear()

    def __call__(self, *args, **kwargs):
        pl = _PLANE
        if not pl.enabled:
            # plane off -> the byte-identical plain path
            return self._jit(*args, **kwargs)
        sig = _signature_or_none(args, kwargs)
        if sig is None:    # tracer args: this jit is inlining inside
            return self._jit(*args, **kwargs)   # an outer trace
        # lock-free read: dict get is GIL-atomic, writes stay locked
        comp = self._compiled.get(sig)
        if comp is None:
            with self._mtx:
                comp = self._compiled.get(sig)
                if comp is None:
                    comp = _timed_compile(pl, self._cache, self._entry,
                                          self._jit, args, kwargs)
                    self._compiled[sig] = comp
        if comp is _DISPATCH:
            return self._jit(*args, **kwargs)
        try:
            return comp(*args, **kwargs)
        except (TypeError, ValueError) as e:
            # an aval aspect the signature cannot see (layout,
            # sharding): this signature routes through plain jit
            # dispatch from now on.  TypeError covers the classic
            # aval mismatch; newer jax raises ValueError for a
            # committed-sharding mismatch (e.g. a mesh-placed array
            # calling an executable compiled for a single device --
            # the mesh fallback path's shape).  Both are raised
            # BEFORE execution/donation, so the re-dispatch is safe;
            # any OTHER ValueError surfaces unchanged.
            if isinstance(e, ValueError) and \
                    "sharding" not in str(e) and \
                    "layout" not in str(e):
                raise
            with self._mtx:
                self._compiled[sig] = _DISPATCH
            pl.note_dispatch_fallback(self._cache, self._entry)
            return self._jit(*args, **kwargs)

    # the underlying jit, for callers that need .lower() etc.
    @property
    def jitted(self):
        return self._jit


def instrumented_jit(fn, *, cache: str, entry: Any,
                     **jit_kwargs) -> InstrumentedJit:
    """The module-jit-cache building block:
    ``_CACHE[key] = instrumented_jit(fn, cache="queue", entry=key)``
    replaces ``_CACHE[key] = jax.jit(fn)`` everywhere (docs/
    OBSERVABILITY.md "Capacity plane")."""
    return InstrumentedJit(fn, cache=cache, entry=entry, **jit_kwargs)


def aot_record(cache: str, entry: Any, jitted, *args, **kwargs):
    """Timed+recorded twin of the bench's AOT discipline
    ``jax.jit(fn).lower(*args).compile()``: same Compiled handle back,
    with the lower/compile walls, cost_analysis, and memory_analysis
    folded into the plane under ``(cache, entry)``."""
    pl = _PLANE
    if not pl.enabled:
        return jitted.lower(*args, **kwargs).compile()
    return _timed_compile(pl, cache, _entry_str(entry), jitted,
                          args, kwargs)


def publish_compile_metrics(registry, pl: Optional[CompilePlane] = None
                            ) -> None:
    """Drain the plane into a registry as ``dmclock_compile_*``
    families: process totals plus per-cache-family rollups (labelled
    ``{cache=...}``; per-ENTRY labels would explode cardinality)."""
    pl = pl or _PLANE
    t = pl.totals()
    rows = (
        ("dmclock_compile_events_total", "lower+compile events "
         "recorded by the compile plane (docs/OBSERVABILITY.md "
         "capacity plane)", t["compiles"]),
        ("dmclock_compile_retraces_total", "cache entries re-traced "
         "by a changed argument signature", t["retraces"]),
        ("dmclock_compile_ms_total", "total XLA compile wall (ms)",
         t["compile_ms_total"]),
        ("dmclock_compile_lower_ms_total", "total jaxpr lowering "
         "wall (ms)", t["lower_ms_total"]),
        ("dmclock_compile_cache_entries", "live instrumented jit "
         "cache entries", t["entries"]),
    )
    for name, help_text, v in rows:
        registry.gauge(name, help_text).set(float(v))
    by_cache: Dict[str, dict] = {}
    for e in pl.entries():
        acc = by_cache.setdefault(e["cache"], {
            "compile_ms": 0.0, "retraces": 0, "flops": 0.0,
            "bytes_accessed": 0.0, "hbm_total_bytes": 0})
        acc["compile_ms"] += e["compile_ms"]
        acc["retraces"] += e["retraces"]
        acc["flops"] += e["cost_analysis"].get("flops", 0.0)
        acc["bytes_accessed"] += \
            e["cost_analysis"].get("bytes_accessed", 0.0)
        acc["hbm_total_bytes"] += \
            e["memory_analysis"].get("total_bytes", 0)
    for cache, acc in by_cache.items():
        lbl = {"cache": cache}
        registry.gauge("dmclock_compile_ms_total", "", labels=lbl) \
            .set(acc["compile_ms"])
        registry.gauge("dmclock_compile_retraces_total", "",
                       labels=lbl).set(acc["retraces"])
        registry.gauge(
            "dmclock_compile_flops", "XLA cost_analysis flops, summed "
            "over the cache family's latest executables (advisory on "
            "XLA:CPU)", labels=lbl).set(acc["flops"])
        registry.gauge(
            "dmclock_compile_bytes_accessed", "XLA cost_analysis "
            "bytes accessed (advisory on XLA:CPU)",
            labels=lbl).set(acc["bytes_accessed"])
        registry.gauge(
            "dmclock_compile_hbm_bytes", "XLA memory_analysis "
            "resident total (args+outputs+temps+code-aliased)",
            labels=lbl).set(acc["hbm_total_bytes"])
