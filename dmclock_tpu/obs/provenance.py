"""Decision provenance plane: device-resident "why" records.

The planes shipped so far answer *what* a run did -- counts
(``obs.device``), tails (``obs.histograms``), wall time
(``obs.spans``), conformance (``obs.slo``), cost (``obs.capacity``) --
but when the SLO plane flags a client's window as violating, nothing
can say *why*: was the client limit-capped, out-competed on
proportional tags, or starved behind tardy reservations?  The mClock
algorithm's whole identity is the per-decision phase choice
(reservation -> ready -> weight -> limit-break, reference
do_next_request :1115-1186), and the decision stream used to discard
everything about that choice except the winner.  This module keeps the
choice's *context* in the data path (the RackSched per-decision
queue-state-visibility thesis, PAPERS.md), under the same contract as
every prior plane: pure reductions over arrays the engines already
materialize, riding the epoch-scan carries, decisions bit-identical
with the plane on or off (tests/test_provenance.py, ci.sh provenance
smoke).

**The provenance block** (:class:`ProvBlock`):

- ``margin_hist`` (``int64[NUM_BUCKETS + 1]``): log2 histogram (+
  ns-sum column, the ``obs.histograms`` bucket layout) of per-record
  **winner margins** -- the runner-up candidate's unified key minus the
  winner's, the "how close was this choice" signal.  For the sorted
  engines the runner-up at the instant decision *j* commits is exactly
  ``min(next sorted entry, min exit key of the already-served prefix)``
  -- both arrays the prefix condition already materializes -- so the
  margin is exact, not an estimate.  For the calendar engine the margin
  is the distance from a client's last unit-entry key to the committed
  boundary ``B_eff`` (how much headroom the boundary left it).
  Margins >= ~2^32 ns mean the runner-up sat in a LOWER phase (the
  packed key's class bits dominate): the phase ladder, not the tag,
  decided.  A record with no runner-up (sole candidate) observes
  nothing.
- ``scal`` (``int64[PS_FIELDS]``): per-batch aggregates -- the
  limit-gate state (how many clients sat queued but non-candidate
  behind their limit tag at batch entry), the eligible-set depth, the
  winning phase (the minimum class among candidates -- classes sort
  first in the unified key, so the batch's min class IS its first
  winner's phase), and the starvation high-watermark.
- ``last_served`` (``int64[N]``): per-client watermark of the virtual
  time of the last committed serve (a never-served client holds the
  block-creation baseline, so staleness is measured from when the
  block was armed).  Feeds the starvation detector: at every batch
  entry, ``now - last_served`` over backlogged clients, max'd into
  ``PS_STARVE_MAX``.

Merge algebra matches the metrics vector: counter rows add, ``*_MAX``
rows and ``last_served`` max (:func:`prov_combine` /
:func:`prov_mesh_reduce` psum/pmax).  The tag32 dead-batch rule is a
whole-block select (:func:`prov_select`): a tripped batch's
observations never land.

**Starvation detector** (:class:`StarvationMonitor`): host side, fed
at drain points.  Publishes the ``dmclock_starvation_*`` families and
fires a once-per-episode ``client_starved`` warning through the PR-7
watchdog's external-warning hook (or a log line) when a backlogged
client's time-since-service crosses the threshold; a client served
again re-arms its episode.

**Per-shard pressure gauges** (:func:`pressure_vec` /
:func:`publish_shard_pressure`): the placement signal the ROADMAP
rack-scheduling item needs -- live/peak eligible-set depth, backlog,
and a head-wait starvation watermark (``now - head_arrival`` over
queued heads: how long the current head has sat unserved, computable
from any shard's :class:`EngineState` alone) per shard, merged across
the mesh with the usual psum/pmax collective
(:func:`pressure_mesh_reduce`) and published as
``dmclock_shard_pressure_*``.

Offline, ``scripts/explain.py`` joins the flight ring (now carrying
margin/gate columns, ``obs.flight``), the decision trace (schema v2,
``obs.trace``), and the SLO window ring into a ranked causal
attribution per (client, window): limit_capped vs out_competed vs
reservation_tardy vs no_demand.
"""

from __future__ import annotations

import json
import sys
from typing import Callable, List, NamedTuple, Optional, Set

import numpy as np

from . import histograms as obshist

# -- scalar rows -------------------------------------------------------
PS_BATCHES = 0        # live batches observed
PS_GATED_BATCHES = 1  # batches with >= 1 limit-gated client
PS_GATE_SUM = 2       # sum over batches of limit-gated client count
PS_GATE_MAX = 3       # max limit-gated count in one batch  (merge: max)
PS_ELIG_SUM = 4       # sum over batches of eligible-set depth
PS_ELIG_MAX = 5       # max eligible-set depth               (merge: max)
PS_WIN_RESV = 6       # batches won by the constraint phase (min cls 0)
PS_WIN_PROP = 7       # batches won by the weight phase     (min cls 1)
PS_WIN_LB = 8         # batches won by a limit-break        (min cls 2)
PS_STARVE_MAX = 9     # max time-since-service over backlogged clients
#                       observed at any batch entry, ns     (merge: max)
PS_FIELDS = 10

PS_NAMES = ("batches", "gated_batches", "limit_gate_sum",
            "limit_gate_max", "eligible_depth_sum",
            "eligible_depth_max", "phase_wins_reservation",
            "phase_wins_weight", "phase_wins_limit_break",
            "starvation_max_ns")

# max-merged rows as a HOST constant (the obs.device _HWM_MASK rule:
# a module-level jnp array would leak a tracer under a lazy import
# inside a jit trace)
_PS_MAX_MASK = np.zeros((PS_FIELDS,), dtype=bool)
for _i in (PS_GATE_MAX, PS_ELIG_MAX, PS_STARVE_MAX):
    _PS_MAX_MASK[_i] = True


class ProvBlock(NamedTuple):
    """The device-resident provenance accumulator (see module doc)."""

    margin_hist: object   # int64[NUM_BUCKETS + 1]
    scal: object          # int64[PS_FIELDS]
    last_served: object   # int64[N]; a never-served client holds the
    #                       block-creation baseline (prov_init now_ns)


def prov_init(n: int, now_ns: int = 0) -> ProvBlock:
    """Fresh block.  ``now_ns`` is the measurement baseline the
    ``last_served`` watermark starts from: staleness of a
    never-served client is measured from BLOCK CREATION, not from
    virtual t=0 -- a block armed mid-run (the bench's
    post-calibration reset) must not read every backlogged client as
    starved since the beginning of time."""
    import jax.numpy as jnp

    return ProvBlock(
        margin_hist=jnp.zeros((obshist.NUM_BUCKETS + 1,),
                              dtype=jnp.int64),
        scal=jnp.zeros((PS_FIELDS,), dtype=jnp.int64),
        last_served=jnp.full((n,), jnp.int64(now_ns)))


def _margin_row(margins):
    """One batch's margin-histogram delta from a masked margin array
    (``-1`` = no observation): one-hot bucket compares + a sum
    reduction, the ``obs.histograms.hist_observe`` fold on a single
    standalone row."""
    import jax.numpy as jnp

    m = jnp.asarray(margins, dtype=jnp.int64)
    mask = m >= 0
    v = jnp.maximum(m, 0)
    idx = obshist.bucket_index(v)
    onehot = (idx[:, None] == jnp.arange(obshist.NUM_BUCKETS,
                                         dtype=jnp.int32)[None, :]) \
        & mask[:, None]
    counts = jnp.sum(onehot, axis=0).astype(jnp.int64)
    total = jnp.sum(jnp.where(mask, v, 0))
    return jnp.concatenate([counts, total[None]])


def prov_observe(prov: ProvBlock, *, now, elig, gated, win_cls,
                 served_pc, margins=None) -> ProvBlock:
    """Fold one batch/level's observations (see module doc for the
    semantics of each row).  Pure reductions over the entry
    classification and commit arrays the batch already computed, so
    the decision stream cannot be perturbed.

    ``elig``/``gated`` are bool[N] masks over the batch-ENTRY state
    (candidates / queued-but-non-candidate clients); ``win_cls`` is
    the scalar min class among candidates (CLS_NONE = no candidate);
    ``served_pc`` int32[N] decisions committed per client;
    ``margins`` (optional) the per-record margin array, ``-1`` = no
    observation.  The caller gates liveness with
    :func:`prov_select` (the tag32 dead-batch rule)."""
    import jax.numpy as jnp

    now = jnp.asarray(now, dtype=jnp.int64)
    elig = jnp.asarray(elig, dtype=bool)
    gated = jnp.asarray(gated, dtype=bool)
    elig_n = jnp.sum(elig).astype(jnp.int64)
    gate_n = jnp.sum(gated).astype(jnp.int64)
    backlog = elig | gated
    # staleness read at batch ENTRY, before this batch's serves land
    starve = jnp.max(jnp.where(backlog, now - prov.last_served,
                               jnp.int64(0)))
    win_cls = jnp.asarray(win_cls, dtype=jnp.int32)
    wins = (win_cls == jnp.arange(3, dtype=jnp.int32)) \
        .astype(jnp.int64)
    delta = jnp.stack([
        jnp.int64(1), (gate_n > 0).astype(jnp.int64), gate_n,
        gate_n, elig_n, elig_n, wins[0], wins[1], wins[2], starve])
    scal = jnp.where(jnp.asarray(_PS_MAX_MASK),
                     jnp.maximum(prov.scal, delta), prov.scal + delta)
    hist = prov.margin_hist if margins is None \
        else prov.margin_hist + _margin_row(margins)
    served = jnp.asarray(served_pc) > 0
    last = jnp.where(served, now, prov.last_served)
    return ProvBlock(margin_hist=hist, scal=scal, last_served=last)


def prov_select(live, new: ProvBlock, old: ProvBlock) -> ProvBlock:
    """Whole-block liveness gate (the tag32 dead-batch rule): a dead
    batch's observations -- including its ``last_served`` writes --
    never land."""
    import jax
    import jax.numpy as jnp

    live = jnp.asarray(live, dtype=bool)
    return jax.tree.map(lambda a, b: jnp.where(live, a, b), new, old)


def prov_combine(a: ProvBlock, b: ProvBlock) -> ProvBlock:
    """Merge two blocks over the SAME client set: histogram + counter
    rows add, ``*_MAX`` rows and ``last_served`` max -- associative
    and commutative, the metrics-vector algebra."""
    import jax.numpy as jnp

    return ProvBlock(
        margin_hist=a.margin_hist + b.margin_hist,
        scal=jnp.where(jnp.asarray(_PS_MAX_MASK),
                       jnp.maximum(a.scal, b.scal), a.scal + b.scal),
        last_served=jnp.maximum(a.last_served, b.last_served))


def prov_mesh_reduce(p: ProvBlock, axis_name: str) -> ProvBlock:
    """In-graph mesh merge for REPLICATED client sets: counters psum,
    max rows + ``last_served`` pmax (the ledger collective applied per
    provenance field)."""
    import jax.numpy as jnp
    from jax import lax

    return ProvBlock(
        margin_hist=lax.psum(p.margin_hist, axis_name),
        scal=jnp.where(jnp.asarray(_PS_MAX_MASK),
                       lax.pmax(p.scal, axis_name),
                       lax.psum(p.scal, axis_name)),
        last_served=lax.pmax(p.last_served, axis_name))


def prov_from_arrays(margin_hist, scal, last_served) -> ProvBlock:
    """Rebuild a ProvBlock from checkpointed numpy leaves (the
    ``robust.supervisor`` payload round-trip)."""
    import jax.numpy as jnp

    return ProvBlock(
        margin_hist=jnp.asarray(margin_hist, dtype=jnp.int64),
        scal=jnp.asarray(scal, dtype=jnp.int64),
        last_served=jnp.asarray(last_served, dtype=jnp.int64))


# ----------------------------------------------------------------------
# host side: percentiles, dict views, publishing
# ----------------------------------------------------------------------

def margin_percentile(prov, q: float) -> float:
    """Margin percentile from the log2 buckets (bucket-upper-bound, so
    never under-reported -- the ``obs.histograms`` quantization math on
    the standalone margin row)."""
    h = np.asarray(getattr(prov, "margin_hist", prov), dtype=np.int64)
    block = np.zeros((obshist.NUM_HISTS, obshist.NUM_BUCKETS + 1),
                     dtype=np.int64)
    block[0] = h
    return obshist.hist_percentile(block, 0, q)


def prov_dict(prov) -> dict:
    """Name a fetched block (host side): the scalar rows plus the
    derived margin percentiles and the limit-gate share."""
    import jax

    scal = np.asarray(jax.device_get(prov.scal), dtype=np.int64)
    out = {name: int(scal[i]) for i, name in enumerate(PS_NAMES)}
    batches = max(out["batches"], 1)
    out["limit_gate_share"] = out["gated_batches"] / batches
    out["eligible_depth_mean"] = out["eligible_depth_sum"] / batches
    out["margin_p50_ns"] = margin_percentile(prov, 0.50)
    out["margin_p99_ns"] = margin_percentile(prov, 0.99)
    h = np.asarray(jax.device_get(prov.margin_hist), dtype=np.int64)
    n = int(h[:obshist.NUM_BUCKETS].sum())
    out["margin_count"] = n
    out["margin_mean_ns"] = float(h[obshist.HIST_SUM_COL]) / n \
        if n else 0.0
    return out


def stale_clients(prov, now_ns: int, threshold_ns: int,
                  backlog=None) -> List[dict]:
    """Clients whose time-since-service exceeds ``threshold_ns`` at
    ``now_ns`` (host side), worst first.  ``backlog`` (optional
    int[N]) restricts to clients with queued work -- without it, a
    never-served idle client would read as infinitely starved."""
    import jax

    last = np.asarray(jax.device_get(prov.last_served),
                      dtype=np.int64)
    stale = np.int64(now_ns) - last
    mask = stale > threshold_ns
    if backlog is not None:
        mask &= np.asarray(jax.device_get(backlog)) > 0
    idx = np.nonzero(mask)[0]
    rows = [{"client": int(c), "stale_ns": int(stale[c]),
             "last_served_ns": int(last[c])} for c in idx]
    rows.sort(key=lambda r: -r["stale_ns"])
    return rows


def publish_provenance(registry, prov, labels=None) -> None:
    """Fold a fetched block into a host registry:
    ``dmclock_provenance_*`` gauges (margin percentiles, gate share,
    eligible depth) and the ``dmclock_starvation_max_ns`` watermark."""
    d = prov_dict(prov)
    for key in ("margin_p50_ns", "margin_p99_ns", "limit_gate_share",
                "eligible_depth_mean", "eligible_depth_max",
                "phase_wins_reservation", "phase_wins_weight",
                "phase_wins_limit_break"):
        registry.gauge(f"dmclock_provenance_{key}",
                       "decision provenance plane scalar "
                       "(docs/OBSERVABILITY.md)",
                       labels=labels).set(float(d[key]))
    registry.gauge("dmclock_starvation_max_ns",
                   "max time-since-service over backlogged clients "
                   "observed at any batch entry (provenance plane)",
                   labels=labels).set(float(d["starvation_max_ns"]))


# ----------------------------------------------------------------------
# starvation detector (host half)
# ----------------------------------------------------------------------

def _stderr_log(line: str) -> None:
    print(line, file=sys.stderr)


class StarvationMonitor:
    """Once-per-episode ``client_starved`` warnings over the
    provenance watermark.

    Fed at drain points with the fetched ``last_served`` watermark (or
    a whole ProvBlock), the current virtual time, and the per-client
    backlog; fires on the rising edge of ``now - last_served >
    threshold_ns`` per client and re-arms when the client is served
    again (staleness back under threshold).  Warnings route through a
    PR-7 :class:`~.watchdog.Watchdog`'s ``external_warning`` hook when
    attached (one warning stream + counter for the run), else a
    ``# starvation:`` JSON log line.  Deterministic: the same
    watermark stream fires the same episodes, so a resumed run (the
    watermark rides the rotation checkpoints) reconstructs them."""

    def __init__(self, threshold_ns: int, *, watchdog=None,
                 registry=None,
                 log: Callable[[str], None] = _stderr_log):
        self.threshold_ns = int(threshold_ns)
        self._watchdog = watchdog
        self._log = log
        self.active: Set[int] = set()
        self.fired: List[dict] = []
        self.episodes_total = 0
        self._counter = None
        self._max_gauge = None
        self._stale_gauge = None
        if registry is not None:
            self.attach_registry(registry)

    def attach_registry(self, registry) -> None:
        self._counter = registry.counter(
            "dmclock_starvation_episodes_total",
            "client_starved episodes fired (once per episode; "
            "provenance plane, docs/OBSERVABILITY.md)")
        self._max_gauge = registry.gauge(
            "dmclock_starvation_max_ns",
            "max time-since-service over backlogged clients "
            "(provenance plane)")
        self._stale_gauge = registry.gauge(
            "dmclock_starvation_stale_clients",
            "backlogged clients currently past the starvation "
            "threshold (provenance plane)")

    def observe(self, prov, now_ns: int, backlog=None) -> List[dict]:
        """One drain-point pass; returns the warnings fired (rising
        edges only)."""
        rows = stale_clients(prov, now_ns, self.threshold_ns,
                             backlog=backlog)
        over = {r["client"] for r in rows}
        # clients back under the threshold re-arm their episodes
        self.active &= over
        out = []
        for r in rows:
            if r["client"] in self.active:
                continue
            self.active.add(r["client"])
            w = {"kind": "client_starved", **r,
                 "threshold_ns": self.threshold_ns}
            out.append(w)
            self.fired.append(w)
            self.episodes_total += 1
            if self._counter is not None:
                self._counter.inc()
            if self._watchdog is not None:
                self._watchdog.external_warning(w)
            else:
                self._log("# starvation: "
                          + json.dumps(w, separators=(",", ":")))
        if self._max_gauge is not None:
            worst = rows[0]["stale_ns"] if rows else 0
            self._max_gauge.set(float(worst))
            self._stale_gauge.set(float(len(over)))
        return out


# ----------------------------------------------------------------------
# per-shard pressure gauges (the rack-scheduling placement signal)
# ----------------------------------------------------------------------

PRESS_ELIG = 0       # live eligible-set depth            (merge: add)
PRESS_BACKLOG = 1    # queued requests across clients     (merge: add)
PRESS_ELIG_PEAK = 2  # peak eligible depth                (merge: max)
PRESS_WAIT_WM = 3    # head-wait starvation watermark, ns (merge: max)
PRESS_FIELDS = 4

PRESS_NAMES = ("eligible_live", "backlog", "eligible_peak",
               "head_wait_max_ns")

_PRESS_MAX_MASK = np.zeros((PRESS_FIELDS,), dtype=bool)
for _i in (PRESS_ELIG_PEAK, PRESS_WAIT_WM):
    _PRESS_MAX_MASK[_i] = True


def pressure_vec(engine_state, now):
    """One server's pressure vector (``int64[PRESS_FIELDS]``) from its
    own :class:`EngineState` -- computable on ANY shard with no extra
    state: live eligible-set depth (candidates at ``now``), backlog,
    the same value as peak (the mesh/time merges max it), and the
    head-wait watermark ``max(now - head_arrival)`` over queued heads
    (how long the current head has sat unserved -- the shard-local
    starvation signal)."""
    import jax.numpy as jnp

    from ..engine import fastpath

    now = jnp.asarray(now, dtype=jnp.int64)
    cls, _key = fastpath._classify(engine_state, now, True)
    elig = jnp.sum(cls != fastpath.CLS_NONE).astype(jnp.int64)
    has_req = engine_state.active & (engine_state.depth > 0)
    backlog = jnp.sum(jnp.where(has_req, engine_state.depth, 0)) \
        .astype(jnp.int64)
    wait = jnp.max(jnp.where(
        has_req,
        jnp.maximum(now - engine_state.head_arrival, 0),
        jnp.int64(0)))
    return jnp.stack([elig, backlog, elig, wait])


def pressure_combine_axis(mat):
    """Reduce stacked [S, PRESS_FIELDS] vectors along the leading axis
    (counters add, peaks max) -- the local-shard half of a mesh
    merge."""
    import jax.numpy as jnp

    return jnp.where(jnp.asarray(_PRESS_MAX_MASK),
                     jnp.max(mat, axis=0), jnp.sum(mat, axis=0))


def pressure_mesh_reduce(vec, axis_name: str):
    """In-graph mesh merge: counters psum, peaks pmax -- the
    ``metrics_mesh_reduce`` collective applied to the pressure
    fields."""
    import jax.numpy as jnp
    from jax import lax

    return jnp.where(jnp.asarray(_PRESS_MAX_MASK),
                     lax.pmax(vec, axis_name),
                     lax.psum(vec, axis_name))


def pressure_dict(vec) -> dict:
    v = np.asarray(vec, dtype=np.int64).reshape(-1)
    return {name: int(v[i]) for i, name in enumerate(PRESS_NAMES)}


def publish_shard_pressure(registry, per_shard, merged=None) -> None:
    """Publish a fetched [S, PRESS_FIELDS] per-shard matrix (plus the
    optional mesh-merged total) as ``dmclock_shard_pressure_*`` gauges
    labelled by shard -- the live placement signal power-of-two-choices
    routing reads."""
    mat = np.asarray(per_shard, dtype=np.int64)
    if mat.ndim == 1:
        mat = mat[None]
    for s in range(mat.shape[0]):
        for i, name in enumerate(PRESS_NAMES):
            registry.gauge(
                f"dmclock_shard_pressure_{name}",
                "per-shard scheduling pressure (provenance plane; "
                "docs/OBSERVABILITY.md)",
                labels={"shard": str(s)}).set(float(mat[s, i]))
    if merged is not None:
        for i, name in enumerate(PRESS_NAMES):
            registry.gauge(
                f"dmclock_shard_pressure_{name}",
                "mesh-merged scheduling pressure (provenance plane)",
                labels={"shard": "all"}) \
                .set(float(np.asarray(merged).reshape(-1)[i]))
