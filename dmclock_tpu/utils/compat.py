"""Version-compatibility shims.

The repo targets current jax APIs; this container ships jax 0.4.x,
where some of them live elsewhere or spell their kwargs differently.
Import the shimmed name from here instead of feature-testing at every
call site.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map                    # jax >= 0.6
except AttributeError:
    # jax 0.4/0.5: experimental home, and the replication check kwarg
    # is spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map(f, **kw)

__all__ = ["shard_map"]
