from .periodic import PeriodicTask
from .profile import ProfileCombiner, ProfileTimer

__all__ = ["PeriodicTask", "ProfileTimer", "ProfileCombiner"]
