"""Profiling accumulators.

Equivalent of the reference's ``support/src/profile.h``: start/stop
timers accumulating count / sum / sum-of-squares / min / max (for mean
and standard deviation), plus a combiner that merges timers collected on
different threads/servers (``ProfileCombiner``, profile.h:100-120).
Always compiled in (the reference gates these behind -DPROFILE).
"""

from __future__ import annotations

import math
import time as _walltime


class _ProfileBase:
    def __init__(self):
        self.count = 0
        self.sum_ns = 0
        self.sum_sq_ns = 0.0
        self.low_ns = None
        self.high_ns = None

    def _accumulate(self, duration_ns: int) -> None:
        self.count += 1
        self.sum_ns += duration_ns
        self.sum_sq_ns += float(duration_ns) * duration_ns
        if self.low_ns is None or duration_ns < self.low_ns:
            self.low_ns = duration_ns
        if self.high_ns is None or duration_ns > self.high_ns:
            self.high_ns = duration_ns

    def mean_ns(self) -> float:
        return self.sum_ns / self.count if self.count else 0.0

    def std_dev_ns(self) -> float:
        # same estimator as reference ProfileBase (profile.h:43-51)
        if self.count < 2:
            return 0.0
        mean = self.mean_ns()
        var = (self.sum_sq_ns - self.count * mean * mean) / (self.count - 1)
        return math.sqrt(max(0.0, var))


class ProfileTimer(_ProfileBase):
    """ns-resolution start/stop accumulator (profile.h:61-97)."""

    def __init__(self):
        super().__init__()
        self._start_ns = None
        # start() calls that found the timer already running: the
        # in-flight interval is abandoned and the timer restarts
        # cleanly (under PYTHONOPTIMIZE the old assert stripped and
        # the discard was SILENT -- a reentrant caller deflated its
        # own count/sum without a trace)
        self.reentries = 0

    def start(self) -> None:
        if self._start_ns is not None:
            self.reentries += 1
        self._start_ns = _walltime.perf_counter_ns()

    def stop(self) -> None:
        assert self._start_ns is not None, "timer not started"
        self._accumulate(_walltime.perf_counter_ns() - self._start_ns)
        self._start_ns = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class ProfileCombiner(_ProfileBase):
    """Merge timers from multiple sources (profile.h:100-120)."""

    def combine(self, timer: _ProfileBase) -> None:
        if timer.count == 0:
            return
        self.count += timer.count
        self.sum_ns += timer.sum_ns
        self.sum_sq_ns += timer.sum_sq_ns
        if self.low_ns is None or (timer.low_ns is not None
                                   and timer.low_ns < self.low_ns):
            self.low_ns = timer.low_ns
        if self.high_ns is None or (timer.high_ns is not None
                                    and timer.high_ns > self.high_ns):
            self.high_ns = timer.high_ns
