"""Checkpoint / resume for device-resident scheduler and sim state.

The reference has no checkpointing (all state is in-memory and sims run
to completion; SURVEY.md section 5).  Here every piece of device state
-- ``EngineState``, the cluster's tracker shards, a whole ``DeviceSim``
-- is a pytree of arrays, so orbax makes save/restore nearly free, and
long simulations (or an embedding storage service) can snapshot the
scheduler mid-flight and resume bit-exactly.

Host-side bookkeeping (client-id maps, payload FIFOs) lives outside the
pytree; ``TpuPullPriorityQueue`` snapshots it alongside via
``queue_state_dict``/``restore_queue_state``.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np


def save_pytree(path: str, tree: Any) -> None:
    """Write any pytree-of-arrays checkpoint (orbax)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), tree, force=True)


def restore_pytree(path: str, like: Any) -> Any:
    """Restore a checkpoint saved by ``save_pytree``; ``like`` provides
    the tree structure and array shapes/dtypes (e.g. a freshly built
    state)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), like)
        return ckptr.restore(os.path.abspath(path), abstract)


def queue_state_dict(q) -> dict:
    """Host bookkeeping of a TpuPullPriorityQueue as plain data.

    Call this BEFORE ``save_pytree(path, q.state)``: it flushes any
    buffered ops into the device state, so saving the device state
    first would serialize a state the returned payload FIFOs are ahead
    of."""
    with q.data_mtx:
        # drop any speculative prefetch so q.state is the logical
        # state (exactly the decisions handed out so far), then flush
        if hasattr(q, "_settle_spec"):
            q._settle_spec()
        q._flush()
        return {
            "slot_of": dict(q._slot_of),
            "payloads": {s: list(d) for s, d in q._payloads.items()},
            "free": list(q._free),
            "next_order": q._next_order,
            "last_tick": dict(q._last_tick),
            "tick": q.tick,
            "counters": (q.reserv_sched_count, q.prop_sched_count,
                         q.limit_break_sched_count),
        }


def restore_queue_state(q, st: dict) -> None:
    """Restore host bookkeeping saved by ``queue_state_dict``.

    Restore the device state FIRST (``q.state = restore_pytree(...)``),
    then call this: cheap consistency checks against the restored
    device state catch a mismatched pair of snapshots (payload FIFOs
    desynced from device queue depths would silently hand out wrong
    request payloads)."""
    from collections import deque

    capacity = int(q.state.capacity)
    depth = np.asarray(q.state.depth)
    active = np.asarray(q.state.active)
    for c, s in st["slot_of"].items():
        if not 0 <= s < capacity:
            raise ValueError(
                f"restore mismatch: client {c!r} maps to slot {s}, "
                f"device capacity {capacity}")
    for s, d in st["payloads"].items():
        if not 0 <= s < capacity:
            raise ValueError(
                f"restore mismatch: payload FIFO for slot {s} is "
                f"outside device capacity {capacity}")
        if len(d) != int(depth[s]):
            raise ValueError(
                f"restore mismatch: slot {s} has {len(d)} payloads but "
                f"device depth {int(depth[s])} -- device and host "
                "snapshots are from different moments")
    # ... and the other direction: every occupied device slot must be
    # known to the host snapshot (a client admitted after the host
    # snapshot was taken would otherwise KeyError at dispatch time)
    occupied = np.flatnonzero(active & (depth > 0))
    missing = [int(s) for s in occupied if s not in st["payloads"]]
    if missing:
        raise ValueError(
            f"restore mismatch: device slots {missing} hold queued "
            "requests but have no host payload FIFO -- device and host "
            "snapshots are from different moments")

    with q.data_mtx:
        q._pending = []      # drop ops buffered against the old state
        # discard any speculative prefetch computed against the old
        # state WITHOUT settling (settle would replay pre-restore
        # decisions over the freshly restored device state); guarded
        # like the save side so non-speculative queue types round-trip
        if hasattr(q, "_buf"):
            q._buf.clear()
            q._buf_slots.clear()
            q._buf_horizon = 0
            q._spec_pre = None
            q._spec_consumed = 0
            q._host_idle.clear()
            if q._spec:
                q._spec_size = 1
        q._clean_mark_points.clear()
        q._last_erase_point = 0
        q._slot_of = dict(st["slot_of"])
        q._client_of = {s: c for c, s in q._slot_of.items()}
        q._payloads = {s: deque(d) for s, d in st["payloads"].items()}
        q._free = list(st["free"])
        q._next_order = st["next_order"]
        q._last_tick = dict(st["last_tick"])
        q.tick = st["tick"]
        (q.reserv_sched_count, q.prop_sched_count,
         q.limit_break_sched_count) = st["counters"]
