"""Crash-safe checkpoint / resume for device-resident state.

The reference has no checkpointing (all state is in-memory and sims run
to completion; SURVEY.md section 5).  Here every piece of device state
-- ``EngineState``, the cluster's tracker shards, a whole ``DeviceSim``
-- is a pytree of arrays; a snapshot is one ``.npz`` of the flattened
leaves plus a sha256 **digest sidecar** (``<path>.sha256``).

Crash safety (docs/ROBUSTNESS.md):

- ``save_pytree`` is **atomic**: data and sidecar are written to temp
  files, fsynced, and ``os.replace``d into place (data first, then
  sidecar; the parent directory is fsynced after each rename).  A
  crash at ANY point leaves either the previous snapshot pair intact
  or a data/sidecar pair that fails verification -- never a
  restorable-but-torn state (pinned by the kill-during-save matrix in
  ``tests/test_checkpoint.py``; the ``_crash_hook`` module attribute
  is the test's injection seam).
- ``restore_pytree`` verifies the sidecar digest against the loaded
  leaves and raises :class:`CheckpointCorruptError` on a truncated
  file, a flipped byte, or a missing/mismatched sidecar.
- ``save_pytree_rotating`` / ``restore_pytree_rotating`` keep a
  rotation directory of ``ckpt-<seq>`` snapshots; restore walks newest
  to oldest and lands on the first intact entry.

Host-side bookkeeping (client-id maps, payload FIFOs) lives outside the
pytree; ``TpuPullPriorityQueue`` snapshots it alongside via
``queue_state_dict``/``restore_queue_state``.
"""

from __future__ import annotations

import hashlib
import os
import re
from typing import Any, Callable, List, Optional, Tuple

import jax
import numpy as np


class CheckpointCorruptError(RuntimeError):
    """The snapshot at a path is unreadable, torn, or fails its
    digest -- restore must not hand it out."""


# test seam: called with a stage label at every point a crash could
# interrupt a save ("data_written", "data_synced", "data_renamed",
# "sidecar_written", "done"); tests raise from it to simulate a kill
_crash_hook: Optional[Callable[[str], None]] = None

# fault seam: called with the committed path after a save fully
# commits (data + sidecar durable, .prev pruned).  robust.host_faults
# flips payload bytes from it to model media corruption racing a save;
# restore must then fall back to an older intact rotation entry.
_post_commit_hook: Optional[Callable[[str], None]] = None

SAVE_STAGES = ("data_written", "data_synced", "data_renamed",
               "sidecar_written", "done")


def _crash(stage: str) -> None:
    if _crash_hook is not None:
        _crash_hook(stage)


def _leaf_digest(arrays) -> str:
    """sha256 over every leaf's dtype, shape, and bytes (order
    matters; the treedef comes from ``like`` at restore time)."""
    h = hashlib.sha256()
    for a in arrays:
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                 os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sidecar(path: str) -> str:
    return path + ".sha256"


def _prev(path: str) -> str:
    return path + ".prev"


def _pair_verifies(path: str) -> bool:
    """True when the (data, sidecar) pair at ``path`` is internally
    consistent (loads cleanly, digest matches).  Structure is not
    checked -- this is the is-it-torn probe the save path uses before
    deciding which pair to preserve."""
    side = _sidecar(path)
    if not (os.path.exists(path) and os.path.exists(side)):
        return False
    try:
        with open(side) as fh:
            want = fh.read().strip()
        with np.load(path) as z:
            arrays = [z[n] for n in sorted(z.files)]
        return _leaf_digest(arrays) == want
    except Exception:
        return False


def save_pytree(path, tree: Any) -> None:
    """Atomically write a pytree-of-arrays checkpoint (tmp + fsync +
    rename, digest sidecar).

    Overwriting an existing snapshot in place cannot swap a (data,
    sidecar) PAIR in one rename, so before the destructive renames the
    old pair is hard-linked to ``<path>.prev`` / ``<path>.prev.sha256``
    -- at every crash point the previous snapshot survives intact
    under one name or the other, and ``restore_pytree`` falls back to
    the ``.prev`` pair when the primary fails verification.  The links
    are removed once the new pair is fully committed."""
    path = os.fspath(path)
    arrays = [np.asarray(leaf) for leaf in jax.tree.leaves(tree)]
    digest = _leaf_digest(arrays)
    tmp_data = f"{path}.tmp.{os.getpid()}"
    tmp_side = f"{_sidecar(path)}.tmp.{os.getpid()}"
    # Preserve the newest INTACT snapshot as .prev before the
    # destructive renames.  In the healthy case .prev is absent (it is
    # pruned on every successful commit) and the primary pair is
    # linked without a verify read.  A leftover .prev means the last
    # save crashed somewhere mid-commit: the primary may be torn (keep
    # the old .prev) or fully committed with only the .prev prune
    # missing (refresh .prev from it -- otherwise a crash in the NEXT
    # save could fall back past the newest committed state), so the
    # rare post-crash path pays one digest read to decide.
    if os.path.exists(path) and os.path.exists(_sidecar(path)):
        have_prev = os.path.exists(_prev(path)) and \
            os.path.exists(_sidecar(_prev(path)))
        if not have_prev or _pair_verifies(path):
            for src, dst in ((path, _prev(path)),
                             (_sidecar(path), _sidecar(_prev(path)))):
                if os.path.exists(dst):
                    os.unlink(dst)
                os.link(src, dst)
            _fsync_dir(path)
    try:
        with open(tmp_data, "wb") as fh:
            np.savez(fh, **{f"leaf_{i:05d}": a
                            for i, a in enumerate(arrays)})
            _crash("data_written")
            fh.flush()
            os.fsync(fh.fileno())
        _crash("data_synced")
        os.replace(tmp_data, path)
        _fsync_dir(path)
        _crash("data_renamed")
        with open(tmp_side, "w") as fh:
            fh.write(digest + "\n")
            _crash("sidecar_written")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp_side, _sidecar(path))
        _fsync_dir(path)
        _crash("done")
        for old in (_prev(path), _sidecar(_prev(path))):
            if os.path.exists(old):
                os.unlink(old)
        if _post_commit_hook is not None:
            _post_commit_hook(path)
    finally:
        for tmp in (tmp_data, tmp_side):
            if os.path.exists(tmp):
                os.unlink(tmp)


def restore_pytree(path, like: Any, *,
                   strict_shapes: bool = True) -> Any:
    """Restore a checkpoint saved by ``save_pytree``; ``like`` provides
    the tree structure and array shapes/dtypes (e.g. a freshly built
    state).  Raises :class:`CheckpointCorruptError` unless the data
    loads cleanly AND matches its sidecar digest AND fits ``like``.
    When the primary pair fails verification but an intact ``.prev``
    pair exists (an in-place overwrite was interrupted mid-commit),
    the previous snapshot is returned instead.

    ``strict_shapes=False`` relaxes the per-leaf shape check along
    **axis 0 only** (dtype, rank, and every trailing dimension still
    gate): grow-on-demand payloads -- the lifecycle plane's
    geometrically-doubled client arrays, its variable-length journals
    -- vary exactly there, while fixed-shape leaves (histogram
    blocks, ring widths, metric vectors) keep their full check.  The
    sidecar digest still gates integrity; only the template's axis-0
    expectation is waived."""
    path = os.fspath(path)
    try:
        return _restore_exact(path, like, strict_shapes=strict_shapes)
    except CheckpointCorruptError:
        prev = _prev(path)
        if os.path.exists(prev) and os.path.exists(_sidecar(prev)):
            return _restore_exact(prev, like,
                                  strict_shapes=strict_shapes)
        raise


def _restore_exact(path: str, like: Any, *,
                   strict_shapes: bool = True) -> Any:
    side = _sidecar(path)
    if not os.path.exists(path):
        raise CheckpointCorruptError(f"no checkpoint at {path}")
    if not os.path.exists(side):
        raise CheckpointCorruptError(
            f"{path}: missing digest sidecar {side} -- save was "
            "interrupted or the sidecar was lost; refusing to restore")
    with open(side) as fh:
        want = fh.read().strip()
    like_leaves, treedef = jax.tree.flatten(like)
    try:
        with np.load(path) as z:
            names = sorted(z.files)
            arrays = [z[n] for n in names]
    except Exception as e:
        raise CheckpointCorruptError(f"{path}: unreadable ({e})")
    if len(arrays) != len(like_leaves):
        raise CheckpointCorruptError(
            f"{path}: {len(arrays)} leaves saved, structure needs "
            f"{len(like_leaves)}")
    got = _leaf_digest(arrays)
    if got != want:
        raise CheckpointCorruptError(
            f"{path}: digest mismatch (sidecar {want[:16]}..., "
            f"content {got[:16]}...) -- torn or corrupted snapshot")
    out = []
    for arr, ref in zip(arrays, like_leaves):
        ref = np.asarray(ref)
        if arr.dtype != ref.dtype or \
                (strict_shapes and arr.shape != ref.shape) or \
                (not strict_shapes and
                 (arr.ndim != ref.ndim or
                  arr.shape[1:] != ref.shape[1:])):
            raise CheckpointCorruptError(
                f"{path}: leaf shape/dtype {arr.shape}/{arr.dtype} != "
                f"expected {ref.shape}/{ref.dtype}")
        out.append(jax.numpy.asarray(arr))
    return jax.tree.unflatten(treedef, out)


# ----------------------------------------------------------------------
# rotation directory
# ----------------------------------------------------------------------

_ROT_RE = re.compile(r"^ckpt-(\d{8})$")


def _rotation_entries(dirpath: str) -> List[Tuple[int, str]]:
    out = []
    if not os.path.isdir(dirpath):
        return out
    for name in os.listdir(dirpath):
        m = _ROT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(dirpath, name)))
    return sorted(out)


def rotation_paths(dirpath) -> List[str]:
    """Snapshot paths in a rotation directory, oldest to newest (the
    supervisor's resume log and the corruption-fault targeting both
    need the on-disk view without reaching into the module's
    privates)."""
    return [p for _, p in _rotation_entries(os.fspath(dirpath))]


def save_pytree_rotating(dirpath, tree: Any, keep: int = 4) -> str:
    """Write the next ``ckpt-<seq>`` snapshot into a rotation
    directory (created on demand), then prune to the newest ``keep``
    entries.  Returns the written path.  Each entry is an independent
    atomic ``save_pytree``, so a crash mid-save never harms the older
    entries restore falls back to."""
    dirpath = os.fspath(dirpath)
    os.makedirs(dirpath, exist_ok=True)
    entries = _rotation_entries(dirpath)
    seq = entries[-1][0] + 1 if entries else 1
    path = os.path.join(dirpath, f"ckpt-{seq:08d}")
    save_pytree(path, tree)
    for _, old in _rotation_entries(dirpath)[:-keep]:
        for p in (old, _sidecar(old)):
            if os.path.exists(p):
                os.unlink(p)
    return path


def restore_pytree_rotating(dirpath, like: Any, *,
                            strict_shapes: bool = True
                            ) -> Tuple[Any, str]:
    """Restore the newest INTACT snapshot from a rotation directory,
    walking newest to oldest past torn/corrupt entries.  Returns
    ``(tree, path)``; raises :class:`CheckpointCorruptError` when no
    entry verifies.  ``strict_shapes`` as in :func:`restore_pytree`
    (grow-on-demand payloads restore with it off)."""
    dirpath = os.fspath(dirpath)
    entries = _rotation_entries(dirpath)
    errors = []
    for _, path in reversed(entries):
        try:
            return restore_pytree(path, like,
                                  strict_shapes=strict_shapes), path
        except CheckpointCorruptError as e:
            errors.append(str(e))
    raise CheckpointCorruptError(
        f"{dirpath}: no intact snapshot in rotation"
        + (f" ({'; '.join(errors)})" if errors else " (empty)"))


def queue_state_dict(q) -> dict:
    """Host bookkeeping of a TpuPullPriorityQueue as plain data.

    Call this BEFORE ``save_pytree(path, q.state)``: it flushes any
    buffered ops into the device state, so saving the device state
    first would serialize a state the returned payload FIFOs are ahead
    of."""
    with q.data_mtx:
        # drop any speculative prefetch so q.state is the logical
        # state (exactly the decisions handed out so far), then flush
        if hasattr(q, "_settle_spec"):
            q._settle_spec()
        q._flush()
        return {
            "slot_of": dict(q._slot_of),
            "payloads": {s: list(d) for s, d in q._payloads.items()},
            "free": list(q._free),
            "next_order": q._next_order,
            "last_tick": dict(q._last_tick),
            "tick": q.tick,
            "counters": (q.reserv_sched_count, q.prop_sched_count,
                         q.limit_break_sched_count),
        }


def restore_queue_state(q, st: dict) -> None:
    """Restore host bookkeeping saved by ``queue_state_dict``.

    Restore the device state FIRST (``q.state = restore_pytree(...)``),
    then call this: cheap consistency checks against the restored
    device state catch a mismatched pair of snapshots (payload FIFOs
    desynced from device queue depths would silently hand out wrong
    request payloads)."""
    from collections import deque

    capacity = int(q.state.capacity)
    depth = np.asarray(q.state.depth)
    active = np.asarray(q.state.active)
    for c, s in st["slot_of"].items():
        if not 0 <= s < capacity:
            raise ValueError(
                f"restore mismatch: client {c!r} maps to slot {s}, "
                f"device capacity {capacity}")
    for s, d in st["payloads"].items():
        if not 0 <= s < capacity:
            raise ValueError(
                f"restore mismatch: payload FIFO for slot {s} is "
                f"outside device capacity {capacity}")
        if len(d) != int(depth[s]):
            raise ValueError(
                f"restore mismatch: slot {s} has {len(d)} payloads but "
                f"device depth {int(depth[s])} -- device and host "
                "snapshots are from different moments")
    # ... and the other direction: every occupied device slot must be
    # known to the host snapshot (a client admitted after the host
    # snapshot was taken would otherwise KeyError at dispatch time)
    occupied = np.flatnonzero(active & (depth > 0))
    missing = [int(s) for s in occupied if s not in st["payloads"]]
    if missing:
        raise ValueError(
            f"restore mismatch: device slots {missing} hold queued "
            "requests but have no host payload FIFO -- device and host "
            "snapshots are from different moments")

    with q.data_mtx:
        q._pending = []      # drop ops buffered against the old state
        # discard any speculative prefetch computed against the old
        # state WITHOUT settling (settle would replay pre-restore
        # decisions over the freshly restored device state); guarded
        # like the save side so non-speculative queue types round-trip
        if hasattr(q, "_buf"):
            q._buf.clear()
            q._buf_slots.clear()
            q._buf_horizon = 0
            q._spec_pre = None
            q._spec_consumed = 0
            q._host_idle.clear()
            if q._spec:
                q._spec_size = 1
        q._clean_mark_points.clear()
        q._last_erase_point = 0
        q._slot_of = dict(st["slot_of"])
        q._client_of = {s: c for c, s in q._slot_of.items()}
        q._payloads = {s: deque(d) for s, d in st["payloads"].items()}
        q._free = list(st["free"])
        q._next_order = st["next_order"]
        q._last_tick = dict(st["last_tick"])
        q.tick = st["tick"]
        (q.reserv_sched_count, q.prop_sched_count,
         q.limit_break_sched_count) = st["counters"]
