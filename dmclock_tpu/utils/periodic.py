"""Periodic background job thread.

Equivalent of the reference's ``RunEvery`` (``support/src/run_every.h:32-80``,
``support/src/run_every.cc:61-94``): a thread that waits ``period`` between
invocations of ``body``, supports live period updates (``try_update``),
and joins cleanly on destruction/stop.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class PeriodicTask:
    """Run ``body()`` every ``period_s`` seconds on a daemon thread.

    ``try_update(new_period_s)`` only shortens the *next* wait if the
    new period is smaller, mirroring ``RunEvery::try_update``
    (run_every.cc:77-81) which resets the wait window.
    """

    def __init__(self, period_s: float, body: Callable[[], None],
                 start: bool = True):
        self._period_s = float(period_s)
        self._body = body
        self._finishing = False
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="dmclock-periodic")
            self._thread.start()

    def try_update(self, new_period_s: float) -> None:
        with self._cv:
            self._period_s = float(new_period_s)
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._finishing = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # join-on-delete mirrors RunEvery's destructor (run_every.cc:61-74)
    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self.stop()
        except Exception:
            pass

    def _run(self) -> None:
        import time as _time
        with self._cv:
            deadline = _time.monotonic() + self._period_s
            while not self._finishing:
                remaining = deadline - _time.monotonic()
                if remaining > 0:
                    # woken early by try_update/stop: recompute deadline
                    # against the (possibly shortened) period and re-wait
                    self._cv.wait(timeout=remaining)
                    deadline = min(deadline, _time.monotonic() + self._period_s)
                    continue
                if self._finishing:
                    return
                # run the body outside the lock so body() may call
                # try_update without deadlocking
                self._cv.release()
                try:
                    self._body()
                finally:
                    self._cv.acquire()
                deadline = _time.monotonic() + self._period_s
