"""Per-client QoS parameters.

Equivalent of the reference's ``ClientInfo`` (``src/dmclock_server.h:95-132``):
(reservation, weight, limit) rates plus cached per-unit-cost virtual-time
increments.  The reference caches multiplicative inverses as doubles; we
cache integer nanosecond increments (see ``timebase.rate_to_inv_ns``)
with the same 0 -> 0 "axis disabled" sentinel.
"""

from __future__ import annotations

from .timebase import rate_to_inv_ns


class ClientInfo:
    """QoS triple: minimum (reservation), proportional (weight), maximum
    (limit) -- with cached ns-per-unit-cost increments.

    Mutable via :meth:`update` to support ``update_client_info``
    (reference dmclock_server.h:633-648).
    """

    __slots__ = ("reservation", "weight", "limit",
                 "reservation_inv_ns", "weight_inv_ns", "limit_inv_ns")

    def __init__(self, reservation: float, weight: float, limit: float):
        self.update(reservation, weight, limit)

    def update(self, reservation: float, weight: float, limit: float) -> None:
        self.reservation = float(reservation)
        self.weight = float(weight)
        self.limit = float(limit)
        self.reservation_inv_ns = rate_to_inv_ns(self.reservation)
        self.weight_inv_ns = rate_to_inv_ns(self.weight)
        self.limit_inv_ns = rate_to_inv_ns(self.limit)

    def __repr__(self) -> str:
        return (f"ClientInfo(r={self.reservation}, w={self.weight}, "
                f"l={self.limit})")
