"""Per-client QoS parameters.

Equivalent of the reference's ``ClientInfo`` (``src/dmclock_server.h:95-132``):
(reservation, weight, limit) rates plus cached per-unit-cost virtual-time
increments.  The reference caches multiplicative inverses as doubles; we
cache integer nanosecond increments (see ``timebase.rate_to_inv_ns``)
with the same 0 -> 0 "axis disabled" sentinel.

Construction VALIDATES its inputs (docs/ROBUSTNESS.md): a NaN,
infinite, or negative rate -- or a nonzero limit below the reservation
-- would silently produce garbage tags (``rate_to_inv_ns`` of NaN/inf
degenerates to the axis-disabled sentinel, and an impossible
limit-below-reservation contract stalls the client forever), so each is
rejected with a ``ValueError`` naming the client when the caller
provides one.
"""

from __future__ import annotations

import math
from typing import Any, Optional

from .timebase import rate_to_inv_ns


def _validate_qos(reservation: float, weight: float, limit: float,
                  client: Optional[Any]) -> None:
    who = f" for client {client!r}" if client is not None else ""
    for label, v in (("reservation", reservation), ("weight", weight),
                     ("limit", limit)):
        if math.isnan(v):
            raise ValueError(f"QoS {label} is NaN{who}")
        if math.isinf(v):
            raise ValueError(f"QoS {label} is infinite{who} "
                             "(use 0 to disable the axis)")
        if v < 0:
            raise ValueError(f"QoS {label} must be >= 0{who}, "
                             f"got {v}")
    if limit > 0 and limit < reservation:
        raise ValueError(
            f"QoS limit {limit} < reservation {reservation}{who}: "
            "the cap would sit below the guaranteed floor, so the "
            "contract is unsatisfiable")


def validate_client_info(info, name: Optional[Any] = None) -> None:
    """Validate a QoS triple without constructing a :class:`ClientInfo`.

    ``info`` is a ClientInfo, anything with reservation/weight/limit
    attributes, or a ``(reservation, weight, limit)`` sequence.  The
    ONE validation path shared by init-time construction and the live
    lifecycle-update path (``lifecycle.api`` admin rejections carry
    the same client-naming ValueErrors as init-time ones).  ``name``
    names the owner in errors; a ClientInfo's own ``client`` is used
    when ``name`` is not given.  Non-numeric values raise the same
    ``ValueError`` family (a live API must not 500 on ``"abc"``)."""
    if isinstance(info, (tuple, list)):
        r, w, l = info
    else:
        r, w, l = info.reservation, info.weight, info.limit
        if name is None:
            name = getattr(info, "client", None)
    try:
        r, w, l = float(r), float(w), float(l)
    except (TypeError, ValueError):
        who = f" for client {name!r}" if name is not None else ""
        raise ValueError(f"QoS triple must be numeric{who}, got "
                         f"({r!r}, {w!r}, {l!r})")
    _validate_qos(r, w, l, name)


class ClientInfo:
    """QoS triple: minimum (reservation), proportional (weight), maximum
    (limit) -- with cached ns-per-unit-cost increments.

    Mutable via :meth:`update` to support ``update_client_info``
    (reference dmclock_server.h:633-648).  ``client`` (optional) names
    the owner in validation errors.
    """

    __slots__ = ("reservation", "weight", "limit",
                 "reservation_inv_ns", "weight_inv_ns", "limit_inv_ns",
                 "client")

    def __init__(self, reservation: float, weight: float, limit: float,
                 client: Optional[Any] = None):
        self.client = client
        self.update(reservation, weight, limit)

    def update(self, reservation: float, weight: float, limit: float) -> None:
        reservation = float(reservation)
        weight = float(weight)
        limit = float(limit)
        validate_client_info((reservation, weight, limit),
                             name=self.client)
        self.reservation = reservation
        self.weight = weight
        self.limit = limit
        self.reservation_inv_ns = rate_to_inv_ns(self.reservation)
        self.weight_inv_ns = rate_to_inv_ns(self.weight)
        self.limit_inv_ns = rate_to_inv_ns(self.limit)

    def __repr__(self) -> str:
        return (f"ClientInfo(r={self.reservation}, w={self.weight}, "
                f"l={self.limit})")
