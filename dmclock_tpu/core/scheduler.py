"""Oracle (host, pure-Python) dmClock scheduler.

A complete, deterministic re-implementation of the reference server-side
engine (``src/dmclock_server.h``): the two-phase
reservation-then-weight selection of ``do_next_request`` (:1115-1186),
delayed/immediate tag calculation (:878-907), AtLimit policies
{Wait, Allow, Reject} (:74-93), anticipation, idle-reactivation
prop_delta (:937-985), tick-mark GC (:1206-1255), and the Pull/Push
queue surfaces (:1279-1797).

Design departure from the reference (deliberate, TPU-first): the
reference keeps three intrusive k-way heaps and makes one O(log n)
decision at a time under a mutex.  This oracle instead defines a TOTAL
order per selection axis -- the reference's ``ClientCompare`` semantics
(:722-757) extended with a creation-index tie-break -- and selects by
linear scan.  The same total order is implemented by the C++ native
backend's k-way heaps and by the TPU engine's stable argmin, which is
what makes request-ordering parity across backends exact rather than
luck-of-the-heap.  The oracle is the golden model: every other backend
is tested against it.

All times/tags are int64 nanoseconds (see ``timebase``).
"""

from __future__ import annotations

import enum
import errno
import threading
import time as _walltime
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Generic, List, Optional, Tuple, TypeVar, Union

from .qos import ClientInfo
from .recs import Cost, Phase, ReqParams
from .tags import RequestTag, ZERO_TAG
from .timebase import (LOWEST_PROP_TAG_TRIGGER, MAX_TAG, NS_PER_SEC,
                       TIME_MAX, TIME_ZERO, min_not_0_time, sec_to_ns)
from ..utils.periodic import PeriodicTask

C = TypeVar("C")  # client id type
R = TypeVar("R")  # request payload type

ClientInfoFunc = Callable[[Any], Optional[ClientInfo]]

# GC defaults (reference dmclock_server.h:68-72)
STANDARD_IDLE_AGE_S = 300.0
STANDARD_ERASE_AGE_S = 600.0
STANDARD_CHECK_TIME_S = 60.0
AGGRESSIVE_CHECK_TIME_S = 5.0
STANDARD_ERASE_MAX = 2000


class AtLimit(enum.Enum):
    """Over-limit policy (reference dmclock_server.h:74-84)."""

    WAIT = 0    # hold over-limit requests until the limit tag passes
    ALLOW = 1   # limit-break when nothing else is eligible
    REJECT = 2  # add_request returns EAGAIN for over-limit requests


class NextReqType(enum.Enum):
    RETURNING = 0
    FUTURE = 1
    NONE = 2


class HeapId(enum.Enum):
    RESERVATION = 0
    READY = 1


@dataclass
class NextReq:
    """Outcome of a scheduling decision (reference NextReq, :512-538)."""

    type: NextReqType
    heap_id: Optional[HeapId] = None
    when_ready: Optional[int] = None  # ns

    @staticmethod
    def none() -> "NextReq":
        return NextReq(NextReqType.NONE)

    @staticmethod
    def returning(heap_id: HeapId) -> "NextReq":
        return NextReq(NextReqType.RETURNING, heap_id=heap_id)

    @staticmethod
    def future(when_ns: int) -> "NextReq":
        return NextReq(NextReqType.FUTURE, when_ready=when_ns)


@dataclass
class ClientReq(Generic[R]):
    """One queued request (reference ClientReq, :311-335)."""

    tag: RequestTag
    client_id: Any
    request: Any


class ClientRec(Generic[C, R]):
    """Per-client scheduler record (reference ClientRec, :355-499).

    ``order`` is the creation index used as the deterministic tie-break
    in every selection -- this framework's replacement for the
    reference's arbitrary heap tie ordering.
    """

    __slots__ = ("client", "order", "prev_tag", "requests", "prop_delta",
                 "info", "idle", "last_tick", "cur_rho", "cur_delta")

    def __init__(self, client: Any, info: Optional[ClientInfo],
                 current_tick: int, order: int):
        self.client = client
        self.order = order
        self.prev_tag = ZERO_TAG.copy()
        self.requests: Deque[ClientReq] = deque()
        self.prop_delta = 0  # ns shift applied in ready comparisons
        self.info = info
        self.idle = True
        self.last_tick = current_tick
        self.cur_rho = 1
        self.cur_delta = 1

    # -- request queue ------------------------------------------------
    def has_request(self) -> bool:
        return bool(self.requests)

    def next_request(self) -> ClientReq:
        return self.requests[0]

    def pop_request(self) -> None:
        self.requests.popleft()

    def request_count(self) -> int:
        return len(self.requests)

    def add_request(self, tag: RequestTag, request: Any) -> None:
        self.requests.append(ClientReq(tag, self.client, request))

    # -- prev-tag maintenance (reference :399-412) --------------------
    def update_req_tag(self, tag: RequestTag, tick: int) -> None:
        # sentinels (pinned tags) are never copied into prev_tag
        if tag.reservation != MAX_TAG and tag.reservation != -MAX_TAG:
            self.prev_tag.reservation = tag.reservation
        if tag.limit != MAX_TAG and tag.limit != -MAX_TAG:
            self.prev_tag.limit = tag.limit
        if tag.proportion != MAX_TAG and tag.proportion != -MAX_TAG:
            self.prev_tag.proportion = tag.proportion
        self.prev_tag.arrival = tag.arrival
        self.last_tick = tick

    # -- filtered removal (reference :440-480) ------------------------
    def remove_by_req_filter(self, filter_accum: Callable[[Any], bool],
                             visit_backwards: bool) -> bool:
        any_removed = False
        idxs = range(len(self.requests) - 1, -1, -1) if visit_backwards \
            else range(len(self.requests))
        keep: List[Optional[ClientReq]] = list(self.requests)
        for i in idxs:
            if filter_accum(keep[i].request):
                any_removed = True
                keep[i] = None
        if any_removed:
            self.requests = deque(r for r in keep if r is not None)
        return any_removed


class PriorityQueueBase(Generic[C, R]):
    """Core engine shared by pull/push queues
    (reference PriorityQueueBase, dmclock_server.h:283-1276).

    Selection axes (reference's three heaps + ClientCompare :722-757),
    expressed as total-order sort keys over clients:

      reservation: (has_request DESC, head.reservation ASC, order ASC)
      limit:       (has_request DESC, head.ready ASC,
                    head.limit ASC, order ASC)          # ready lowers
      ready:       (has_request DESC, head.ready DESC,
                    head.proportion + prop_delta ASC, order ASC)
    """

    def __init__(self,
                 client_info_f: ClientInfoFunc,
                 *,
                 delayed_tag_calc: bool = False,
                 dynamic_cli_info: bool = False,
                 at_limit: Union[AtLimit, int, float] = AtLimit.WAIT,
                 anticipation_timeout_ns: int = 0,
                 idle_age_s: float = STANDARD_IDLE_AGE_S,
                 erase_age_s: float = STANDARD_ERASE_AGE_S,
                 check_time_s: float = STANDARD_CHECK_TIME_S,
                 erase_max: int = STANDARD_ERASE_MAX,
                 run_gc_thread: bool = True,
                 monotonic_clock: Callable[[], float] = _walltime.monotonic):
        self.client_info_f = client_info_f
        self.delayed_tag_calc = delayed_tag_calc
        self.is_dynamic_cli_info_f = dynamic_cli_info
        # a bare number passed for at_limit is a RejectThreshold and
        # implies AtLimit.Reject (reference AtLimitParam, :89-93,829-846)
        if isinstance(at_limit, AtLimit):
            self.at_limit = at_limit
            self.reject_threshold_ns = 0
        else:
            self.at_limit = AtLimit.REJECT
            self.reject_threshold_ns = int(at_limit)
        self.anticipation_timeout_ns = int(anticipation_timeout_ns)
        # AtLimit::Reject needs accurate tags at add time
        # (reference assert, :856-857)
        assert not (self.at_limit is AtLimit.REJECT and self.delayed_tag_calc), \
            "AtLimit.REJECT requires immediate tag calculation"
        assert erase_age_s >= idle_age_s
        assert check_time_s < idle_age_s

        self.data_mtx = threading.Lock()
        self.client_map: Dict[Any, ClientRec] = {}
        self.finishing = False
        self.tick = 0
        self._next_order = 0

        # scheduling counters (reference :810-812)
        self.reserv_sched_count = 0
        self.prop_sched_count = 0
        self.limit_break_sched_count = 0

        # GC state (reference :814-821, do_clean :1206-1255)
        self.idle_age_s = idle_age_s
        self.erase_age_s = erase_age_s
        self.check_time_s = check_time_s
        self.erase_max = erase_max
        self.last_erase_point = 0
        self._clean_mark_points: Deque[Tuple[float, int]] = deque()
        self._monotonic = monotonic_clock
        self._cleaning_job: Optional[PeriodicTask] = None
        if run_gc_thread:
            self._cleaning_job = PeriodicTask(check_time_s, self.do_clean)

    # ------------------------------------------------------------------
    # public inspection API (reference :545-564)
    # ------------------------------------------------------------------
    def empty(self) -> bool:
        with self.data_mtx:
            top = self._resv_top()
            return top is None or not top.has_request()

    def client_count(self) -> int:
        with self.data_mtx:
            return len(self.client_map)

    def request_count(self) -> int:
        with self.data_mtx:
            return sum(c.request_count() for c in self.client_map.values())

    # ------------------------------------------------------------------
    # removal / info-update API (reference :567-648)
    # ------------------------------------------------------------------
    def remove_by_req_filter(self, filter_accum: Callable[[Any], bool],
                             visit_backwards: bool = False) -> bool:
        with self.data_mtx:
            any_removed = False
            for rec in self.client_map.values():
                if rec.remove_by_req_filter(filter_accum, visit_backwards):
                    any_removed = True
            return any_removed

    def remove_by_client(self, client: Any, reverse: bool = False,
                         accum: Optional[Callable[[Any], None]] = None) -> None:
        with self.data_mtx:
            rec = self.client_map.get(client)
            if rec is None:
                return
            reqs = reversed(rec.requests) if reverse else iter(rec.requests)
            if accum is not None:
                for r in reqs:
                    accum(r.request)
            rec.requests.clear()

    def update_client_info(self, client_id: Any) -> None:
        with self.data_mtx:
            rec = self.client_map.get(client_id)
            if rec is not None:
                rec.info = self.client_info_f(client_id)

    def update_client_infos(self) -> None:
        with self.data_mtx:
            for rec in self.client_map.values():
                rec.info = self.client_info_f(rec.client)

    def shutdown(self) -> None:
        self.finishing = True
        if self._cleaning_job is not None:
            self._cleaning_job.stop()
            self._cleaning_job = None

    # ------------------------------------------------------------------
    # selection axes (reference heaps + ClientCompare :722-797)
    # ------------------------------------------------------------------
    def _resv_key(self, c: ClientRec):
        if c.has_request():
            return (0, c.next_request().tag.reservation, c.order)
        return (1, 0, c.order)

    def _limit_key(self, c: ClientRec):
        if c.has_request():
            t = c.next_request().tag
            return (0, 1 if t.ready else 0, t.limit, c.order)
        return (1, 0, 0, c.order)

    def _ready_key(self, c: ClientRec):
        if c.has_request():
            t = c.next_request().tag
            return (0, 0 if t.ready else 1, t.proportion + c.prop_delta,
                    c.order)
        return (1, 0, 0, c.order)

    def _resv_top(self) -> Optional[ClientRec]:
        if not self.client_map:
            return None
        return min(self.client_map.values(), key=self._resv_key)

    def _limit_top(self) -> Optional[ClientRec]:
        if not self.client_map:
            return None
        return min(self.client_map.values(), key=self._limit_key)

    def _ready_top(self) -> Optional[ClientRec]:
        if not self.client_map:
            return None
        return min(self.client_map.values(), key=self._ready_key)

    # ------------------------------------------------------------------
    # tag helpers
    # ------------------------------------------------------------------
    def _get_cli_info(self, client: ClientRec) -> Optional[ClientInfo]:
        # reference get_cli_info (:870-875)
        if self.is_dynamic_cli_info_f:
            client.info = self.client_info_f(client.client)
        return client.info

    def _initial_tag(self, client: ClientRec, params: ReqParams,
                     time_ns: int, cost: int) -> RequestTag:
        if self.delayed_tag_calc:
            # reference initial_tag(DelayedTagCalc) :878-893: only tag
            # for real if the request goes straight to the queue head
            if not client.has_request():
                info = self._get_cli_info(client)
                assert info is not None
                tag = RequestTag.from_prev(client.prev_tag, info,
                                           params.delta, params.rho,
                                           time_ns, cost,
                                           self.anticipation_timeout_ns)
                client.update_req_tag(tag, self.tick)
                return tag
            return RequestTag(reservation=0, proportion=0, limit=0,
                              arrival=time_ns, delta=0, rho=0, cost=cost)
        # reference initial_tag(ImmediateTagCalc) :896-907
        info = self._get_cli_info(client)
        assert info is not None
        tag = RequestTag.from_prev(client.prev_tag, info,
                                   params.delta, params.rho, time_ns,
                                   cost, self.anticipation_timeout_ns)
        client.update_req_tag(tag, self.tick)
        return tag

    # ------------------------------------------------------------------
    # core: add (reference do_add_request :913-1018)
    # ------------------------------------------------------------------
    def _do_add_request(self, request: Any, client_id: Any,
                        req_params: ReqParams, time_ns: int,
                        cost: int = 1) -> int:
        self.tick += 1

        rec = self.client_map.get(client_id)
        if rec is None:
            info = self.client_info_f(client_id)
            rec = ClientRec(client_id, info, self.tick, self._next_order)
            self._next_order += 1
            self.client_map[client_id] = rec

        if rec.idle:
            # Idle-reactivation (reference :937-985): shift the
            # returning client's effective proportion tag next to the
            # lowest active one so it competes fairly rather than
            # replaying a stale low tag.
            lowest_prop_tag = None
            for other in self.client_map.values():
                if other.idle:
                    continue  # self is still marked idle here too
                if other.has_request():
                    p = other.next_request().tag.proportion + other.prop_delta
                else:
                    p = other.prev_tag.proportion + other.prop_delta
                if lowest_prop_tag is None or p < lowest_prop_tag:
                    lowest_prop_tag = p
            if lowest_prop_tag is not None and \
                    lowest_prop_tag < LOWEST_PROP_TAG_TRIGGER:
                rec.prop_delta = lowest_prop_tag - time_ns
            rec.idle = False

        tag = self._initial_tag(rec, req_params, time_ns, cost)

        if self.at_limit is AtLimit.REJECT and \
                tag.limit > time_ns + self.reject_threshold_ns:
            # over-limit: reject without taking ownership
            # (reference :989-993)
            return errno.EAGAIN

        rec.add_request(tag, request)
        rec.cur_rho = req_params.rho
        rec.cur_delta = req_params.delta
        return 0

    # ------------------------------------------------------------------
    # core: decide (reference do_next_request :1115-1186)
    # ------------------------------------------------------------------
    def _do_next_request(self, now_ns: int) -> NextReq:
        if not self.client_map:
            return NextReq.none()

        # constraint (reservation) phase
        reserv = self._resv_top()
        if reserv.has_request() and \
                reserv.next_request().tag.reservation <= now_ns:
            return NextReq.returning(HeapId.RESERVATION)

        # promote newly within-limit requests to ready
        # (reference :1135-1144); the loop takes the minimum-limit
        # non-ready client each time, so it marks exactly the clients
        # with head limit <= now
        while True:
            limits = self._limit_top()
            if not (limits.has_request()
                    and not limits.next_request().tag.ready
                    and limits.next_request().tag.limit <= now_ns):
                break
            limits.next_request().tag.ready = True

        # weight (proportion) phase
        readys = self._ready_top()
        if readys.has_request() and readys.next_request().tag.ready and \
                readys.next_request().tag.proportion < MAX_TAG:
            return NextReq.returning(HeapId.READY)

        # limit-break (reference :1157-1165); unlike the reference
        # (whose limit_break_sched_count is declared but never bumped)
        # we actually count these
        if self.at_limit is AtLimit.ALLOW:
            if readys.has_request() and \
                    readys.next_request().tag.proportion < MAX_TAG:
                self.limit_break_sched_count += 1
                return NextReq.returning(HeapId.READY)
            if reserv.has_request() and \
                    reserv.next_request().tag.reservation < MAX_TAG:
                self.limit_break_sched_count += 1
                return NextReq.returning(HeapId.RESERVATION)

        # nothing schedulable now: compute the next wake-up time
        # (reference :1170-1185)
        next_call = TIME_MAX
        if reserv.has_request():
            next_call = min_not_0_time(
                next_call, reserv.next_request().tag.reservation)
        limits = self._limit_top()
        if limits.has_request():
            nxt = limits.next_request().tag
            assert not nxt.ready or nxt.proportion >= MAX_TAG
            next_call = min_not_0_time(next_call, nxt.limit)
        if next_call < TIME_MAX:
            return NextReq.future(next_call)
        return NextReq.none()

    # ------------------------------------------------------------------
    # core: pop (reference pop_process_request :1046-1073,
    #            update_next_tag :1021-1041)
    # ------------------------------------------------------------------
    def _pop_process_request(self, heap_id: HeapId,
                             process: Callable[[Any, Cost, Any], None]
                             ) -> RequestTag:
        top = self._resv_top() if heap_id is HeapId.RESERVATION \
            else self._ready_top()
        head = top.next_request()
        request_cost = head.tag.cost
        request = head.request
        tag = head.tag
        top.pop_request()

        if self.delayed_tag_calc and top.has_request():
            # tag the new head with the latest rho/delta, using the
            # just-popped tag as the recurrence predecessor
            nxt = top.next_request()
            info = self._get_cli_info(top)
            assert info is not None
            nxt.tag = RequestTag.from_prev(tag, info, top.cur_delta,
                                           top.cur_rho, nxt.tag.arrival,
                                           nxt.tag.cost,
                                           self.anticipation_timeout_ns)
            top.update_req_tag(nxt.tag, self.tick)

        process(top.client, request_cost, request)
        return tag

    # reference reduce_reservation_tags (:1077-1111): weight-phase
    # service also pays down reservation debt
    def _reduce_reservation_tags(self, client_id: Any,
                                 tag: RequestTag) -> None:
        rec = self.client_map.get(client_id)
        assert rec is not None, "client GC'd while being scheduled"
        offset = rec.info.reservation_inv_ns * (tag.cost + tag.rho)
        if self.delayed_tag_calc:
            if rec.requests:
                rec.requests[0].tag.reservation -= offset
        else:
            for r in rec.requests:
                r.tag.reservation -= offset
        rec.prev_tag.reservation -= offset

    # ------------------------------------------------------------------
    # GC (reference do_clean :1206-1255)
    # ------------------------------------------------------------------
    def do_clean(self) -> None:
        now = self._monotonic()
        with self.data_mtx:
            self._clean_mark_points.append((now, self.tick))

            erase_point = self.last_erase_point
            while self._clean_mark_points and \
                    self._clean_mark_points[0][0] <= now - self.erase_age_s:
                self.last_erase_point = self._clean_mark_points[0][1]
                erase_point = self.last_erase_point
                self._clean_mark_points.popleft()

            idle_point = 0
            for t, tick in self._clean_mark_points:
                if t <= now - self.idle_age_s:
                    idle_point = tick
                else:
                    break

            erased_num = 0
            if erase_point > 0 or idle_point > 0:
                for key in list(self.client_map.keys()):
                    rec = self.client_map[key]
                    if erase_point and erased_num < self.erase_max and \
                            rec.last_tick <= erase_point:
                        del self.client_map[key]
                        erased_num += 1
                    elif idle_point and rec.last_tick <= idle_point:
                        rec.idle = True
                if erased_num >= self.erase_max:
                    if self._cleaning_job is not None:
                        self._cleaning_job.try_update(AGGRESSIVE_CHECK_TIME_S)
                else:
                    self.last_erase_point = 0
                    if self._cleaning_job is not None:
                        self._cleaning_job.try_update(self.check_time_s)

    # ------------------------------------------------------------------
    # observability (obs.registry wiring)
    # ------------------------------------------------------------------
    def register_metrics(self, registry, labels=None) -> None:
        """Expose the scheduling counters (reference :810-812) as
        callback gauges -- read lazily at drain time, so the hot path
        pays nothing."""
        for name, attr in (
                ("dmclock_sched_reservation_total", "reserv_sched_count"),
                ("dmclock_sched_priority_total", "prop_sched_count"),
                ("dmclock_sched_limit_break_total",
                 "limit_break_sched_count")):
            registry.gauge(name, "scheduling decisions by phase",
                           labels=labels).set_function(
                lambda a=attr: getattr(self, a))
        registry.gauge("dmclock_clients", "tracked client records",
                       labels=labels).set_function(
            lambda: len(self.client_map))

    # debugging dump (reference display_queues :676-697)
    def display_queues(self) -> str:
        with self.data_mtx:
            lines = []
            for name, key in (("RESER", self._resv_key),
                              ("LIMIT", self._limit_key),
                              ("READY", self._ready_key)):
                order = sorted(self.client_map.values(), key=key)
                lines.append(name + ": " + " | ".join(
                    f"{c.client}:{c.next_request().tag if c.has_request() else 'noreq'}"
                    for c in order))
            return "\n".join(lines)


@dataclass
class PullReq(Generic[C, R]):
    """Result of a pull (reference PullReq, :1286-1306).

    ``tag`` is the served request's tag triple when the backend
    materializes per-decision tags on the host (the oracle queues do;
    the TPU batch engine leaves it None) -- consumed by the decision
    trace (``obs.trace``), never by scheduling.
    """

    type: NextReqType
    client: Any = None
    request: Any = None
    phase: Optional[Phase] = None
    cost: int = 0
    when_ready: Optional[int] = None  # ns
    tag: Optional[RequestTag] = None

    def is_none(self) -> bool:
        return self.type is NextReqType.NONE

    def is_retn(self) -> bool:
        return self.type is NextReqType.RETURNING

    def is_future(self) -> bool:
        return self.type is NextReqType.FUTURE


def _now_ns() -> int:
    return sec_to_ns(_walltime.time())


class PullPriorityQueue(PriorityQueueBase[C, R]):
    """Server-polls mode (reference PullPriorityQueue, :1279-1501)."""

    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        if time_ns is None:
            time_ns = _now_ns()
        with self.data_mtx:
            return self._do_add_request(request, client_id, req_params,
                                        time_ns, cost)

    def pull_request(self, now_ns: Optional[int] = None) -> PullReq:
        if now_ns is None:
            now_ns = _now_ns()
        result: PullReq = PullReq(NextReqType.NONE)
        with self.data_mtx:
            nxt = self._do_next_request(now_ns)
            result.type = nxt.type
            if nxt.type is NextReqType.NONE:
                return result
            if nxt.type is NextReqType.FUTURE:
                result.when_ready = nxt.when_ready
                return result

            def process(client, cost, request):
                result.client = client
                result.request = request
                result.cost = cost

            if nxt.heap_id is HeapId.RESERVATION:
                result.phase = Phase.RESERVATION
                result.tag = self._pop_process_request(
                    HeapId.RESERVATION, process)
                self.reserv_sched_count += 1
            else:
                result.phase = Phase.PRIORITY
                tag = self._pop_process_request(HeapId.READY, process)
                self._reduce_reservation_tags(result.client, tag)
                result.tag = tag
                self.prop_sched_count += 1
            return result


class PushPriorityQueue(PriorityQueueBase[C, R]):
    """Queue-drives-server mode (reference PushPriorityQueue, :1504-1797).

    ``handle_f(client, request, phase, cost)`` is invoked whenever
    ``can_handle_f()`` is true and a request is eligible; timed wakeups
    for future-eligible requests run on a dedicated sched-ahead thread
    (reference run_sched_ahead :1760-1786).

    Virtual-time embedding (the discrete-event sim): pass ``now_ns_f``
    (the simulated clock) and ``sched_at_f`` (schedules a callback that
    must invoke ``sched_ahead_fire()`` at the given virtual time -- it
    disarms the deduplicated deadline before re-evaluating); no
    sched-ahead thread is spawned then, and scheduling decisions and
    default arrival stamps read the virtual clock.
    """

    def __init__(self, client_info_f: ClientInfoFunc,
                 can_handle_f: Callable[[], bool],
                 handle_f: Callable[[Any, Any, Phase, Cost], None],
                 now_ns_f: Optional[Callable[[], int]] = None,
                 sched_at_f: Optional[Callable[[int], None]] = None,
                 **kwargs):
        super().__init__(client_info_f, **kwargs)
        self.can_handle_f = can_handle_f
        self.handle_f = handle_f
        self._now_ns_f = now_ns_f or _now_ns
        self._sched_at_f = sched_at_f
        self._sched_ahead_cv = threading.Condition()
        self._sched_ahead_when = TIME_ZERO  # ns
        self._sched_ahead_thd = None
        if sched_at_f is None:
            self._sched_ahead_thd = threading.Thread(
                target=self._run_sched_ahead, daemon=True,
                name="dmclock-sched-ahead")
            self._sched_ahead_thd.start()

    def shutdown(self) -> None:
        super().shutdown()
        with self._sched_ahead_cv:
            self._sched_ahead_cv.notify_all()
        if self._sched_ahead_thd is not None:
            self._sched_ahead_thd.join()

    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        if time_ns is None:
            time_ns = self._now_ns_f()
        with self.data_mtx:
            r = self._do_add_request(request, client_id, req_params,
                                     time_ns, cost)
            if r == 0:
                self._schedule_request()
            return r

    def request_completed(self) -> None:
        with self.data_mtx:
            self._schedule_request()

    # -- internals (data_mtx held) ------------------------------------
    def _submit_request(self, heap_id: HeapId) -> None:
        # reference submit_top_request/submit_request (:1674-1715)
        meta: Dict[str, Any] = {}

        def process(client, cost, request):
            meta["client"] = client
            self.handle_f(client, request,
                          Phase.RESERVATION if heap_id is HeapId.RESERVATION
                          else Phase.PRIORITY, cost)

        tag = self._pop_process_request(heap_id, process)
        if heap_id is HeapId.RESERVATION:
            self.reserv_sched_count += 1
        else:
            self._reduce_reservation_tags(meta["client"], tag)
            self.prop_sched_count += 1

    def _schedule_request(self) -> None:
        # reference schedule_request (:1741-1755) + can_handle gate
        # (next_request :1729-1737)
        if not self.can_handle_f():
            return
        nxt = self._do_next_request(self._now_ns_f())
        if nxt.type is NextReqType.RETURNING:
            self._submit_request(nxt.heap_id)
        elif nxt.type is NextReqType.FUTURE:
            self._sched_at(nxt.when_ready)

    def _sched_at(self, when_ns: int) -> None:
        # reference sched_at (:1789-1796); with a virtual sched_at_f
        # the armed-deadline dedup still applies, and the embedder's
        # timed callback must invoke sched_ahead_fire()
        with self._sched_ahead_cv:
            if self.finishing:
                return
            if self._sched_ahead_when == TIME_ZERO or \
                    when_ns < self._sched_ahead_when:
                self._sched_ahead_when = when_ns
                if self._sched_at_f is not None:
                    self._sched_at_f(when_ns)
                else:
                    self._sched_ahead_cv.notify_all()

    def sched_ahead_fire(self) -> None:
        """Virtual-time embedding: the ``sched_at_f`` callback landed --
        disarm and re-evaluate scheduling at the (virtual) now."""
        with self._sched_ahead_cv:
            if self.finishing:
                return
            self._sched_ahead_when = TIME_ZERO
        with self.data_mtx:
            self._schedule_request()

    def _run_sched_ahead(self) -> None:
        # reference run_sched_ahead (:1760-1786); the armed deadline is
        # only consumed once it has actually passed -- an early wakeup
        # (a newer, earlier deadline from _sched_at) just re-evaluates
        # the wait, so timed wakeups can't be dropped
        with self._sched_ahead_cv:
            while not self.finishing:
                if self._sched_ahead_when == TIME_ZERO:
                    self._sched_ahead_cv.wait()
                    continue
                delay_s = (self._sched_ahead_when
                           - self._now_ns_f()) / NS_PER_SEC
                if delay_s > 0:
                    self._sched_ahead_cv.wait(timeout=delay_s)
                    continue
                self._sched_ahead_when = TIME_ZERO
                if self.finishing:
                    return
                self._sched_ahead_cv.release()
                try:
                    with self.data_mtx:
                        self._schedule_request()
                finally:
                    self._sched_ahead_cv.acquire()
