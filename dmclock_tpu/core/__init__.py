"""Core dmClock semantics: tag algebra, records, oracle scheduler, tracker.

This layer is pure Python (no JAX) and is the golden model every other
backend (C++ native runtime, TPU batch engine) is verified against.
"""

from .qos import ClientInfo
from .recs import Cost, Counter, Phase, ReqParams
from .scheduler import (AtLimit, ClientRec, ClientReq, HeapId, NextReq,
                        NextReqType, PriorityQueueBase, PullPriorityQueue,
                        PullReq, PushPriorityQueue)
from .tags import RequestTag, ZERO_TAG, tag_calc
from .timebase import (MAX_TAG, MIN_TAG, NS_PER_SEC, TIME_MAX, TIME_ZERO,
                       format_tag, min_not_0_time, ns_to_sec,
                       rate_to_inv_ns, sec_to_ns)
from .tracker import (BorrowingTracker, GlobalCounters, OrigTracker,
                      ServiceTracker)

__all__ = [
    "ClientInfo", "Cost", "Counter", "Phase", "ReqParams",
    "AtLimit", "ClientRec", "ClientReq", "HeapId", "NextReq",
    "NextReqType", "PriorityQueueBase", "PullPriorityQueue", "PullReq",
    "PushPriorityQueue",
    "RequestTag", "ZERO_TAG", "tag_calc",
    "MAX_TAG", "MIN_TAG", "NS_PER_SEC", "TIME_MAX", "TIME_ZERO",
    "format_tag", "min_not_0_time", "ns_to_sec", "rate_to_inv_ns",
    "sec_to_ns",
    "BorrowingTracker", "GlobalCounters", "OrigTracker", "ServiceTracker",
]
