"""Shared wire-level record types of the dmClock protocol.

Equivalents of the reference's ``dmclock_recs.h``: ``Counter``/``Cost``
scalar types, the reservation-vs-priority phase marker, and
``ReqParams{delta, rho}`` -- the entire payload a client piggybacks onto
each request (reference ``src/dmclock_recs.h:25-72``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

# Counter: monotone completion counters (reference dmclock_recs.h:25).
Counter = int
# Cost: per-request service cost (reference dmclock_recs.h:31).
Cost = int


class Phase(enum.IntEnum):
    """Which scheduling phase served a request (dmclock_recs.h:33).

    Servers return this to clients; clients bump rho only for
    reservation-phase completions.
    """

    RESERVATION = 0
    PRIORITY = 1

    def __str__(self) -> str:  # matches reference operator<< spirit
        return "reservation" if self is Phase.RESERVATION else "priority"


@dataclass(frozen=True)
class ReqParams:
    """Per-request distributed-protocol payload (dmclock_recs.h:40-72).

    delta: count of ALL completions this client saw (across every
    server) since its previous request to the receiving server.
    rho: same, but only reservation-phase completions.
    Invariant: rho <= delta (dmclock_recs.h:51).
    """

    delta: int = 0
    rho: int = 0

    def __post_init__(self) -> None:
        if self.rho > self.delta:
            raise ValueError(f"ReqParams invariant violated: rho {self.rho} > delta {self.delta}")

    def __str__(self) -> str:
        return f"ReqParams{{ delta:{self.delta}, rho:{self.rho} }}"
