"""The mClock/dmClock request-tag algebra, in int64-nanosecond fixed point.

Equivalent of the reference's ``RequestTag`` (``src/dmclock_server.h:135-274``).
Each request carries three virtual-time tags:

  reservation = max(t, prev_r + r_inv * (rho   + cost))   # uses rho
  proportion  = max(t, prev_p + w_inv * (delta + cost))   # uses delta
  limit       = max(t, prev_l + l_inv * (delta + cost))   # uses delta

where a zero inverse pins the tag to MAX_TAG (reservation/proportion:
"never eligible on this axis") or MIN_TAG (limit: "never limited") --
reference ``tag_calc`` at ``dmclock_server.h:246-259``.

Anticipation (deceptive-idleness countermeasure, ``:159-161``): an
arrival within ``anticipation_timeout`` of the previous request's
arrival is backdated by the timeout so briefly-idle clients don't lose
accumulated credit.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from .qos import ClientInfo
from .timebase import (MAX_CHARGE_UNITS, MAX_TAG, MIN_TAG,
                       ORGANIC_TAG_CAP)

__all__ = ["tag_calc", "RequestTag", "ZERO_TAG"]


def tag_calc(time_ns: int, prev_ns: int, inv_ns: int, dist_val: int,
             extreme_is_high: bool, cost: int) -> int:
    """One tag-axis update (reference dmclock_server.h:246-259).

    inv_ns == 0 means the axis is disabled -> pin to the sentinel.
    Otherwise advance the per-client virtual clock by inv_ns units per
    unit of (distributed credit + cost), floored at wall time.

    Charged units saturate at MAX_CHARGE_UNITS and the result at
    ORGANIC_TAG_CAP so organic tags never reach a sentinel and the
    arithmetic stays in-range on true-int64 backends.
    """
    if inv_ns == 0:
        return MAX_TAG if extreme_is_high else MIN_TAG
    units = min(dist_val + cost, MAX_CHARGE_UNITS)
    return min(max(time_ns, prev_ns + inv_ns * units), ORGANIC_TAG_CAP)


@dataclass
class RequestTag:
    """Tags + protocol metadata for one queued request
    (reference dmclock_server.h:135-274).

    ``ready`` flips true once the request's limit tag has passed
    (within-limit), enabling weight-phase service.  ``arrival`` is the
    wall time the request entered the queue (drives anticipation).
    """

    reservation: int
    proportion: int
    limit: int
    arrival: int
    delta: int = 0
    rho: int = 0
    cost: int = 1
    ready: bool = False

    @classmethod
    def from_prev(cls, prev: "RequestTag", info: ClientInfo,
                  delta: int, rho: int, time_ns: int, cost: int = 1,
                  anticipation_timeout_ns: int = 0) -> "RequestTag":
        """The tag recurrence (reference dmclock_server.h:145-183)."""
        assert cost > 0
        max_time = time_ns
        if time_ns - anticipation_timeout_ns < prev.arrival:
            max_time -= anticipation_timeout_ns
        reservation = tag_calc(max_time, prev.reservation,
                               info.reservation_inv_ns, rho, True, cost)
        proportion = tag_calc(max_time, prev.proportion,
                              info.weight_inv_ns, delta, True, cost)
        limit = tag_calc(max_time, prev.limit,
                         info.limit_inv_ns, delta, False, cost)
        # At least one of reservation/proportion must be usable
        # (reference asserts this, dmclock_server.h:182).
        assert reservation < MAX_TAG or proportion < MAX_TAG, \
            "client has neither reservation nor weight"
        return cls(reservation=reservation, proportion=proportion,
                   limit=limit, arrival=time_ns, delta=delta, rho=rho,
                   cost=cost, ready=False)

    def copy(self) -> "RequestTag":
        return replace(self)

    def __str__(self) -> str:
        from .timebase import format_tag
        return (f"{{ RequestTag:: ready:{str(self.ready).lower()}"
                f" r:{format_tag(self.reservation)}"
                f" p:{format_tag(self.proportion)}"
                f" l:{format_tag(self.limit)} }}")


# The zero tag used for not-yet-tagged queued requests under delayed tag
# calculation (reference initial_tag(DelayedTagCalc), dmclock_server.h:878-880)
# and as every client's initial prev_tag (reference ClientRec ctor :385).
ZERO_TAG = RequestTag(reservation=0, proportion=0, limit=0, arrival=0,
                      delta=0, rho=0, cost=1, ready=False)
