"""Canonical time / tag arithmetic base for dmclock-tpu.

The reference (``/root/reference/src/dmclock_util.h:33``) represents time
as ``double`` seconds since the epoch and tags as ``double`` virtual
times.  TPUs have no fast f64, so this framework instead defines ONE
canonical fixed-point algebra -- int64 nanoseconds -- implemented
identically in the Python oracle scheduler, the C++ native runtime, and
the JAX/Pallas device kernels.  Because every backend performs the same
integer arithmetic, request-ordering parity between backends is exact
(bit-equal), not merely approximate.

Sentinels: the reference pins tags to +/-infinity when a QoS rate is
zero (``dmclock_server.h:60-65``, ``tag_calc`` at ``:246-259``).  Here
MAX_TAG / MIN_TAG are +/-2^62 -- far beyond any organic nanosecond
timestamp (year-2026 epoch ns ~= 1.8e18 < 2^62 ~= 4.6e18) yet leaving
int64 headroom so that ``prev + increment`` on organic values can never
collide with a sentinel.
"""

from __future__ import annotations

NS_PER_SEC = 1_000_000_000

# Tag sentinels (reference: max_tag/min_tag, dmclock_server.h:60-65).
MAX_TAG = 1 << 62
MIN_TAG = -(1 << 62)

# Time sentinels (reference: TimeZero/TimeMax, dmclock_util.h:34-35).
TIME_ZERO = 0
TIME_MAX = 1 << 62

# Idle-reactivation trigger: the reference uses DBL_MAX/3 as "much
# larger than any organic value" (dmclock_server.h:957-958); ours is
# MAX_TAG/2 for the same purpose.
LOWEST_PROP_TAG_TRIGGER = MAX_TAG // 2

# Saturation bounds keeping the int64 algebra overflow-free on every
# backend (Python ints don't overflow, but the C++/JAX backends are
# true int64 where wraparound is silent):
#   inv <= 2^40 ns/unit (rates below ~0.00091 ops/s saturate),
#   charged units (dist + cost) <= 2^20 per request,
# so one increment is < 2^60 and prev (< 2^62) + increment < 2^63.
# Organic tags are additionally capped at MAX_TAG - 1 so they can never
# equal a sentinel.
MAX_INV_NS = 1 << 40
MAX_CHARGE_UNITS = 1 << 20
ORGANIC_TAG_CAP = MAX_TAG - 1


def sec_to_ns(t: float) -> int:
    """Convert float seconds to integer nanoseconds (round-to-nearest)."""
    return round(t * NS_PER_SEC)


def ns_to_sec(t_ns: int) -> float:
    return t_ns / NS_PER_SEC


def rate_to_inv_ns(rate: float) -> int:
    """QoS rate (ops/sec) -> nanoseconds of virtual time per unit cost.

    Mirrors ``ClientInfo::update`` (dmclock_server.h:111-118) which
    caches ``1/rate`` with a 0 -> 0 sentinel meaning "axis disabled".
    Rounding happens exactly once, here, so all backends agree.
    Saturates at MAX_INV_NS (see above) to keep int64 backends
    overflow-free for absurdly low rates.
    """
    if rate == 0.0:
        return 0
    return min(round(NS_PER_SEC / rate), MAX_INV_NS)


def min_not_0_time(current: int, possible: int) -> int:
    """Minimum of two times where TIME_ZERO means "no time".

    Mirrors ``min_not_0_time`` (dmclock_server.h:1192-1195).
    """
    if possible == TIME_ZERO:
        return current
    return min(current, possible)


def format_tag(value_ns: int, modulo: int = 1_000_000) -> str:
    """Human-readable tag: 'max' / 'min' sentinels else seconds modulo.

    Mirrors ``RequestTag::format_tag`` (dmclock_server.h:234-242) and
    ``format_time`` (dmclock_util.cc:24-29).
    """
    if value_ns >= MAX_TAG:
        return "max"
    if value_ns <= MIN_TAG:
        return "min"
    sec = value_ns / NS_PER_SEC
    return f"{sec % modulo:0.6f}"
