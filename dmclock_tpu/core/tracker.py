"""Client-side distributed service tracking.

Equivalent of the reference's ``src/dmclock_client.h``: a client keeps
global completion counters (delta = all completions, rho =
reservation-phase completions) plus one per-server tracker; each request
to server S carries the counter movement since the previous request to
S, minus the client's own contribution there -- the entire "distributed
protocol" of dmClock.  Two accounting policies are provided, mirroring
``OrigTracker`` (:39-84) and ``BorrowingTracker`` (:90-154).

The TPU-native scale-out version of the same contract (counters as
mesh-sharded arrays, corrections via psum) lives in
``dmclock_tpu.parallel``.
"""

from __future__ import annotations

import threading
import time as _walltime
from collections import deque
from typing import Callable, Deque, Dict, Generic, Tuple, TypeVar

from .recs import Cost, Counter, Phase, ReqParams
from ..utils.periodic import PeriodicTask

S = TypeVar("S")  # server id type


class OrigTracker:
    """Best-effort original dmClock delta/rho accounting
    (reference dmclock_client.h:39-84)."""

    __slots__ = ("delta_prev_req", "rho_prev_req", "my_delta", "my_rho")

    def __init__(self, global_delta: Counter, global_rho: Counter):
        self.delta_prev_req = global_delta
        self.rho_prev_req = global_rho
        self.my_delta = 0
        self.my_rho = 0

    @classmethod
    def create(cls, the_delta: Counter, the_rho: Counter) -> "OrigTracker":
        return cls(the_delta, the_rho)

    def prepare_req(self, counters: "GlobalCounters") -> ReqParams:
        delta_out = counters.delta - self.delta_prev_req - self.my_delta
        rho_out = counters.rho - self.rho_prev_req - self.my_rho
        self.delta_prev_req = counters.delta
        self.rho_prev_req = counters.rho
        self.my_delta = 0
        self.my_rho = 0
        return ReqParams(int(delta_out), int(rho_out))

    def resp_update(self, phase: Phase, counters: "GlobalCounters",
                    cost: Cost) -> None:
        counters.delta += cost
        self.my_delta += cost
        if phase is Phase.RESERVATION:
            counters.rho += cost
            self.my_rho += cost

    def get_last_delta(self) -> Counter:
        return self.delta_prev_req


class BorrowingTracker:
    """Always-positive delta/rho accounting by borrowing future replies
    (reference dmclock_client.h:90-154)."""

    __slots__ = ("delta_prev_req", "rho_prev_req", "delta_borrow",
                 "rho_borrow")

    def __init__(self, global_delta: Counter, global_rho: Counter):
        self.delta_prev_req = global_delta
        self.rho_prev_req = global_rho
        self.delta_borrow = 0
        self.rho_borrow = 0

    @classmethod
    def create(cls, the_delta: Counter, the_rho: Counter) -> "BorrowingTracker":
        return cls(the_delta, the_rho)

    @staticmethod
    def _calc_with_borrow(global_c: Counter, previous: Counter,
                          borrow: int) -> Tuple[Counter, int]:
        # reference calc_with_borrow (:110-129)
        result = global_c - previous
        if result == 0:
            return 1, borrow + 1
        if result > borrow:
            return result - borrow, 0
        return 1, borrow - result + 1

    def prepare_req(self, counters: "GlobalCounters") -> ReqParams:
        delta_out, self.delta_borrow = self._calc_with_borrow(
            counters.delta, self.delta_prev_req, self.delta_borrow)
        rho_out, self.rho_borrow = self._calc_with_borrow(
            counters.rho, self.rho_prev_req, self.rho_borrow)
        self.delta_prev_req = counters.delta
        self.rho_prev_req = counters.rho
        return ReqParams(int(delta_out), int(rho_out))

    def resp_update(self, phase: Phase, counters: "GlobalCounters",
                    cost: Cost) -> None:
        counters.delta += cost
        if phase is Phase.RESERVATION:
            counters.rho += cost

    def get_last_delta(self) -> Counter:
        return self.delta_prev_req


class GlobalCounters:
    """The client's global completion counters.

    Start at 1 because 0 is reserved by the cleaning logic
    (reference dmclock_client.h:191-198)."""

    __slots__ = ("delta", "rho")

    def __init__(self):
        self.delta: Counter = 1
        self.rho: Counter = 1


class ServiceTracker(Generic[S]):
    """Per-client distributed state across servers
    (reference ServiceTracker, dmclock_client.h:157-287).

    tracker_cls plugs in the accounting policy (OrigTracker default).
    """

    def __init__(self, tracker_cls=OrigTracker,
                 clean_every_s: float = 300.0,
                 clean_age_s: float = 600.0,
                 run_gc_thread: bool = True,
                 monotonic_clock: Callable[[], float] = _walltime.monotonic):
        self._tracker_cls = tracker_cls
        self.counters = GlobalCounters()
        self.server_map: Dict[S, object] = {}
        self.data_mtx = threading.Lock()
        self.clean_age_s = clean_age_s
        self._clean_mark_points: Deque[Tuple[float, Counter]] = deque()
        self._monotonic = monotonic_clock
        self._cleaning_job: PeriodicTask | None = None
        if run_gc_thread:
            self._cleaning_job = PeriodicTask(clean_every_s, self.do_clean)

    def shutdown(self) -> None:
        if self._cleaning_job is not None:
            self._cleaning_job.stop()
            self._cleaning_job = None

    def track_resp(self, server_id: S, phase: Phase,
                   request_cost: Cost = 1) -> None:
        """Incorporate a response (reference track_resp :221-236).

        Self-heals by creating a tracker if a response arrives for an
        unknown (possibly GC'd) server.
        """
        with self.data_mtx:
            t = self.server_map.get(server_id)
            if t is None:
                t = self._tracker_cls.create(self.counters.delta,
                                             self.counters.rho)
                self.server_map[server_id] = t
            t.resp_update(phase, self.counters, request_cost)

    def get_req_params(self, server: S) -> ReqParams:
        """ReqParams to piggyback on the next request to ``server``
        (reference get_req_params :241-251)."""
        with self.data_mtx:
            t = self.server_map.get(server)
            if t is None:
                self.server_map[server] = self._tracker_cls.create(
                    self.counters.delta, self.counters.rho)
                return ReqParams(1, 1)
            return t.prepare_req(self.counters)

    def do_clean(self) -> None:
        """GC server records unused for clean_age
        (reference do_clean :263-286)."""
        now = self._monotonic()
        with self.data_mtx:
            self._clean_mark_points.append((now, self.counters.delta))
            earliest = 0
            while self._clean_mark_points and \
                    self._clean_mark_points[0][0] <= now - self.clean_age_s:
                earliest = self._clean_mark_points[0][1]
                self._clean_mark_points.popleft()
            if earliest > 0:
                for key in list(self.server_map.keys()):
                    if self.server_map[key].get_last_delta() <= earliest:
                        del self.server_map[key]
