"""Slot management: client id <-> dense slot index over growing HBM arrays.

The epoch engines run dense passes over ``[capacity]`` state arrays, so
an *open* client population (the reference serves one: clients register,
idle out, get erased -- ``dmclock_server.h:913-932``, ``:1206-1255``)
needs three mechanisms the frozen-at-init state lacked:

- **allocation**: a host-side map from client id to slot index, with a
  lowest-slot-first free list.  Lowest-first is deliberate: the free
  order is then a pure function of the occupied-slot set, so a resume
  can rebuild the exact allocator state from the checkpointed
  ``cid_of_slot`` array alone (docs/LIFECYCLE.md).
- **growth**: geometric doubling via ``engine.state.grow_state`` -- an
  exact pytree migration whose new slots are byte-identical to
  init-time ones, so growing mid-run cannot perturb a decision.
- **compaction**: churn fragments the live set across the slot space,
  and every launch pays a dense pass over ALL of it.  A compaction
  epoch repacks live clients into a dense prefix as ONE device launch
  (a gather by a host-computed permutation).  Every selection reduction
  in the engines is permutation-invariant (mins/sums/any; sorts and
  argmin tie-breaks key on the per-client ``order`` field, which moves
  with its row), so a compacted run serves the same client-id decision
  stream as an uncompacted one -- the digest gate in
  tests/test_lifecycle.py and the ci.sh churn smoke pin it.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional

import numpy as np


def owner_shard(cids, n_shards: int):
    """Client->shard ownership for the mesh lifecycle plane: id
    ``c`` lives on shard ``c % n_shards``.  Deterministic and
    spec-independent, so a dynamic run, its static variant, and a
    resumed incarnation all route the same id to the same shard --
    the precondition of the S>1 dynamic==static digest gate
    (docs/LIFECYCLE.md "Per-shard routing")."""
    return np.asarray(cids) % int(n_shards)


def owned_ids(total: int, shard: int, n_shards: int) -> np.ndarray:
    """Ascending client ids shard ``shard`` owns out of ``total``."""
    ids = np.arange(int(total), dtype=np.int64)
    return ids[ids % int(n_shards) == int(shard)]


class SlotMap:
    """Host-side client-id <-> slot-index map with slot recycling.

    Client ids are non-negative ints (the lifecycle plane's id space;
    the pull queue keeps its own hashable-id map).  ``cid_of_slot`` is
    the canonical state: everything else (the reverse map, the free
    heap) is derived, which is what makes the map checkpointable as a
    single int64 array plus three scalars."""

    def __init__(self, capacity: int):
        self.cid_of_slot = np.full(capacity, -1, dtype=np.int64)
        self.ever_used = np.zeros(capacity, dtype=bool)
        self.slot_of: Dict[int, int] = {}
        self._free: List[int] = list(range(capacity))
        heapq.heapify(self._free)
        self.next_order = 0

    @property
    def capacity(self) -> int:
        return int(self.cid_of_slot.shape[0])

    @property
    def live_count(self) -> int:
        return len(self.slot_of)

    def allocate(self, cid: int) -> int:
        """Bind ``cid`` to the lowest free slot; returns the slot and
        the creation order it should carry (via ``take_order``), or -1
        when full (caller grows and retries).  ``cid`` must not be
        registered."""
        cid = int(cid)
        assert cid >= 0 and cid not in self.slot_of, cid
        if not self._free:
            return -1
        slot = heapq.heappop(self._free)
        self.cid_of_slot[slot] = cid
        self.slot_of[cid] = slot
        return slot

    def take_order(self) -> int:
        order = self.next_order
        self.next_order += 1
        return order

    def was_used(self, slot: int) -> bool:
        """True when ``slot`` held an earlier tenant (a recycle); marks
        it used either way."""
        prior = bool(self.ever_used[slot])
        self.ever_used[slot] = True
        return prior

    def release(self, cid: int) -> int:
        slot = self.slot_of.pop(int(cid))
        self.cid_of_slot[slot] = -1
        heapq.heappush(self._free, slot)
        return slot

    def grow(self, new_capacity: int) -> None:
        old = self.capacity
        assert new_capacity > old
        self.cid_of_slot = np.concatenate(
            [self.cid_of_slot,
             np.full(new_capacity - old, -1, dtype=np.int64)])
        self.ever_used = np.concatenate(
            [self.ever_used, np.zeros(new_capacity - old, dtype=bool)])
        for s in range(old, new_capacity):
            heapq.heappush(self._free, s)

    # -- compaction ----------------------------------------------------
    def compaction_perm(self) -> Optional[np.ndarray]:
        """Permutation packing live slots into a dense prefix (stable:
        live slots keep their relative order), or None when the live
        set is already dense -- the caller skips the launch."""
        live = np.flatnonzero(self.cid_of_slot >= 0)
        if live.size == 0 or int(live[-1]) == live.size - 1:
            return None
        free = np.flatnonzero(self.cid_of_slot < 0)
        return np.concatenate([live, free]).astype(np.int32)

    def apply_perm(self, perm: np.ndarray) -> None:
        """Re-map after the device state was gathered by ``perm``."""
        self.cid_of_slot = self.cid_of_slot[perm]
        self.ever_used = self.ever_used[perm]
        self.slot_of = {int(c): s
                        for s, c in enumerate(self.cid_of_slot)
                        if c >= 0}
        self._free = [int(s) for s in
                      np.flatnonzero(self.cid_of_slot < 0)]
        heapq.heapify(self._free)

    # -- client-id-space views -----------------------------------------
    def translate(self, slot_arr) -> np.ndarray:
        """Map an int slot array into client-id space (-1 and other
        negative pads pass through) -- the canonicalization that makes
        decision streams comparable across slot layouts (compaction,
        recycling, growth all shuffle slots but never client ids)."""
        a = np.asarray(slot_arr)
        out = np.full(a.shape, -1, dtype=np.int64)
        valid = (a >= 0) & (a < self.capacity)
        out[valid] = self.cid_of_slot[a[valid]]
        return out

    def scatter_by_cid(self, arr, total: int) -> np.ndarray:
        """Re-index a per-slot array (last axis = capacity) into a
        per-client-id array of width ``total`` (unregistered ids keep
        zero) -- the calendar engine's per-client ``served`` counts
        canonicalize this way."""
        a = np.asarray(arr)
        assert a.shape[-1] == self.capacity, (a.shape, self.capacity)
        out = np.zeros(a.shape[:-1] + (total,), dtype=a.dtype)
        live = self.cid_of_slot >= 0
        out[..., self.cid_of_slot[live]] = a[..., live]
        return out

    # -- checkpoint round-trip -----------------------------------------
    def encode(self) -> dict:
        return {"lc_cids": self.cid_of_slot.copy(),
                "lc_ever": self.ever_used.copy(),
                "lc_next_order": np.int64(self.next_order)}

    @classmethod
    def load(cls, payload: dict) -> "SlotMap":
        cids = np.asarray(payload["lc_cids"], dtype=np.int64)
        m = cls(int(cids.shape[0]))
        m.cid_of_slot = cids.copy()
        m.ever_used = np.asarray(payload["lc_ever"],
                                 dtype=bool).copy()
        m.next_order = int(payload["lc_next_order"])
        m.slot_of = {int(c): s for s, c in enumerate(cids) if c >= 0}
        m._free = [int(s) for s in np.flatnonzero(cids < 0)]
        heapq.heapify(m._free)
        return m


# ----------------------------------------------------------------------
# device-side compaction launch
# ----------------------------------------------------------------------

_COMPACT_JIT: dict = {}


def compact_tree(tree, perm):
    """Gather every leaf of a pytree of ``[capacity, ...]`` arrays by
    ``perm`` along axis 0 in ONE jitted launch -- the compaction
    epoch's device half.  Works for the EngineState and for the
    per-slot telemetry ledger alike; jax retraces per new
    shape-structure automatically."""
    import jax
    import jax.numpy as jnp

    if "take" not in _COMPACT_JIT:
        _COMPACT_JIT["take"] = jax.jit(
            lambda t, p: jax.tree.map(
                lambda a: jnp.take(a, p, axis=0), t))
    return _COMPACT_JIT["take"](tree, jnp.asarray(perm,
                                                  dtype=jnp.int32))
