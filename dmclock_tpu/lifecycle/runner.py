"""Serial-engine churn runner: the lifecycle digest gate's oracle leg.

The supervisor (``robust.supervisor.EpochJob(churn=spec)``) runs churn
specs on the prefix/chain/calendar epoch engines, round and stream
loops.  This module runs the SAME spec on the serial reference engine
(``kernels.engine_run`` -- the oracle every epoch engine is pinned
against), with the same boundary grid, the same RNG consumption, and
the same canonical client-id-space digest, so the dynamic-vs-static
gate covers serial too (ISSUE 9 acceptance: serial/prefix/chain/
calendar x round/stream)."""

from __future__ import annotations

import hashlib
from types import SimpleNamespace

import numpy as np

from ..engine import kernels
from ..engine.state import init_state
from ..engine.stream import jit_ingest_step
from . import churn as churn_mod
from .plane import LifecyclePlane

_RUN_JIT: dict = {}


def _jit_run(steps: int):
    if steps not in _RUN_JIT:
        import functools

        import jax

        _RUN_JIT[steps] = jax.jit(functools.partial(
            kernels.engine_run, steps=steps, allow_limit_break=False,
            anticipation_ns=0))
    return _RUN_JIT[steps]


def run_serial_churn(spec: dict, *, epochs: int, every: int = 2,
                     steps: int = 16, ring: int = 16, waves: int = 2,
                     dt_epoch_ns: int = 10 ** 8, seed: int = 11,
                     plane: LifecyclePlane = None):
    """Run ``spec`` for ``epochs`` on the serial engine; boundary grid
    = every ``every`` epochs (the supervisor's ``ckpt_every`` grid).
    Returns ``(digest_hex, plane, decisions)`` where the digest is the
    canonical client-id-space chain digest -- comparable across the
    dynamic spec and its :func:`~.churn.static_variant`, and across
    engines only in the sense of the same canonical FORM (each engine
    keeps its own decision layout).  ``plane`` may be passed in (e.g.
    pre-loaded with accepted control ops)."""
    from ..robust.supervisor import _digest_update

    if plane is None:
        plane = LifecyclePlane(spec)
    state = init_state(spec["capacity0"], ring)
    rng = np.random.Generator(np.random.PCG64(seed))
    ingest = jit_ingest_step(dt_epoch_ns=dt_epoch_ns, waves=waves)
    run = _jit_run(steps)
    digest = b"\x00" * 32
    decisions = 0
    for e in range(epochs):
        if e % every == 0:
            state, _ = plane.boundary(state, e, every)
        lam = churn_mod.lam_vector(spec, e)
        raw = rng.poisson(lam).astype(np.int32)
        t_base = e * dt_epoch_ns
        state = ingest(state, plane.map_counts(raw), t_base)
        state, _, decs = run(state, np.int64(t_base + dt_epoch_ns))
        import jax

        d = jax.device_get(decs)
        dec = SimpleNamespace(type=d.type, phase=d.phase, cost=d.cost)
        dec.slot = plane.slots.translate(np.asarray(d.slot))
        decisions += int((np.asarray(d.type) == kernels.RETURNING)
                         .sum())
        digest = _digest_update(digest, (dec,))
    return hashlib.sha256(digest).hexdigest(), plane, decisions
