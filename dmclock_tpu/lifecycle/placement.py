"""Shard placement + live client migration: the inter-server routing
layer over the per-shard lifecycle planes (docs/LIFECYCLE.md
"Placement and migration").

The paper's inter-server coordination is exactly a per-client
(delta, rho) counter handoff, which means a client can MOVE between
servers with nothing but the piggyback contract -- yet the mesh pinned
every client to ``cid % n_shards`` forever, so the ``shard_skew``
scenario melts one shard while its siblings idle.  This module is the
RackSched-shaped two-level fix: an inter-server placement policy
routing over otherwise-unmodified per-server engines.

- **Placement** (:meth:`PlacementMap.place_batch`): new registrations
  sample TWO candidate shards from the checkpointed placement RNG and
  pick the lower ``dmclock_shard_pressure_*`` backlog
  (power-of-two-choices).  ``mode="static"`` keeps the historical
  ``cid % n_shards`` ownership bit-identically (the map is not even
  attached then); scenario **pins** (``placement_pins``) keep
  workloads whose shape IS the ownership function -- ``shard_skew``'s
  hot mask is ``cid % n_shards == hot_shard`` -- on their scripted
  shards without consuming RNG.  Under a fault plan, a registration
  whose sampled choices are DOWN re-routes to the live one, or defers
  one boundary when both are down (the supervisor's old up-front
  ValueError became this defined behavior).
- **Migration** (:meth:`PlacementMap.plan_moves` + the supervisor's
  ``_mesh_migrate``): at a controller-fired boundary, drained clients
  leave the hottest shard as the EXISTING digest-neutral ops -- EVICT
  on the source (final ledger row folded into the departed report),
  REGISTER on the destination with the carried (delta, rho) counter
  views and provenance watermark riding as boundary extras.  The
  canonical client-id-space digest gate: a run that migrates a
  quiet-since-start client at boundary B is bit-identical to a run
  that placed it on the destination from the start (tests/
  test_placement.py; the ci.sh migration smoke).
- **Determinism**: the RNG is a checkpointed PCG64 (``pm_*`` rotation
  leaves), pinned ids never consume draws, unpinned registrations
  always consume exactly two -- so a resumed incarnation, and a twin
  run given ``overrides`` (run B of the digest gate), replay the
  identical placement stream.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# test seam: called between the stages of a live migration
# (``evicted`` -> ``handoff`` -> ``registered``) -- the "SIGKILL
# mid-migration" injection points of the crash-equivalence matrix
# (tests/test_placement.py).  Signature: hook(stage: str).
_migrate_hook = None

PM_COUNTER_KEYS = ("placements", "p2c_draws", "migrations",
                   "reroutes", "defers", "overrides")


def parse_placement(obj) -> Tuple[str, Dict[int, int]]:
    """Normalize ``EpochJob.placement`` (None / ``"static"`` /
    ``"p2c"`` / ``{"mode": .., "overrides": {cid: shard}}``) to
    ``(mode, overrides)``.  The dict form is what the digest gate's
    run-B twin uses: moved clients placed on their run-A destinations
    from the start (JSON keys arrive as strings)."""
    if obj is None or obj == "static":
        return "static", {}
    if obj == "p2c":
        return "p2c", {}
    if isinstance(obj, dict):
        mode = str(obj.get("mode", "p2c"))
        if mode not in ("static", "p2c"):
            raise ValueError(f"unknown placement mode {mode!r} "
                             "(one of 'static', 'p2c')")
        ov = {int(k): int(v)
              for k, v in (obj.get("overrides") or {}).items()}
        return mode, ov
    raise ValueError(f"unknown placement spec {obj!r} (expected "
                     "'static', 'p2c', or a {'mode', 'overrides'} "
                     "dict)")


def placement_pins(spec: Optional[dict], n_shards: int) -> np.ndarray:
    """Scenario pins: ``bool[total_ids]``, True where the churn
    scenario's SHAPE is the ownership function and p2c must not
    re-route it.  ``shard_skew`` pins every id -- its hot mask is
    ``cid % n_shards == hot_shard`` (lifecycle.churn), so spreading
    the boundary-0 registrations would dissolve the melt the scenario
    exists to produce (migration, not placement, is what fixes it).
    Every other scenario is placement-free (no pins)."""
    if spec is None:
        return np.zeros(0, dtype=bool)
    total = int(spec["total_ids"])
    if spec.get("scenario") == "shard_skew":
        return np.ones(total, dtype=bool)
    return np.zeros(total, dtype=bool)


class PlacementMap:
    """The cluster-wide client->shard assignment (one instance shared
    by every per-shard :class:`~.plane.LifecyclePlane`; their
    ``_owns`` consults it instead of ``slots.owner_shard``).

    Checkpoint state (rides the rotation payload as ``pm_*`` leaves):
    the assignment array, the placement RNG (PCG64 as uint64[6]),
    the counters, the move log, and the deferred-registration list.
    Everything else (pins, overrides, mode) re-derives from the job
    config."""

    def __init__(self, n_shards: int, total_ids: int, *,
                 mode: str = "p2c", seed: int = 0,
                 pins: Optional[np.ndarray] = None,
                 overrides: Optional[Dict[int, int]] = None):
        self.mode = str(mode)
        self.n_shards = int(n_shards)
        self.total = int(total_ids)
        self.assign = np.full(self.total, -1, dtype=np.int64)
        if self.mode == "static":
            self.assign = np.arange(self.total,
                                    dtype=np.int64) % self.n_shards
        self.pins = np.zeros(self.total, dtype=bool) \
            if pins is None else np.asarray(pins, dtype=bool).copy()
        self.override = np.full(self.total, -1, dtype=np.int64)
        for cid, s in (overrides or {}).items():
            if not 0 <= int(s) < self.n_shards:
                raise ValueError(f"placement override for client "
                                 f"{cid} targets shard {s} outside "
                                 f"[0, {self.n_shards})")
            self.override[int(cid)] = int(s)
        # a DISTINCT stream from the arrival RNG (same job seed, own
        # spawn key), so placement draws never perturb arrival draws
        self.rng = np.random.Generator(np.random.PCG64(
            np.random.SeedSequence([int(seed), 0x706C6163])))
        self.counters = {k: 0 for k in PM_COUNTER_KEYS}
        self.moves: List[Tuple[int, int, int, int]] = []
        self.deferred: List[int] = []

    # -- lookups -------------------------------------------------------
    def shard_of(self, cid: int) -> int:
        """Current owner shard of ``cid`` (-1 = not placed yet --
        either never registered or deferred while its p2c choices
        were both down)."""
        return int(self.assign[int(cid)])

    def shard_counts(self) -> np.ndarray:
        """Placed clients per shard (``int64[S]``)."""
        out = np.zeros(self.n_shards, dtype=np.int64)
        placed = self.assign[self.assign >= 0]
        np.add.at(out, placed, 1)
        return out

    # -- power-of-two-choices placement --------------------------------
    def _draw2(self) -> Tuple[int, int]:
        a = int(self.rng.integers(self.n_shards))
        b = int(self.rng.integers(self.n_shards))
        self.counters["p2c_draws"] += 2
        return a, b

    def place_batch(self, cids: Sequence[int], *, backlog,
                    up: Optional[np.ndarray] = None) -> List[int]:
        """Assign shards to the registrations due at one boundary
        (deferred-first, then ascending-cid -- the caller's order).
        ``backlog`` is the per-shard pressure vector the choice
        minimizes (``dmclock_shard_pressure_backlog``: per-shard
        queued totals); ``up`` the boundary's liveness row (None =
        every shard live).  A pinned id takes ``cid % n_shards`` with
        NO draw; an unpinned id always consumes exactly two draws
        (override ids too -- RNG parity is what keeps a twin run's
        stream aligned), picks the lower-backlog live choice, and
        DEFERS to the next boundary when both choices are down.
        Returns the cids actually placed."""
        backlog = np.asarray(backlog, dtype=np.int64)
        placed: List[int] = []
        deferred: List[int] = []
        for cid in cids:
            cid = int(cid)
            if self.assign[cid] >= 0:
                continue                      # replayed boundary
            if self.pins[cid] and self.override[cid] < 0:
                self.assign[cid] = cid % self.n_shards
                self.counters["placements"] += 1
                placed.append(cid)
                continue
            a, b = (None, None)
            if not self.pins[cid]:
                a, b = self._draw2()
            if self.override[cid] >= 0:
                self.assign[cid] = int(self.override[cid])
                self.counters["placements"] += 1
                self.counters["overrides"] += 1
                placed.append(cid)
                continue
            live = [s for s in (a, b)
                    if up is None or bool(up[s])]
            if not live:
                # both sampled shards down: defer one boundary (the
                # registration stays pending; re-offered next time)
                deferred.append(cid)
                self.counters["defers"] += 1
                continue
            if len(live) < 2:
                # one choice was down: deterministic re-route to the
                # healthier (here: only) live sample
                self.counters["reroutes"] += 1
            dst = min(live, key=lambda s: (int(backlog[s]), s))
            self.assign[cid] = dst
            self.counters["placements"] += 1
            placed.append(cid)
        self.deferred = deferred
        return placed

    def take_deferred(self) -> List[int]:
        """Registrations deferred at the previous boundary (both p2c
        choices down), in original order; cleared on read -- the
        caller re-offers them through :meth:`place_batch`."""
        out = list(self.deferred)
        self.deferred = []
        return out

    # -- migration planning --------------------------------------------
    def plan_moves(self, b: int, *, src: int,
                   candidates: Sequence[int], backlog,
                   up: Optional[np.ndarray] = None,
                   max_moves: int = 4) -> List[Tuple[int, int]]:
        """Plan up to ``max_moves`` migrations off shard ``src`` at
        boundary ``b``: each candidate (the caller orders them by its
        pick policy) samples two destination shards from the
        placement RNG and takes the lower-backlog LIVE one; samples
        that land back on the source (or on a down shard) drop out,
        and a candidate with no usable choice is skipped -- no move,
        deterministic either way.  Records the move log and updates
        the assignment; returns ``[(cid, dst)]`` in plan order."""
        backlog = np.asarray(backlog, dtype=np.int64)
        out: List[Tuple[int, int]] = []
        for cid in candidates:
            if len(out) >= int(max_moves):
                break
            cid = int(cid)
            a, c = self._draw2()
            live = [s for s in (a, c)
                    if s != int(src) and (up is None or bool(up[s]))]
            if not live:
                continue
            dst = min(live, key=lambda s: (int(backlog[s]), s))
            self.assign[cid] = dst
            self.moves.append((int(b), cid, int(src), dst))
            self.counters["migrations"] += 1
            out.append((cid, dst))
        return out

    def move_log(self) -> List[List[int]]:
        """JSON-able ``[[boundary, cid, src, dst]]`` in move order --
        the run-B twin's ``overrides`` source and the bench record's
        rebalance block."""
        return [[int(x) for x in row] for row in self.moves]

    def snapshot(self) -> dict:
        return {"mode": self.mode, "n_shards": self.n_shards,
                "deferred": len(self.deferred),
                **{k: int(v) for k, v in self.counters.items()}}

    # -- observability -------------------------------------------------
    def publish(self, registry, labels=None) -> None:
        """Mount the ``dmclock_placement_*`` / ``dmclock_migration_*``
        families (docs/OBSERVABILITY.md metric-family index)."""
        rows = (
            ("dmclock_placement_total", "placements",
             "registrations routed by the placement map (pins + "
             "power-of-two-choices)"),
            ("dmclock_placement_draws_total", "p2c_draws",
             "placement RNG samples consumed (2 per unpinned "
             "registration, 2 per migration candidate)"),
            ("dmclock_placement_reroutes_total", "reroutes",
             "registrations re-routed off a DOWN sampled shard to "
             "the live choice"),
            ("dmclock_placement_defers_total", "defers",
             "registrations deferred one boundary because both "
             "sampled shards were down"),
            ("dmclock_placement_overrides_total", "overrides",
             "registrations placed by an explicit override (the "
             "digest gate's placed-from-start twin)"),
            ("dmclock_migration_total", "migrations",
             "live clients moved between shards (EVICT on source + "
             "REGISTER on destination with carried counter views)"),
        )
        for name, key, help_text in rows:
            registry.gauge(name, help_text, labels=labels) \
                .set_function(lambda k=key: float(self.counters[k]))
        registry.gauge(
            "dmclock_migration_last_boundary",
            "epoch boundary of the most recent migration (-1 = "
            "never)", labels=labels) \
            .set_function(lambda: float(self.moves[-1][0]
                                        if self.moves else -1))

    # -- checkpoint round-trip -----------------------------------------
    def encode(self) -> dict:
        from ..robust.supervisor import _rng_state_array

        return {"pm_assign": self.assign.copy(),
                "pm_rng": _rng_state_array(self.rng),
                "pm_counters": np.asarray(
                    [self.counters[k] for k in PM_COUNTER_KEYS],
                    dtype=np.int64),
                "pm_moves": np.asarray(
                    self.moves, dtype=np.int64).reshape(
                        len(self.moves), 4),
                "pm_deferred": np.asarray(self.deferred,
                                          dtype=np.int64)}

    def load(self, payload: dict) -> None:
        from ..robust.supervisor import _rng_from_array

        assign = np.asarray(payload["pm_assign"], dtype=np.int64)
        if assign.shape[0] == 0:
            return                       # pre-placement payload
        self.assign = assign.copy()
        self.rng = _rng_from_array(payload["pm_rng"])
        ctr = np.asarray(payload["pm_counters"], dtype=np.int64)
        self.counters = {k: int(v)
                         for k, v in zip(PM_COUNTER_KEYS, ctr)}
        self.moves = [tuple(int(x) for x in row)
                      for row in np.asarray(payload["pm_moves"],
                                            dtype=np.int64)]
        self.deferred = [int(x)
                         for x in np.asarray(payload["pm_deferred"],
                                             dtype=np.int64)]

    @staticmethod
    def empty_leaves() -> dict:
        """Zero-size ``pm_*`` leaves for jobs without a placement map
        (the always-present payload-structure convention)."""
        return {"pm_assign": np.zeros(0, dtype=np.int64),
                "pm_rng": np.zeros(6, dtype=np.uint64),
                "pm_counters": np.zeros(len(PM_COUNTER_KEYS),
                                        dtype=np.int64),
                "pm_moves": np.zeros((0, 4), dtype=np.int64),
                "pm_deferred": np.zeros(0, dtype=np.int64)}
