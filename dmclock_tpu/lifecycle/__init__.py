"""Client lifecycle plane: dynamic slot management, live ClientInfo
control, and the churn scenario suite (docs/LIFECYCLE.md)."""

from .api import AdminAPI, mount_admin_api
from .churn import (SCENARIOS, events, init_qos, lam_vector, make_spec,
                    peak_ids, static_variant)
from .plane import (COUNTER_KEYS, LifecyclePlane, apply_op_vector,
                    wal_append)
from .runner import run_serial_churn
from .slots import SlotMap, compact_tree

__all__ = [
    "AdminAPI", "COUNTER_KEYS", "LifecyclePlane", "SCENARIOS",
    "SlotMap", "apply_op_vector", "compact_tree", "events",
    "init_qos", "lam_vector", "make_spec", "mount_admin_api",
    "peak_ids", "run_serial_churn", "static_variant", "wal_append",
]
