"""Admin control API: the lifecycle plane over HTTP.

A small JSON API mounted on the existing scrape endpoint
(``obs.registry.MetricsHTTPServer.mount``), so ONE port serves
Prometheus scrape + health + client control:

- ``POST /clients``            register ``{"id", "reservation",
  "weight", "limit", "apply_at"?}``
- ``PUT /clients/{id}/qos``    live ClientInfo update (same body,
  minus ``id``)
- ``DELETE /clients/{id}``     evict (waits for the client's queue to
  drain; the slot is recycled at the boundary that finds it drained)
- ``GET /clients``             population summary + lifecycle counters
- ``GET /clients/{id}``        one client's QoS / slot / ledger row

Acceptance is **journaled, not immediate**: a 202 means the op is in
the pending-update journal (WAL-fsynced when the run is supervised)
and will apply at its epoch boundary -- ``apply_at`` pins a specific
boundary, ``null``/absent means the next one.  Invalid QoS triples are
rejected at accept time with 400 carrying the SAME client-naming
ValueError message init-time construction raises
(``core.qos.validate_client_info`` -- one validation path).
"""

from __future__ import annotations

import json
import re
from typing import Optional, Tuple

import numpy as np

from .plane import LifecyclePlane

_ID_RE = re.compile(r"^/clients/(\d+)(/qos|/conformance)?$")
_JSON = "application/json"


def _resp(status: int, obj) -> Tuple[int, str, bytes]:
    return status, _JSON, json.dumps(obj).encode()


class AdminAPI:
    """``handler(method, path, body)`` for ``MetricsHTTPServer.mount``
    over one :class:`~.plane.LifecyclePlane`."""

    def __init__(self, plane: LifecyclePlane, *, ledger_rows=None,
                 slo=None):
        self.plane = plane
        # optional callable () -> {cid: int64[5] LED_* row} supplying
        # live conformance rows for GET /clients/{id}
        self.ledger_rows = ledger_rows
        # optional obs.slo.SloPlane: serves the windowed per-contract-
        # epoch conformance view (GET /clients/{id}/conformance)
        self.slo = slo

    # -- mountable entry point ----------------------------------------
    def handler(self, method: str, path: str,
                body: bytes) -> Tuple[int, str, bytes]:
        try:
            return self._route(method, path, body)
        except ValueError as e:
            # validation failures are client errors, with the same
            # client-naming message init-time construction raises
            return _resp(400, {"error": str(e)})

    def _route(self, method, path, body):
        if path.rstrip("/") == "/clients":
            if method == "GET":
                return _resp(200, self.plane.snapshot())
            if method == "POST":
                return self._register(_body_json(body))
            return _resp(405, {"error": f"{method} not allowed"})
        m = _ID_RE.match(path)
        if not m:
            return _resp(404, {"error": f"no route {path!r}"})
        cid = int(m.group(1))
        if m.group(2) == "/conformance":
            if method != "GET":
                return _resp(405, {"error": f"{method} not allowed"})
            return self._conformance(cid)
        if m.group(2):                       # /clients/{id}/qos
            if method != "PUT":
                return _resp(405, {"error": f"{method} not allowed"})
            return self._update(cid, _body_json(body))
        if method == "GET":
            return self._get(cid)
        if method == "DELETE":
            return self._evict(cid)
        return _resp(405, {"error": f"{method} not allowed"})

    # -- verbs ---------------------------------------------------------
    def _register(self, obj: dict):
        cid = int(obj["id"])
        with self.plane.lock:
            if cid in self.plane.slots.slot_of or any(
                    p["cid"] == cid and p["op"] == "register"
                    for p in self.plane.pending_view()):
                return _resp(409, {"error": f"client {cid} already "
                                            "registered"})
            seq = self.plane.accept(
                {"op": "register", "cid": cid,
                 "r": obj.get("reservation", 0.0),
                 "w": obj.get("weight", 1.0),
                 "l": obj.get("limit", 0.0),
                 "apply_at": obj.get("apply_at")})
        return _resp(202, {"accepted": True, "seq": seq,
                           "apply_at": obj.get("apply_at")})

    def _update(self, cid: int, obj: dict):
        with self.plane.lock:
            if cid not in self.plane.slots.slot_of and not any(
                    p["cid"] == cid and p["op"] == "register"
                    for p in self.plane.pending_view()):
                return _resp(404, {"error": f"no client {cid}"})
            seq = self.plane.accept(
                {"op": "update", "cid": cid,
                 "r": obj.get("reservation", 0.0),
                 "w": obj.get("weight", 1.0),
                 "l": obj.get("limit", 0.0),
                 "apply_at": obj.get("apply_at")})
        return _resp(202, {"accepted": True, "seq": seq,
                           "apply_at": obj.get("apply_at")})

    def _evict(self, cid: int):
        with self.plane.lock:
            if cid not in self.plane.slots.slot_of:
                return _resp(404, {"error": f"no client {cid}"})
            seq = self.plane.accept({"op": "evict", "cid": cid,
                                     "apply_at": None})
        return _resp(202, {"accepted": True, "seq": seq})

    def _conformance(self, cid: int):
        """The windowed conformance view (obs.slo): the client's
        closed-window ring judged per window against its OWN contract
        version, plus the live contract epoch.  404s without an
        attached SLO plane (the run was started with it off)."""
        if self.slo is None:
            return _resp(404, {"error": "SLO plane not enabled "
                                        "(run with with_slo/--slo)"})
        with self.plane.lock:
            known = cid in self.plane.slots.slot_of or \
                cid in self.plane.qos
        view = self.slo.client_view(cid)
        if not known and not view["windows"] \
                and view["contract_epoch"] == 0:
            return _resp(404, {"error": f"no client {cid}"})
        return _resp(200, view)

    def _get(self, cid: int):
        with self.plane.lock:
            slot = self.plane.slots.slot_of.get(cid)
            qos = self.plane.qos.get(cid)
            pending = [p["op"] for p in self.plane.pending_view()
                       if p["cid"] == cid]
        if slot is None and qos is None and not pending:
            return _resp(404, {"error": f"no client {cid}"})
        out = {"id": cid, "slot": slot,
               "registered": slot is not None,
               "pending": pending}
        if qos is not None:
            out["qos"] = {"reservation": qos[0], "weight": qos[1],
                          "limit": qos[2]}
        if self.ledger_rows is not None and slot is not None:
            rows = self.ledger_rows()
            row = rows.get(cid) if rows else None
            if row is not None:
                out["ledger"] = np.asarray(row).tolist()
        return _resp(200, out)


def _body_json(body: bytes) -> dict:
    if not body:
        return {}
    try:
        obj = json.loads(body.decode())
    except Exception:
        raise ValueError("request body is not valid JSON")
    if not isinstance(obj, dict):
        raise ValueError("request body must be a JSON object")
    return obj


def mount_admin_api(server, plane: LifecyclePlane, *,
                    ledger_rows=None, slo=None) -> Optional[AdminAPI]:
    """Mount the control API on a (possibly None, fail-soft)
    ``MetricsHTTPServer`` and publish the lifecycle counters into its
    registry.  ``slo`` (an ``obs.slo.SloPlane``) additionally serves
    ``GET /clients/{id}/conformance``.  Returns the API object, or
    None when there is no server."""
    if server is None:
        return None
    api = AdminAPI(plane, ledger_rows=ledger_rows, slo=slo)
    server.mount("/clients", api.handler)
    plane.publish(server.registry)
    return api
