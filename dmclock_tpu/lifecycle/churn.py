"""Churn scenarios: deterministic open-population workload scripts.

A churn spec is a plain JSON-able dict describing an *open* client
population over an epoch-loop run: per-epoch arrival intensities
(``lam_vector``) plus the lifecycle events due at each boundary
(``events``) -- cohort registrations, scripted QoS updates, scripted
compaction points.  Everything is a pure function of the spec, so a
spec rides ``EpochJob.to_json()`` into a spawned child process and two
runs of the same spec are bit-identical.

**The static variant is a spec transform, not a second code path**:
:func:`static_variant` returns the same scenario with every client
registered at boundary 0, eviction off, compaction off, and the
initial capacity equal to the id space -- the statically pre-registered
reference population the lifecycle digest gate compares against
(docs/LIFECYCLE.md).  Arrival draws, QoS update scripts, and the
idle-marking policy are shared verbatim, so the ONLY delta between the
two runs is the slot dynamics (registration timing, recycling, growth,
compaction) -- exactly what the gate pins as decision-neutral.

Digest-gate discipline the generators maintain (the plane does not
enforce these; a hand-written spec that breaks them still *runs*, it
just is not digest-comparable to its static variant):

- cohorts occupy ascending client-id ranges in start order, so dynamic
  registration order matches the static run's ascending-id order (the
  engines tie-break on creation order);
- a cohort's arrival rate is zero strictly before its start boundary
  (a client registers before its first arrival);
- once a departing cohort's rate reaches zero it stays zero, and
  ``evict_after`` exceeds any *temporary* quiet window (diurnal
  nights), so an evicted client never returns -- re-registration is a
  NEW client (fresh tags, new creation order), same as the reference's
  erase + re-create, and would legitimately diverge from a
  never-erased run.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

SCENARIOS = ("flash_crowd", "diurnal", "churn_storm", "limit_thrash",
             "shard_skew")


def make_spec(scenario: str, *, total_ids: int, seed: int = 0,
              capacity0: int = 0, static: bool = False,
              base_lam: float = 1.0, evict_after: int = 2,
              compact_every: int = 4, qos_r: float = 0.0,
              qos_l: float = 0.0, qos_wmod: int = 4,
              **params) -> dict:
    """Build a churn spec with per-scenario parameter defaults.

    ``capacity0`` is the dynamic run's initial slot capacity (0 picks
    ``max(8, total_ids // 4)`` -- small on purpose, so grow-on-demand
    is exercised); ``evict_after`` the number of consecutive
    no-arrival boundaries before an idle client's slot is recycled
    (0 = never); ``compact_every`` compacts at every k-th boundary
    (0 = off).  Initial QoS of client ``c`` is ``(qos_r,
    1 + c % qos_wmod, qos_l)`` -- shared by init-time registration and
    the static variant."""
    if scenario not in SCENARIOS:
        raise ValueError(f"unknown churn scenario {scenario!r} "
                         f"(one of {SCENARIOS})")
    total_ids = int(total_ids)
    spec = {
        "scenario": scenario, "total_ids": total_ids,
        "seed": int(seed), "static": bool(static),
        "capacity0": int(capacity0) or max(8, total_ids // 4),
        "base_lam": float(base_lam), "evict_after": int(evict_after),
        "compact_every": int(compact_every),
        "qos_r": float(qos_r), "qos_l": float(qos_l),
        "qos_wmod": int(qos_wmod),
    }
    defaults: Dict[str, dict] = {
        # steady base cohort + a crowd cohort that arrives in one
        # burst, stays for crowd_len epochs, and departs for good
        "flash_crowd": {"base_frac": 0.5, "crowd_at": 8,
                        "crowd_len": 8, "crowd_lam_x": 4.0},
        # everyone registered up front; day/night square wave with
        # per-cohort phase.  night_x > 0 keeps night arrivals trickling
        # so nobody idles into eviction (evict_after=0 by default here)
        "diurnal": {"cohorts": 4, "period": 8, "night_x": 0.25},
        # G generations of cohorts, each living `life` epochs starting
        # `stride` apart: continuous register/depart traffic, heavy
        # slot recycling, fragmentation for compaction to repack
        "churn_storm": {"gens": 6, "stride": 4, "life": 10},
        # static population, but a victim cohort's limit flip-flops
        # between tight and disabled at EVERY boundary -- the
        # adversarial control-plane load shape
        "limit_thrash": {"victim_frac": 0.25, "tight_limit": 50.0,
                         "thrash_every": 1},
        # the mesh plane's first IMBALANCE workload (ROADMAP
        # rack-scheduling item): everyone registered from epoch 0,
        # but the ids OWNED BY one shard (cid % n_shards == hot_shard
        # -- the mesh lifecycle routing) carry a Zipf(zipf_a) head
        # melting at hot_x times the base rate while every other
        # shard's ids trickle at idle_x.  Invisible at one shard;
        # at S=4 it is the one-shard-melts-while-others-idle shape
        # inter-shard placement/migration will have to fix.
        # cold_frac / cold_until carve a QUIET TAIL out of the hot
        # shard's partition: the lowest-rate ``cold_frac`` of its
        # Zipf ranks stay at lam = 0 until epoch ``cold_until``
        # (0 = knob off, bit-identical to before).  Those ids are
        # registered-but-drained with zero completions -- exactly the
        # movers the migration twin gate can prove placement-
        # equivalent (docs/LIFECYCLE.md "quiet-since-start").
        "shard_skew": {"n_shards": 4, "hot_shard": 0,
                       "zipf_a": 1.2, "hot_x": 8.0, "idle_x": 0.1,
                       "cold_frac": 0.0, "cold_until": 0},
    }
    d = dict(defaults[scenario])
    unknown = set(params) - set(d)
    if unknown:
        raise ValueError(f"unknown {scenario} params: {sorted(unknown)}")
    d.update(params)
    spec.update(d)
    if scenario == "diurnal":
        spec["evict_after"] = int(params.get("evict_after", 0)) or 0
    if scenario == "limit_thrash":
        spec.setdefault("evict_after", 0)
        spec["evict_after"] = 0
    if scenario == "shard_skew":
        # static-population imbalance shape: nobody departs (the cold
        # shards' trickle is the point -- they idle, not evict)
        spec["evict_after"] = 0
    return spec


def static_variant(spec: dict) -> dict:
    """The statically pre-registered reference of ``spec``: same
    arrival trace, same QoS update script, and the same idle-marking
    policy (``evict_after`` is KEPT -- where the dynamic run evicts a
    drained client, the static run idle-marks it, so departure leaves
    the engines' idle-reactivation min identically); no registration
    timing, no erasure, no growth, no compaction."""
    s = dict(spec)
    s["static"] = True
    s["compact_every"] = 0
    s["capacity0"] = s["total_ids"]
    return s


def init_qos(spec: dict, cid: int):
    """Initial (reservation, weight, limit) of client ``cid``."""
    return (spec["qos_r"], 1.0 + (int(cid) % spec["qos_wmod"]),
            spec["qos_l"])


# ----------------------------------------------------------------------
# cohort tables (host-side, derived once per call; specs are tiny)
# ----------------------------------------------------------------------

def _cohorts(spec: dict) -> List[dict]:
    """[{lo, hi, start, end, lam}] id ranges in ascending-id = start
    order; ``end`` is the epoch the cohort's rate drops to zero
    forever (None = never)."""
    n = spec["total_ids"]
    lam = spec["base_lam"]
    sc = spec["scenario"]
    if sc == "flash_crowd":
        nb = max(1, int(n * spec["base_frac"]))
        return [
            {"lo": 0, "hi": nb, "start": 0, "end": None, "lam": lam},
            {"lo": nb, "hi": n, "start": spec["crowd_at"],
             "end": spec["crowd_at"] + spec["crowd_len"],
             "lam": lam * spec["crowd_lam_x"]},
        ]
    if sc == "churn_storm":
        g, stride, life = spec["gens"], spec["stride"], spec["life"]
        gs = n // g
        out = []
        for i in range(g):
            hi = (i + 1) * gs if i < g - 1 else n
            out.append({"lo": i * gs, "hi": hi, "start": i * stride,
                        "end": i * stride + life, "lam": lam})
        return out
    # diurnal / limit_thrash: everyone from epoch 0
    return [{"lo": 0, "hi": n, "start": 0, "end": None, "lam": lam}]


def lam_vector(spec: dict, epoch: int) -> np.ndarray:
    """Per-client Poisson arrival rate for ``epoch``
    (``float64[total_ids]``).  Shared verbatim by the dynamic run and
    its static variant -- identical RNG consumption is what makes the
    digest gate meaningful."""
    lam = np.zeros(spec["total_ids"], dtype=np.float64)
    for c in _cohorts(spec):
        live = epoch >= c["start"] and \
            (c["end"] is None or epoch < c["end"])
        if live:
            lam[c["lo"]:c["hi"]] = c["lam"]
    if spec["scenario"] == "diurnal":
        n, period = spec["total_ids"], spec["period"]
        cohorts, night_x = spec["cohorts"], spec["night_x"]
        size = max(1, n // cohorts)
        cidx = np.minimum(np.arange(n) // size, cohorts - 1)
        phase = (epoch + cidx * (period // max(cohorts, 1))) % period
        night = phase >= (period + 1) // 2
        lam = np.where(night, lam * night_x, lam)
    if spec["scenario"] == "shard_skew":
        n, S = spec["total_ids"], int(spec["n_shards"])
        ids = np.arange(n)
        hot = ids % S == int(spec["hot_shard"])
        # Zipf head over the hot shard's owned ids, by ownership
        # rank: the head client melts hardest, the tail still runs
        # hotter than any cold shard.  Mean over the hot partition is
        # pinned at base_lam * hot_x so the aggregate offered load is
        # a pure function of the spec knobs.
        rank = ids // S   # ownership rank within a shard's partition
        zipf = 1.0 / np.power(rank + 1.0, float(spec["zipf_a"]))
        n_hot = max(int(hot.sum()), 1)
        zipf_mean = float(zipf[hot].sum()) / n_hot if hot.any() \
            else 1.0
        lam = np.where(
            hot,
            lam * float(spec["hot_x"]) * zipf / max(zipf_mean, 1e-12),
            lam * float(spec["idle_x"]))
        cf = float(spec.get("cold_frac", 0.0))
        until = int(spec.get("cold_until", 0))
        if cf > 0 and epoch < until:
            # quiet tail: the coldest cold_frac of the hot shard's
            # ranks arrive NOTHING until cold_until -- drained,
            # zero-completion residents the migrate rule can move
            # with a provably placement-equivalent digest
            n_cold = int(round(cf * n_hot))
            quiet = hot & (rank >= n_hot - n_cold)
            lam = np.where(quiet, 0.0, lam)
    return lam


def events(spec: dict, boundary: int, every: int) -> List[dict]:
    """Scripted lifecycle ops due at ``boundary`` (ascending-cid
    registration order), for a boundary cadence of ``every`` epochs:
    cohorts starting in ``[boundary, boundary + every)`` register now
    (their rate is still zero strictly before ``start``, so an early
    registration just idles).  Update scripts fire on their own
    cadence.  Registrations/evictions are ignored by a static-mode
    plane; updates apply in both modes."""
    out: List[dict] = []
    for c in _cohorts(spec):
        due = boundary <= c["start"] < boundary + every or \
            (c["start"] < boundary == 0)
        if due:
            for cid in range(c["lo"], c["hi"]):
                r, w, l = init_qos(spec, cid)
                out.append({"op": "register", "cid": cid,
                            "r": r, "w": w, "l": l})
    if spec["scenario"] == "limit_thrash" and boundary > 0:
        te = max(1, spec["thrash_every"])
        if (boundary // every) % te == 0:
            n = spec["total_ids"]
            nv = max(1, int(n * spec["victim_frac"]))
            tight = (boundary // every // te) % 2 == 1
            for cid in range(n - nv, n):
                r, w, _ = init_qos(spec, cid)
                lim = spec["tight_limit"] if tight else 0.0
                out.append({"op": "update", "cid": cid,
                            "r": r, "w": w, "l": lim})
    return out


def peak_ids(spec: dict) -> int:
    """Maximum simultaneously-live client count the script reaches
    (sizing hint for ring budgets and bench reports)."""
    marks = sorted({c["start"] for c in _cohorts(spec)})
    peak = 0
    for t in marks:
        live = sum(c["hi"] - c["lo"] for c in _cohorts(spec)
                   if c["start"] <= t and
                   (c["end"] is None or t < c["end"]))
        peak = max(peak, live)
    return peak
