"""The client lifecycle plane: open-population control over the engines.

The reference serves an *open* population -- clients register
(``dmclock_server.h:913-932``), idle out and get erased (:1206-1255),
and have their QoS triple replaced in flight (``update_client_info``).
Every dmclock_tpu engine ran over a client table frozen at init.  This
module closes that gap as a HOST-side control plane over the existing
device engines, on one discipline: **lifecycle ops apply only at epoch
boundaries** (the PR-5 checkpoint / PR-8 stream-chunk grid), batched
into a single ordered device launch, so the epoch scans themselves
never change and the hot path never takes a lock.

Pieces:

- :class:`LifecyclePlane` -- owns the :class:`~.slots.SlotMap`, the
  pending-update journal (accepted control ops waiting for their
  boundary), per-client zero-arrival streaks (idle eviction), the
  lifecycle counters, and the departed-clients ledger report.
- :func:`apply_op_vector` -- the device half: an ordered
  ``lax.scan`` over (register | qos-update | evict) rows, the
  ``kernels.ingest`` OP_CREATE pattern extended with live updates and
  slot recycling.  Register and evict both reset the row to
  ``engine.state._FRESH_FILLS``, so a recycled slot is byte-identical
  to a freshly-initialized one.
- a write-ahead **admin WAL** (``admin.wal`` in the supervisor
  workdir): every op accepted through the control API is fsynced
  before it is acknowledged, and the plane's checkpointed
  ``wal_seen`` cursor makes acceptance-vs-application exactly-once
  across SIGKILL (docs/LIFECYCLE.md).
- canonical **client-id-space digest views**
  (:meth:`LifecyclePlane.canon_results`): decision streams hash with
  slots translated to client ids and per-slot arrays scattered to the
  id space, so registration timing, slot recycling, growth, and
  compaction are all digest-neutral -- the dynamic-vs-static gate of
  tests/test_lifecycle.py and the ci.sh churn smoke.
"""

from __future__ import annotations

import json
import os
import threading
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.qos import validate_client_info
from ..core.timebase import rate_to_inv_ns
from ..engine.state import _FRESH_FILLS, EngineState, grow_state
from . import churn as churn_mod
from .slots import SlotMap, compact_tree

# op codes of the device-side update vector (0 = padding NOP).
# LC_IDLE sets the slot's idle flag and nothing else: the static
# reference population applies it at exactly the boundaries the
# dynamic run EVICTS, so a departed client leaves the engines'
# idle-reactivation min (``others = active & ~idle`` in
# ``kernels.ingest``) identically in both runs -- without it a
# never-erased static client's frozen tags would keep participating
# in that global min and the digest gate could not hold.
LC_NOP, LC_REGISTER, LC_UPDATE, LC_EVICT, LC_IDLE = 0, 1, 2, 3, 4

WAL_FILE = "admin.wal"

# test seam: called between the compaction gather launch and the
# host-side slot-map re-map -- the "SIGKILL mid-compaction" injection
# point of the crash-equivalence matrix (tests/test_supervisor.py)
_compact_hook = None


# ----------------------------------------------------------------------
# device half: one ordered launch applying a boundary's op vector
# ----------------------------------------------------------------------

_OPS_JIT: dict = {}


def _pad_len(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def apply_op_vector(state: EngineState, kind, slot, resv_inv,
                    weight_inv, limit_inv, order) -> EngineState:
    """Apply an ordered batch of lifecycle ops in ONE device launch.

    ``kind`` int32[B] of LC_* codes; rows run in order (a register and
    an update for the same slot in one boundary compose like separate
    boundary launches would).  REGISTER resets the row to the
    ``init_state`` fills then installs active/order/QoS-inverses --
    exactly ``kernels.ingest``'s OP_CREATE; UPDATE replaces the three
    QoS inverses and nothing else (tags already issued stand; future
    tags use the new rates -- docs/LIFECYCLE.md "update semantics");
    EVICT resets the row to the fills (active=False), including the
    tail-ring rows, so the next tenant of the slot is byte-identical
    to a fresh one; IDLE sets the slot's idle flag and nothing else
    (the static reference's twin of EVICT -- see the LC_* comment)."""
    import jax

    b = int(np.asarray(kind).shape[0])
    key = (state.capacity, state.ring_capacity, b)
    if key not in _OPS_JIT:
        import jax.numpy as jnp
        from jax import lax

        def run(st: EngineState, ops):
            def body(st: EngineState, op):
                knd, s, ri, wi, li, o = op
                reset = (knd == LC_REGISTER) | (knd == LC_EVICT)
                reg = knd == LC_REGISTER
                setr = reg | (knd == LC_UPDATE)
                idl = knd == LC_IDLE

                def fset(arr, name, pred, value):
                    fill = _FRESH_FILLS[name]
                    v = jnp.where(pred, value,
                                  jnp.where(reset, fill, arr[s]))
                    return arr.at[s].set(v.astype(arr.dtype))

                new = {}
                for name in EngineState._fields:
                    arr = getattr(st, name)
                    if name in ("q_arrival", "q_cost"):
                        # whole tail-ring row resets with the slot
                        row = jnp.where(reset, 0, arr[s])
                        new[name] = arr.at[s].set(row)
                    elif name == "idle":
                        # fill is True, and LC_IDLE sets exactly True
                        v = jnp.where(reset | idl, True, arr[s])
                        new[name] = arr.at[s].set(v)
                    elif name == "active":
                        new[name] = fset(arr, name, reg, True)
                    elif name == "order":
                        new[name] = fset(arr, name, reg, o)
                    elif name == "resv_inv":
                        new[name] = fset(arr, name, setr, ri)
                    elif name == "weight_inv":
                        new[name] = fset(arr, name, setr, wi)
                    elif name == "limit_inv":
                        new[name] = fset(arr, name, setr, li)
                    else:
                        # untouched unless the row resets
                        fill = _FRESH_FILLS[name]
                        v = jnp.where(reset, fill, arr[s])
                        new[name] = arr.at[s].set(v.astype(arr.dtype))
                return EngineState(**new), None

            st, _ = lax.scan(body, st, ops)
            return st

        _OPS_JIT[key] = jax.jit(run)

    import jax.numpy as jnp

    ops = (jnp.asarray(kind, dtype=jnp.int32),
           jnp.asarray(slot, dtype=jnp.int32),
           jnp.asarray(resv_inv, dtype=jnp.int64),
           jnp.asarray(weight_inv, dtype=jnp.int64),
           jnp.asarray(limit_inv, dtype=jnp.int64),
           jnp.asarray(order, dtype=jnp.int64))
    return _OPS_JIT[key](state, ops)


# ----------------------------------------------------------------------
# the plane
# ----------------------------------------------------------------------

COUNTER_KEYS = ("registrations", "evictions", "compactions",
                "qos_updates", "slot_recycles", "grows", "idle_marks",
                "migrations_in", "migrations_out")


class LifecyclePlane:
    """Host-side lifecycle controller for one churn-spec run.

    Drives registration / QoS update / idle eviction / compaction at
    epoch boundaries over a (state, ledger) pair, keeps the
    client-id <-> slot map, journals control-API ops through the admin
    WAL, and provides the canonical client-id-space decision views the
    digest gates hash.  ``spec`` is a ``lifecycle.churn`` spec dict
    (``static=True`` = the pre-registered reference population: all
    ids register at boundary 0, eviction/growth/compaction off).

    Thread contract: :meth:`accept` (the HTTP control plane) and
    :meth:`boundary` (the epoch loop) synchronize on ``self.lock``;
    everything else is loop-thread-only.
    """

    def __init__(self, spec: dict, *, workdir: Optional[str] = None,
                 tracer=None, shard: Optional[Tuple[int, int]] = None):
        """``shard=(s, n_shards)`` makes this a PER-SHARD plane of a
        mesh job (docs/LIFECYCLE.md "Per-shard routing"): scripted
        events and control ops are filtered to the client ids shard
        ``s`` OWNS (``slots.owner_shard``: ``cid % n_shards == s``),
        its slot map covers only that partition, and ``map_counts``
        drops un-owned ids' draws (their arrivals belong to another
        shard's plane).  ``shard=None`` is the single-shard plane the
        round/stream loops drive."""
        self.spec = dict(spec)
        self.static = bool(spec["static"])
        self.total = int(spec["total_ids"])
        self.shard = None if shard is None \
            else (int(shard[0]), int(shard[1]))
        self.slots = SlotMap(int(spec["capacity0"]))
        self.streak = np.zeros(self.total, dtype=np.int64)
        self.qos: Dict[int, Tuple[float, float, float]] = {}
        self.pending: List[dict] = []   # accepted, awaiting a boundary
        self.wal_seen = 0               # WAL lines already ingested
        self._wal_lines = None          # cached WAL line count (lazy)
        self.counters = {k: 0 for k in COUNTER_KEYS}
        self.departed: List[Tuple[int, np.ndarray]] = []
        self.peak_live = 0
        self.lock = threading.RLock()
        self.workdir = workdir
        self.tracer = tracer
        # optional obs.slo.SloPlane: every applied REGISTER/UPDATE/
        # EVICT bumps the client's contract-epoch counter there, so
        # closed conformance windows attribute to exactly one
        # (client, contract_version) pair (docs/OBSERVABILITY.md)
        self._slo = None
        # optional lifecycle.placement.PlacementMap, shared by every
        # shard of a mesh job: when attached, IT is the routing
        # contract (``_owns`` consults it instead of the static
        # ``slots.owner_shard``) and registration ``order`` becomes
        # the client id -- placement-path-independent, which is what
        # makes a migrated client's REGISTER on the destination
        # byte-identical to a placed-there-from-start one
        self.placement = None

    def attach_placement(self, pm) -> None:
        self.placement = pm

    def attach_slo(self, slo) -> None:
        self._slo = slo

    # -- control-plane ingress (HTTP thread) ---------------------------
    @property
    def wal_path(self) -> Optional[str]:
        return os.path.join(self.workdir, WAL_FILE) \
            if self.workdir else None

    def accept(self, op: dict) -> int:
        """Accept one control op (``{"op": "register"|"update"|
        "evict", "cid", "r", "w", "l", "apply_at": boundary|None}``)
        into the pending journal; returns its sequence number.
        Validation happens HERE -- an accepted op cannot fail at its
        boundary -- with the same client-naming ValueErrors as
        init-time construction (``core.qos.validate_client_info``).
        With a workdir the op is fsynced to the admin WAL before it is
        acknowledged: accepted-but-unapplied ops survive SIGKILL, and
        the checkpointed ``wal_seen`` cursor makes their application
        exactly-once across a resume."""
        kind = op["op"]
        assert kind in ("register", "update", "evict"), kind
        cid = int(op["cid"])
        if cid < 0:
            raise ValueError(f"client id must be >= 0, got {cid}")
        if cid >= self.total:
            # the id space is spec-bounded: arrival draws, the streak
            # array, and the canonical digest views are all
            # [total_ids]-wide, so an out-of-space registration could
            # never receive arrivals and would crash the id-space
            # scatter -- reject it at accept time instead
            raise ValueError(
                f"client id {cid} outside the churn spec's id space "
                f"[0, {self.total})")
        if not self._owns(cid):
            raise ValueError(
                f"client id {cid} is owned by shard "
                f"{self._owner_of(cid)}, not this plane's shard "
                f"{self.shard[0]} (route by the placement map when "
                f"attached, else slots.owner_shard)")
        if kind in ("register", "update"):
            validate_client_info(
                (op["r"], op["w"], op["l"]), name=cid)
        with self.lock:
            rec = {"op": kind, "cid": cid,
                   "r": float(op.get("r", 0.0)),
                   "w": float(op.get("w", 1.0)),
                   "l": float(op.get("l", 0.0)),
                   "apply_at": op.get("apply_at")}
            if self.wal_path is not None:
                rec["seq"] = self._wal_append(rec)
            else:
                rec["seq"] = self.wal_seen + len(self.pending)
                self.pending.append(rec)
            return rec["seq"]

    def _wal_count(self) -> int:
        """Total WAL lines, counted from the file once then cached --
        sequence numbering must not re-scan the whole journal per
        accepted op (acceptance holds ``self.lock``, which the epoch
        loop's boundary also takes)."""
        if self._wal_lines is None:
            self._wal_lines = 0
            if self.wal_path is not None and \
                    os.path.exists(self.wal_path):
                with open(self.wal_path) as fh:
                    self._wal_lines = sum(1 for ln in fh
                                          if ln.strip())
        return self._wal_lines

    def _wal_append(self, rec: dict) -> int:
        seq = self._wal_count()
        with open(self.wal_path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        self._wal_lines = seq + 1
        return seq

    def _wal_ingest(self) -> None:
        """Pull WAL lines past the ``wal_seen`` cursor into pending --
        the resume-safe half of acceptance (a line is ingested exactly
        once per committed checkpoint lineage: the cursor rides the
        rotation snapshots, so a replayed boundary re-ingests exactly
        the lines the dead incarnation had)."""
        if self.wal_path is None or not os.path.exists(self.wal_path):
            return
        with open(self.wal_path) as fh:
            lines = [ln for ln in fh if ln.strip()]
        for i in range(self.wal_seen, len(lines)):
            rec = json.loads(lines[i])
            rec["seq"] = i
            if not 0 <= int(rec["cid"]) < self.total:
                # a hand-written WAL bypasses accept()'s bound check;
                # an out-of-space id can never receive arrivals and
                # would crash the id-space scatter at every resume --
                # drop it (deterministically: every incarnation drops
                # the same line) instead of poisoning the run
                import sys
                print(f"# lifecycle: dropping WAL line {i}: client "
                      f"id {rec['cid']} outside [0, {self.total})",
                      file=sys.stderr)
                continue
            self.pending.append(rec)
        self.wal_seen = len(lines)
        self._wal_lines = len(lines)

    def pending_view(self) -> List[dict]:
        """Read-only view of every accepted-but-unapplied op: the
        in-memory pending journal PLUS WAL lines past the ``wal_seen``
        cursor that no boundary has ingested yet.  The control API's
        existence/duplicate checks consult THIS -- in WAL mode an
        accepted op lives only in the file until the next boundary,
        and a 202'd registration must be visible to the PUT (and 409
        a duplicate POST) that follows it."""
        with self.lock:
            out = list(self.pending)
            if self.wal_path is not None and \
                    os.path.exists(self.wal_path):
                with open(self.wal_path) as fh:
                    lines = [ln for ln in fh if ln.strip()]
                for i in range(self.wal_seen, len(lines)):
                    out.append(json.loads(lines[i]))
            return out

    # -- scripted + pending op resolution ------------------------------
    def _owner_of(self, cid: int) -> int:
        # the routing contract, in one place: the shared PlacementMap
        # when one is attached (p2c placement / live migration), else
        # the historical static ``slots.owner_shard``
        if self.placement is not None:
            return int(self.placement.shard_of(cid))
        from .slots import owner_shard

        return int(owner_shard(cid, self.shard[1]))

    def _owns(self, cid: int) -> bool:
        return self.shard is None or \
            self._owner_of(cid) == self.shard[0]

    def _due_scripted(self, b: int, every: int) -> List[dict]:
        if self.static:
            out = []
            if b == 0:
                for cid in range(self.total):
                    if not self._owns(cid):
                        continue
                    r, w, l = churn_mod.init_qos(self.spec, cid)
                    out.append({"op": "register", "cid": cid,
                                "r": r, "w": w, "l": l})
            out += [e for e in churn_mod.events(self.spec, b, every)
                    if e["op"] == "update" and self._owns(e["cid"])]
            return out
        return [e for e in churn_mod.events(self.spec, b, every)
                if self._owns(e["cid"])]

    # -- the boundary --------------------------------------------------
    def boundary(self, state: EngineState, b: int, every: int, *,
                 ledger=None, slo_block=None, extras=None):
        """Apply everything due at boundary ``b`` (the epoch index the
        next window starts at): WAL ingest, scripted registrations and
        QoS updates, pending control ops with ``apply_at <= b`` (None
        = first boundary after acceptance), idle evictions, then the
        compaction epoch when due.  Returns the possibly grown /
        compacted ``(state, ledger)``; ``ledger=None`` passes through.
        Deterministic: a resumed incarnation replaying this boundary
        from the same checkpoint applies the identical ops.

        ``slo_block`` (the obs.slo window block; pass only with an
        attached SloPlane) makes the return a 3-tuple: the block grows
        with capacity, permutes with compaction, zeroes with eviction,
        and leaves re-stamped with the post-boundary contract epochs.
        Boundaries sit exactly on the window-roll grid, so the block's
        counters are zero here and only the contract-epoch column is
        live -- a lifecycle op can never smear into a closed window.

        ``extras`` (list of ``(array, fill)`` pairs; axis 0 = slot)
        rides additional per-slot arrays through the SAME transforms:
        grown capacity pads with ``fill``, eviction resets the
        departing slot's row to ``fill`` (a recycled slot must look
        fresh), compaction gathers by the same permutation -- the
        mesh counter plane's cd/cr (fill 0) and held views (fill 1,
        the protocol origin) follow the slot layout this way.  When
        given, the transformed list is appended to the return
        tuple."""
        import jax

        from ..obs import spans as _spans

        slo_wanted = slo_block is not None
        extras_wanted = extras is not None
        extras = list(extras) if extras is not None else None

        with self.lock:
            self._wal_ingest()
            due = self._due_scripted(b, every)
            still: List[dict] = []
            for rec in self.pending:
                at = rec.get("apply_at")
                if at is None or int(at) <= b:
                    due.append(rec)
                else:
                    still.append(rec)
            self.pending = still

            rows: List[Tuple[int, int, int, int, int, int]] = []
            evict_api: List[dict] = []
            for op in due:
                if op["op"] == "register":
                    rows += self._register_row(op)
                    # growth may be needed before the row's slot exists
                elif op["op"] == "update":
                    rows += self._update_row(op)
                else:
                    evict_api.append(op)

            # growth happens inside _register_row via self._grow_to;
            # the grown state is staged on the instance
            state, ledger, slo_block, extras = self._take_growth(
                state, ledger, slo_block, extras)

            # idle evictions: scripted policy (zero-arrival streak,
            # drained queue) + control-plane DELETEs (drained only;
            # an undrained DELETE stays pending for the next boundary).
            # A STATIC plane runs the identical policy but IDLE-MARKS
            # instead of erasing (LC_IDLE): departure must leave the
            # engines' idle-reactivation min the same way in both
            # runs, or the dynamic-vs-static digest gate cannot hold.
            depth = None
            evict_slots: List[int] = []
            cand = self._evict_candidates(b, evict_api)
            if cand:
                depth = np.asarray(jax.device_get(state.depth),
                                   dtype=np.int64)
                for op in cand:
                    cid = op["cid"]
                    slot = self.slots.slot_of.get(cid)
                    if slot is None:
                        continue          # already gone
                    if depth[slot] != 0:
                        if op.get("seq") is not None:
                            still.append(op)   # DELETE waits for drain
                        continue
                    if self.static:
                        rows.append((LC_IDLE, slot, 0, 0, 0, 0))
                        if cid < self.total:
                            self.streak[cid] = 0
                        self.counters["idle_marks"] += 1
                    else:
                        rows.append((LC_EVICT, slot, 0, 0, 0, 0))
                        evict_slots.append(slot)
                        self._retire(cid, slot, ledger)
                self.pending = still

            if rows:
                pad = _pad_len(len(rows))
                rows += [(LC_NOP, 0, 0, 0, 0, 0)] * (pad - len(rows))
                arr = np.asarray(rows, dtype=np.int64)
                state = apply_op_vector(
                    state, arr[:, 0], arr[:, 1], arr[:, 2],
                    arr[:, 3], arr[:, 4], arr[:, 5])
            if evict_slots and ledger is not None:
                import jax.numpy as jnp
                ledger = ledger.at[jnp.asarray(evict_slots)].set(0)
            if evict_slots and slo_block is not None:
                import jax.numpy as jnp
                slo_block = slo_block.at[jnp.asarray(evict_slots)] \
                    .set(0)
            if evict_slots and extras is not None:
                import jax.numpy as jnp
                idx = jnp.asarray(evict_slots)
                extras = [(arr.at[idx].set(fill), fill)
                          for arr, fill in extras]

            # streaks for the upcoming window [b, b+every): counted
            # BEFORE serving it, so boundary b+every evicts on
            # completed-window information only.  Only REGISTERED
            # clients accrue quiet windows -- a cohort's rate is zero
            # before its start, and counting those windows would evict
            # a flash crowd at the very boundary it registers.  Runs
            # in BOTH modes: the static reference shares the policy
            # (it idle-marks where the dynamic run evicts).
            if self.spec["evict_after"] > 0:
                lam = np.zeros(self.total)
                for e in range(b, b + every):
                    lam += churn_mod.lam_vector(self.spec, e)
                quiet = lam == 0.0
                reg = np.zeros(self.total, dtype=bool)
                for cid in self.slots.slot_of:
                    if cid < self.total:
                        reg[cid] = True
                self.streak = np.where(reg & quiet, self.streak + 1, 0)

            state, ledger, slo_block, extras = self._maybe_compact(
                state, ledger, slo_block, extras, b, every, _spans)
            self.peak_live = max(self.peak_live, self.slots.live_count)
            if slo_wanted and self._slo is not None:
                slo_block = self._slo.stamp(
                    slo_block, self.slots.cid_of_slot)
            out = (state, ledger)
            if slo_wanted:
                out += (slo_block,)
            if extras_wanted:
                out += (extras,)
            return out

    # -- boundary internals --------------------------------------------
    def _register_row(self, op: dict):
        cid = op["cid"]
        if cid in self.slots.slot_of:
            return []                     # replayed / duplicate accept
        slot = self.slots.allocate(cid)
        while slot < 0:
            self._grow_pending = max(
                getattr(self, "_grow_pending", 0),
                self.slots.capacity * 2)
            self.slots.grow(self.slots.capacity * 2)
            slot = self.slots.allocate(cid)
        if self.slots.was_used(slot):
            self.counters["slot_recycles"] += 1
        if self.placement is not None:
            # placement-path-independent tie-break rank: a client
            # must carry the SAME order whether it registered here
            # at its cohort boundary or arrived by migration -- the
            # client id is the one rank every path agrees on (the
            # churn generators register cohorts in ascending-id =
            # start order, so at S=1 this matches take_order exactly)
            order = cid
        else:
            order = self.slots.take_order()
        self.qos[cid] = (op["r"], op["w"], op["l"])
        if cid < self.total:
            self.streak[cid] = 0
        self.counters["registrations"] += 1
        if self._slo is not None:
            self._slo.register(cid, op["r"], op["w"], op["l"])
        return [(LC_REGISTER, slot,
                 rate_to_inv_ns(op["r"]), rate_to_inv_ns(op["w"]),
                 rate_to_inv_ns(op["l"]), order)]

    def _update_row(self, op: dict):
        cid = op["cid"]
        slot = self.slots.slot_of.get(cid)
        if slot is None:
            return []                     # departed before its boundary
        self.qos[cid] = (op["r"], op["w"], op["l"])
        self.counters["qos_updates"] += 1
        if self._slo is not None:
            self._slo.update(cid, op["r"], op["w"], op["l"])
        return [(LC_UPDATE, slot,
                 rate_to_inv_ns(op["r"]), rate_to_inv_ns(op["w"]),
                 rate_to_inv_ns(op["l"]), 0)]

    def _take_growth(self, state, ledger, slo_block=None,
                     extras=None):
        new_n = getattr(self, "_grow_pending", 0)
        if new_n > state.capacity:
            import jax.numpy as jnp
            state = grow_state(state, new_n)
            if ledger is not None:
                pad = jnp.zeros((new_n - ledger.shape[0],
                                 ledger.shape[1]), dtype=ledger.dtype)
                ledger = jnp.concatenate([ledger, pad], axis=0)
            if slo_block is not None:
                pad = jnp.zeros((new_n - slo_block.shape[0],
                                 slo_block.shape[1]),
                                dtype=slo_block.dtype)
                slo_block = jnp.concatenate([slo_block, pad], axis=0)
            if extras is not None:
                import jax.numpy as jnp
                grown = []
                for arr, fill in extras:
                    pad = jnp.full((new_n - arr.shape[0],)
                                   + arr.shape[1:], fill,
                                   dtype=arr.dtype)
                    grown.append((jnp.concatenate([arr, pad],
                                                  axis=0), fill))
                extras = grown
            self.counters["grows"] += 1
        self._grow_pending = 0
        return state, ledger, slo_block, extras

    def ensure_capacity(self, cap: int, state, ledger=None,
                        slo_block=None, extras=None):
        """Grow this plane's slot space AND state arrays to at least
        ``cap`` (no-op below current capacity) -- how a mesh job keeps
        the STACKED per-shard layout rectangular: one shard's
        grow-on-demand doubling forces every sibling to the same
        capacity before the restack (docs/LIFECYCLE.md "Per-shard
        routing").  Same return shape discipline as :meth:`boundary`:
        ``(state, ledger[, slo_block][, extras])``."""
        with self.lock:
            cap = int(cap)
            if cap > self.slots.capacity:
                self.slots.grow(cap)
            if cap > state.capacity:
                self._grow_pending = max(
                    getattr(self, "_grow_pending", 0), cap)
            state, ledger, slo_block, extras = self._take_growth(
                state, ledger, slo_block, extras)
            out = (state, ledger)
            if slo_block is not None:
                out += (slo_block,)
            if extras is not None:
                out += (extras,)
            return out

    def _evict_candidates(self, b: int, evict_api: List[dict]):
        out = list(evict_api)
        if self.spec["evict_after"] > 0 and b > 0:
            for cid in sorted(self.slots.slot_of):
                if cid < self.total and \
                        self.streak[cid] >= self.spec["evict_after"]:
                    out.append({"op": "evict", "cid": cid})
        return out

    def _retire(self, cid: int, slot: int, ledger) -> None:
        """Fold the departing client's final conformance-ledger row
        into the departed report BEFORE its slot is recycled -- a
        silently zeroed row would erase QoS history with no trace
        (the ``engine/queue.py`` host mirror keeps the same
        contract)."""
        import jax

        if ledger is not None:
            row = np.asarray(jax.device_get(ledger[slot]),
                             dtype=np.int64).copy()
        else:
            row = np.zeros(5, dtype=np.int64)
        self.departed.append((cid, row))
        self.slots.release(cid)
        self.qos.pop(cid, None)
        if cid < self.total:
            self.streak[cid] = 0
        self.counters["evictions"] += 1
        if self._slo is not None:
            self._slo.evict(cid)

    # -- live migration halves (docs/LIFECYCLE.md "Placement and
    # migration"): the supervisor's ``_mesh_migrate`` drives these as
    # one two-sided move -- EVICT on the source plane, REGISTER on the
    # destination -- both expressed as the EXISTING digest-neutral op
    # vector, with the carried per-slot riders (counter views,
    # provenance watermark) installed by the caller.
    def migrate_out(self, cid: int, ledger):
        """Source half of a live move: fold the departing client's
        final ledger row into the departed report (same contract as
        idle eviction -- QoS history never silently zeroes), release
        its slot, and hand back ``(slot, qos_triple)`` for the
        destination's REGISTER.  Returns None when the client is not
        (or no longer -- a replayed boundary) resident here; counted
        as ``migrations_out``, NOT an eviction."""
        import jax

        with self.lock:
            slot = self.slots.slot_of.get(cid)
            if slot is None:
                return None
            qos = self.qos.get(cid, (0.0, 1.0, 0.0))
            if ledger is not None:
                row = np.asarray(jax.device_get(ledger[slot]),
                                 dtype=np.int64).copy()
            else:
                row = np.zeros(5, dtype=np.int64)
            self.departed.append((cid, row))
            self.slots.release(cid)
            self.qos.pop(cid, None)
            if cid < self.total:
                self.streak[cid] = 0
            self.counters["migrations_out"] += 1
            if self._slo is not None:
                self._slo.evict(cid)
            return slot, qos

    def migrate_in(self, cid: int, qos) -> list:
        """Destination half: a plain registration (``_register_row``
        semantics -- growth staged on demand, order = client id under
        an attached placement map, SLO contract epoch bumped) carrying
        the source's QoS triple.  Returns the LC_REGISTER op rows for
        the destination's batched ``apply_op_vector`` launch; also
        counted as ``migrations_in`` (``registrations`` counts every
        REGISTER, migrations included)."""
        with self.lock:
            r, w, l = (float(qos[0]), float(qos[1]), float(qos[2]))
            rows = self._register_row({"op": "register", "cid": cid,
                                       "r": r, "w": w, "l": l})
            if rows:
                self.counters["migrations_in"] += 1
            return rows

    def _maybe_compact(self, state, ledger, slo_block, extras,
                       b: int, every: int, _spans):
        ce = self.spec["compact_every"]
        if self.static or not ce or b == 0 or (b // every) % ce != 0:
            return state, ledger, slo_block, extras
        perm = self.slots.compaction_perm()
        if perm is None:
            return state, ledger, slo_block, extras
        with _spans.span(self.tracer, "lifecycle.compact", "dispatch",
                         boundary=b, live=self.slots.live_count):
            more = tuple(x for x in (ledger, slo_block)
                         if x is not None)
            xarrs = tuple(arr for arr, _fill in extras) \
                if extras is not None else ()
            out = compact_tree((state,) + more + xarrs, perm)
            state = out[0]
            it = iter(out[1:])
            if ledger is not None:
                ledger = next(it)
            if slo_block is not None:
                slo_block = next(it)
            if extras is not None:
                extras = [(next(it), fill) for _arr, fill in extras]
        if _compact_hook is not None:
            _compact_hook()      # crash seam: device gather done,
        #                          host map not yet re-mapped
        self.slots.apply_perm(perm)
        self.counters["compactions"] += 1
        return state, ledger, slo_block, extras

    def force_compact(self, state, ledger=None, slo_block=None,
                      extras=None, *, b: int = 0):
        """Controller-triggered compaction OFF the ``compact_every``
        grid (the control plane's compaction actuation,
        docs/CONTROLLER.md): same gather, same perm source, same
        digest-neutrality invariant as the scheduled epoch -- a
        compacted run's canonical digest equals the uncompacted one.
        No-op (gracefully) when the layout is already dense or the
        plane is static; deterministic either way, so a journal-
        replayed trigger reproduces the identical layout.  Same
        return-shape discipline as :meth:`boundary`:
        ``(state, ledger[, slo_block][, extras])``."""
        from ..obs import spans as _spans

        slo_wanted = slo_block is not None
        extras_wanted = extras is not None
        extras = list(extras) if extras is not None else None
        with self.lock:
            perm = None if self.static else self.slots.compaction_perm()
            if perm is not None:
                with _spans.span(self.tracer, "lifecycle.compact",
                                 "dispatch", boundary=b,
                                 live=self.slots.live_count):
                    more = tuple(x for x in (ledger, slo_block)
                                 if x is not None)
                    xarrs = tuple(arr for arr, _fill in extras) \
                        if extras is not None else ()
                    out = compact_tree((state,) + more + xarrs, perm)
                    state = out[0]
                    it = iter(out[1:])
                    if ledger is not None:
                        ledger = next(it)
                    if slo_block is not None:
                        slo_block = next(it)
                    if extras is not None:
                        extras = [(next(it), fill)
                                  for _arr, fill in extras]
                if _compact_hook is not None:
                    _compact_hook()
                self.slots.apply_perm(perm)
                self.counters["compactions"] += 1
                if slo_wanted and self._slo is not None:
                    slo_block = self._slo.stamp(
                        slo_block, self.slots.cid_of_slot)
            out = (state, ledger)
            if slo_wanted:
                out += (slo_block,)
            if extras_wanted:
                out += (extras,)
            return out

    # -- arrival-count mapping -----------------------------------------
    def map_counts(self, raw) -> np.ndarray:
        """Map RAW per-client-id Poisson draws (``[..., total_ids]``)
        onto the current slot layout (``[..., capacity]``,
        unregistered ids dropped -- the churn generators keep their
        rates zero, so nothing real is ever dropped).  The RNG draw
        itself stays in id space: identical consumption in the dynamic
        run and its static reference is what makes the digest gate
        meaningful."""
        raw = np.asarray(raw)
        out = np.zeros(raw.shape[:-1] + (self.slots.capacity,),
                       dtype=np.int32)
        live = self.slots.cid_of_slot >= 0
        cids = self.slots.cid_of_slot[live]
        out[..., live] = raw[..., cids]
        return out

    # -- canonical digest views ----------------------------------------
    def canon_results(self, results) -> tuple:
        """Decision-stream results re-expressed in client-id space:
        slot-indexed fields translate through the map (-1 pads pass
        through), per-slot capacity arrays scatter to the id space.
        What the chain digest hashes for a churn run -- invariant
        under registration timing, recycling, growth, and compaction
        (``engine.fastpath.DECISION_SLOT_FIELDS``)."""
        import jax

        out = []
        for r in results:
            ns = SimpleNamespace()
            for name in ("count", "unit_count", "resv_count", "cls",
                         "length", "phase", "cost", "lb", "type"):
                if hasattr(r, name) and getattr(r, name) is not None:
                    setattr(ns, name, getattr(r, name))
            if hasattr(r, "slot") and r.slot is not None:
                ns.slot = self.slots.translate(
                    np.asarray(jax.device_get(r.slot)))
            if hasattr(r, "served") and r.served is not None:
                ns.served = self.slots.scatter_by_cid(
                    np.asarray(jax.device_get(r.served)), self.total)
            out.append(ns)
        return tuple(out)

    # -- reports / observability ---------------------------------------
    def departed_report(self, drain: bool = True):
        """``(cid, int64[5] final ledger row)`` per departed client in
        eviction order (LED_* columns); ``drain=False`` peeks."""
        with self.lock:
            out = list(self.departed)
            if drain:
                self.departed.clear()
            return out

    def snapshot(self) -> dict:
        """Control-plane summary (the admin API's ``GET /clients`` and
        the bench/result JSON block)."""
        with self.lock:
            return {"live_clients": self.slots.live_count,
                    "peak_clients": self.peak_live,
                    "capacity": self.slots.capacity,
                    "pending_ops": len(self.pending),
                    **{k: int(v) for k, v in self.counters.items()}}

    def publish(self, registry, labels=None) -> None:
        """Register the lifecycle counters as scrape gauges."""
        rows = (
            ("dmclock_lc_registrations_total", "registrations",
             "clients registered through the lifecycle plane"),
            ("dmclock_lc_evictions_total", "evictions",
             "idle clients evicted (slot recycled; final ledger row "
             "folded into the departed-clients report first)"),
            ("dmclock_lc_compactions_total", "compactions",
             "compaction epochs launched (live clients repacked into "
             "a dense prefix)"),
            ("dmclock_lc_qos_updates_total", "qos_updates",
             "live ClientInfo updates applied at epoch boundaries"),
            ("dmclock_lc_slot_recycles_total", "slot_recycles",
             "registrations that re-used a previously-owned slot"),
            ("dmclock_lc_grows_total", "grows",
             "geometric state-array doublings"),
        )
        for name, key, help_text in rows:
            registry.gauge(name, help_text, labels=labels)\
                .set_function(lambda k=key: float(self.counters[k]))
        registry.gauge("dmclock_lc_live_clients",
                       "currently registered clients", labels=labels)\
            .set_function(lambda: float(self.slots.live_count))
        registry.gauge("dmclock_lc_peak_clients",
                       "peak simultaneously-registered clients",
                       labels=labels)\
            .set_function(lambda: float(self.peak_live))

    # -- checkpoint round-trip -----------------------------------------
    def encode(self) -> dict:
        """The plane as flat ``lc_*`` checkpoint leaves (rides the
        PR-5 rotation payload; variable-capacity arrays restore with
        ``strict_shapes=False``)."""
        with self.lock:
            pend = np.asarray(
                [[{"register": 1, "update": 2, "evict": 3}[p["op"]],
                  p["cid"], p["r"], p["w"], p["l"],
                  -1.0 if p.get("apply_at") is None
                  else float(p["apply_at"]),
                  float(p.get("seq", -1))]
                 for p in self.pending],
                dtype=np.float64).reshape(len(self.pending), 7)
            qos = np.asarray(
                [[cid, r, w, l]
                 for cid, (r, w, l) in sorted(self.qos.items())],
                dtype=np.float64).reshape(len(self.qos), 4)
            dep = np.asarray(
                [[cid] + row.tolist() for cid, row in self.departed],
                dtype=np.int64).reshape(len(self.departed), 6)
            return {**self.slots.encode(),
                    "lc_streak": self.streak.copy(),
                    "lc_wal_seen": np.int64(self.wal_seen),
                    "lc_pending": pend,
                    "lc_qos": qos,
                    "lc_departed": dep,
                    "lc_counters": np.asarray(
                        [self.counters[k] for k in COUNTER_KEYS],
                        dtype=np.int64),
                    "lc_peak": np.int64(self.peak_live)}

    @classmethod
    def load(cls, payload: dict, spec: dict, *,
             workdir: Optional[str] = None,
             tracer=None,
             shard: Optional[Tuple[int, int]] = None
             ) -> "LifecyclePlane":
        p = cls(spec, workdir=workdir, tracer=tracer, shard=shard)
        p.slots = SlotMap.load(payload)
        p.streak = np.asarray(payload["lc_streak"],
                              dtype=np.int64).copy()
        p.wal_seen = int(payload["lc_wal_seen"])
        opname = {1: "register", 2: "update", 3: "evict"}
        p.pending = [
            {"op": opname[int(row[0])], "cid": int(row[1]),
             "r": float(row[2]), "w": float(row[3]),
             "l": float(row[4]),
             "apply_at": None if row[5] < 0 else int(row[5]),
             "seq": None if row[6] < 0 else int(row[6])}
            for row in np.asarray(payload["lc_pending"],
                                  dtype=np.float64)]
        p.qos = {int(row[0]): (float(row[1]), float(row[2]),
                               float(row[3]))
                 for row in np.asarray(payload["lc_qos"],
                                       dtype=np.float64)}
        p.departed = [
            (int(row[0]), np.asarray(row[1:], dtype=np.int64))
            for row in np.asarray(payload["lc_departed"],
                                  dtype=np.int64)]
        ctr = np.asarray(payload["lc_counters"], dtype=np.int64)
        p.counters = {k: int(v) for k, v in zip(COUNTER_KEYS, ctr)}
        p.peak_live = int(payload["lc_peak"])
        return p

    @classmethod
    def empty_leaves(cls) -> dict:
        """Zero-size ``lc_*`` leaves for jobs without a churn spec --
        the checkpoint payload's structure must depend only on the job
        config (the PR-6 telemetry-leaf convention)."""
        return {"lc_cids": np.zeros(0, dtype=np.int64),
                "lc_ever": np.zeros(0, dtype=bool),
                "lc_next_order": np.int64(0),
                "lc_streak": np.zeros(0, dtype=np.int64),
                "lc_wal_seen": np.int64(0),
                "lc_pending": np.zeros((0, 7), dtype=np.float64),
                "lc_qos": np.zeros((0, 4), dtype=np.float64),
                "lc_departed": np.zeros((0, 6), dtype=np.int64),
                "lc_counters": np.zeros(len(COUNTER_KEYS),
                                        dtype=np.int64),
                "lc_peak": np.int64(0)}


def wal_append(workdir, op: dict) -> int:
    """Append one control op to a workdir's admin WAL without a live
    plane -- how a test (or an operator) pre-seeds accepted ops that a
    supervised run must apply exactly once (validated with the same
    client-naming errors as the live path)."""
    total = max(int(op.get("cid", 0)) + 1, 1)
    plane = LifecyclePlane({"scenario": "flash_crowd",
                            "total_ids": total,
                            "static": False, "capacity0": 1,
                            "base_lam": 0.0, "evict_after": 0,
                            "compact_every": 0, "qos_r": 0.0,
                            "qos_l": 0.0, "qos_wmod": 1},
                           workdir=os.fspath(workdir))
    return plane.accept(op)
