#!/usr/bin/env python
"""Trustworthy timing on the tunneled TPU.

``block_until_ready`` has proven unreliable through the axon tunnel
(some buffers report ready early), so every measurement here forces a
``device_get`` of a SCALAR digest that data-depends on the full
computation chain, and subtracts the independently measured scalar
round-trip latency.  Use long chains (>= 1s of device work) so the
residual noise is irrelevant."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

# the tag algebra is int64 ns end to end; enable x64 before any scalar
# below is created so callers importing this module first (the sweep
# scripts) don't silently truncate to int32
jax.config.update("jax_enable_x64", True)


@jax.jit
def state_digest(st):
    """Scalar that data-depends on every committed batch of an epoch
    chain (depth/head_prop/prev_resv are all mutated per commit)."""
    return st.depth.sum() + st.head_prop.sum() + st.prev_resv.sum()


def scalar_latency(reps: int = 5) -> float:
    """Round-trip cost of device_get on a freshly computed scalar."""
    x = jnp.int64(3)
    f = jax.jit(lambda v: v * 2 + 1)
    jax.device_get(f(x))
    t0 = time.perf_counter()
    v = x
    for _ in range(reps):
        v = f(v)
        jax.device_get(v)
    return (time.perf_counter() - t0) / reps


def timed_chain(step_fn, state0, n_steps: int, digest_fn,
                latency: float | None = None):
    """Run ``state = step_fn(state)`` n_steps times, then device_get
    ``digest_fn(state)`` (a jitted scalar).  Returns (seconds, digest),
    latency-corrected."""
    if latency is None:
        latency = scalar_latency()
    t0 = time.perf_counter()
    st = state0
    for _ in range(n_steps):
        st = step_fn(st)
    digest = jax.device_get(digest_fn(st))
    t = time.perf_counter() - t0 - latency
    return t, digest, st
