#!/usr/bin/env python
"""Headline benchmark: dmClock scheduling decisions/sec at 100k clients.

Preloads a 100k-client engine state (uniform reservation, mixed weights
-- BASELINE.json config #3 shape), then times ``engine_run`` batches in
advance-now mode (infinitely fast server: every launch is pure
scheduling work).  Prints ONE json line; ``vs_baseline`` is the ratio to
the BASELINE.json north-star target of 10M decisions/sec/chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def main() -> None:
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine import kernels

    n_clients = 100_000
    depth = 8
    batch = 2048
    state = _preloaded_state(n_clients, depth, ring=depth)

    run = jax.jit(lambda st, now: kernels.engine_run(
        st, now, batch, allow_limit_break=False, anticipation_ns=0,
        advance_now=True))

    # compile + warm
    state, now, decs = run(state, jnp.int64(0))
    jax.block_until_ready(decs)

    total = 0
    t0 = time.perf_counter()
    launches = 8
    for _ in range(launches):
        state, now, decs = run(state, now)
    served = int((jax.device_get(decs.type) == 0).sum())  # syncs all
    elapsed = time.perf_counter() - t0
    total = launches * batch  # all decisions in steady state serve
    assert served == batch, f"engine starved: {served}/{batch}"

    dps = total / elapsed
    print(json.dumps({
        "metric": "dmclock scheduling decisions/sec @100k clients",
        "value": round(dps, 1),
        "unit": "decisions/sec/chip",
        "vs_baseline": round(dps / 10_000_000, 4),
    }))


if __name__ == "__main__":
    main()
