#!/usr/bin/env python
"""Headline benchmark: dmClock scheduling decisions/sec, arrivals included.

Three measured workloads (BASELINE.json configs), all on the
prefix-commit epoch engine (``fastpath.scan_prefix_epoch``, bit-exact
vs the serial engine -- ``tests/test_prefix.py``):

- **serve-only**: preloaded 100k-client weight steady state (the
  round-1/2 headline protocol, kept for continuity).
- **config #3 sustained**: 10k clients, uniform ClientInfo, Poisson
  arrival waves ingested ON DEVICE between serve epochs
  (``kernels.ingest_superwave``) -- the closed loop pays for ingest,
  ring traffic, and epoch boundaries.
- **config #4 sustained**: 100k clients, Zipfian weights, uniform
  reservations sized so the constraint phase takes ~half of service
  (reservation-constrained multi-tenant); Poisson arrivals scaled to
  each client's service share; both dmClock phases active every round.

The PRIMARY value is the config #4 sustained rate (arrivals included);
the metric string carries the other two plus decision-latency
percentiles: a decision's latency is bounded by the round it rides in,
so p50 = mean round wall time from the async chain (pure device work,
trustworthy aggregate) and p99 = that mean plus the observed p99-p50
spread of individually sync'd rounds (tunnel jitter included, hence
conservative).

Timing: rounds/epochs are chained asynchronously on device; one scalar
digest that data-depends on every round is fetched at the end
(block_until_ready alone is unreliable through the tunneled runtime).
Decision counts are read back untimed and are exact (per-batch commit
counts).  Prints ONE json line; vs_baseline is the ratio to the 10M
north star.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def bench_serve_only(epochs: int = 7, k: int = 49152, m: int = 21):
    """Preloaded weight steady state, serving only (no ingest)."""
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine.fastpath import scan_prefix_epoch
    from profile_util import scalar_latency, state_digest

    state = _preloaded_state(100_000, 128, ring=128)
    run = jax.jit(functools.partial(
        scan_prefix_epoch, m=m, k=k, anticipation_ns=0),
        donate_argnums=(0,))
    ep = run(state, jnp.int64(0))
    jax.device_get(state_digest(ep.state))
    state = ep.state
    lat = scalar_latency()

    t0 = time.perf_counter()
    counts = []
    for _ in range(epochs):
        ep = run(state, jnp.int64(0))
        state = ep.state
        counts.append(ep.count)
    jax.device_get(state_digest(state))
    elapsed = time.perf_counter() - t0 - lat
    assert bool(jax.device_get(ep.guards_ok).all()), \
        "rebase guards tripped -- counts are not trustworthy"
    total = int(sum(int(jax.device_get(c).sum()) for c in counts))
    return {"dps": total / elapsed, "decisions": total,
            "fill": total / (epochs * m * k)}


def _zipf_weights(n: int, s: float = 1.1, lo: float = 0.5,
                  hi: float = 64.0) -> np.ndarray:
    """Zipf-by-rank weights, clipped to a sane QoS range and shuffled
    so slot order does not correlate with weight."""
    w = 1.0 / np.arange(1, n + 1) ** s
    w = np.clip(w / w[n // 2], lo, hi)
    rng = np.random.default_rng(7)
    rng.shuffle(w)
    return w


def _sustained_setup(n: int, ring: int, depth0: int, resv_rate: float,
                     weights: np.ndarray):
    from dmclock_tpu.core.timebase import rate_to_inv_ns
    from dmclock_tpu.engine import init_state

    st = init_state(n, ring)
    c = np.arange(n)
    rinv = np.full(n, rate_to_inv_ns(resv_rate), dtype=np.int64)
    winv = np.asarray([rate_to_inv_ns(w) for w in weights],
                      dtype=np.int64)
    phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
    jitter = (phase * 2.0 * winv).astype(np.int64)
    rjit = (phase * 2.0 * rinv).astype(np.int64)
    arrivals = np.tile(np.arange(1, depth0), (n, 1)).astype(np.int64)
    q_arr = np.zeros((n, ring), dtype=np.int64)
    q_arr[:, :depth0 - 1] = arrivals
    return st._replace(
        active=jnp.ones(n, dtype=bool),
        idle=jnp.zeros(n, dtype=bool),
        order=jnp.arange(n, dtype=jnp.int64),
        resv_inv=jnp.asarray(rinv),
        weight_inv=jnp.asarray(winv),
        head_resv=jnp.asarray(rinv + rjit),
        head_prop=jnp.asarray(winv + jitter),
        head_limit=jnp.full(n, -(1 << 62), dtype=jnp.int64),
        depth=jnp.full(n, depth0, dtype=jnp.int32),
        q_arrival=jnp.asarray(q_arr),
        q_cost=jnp.ones((n, ring), dtype=jnp.int64),
    )


def bench_sustained(n: int, k: int, m: int, rounds: int, *,
                    zipf: bool, resv_rate: float, dt_round_ns: int,
                    waves: int = 32, ring: int = 128,
                    depth0: int = 64, latency_rounds: int = 0):
    """Closed loop: Poisson superwave ingest + prefix serve epoch per
    round, chained async on device; ingest IS inside the timed region.

    Arrival rates match each client's expected service share
    (reservation floor + weight share of the surplus), so the loop is
    sustained: queues hover around depth0 instead of draining.
    Admission is clamped to ring headroom on device (the AtLimit
    Reject/EAGAIN analog, reference dmclock_server.h:989-993)."""
    from dmclock_tpu.engine import kernels
    from dmclock_tpu.engine.fastpath import scan_prefix_epoch
    from profile_util import scalar_latency, state_digest

    weights = _zipf_weights(n) if zipf else \
        np.asarray([1.0 + (i % 4) for i in range(n)])
    state = _sustained_setup(n, ring, depth0, resv_rate, weights)

    # initial arrival-rate guess: reservation floor + weight share of
    # the surplus; calibration rounds below replace it with measured
    # per-client service so the loop is self-consistent (stable depth)
    serve_per_round = m * k
    resv_per_round = n * resv_rate * (dt_round_ns / 1e9)
    surplus = max(serve_per_round - resv_per_round, 0.0)
    lam = resv_rate * (dt_round_ns / 1e9) + \
        surplus * (weights / weights.sum())
    lam = np.minimum(lam, waves - 1.0)

    cost = jnp.ones((n,), dtype=jnp.int64)
    dt_wave = dt_round_ns // waves

    def round_fn(st, counts, t_base):
        headroom = jnp.maximum(
            st.ring_capacity - st.depth, 0).astype(jnp.int32)
        counts = jnp.minimum(counts, headroom)
        wave_times = t_base + jnp.arange(waves, dtype=jnp.int64) \
            * dt_wave
        st = kernels.ingest_superwave(
            st, counts, wave_times, cost, cost, cost,
            anticipation_ns=0)
        ep = scan_prefix_epoch(st, t_base + dt_round_ns, m, k,
                               anticipation_ns=0)
        return ep

    run = jax.jit(round_fn, donate_argnums=(0,))
    rng = np.random.default_rng(11)

    def draw():
        return jnp.asarray(
            np.minimum(rng.poisson(lam), waves).astype(np.int32))

    # warm/compile, then calibration: measure per-client service over
    # two rounds and set each client's arrival rate to its measured
    # share -- arrivals == service, so the sustained loop neither
    # drains nor hits the admission clamp (untimed)
    ep = run(state, draw(), jnp.int64(0))
    jax.device_get(state_digest(ep.state))
    state = ep.state
    t_base = dt_round_ns
    served = np.zeros(n, dtype=np.int64)
    cal_rounds = 2
    for _ in range(cal_rounds):
        ep = run(state, draw(), jnp.int64(t_base))
        state = ep.state
        t_base += dt_round_ns
        slots = jax.device_get(ep.slot).ravel()
        np.add.at(served, slots[slots >= 0], 1)
    lam = np.minimum(served / cal_rounds, waves - 1.0)
    lat = scalar_latency()

    # pregenerate + upload every round's Poisson draws BEFORE timing:
    # the host RNG and the tunnel upload are the load GENERATOR, not
    # the scheduler (the reference's ns/call numbers likewise exclude
    # its client threads' own work); the on-device ingest of those
    # arrivals stays inside the timed region
    pre = [draw() for _ in range(rounds)]
    jax.block_until_ready(pre)

    t0 = time.perf_counter()
    counts_out, phases = [], []
    for i in range(rounds):
        ep = run(state, pre[i], jnp.int64(t_base))
        state = ep.state
        counts_out.append(ep.count)
        phases.append(ep.phase)
        t_base += dt_round_ns
    jax.device_get(state_digest(state))
    elapsed = time.perf_counter() - t0 - lat

    assert bool(jax.device_get(ep.guards_ok).all()), \
        "rebase guards tripped -- counts are not trustworthy"
    total = int(sum(int(jax.device_get(c).sum()) for c in counts_out))
    ph = np.concatenate([jax.device_get(p) for p in phases])
    cnts = np.concatenate([jax.device_get(c) for c in counts_out])
    resv_frac = float(cnts[ph == 0].sum()) / max(cnts.sum(), 1)
    out = {"dps": total / elapsed, "decisions": total,
           "fill": total / (rounds * m * k),
           "resv_phase_frac": resv_frac,
           "mean_depth": float(np.asarray(state.depth).mean())}

    if latency_rounds:
        # Decision-latency percentiles.  A decision's latency is
        # bounded by the wall time of the round it rides in.  The mean
        # round time from the async chain is trustworthy (aggregate of
        # pure device work); per-round sync'd samples measure device
        # work + tunnel round-trip whose jitter exceeds the device
        # work, so p99 is reported as the trusted mean plus the
        # OBSERVED sync'd jitter spread -- tunnel-inclusive, hence
        # conservative (a production runtime without the tunnel would
        # sit at or below these numbers).
        mean_ms = elapsed / rounds * 1e3
        samples = []
        for _ in range(latency_rounds):
            nxt = draw()
            t1 = time.perf_counter()
            ep = run(state, nxt, jnp.int64(t_base))
            state = ep.state
            jax.device_get(state_digest(state))
            samples.append(time.perf_counter() - t1)
            t_base += dt_round_ns
        spread = max(0.0, float(np.percentile(samples, 99)
                                - np.percentile(samples, 50))) * 1e3
        out["round_ms_p50"] = mean_ms
        out["round_ms_p99"] = mean_ms + spread
    return out


def main() -> None:
    import argparse
    import contextlib

    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", metavar="DIR", default=None)
    ap.add_argument("--mode", choices=["all", "serve", "cfg3", "cfg4"],
                    default="all")
    args = ap.parse_args()
    trace_ctx = (jax.profiler.trace(args.profile) if args.profile
                 else contextlib.nullcontext())

    results = {}
    with trace_ctx:
        if args.mode in ("all", "serve"):
            results["serve"] = bench_serve_only()
        if args.mode in ("all", "cfg3"):
            # 10k clients, uniform QoS, Poisson arrivals; weight regime
            results["cfg3"] = bench_sustained(
                10_000, 4096, 32, 20, zipf=False, resv_rate=100.0,
                dt_round_ns=100_000_000, ring=256, depth0=128)
        if args.mode in ("all", "cfg4"):
            # 100k clients, Zipfian weights, reservation-constrained:
            # resv floor ~= half of service capacity per round
            results["cfg4"] = bench_sustained(
                100_000, 49152, 21, 10, zipf=True, resv_rate=100.0,
                dt_round_ns=50_000_000, latency_rounds=12)

    c4 = results.get("cfg4")
    primary = c4 or results.get("cfg3") or results["serve"]
    parts = []
    if "serve" in results:
        parts.append(f"serve-only {results['serve']['dps']/1e6:.1f}M "
                     f"(fill {results['serve']['fill']:.2f})")
    if "cfg3" in results:
        r = results["cfg3"]
        parts.append(f"cfg3 10k-client Poisson sustained "
                     f"{r['dps']/1e6:.1f}M (fill {r['fill']:.2f}, "
                     f"depth {r['mean_depth']:.0f})")
    if c4:
        parts.append(
            f"cfg4 100k-client Zipf resv-constrained "
            f"{c4['dps']/1e6:.1f}M (resv phase "
            f"{c4['resv_phase_frac']:.2f}, round p50 "
            f"{c4.get('round_ms_p50', 0):.0f}ms p99 "
            f"{c4.get('round_ms_p99', 0):.0f}ms)")

    print(json.dumps({
        "metric": "dmclock sustained scheduling decisions/sec, "
                  "ARRIVALS INCLUDED (Poisson superwave ingest on "
                  "device each round; prefix-commit epochs, bit-exact "
                  "vs serial engine; decision stream in HBM, counts "
                  "read back untimed) -- " + "; ".join(parts),
        "value": round(primary["dps"], 1),
        "unit": "decisions/sec/chip",
        "vs_baseline": round(primary["dps"] / 10_000_000, 4),
    }))


if __name__ == "__main__":
    main()
