#!/usr/bin/env python
"""Headline benchmark: dmClock scheduling decisions/sec, arrivals included.

Three measured workloads (BASELINE.json configs), all on the
prefix-commit epoch engine (``fastpath.scan_prefix_epoch``, bit-exact
vs the serial engine -- ``tests/test_prefix.py``):

- **serve-only**: preloaded 100k-client weight steady state (the
  round-1/2 headline protocol, kept for continuity).
- **config #3 sustained**: 10k clients, uniform ClientInfo, Poisson
  arrival waves ingested ON DEVICE between serve epochs
  (``kernels.ingest_superwave``) -- the closed loop pays for ingest,
  ring traffic, and epoch boundaries.
- **config #4 sustained**: 100k clients, Zipfian weights, uniform
  reservations sized so the constraint phase takes ~half of service
  (reservation-constrained multi-tenant); Poisson arrivals scaled to
  each client's service share; both dmClock phases active every round.

The PRIMARY value is the config #4 sustained rate (arrivals included);
the metric string carries the other two plus MEASURED decision-latency
percentiles: a decision's latency is bounded by the round it rides in,
and per-round wall times are sampled from a windowed async chain (W
rounds in flight; each device_get returns when its round completes, so
successive return times are the real per-round completion intervals
with the tunnel round-trip hidden by the pipeline).  p50/p99 are
percentiles of >= 100 such samples.

Timing: rounds/epochs are chained asynchronously on device; one scalar
digest that data-depends on every round is fetched at the end
(block_until_ready alone is unreliable through the tunneled runtime).
Decision counts are read back untimed and are exact (per-batch commit
counts).  Prints ONE json line; vs_baseline is the ratio to the 10M
north star.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dmclock_tpu.obs import spans as obsspans

# LEGACY sorted-engine cfg4 reservation rate (round-4 calibration:
# share 0.49 at the sorted engine's ~6M dec/s equilibrium; kept for
# benchmark/run_sweeps.py's sorted-engine comparison rows).  The
# shipped cfg4 bench auto-calibrates the rate to target_resv_share on
# the calendar engine (round-5 equilibrium lands near 1200/s/client
# at ~46M dec/s -- the share is a function of rate/throughput).
CFG4_RESV_RATE = 25.0


def _timed_chain(run, state, epochs: int, tracer=None):
    """Chain ``epochs`` async epoch calls with ONE digest sync; returns
    (state, total_decisions, wall_s, guards_ok, metrics).  Guards are
    collected for EVERY epoch: a mid-chain trip zeroes that epoch's
    counts, and checking only the final epoch would report the deflated
    rate as valid.  ``metrics`` is the combined on-device obs vector
    (zeros when the runner compiled with metrics off), fetched UNTIMED
    after the wall clock stops.

    With a span ``tracer`` each async epoch call records a dispatch
    span (the per-launch dispatch tax -- the call returns once
    enqueued) and the digest sync a device_compute span (the chain's
    device-side remainder); together they cover the chain wall, the
    decomposition ``--spans`` reports."""
    from profile_util import state_digest

    from dmclock_tpu.obs import device as obsdev

    t0 = time.perf_counter()
    counts, guards, mets = [], [], []
    for _ in range(epochs):
        # the span covers the async call AND the result rebind: both
        # are per-launch host bookkeeping (on cpu the rebind also
        # absorbs wall time stolen by concurrently-running compute
        # threads, which would otherwise be attributed to nothing)
        with obsspans.span(tracer, "bench.epoch", "dispatch"):
            ep = run(state, jnp.int64(0))
            state = ep.state
            counts.append(ep.count)
            guards.append(ep.guards_ok)
            mets.append(ep.metrics)
    with obsspans.span(tracer, "bench.digest_sync", "device_compute"):
        jax.device_get(state_digest(state))
    wall = time.perf_counter() - t0
    g_ok = all(bool(jax.device_get(g).all()) for g in guards)
    total = int(sum(int(jax.device_get(c).sum()) for c in counts))
    met = obsdev_np_combine(
        np.zeros(obsdev.NUM_METRICS, dtype=np.int64),
        *[jax.device_get(m) for m in mets])
    return state, total, wall, g_ok, met


def _span_window(tracer):
    """Snapshot the tracer's per-category self-time totals at the
    start of a timed region (None tracer -> None window)."""
    return None if tracer is None else tracer.category_totals()


def _span_summary(tracer, window, wall_s: float, launches: int):
    """Close a span window over the timed chains: per-category
    self-time deltas, the per-launch dispatch/device split, and the
    host-overhead share of wall time -- the dispatch-tax decomposition
    the JSON line carries (``"spans"``) and the acceptance gate
    measures (host_prep + dispatch + device_compute + fetch + drain
    must cover >= 95% of the measured wall)."""
    if tracer is None or window is None:
        return None
    now = tracer.category_totals()
    d = {c: now.get(c, 0) - window.get(c, 0)
         for c in obsspans.CATEGORIES}
    wall_ns = max(wall_s * 1e9, 1.0)
    host_ns = d["ingest"] + d["host_prep"] + d["dispatch"] + \
        d["fetch"] + d["drain"]
    covered = host_ns + d["device_compute"] + d["checkpoint"]
    launches = max(launches, 1)
    return {
        "launches": launches,
        "dispatch_ms_per_launch": d["dispatch"] / launches / 1e6,
        "device_ms_per_launch": d["device_compute"] / launches / 1e6,
        "host_overhead_frac": host_ns / wall_ns,
        "covered_frac": covered / wall_ns,
        "wall_ms": wall_ns / 1e6,
        "categories_ms": {c: v / 1e6 for c, v in d.items() if v},
    }


def epoch_cost_analysis(compiled) -> dict:
    """Normalized per-epoch attribution from
    ``jax.stages.Compiled.cost_analysis()`` (the ROADMAP per-kernel
    cost item): the stable aggregates only -- flops and bytes accessed
    -- so PROFILE.md-style breakdowns regenerate from every bench JSON
    line instead of by hand.  Backends that cannot attribute (or old
    jax) degrade to an ``error`` note, never a crash."""
    from dmclock_tpu.obs import compile_plane as _cp

    try:
        ca = compiled.cost_analysis()
    except Exception as e:      # per-backend support varies
        return {"error": f"{type(e).__name__}: {e}"}
    # ONE normalization shared with the compile plane's per-entry
    # records, so the bench row and the record cannot disagree
    return _cp.normalize_cost_analysis(ca)


def _capacity_row(out: dict, cap_cfg: dict, cp0: dict) -> dict:
    """Fold the capacity plane's per-workload record into a result
    row (docs/OBSERVABILITY.md "Capacity plane"): the compile wall +
    retraces this workload added (compile-plane totals delta), the
    projected resident HBM for its knob setting, and the roofline
    verdict joining cost_analysis flops/bytes with the span tracer's
    measured dispatch/device self-time.  Telemetry must never eat the
    measurement -- every leg degrades, none raises."""
    from dmclock_tpu.obs import capacity as obscap
    from dmclock_tpu.obs import compile_plane as _cplane

    t1 = _cplane.plane().totals()
    out["compile_ms_total"] = round(
        t1["compile_ms_total"] - cp0.get("compile_ms_total", 0.0), 3)
    out["retraces"] = int(t1["retraces"] - cp0.get("retraces", 0))
    try:
        cfg = dict(cap_cfg)
        out["projected_hbm_bytes"] = obscap.projected_hbm(
            cfg.pop("n"), **cfg)
    except Exception as e:
        out["projected_hbm_error"] = f"{type(e).__name__}: {e}"
    try:
        rl = obscap.classify_bench_row(out)
        out["roofline"] = rl
        out["bound_class"] = rl["bound_class"]
    except Exception:
        out["bound_class"] = "unknown"
    return out


def _capacity_gate(cap_cfg: dict, *, select_impl: str = "sort",
                   calendar_impl: str = "minstop",
                   engine_loop: str = "round"):
    """Pre-launch projected-HBM check (``--capacity``): when the
    projection exceeds the detected device budget the workload is
    DOWNGRADED -- a stderr warning and a tagged skip row, never a
    crash (the BENCH_r05 unkillable-bench discipline).  Returns None
    when the workload fits or nothing is known (cpu boxes report no
    budget)."""
    import sys

    from dmclock_tpu.obs import capacity as obscap

    try:
        cfg = dict(cap_cfg)
        n = cfg.pop("n")
        budget = obscap.device_hbm_budget()
        if budget is None:
            return None
        projected = obscap.projected_hbm(n, **cfg)
        ok = obscap.fits(n, budget, **cfg)
    except Exception as e:   # the gate must never kill the bench
        print(f"# capacity: projection failed "
              f"({type(e).__name__}: {e}); workload not gated",
              file=sys.stderr)
        return None
    if ok:
        return None

    def gib(v):
        return f"{v / 2**30:.2f} GiB" if v >= (1 << 28) \
            else f"{v / 2**20:.1f} MiB"

    usable = int(budget * 0.9)   # fits()'s default slack_frac
    print(f"# capacity: projected {gib(projected)} exceeds the "
          f"usable budget {gib(usable)} (device {gib(budget)} minus "
          f"10% slack) -- workload SKIPPED, not crashed (n={n}; "
          f"plan_capacity() for the fitting shape)", file=sys.stderr)
    # the skip row keeps the standard scalar keys so the metric
    # string / history plumbing never KeyErrors; bench_guard excludes
    # capacity_skipped rows from the medians and never judges them
    return {"dps": 0.0, "decisions": 0, "fill": 0.0,
            "resv_phase_frac": 0.0, "mean_depth": 0.0,
            "decisions_per_launch": 0.0,
            "select_impl": select_impl,
            "calendar_impl": calendar_impl,
            "engine_loop": engine_loop,
            "capacity_skipped": True,
            "projected_hbm_bytes": int(projected),
            "hbm_budget_bytes": int(budget),
            "cost_analysis": {}}


def _feed_cost_registry(workload: str, cost: dict) -> None:
    """Mirror the attribution into the process-wide obs registry so
    embedders that scrape it (docs/OBSERVABILITY.md) see per-epoch
    cost without parsing the bench JSON line."""
    from dmclock_tpu.obs import default_registry

    reg = default_registry()
    for key, v in cost.items():
        if isinstance(v, (int, float)):
            reg.gauge(f"dmclock_epoch_cost_{key}",
                      "XLA cost_analysis attribution of the jitted "
                      "epoch", labels={"workload": workload}).set(v)


def bench_serve_only(k: int = 65536, m: int = 32, *,
                     epochs_lo: int = 3, epochs_hi: int = 6,
                     depth: int = 320, reps: int = 5,
                     n: int = 100_000, with_metrics: bool = True,
                     select_impl: str = "sort", tag_width: int = 64,
                     window_m: int | None = None, tracer=None):
    """Preloaded weight steady state, serving only (no ingest).

    DIFFERENCED chains: a short and a long chain each pay one dispatch
    ramp + one sync, so ``(D_hi - D_lo) / (T_hi - T_lo)`` cancels the
    fixed per-chain overhead exactly -- through the ~110ms tunnel a
    single-chain measurement of ~50ms of device work is mostly
    overhead, and round 3's two protocols disagreed 2-3x on identical
    shapes for exactly that reason (VERDICT r3 weak #3).

    BOTH chains must be device-bound: a chain's wall time is
    ``max(device_time, sync round-trip)``, so if the SHORT chain sits
    under the ~100ms RTT floor the difference divides by a truncated
    delta and the rate explodes (observed: a 1-epoch lo chain
    reporting 202M where the true rate was ~39M).  Chain sizes below
    keep the lo chain at ~150ms+ of device work, and reps whose lo
    wall is at the RTT floor are discarded.

    Operating point: the round-4 k/m sweep's argmax (benchmark/
    RESULTS.md, median-of-3 differenced pairs per point): k=65536,
    with a plateau of ~36-40M across m in {21, 32, 64} (protocol
    noise +-15% -- single-shot pairs at these shapes spread 41-71M,
    hence the medians).  m amortizes the ~17ms per-epoch dispatch
    cost (m=8 is ~40% below the plateau); m=128 regresses (the
    unrolled window-select chain scales with m); k=98304 regresses
    (the int32 rebase window clamps the selection boundary,
    fill 0.64)."""
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine.fastpath import scan_prefix_epoch
    from dmclock_tpu.obs import device as obsdev

    state = _preloaded_state(n, depth, ring=depth)
    need = (epochs_lo + epochs_hi + 1) * m * k
    # margin 1.5x: weights are 1..4, so the heaviest class is served
    # ~1.6x the mean; chains sized to the MEAN backlog drain the
    # heavy clients mid-chain and deflate both fill and the rate
    # (measured: 70.9M at 168 serves/client mean vs 28.6M at 360).
    # Ring width itself also costs: depth 384 measured 38.8M at the
    # same k/m (wider Pallas-rotate chunking + ring traffic), so the
    # operating point keeps the smallest ring that feeds the chains.
    assert need * 1.5 <= n * depth, \
        f"backlog {n * depth} cannot feed {need} decisions " \
        "with heavy-class margin"
    # AOT lower+compile: the Compiled handle both runs the chains and
    # carries the cost_analysis attribution (one compilation, not
    # two); routed through the compile plane so the JSON line's
    # compile_ms_total / retraces cover the bench's own programs
    from dmclock_tpu.obs import compile_plane as _cplane

    cp0 = _cplane.plane().totals()
    run = _cplane.aot_record(
        "bench.serve",
        (n, k, m, depth, select_impl, tag_width, window_m,
         with_metrics),
        jax.jit(functools.partial(
            scan_prefix_epoch, m=m, k=k, anticipation_ns=0,
            with_metrics=with_metrics, select_impl=select_impl,
            tag_width=tag_width, window_m=window_m),
            donate_argnums=(0,)),
        state, jnp.int64(0))
    cost = epoch_cost_analysis(run)
    # a single differenced pair still carries tunnel jitter of the
    # chains' own order; the MEDIAN over fresh-state reps is stable
    # (measured spread of singles at this shape: 41-71M)
    from profile_util import scalar_latency

    lat = scalar_latency()
    rates, total_d, total_pot = [], 0, 0
    met = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
    win = _span_window(tracer)
    wall_total = 0.0
    launches = 0
    for rep in range(max(reps, 1)):
        if rep:
            state = _preloaded_state(n, depth, ring=depth)
        state, _, w0, _, _ = _timed_chain(run, state, 1,
                                          tracer)   # warm/compile
        state, d_lo, t_lo, g1, m1 = _timed_chain(run, state,
                                                 epochs_lo, tracer)
        state, d_hi, t_hi, g2, m2 = _timed_chain(run, state,
                                                 epochs_hi, tracer)
        assert g1 and g2, "rebase guards tripped -- untrustworthy"
        met = obsdev_np_combine(met, m1, m2)
        wall_total += w0 + t_lo + t_hi
        launches += 1 + epochs_lo + epochs_hi
        if t_hi <= t_lo or t_lo < 1.2 * lat:
            continue    # jitter-inverted or RTT-floor-bound lo chain
        rates.append((d_hi - d_lo) / (t_hi - t_lo))
        total_d += d_hi + d_lo
        total_pot += (epochs_hi + epochs_lo) * m * k
    assert rates, \
        "no valid pair: chains too short for the tunnel RTT floor"
    out = {"dps": float(np.median(rates)), "decisions": total_d,
           "reps": [round(r / 1e6, 1) for r in rates],
           "fill": total_d / total_pot,
           "select_impl": select_impl, "tag_width": tag_width,
           "cost_analysis": cost}
    sp = _span_summary(tracer, win, wall_total, launches)
    if sp is not None:
        out["spans"] = sp
        out["dispatch_ms_per_launch"] = sp["dispatch_ms_per_launch"]
        out["host_overhead_frac"] = sp["host_overhead_frac"]
    if with_metrics:
        out["device_metrics"] = obsdev.metrics_dict(met)
    _capacity_row(out, dict(n=n, ring=depth, engine="prefix", m=m,
                            k=k, select_impl=select_impl,
                            tag_width=tag_width,
                            window_m=window_m), cp0)
    return out


def obsdev_np_combine(acc, *vecs):
    """Host-side metrics merge (counters add, hwm max) -- the shared
    numpy mirror of obs.device.metrics_combine."""
    from dmclock_tpu.obs import device as obsdev

    return obsdev.metrics_combine_np(acc, *vecs)


def _slo_result_block(out: dict, slo_eval) -> None:
    """Fold the burn-rate evaluator's verdict into a workload row:
    the readable 'slo' block plus the flat scalars bench_guard tracks
    as its own warn-only series -- ONE implementation for the
    sustained and churn workloads."""
    s = slo_eval.summary()
    out["slo"] = s
    out["slo_violations_total"] = s["violations_total"]
    out["slo_worst_share_err"] = s["worst_window_share_err"]
    out["slo_window_tardiness_p99_ns"] = s["window_tardiness_p99_ns"]
    out["slo_windows_closed"] = s["windows_closed"]


def _per_pass_cap(n: int, k: int, calendar_steps: int,
                  calendar_impl: str, ladder_levels: int) -> int:
    """Max decisions one batch/pass can commit -- the fill metric's
    denominator.  A bucketed calendar batch refreshes the per-client
    ``steps`` budget at every ladder level, so its cap scales with
    ``ladder_levels``; without the factor a bucketed run's fill would
    inflate past 1.0 and stop being comparable to the minstop series
    it is A/B'd against."""
    if not calendar_steps:
        return k
    levels = ladder_levels \
        if calendar_impl in ("bucketed", "wheel") else 1
    return n * calendar_steps * levels


def _zipf_weights(n: int, s: float = 1.1, lo: float = 0.5,
                  hi: float = 64.0) -> np.ndarray:
    """Zipf-by-rank weights, clipped to a sane QoS range and shuffled
    so slot order does not correlate with weight."""
    w = 1.0 / np.arange(1, n + 1) ** s
    w = np.clip(w / w[n // 2], lo, hi)
    rng = np.random.default_rng(7)
    rng.shuffle(w)
    return w


def _sustained_setup(n: int, ring: int, depth0: int,
                     resv_rates: np.ndarray, weights: np.ndarray,
                     resv_aligned: bool = False):
    """Preload ``depth0``-deep queues for a mixed-QoS population.

    ``resv_rates`` / ``weights`` are per-client; a zero disables that
    axis for the client (reference ClientInfo 0 -> 0 sentinel) and its
    preloaded head tag is pinned to MAX_TAG exactly as the tag kernel
    pins recomputed tags.

    ``resv_aligned`` drops the per-client reservation-phase stagger so
    reservation tags advance in lock-stepped cohorts (simultaneous-
    onset tenants); staggered tags spread each client's eligibility
    instant uniformly over its own period."""
    from dmclock_tpu.core.timebase import MAX_TAG, rate_to_inv_ns
    from dmclock_tpu.engine import init_state

    st = init_state(n, ring)
    c = np.arange(n)
    rinv = np.asarray([rate_to_inv_ns(r) for r in resv_rates],
                      dtype=np.int64)
    winv = np.asarray([rate_to_inv_ns(w) for w in weights],
                      dtype=np.int64)
    phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
    jitter = (phase * 2.0 * winv).astype(np.int64)
    rjit = np.zeros(n, dtype=np.int64) if resv_aligned else \
        (phase * 2.0 * rinv).astype(np.int64)
    head_resv = np.where(rinv == 0, np.int64(MAX_TAG), rinv + rjit)
    head_prop = np.where(winv == 0, np.int64(MAX_TAG), winv + jitter)
    arrivals = np.tile(np.arange(1, depth0), (n, 1)).astype(np.int64)
    q_arr = np.zeros((n, ring), dtype=np.int64)
    q_arr[:, :depth0 - 1] = arrivals
    return st._replace(
        active=jnp.ones(n, dtype=bool),
        idle=jnp.zeros(n, dtype=bool),
        order=jnp.arange(n, dtype=jnp.int64),
        resv_inv=jnp.asarray(rinv),
        weight_inv=jnp.asarray(winv),
        head_resv=jnp.asarray(head_resv),
        head_prop=jnp.asarray(head_prop),
        head_limit=jnp.full(n, -(1 << 62), dtype=jnp.int64),
        depth=jnp.full(n, depth0, dtype=jnp.int32),
        q_arrival=jnp.asarray(q_arr),
        q_cost=jnp.ones((n, ring), dtype=jnp.int64),
    )


def bench_sustained(n: int, k: int, m: int, rounds: int, *,
                    zipf: bool, resv_rate: float, dt_round_ns: int,
                    waves: int = 32, ring: int = 128,
                    depth0: int = 64, latency_rounds: int = 0,
                    rounds_lo: int = 0, resv_aligned: bool = False,
                    split_resv: float = 0.0, reps: int = 3,
                    chain_depth: int = 1, calendar_steps: int = 0,
                    target_resv_share: float = 0.0,
                    with_metrics: bool = True,
                    conformance_rounds: int = 2,
                    conformance_out: str = None,
                    select_impl: str = "sort",
                    calendar_impl: str = "minstop",
                    ladder_levels: int = 8,
                    wheel_kernel: str = "xla",
                    engine_loop: str = "round",
                    stream_chunk: int = 8,
                    telemetry: bool = True, slo: bool = False,
                    provenance: bool = True,
                    capacity_check: bool = True,
                    tracer=None, watchdog=None):
    """Closed loop: Poisson superwave ingest + prefix serve epoch per
    round, chained async on device; ingest IS inside the timed region.

    Arrival rates match each client's expected service share
    (reservation floor + weight share of the surplus), so the loop is
    sustained: queues hover around depth0 instead of draining.
    Admission is clamped to ring headroom on device (the AtLimit
    Reject/EAGAIN analog, reference dmclock_server.h:989-993).

    ``engine_loop`` (docs/ENGINE.md): "round" launches one fused
    ingest+serve round per dispatch (the PR-1..7 shape); "stream"
    fuses ``stream_chunk`` consecutive rounds into ONE launch (a
    ``lax.scan`` over the identical round body, so decisions are
    bit-identical) with the pre-generated Poisson draws uploaded as a
    block -- the launches-per-decision killer the streaming serve
    loop exists for.  Calibration / conformance / latency rounds stay
    on the round program either way (they are untimed and need the
    per-round slot outputs)."""
    from dmclock_tpu.engine import kernels
    from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                             scan_chain_epoch,
                                             scan_prefix_epoch)
    from dmclock_tpu.obs import compile_plane as _cplane
    from dmclock_tpu.obs import device as obsdev
    from dmclock_tpu.obs import histograms as obshist
    from profile_util import scalar_latency, state_digest

    # capacity plane (docs/OBSERVABILITY.md): the knob setting's
    # resident-HBM shape, for the pre-launch projected-HBM gate and
    # the JSON line's projected_hbm_bytes
    cap_engine = "calendar" if calendar_steps else \
        ("chain" if chain_depth > 1 else "prefix")
    cap_cfg = dict(
        n=n, ring=ring, engine=cap_engine, m=m,
        k=(calendar_steps if calendar_steps else k),
        chain_depth=chain_depth, select_impl=select_impl,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        telemetry=telemetry, slo=slo,
        stream_chunk=(stream_chunk if engine_loop == "stream" else 0))
    if capacity_check:
        skip = _capacity_gate(cap_cfg, select_impl=select_impl,
                              calendar_impl=calendar_impl,
                              engine_loop=engine_loop)
        if skip is not None:
            return skip
    cp0 = _cplane.plane().totals()

    # ``split_resv`` > 0 models split-population multi-tenancy: that
    # fraction of clients are reservation-ONLY floor tenants (w=0) and
    # the rest weight-only best-effort tenants (r=0).  Mixed-QoS
    # clients (both axes live) make the two dmClock phases alternate
    # PER DECISION at steady state -- every weight serve's reservation-
    # debt reduction (reference reduce_reservation_tags :1077-1111)
    # drags that client's reservation tag back to eligibility -- which
    # is semantically exact but serves the batch engine one-regime
    # slivers.  Disjoint populations keep each round's constraint debt
    # a coarse burst, which is also the more realistic storage-tenant
    # model (bought-floor tenants vs best-effort tenants).
    if split_resv > 0:
        n_resv = int(n * split_resv)
        w_tail = _zipf_weights(n - n_resv) if zipf else \
            np.asarray([1.0 + (i % 4) for i in range(n - n_resv)])
        weights = np.concatenate([np.zeros(n_resv), w_tail])
        resv_rates = np.concatenate(
            [np.full(n_resv, resv_rate), np.zeros(n - n_resv)])
    else:
        weights = _zipf_weights(n) if zipf else \
            np.asarray([1.0 + (i % 4) for i in range(n)])
        resv_rates = np.full(n, resv_rate)
    state = _sustained_setup(n, ring, depth0, resv_rates, weights,
                             resv_aligned=resv_aligned)

    # initial arrival-rate guess: reservation floor + weight share of
    # the surplus; calibration rounds below replace it with measured
    # per-client service so the loop is self-consistent (stable depth)
    # initial guess only: the calibration rounds replace it with
    # measured service.  Calendar mode has no [k] cap; seed with an
    # optimistic bound so calibration sees a saturated engine.
    serve_per_round = m * (n * calendar_steps if calendar_steps else k)
    resv_per_round = float(resv_rates.sum()) * (dt_round_ns / 1e9)
    surplus = max(serve_per_round - resv_per_round, 0.0)
    lam = resv_rates * (dt_round_ns / 1e9) + \
        surplus * (weights / weights.sum())
    lam = np.minimum(lam, waves - 1.0)

    cost = jnp.ones((n,), dtype=jnp.int64)
    dt_wave = dt_round_ns // waves

    # device telemetry accumulators (histograms + per-client ledger;
    # docs/OBSERVABILITY.md): threaded through every round AS CARRIED
    # STATE so chained rounds accumulate on device and the host
    # fetches once, untimed, at the end -- the async-drain discipline
    # the flight recorder uses.  The accumulation itself runs inside
    # the timed kernels (telemetry in the data path is the point);
    # --telemetry off A/Bs that cost, decisions bit-identical.
    # the SLO window block (obs.slo) rides the same donated carry:
    # windows roll between timed chains (one chain = one window,
    # fetched + re-zeroed untimed), and the burn-rate evaluator judges
    # each roll against the workload's reservation/weight contracts
    from dmclock_tpu.obs import slo as obsslo
    from dmclock_tpu.obs.alerts import SloEvaluator

    slo_plane = slo_eval = None
    if slo:
        slo_plane = obsslo.SloPlane(n, dt_epoch_ns=dt_round_ns,
                                    ring_depth=32)
        # initial contracts from the configured rates; calibration
        # rewrites resv_inv below, and the post-calibration
        # register_from_inv re-registers everyone from the DEVICE
        # arrays (a fresh contract epoch: the timed windows must be
        # judged against the floors the engine actually enforces,
        # not the pre-calibration guess)
        for c in range(n):
            slo_plane.register(c, float(resv_rates[c]),
                               float(weights[c]), 0.0)
        slo_eval = SloEvaluator(slo_plane, log=lambda _line: None)

    from dmclock_tpu.obs import provenance as obsprov

    def tele_zero(t0=0):
        out = (obshist.hist_zero(), obshist.ledger_zero(n)) \
            if telemetry else ()
        if provenance:
            # t0 = the measurement baseline: the post-calibration
            # reset must not read continuously-served clients as
            # starved since virtual t=0
            out = out + (obsprov.prov_init(n, now_ns=t0),)
        if slo:
            # the SLO block stays LAST: the per-chain roll reads and
            # replaces tele[-1]
            out = out + (slo_plane.stamp(obsslo.window_zero(n)),)
        return out

    def tele_unpack(tele):
        i = 0
        th = tl = tp = ts = None
        if telemetry:
            th, tl = tele[0], tele[1]
            i = 2
        if provenance:
            tp = tele[i]
            i += 1
        if slo:
            ts = tele[i]
        return th, tl, tp, ts

    tele = tele_zero()

    def round_fn(st, counts, t_base, tele):
        th, tl, tp, ts = tele_unpack(tele)
        headroom = jnp.maximum(
            st.ring_capacity - st.depth, 0).astype(jnp.int32)
        # admission clamp (the AtLimit Reject/EAGAIN analog); the drop
        # count feeds the on-device obs vector instead of vanishing
        counts, dropped = obsdev.admission_clamp(counts, headroom)
        wave_times = t_base + jnp.arange(waves, dtype=jnp.int64) \
            * dt_wave
        st = kernels.ingest_superwave(
            st, counts, wave_times, cost, cost, cost,
            anticipation_ns=0)
        now = t_base + dt_round_ns
        drop_met = obsdev.metrics_delta(ingest_drops=dropped) \
            if with_metrics else obsdev.metrics_zero()

        def tele_pack(ep):
            out = (ep.hists, ep.ledger) if telemetry else ()
            if provenance:
                out = out + (ep.prov,)
            return out + (ep.slo,) if slo else out
        # returns (state, count[m], guards[m], resv_decisions[m],
        # slot[m,k], length[m,k], metrics): the phase split reduces ON
        # DEVICE so per-round readbacks stay O(m) scalars; slot/length
        # are fetched only by the untimed calibration rounds (unfetched
        # device arrays cost nothing).
        if calendar_steps:
            # sortless calendar batches: per-client counts come back
            # directly ([N] served vector doubles as the calibration
            # feed; lens column unused).  calendar_impl="bucketed"
            # fuses ladder_levels refreshed-boundary commits per batch
            # (one launch = what took L minstop batches).
            ep = scan_calendar_epoch(st, now, m, steps=calendar_steps,
                                     anticipation_ns=0,
                                     with_metrics=with_metrics,
                                     calendar_impl=calendar_impl,
                                     ladder_levels=ladder_levels,
                                     wheel_kernel=wheel_kernel,
                                     hists=th, ledger=tl, slo=ts,
                                    prov=tp)
            return (ep.state, ep.count, ep.progress_ok,
                    ep.resv_count, ep.served,
                    jnp.ones_like(ep.served),
                    obsdev.metrics_combine(ep.metrics, drop_met),
                    tele_pack(ep))
        if chain_depth > 1:
            ep = scan_chain_epoch(st, now, m, k,
                                  chain_depth=chain_depth,
                                  anticipation_ns=0,
                                  with_metrics=with_metrics,
                                  select_impl=select_impl,
                                  hists=th, ledger=tl, slo=ts,
                                    prov=tp)
            units = ep.slot >= 0
            lens = ep.length.astype(jnp.int32)
            # a unit's entry serve is weight-phase iff class >= 1;
            # its induced serves are all constraint-phase
            resv = jnp.sum(jnp.where(units,
                                     lens - (ep.cls >= 1), 0),
                           axis=1).astype(jnp.int32)
        else:
            ep = scan_prefix_epoch(st, now, m, k, anticipation_ns=0,
                                   with_metrics=with_metrics,
                                   select_impl=select_impl,
                                   hists=th, ledger=tl, slo=ts,
                                    prov=tp)
            srv_pos = ep.slot >= 0
            resv = jnp.sum(srv_pos & (ep.phase == 0),
                           axis=1).astype(jnp.int32)
            lens = srv_pos.astype(jnp.int32)
        return (ep.state, ep.count, ep.guards_ok, resv, ep.slot, lens,
                obsdev.metrics_combine(ep.metrics, drop_met),
                tele_pack(ep))

    # AOT lower+compile with a zero-arrivals sample (same avals as the
    # real draws, and the Poisson stream stays byte-identical to prior
    # sessions): one compilation serves the whole bench and carries the
    # per-epoch cost_analysis attribution
    # the telemetry accumulators are donated alongside the state: they
    # are pure carried state, and an un-donated [N, 5] ledger would
    # pay a fresh HBM allocation every round
    run = _cplane.aot_record(
        "bench.round",
        (n, k, m, ring, cap_engine, select_impl, calendar_impl,
         calendar_steps, ladder_levels, wheel_kernel, chain_depth,
         telemetry, slo, with_metrics),
        jax.jit(round_fn, donate_argnums=(0, 3)),
        state, jnp.zeros((n,), jnp.int32), jnp.int64(0), tele)
    # NOT named `cost`: round_fn closes over the per-client cost
    # vector of that name, and the stream chunk re-traces round_fn
    # lazily -- shadowing it with this dict would poison the trace
    cost_attr = epoch_cost_analysis(run)
    rng = np.random.default_rng(11)

    assert engine_loop in ("round", "stream"), engine_loop
    stream_on = engine_loop == "stream"
    stream_chunk = max(int(stream_chunk), 1)
    _chunk_jits: dict = {}

    def chunk_run(c: int):
        """One device launch covering ``c`` rounds: a ``lax.scan``
        over the IDENTICAL round body (same integer ops in the same
        order -- decisions bit-identical to the round loop, gated in
        ci.sh), state + telemetry donated as carried HBM state,
        per-round count/guards/resv/metrics stacking in HBM as scan
        outputs and drained once per chunk.  AOT lower+compile (the
        round program's discipline): a lazy first-call compile would
        land inside the first timed chain and read as launch cost."""
        if c not in _chunk_jits:
            from jax import lax

            def chunk_fn(st, counts_c, t0, tele):
                def body(carry, xs):
                    st, tele = carry
                    counts, i = xs
                    out = round_fn(st, counts, t0 + i * dt_round_ns,
                                   tele)
                    return (out[0], out[7]), (out[1], out[2], out[3],
                                              out[6])

                (st, tele), outs = lax.scan(
                    body, (st, tele),
                    (counts_c, jnp.arange(c, dtype=jnp.int64)))
                return st, outs, tele

            _chunk_jits[c] = _cplane.aot_record(
                "bench.chunk",
                (n, k, m, ring, cap_engine, select_impl,
                 calendar_impl, calendar_steps, wheel_kernel,
                 telemetry, slo, with_metrics, c),
                jax.jit(chunk_fn, donate_argnums=(0, 3)),
                state, jnp.zeros((c, n), jnp.int32), jnp.int64(0),
                tele)
        return _chunk_jits[c]

    def draw():
        return jnp.asarray(
            np.minimum(rng.poisson(lam), waves).astype(np.int32))

    # warm/compile, then calibration (untimed): iterate toward the
    # self-consistent sustained equilibrium.  Each iteration measures
    # per-client service over two rounds and sets arrival rates to the
    # measured shares (arrivals == service, so the loop neither drains
    # nor hits the admission clamp).  Two adaptive corrections on top:
    #
    #  - load probing: if the queues drained (engine idle part of the
    #    round), the measured service is ARRIVAL-limited, not the
    #    engine's capacity -- scale lambda up and re-measure until the
    #    backlog holds, so the reported rate is engine-limited;
    #  - constraint-share targeting (``target_resv_share`` > 0): the
    #    share of constraint-phase decisions is an emergent property
    #    of resv_rate vs throughput, so a faster engine needs a
    #    proportionally larger reservation floor to stay at the same
    #    phase mix.  The damped multiplicative update converges in a
    #    few iterations; the measured share is reported.
    with obsspans.span(tracer, "bench.round", "dispatch"):
        state, _, _, _, _, _, _, tele = run(state, draw(),
                                            jnp.int64(0), tele)
    with obsspans.span(tracer, "bench.digest_sync", "device_compute"):
        jax.device_get(state_digest(state))
    t_base = dt_round_ns
    cal_iters = 5 if (calendar_steps or target_resv_share) else 1
    from dmclock_tpu.core.timebase import rate_to_inv_ns
    for _it in range(cal_iters):
        served = np.zeros(n, dtype=np.int64)
        resv_total = 0
        cal_rounds = 2
        for _ in range(cal_rounds):
            with obsspans.span(tracer, "bench.round", "dispatch"):
                state, cnt_, _, resv_, slot, lens, _, tele = run(
                    state, draw(), jnp.int64(t_base), tele)
            t_base += dt_round_ns
            resv_total += int(jax.device_get(resv_).sum())
            if calendar_steps:
                served += jax.device_get(slot).astype(np.int64)
            else:
                slots = jax.device_get(slot).ravel()
                cnt = jax.device_get(lens).ravel()
                ok = slots >= 0
                np.add.at(served, slots[ok], cnt[ok])
        total = int(served.sum())
        lam = np.minimum(served / cal_rounds, waves - 1.0)
        depth_mean = float(np.asarray(state.depth).mean())
        if depth_mean < 0.75 * depth0 and _it < cal_iters - 1:
            # arrival-limited: probe a higher load (clamped by waves)
            lam = np.minimum(np.maximum(lam * 1.4, lam + 0.5),
                             waves - 1.0)
        elif depth_mean > 1.5 * depth0 and _it < cal_iters - 1:
            # overloaded: back off before arrears outgrow the serve
            # budget (the calendar step cap) and the backlog spirals.
            # Guarded like the probe branch: legacy single-iteration
            # configs must keep their recorded arrivals==service
            # calibration untouched (bench_guard compares history)
            lam = lam * 0.85
        if target_resv_share and total:
            share = resv_total / max(total, 1)
            adj = float(np.clip((target_resv_share
                                 / max(share, 1e-3)) ** 0.6,
                                0.33, 3.0))
            resv_rates = resv_rates * adj
            # vectorized rate -> inverse (rate_to_inv_ns per element
            # costs seconds at n=100k x 5 iterations); same rounding
            # and sentinels as timebase.rate_to_inv_ns
            from dmclock_tpu.core.timebase import MAX_INV_NS, NS_PER_SEC
            with np.errstate(divide="ignore"):
                rinv = np.where(
                    resv_rates <= 0, 0,
                    np.minimum(np.rint(NS_PER_SEC
                                       / np.maximum(resv_rates, 1e-12)),
                               MAX_INV_NS)).astype(np.int64)
            state = state._replace(resv_inv=jnp.asarray(rinv))

    # pregenerate + upload every round's Poisson draws BEFORE timing:
    # the host RNG and the tunnel upload are the load GENERATOR, not
    # the scheduler (the reference's ns/call numbers likewise exclude
    # its client threads' own work); the on-device ingest of those
    # arrivals stays inside the timed region.
    #
    # DIFFERENCED chains (see bench_serve_only): a short chain of
    # ``rounds_lo`` and a long one of ``rounds`` each pay one dispatch
    # ramp + one sync; the difference cancels fixed overhead.  One
    # pair still carries tunnel jitter of the chains' own order
    # (single-pair cfg3 rates spread 21-55M run to run), so ``reps``
    # pairs run back to back in the steady state and the MEDIAN rate
    # is reported.  With rounds_lo=0 a single lat-corrected chain is
    # used instead (cheap smoke runs).
    rlo = max(rounds_lo, 0)
    n_pre = reps * (rlo + rounds) if rlo else rounds
    with obsspans.span(tracer, "bench.pregen_arrivals", "host_prep"):
        pre = [draw() for _ in range(n_pre)]
        jax.block_until_ready(pre)
        # stream mode uploads each chunk's draws as one [c, N] block;
        # stacking is load-generator work, pre-paid like the draws --
        # and the per-round list is then DEAD on the stream path, so
        # drop it rather than carry a second full copy of the draws
        # (83 MB at the cfg4 shape) through the timed chains
        pre_all = None
        if stream_on:
            pre_all = jax.block_until_ready(jnp.stack(pre))
            pre = None
    if stream_on:
        # AOT-compile every chunk length the timed chains will use,
        # BEFORE the timing window opens (chain lengths split into
        # stream_chunk-sized launches plus one remainder each)
        lens = set()
        for L in ((rlo, rounds) if rlo else (rounds,)):
            if L >= stream_chunk:
                lens.add(stream_chunk)
            if L % stream_chunk:
                lens.add(L % stream_chunk)
        for c in sorted(lens):
            chunk_run(c)

    met_acc = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
    if slo:
        # calibration rescaled the reservation floors on device:
        # re-register every contract from the device-truth inverse
        # arrays (the supervisor's register_from_inv discipline), so
        # the timed windows judge delivered-vs-ENFORCED contract
        slo_plane.register_from_inv(state.resv_inv, state.weight_inv,
                                    state.limit_inv)
    # calibration's warm-up serves pollute the distribution: reset the
    # telemetry accumulators so the reported percentiles cover the
    # measured steady state only (the provenance watermark re-arms at
    # the current virtual time)
    tele = tele_zero(int(t_base))
    # span window opens HERE: the summary covers the timed chains
    # only (calibration spans stay in the timeline but out of the
    # dispatch-tax decomposition)
    span_win = _span_window(tracer)
    chain_walls = []
    chain_launches = [0]
    slo_round0 = [0]

    def chain(idx):
        nonlocal state, t_base, met_acc, tele
        idx = list(idx)
        n_rounds = len(idx)
        t0 = time.perf_counter()
        counts_out, resv_out, guards, mets = [], [], [], []
        launches = 0
        if stream_on:
            # one launch per stream chunk of rounds; idx is always a
            # contiguous range here, so the pre-stacked draw block
            # slices straight onto the device
            pos = 0
            while pos < len(idx):
                c = min(stream_chunk, len(idx) - pos)
                i0 = idx[pos]
                with obsspans.span(tracer, "bench.chunk", "dispatch",
                                   rounds=c):
                    state, outs, tele = chunk_run(c)(
                        state, pre_all[i0:i0 + c],
                        jnp.int64(t_base), tele)
                    counts_out.append(outs[0])
                    guards.append(outs[1])
                    resv_out.append(outs[2])
                    mets.append(outs[3])
                t_base += c * dt_round_ns
                launches += 1
                pos += c
        else:
            for i in idx:
                with obsspans.span(tracer, "bench.round", "dispatch"):
                    state, cnt, g, resv, _, _, met_, tele = run(
                        state, pre[i], jnp.int64(t_base), tele)
                    counts_out.append(cnt)
                    resv_out.append(resv)
                    guards.append(g)
                    mets.append(met_)
                t_base += dt_round_ns
                launches += 1
        with obsspans.span(tracer, "bench.digest_sync",
                           "device_compute"):
            jax.device_get(state_digest(state))
        wall = time.perf_counter() - t0
        chain_walls.append(wall)
        chain_launches[0] += launches
        assert all(bool(jax.device_get(g).all()) for g in guards), \
            "rebase guards tripped -- counts are not trustworthy"
        # ravel: stream chunks stack per-round rows on a leading axis
        cnts = np.concatenate([np.asarray(jax.device_get(c)).ravel()
                               for c in counts_out])
        rs = np.concatenate([np.asarray(jax.device_get(r)).ravel()
                             for r in resv_out])
        # metrics ride the same round outputs, fetched untimed
        met_rows = [row for mv in mets
                    for row in np.atleast_2d(np.asarray(
                        jax.device_get(mv), dtype=np.int64))]
        met_acc = obsdev_np_combine(met_acc, *met_rows)
        if slo:
            # one timed chain = one conformance window: roll the block
            # UNTIMED (wall is already banked above), judge it, and
            # re-arm a fresh stamped block as the next chain's carry
            fresh, closed = slo_plane.roll(
                tele[-1], slo_round0[0], slo_round0[0] + n_rounds,
                skip_idle=True)
            slo_round0[0] += n_rounds
            slo_eval.observe_roll(closed)
            tele = tele[:-1] + (fresh,)
        return int(cnts.sum()), wall, cnts, rs

    if rlo:
        lat = scalar_latency()
        rates, all_cnts, all_rs, total = [], [], [], 0
        pos = 0
        for _ in range(max(reps, 1)):
            d_lo, t_lo, cnts_lo, rs_lo = chain(range(pos, pos + rlo))
            d_hi, t_hi, cnts_hi, rs_hi = chain(
                range(pos + rlo, pos + rlo + rounds))
            pos += rlo + rounds
            total += d_lo + d_hi
            all_cnts += [cnts_lo, cnts_hi]
            all_rs += [rs_lo, rs_hi]
            if t_hi <= t_lo or t_lo < 1.2 * lat:
                # jitter-inverted, or the lo chain sat at the tunnel
                # RTT floor (wall = max(device, RTT)): the difference
                # would divide by a truncated delta
                continue
            rates.append((d_hi - d_lo) / (t_hi - t_lo))
        assert rates, \
            "no valid pair: chains too short for the tunnel RTT floor"
        dps = float(np.median(rates))
        cnts = np.concatenate(all_cnts)
        rs = np.concatenate(all_rs)
        denom = n_pre * m * _per_pass_cap(n, k, calendar_steps,
                                          calendar_impl, ladder_levels)
    else:
        lat = scalar_latency()
        d_hi, t_hi, cnts, rs = chain(range(rounds))
        dps = d_hi / (t_hi - lat)
        total = d_hi
        denom = rounds * m * _per_pass_cap(n, k, calendar_steps,
                                           calendar_impl, ladder_levels)

    resv_frac = float(rs.sum()) / max(cnts.sum(), 1)
    mean_depth = float(np.asarray(state.depth).mean())
    out = {"dps": dps, "decisions": total,
           "fill": total / denom,
           "resv_phase_frac": resv_frac,
           "mean_depth": mean_depth,
           "select_impl": select_impl,
           "engine_loop": engine_loop,
           # part of the bench_guard series identity: a
           # provenance-off session's rates must never enter (or be
           # judged against) provenance-on medians
           "provenance_on": bool(provenance),
           "cost_analysis": cost_attr}
    # launches-per-decision is the streaming loop's acceptance
    # currency (ROADMAP #1): decisions_per_launch counts the TIMED
    # chains' device launches only, so round vs stream compare the
    # same measured region
    out["decisions_per_launch"] = total / max(chain_launches[0], 1)
    if stream_on:
        out["stream_chunk"] = stream_chunk
    sp = _span_summary(tracer, span_win, sum(chain_walls),
                       chain_launches[0])
    if sp is not None:
        out["spans"] = sp
        # scalars ride the history record as their own bench_guard
        # series (a dispatch-tax regression is a structural
        # regression even when dec/s holds)
        out["dispatch_ms_per_launch"] = sp["dispatch_ms_per_launch"]
        out["host_overhead_frac"] = sp["host_overhead_frac"]
        # per-decision amortized dispatch: what one decision pays in
        # dispatch tax when a single launch covers a whole stream
        # chunk (docs/OBSERVABILITY.md)
        sp["decisions_per_launch"] = out["decisions_per_launch"]
        out["dispatch_ns_per_decision"] = sp["dispatch_ns_per_decision"] = \
            sp["dispatch_ms_per_launch"] * 1e6 \
            / max(out["decisions_per_launch"], 1e-9)
    if calendar_steps:
        # decisions per device launch (pass = one calendar batch):
        # the bucketed-vs-minstop acceptance currency -- the ladder's
        # whole point is committing more per pass on skewed stops
        n_passes = n_pre * m
        out["calendar_impl"] = calendar_impl
        out["decisions_per_pass"] = total / max(n_passes, 1)
        if calendar_impl in ("bucketed", "wheel"):
            out["ladder_levels"] = ladder_levels
        if calendar_impl == "wheel":
            # the kernel tag: requested + what actually ran (the
            # Pallas path falls back to the XLA reference off-TPU or
            # past the padded-lane budget, counted per batch in the
            # wheel_pallas_fallbacks metric row)
            from dmclock_tpu.engine.fastpath import _wheel_resolve
            out["wheel_kernel"] = wheel_kernel
            _, fb = _wheel_resolve(wheel_kernel, n)
            out["wheel_kernel_effective"] = \
                "xla" if fb else wheel_kernel
    if with_metrics:
        md = obsdev.metrics_dict(met_acc)
        out["device_metrics"] = md
        # what bounded this run (the ROADMAP limit-stall item): the
        # device counters separate the cases a bare rate cannot --
        #  - limit_stalls > 0: batches committed NOTHING while work sat
        #    queued (every head capped by its limit/reservation tag):
        #    the SCHEDULER stalled;
        #  - drained queues with zero admission drops: arrivals (the
        #    waves cap / lambda calibration) bounded the decisions --
        #    the LOAD GENERATOR capped the run, the engine had slack;
        #  - otherwise the backlog held and the engine's own
        #    throughput is the binding constraint (drops > 0 means the
        #    generator pushed past ring headroom -- engine-bound too).
        stalls = md.get("limit_stalls", 0)
        drops = md.get("ingest_drops", 0)
        if stalls:
            out["bounded_by"] = "scheduler_stalled"
        elif mean_depth < 0.75 * depth0 and not drops:
            out["bounded_by"] = "load_generator_capped"
        else:
            out["bounded_by"] = "engine_throughput"

    if conformance_rounds:
        # end-of-run per-client QoS conformance: a few extra UNTIMED
        # rounds fetch the per-client served counts (the calendar
        # served vector, or slot/length scatter otherwise), and the
        # delivered per-client rate is judged against the reservation
        # floor and the weight share of the surplus -- the sim
        # harness's table (SimReport.conformance), at bench scale
        served_c = np.zeros(n, dtype=np.int64)
        for _ in range(conformance_rounds):
            with obsspans.span(tracer, "bench.round", "dispatch"):
                state, _c, _g, _r, slot, lens, _m, tele = run(
                    state, draw(), jnp.int64(t_base), tele)
            t_base += dt_round_ns
            if calendar_steps:
                served_c += jax.device_get(slot).astype(np.int64)
            else:
                slots = jax.device_get(slot).ravel()
                ln = jax.device_get(lens).ravel()
                ok = slots >= 0
                np.add.at(served_c, slots[ok], ln[ok])
        window_s = conformance_rounds * dt_round_ns / 1e9
        rate_c = served_c / window_s
        total_rate = rate_c.sum()
        has_resv = resv_rates > 0
        resv_met = rate_c >= 0.95 * resv_rates
        surplus = max(total_rate - float(resv_rates.sum()), 0.0)
        w_share = np.where(weights.sum() > 0,
                           weights / max(weights.sum(), 1e-12), 0.0)
        expect = resv_rates + surplus * w_share
        has_w = weights > 0
        share_err = np.abs(rate_c - expect) / np.maximum(expect, 1e-9)
        out["conformance"] = {
            "window_s": window_s,
            "clients": int(n),
            "resv_clients": int(has_resv.sum()),
            "resv_met_frac": float(resv_met[has_resv].mean())
            if has_resv.any() else 1.0,
            "share_err_mean": float(share_err[has_w].mean())
            if has_w.any() else 0.0,
            "delivered_rate_total": float(total_rate),
        }
        if conformance_out:
            # telemetry must never eat the measurement: a bad path
            # here would crash AFTER the full run and lose the JSON
            # line main()'s emit() guarantees
            try:
                with open(conformance_out, "w") as fh:
                    for i in range(n):
                        fh.write(json.dumps({
                            "client": i,
                            "reservation": float(resv_rates[i]),
                            "weight": float(weights[i]),
                            "ops": int(served_c[i]),
                            "rate": float(rate_c[i]),
                            "expected_rate": float(expect[i]),
                            "resv_met": bool(resv_met[i])
                            if has_resv[i] else True,
                        }) + "\n")
            except OSError as e:
                print(f"# conformance-out write failed: {e}",
                      file=__import__("sys").stderr)

    if latency_rounds:
        # MEASURED per-round latency percentiles.  A decision's latency
        # is bounded by the wall time of the round it rides in.  A
        # window of W rounds stays in flight; device_get on round i's
        # commit counts returns when round i completes, so successive
        # return times sample each round's true completion interval
        # while the full pipeline hides the tunnel round-trip
        # (W * round_time >> RTT).  Only intervals recorded while the
        # window was full count -- the drain tail would measure RTT,
        # not device work.
        from collections import deque

        from profile_util import scalar_latency

        # window size: enough rounds in flight that the ~110ms tunnel
        # round-trip of each device_get is hidden by device progress
        # (w * round_time > ~2x RTT); otherwise the marks would sample
        # the RTT, not the rounds
        lat_rt = scalar_latency()
        # device-side seconds per round, from the differenced median
        round_est = (total / max(n_pre, 1)) / max(dps, 1.0)
        w = max(4, int(np.ceil(2.0 * lat_rt / max(round_est, 1e-4))))
        w = min(w, max(latency_rounds // 4, 4))
        n_rounds = latency_rounds + w
        pre2 = [draw() for _ in range(n_rounds)]
        jax.block_until_ready(pre2)
        pending: deque = deque()
        marks = []
        for i in range(n_rounds):
            with obsspans.span(tracer, "bench.round", "dispatch"):
                state, cnt, _, _, _, _, _, tele = run(
                    state, pre2[i], jnp.int64(t_base), tele)
            t_base += dt_round_ns
            pending.append(cnt)
            if len(pending) >= w:
                jax.device_get(pending.popleft())
                marks.append(time.perf_counter())
        while pending:                   # drain untimed
            jax.device_get(pending.popleft())
        samples_ms = np.diff(np.asarray(marks)) * 1e3
        out["latency_samples"] = int(samples_ms.size)
        out["latency_window"] = w
        # MEASURED percentiles of per-round completion intervals.
        # Through this tunnel every device_get pays ~110ms wall
        # regardless of readiness, so when the true round time is
        # below that, the samples floor at the RTT: the percentiles
        # are honest tunnel-inclusive UPPER BOUNDS on round latency.
        # round_ms_mean is the differenced-chain device-side mean --
        # the true per-round cost a tunnel-free runtime would see.
        out["round_ms_p50"] = float(np.percentile(samples_ms, 50))
        out["round_ms_p99"] = float(np.percentile(samples_ms, 99))
        out["round_ms_mean"] = round_est * 1e3

    if slo:
        # the windowed-conformance verdict of the timed chains: a
        # chain-per-window series judged by the burn-rate evaluator
        # (docs/OBSERVABILITY.md "SLO plane")
        _slo_result_block(out, slo_eval)

    if telemetry:
        # ONE untimed fetch of the device accumulators (steady-state
        # rounds only; calibration was excluded by the reset above).
        # p50/p90/p99 come from the log2 reservation-tardiness
        # histogram (upper-bound-of-bucket, so never under-reported);
        # max/mean cross the per-client ledger -- the device-truth
        # replacement for the sims' host-side recomputation.
        h_np = np.asarray(jax.device_get(tele[0]), dtype=np.int64)
        led_np = np.asarray(jax.device_get(tele[1]), dtype=np.int64)
        lt = obshist.ledger_totals(led_np)
        for q, key in ((0.50, "tardiness_p50_ns"),
                       (0.90, "tardiness_p90_ns"),
                       (0.99, "tardiness_p99_ns")):
            out[key] = obshist.hist_percentile(
                h_np, obshist.HIST_RESV_TARDINESS, q)
        out["tardiness_mean_ns"] = obshist.hist_mean(
            h_np, obshist.HIST_RESV_TARDINESS)
        out["tardiness_max_ns"] = float(lt["tardiness_max_ns"])
        out["telemetry"] = {"histograms": obshist.hist_dict(h_np),
                            "ledger_totals": lt}
        out["_hist_block"] = h_np.tolist()   # registry feed; stripped
        #                                      by main before emit
    if provenance:
        # ONE untimed fetch of the provenance block (the telemetry
        # drain discipline): margin percentiles from the on-device
        # log2 histogram, the limit-gate share, the starvation
        # watermark -- the "why" scalars next to the "what" ones
        prov_f = tele[2 if telemetry else 0]
        pd = obsprov.prov_dict(prov_f)
        out["provenance"] = pd
        out["margin_p50_ns"] = pd["margin_p50_ns"]
        out["margin_p99_ns"] = pd["margin_p99_ns"]
        out["starvation_max_ns"] = pd["starvation_max_ns"]
        out["limit_gate_share"] = round(pd["limit_gate_share"], 4)
        # once-per-episode client_starved warnings through the PR-7
        # watchdog external-warning hook (or stderr): a backlogged
        # client unserved for > 8 rounds of virtual time at the end
        # of the measured region is starving RIGHT NOW
        mon = obsprov.StarvationMonitor(8 * dt_round_ns,
                                        watchdog=watchdog)
        mon.observe(prov_f, int(t_base), backlog=state.depth)
        if mon.fired:
            out["starved_clients"] = mon.fired[:8]
    _capacity_row(out, cap_cfg, cp0)
    return out


def bench_frontier(points=((2, 64), (3, 64), (6, 64), (12, 64)), *,
                   n: int = 100_000, dt_round_ns: int = 50_000_000,
                   target_latency_ms: float = 0.0):
    """Throughput/latency frontier for the cfg4 calendar workload.

    A decision's latency is bounded by the round it rides in, and the
    round's device time scales with its batch count m -- so sweeping m
    at fixed per-batch depth traces the frontier.  Each point reports
    the differenced-chain dec/s, the device-side mean round time, and
    windowed per-round completion-interval percentiles (device-bound
    once W rounds in flight amortize the ~110ms tunnel round-trip; the
    floor of the method is RTT/W per interval).

    With ``target_latency_ms`` the sweep instead returns the
    highest-throughput point whose device-side mean round time fits
    the budget (the --target-latency mode).
    """
    rows = []
    for m, steps in points:
        r = bench_sustained(
            n, 0, m, 24, zipf=True, resv_rate=1200.0,
            dt_round_ns=dt_round_ns, waves=64, rounds_lo=8,
            latency_rounds=60, calendar_steps=steps,
            target_resv_share=0.5, reps=2)
        rows.append({"m": m, "steps": steps,
                     "dps": r["dps"],
                     "round_ms_mean": r.get("round_ms_mean", 0.0),
                     "round_ms_p50": r.get("round_ms_p50", 0.0),
                     "round_ms_p99": r.get("round_ms_p99", 0.0),
                     "resv_phase_frac": r["resv_phase_frac"],
                     "decisions": r["decisions"]})
        import sys
        print(f"# frontier m={m} steps={steps}: "
              f"{r['dps']/1e6:.1f}M dec/s, round mean "
              f"{r.get('round_ms_mean', 0):.1f}ms, interval p99 "
              f"{r.get('round_ms_p99', 0):.1f}ms", file=sys.stderr)
    if target_latency_ms:
        # an operating point only counts if it holds the workload's
        # defining 0.50 constraint share (+-0.1): a resv-saturated or
        # off-mix point's throughput is a different workload's number
        fits = [x for x in rows
                if x["round_ms_mean"] <= target_latency_ms
                and abs(x["resv_phase_frac"] - 0.5) <= 0.1]
        pick = max(fits, key=lambda x: x["dps"]) if fits else \
            min((x for x in rows
                 if abs(x["resv_phase_frac"] - 0.5) <= 0.1),
                key=lambda x: x["round_ms_mean"], default=rows[0])
        pick = dict(pick)
        pick["met_budget"] = bool(fits)
        return pick, rows
    return None, rows


def bench_churn(scenario: str = "flash_crowd", *,
                total_ids: int = 4096, epochs: int = 64,
                every: int = 4, engine: str = "prefix", m: int = 4,
                k: int = 256, ring: int = 32, waves: int = 8,
                base_lam: float = 2.0, dt_epoch_ns: int = 50_000_000,
                seed: int = 11, boost_client: int = None,
                boost_factor: float = 8.0, slo: bool = False,
                tracer=None) -> dict:
    """Open-population churn workload (docs/LIFECYCLE.md): the
    lifecycle plane drives a ``lifecycle.churn`` scenario -- flash
    crowds arriving and departing, idle eviction recycling slots,
    grow-on-demand capacity, periodic compaction -- over a sustained
    ingest+serve epoch loop, with the admin control API mounted on a
    live scrape endpoint.

    The control-plane acceptance demo rides in: at the halfway
    boundary the bench issues a REAL ``PUT /clients/{id}/qos`` over
    HTTP boosting ``boost_client``'s weight by ``boost_factor``; the
    per-client conformance table reports delivered throughput shares
    in the windows before and after, so the live update's effect is
    visible in the output (weight share up ~boost_factor among its
    weight class).  Population size is dynamic, so the row records
    peak/live client counts next to the rate (bench_guard keys the
    series by scenario + total_ids)."""
    import urllib.request

    from dmclock_tpu.engine import stream as stream_mod
    from dmclock_tpu.engine.state import init_state
    from dmclock_tpu.lifecycle import churn as churn_mod
    from dmclock_tpu.lifecycle import make_spec
    from dmclock_tpu.lifecycle.api import mount_admin_api
    from dmclock_tpu.lifecycle.plane import LifecyclePlane
    from dmclock_tpu.obs import histograms as obshist
    from dmclock_tpu.obs.registry import (MetricsHTTPServer,
                                          MetricsRegistry)
    from dmclock_tpu.robust.guarded import run_epoch_guarded

    spec = make_spec(scenario, total_ids=total_ids, seed=seed,
                     base_lam=base_lam, compact_every=2)
    from dmclock_tpu.obs import compile_plane as _cplane

    cp0 = _cplane.plane().totals()
    plane = LifecyclePlane(spec, tracer=tracer)
    state = init_state(spec["capacity0"], ring)
    hists = obshist.hist_zero()
    ledger = obshist.ledger_zero(spec["capacity0"])
    # the SLO plane rides the churn loop exactly as in the supervisor:
    # window rolls on the lifecycle boundary grid, contract epochs
    # bumped by the plane's REGISTER/UPDATE/EVICT -- the live-PUT demo
    # below lands in a FRESH contract epoch's windows (no smearing)
    slo_block = slo_plane = slo_eval = None
    slo_w0 = 0
    if slo:
        from dmclock_tpu.obs import slo as obsslo
        from dmclock_tpu.obs.alerts import SloEvaluator
        slo_plane = obsslo.SloPlane(spec["capacity0"],
                                    dt_epoch_ns=dt_epoch_ns,
                                    ring_depth=max(epochs // every, 8))
        slo_eval = SloEvaluator(slo_plane, log=lambda _line: None)
        slo_block = obsslo.window_zero(spec["capacity0"])
        plane.attach_slo(slo_plane)
    ingest = stream_mod.jit_ingest_step(dt_epoch_ns=dt_epoch_ns,
                                        waves=waves)
    rng = np.random.Generator(np.random.PCG64(seed))
    boost_at = max((epochs // 2 // every) * every, every)

    def ops_by_cid(led) -> np.ndarray:
        """Cumulative delivered ops per CLIENT ID (the ledger is
        per-slot; evicted clients are out of scope for the shares)."""
        col = np.asarray(jax.device_get(led))[:, obshist.LED_OPS]
        return plane.slots.scatter_by_cid(col, total_ids)

    # ephemeral control endpoint for the live-PUT demo (fail-soft:
    # a refused bind downgrades to the in-process handler -- the
    # workload must not die on a busy box)
    server = None
    try:
        server = MetricsHTTPServer(MetricsRegistry(), port=0)
    except OSError:
        pass
    api = mount_admin_api(server, plane, slo=slo_plane) \
        if server is not None else None

    def live_put(cid: int, r: float, w: float, l: float,
                 apply_at: int) -> bool:
        body = json.dumps({"reservation": r, "weight": w, "limit": l,
                           "apply_at": apply_at}).encode()
        if server is not None:
            req = urllib.request.Request(
                f"http://{server.host}:{server.port}/clients/{cid}/qos",
                data=body, method="PUT")
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 202, resp.status
            return True
        plane.accept({"op": "update", "cid": cid, "r": r, "w": w,
                      "l": l, "apply_at": apply_at})
        return False

    decisions = 0
    ops_mid = None
    boosted = None
    t0 = time.perf_counter()
    try:
        for e in range(epochs):
            if e % every == 0:
                if slo_plane is not None and e > 0:
                    slo_block, closed = slo_plane.roll(
                        slo_block, slo_w0, e,
                        cid_of_slot=plane.slots.cid_of_slot,
                        depth=state.depth)
                    slo_w0 = e
                    slo_eval.observe_roll(closed)
                if e == boost_at:
                    if boost_client is None or \
                            boost_client not in plane.qos:
                        # lowest LIVE client id: churn scenarios may
                        # have evicted any fixed pick by now
                        boost_client = min(plane.slots.slot_of)
                    r0, w0, l0 = plane.qos[boost_client]
                    boosted = {"client": boost_client,
                               "weight_before": w0,
                               "weight_after": w0 * boost_factor,
                               "boundary": e,
                               "http": live_put(
                                   boost_client, r0,
                                   w0 * boost_factor, l0, e)}
                    ops_mid = ops_by_cid(ledger)
                with obsspans.span(tracer, "lifecycle.boundary",
                                   "host_prep", epoch=e):
                    if slo_block is not None:
                        state, ledger, slo_block = plane.boundary(
                            state, e, every, ledger=ledger,
                            slo_block=slo_block)
                    else:
                        state, ledger = plane.boundary(
                            state, e, every, ledger=ledger)
            t_base = e * dt_epoch_ns
            raw = rng.poisson(churn_mod.lam_vector(spec, e)) \
                .astype(np.int32)
            with obsspans.span(tracer, "bench.round", "dispatch"):
                state = ingest(state,
                               jnp.asarray(plane.map_counts(raw)),
                               jnp.int64(t_base))
                ep = run_epoch_guarded(
                    state, t_base + dt_epoch_ns, engine=engine, m=m,
                    k=k, with_metrics=True, hists=hists,
                    ledger=ledger, slo=slo_block, tracer=tracer)
            state, hists, ledger = ep.state, ep.hists, ep.ledger
            if slo_block is not None:
                slo_block = ep.slo
            decisions += ep.count
        jax.block_until_ready(state.depth)
        wall_s = time.perf_counter() - t0
        if slo_plane is not None:
            slo_block, closed = slo_plane.roll(
                slo_block, slo_w0, epochs,
                cid_of_slot=plane.slots.cid_of_slot,
                depth=state.depth)
            slo_eval.observe_roll(closed)
        ops_end = ops_by_cid(ledger)
    finally:
        if server is not None:
            server.close()

    # conformance: delivered throughput shares in the windows before
    # and after the live update, within the clients holding work both
    # windows -- the visible-effect gate for PUT /clients/{id}/qos.
    # A run too short to reach the boost boundary (epochs <= every)
    # skips the demo instead of crashing on the never-taken branch.
    conf = None
    if boosted is not None:
        before = ops_mid
        # clamp: a client evicted after the boost has its cumulative
        # row folded into the departed report and zeroed, so
        # end - mid can go negative for it; its after-window share is
        # simply zero
        after = np.maximum(ops_end - ops_mid, 0)
        sb, sa = max(before.sum(), 1), max(after.sum(), 1)
        bc = boost_client
        rows = sorted(set(range(min(6, total_ids))) | {bc})
        conf = [{"client": c,
                 "weight": plane.qos.get(c, (0.0, 0.0, 0.0))[1],
                 "ops_before": int(before[c]),
                 "ops_after": int(after[c]),
                 "share_before": float(before[c] / sb),
                 "share_after": float(after[c] / sa)} for c in rows]
        boosted["share_before"] = float(before[bc] / sb)
        boosted["share_after"] = float(after[bc] / sa)
        boosted["share_gain"] = boosted["share_after"] \
            / max(boosted["share_before"], 1e-12)

    snap = plane.snapshot()
    h_np = np.asarray(jax.device_get(hists), dtype=np.int64)
    out = {"dps": decisions / max(wall_s, 1e-9),
           "decisions": decisions, "wall_s": wall_s,
           "scenario": scenario, "engine": engine,
           "total_ids": total_ids, "epochs": epochs,
           "boundary_every": every,
           "peak_clients": snap["peak_clients"],
           "live_clients": snap["live_clients"],
           "capacity": snap["capacity"],
           "registrations": snap["registrations"],
           "evictions": snap["evictions"],
           "compactions": snap["compactions"],
           "qos_updates": snap["qos_updates"],
           "slot_recycles": snap["slot_recycles"],
           "grows": snap["grows"],
           "boost": boosted, "conformance": conf}
    for q, key in ((0.50, "tardiness_p50_ns"),
                   (0.90, "tardiness_p90_ns"),
                   (0.99, "tardiness_p99_ns")):
        out[key] = obshist.hist_percentile(
            h_np, obshist.HIST_RESV_TARDINESS, q)
    out["tardiness_mean_ns"] = obshist.hist_mean(
        h_np, obshist.HIST_RESV_TARDINESS)
    out["tardiness_max_ns"] = float(obshist.ledger_totals(
        np.asarray(jax.device_get(ledger),
                   dtype=np.int64))["tardiness_max_ns"])
    if slo_plane is not None:
        _slo_result_block(out, slo_eval)
        if boosted is not None:
            # the no-smearing demo: the boosted client's closed
            # windows report against their OWN contract versions --
            # the live PUT lands in a fresh contract epoch
            out["slo_boost_windows"] = [
                {"window": [w.e0, w.e1],
                 "contract_epoch": w.cepoch, "ops": w.ops}
                for w in slo_plane.ring_rows(boost_client)]
    out["_hist_block"] = h_np.tolist()
    # capacity record: the open population's projection is sized for
    # the full scripted id space landing at once (the conservative
    # per-shard planning number), lifecycle slot map included
    _capacity_row(out, dict(n=total_ids, ring=ring, engine=engine,
                            m=m, k=k, telemetry=True, slo=slo,
                            lifecycle=True), cp0)
    return out


def plan_mesh_shards(clients: int, n_shards=None, *,
                     ring: int = 16, engine: str = "prefix",
                     m: int = 4, k: int = 256,
                     telemetry: bool = True, slo: bool = True,
                     stream_chunk: int = 8) -> dict:
    """Shard planning for ``--mode mesh``: when ``--n-shards`` is not
    given, the count FALLS OUT of the client target by inverting the
    capacity plane's HBM ledger (``obs.capacity.plan_capacity`` over
    ``device_hbm_budget()`` -- the ROADMAP rule: never guessed on
    silicon).  Returns the plan record the JSON line carries:
    ``shards_planned`` (None when no budget is detectable, e.g. cpu),
    the effective shard count (capped at the attached device count),
    per-shard clients, and ``projected_hbm_bytes_per_shard``."""
    from dmclock_tpu.obs import capacity as obscap

    cap_cfg = dict(ring=ring, engine=engine, m=m, k=k,
                   telemetry=telemetry, slo=slo,
                   stream_chunk=stream_chunk)
    budget = obscap.device_hbm_budget()
    shards_planned = None
    max_per_shard = None
    if budget is not None:
        plan = obscap.plan_capacity(budget, **cap_cfg)
        max_per_shard = max(int(plan["max_clients"]), 1)
        shards_planned = max(1, -(-int(clients) // max_per_shard))
    n_dev = len(jax.devices())
    eff = int(n_shards) if n_shards else (shards_planned or n_dev)
    if eff > n_dev:
        print(f"# mesh: {eff} shards requested/planned but only "
              f"{n_dev} devices attached -- capping (force a host "
              "mesh with --xla_force_host_platform_device_count)",
              file=__import__("sys").stderr)
        eff = n_dev
    per_shard = -(-int(clients) // eff)
    plan = {
        "clients_total": int(clients),
        "n_shards": eff,
        "clients_per_shard": per_shard,
        "shards_planned": shards_planned,
        "max_clients_per_shard": max_per_shard,
        "hbm_budget_bytes": budget,
        "projected_hbm_bytes_per_shard":
            int(obscap.projected_hbm(per_shard, **cap_cfg)),
    }
    # the device cap can push the per-shard partition BACK over the
    # budget the planner just inverted (e.g. 9 shards planned, 8
    # devices attached): surface it so bench_mesh can apply the
    # PR-11 capacity-gate discipline (warn + skip, never OOM)
    if max_per_shard is not None and per_shard > max_per_shard:
        plan["over_budget"] = True
    return plan


def bench_mesh(clients: int = 100_000, *, n_shards=None,
               counter_sync_every: int = 1, engine: str = "prefix",
               epochs: int = 24, warmup_epochs: int = 8,
               chunk: int = 8, m: int = 4, k: int = 256,
               ring: int = 16, depth: int = 12,
               arrival_lam: float = 2.0, waves: int = 4,
               dt_epoch_ns: int = 10 ** 8,
               with_metrics: bool = True, slo: bool = True,
               tracer=None, fault_spec=None) -> dict:
    """The mesh serving plane's aggregate-throughput trajectory
    (docs/ENGINE.md "Mesh serving"; the MULTICHIP v2 record shape):
    S full per-device engines -- each one server owning a DISTINCT
    ``clients/S``-client partition with its own queue state and
    Poisson arrival stream, so ``clients`` total contracts live
    across the mesh -- advance whole chunks of fused ingest+serve
    epochs inside ONE shard_map launch per chunk, exchanging only the
    [clients/S]-sized delta/rho counter psum at epoch boundaries
    (views refresh on the ``counter_sync_every`` grid).  On CPU
    (forced host devices) this proves the SCALING SHAPE; the silicon
    campaign inherits it as the >=100M dec/s @ 1M clients one-command
    repro.

    ``fault_spec`` (a parsed ``robust.faults.parse_fault_spec``
    dict) turns the session into a CHAOS run: a deterministic
    FaultPlan over every (warmup + timed) epoch is compiled INTO the
    fused chunks, and the row records the plan tag plus the
    per-shard dropout/resync counts read off the device metric rows
    (cross-checked against the plan oracle by the CI mesh chaos
    smoke).  Chaos rows never enter bench_guard's clean-run
    medians."""
    import dataclasses

    from dmclock_tpu.obs import device as obsdev
    from dmclock_tpu.obs import slo as obsslo
    from dmclock_tpu.parallel import mesh as mesh_mod
    from dmclock_tpu.parallel import tracker as trk
    from dmclock_tpu.robust import faults as faults_mod
    from dmclock_tpu.robust.supervisor import EpochJob, _job_state

    plan = plan_mesh_shards(clients, n_shards, ring=ring,
                            engine=engine, m=m, k=k, slo=slo,
                            stream_chunk=chunk)
    S = plan["n_shards"]
    n = plan["clients_per_shard"]
    if plan.pop("over_budget", False):
        # the capacity-gate discipline (PR-11): a partition the
        # planner's own inversion says exceeds the per-device budget
        # is warned + skipped with a tagged row, never launched into
        # an OOM mid-session
        import sys as _sys

        print(f"# mesh: SKIPPED -- {n} clients/shard exceeds the "
              f"planned {plan['max_clients_per_shard']} for the "
              f"detected budget even at the device-capped {S} "
              "shards; lower --clients or attach more devices",
              file=_sys.stderr)
        return {"workload": "mesh", "engine": engine,
                "engine_loop": "mesh", "dps": 0.0, "decisions": 0,
                "capacity_skipped": True,
                "projected_hbm_bytes":
                    plan["projected_hbm_bytes_per_shard"],
                "counter_sync_every":
                    int(max(counter_sync_every, 1)),
                **{key: val for key, val in plan.items()
                   if val is not None}}
    job = EpochJob(engine=engine, engine_loop="mesh", n_shards=S,
                   counter_sync_every=counter_sync_every, n=n,
                   depth=depth, ring=ring, m=m, k=k,
                   arrival_lam=arrival_lam, waves=waves,
                   dt_epoch_ns=dt_epoch_ns)
    mesh = mesh_mod.make_mesh(S)
    state = mesh_mod.stack_shards(
        _job_state(dataclasses.replace(job, engine_loop="stream")),
        S, mesh)
    cd, cr, vd, vr = mesh_mod.counter_init(S, n)
    wblock = mesh_mod.stack_shards(obsslo.window_zero(n), S, mesh)
    warm_chunks = max(1, warmup_epochs // chunk)
    n_chunks = max(1, epochs // chunk)
    fplan = None
    if fault_spec is not None:
        # one deterministic plan over EVERY epoch the session runs
        # (warmup included: a chaos session is chaotic end to end)
        fplan = faults_mod.plan_from_spec(
            fault_spec, (warm_chunks + n_chunks) * chunk, S)
    # chunks launch at e0 = multiples of chunk, so chunk % K == 0
    # keeps every group head on the sync grid and the grouped
    # (collective-free non-sync epoch) program stays bit-identical
    every = int(max(counter_sync_every, 1))
    skipping = fplan is None and every > 1 and chunk % every == 0
    fn = mesh_mod.jit_mesh_chunk(
        mesh, engine=engine, epochs=chunk, m=m, k=k,
        dt_epoch_ns=dt_epoch_ns, waves=waves,
        with_metrics=with_metrics,
        counter_sync_every=counter_sync_every, ingest=True,
        with_faults=fplan is not None,
        collective_skipping=skipping)
    rng = np.random.Generator(np.random.PCG64(29))

    def draw(e):
        return jnp.asarray(np.swapaxes(np.stack(
            [rng.poisson(arrival_lam, (S, n)).astype(np.int32)
             for _ in range(e)]), 0, 1))

    fault_mets = []

    def fault_chunk(e0):
        # sliced + device-resident BEFORE any timed launch (see the
        # pregen discipline below): the timed loop must not pay
        # host-side mask slicing or H2D transfers per chunk
        if fplan is None:
            return None
        fc = faults_mod.plan_chunk(fplan, e0, e0 + chunk)
        return tuple(jnp.asarray(a) for a in fc)

    def launch(out, e0, counts, fc):
        with obsspans.span(tracer, "mesh.bench_chunk", "dispatch",
                           epoch0=e0, shards=S,
                           chaos=fplan is not None):
            out = fn(out.state, out.cd, out.cr, out.view_d,
                     out.view_r, jnp.int64(e0), counts,
                     None, None, out.slo, None, None, fc)
        if fplan is not None:
            # per-shard fault rows ride the per-epoch metric vectors;
            # fetched untimed after the run (async-safe append)
            fault_mets.append(out.outs["metrics"])
        return out

    # warmup (covers compile + tag-transient), untimed
    out = mesh_mod.MeshChunk(state=state, outs={}, cd=cd, cr=cr,
                             view_d=vd, view_r=vr, slo=wblock)
    e0 = 0
    for _ in range(warm_chunks):
        out = launch(out, e0, draw(chunk), fault_chunk(e0))
        e0 += chunk
    jax.block_until_ready(out.state)

    # timed window: ALL raw draws AND chaos mask slices pre-generated
    # (and device-resident) before the clock starts -- the
    # every-other-bench pregen discipline; host RNG/slicing time must
    # not serialize into the async chunk chain and bias the aggregate
    # dec/s the MULTICHIP record reads -- then chain chunks
    # asynchronously, one sync at the end
    pregen = [(draw(chunk), fault_chunk(e0 + i * chunk))
              for i in range(n_chunks)]
    jax.block_until_ready([p[0] for p in pregen])
    if fplan is not None:
        jax.block_until_ready([p[1] for p in pregen])
    timed = []
    t0 = time.perf_counter()
    for counts_c, fc in pregen:
        out = launch(out, e0, counts_c, fc)
        timed.append(out.outs["count"])
        e0 += chunk
    jax.block_until_ready(out.state)
    wall = time.perf_counter() - t0

    # exact decision counts, fetched untimed; [S, E, ...] per chunk
    per_shard = np.zeros(S, dtype=np.int64)
    for counts_arr in timed:
        a = np.asarray(jax.device_get(counts_arr))
        per_shard += a.reshape(S, -1).sum(axis=1)
    total = int(per_shard.sum())
    dps = total / wall
    shard_dps = per_shard / wall
    # the timed window starts at the post-warmup GLOBAL epoch: the
    # device sync grid is epoch % K == 0, so the sync count inside
    # the window depends on where it starts
    sched = trk.exchange_schedule(n_chunks * chunk,
                                  counter_sync_every,
                                  start=warm_chunks * chunk)
    bytes_per_sync = trk.counter_view_bytes(n)
    row = {
        "workload": "mesh",
        "engine": engine,
        "engine_loop": "mesh",
        "dps": dps,
        "dps_per_shard_mean": float(shard_dps.mean()),
        "dps_per_shard_min": float(shard_dps.min()),
        "dps_per_shard_max": float(shard_dps.max()),
        "dps_per_shard": [float(x) for x in shard_dps],
        "decisions": total,
        "wall_s": wall,
        "epochs": n_chunks * chunk,
        "stream_chunk": chunk,
        "counter_sync_every": int(max(counter_sync_every, 1)),
        "counter_syncs": sched["syncs"],
        "counter_bytes_per_sync": bytes_per_sync,
        "collective_skipping": bool(skipping),
        # what the compiled program EXECUTES: with collective
        # skipping the [C]-sized psum runs once per K-epoch sync
        # group (non-sync epochs are collective-free by program
        # structure), so the per-epoch wire cost is bytes/K; the
        # flat program (chaos, or K not dividing the chunk) still
        # pays it every epoch
        "counter_bytes_per_epoch":
            float(bytes_per_sync / every if skipping
                  else bytes_per_sync),
        # view-refresh bytes amortized over the sync grid -- the
        # window-aware figure (sync count depends on where the timed
        # window starts on the epoch % K grid)
        "counter_view_bytes_per_epoch":
            bytes_per_sync * sched["syncs"] / max(sched["epochs"], 1),
        **{key: val for key, val in plan.items() if val is not None},
    }
    # chaos accounting: the plan tag + per-shard dropout/resync
    # counts read off the DEVICE metric rows (every launched chunk,
    # warmup included, so the totals equal the plan_events oracle --
    # the CI mesh chaos smoke pins the equality).  Clean sessions
    # record fault_plan="none"; bench_guard keys both the record- and
    # the row-level exclusion on it.
    row["fault_plan"] = faults_mod.describe(fplan)
    if fplan is not None:
        mets = np.zeros((S, obsdev.NUM_METRICS), dtype=np.int64)
        for mchunk in fault_mets:
            a = np.asarray(jax.device_get(mchunk), dtype=np.int64)
            for s in range(S):
                mets[s] = obsdev.metrics_combine_np(mets[s], *a[s])
        row["fault_dropouts_per_shard"] = [
            int(x) for x in mets[:, obsdev.MET_SERVER_DROPOUTS]]
        row["fault_resyncs_per_shard"] = [
            int(x) for x in mets[:, obsdev.MET_TRACKER_RESYNCS]]
        row["faults_injected_total"] = int(
            mets[:, obsdev.MET_FAULTS_INJECTED].sum())
        try:
            from dmclock_tpu.obs import default_registry
            obsdev.publish_shard_faults(
                default_registry(), mets, labels={"workload": "mesh"})
        except Exception:
            pass
    # the cluster-wide conformance table (window_mesh_reduce merge)
    # rides the scrape registry with per-shard decomposition
    try:
        from dmclock_tpu.obs import default_registry
        obsslo.publish_shard_windows(
            default_registry(), np.asarray(jax.device_get(out.slo)),
            merged=np.asarray(jax.device_get(out.slo_merged)),
            workload="mesh")
    except Exception:
        pass
    return row


def bench_controller(scenarios=("shard_skew", "limit_thrash",
                                "diurnal"), *,
                     sides: str = "both", total_ids: int = 192,
                     epochs: int = 48, ckpt_every: int = 4,
                     engine: str = "prefix",
                     engine_loop: str = "stream", m: int = 2,
                     k: int = 32, ring: int = 16, waves: int = 6,
                     seed: int = 17, tracer=None) -> dict:
    """The closed-loop controller A/B (docs/CONTROLLER.md): each
    churn scenario runs as a pair of EXACT-TWIN supervised jobs --
    identical engine, arrival stream, lifecycle spec, and SLO plane,
    differing ONLY in ``EpochJob(controller=...)`` -- so the row's
    recovered dec/s and burn-episode-duration delta are attributable
    to the controller's actuations alone (controller=off is
    bit-identical to the bare runner by the PR-18 digest gate, so
    the off side doubles as the clean reference).

    Scenarios: ``shard_skew`` (hot-shard melt; admission clamp +
    ladder pressure), ``limit_thrash`` (alternating tight limits;
    limit-break burn drives the clamp rule), and the ``diurnal``
    autoscale variant (day/night load swings; the clean-streak
    up-rules walk the knobs back out at night).  ``sides`` picks
    which twins run: "off", "on", or "both" (recovered deltas need
    both).  Wall time includes compile -- both twins pay it, and the
    row records the actuation count so a recompile-heavy trajectory
    is visible; this is a control-plane demo row, not a throughput
    record (bench_guard excludes controller-actuated sessions from
    clean medians)."""
    import dataclasses

    from dmclock_tpu.lifecycle import make_spec
    from dmclock_tpu.robust.supervisor import EpochJob, run_job

    def one(job):
        t0 = time.perf_counter()
        res = run_job(job)
        return res, time.perf_counter() - t0

    out = {}
    for scenario in scenarios:
        spec = make_spec(scenario, total_ids=total_ids,
                         capacity0=max(16, total_ids // 4),
                         seed=seed)
        job = EpochJob(engine=engine, engine_loop=engine_loop,
                       churn=spec, epochs=epochs, m=m, k=k,
                       ring=ring, waves=waves,
                       ckpt_every=ckpt_every, seed=seed,
                       with_slo=True)
        row = {"workload": "controller", "scenario": scenario,
               "engine": engine, "engine_loop": engine_loop,
               "epochs": epochs, "ckpt_every": ckpt_every,
               "total_ids": total_ids, "controller": sides}
        with obsspans.span(tracer, "controller.bench_ab",
                           "dispatch", scenario=scenario,
                           sides=sides):
            if sides == "both":
                # untimed warmup: the twins share the process-level
                # jit cache, so whoever ran FIRST would otherwise pay
                # the whole compile and hand the other twin a free
                # ride -- warm the cache on the off-config once, then
                # time both (actuation-induced retraces still land on
                # the on twin's clock; that cost is real)
                run_job(job)
            if sides in ("off", "both"):
                off, wall = one(job)
                row.update(
                    dps_off=off.decisions / wall,
                    decisions_off=int(off.decisions),
                    wall_s_off=wall,
                    violations_off=int(
                        off.slo["violations_total"]),
                    burn_windows_off=int(
                        off.slo.get("burn_windows", 0)),
                    burn_epochs_off=int(
                        off.slo.get("burn_epochs", 0)))
                if sides == "off":
                    row["slo"] = off.slo
            if sides in ("on", "both"):
                on, wall = one(
                    dataclasses.replace(job, controller=True))
                traj = on.controller_trajectory or []
                row.update(
                    dps_on=on.decisions / wall,
                    decisions_on=int(on.decisions),
                    wall_s_on=wall,
                    violations_on=int(on.slo["violations_total"]),
                    burn_windows_on=int(
                        on.slo.get("burn_windows", 0)),
                    burn_epochs_on=int(
                        on.slo.get("burn_epochs", 0)),
                    controller_decisions=int(
                        on.controller_decisions),
                    controller_knobs=on.controller_knobs,
                    controller_trajectory=traj,
                    slo=on.slo)
        # the A/B verdicts: throughput recovered and burn duration
        # shed by closing the loop (positive = controller helped)
        if sides == "both":
            row["dps"] = row["dps_on"]
            row["recovered_dps"] = row["dps_on"] - row["dps_off"]
            row["burn_epochs_recovered"] = (row["burn_epochs_off"]
                                            - row["burn_epochs_on"])
            row["violations_recovered"] = (row["violations_off"]
                                           - row["violations_on"])
        else:
            row["dps"] = row.get("dps_on", row.get("dps_off", 0.0))
        out[f"controller_{scenario}"] = row
    return out


def bench_rpc(*, workers: int = 4, requests: int = 64, n: int = 32,
              epochs: int = 16, ckpt_every: int = 2, m: int = 2,
              k: int = 32, ring: int = 16, waves: int = 6,
              seed: int = 17, engine: str = "prefix",
              fault_spec=None, tracer=None) -> dict:
    """The RPC ingest front-end leg (docs/RPC.md): a real loopback
    :class:`net.server.IngestServer`, ``workers`` concurrent
    loadgen clients driving seeded deterministic schedules over real
    sockets, the serving loop admitting the coalesced superwaves
    through the existing device clamp -- then the acceptance gate
    in-process: a self-generated replay fed the journaled
    admitted-counts trace must land on the IDENTICAL chain digest
    (``digest_match``).  ``fault_spec`` runs the leg as seeded
    network chaos with exact drop/dup/reorder accounting against
    the host oracle (``chaos_exact``).  This is a serving-plane
    demo row, not a throughput record: wall time includes socket
    round-trips and the journal's fsyncs (that cost is the point)."""
    import dataclasses
    import tempfile
    import threading

    from dmclock_tpu.net import faults as net_faults
    from dmclock_tpu.net.journal import ArrivalJournal
    from dmclock_tpu.net.serve import (RpcServeConfig, make_server,
                                       run_serve, trace_sha)
    from scripts.loadgen import full_schedule, run_worker

    scheds = full_schedule(seed, workers=workers, requests=requests,
                           n_clients=n, max_nops=3)
    spec = net_faults.parse_net_fault_spec(fault_spec)
    oracle = net_faults.plan_schedule_events(
        spec, [[(c, s) for c, s, _ in sc] for sc in scheds])
    with tempfile.TemporaryDirectory() as d:
        cfg = RpcServeConfig(
            engine=engine, n=n, epochs=epochs, ckpt_every=ckpt_every,
            m=m, k=k, ring=ring, waves=waves, seed=seed, workdir=d,
            fault_spec=fault_spec, high_watermark=10 ** 6,
            wait_ops=1, wait_timeout_s=60)
        server = make_server(cfg).start()
        threads = [threading.Thread(
            target=run_worker,
            args=("127.0.0.1", server.port, scheds[w]),
            kwargs=dict(timeout_s=0.5, max_attempts=10))
            for w in range(workers)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        out = run_serve(cfg, server=server)
        wall = time.perf_counter() - t0
        server.stop()
        trace = ArrivalJournal(d).counts_trace()
        replay = run_serve(dataclasses.replace(cfg, workdir=None,
                                               wait_ops=0),
                           trace=trace)
    ev = out["events"]
    chaos_exact = (ev.get("drops_injected", 0) == oracle["drops"]
                   and ev.get("dup_frames", 0) == oracle["dups"]
                   and ev.get("reordered", 0) == oracle["reorders"])
    return {"rpc": {
        "workload": "rpc",
        "scenario": net_faults.describe(spec),
        "workers": int(workers),
        "requests_per_worker": int(requests),
        "engine": engine, "epochs": epochs,
        "dps": out["decisions"] / max(wall, 1e-9),
        "decisions": out["decisions"],
        "wall_s": wall,
        "admitted_ops": out["admitted_ops_traced"],
        "carry_ops": out["carry_ops"],
        "ingest_drops": out["ingest_drops"],
        "digest": out["digest"],
        "digest_match": bool(replay["digest"] == out["digest"]
                             and replay["trace_sha"]
                             == out["trace_sha"]),
        "chaos_exact": bool(chaos_exact),
        "oracle_drops": oracle["drops"],
        "oracle_dups": oracle["dups"],
        "oracle_reorders": oracle["reorders"],
        "chaos_drops": int(ev.get("drops_injected", 0)),
        "chaos_dups": int(ev.get("dup_frames", 0)),
        "chaos_reorders": int(ev.get("reordered", 0)),
        "busy": int(ev.get("busy", 0)),
        "deduped": int(ev.get("deduped", 0)),
        "lat_p50_ms": out["latency"]["p50_ms"],
        "lat_p99_ms": out["latency"]["p99_ms"],
    }}


def bench_mesh_rebalance(*, n_shards: int = 4, total_ids: int = 64,
                         epochs: int = 24, ckpt_every: int = 4,
                         engine: str = "prefix", m: int = 2,
                         k: int = 32, ring: int = 16, waves: int = 6,
                         seed: int = 17, tracer=None) -> dict:
    """The shard-rebalancing A/B (docs/LIFECYCLE.md "Placement and
    migration"): two EXACT-TWIN supervised mesh jobs on the
    ``shard_skew`` churn scenario -- identical engine, arrival
    stream, and lifecycle spec -- differing ONLY in the placement
    plane.  The off twin is today's static ``cid % S`` mesh (no
    placement map, no controller: bit-identical to ``--rebalance
    off``); the on twin runs ``placement="p2c"`` with a controller
    whose ONLY live rule is ``migrate`` (sync pinned, clamp/compact
    thresholds parked), so the row's recovered dec/s and shard-skew
    delta are attributable to the migrations alone.

    Skew metric: max/mean of the per-shard delta-completion totals
    (``mesh_counters[0]``) at the end of the run -- 1.0 is perfectly
    level, S is everything-on-one-shard.  ``skew_before`` is the off
    twin's final skew (what the static mesh ends at), ``skew_after``
    the on twin's."""
    import dataclasses

    import jax

    from dmclock_tpu.lifecycle import make_spec
    from dmclock_tpu.robust.supervisor import EpochJob, run_job

    S = min(int(n_shards), len(jax.devices()))
    spec = make_spec("shard_skew", total_ids=total_ids,
                     n_shards=S, seed=seed)
    # pick="hot": move the largest-demand DRAINED clients -- their
    # future arrivals follow them (arrival rate is a property of the
    # id, routing is a property of the placement map), so each move
    # sheds real offered load onto an idle shard's serve budget.
    # (The cold pick is the digest-twin-provable class; the bench
    # measures throughput, the tests prove equivalence.)
    ctl = dict(sync_max=1, backlog_hi=10**9, occ_lo=0.0,
               hysteresis=1, cooldown=2,
               migrate_skew_hi=1.5, migrate_pick="hot",
               migrate_max=4)
    job = EpochJob(engine=engine, engine_loop="mesh", n_shards=S,
                   churn=spec, epochs=epochs, m=m, k=k, ring=ring,
                   waves=waves, ckpt_every=ckpt_every, seed=seed)

    def one(job):
        t0 = time.perf_counter()
        res = run_job(job)
        return res, time.perf_counter() - t0

    def skew(res):
        tot = np.asarray(res.mesh_counters[0],
                         dtype=np.float64).sum(axis=1)
        return float(tot.max() / max(tot.mean(), 1e-12)), \
            [int(t) for t in tot]

    row = {"workload": "mesh_rebalance", "scenario": "shard_skew",
           "engine": engine, "engine_loop": "mesh", "n_shards": S,
           "epochs": epochs, "ckpt_every": ckpt_every,
           "total_ids": total_ids, "rebalance": "on",
           "placement": "p2c"}
    with obsspans.span(tracer, "mesh.bench_rebalance", "dispatch",
                       n_shards=S, epochs=epochs):
        run_job(job)    # untimed warmup: twins share the jit cache
        off, wall_off = one(job)
        on, wall_on = one(dataclasses.replace(
            job, placement="p2c", controller=ctl))
    skew_off, shards_off = skew(off)
    skew_on, shards_on = skew(on)
    row.update(
        dps_off=off.decisions / wall_off,
        dps_on=on.decisions / wall_on,
        decisions_off=int(off.decisions),
        decisions_on=int(on.decisions),
        wall_s_off=wall_off, wall_s_on=wall_on,
        shard_skew_before=skew_off, shard_skew_after=skew_on,
        shard_skew_final=skew_on,
        shard_decisions_off=shards_off, shard_decisions_on=shards_on,
        migrations=int(on.migrations),
        migration_log=on.migration_log,
        placement_counters=on.placement_counters,
        controller_knobs=on.controller_knobs)
    row["dps"] = row["dps_on"]
    row["recovered_dps"] = row["dps_on"] - row["dps_off"]
    # the wall-clock-free signal: completions the migrations unlocked
    # (arrivals served that the static mesh left queued on the hot
    # shard).  On a scaled cpu shape the on twin's wall time is
    # dominated by host actuation + retraces -- like
    # bench_controller, this is a control-plane demo row, and
    # recovered_decisions is the honest recovery currency there.
    row["recovered_decisions"] = (row["decisions_on"]
                                  - row["decisions_off"])
    row["shard_skew_recovered"] = skew_off - skew_on
    return row


def _with_ladder(ladder, cfg: dict, fn):
    """Run one workload under the degradation ladder
    (robust.guarded.DegradationLadder): a failed run whose config
    still has a fast path engaged (radix selection, bucketed
    calendar, tag32 carry) steps that knob down to its proven-exact
    twin and retries, instead of losing the whole session to one
    wedged fast path.  A device-side failure (XlaRuntimeError -- the
    wedged-kernel shape) IS ladder-eligible; only a backend that is
    plainly dead (init/connect failure messages) re-raises for the
    cpu-fallback machinery, since no fast-path concession can revive
    it.  Returns (result_row, effective_cfg)."""
    import sys

    while True:
        c = ladder.apply(cfg)
        try:
            return fn(**c), c
        except (AssertionError, RuntimeError) as e:
            msg = str(e).lower()
            if isinstance(e, RuntimeError) and \
                    ("unable to initialize" in msg
                     or "failed to connect" in msg):
                raise           # dead backend, not a fast-path fault
            # device errors count as launch failures, tripped guard
            # asserts as guard trips -- same escalation either way
            stepped = ladder.note_epoch(
                c, guard_trips=int(isinstance(e, AssertionError)),
                launch_failures=int(isinstance(e, RuntimeError)))
            if not stepped:
                raise           # nothing left to concede
            step = ladder.steps[-1]
            print(f"# ladder: {step.knob} {step.from_value} -> "
                  f"{step.to_value} after {type(e).__name__}: {e}",
                  file=sys.stderr)


def _is_backend_error(e: BaseException) -> bool:
    """A device-launch failure that means the BACKEND is unusable, not
    that the bench is buggy: the tunneled runtime can pass the
    init-time probe and then raise at the first real dispatch
    (BENCH_r05: ``RuntimeError: Unable to initialize backend 'axon'``
    surfaced at the first device launch after ``jax.devices()``
    succeeded).  XlaRuntimeError subclasses RuntimeError."""
    if not isinstance(e, RuntimeError):
        return False
    msg = str(e).lower()
    return (type(e).__name__ == "XlaRuntimeError"
            or "backend" in msg or "unable to initialize" in msg
            or "failed to connect" in msg)


def _switch_to_cpu_backend() -> None:
    """Best-effort mid-process backend switch after a dispatch-time
    failure: point jax at cpu and drop every cached backend/program so
    the re-entered run initializes fresh."""
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.clear_caches()
    except Exception:
        pass
    try:
        # the compile plane's instrumented caches hold AOT executables
        # bound to the dead backend -- drop them or the re-entered run
        # would dispatch into the corpse
        from dmclock_tpu.obs import compile_plane as _cp
        _cp.clear_compiled()
    except Exception:
        pass
    try:
        import jax.extend.backend as _jeb
        _jeb.clear_backends()
    except Exception:
        try:    # older jax spells it on the bridge module
            from jax._src import xla_bridge as _xb
            _xb._clear_backends()
        except Exception:
            pass


def _probe_backend_eager() -> None:
    """Force one real eager dispatch through the backend.

    ``jax.devices()`` succeeding is NOT proof the backend works: the
    BENCH_r05 rc=1 failure raised at an eager ``_convert_element_type``
    during the first array bind -- after device enumeration passed and
    before any jitted dispatch, a window neither the init fallback nor
    the dispatch fallback covered.  This probe walks that exact path
    (eager convert + compute + fetch) so a dead tunnel is caught
    BEFORE the bench builds any state on it."""
    x = jnp.asarray(np.arange(4, dtype=np.int32))
    y = (x.astype(jnp.int64) + 1).sum()       # eager convert + compute
    # explicit raise, not assert: under PYTHONOPTIMIZE an assert (and
    # the device_get inside it) would be stripped, silently skipping
    # the transfer leg the probe exists to exercise
    if int(jax.device_get(y)) != 10:
        raise RuntimeError("backend probe computed garbage")


def _resolve_backend():
    """Probe the accelerator backend BEFORE any eager array creation,
    falling back to CPU when setup fails (BENCH_r05: the tunneled TPU
    runtime raised at backend init / first eager bind and the whole
    bench crashed with rc=1 and no JSON line).  Returns (platform,
    fallback, error_str)."""
    try:
        platform = jax.devices()[0].platform
        _probe_backend_eager()
        return platform, False, None
    except Exception as e:  # RuntimeError from backend setup, usually
        err = f"{type(e).__name__}: {e}"
        try:
            _switch_to_cpu_backend()
            _probe_backend_eager()
            return jax.devices()[0].platform, True, err
        except Exception as e2:     # even CPU failed: report, no crash
            return "none", True, f"{err}; cpu fallback: {e2}"


def main() -> None:
    import argparse
    import contextlib
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", metavar="DIR", default=None)
    ap.add_argument("--mode",
                    choices=["all", "serve", "cfg3", "cfg4",
                             "frontier", "churn", "mesh",
                             "controller", "rpc"],
                    default="all")
    ap.add_argument("--clients", type=int, default=100_000,
                    metavar="N",
                    help="--mode mesh: TOTAL client population across "
                    "all shards; without --n-shards the shard count "
                    "is derived by inverting the capacity plane's HBM "
                    "ledger (obs.capacity.plan_capacity over the "
                    "detected device budget) -- the shard count falls "
                    "out of the client target, never guessed")
    ap.add_argument("--n-shards", type=int, default=None, metavar="S",
                    help="--mode mesh: per-device engine count (caps "
                    "at the attached device count; on cpu boxes "
                    "bench forces a virtual host mesh of this size "
                    "before backend init)")
    ap.add_argument("--counter-sync-every", type=int, default=1,
                    metavar="K",
                    help="--mode mesh: exchange the [C]-sized "
                    "delta/rho counter psum only on epochs where "
                    "epoch %% K == 0 (the staleness knob; the "
                    "paper's piggybacked views are naturally stale, "
                    "and K>1 is pinned decision-exact against the "
                    "host loop's delay_counters fault)")
    ap.add_argument("--rebalance", choices=["off", "on"],
                    default="off",
                    help="--mode mesh: 'on' adds the shard-"
                    "rebalancing A/B row (bench_mesh_rebalance; "
                    "docs/LIFECYCLE.md \"Placement and migration\"): "
                    "exact supervised twins on the shard_skew churn "
                    "scenario differing only in placement='p2c' + "
                    "the migrate controller rule, recording shard "
                    "skew before/after and the aggregate dec/s "
                    "recovered.  'off' (default) is bit-identical "
                    "to today's static mesh -- the flag adds a row, "
                    "it never perturbs the mesh series")
    ap.add_argument("--churn-scenario",
                    choices=["flash_crowd", "diurnal", "churn_storm",
                             "limit_thrash"],
                    default="flash_crowd",
                    help="open-population scenario for the churn "
                    "workload (lifecycle.churn; docs/LIFECYCLE.md): "
                    "clients register/depart through the lifecycle "
                    "plane, slots recycle, capacity grows on demand, "
                    "compaction repacks -- and a live PUT "
                    "/clients/{id}/qos lands mid-run through the "
                    "mounted admin API (its delivered-share effect "
                    "rides the conformance table).  Runs under "
                    "--mode churn (any backend; scaled shape on cpu) "
                    "or --mode all (accelerator only)")
    ap.add_argument("--target-latency", type=float, default=0.0,
                    metavar="MS",
                    help="pick the fastest cfg4 operating point whose "
                         "device-side mean round time fits this "
                         "budget; implies --mode frontier")
    ap.add_argument("--select-impl", choices=["sort", "radix", "both"],
                    default="sort",
                    help="prefix-engine selection backend (fastpath "
                    "select_impl) for the serve/cfg3 workloads; 'both' "
                    "runs serve under each and reports serve + "
                    "serve_radix (bit-identical decisions, A/B timing; "
                    "cfg4's calendar engine is sortless and ignores "
                    "this)")
    ap.add_argument("--calendar-impl",
                    choices=["minstop", "bucketed", "wheel", "both"],
                    default="minstop",
                    help="calendar-engine commit-boundary scheme for "
                    "the cfg4 workload (fastpath calendar_impl): "
                    "'bucketed' fuses a stop-key ladder of "
                    "--ladder-levels refreshed boundaries per batch "
                    "(more decisions per pass on skewed populations); "
                    "'wheel' drives the same ladder from a maintained "
                    "timer-wheel bucket index (O(1)-bucket re-slot "
                    "per commit; --wheel-kernel picks its kernel); "
                    "'both' runs cfg4 under all three and reports "
                    "cfg4 + cfg4_bucketed + cfg4_wheel (separate "
                    "bench_guard series)")
    ap.add_argument("--ladder-levels", type=int, default=8,
                    metavar="L",
                    help="ladder levels per bucketed/wheel calendar "
                    "batch")
    ap.add_argument("--wheel-kernel", choices=["xla", "pallas"],
                    default="xla",
                    help="wheel-calendar bucket scatter/scan backend "
                    "(fastpath wheel_kernel): 'pallas' runs the "
                    "hand-written fused kernel on TPU (bit-identical; "
                    "falls back to 'xla' off-TPU, counted in the "
                    "wheel_pallas_fallbacks metric row)")
    ap.add_argument("--engine-loop",
                    choices=["round", "stream", "both"],
                    default="round",
                    help="sustained-workload loop structure "
                    "(docs/ENGINE.md): 'round' = one fused "
                    "ingest+serve launch per round (the historical "
                    "shape); 'stream' = one launch per "
                    "--stream-chunk rounds (lax.scan over the "
                    "identical round body, decisions bit-identical; "
                    "launches-per-decision down by the chunk "
                    "factor); 'both' runs each sustained workload "
                    "under each and reports e.g. cfg4 + cfg4_stream "
                    "(separate bench_guard series).  serve-only has "
                    "no ingest loop and ignores this")
    ap.add_argument("--stream-chunk", type=int, default=8,
                    metavar="R",
                    help="rounds fused per stream-loop launch")
    ap.add_argument("--device-metrics", choices=["on", "off"],
                    default="on",
                    help="accumulate the on-device obs vector inside "
                    "the timed kernels (bit-identical decisions either "
                    "way; 'off' measures the metrics overhead itself)")
    ap.add_argument("--telemetry", choices=["on", "off"],
                    default="on",
                    help="accumulate the device QoS telemetry plane "
                    "(log2 histograms + per-client conformance "
                    "ledger, obs.histograms) inside the timed "
                    "sustained kernels; decisions are bit-identical "
                    "either way, and the JSON line carries "
                    "p50/p90/p99 reservation tardiness from the "
                    "device ledger ('off' measures the overhead)")
    ap.add_argument("--slo", choices=["on", "off"], default="on",
                    help="accumulate the device-resident SLO window "
                    "block (obs.slo) inside the timed sustained "
                    "rounds (donated carry, one window per timed "
                    "chain, fetched untimed) and judge it with the "
                    "burn-rate evaluator (obs.alerts); decisions are "
                    "bit-identical either way, and the JSON line "
                    "carries a per-workload 'slo' block (violation "
                    "counts, worst-window share error, p99 window "
                    "tardiness).  'off' measures the overhead")
    ap.add_argument("--provenance", choices=["on", "off"],
                    default="on",
                    help="accumulate the decision provenance plane "
                    "(obs.provenance) inside the timed sustained "
                    "rounds: per-decision winner margins, the "
                    "limit-gate state, eligible-set depth, winning "
                    "phase, and the per-client last-served "
                    "starvation watermark; decisions are "
                    "bit-identical either way, and the JSON line "
                    "carries margin_p50/p99_ns, limit_gate_share, "
                    "and starvation_max_ns ('off' measures the "
                    "overhead; provenance-off rows form their own "
                    "bench_guard series)")
    ap.add_argument("--capacity", choices=["on", "off"], default="on",
                    help="capacity plane (docs/OBSERVABILITY.md): "
                    "pre-launch projected-HBM check per sustained "
                    "workload (projection over budget -> warn + skip "
                    "the workload, never crash) and the "
                    "compile_ms_total / retraces / "
                    "projected_hbm_bytes / bound_class record in the "
                    "JSON line + history ('off' disables the gate; "
                    "the record always rides)")
    ap.add_argument("--conformance-out", metavar="FILE", default=None,
                    help="write the cfg4 per-client conformance table "
                    "as JSONL")
    ap.add_argument("--spans", action="store_true",
                    help="collect host spans (obs.spans) through "
                    "calibration + the timed chains and report the "
                    "per-launch dispatch-tax decomposition "
                    "(dispatch_ms_per_launch, device_ms_per_launch, "
                    "host_overhead_frac, per-category breakdown) in "
                    "the JSON line; decisions are bit-identical "
                    "either way (spans are host-side only)")
    ap.add_argument("--trace-out", metavar="FILE.json", default=None,
                    help="write the collected spans as a Chrome "
                    "trace-event / Perfetto timeline (implies "
                    "--spans); load in chrome://tracing")
    ap.add_argument("--metrics-port", type=int, metavar="PORT",
                    default=None,
                    help="serve the live default metrics registry over "
                    "HTTP (GET /metrics, Prometheus text) for the "
                    "duration of the bench; 0 picks an ephemeral port "
                    "(printed to stderr)")
    ap.add_argument("--fault-plan", default="none", metavar="TAG",
                    help="label this session's fault-injection plan "
                    "(robust.faults.describe() tag) in the JSON line "
                    "and the benchmark history record; bench_guard "
                    "keeps non-'none' (chaos) sessions out of the "
                    "clean-run regression medians.  With --mode mesh "
                    "a PARSEABLE spec (e.g. 'seed=7,p_dropout=0.05,"
                    "mean_outage_steps=2,p_dup=0.1,max_skew_ns=1000') "
                    "samples a real FaultPlan and compiles it INTO "
                    "the fused chunks -- the chaos mesh session; the "
                    "row then records per-shard dropout/resync "
                    "counts (docs/ROBUSTNESS.md 'Degraded-mode "
                    "mesh')")
    ap.add_argument("--controller",
                    choices=["off", "on", "both"], default="both",
                    help="--mode controller: which twin(s) of the "
                    "closed-loop controller A/B to run under the "
                    "shard_skew / limit_thrash / diurnal churn "
                    "scenarios (docs/CONTROLLER.md).  'both' (the "
                    "default) runs exact twins differing only in "
                    "EpochJob(controller=...) and reports recovered "
                    "dec/s + burn-episode-duration deltas; the "
                    "history record tags controller-actuated "
                    "sessions so bench_guard keeps them out of the "
                    "clean-run medians")
    ap.add_argument("--rpc-workers", type=int, default=4,
                    metavar="W",
                    help="--mode rpc: concurrent loadgen workers "
                    "driving the loopback ingest server (each owns a "
                    "disjoint client-id partition with a seeded, "
                    "byte-identical request schedule)")
    ap.add_argument("--rpc-fault-spec", default=None, metavar="SPEC",
                    help="--mode rpc: seeded network chaos spec "
                    "(net.faults grammar, e.g. 'seed=7,p_drop=0.1,"
                    "p_dup=0.05,p_reorder=0.05'); the row then gates "
                    "exact drop/dup/reorder accounting against the "
                    "host oracle (chaos_exact) and the session is "
                    "kept out of bench_guard's clean medians")
    ap.add_argument("--supervised", action="store_true",
                    default=os.environ.get("DMCLOCK_SUPERVISED")
                    == "1",
                    help="tag this session as running under the "
                    "robust.supervisor (set automatically via "
                    "DMCLOCK_SUPERVISED=1 in supervised "
                    "environments); with DMCLOCK_RESTARTS > 0 the "
                    "history record carries the restart count and "
                    "bench_guard keeps the run out of the clean-run "
                    "medians")
    ap.add_argument("--no-ladder", action="store_true",
                    help="disable the degradation ladder (a failed "
                    "fast-path workload raises instead of stepping "
                    "down to its exact twin and retrying)")
    args = ap.parse_args()
    restarts = int(os.environ.get("DMCLOCK_RESTARTS", "0") or 0)
    if args.mode == "mesh" and args.n_shards:
        # force a virtual host mesh of the requested size BEFORE any
        # backend initializes (the conftest.py discipline; a no-op on
        # accelerator backends -- it only sizes the cpu client)
        try:
            jax.config.update("jax_num_cpu_devices", args.n_shards)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count="
                f"{args.n_shards}")
        except RuntimeError:
            pass   # backend already up; bench_mesh caps the count
    if args.target_latency:
        args.mode = "frontier"
    if args.metrics_port is not None:
        # fail-soft inside start_http_server: a failed bind (port
        # taken, privileged) must not kill the session before the
        # JSON line can be emitted
        import atexit

        from dmclock_tpu.obs import start_http_server
        http_srv = start_http_server(port=args.metrics_port)
        if http_srv is not None:
            print(f"# metrics: serving {http_srv.url}",
                  file=sys.stderr)
            atexit.register(http_srv.close)

    backend, fallback, backend_err = _resolve_backend()
    backend_fallback = None   # "dispatch" after a launch-time switch
    wm = args.device_metrics == "on"
    tele_on = args.telemetry == "on"
    slo_on = args.slo == "on"
    prov_on = args.provenance == "on"
    if args.trace_out:
        args.spans = True
    tracer = obsspans.SpanTracer() if args.spans else None
    watchdog = None
    from dmclock_tpu.obs import compile_plane as _cplane
    if tracer is not None:
        # compile records ride the same span stream as the launches
        # they delay (category "compile"; docs/OBSERVABILITY.md
        # capacity plane)
        _cplane.plane().set_tracer(tracer)
        # steady-state watchdog: warns live when the launch cadence
        # stalls, the dispatch share breaches its threshold, or a jit
        # cache entry retraces storm-fast (docs/OBSERVABILITY.md)
        from dmclock_tpu.obs import default_registry
        from dmclock_tpu.obs.watchdog import Watchdog
        watchdog = Watchdog(tracer, interval_s=2.0,
                            stall_after_s=60.0,
                            registry=default_registry(),
                            compile_plane=_cplane.plane()).start()
    from dmclock_tpu.robust.guarded import DegradationLadder
    ladder = DegradationLadder(enabled=not args.no_ladder,
                               threshold=1, tracer=tracer)

    def emit(out: dict) -> None:
        """THE json line: every exit path goes through here so the
        bench trajectory never has a null round again (BENCH_r05)."""
        out["backend"] = backend
        # chaos sessions self-identify so the regression series stays
        # clean (scripts/bench_guard.py; docs/ROBUSTNESS.md)
        out["fault_plan"] = args.fault_plan
        # supervised/resumed sessions self-identify the same way: a
        # restart-bearing run's rates include recovery work, not the
        # engine alone
        if args.supervised:
            out["supervised"] = True
            out["restarts"] = restarts
        if ladder.steps_taken:
            out["degradation_ladder"] = ladder.describe()
        if fallback:
            out["fallback"] = True
        if backend_err:
            out["backend_error"] = backend_err
        if backend_fallback:
            out["backend_fallback"] = backend_fallback
        if watchdog is not None:
            watchdog.close()
            if watchdog.warnings:
                out["watchdog_warnings"] = watchdog.warnings[-8:]
        if tracer is not None and args.trace_out:
            # export on EVERY exit path (the emit contract): a failed
            # run's timeline is exactly when you want the trace
            try:
                from dmclock_tpu.obs import export_chrome_trace
                n_ev = export_chrome_trace(tracer, args.trace_out)
                print(f"# trace-out: {n_ev} spans -> "
                      f"{args.trace_out}", file=sys.stderr)
            except OSError as e:
                print(f"# trace-out failed: {e}", file=sys.stderr)
        print(json.dumps(out))

    if backend == "none":
        emit({"metric": "bench skipped: no usable jax backend",
              "value": 0.0, "unit": "decisions/sec/chip",
              "vs_baseline": 0.0})
        return

    if args.mode == "frontier" and backend == "cpu":
        emit({"metric": "cfg4 frontier skipped on cpu fallback "
                        "(100k-client calendar sweeps need the "
                        "accelerator)",
              "value": 0.0, "unit": "decisions/sec/chip",
              "vs_baseline": 0.0, "rows": []})
        return

    if args.mode == "frontier":
        pick, rows = bench_frontier(
            target_latency_ms=args.target_latency)
        out = {"metric": "cfg4 throughput/latency frontier "
                         "(calendar engine; device-side round mean + "
                         "windowed completion-interval percentiles)",
               "rows": rows}
        if pick is not None:
            out["picked"] = pick
            out["metric"] += (f"; --target-latency "
                              f"{args.target_latency}ms pick: "
                              f"m={pick['m']} "
                              f"{pick['dps']/1e6:.1f}M dec/s at "
                              f"{pick['round_ms_mean']:.1f}ms rounds"
                              + ("" if pick["met_budget"] else
                                 " (budget NOT met; closest point)"))
        emit(out)
        try:
            _record_history({"frontier_" + str(r["m"]): r
                             for r in rows},
                            fault_plan=args.fault_plan,
                            supervised=args.supervised,
                            restarts=restarts)
        except OSError:
            pass
        return
    trace_ctx = (jax.profiler.trace(args.profile) if args.profile
                 else contextlib.nullcontext())

    def run_workloads(backend: str) -> dict:
        results = {}
        loops = ("round", "stream") if args.engine_loop == "both" \
            else (args.engine_loop,)
        if args.mode in ("all", "serve"):
            # the cpu fallback cannot hold a 100k x 320 backlog in
            # tolerable time; a scaled-down shape keeps the smoke alive
            serve_kw = dict(with_metrics=wm, tracer=tracer)
            if backend == "cpu":
                serve_kw.update(k=1024, m=4, depth=48, n=4096,
                                epochs_lo=1, epochs_hi=2, reps=3)
            impls = ("sort", "radix") if args.select_impl == "both" \
                else (args.select_impl,)
            for impl in impls:
                row, eff = _with_ladder(
                    ladder, {"select_impl": impl},
                    lambda select_impl: bench_serve_only(
                        select_impl=select_impl, **serve_kw))
                # key by the EFFECTIVE impl: a ladder step-down must
                # not masquerade as the requested fast path's history
                # series (setdefault: if radix degraded into sort and
                # sort already ran, the duplicate row is dropped)
                key = "serve" if eff["select_impl"] == "sort" \
                    else "serve_radix"
                results.setdefault(key, row)
        if args.mode in ("all", "cfg3") and \
                (backend != "cpu" or args.mode == "cfg3"):
            # 10k clients, uniform QoS, Poisson arrivals; weight
            # regime.  Rounds are small (~130k decisions, ~7ms), so
            # the chains must be long for the differenced pairs to
            # clear tunnel jitter.  An EXPLICIT --mode cfg3 on the
            # cpu fallback runs a scaled-down shape: the
            # round-vs-stream ingest+serve A/B (PROFILE.md finding
            # 19) needs a sustained workload on cpu-only boxes too,
            # and platform=cpu already keeps the record out of the
            # accelerator medians (bench_guard is_fallback)
            if backend == "cpu":
                cfg3_shape = dict(n=2048, k=512, m=8, rounds=24,
                                  zipf=False, resv_rate=50.0,
                                  dt_round_ns=100_000_000, ring=64,
                                  depth0=48, waves=16, rounds_lo=8,
                                  reps=2)
            else:
                cfg3_shape = dict(n=10_000, k=4096, m=32, rounds=60,
                                  zipf=False, resv_rate=100.0,
                                  dt_round_ns=100_000_000, ring=256,
                                  depth0=128, rounds_lo=20)
            for loop in loops:
                key = "cfg3" if loop == "round" else "cfg3_stream"
                sh = dict(cfg3_shape)
                sh_pos = (sh.pop("n"), sh.pop("k"), sh.pop("m"),
                          sh.pop("rounds"))
                results[key], _ = _with_ladder(
                    ladder,
                    {"select_impl": "radix"
                     if args.select_impl == "radix" else "sort"},
                    lambda select_impl, loop=loop, sh=sh,
                    sh_pos=sh_pos: bench_sustained(
                        *sh_pos, **sh, with_metrics=wm,
                        select_impl=select_impl,
                        engine_loop=loop,
                        stream_chunk=args.stream_chunk,
                        telemetry=tele_on, slo=slo_on,
                        provenance=prov_on,
                        capacity_check=args.capacity == "on",
                        tracer=tracer, watchdog=watchdog))
        if args.mode == "churn" or \
                (args.mode == "all" and backend != "cpu"):
            # open-population churn scenario (docs/LIFECYCLE.md).  An
            # EXPLICIT --mode churn runs a scaled shape on cpu boxes
            # (the cfg3 convention): the lifecycle mechanics + live
            # control-plane demo need no accelerator to be meaningful,
            # and platform=cpu keeps the record out of accelerator
            # medians
            churn_shape = dict(total_ids=512, epochs=32, k=64) \
                if backend == "cpu" \
                else dict(total_ids=4096, epochs=64, k=256)
            key = f"churn_{args.churn_scenario}"
            results[key] = bench_churn(args.churn_scenario,
                                       slo=slo_on, tracer=tracer,
                                       **churn_shape)
        if args.mode == "mesh":
            # the mesh serving plane's aggregate-throughput series
            # (any backend: cpu with forced host devices proves the
            # scaling shape; the silicon campaign inherits the
            # >=100M dec/s @ 1M clients target as the same command).
            # --fault-plan "seed=..,p_dropout=.." (a parseable SPEC,
            # not just a label) samples a real FaultPlan and compiles
            # it INTO the chunks -- the chaos mesh session
            # (docs/ROBUSTNESS.md "Degraded-mode mesh")
            from dmclock_tpu.robust import faults as _faults
            mesh_fault_spec = _faults.parse_fault_spec(
                args.fault_plan)
            results["mesh"] = bench_mesh(
                args.clients, n_shards=args.n_shards,
                counter_sync_every=args.counter_sync_every,
                chunk=args.stream_chunk, with_metrics=wm,
                slo=slo_on, tracer=tracer,
                fault_spec=mesh_fault_spec)
            if mesh_fault_spec is not None:
                # the history/JSON tag becomes the sampled plan's
                # describe() summary (chaos sessions self-identify;
                # bench_guard keeps them out of clean medians)
                args.fault_plan = results["mesh"].get(
                    "fault_plan", args.fault_plan)
            if args.rebalance == "on":
                # the shard-rebalancing A/B rides the mesh session as
                # its own row; the mesh series above is untouched
                # (its identity carries rebalance="off"/P=static)
                results["mesh_rebalance"] = bench_mesh_rebalance(
                    n_shards=args.n_shards or 4, tracer=tracer)
        if args.mode == "controller":
            # the closed-loop controller A/B (docs/CONTROLLER.md):
            # exact supervised twins per churn scenario, differing
            # only in EpochJob(controller=...).  A cpu box runs a
            # scaled shape (the cfg3/churn convention): the control
            # plane's actuation mechanics need no accelerator, and
            # platform=cpu keeps the record out of the accelerator
            # medians
            ctl_shape = dict(total_ids=96, epochs=32) \
                if backend == "cpu" \
                else dict(total_ids=192, epochs=48)
            results.update(bench_controller(
                sides=args.controller, tracer=tracer, **ctl_shape))
        if args.mode == "rpc":
            # the RPC ingest front-end leg (docs/RPC.md): real
            # loopback sockets + N concurrent loadgen workers, then
            # the digest gate vs a self-generated replay of the
            # journaled admitted-counts trace.  cpu boxes run a
            # scaled shape (the controller-mode convention): the
            # serving plane's correctness story needs no accelerator
            rpc_shape = dict(n=16, epochs=8, requests=32) \
                if backend == "cpu" \
                else dict(n=32, epochs=16, requests=64)
            results.update(bench_rpc(
                workers=args.rpc_workers,
                fault_spec=args.rpc_fault_spec, tracer=tracer,
                **rpc_shape))
            if args.rpc_fault_spec:
                # chaos sessions self-identify in the history record
                # (bench_guard keeps them out of clean medians)
                args.fault_plan = "rpc:" \
                    + results["rpc"]["scenario"]
        if args.mode in ("all", "cfg4") and backend != "cpu":
            # 100k clients, Zipfian weights, reservation-constrained
            # (constraint share auto-calibrated to 0.50 -- a faster
            # engine needs a proportionally larger floor for the same
            # phase mix; round-5 equilibrium lands near 1200/s/client).
            # Calendar engine, m=3 batches x 64 serve-steps/client:
            # the frontier sweep showed decisions/round are capped by
            # the load generator (waves=64 ~ 5.8M arrivals/round), so
            # the smallest m whose per-client budget covers the
            # per-round arrival cap (192 >= 63) is strictly fastest
            # (m=12 commits the same decisions in 4x the passes).
            # --calendar-impl A/Bs the bucketed stop-key ladder
            # against minstop (separate bench_guard series; the JSON
            # line records decisions_per_pass for each).
            cals = ("minstop", "bucketed", "wheel") \
                if args.calendar_impl == "both" \
                else (args.calendar_impl,)
            for cal in cals:
                for loop in loops:
                    row, eff = _with_ladder(
                        ladder, {"calendar_impl": cal},
                        lambda calendar_impl, loop=loop:
                        bench_sustained(
                            100_000, 0, 3, 40, zipf=True,
                            resv_rate=1200.0, dt_round_ns=50_000_000,
                            waves=64, rounds_lo=12,
                            latency_rounds=100,
                            calendar_steps=64, target_resv_share=0.5,
                            reps=4, with_metrics=wm,
                            calendar_impl=calendar_impl,
                            ladder_levels=args.ladder_levels,
                            wheel_kernel=args.wheel_kernel,
                            engine_loop=loop,
                            stream_chunk=args.stream_chunk,
                            conformance_out=args.conformance_out,
                            telemetry=tele_on, slo=slo_on,
                            provenance=prov_on,
                            capacity_check=args.capacity == "on",
                            tracer=tracer, watchdog=watchdog))
                    # keyed by the EFFECTIVE impl: a ladder step-down
                    # mid-session must land the row in the series it
                    # actually measured (wheel -> bucketed -> minstop)
                    key = "cfg4" if eff["calendar_impl"] == "minstop" \
                        else f"cfg4_{eff['calendar_impl']}"
                    if loop == "stream":
                        key += "_stream"
                    results.setdefault(key, row)
        return results

    with trace_ctx:
        try:
            try:
                results = run_workloads(backend)
            except RuntimeError as e:
                if not _is_backend_error(e):
                    raise
                # the init-time probe passed but the FIRST dispatch
                # raised (BENCH_r05): switch to cpu and re-enter,
                # keeping the guaranteed JSON line
                print(f"# backend failed at dispatch ({e}); "
                      "re-entering on cpu", file=sys.stderr)
                backend_err = f"{type(e).__name__}: {e}"
                _switch_to_cpu_backend()
                backend, fallback = "cpu", True
                backend_fallback = "dispatch"
                results = run_workloads("cpu")
        except Exception as e:
            # the unkillable-bench contract (ROADMAP): EVERY round
            # exits rc=0 with a valid JSON line, even when the tunnel
            # dies mid-run in a shape no fallback anticipated -- a
            # null round (BENCH_r05) costs the trajectory more than a
            # tagged failure record does
            import traceback
            traceback.print_exc()
            backend_err = f"{type(e).__name__}: {e}"
            emit({"metric": f"bench failed mid-run "
                            f"({type(e).__name__}); no usable rate",
                  "value": 0.0, "unit": "decisions/sec/chip",
                  "vs_baseline": 0.0, "error": backend_err})
            return

    if not results:
        emit({"metric": "sustained workloads skipped on cpu fallback "
                        "(superwave ingest rounds need the "
                        "accelerator)",
              "value": 0.0, "unit": "decisions/sec/chip",
              "vs_baseline": 0.0})
        return
    c4 = results.get("cfg4") or results.get("cfg4_bucketed") \
        or results.get("cfg4_wheel") \
        or results.get("cfg4_stream") \
        or results.get("cfg4_bucketed_stream") \
        or results.get("cfg4_wheel_stream")
    primary = c4 or results.get("cfg3") or results.get("cfg3_stream") \
        or results.get("serve") or next(iter(results.values()))
    parts = []
    for key in ("serve", "serve_radix"):
        if key in results:
            label = "serve-only" if key == "serve" \
                else "serve-only[radix]"
            parts.append(f"{label} {results[key]['dps']/1e6:.1f}M "
                         f"(fill {results[key]['fill']:.2f})")
    if "cfg3" in results:
        r = results["cfg3"]
        parts.append(f"cfg3 10k-client Poisson sustained "
                     f"{r['dps']/1e6:.1f}M (fill {r['fill']:.2f}, "
                     f"depth {r['mean_depth']:.0f})")
    if "cfg3_stream" in results:
        r = results["cfg3_stream"]
        parts.append(f"cfg3[stream] {r['dps']/1e6:.1f}M "
                     f"({r['decisions_per_launch']:.0f} dec/launch, "
                     f"chunk {r.get('stream_chunk', 0)})")
    for key, label in (("cfg4", "cfg4"),
                       ("cfg4_bucketed", "cfg4[bucketed]"),
                       ("cfg4_wheel", "cfg4[wheel]"),
                       ("cfg4_stream", "cfg4[stream]"),
                       ("cfg4_bucketed_stream",
                        "cfg4[bucketed,stream]"),
                       ("cfg4_wheel_stream",
                        "cfg4[wheel,stream]")):
        r4 = results.get(key)
        if not r4:
            continue
        parts.append(
            f"{label} 100k-client Zipf resv-constrained "
            f"{r4['dps']/1e6:.1f}M (resv phase "
            f"{r4['resv_phase_frac']:.2f}; "
            f"{r4.get('decisions_per_pass', 0):.0f} dec/pass; "
            f"round mean "
            f"{r4.get('round_ms_mean', 0):.0f}ms device-side, "
            f"measured-interval p50 "
            f"{r4.get('round_ms_p50', 0):.0f}ms p99 "
            f"{r4.get('round_ms_p99', 0):.0f}ms tunnel-inclusive "
            f"upper bounds)")
    if results.get("mesh", {}).get("capacity_skipped"):
        r = results["mesh"]
        parts.append(
            f"mesh SKIPPED by the capacity gate "
            f"({r['clients_per_shard']} clients/shard > planned "
            f"{r.get('max_clients_per_shard')} for the detected "
            "budget)")
    elif "mesh" in results:
        r = results["mesh"]
        planned = r.get("shards_planned")
        parts.append(
            f"mesh {r['n_shards']} shards x "
            f"{r['clients_per_shard']} clients "
            f"{r['dps']/1e6:.1f}M aggregate "
            f"({r['dps_per_shard_mean']/1e6:.2f}M/shard, "
            f"sync every {r['counter_sync_every']} epochs, "
            f"{r['counter_bytes_per_epoch']:.0f} B/epoch counter "
            f"exchange"
            + (", collective-free non-sync epochs"
               if r.get("collective_skipping") else "")
            + (f", {planned} shards planned from the HBM ledger"
               if planned is not None else "") + ")")
    if "mesh_rebalance" in results:
        r = results["mesh_rebalance"]
        parts.append(
            f"rebalance[{r['scenario']}] skew "
            f"{r['shard_skew_before']:.2f} -> "
            f"{r['shard_skew_after']:.2f} over {r['n_shards']} "
            f"shards ({r['migrations']} migrations; "
            f"{r['dps_on']/1e6:.2f}M on vs {r['dps_off']/1e6:.2f}M "
            f"off, {r['recovered_dps']/1e6:+.2f}M recovered)")
    for key in sorted(results):
        if not key.startswith("churn_"):
            continue
        r = results[key]
        b = r.get("boost")
        put = (f"; live PUT weight "
               f"x{b['weight_after']/max(b['weight_before'], 1e-9):.0f}"
               f" -> delivered share x{b['share_gain']:.1f}") \
            if b else ""
        parts.append(
            f"churn[{r['scenario']}] {r['dps']/1e6:.2f}M over an "
            f"open population (peak {r['peak_clients']} clients, "
            f"{r['evictions']} evictions, {r['slot_recycles']} "
            f"recycles, {r['compactions']} compactions{put})")
    for key in sorted(results):
        if not key.startswith("controller_"):
            continue
        r = results[key]
        if "recovered_dps" in r:
            parts.append(
                f"controller[{r['scenario']}] "
                f"{r['dps_on']/1e6:.2f}M on vs "
                f"{r['dps_off']/1e6:.2f}M off "
                f"({r['recovered_dps']/1e6:+.2f}M recovered; burn "
                f"{r['burn_epochs_on']} vs {r['burn_epochs_off']} "
                f"epochs; {r.get('controller_decisions', 0)} "
                f"actuations)")
        else:
            side = "on" if "dps_on" in r else "off"
            parts.append(
                f"controller[{r['scenario']},{side}] "
                f"{r['dps']/1e6:.2f}M (burn "
                f"{r.get('burn_epochs_' + side, 0)} epochs"
                + (f"; {r.get('controller_decisions', 0)} "
                   f"actuations)" if side == "on" else ")"))
    if "rpc" in results:
        r = results["rpc"]
        parts.append(
            f"rpc[{r['scenario']}] {r['workers']} workers over real "
            f"loopback sockets ({r['admitted_ops']} ops admitted, "
            f"digest {'MATCH' if r['digest_match'] else 'MISMATCH'} "
            f"vs journaled-trace replay"
            + (", chaos accounting "
               + ("EXACT" if r["chaos_exact"] else "INEXACT")
               if r["scenario"] != "none" else "")
            + f"; admit->commit p99 {r['lat_p99_ms']:.0f}ms)")

    # device histogram blocks feed the live scrape registry per
    # workload (proper Prometheus _bucket/_sum/_count families), then
    # leave the result rows -- the JSON line carries the readable
    # "telemetry" digest instead of the raw block twice
    for wl, row in results.items():
        hb = row.pop("_hist_block", None)
        if hb is not None:
            from dmclock_tpu.obs import default_registry
            from dmclock_tpu.obs import histograms as obshist
            obshist.publish_hists(default_registry(),
                                  np.asarray(hb, dtype=np.int64),
                                  labels={"workload": wl})
        if "spans" in row:
            # span-derived dispatch-tax gauges ride the same scrape
            # endpoint as the histogram families
            from dmclock_tpu.obs import (default_registry,
                                         publish_span_gauges)
            publish_span_gauges(default_registry(), row["spans"],
                                labels={"workload": wl})
        if "provenance" in row:
            # per-workload provenance verdicts as labelled gauges on
            # the same scrape endpoint (dmclock_provenance_* /
            # dmclock_starvation_* family names)
            from dmclock_tpu.obs import default_registry
            reg = default_registry()
            pd = row["provenance"]
            for key in ("margin_p50_ns", "margin_p99_ns",
                        "limit_gate_share", "eligible_depth_mean",
                        "eligible_depth_max"):
                reg.gauge(f"dmclock_provenance_{key}",
                          "per-workload decision provenance scalar "
                          "(docs/OBSERVABILITY.md Provenance plane)",
                          labels={"workload": wl}) \
                    .set(float(pd[key]))
            reg.gauge("dmclock_starvation_max_ns",
                      "per-workload starvation watermark "
                      "(provenance plane)",
                      labels={"workload": wl}) \
                .set(float(pd["starvation_max_ns"]))
        if "slo" in row:
            # per-workload SLO verdicts as labelled gauges on the
            # same scrape endpoint (dmclock_slo_* family names)
            from dmclock_tpu.obs import default_registry
            reg = default_registry()
            for key, name in (
                    ("violations_total",
                     "dmclock_slo_violations_total"),
                    ("worst_window_share_err",
                     "dmclock_slo_worst_window_share_err"),
                    ("window_tardiness_p99_ns",
                     "dmclock_slo_window_tardiness_p99_ns"),
                    ("windows_closed",
                     "dmclock_slo_windows_closed_total")):
                reg.gauge(name, "per-workload SLO plane verdict "
                          "(docs/OBSERVABILITY.md SLO plane)",
                          labels={"workload": wl}) \
                    .set(float(row["slo"].get(key, 0)))

    try:
        _record_history(results, fault_plan=args.fault_plan,
                        supervised=args.supervised, restarts=restarts,
                        ladder_steps=ladder.describe(),
                        controller=args.controller
                        if args.mode == "controller" else "off")
    except OSError as e:      # telemetry must never eat the results
        print(f"# history record failed: {e}", file=sys.stderr)
    final = {
        "metric": "dmclock sustained scheduling decisions/sec, "
                  "ARRIVALS INCLUDED (Poisson superwave ingest on "
                  "device each round; cfg4 on the sortless calendar "
                  "engine, serve/cfg3 on the sorted prefix engine, "
                  "both bit-exact vs the serial engine; counts read "
                  "back untimed) -- " + "; ".join(parts),
        "value": round(primary["dps"], 1),
        "unit": "decisions/sec/chip",
        "vs_baseline": round(primary["dps"] / 10_000_000, 4),
    }
    c4conf = c4.get("conformance") if c4 else None
    if c4conf:
        final["conformance"] = c4conf
    # the churn scenario's full block (lifecycle counters, the
    # per-client before/after shares, the live-PUT effect) rides the
    # JSON line -- the ISSUE-9 visible-effect acceptance output
    churn_rows = {wl: {k: v for k, v in row.items()
                       if k != "_hist_block"}
                  for wl, row in results.items()
                  if wl.startswith("churn_")}
    if churn_rows:
        final["churn"] = churn_rows
    # the controller A/B's full rows (recovered dec/s, burn-episode
    # durations, actuation trajectory) ride the JSON line -- the
    # PR-18 acceptance output; the scalar fields land in the history
    # record through the same _record_history scalar filter as every
    # other workload
    ctl_rows = {wl: {k: v for k, v in row.items()
                     if k != "_hist_block"}
                for wl, row in results.items()
                if wl.startswith("controller_")}
    if ctl_rows:
        final["controller"] = ctl_rows
    # the mesh serving plane's full row (aggregate + per-shard dec/s,
    # counter-exchange accounting, shard plan) rides the JSON line --
    # the MULTICHIP v2 record reads it straight off stdout
    if "mesh" in results:
        final["mesh"] = {k: v for k, v in results["mesh"].items()
                         if k != "_hist_block"}
    # the shard-rebalancing A/B row (--rebalance on): the MULTICHIP
    # v3 record's rebalance block reads it straight off stdout
    if "mesh_rebalance" in results:
        final["mesh_rebalance"] = dict(results["mesh_rebalance"])
    if wm and "device_metrics" in primary:
        final["device_metrics"] = primary["device_metrics"]
    # per-epoch XLA attribution + what bounded each sustained run ride
    # the same JSON line (and the obs registry, for live scrapes)
    cost_all = {wl: row["cost_analysis"] for wl, row in results.items()
                if isinstance(row.get("cost_analysis"), dict)}
    if cost_all:
        final["cost_analysis"] = cost_all
        for wl, ca in cost_all.items():
            _feed_cost_registry(wl, ca)
    bounded = {wl: row["bounded_by"] for wl, row in results.items()
               if "bounded_by" in row}
    if bounded:
        final["bounded_by"] = bounded
    # real tardiness percentiles from the device telemetry plane (the
    # sims' host-computed table, replaced by device truth at bench
    # scale); log2-quantized upper bounds, never under-reported
    # the per-launch dispatch-tax decomposition per workload (span
    # tracer; the before/after currency for the streaming-loop PR)
    span_rows = {wl: row["spans"] for wl, row in results.items()
                 if "spans" in row}
    if span_rows:
        final["spans"] = span_rows
    slo_rows = {wl: row["slo"] for wl, row in results.items()
                if "slo" in row}
    if slo_rows:
        final["slo"] = slo_rows
    prov_rows = {wl: row["provenance"] for wl, row in results.items()
                 if "provenance" in row}
    if prov_rows:
        final["provenance"] = prov_rows
    tard = {wl: {"p50": row["tardiness_p50_ns"],
                 "p90": row["tardiness_p90_ns"],
                 "p99": row["tardiness_p99_ns"],
                 "mean": row["tardiness_mean_ns"],
                 "max": row["tardiness_max_ns"]}
            for wl, row in results.items()
            if "tardiness_p99_ns" in row}
    if tard:
        final["tardiness_ns"] = tard
    # capacity plane session record (docs/OBSERVABILITY.md "Capacity
    # plane"): compile/retrace totals over every instrumented jit
    # cache, per-workload projections + roofline verdicts, and the
    # detected device budget -- the full capacity record the next
    # silicon session captures with zero extra flags
    try:
        from dmclock_tpu.obs import (capacity as obscap,
                                     default_registry,
                                     publish_compile_metrics)
        from dmclock_tpu.obs.capacity import publish_capacity_metrics
        cp = _cplane.plane()
        final["compile"] = cp.totals()
        publish_compile_metrics(default_registry())
        budget = obscap.device_hbm_budget()
        cap_block = {}
        if budget is not None:
            cap_block["budget_bytes"] = int(budget)
        for wl, row in results.items():
            if "projected_hbm_bytes" in row:
                cap_block.setdefault("projected_hbm_bytes", {})[wl] = \
                    row["projected_hbm_bytes"]
                publish_capacity_metrics(
                    default_registry(),
                    projected_bytes=row["projected_hbm_bytes"],
                    budget_bytes=budget, workload=wl)
            if "bound_class" in row:
                cap_block.setdefault("bound_class", {})[wl] = \
                    row["bound_class"]
            if "compile_ms_total" in row:
                cap_block.setdefault("compile_ms_total", {})[wl] = \
                    row["compile_ms_total"]
                cap_block.setdefault("retraces", {})[wl] = \
                    row.get("retraces", 0)
        if cap_block:
            final["capacity"] = cap_block
    except Exception as e:   # the capacity record must never eat the
        final["capacity_error"] = f"{type(e).__name__}: {e}"  # line
    emit(final)


def _record_history(results: dict, fault_plan: str = "none",
                    supervised: bool = False, restarts: int = 0,
                    ladder_steps=None,
                    controller: str = "off") -> None:
    """Append this session's rates to benchmark/history/ for the
    drift-aware regression guard (scripts/bench_guard.py).  CPU
    (backend-fallback) sessions are recorded too, tagged
    ``"fallback": true`` so the trajectory stays unbroken -- the guard
    annotates them and keeps them out of the accelerator medians.
    ``fault_plan`` != "none" marks a chaos session: recorded for the
    trajectory, excluded from the clean-run medians.  ``supervised``
    / ``restarts`` mark a session run under robust.supervisor: a
    restart-bearing run's wall time includes recovery (resume +
    replay), so the guard excludes it the same way.  ``controller``
    != "off" marks a closed-loop controller A/B session
    (docs/CONTROLLER.md): the on-twin's wall time includes actuation
    recompiles, so the guard keeps controller-actuated sessions out
    of the clean medians while the trajectory stays recorded."""
    from pathlib import Path

    if not results:
        return
    platform = jax.devices()[0].platform
    hist = Path(__file__).resolve().parent / "benchmark" / "history"
    hist.mkdir(parents=True, exist_ok=True)
    rec = {
        "platform": platform,
        "device": str(jax.devices()[0]),
        "fault_plan": fault_plan,
        # scalars AND tags: select_impl / bounded_by are strings the
        # guard needs (separate per-impl series; stall attribution)
        "workloads": {
            wl: {k: v for k, v in row.items()
                 if isinstance(v, (int, float, str, bool))}
            for wl, row in results.items()},
    }
    if supervised:
        rec["supervised"] = True
        rec["restarts"] = int(restarts)
    if controller != "off":
        rec["controller"] = controller
    if ladder_steps:
        rec["degradation_ladder"] = ladder_steps
    if platform == "cpu":
        rec["fallback"] = True
    out = hist / f"bench_{int(time.time())}.json"
    out.write_text(json.dumps(rec, indent=1))
    print(f"# recorded {out.relative_to(hist.parent.parent)}",
          file=__import__('sys').stderr)


if __name__ == "__main__":
    main()
