#!/usr/bin/env python
"""Headline benchmark: dmClock scheduling decisions/sec at 100k clients.

Preloads a 100k-client engine state (uniform reservation, mixed weights
-- BASELINE.json config #3 shape), then times ``scan_fast_epoch``
(speculative batched serving, bit-identical to the serial engine --
``tests/test_fastpath.py``) in steady weight-regime state, with the
production recovery loop: after each epoch the host checks the commit
mask and, if speculation failed, reruns one exact serial k-batch from
the stalled state before resuming epochs.  Both the epochs and any
serial recoveries are inside the timed region.

Timing method: the decision stream is produced into device memory
(slot/phase/cost arrays per epoch); compute is serialized by a
device_get of a scalar digest that data-depends on every batch
(block_until_ready alone has proven unreliable through the tunneled
runtime).  The per-epoch ok-mask fetch costs one host round-trip; its
measured latency is subtracted (on non-tunneled hardware it is
microseconds).  The bulk decision readback is NOT timed: on the
tunneled dev runtime the host link adds ~100 ms + ~150 ms/MB per
fetch, which measures the tunnel, not the scheduler.

Prints ONE json line; ``vs_baseline`` is the ratio to the BASELINE.json
north-star target of 10M decisions/sec/chip.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine import kernels
    from dmclock_tpu.engine.fastpath import scan_fast_epoch
    from profile_util import scalar_latency, state_digest

    n_clients = 100_000
    depth = 128
    batch = 32768      # decisions per speculative batch
    epoch_m = 32       # batches per launch
    epochs = 6
    state = _preloaded_state(n_clients, depth, ring=depth)

    # donate the state so XLA aliases the (unmodified) 400MB tail rings
    # instead of copying them into the output each epoch
    run = jax.jit(functools.partial(
        scan_fast_epoch, m=epoch_m, k=batch, anticipation_ns=0),
        donate_argnums=(0,))
    serial = jax.jit(lambda s, t: kernels.engine_run(
        s, t, batch, allow_limit_break=False, anticipation_ns=0,
        advance_now=False))

    # compile + warm both paths; measure host round-trip latency
    _ = serial(state, jnp.int64(0))          # compile the recovery path
    ep = run(state, jnp.int64(0))
    jax.device_get(state_digest(ep.state))
    state = ep.state
    latency = scalar_latency()

    t0 = time.perf_counter()
    ep0 = None          # first epoch kept for the untimed sanity check
    n_committed = 0
    n_serial_decisions = 0
    n_serial = 0
    n_roundtrips = 0
    for _ in range(epochs):
        ep = run(state, jnp.int64(0))
        state = ep.state
        if ep0 is None:
            ep0 = ep
        ok = jax.device_get(ep.ok)          # one round-trip per epoch
        n_roundtrips += 1
        n_committed += int(ok.sum())
        if not ok.all():
            # speculation stalled: one exact serial k-batch recovers;
            # count only decisions that actually RETURNING-served
            state, _, decs = serial(state, jnp.int64(0))
            n_serial_decisions += int(
                jax.device_get((decs.type == kernels.RETURNING).sum()))
            n_roundtrips += 1
            n_serial += 1
    jax.device_get(state_digest(state))
    n_roundtrips += 1
    elapsed = time.perf_counter() - t0 - latency * n_roundtrips

    total = n_committed * batch + n_serial_decisions
    n_batches = epochs * epoch_m
    fallback_rate = 1.0 - n_committed / n_batches

    # sanity (untimed, falsifiable): within each committed batch of the
    # first epoch every served slot must be distinct (one serve per
    # client per batch is a speculation invariant)
    ok0 = jax.device_get(ep0.ok)
    slot0 = jax.device_get(ep0.slot)
    for i in range(len(ok0)):
        if ok0[i]:
            assert len(np.unique(slot0[i])) == batch, \
                f"batch {i}: duplicate slots in committed batch"

    dps = total / elapsed
    print(json.dumps({
        "metric": "dmclock scheduling decisions/sec @100k clients "
                  f"(k={batch}, m={epoch_m}, {total} decisions, "
                  f"fallback_rate={fallback_rate:.4f}, "
                  f"serial_recoveries={n_serial}, device-compute + "
                  "recovery timed; decision stream resident in HBM, "
                  "bulk readback untimed)",
        "value": round(dps, 1),
        "unit": "decisions/sec/chip",
        "vs_baseline": round(dps / 10_000_000, 4),
    }))


if __name__ == "__main__":
    main()
