#!/usr/bin/env python
"""Headline benchmark: dmClock scheduling decisions/sec at 100k clients.

Preloads a 100k-client engine state (uniform reservation, mixed weights
-- BASELINE.json config #3 shape), then times ``engine_run`` batches in
advance-now mode (infinitely fast server: every launch is pure
scheduling work).  Prints ONE json line; ``vs_baseline`` is the ratio to
the BASELINE.json north-star target of 10M decisions/sec/chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def main() -> None:
    import functools

    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine.fastpath import scan_fast_epoch

    n_clients = 100_000
    depth = 64
    batch = 4096       # decisions per speculative batch
    epoch_m = 32       # batches per launch (one readback per epoch)
    epochs = 4
    state = _preloaded_state(n_clients, depth, ring=depth)

    run = jax.jit(functools.partial(
        scan_fast_epoch, m=epoch_m, k=batch, anticipation_ns=0),
        donate_argnums=0)

    # compile + warm
    ep = run(state, jnp.int64(0))
    jax.block_until_ready(ep.ok)
    state = ep.state

    t0 = time.perf_counter()
    outs = []
    for _ in range(epochs):
        ep = run(state, jnp.int64(0))
        state = ep.state
        outs.append((ep.ok, ep.slot, ep.phase, ep.cost))
    # one blocking readback per epoch, issued after all dispatches so
    # transfers overlap compute
    fetched = [jax.device_get(o) for o in outs]
    elapsed = time.perf_counter() - t0

    n_fast = sum(int(ok.sum()) for ok, *_ in fetched)
    total = n_fast * batch
    assert n_fast == epochs * epoch_m, \
        f"speculation fell back: {n_fast}/{epochs * epoch_m} batches"
    # sanity: decision stream is dense and well-formed
    assert all((s >= 0).all() for _, s, _, _ in fetched)

    dps = total / elapsed
    print(json.dumps({
        "metric": "dmclock scheduling decisions/sec @100k clients"
                  f" ({n_fast * batch} decisions traced)",
        "value": round(dps, 1),
        "unit": "decisions/sec/chip",
        "vs_baseline": round(dps / 10_000_000, 4),
    }))


if __name__ == "__main__":
    main()
