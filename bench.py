#!/usr/bin/env python
"""Headline benchmark: dmClock scheduling decisions/sec at 100k clients.

Preloads a 100k-client engine state (uniform reservation, mixed
weights, staggered tag phases -- BASELINE.json config #3 shape), then
times ``scan_fast_epoch`` (speculative batched serving, bit-identical
to the serial engine -- ``tests/test_fastpath.py``) in steady
weight-regime state.  Epochs are chained asynchronously on device with
a single timed digest sync; commit masks are read back untimed, and
the decision count comes from them exactly (commit-prefix semantics:
a stalled epoch makes later epochs no-ops, degrading the reported rate
honestly -- regime-transition behavior is measured separately in
benchmark/RESULTS.md).

Timing method: the decision stream is produced into device memory
(slot/phase/cost arrays per epoch); compute is serialized by a
device_get of a scalar digest that data-depends on every batch
(block_until_ready alone has proven unreliable through the tunneled
runtime); one scalar round-trip latency is subtracted.  The bulk
decision readback is NOT timed: on the tunneled dev runtime the host
link adds ~100 ms + ~150 ms/MB per fetch, which measures the tunnel,
not the scheduler.

Prints ONE json line; ``vs_baseline`` is the ratio to the BASELINE.json
north-star target of 10M decisions/sec/chip.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    import argparse
    import contextlib

    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="write a jax profiler (xprof) trace of the "
                    "timed region to DIR")
    args = ap.parse_args()
    trace_ctx = (jax.profiler.trace(args.profile) if args.profile
                 else contextlib.nullcontext())

    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine.fastpath import scan_fast_epoch
    from profile_util import scalar_latency, state_digest

    n_clients = 100_000
    depth = 128
    batch = 32768      # decisions per speculative batch
    epoch_m = 32       # batches per launch
    epochs = 6
    state = _preloaded_state(n_clients, depth, ring=depth)

    # donate the state so XLA aliases the (unmodified) 400MB tail rings
    # instead of copying them into the output each epoch
    run = jax.jit(functools.partial(
        scan_fast_epoch, m=epoch_m, k=batch, anticipation_ns=0),
        donate_argnums=(0,))

    # compile + warm; measure host round-trip latency
    ep = run(state, jnp.int64(0))
    jax.device_get(state_digest(ep.state))
    state = ep.state
    latency = scalar_latency()

    # The epochs are chained ASYNCHRONOUSLY (no mid-run readback): a
    # per-epoch ok fetch costs one ~100ms tunnel round-trip against
    # ~100ms of device work, so subtracting it statistically made the
    # result swing by 2x run to run.  Commit-prefix semantics keep the
    # decision count exact without mid-run recovery: if an epoch
    # stalls, later epochs re-attempt from the exact stalled state and
    # commit nothing new, and the reported rate honestly degrades
    # (fallback_rate shows it; the steady-state workload here never
    # stalls -- regime-transition numbers live in benchmark/RESULTS.md).
    t0 = time.perf_counter()
    eps = []
    with trace_ctx:
        for _ in range(epochs):
            ep = run(state, jnp.int64(0))
            state = ep.state
            eps.append(ep)
        jax.device_get(state_digest(state))
    elapsed = time.perf_counter() - t0 - latency

    ep0 = eps[0]
    oks = [jax.device_get(ep.ok) for ep in eps]      # untimed
    n_committed = int(sum(ok.sum() for ok in oks))
    total = n_committed * batch
    n_batches = epochs * epoch_m
    fallback_rate = 1.0 - n_committed / n_batches

    # sanity (untimed, falsifiable): within each committed batch of the
    # first epoch every served slot must be distinct (one serve per
    # client per batch is a speculation invariant)
    ok0 = jax.device_get(ep0.ok)
    slot0 = jax.device_get(ep0.slot)
    for i in range(len(ok0)):
        if ok0[i]:
            assert len(np.unique(slot0[i])) == batch, \
                f"batch {i}: duplicate slots in committed batch"

    dps = total / elapsed
    print(json.dumps({
        "metric": "dmclock scheduling decisions/sec @100k clients "
                  f"(k={batch}, m={epoch_m}, {total} decisions, "
                  f"fallback_rate={fallback_rate:.4f}, epochs chained "
                  "async on device, one digest sync timed; decision "
                  "stream resident in HBM, bulk readback untimed)",
        "value": round(dps, 1),
        "unit": "decisions/sec/chip",
        "vs_baseline": round(dps / 10_000_000, 4),
    }))


if __name__ == "__main__":
    main()
