#!/usr/bin/env bash
# Suite runner with per-file process isolation.
#
# A single long-lived pytest process accumulates XLA CPU compile state;
# before the conftest-level cache clearing this stalled late-suite
# tests (17+ min for a 2-min test) and eventually segfaulted the
# compiler mid-suite.  One process per test file bounds the blast
# radius either way, reports per-file wall time, and fails fast.
set -uo pipefail
cd "$(dirname "$0")/.."
total_start=$(date +%s)
status=0
for f in tests/test_*.py; do
  t0=$(date +%s)
  if ! python -m pytest "$f" -q -p no:cacheprovider; then
    echo "FAILED: $f"
    status=1
    break
  fi
  echo "-- $f: $(( $(date +%s) - t0 ))s"
done
echo "total: $(( $(date +%s) - total_start ))s"
exit $status
