#!/usr/bin/env python
"""Diff two decision-trace JSONL streams; report the FIRST divergence.

The tool behind radix-vs-sort and silicon-parity triage (ROADMAP
trace-diff item): run the same workload twice with ``--trace`` (dmc_sim
/ ssched_sim, any backend pair -- oracle vs TPU engine, python vs
native, sort vs radix), then

    python scripts/trace_diff.py a.jsonl b.jsonl

prints either ``identical`` or ONE line per differing field of the
first divergent decision, with both rows' tag triples when present --
the full context a parity bug needs, without staring at two
million-line traces.

Comparison semantics (schema: ``docs/OBSERVABILITY.md``):

- decisions are compared in stream order, field by field over
  ``t, server, client, phase, cost``;
- ``tag`` participates only when BOTH rows carry one (backends that
  never materialize per-decision tags host-side emit ``null`` -- a
  null-vs-triple pair is not a divergence, but both values are shown
  at any reported divergence);
- a stream ending early is itself a divergence (reported with the
  surviving row).

Exit status: 0 identical, 1 divergent, 2 bad input.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterator, Optional, Tuple

COMPARE_FIELDS = ("t", "server", "client", "phase", "cost")


def rows(path: str) -> Iterator[Tuple[int, dict]]:
    """(line_number, row) pairs; raises ValueError on malformed rows."""
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}")
            if not isinstance(row, dict) or "client" not in row:
                raise ValueError(f"{path}:{i}: not a decision row")
            yield i, row


def _fmt_tag(tag) -> str:
    if tag is None:
        return "tag=null"
    return f"tag=[resv={tag[0]}, prop={tag[1]}, limit={tag[2]}]"


def _fmt_row(name: str, lineno: Optional[int], row: Optional[dict]) -> str:
    if row is None:
        return f"  {name}: <stream ended>"
    fields = " ".join(f"{k}={row.get(k)!r}" for k in COMPARE_FIELDS)
    return f"  {name}:{lineno}: {fields} {_fmt_tag(row.get('tag'))}"


def diff_row(a: dict, b: dict, ignore=()) -> list:
    """Names of fields that diverge between two decision rows."""
    bad = [f for f in COMPARE_FIELDS
           if f not in ignore and a.get(f) != b.get(f)]
    if "tag" not in ignore and \
            a.get("tag") is not None and b.get("tag") is not None \
            and a["tag"] != b["tag"]:
        bad.append("tag")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="report the first divergent decision between two "
                    "--trace JSONL streams")
    ap.add_argument("trace_a")
    ap.add_argument("trace_b")
    ap.add_argument("--ignore", default="server",
                    help="comma-separated fields excluded from the "
                    "comparison (default: server -- cross-backend "
                    "traces rarely share server ids; pass '' to "
                    "compare everything)")
    ap.add_argument("--limit", type=int, default=0,
                    help="stop after N decisions (0 = whole streams)")
    args = ap.parse_args(argv)
    ignore = tuple(f for f in args.ignore.split(",") if f)

    try:
        it_a, it_b = rows(args.trace_a), rows(args.trace_b)
        n = 0
        while True:
            ra = next(it_a, None)
            rb = next(it_b, None)
            if ra is None and rb is None:
                print(f"identical ({n} decisions)")
                return 0
            if ra is None or rb is None:
                short = args.trace_a if ra is None else args.trace_b
                print(f"divergence at decision {n}: {short} ended "
                      f"after {n} decisions")
                print(_fmt_row(args.trace_a,
                               ra[0] if ra else None,
                               ra[1] if ra else None))
                print(_fmt_row(args.trace_b,
                               rb[0] if rb else None,
                               rb[1] if rb else None))
                return 1
            (la, a), (lb, b) = ra, rb
            bad = diff_row(a, b, ignore)
            if bad:
                print(f"divergence at decision {n}: "
                      f"fields {', '.join(bad)} differ")
                print(_fmt_row(args.trace_a, la, a))
                print(_fmt_row(args.trace_b, lb, b))
                return 1
            n += 1
            if args.limit and n >= args.limit:
                print(f"identical ({n} decisions, --limit reached)")
                return 0
    except (OSError, ValueError) as e:
        print(f"trace_diff: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
