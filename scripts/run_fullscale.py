#!/usr/bin/env python
"""CI entry for the full-scale TPU parity gates.

Runs the env-gated minutes-long parity tests with
``DMCLOCK_FULLSCALE=1`` set, on the virtual CPU mesh (same backend
selection as the test suite): the 100x100 acceptance-config sim parity
(``tests/test_sim_tpu_fullscale.py``) and the 8x1000-client cluster
parity for both tracker policies
(``tests/test_cluster_realism.py::test_cluster_parity_fullscale``).
Kept as a separate entry point so the default ``pytest tests/`` stays
fast; ``scripts/ci.sh`` invokes this after the main suite.

Usage: python scripts/run_fullscale.py [extra pytest args]
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, DMCLOCK_FULLSCALE="1")
    cmd = [sys.executable, "-m", "pytest",
           os.path.join(REPO, "tests", "test_sim_tpu_fullscale.py"),
           os.path.join(REPO, "tests", "test_cluster_realism.py"),
           "-q", *sys.argv[1:]]
    return subprocess.call(cmd, cwd=REPO, env=env)


if __name__ == "__main__":
    sys.exit(main())
