#!/usr/bin/env python
"""CI entry for the full-scale TPU parity gates + the MULTICHIP
record (schema v3).

Runs the env-gated minutes-long parity tests with
``DMCLOCK_FULLSCALE=1`` set, on the virtual CPU mesh (same backend
selection as the test suite): the 100x100 acceptance-config sim parity
(``tests/test_sim_tpu_fullscale.py``) and the 8x1000-client cluster
parity for both tracker policies
(``tests/test_cluster_realism.py::test_cluster_parity_fullscale``).
Kept as a separate entry point so the default ``pytest tests/`` stays
fast; ``scripts/ci.sh`` invokes this after the main suite.

``--record FILE`` additionally writes the MULTICHIP record in
**schema v3**: the v1 fields (``n_devices``/``rc``/``ok``/``tail``
from the QoS dryrun, unchanged) plus the v2 ``mesh`` block -- the
mesh serving plane's aggregate-throughput trajectory from one
``bench.py --mode mesh`` run on the forced host mesh: aggregate and
per-shard dec/s, counter-exchange bytes per epoch, and the sync
cadence -- plus the v3 ``rebalance`` block (``--rebalance on``): the
shard-rebalancing A/B row (placement mode, migration count + log,
shard skew before/after, dec/s + decisions recovered) from the same
bench session's ``mesh_rebalance`` output.  :func:`load_multichip`
reads ALL THREE schemas (v1 records have ``schema`` 1 and ``mesh``
None; v2 records have ``rebalance`` None), so history tooling never
breaks on old rounds.

Usage: python scripts/run_fullscale.py [--record FILE]
       [--clients N] [--n-shards S] [--counter-sync-every K]
       [--rebalance on|off] [extra pytest args]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MULTICHIP_SCHEMA = 3


def load_multichip(path: str) -> dict:
    """Backward-compatible MULTICHIP record reader: v1 rounds
    (``MULTICHIP_r01..r05``, no ``schema`` key) normalize to
    ``schema=1, mesh=None, rebalance=None``; v2 carries the mesh
    throughput block (``rebalance`` normalizes to None); v3 adds the
    rebalance block.  Every v1/v2 key keeps its meaning in v3."""
    with open(path) as fh:
        obj = json.load(fh)
    out = {
        "schema": int(obj.get("schema", 1)),
        "n_devices": int(obj.get("n_devices", 0)),
        "rc": int(obj.get("rc", 0)),
        "ok": bool(obj.get("ok", False)),
        "skipped": bool(obj.get("skipped", False)),
        "tail": obj.get("tail", ""),
        "mesh": obj.get("mesh"),
        "rebalance": obj.get("rebalance"),
    }
    if out["schema"] >= 2 and out["mesh"] is not None:
        m = out["mesh"]
        # normalized view of the trajectory scalars (reader contract:
        # these keys exist whenever a v2 mesh block does).  The chaos
        # fields joined in PR-15; pre-chaos v2 records normalize to a
        # clean run -- schema v2 stays backward-compatible
        m.setdefault("dps", 0.0)
        m.setdefault("n_shards", out["n_devices"])
        m.setdefault("counter_sync_every", 1)
        m.setdefault("counter_bytes_per_epoch", 0)
        m.setdefault("fault_plan", "none")
        m.setdefault("fault_dropouts_per_shard", [])
        m.setdefault("fault_resyncs_per_shard", [])
        m.setdefault("faults_injected_total", 0)
    if out["schema"] >= 3 and out["rebalance"] is not None:
        r = out["rebalance"]
        # reader contract for the v3 rebalance block (the
        # bench_mesh_rebalance row): placement mode, migration count
        # + per-move log, skew before/after, recovery currencies
        r.setdefault("placement", "p2c")
        r.setdefault("migrations", 0)
        r.setdefault("migration_log", [])
        r.setdefault("shard_skew_before", 0.0)
        r.setdefault("shard_skew_after", 0.0)
        r.setdefault("recovered_dps", 0.0)
        r.setdefault("recovered_decisions", 0)
    return out


def _dryrun(n_devices: int):
    """The v1 QoS dryrun block: run ``dryrun_multichip`` in a child
    (its own device forcing must precede backend init) and keep its
    stdout tail."""
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n_devices})"],
        cwd=REPO, capture_output=True, text=True)
    tail = (proc.stdout or "")[-4000:]
    if proc.returncode != 0:
        tail += ("\n" + (proc.stderr or "")[-2000:])
    return proc.returncode, tail


def _mesh_trajectory(n_devices: int, clients: int, sync: int,
                     fault_plan: str = "none",
                     rebalance: str = "off"):
    """The v2 mesh block + v3 rebalance block: one ``bench.py --mode
    mesh`` run on a forced host mesh; the bench JSON line carries the
    full mesh row (aggregate + per-shard dec/s, counter-exchange
    accounting, and -- when ``fault_plan`` is a parseable spec -- the
    chaos counters: plan tag + per-shard dropout/resync counts) and,
    under ``--rebalance on``, the ``mesh_rebalance`` A/B row."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "mesh", "--clients", str(clients),
         "--n-shards", str(n_devices),
         "--counter-sync-every", str(sync),
         "--fault-plan", fault_plan,
         "--rebalance", rebalance],
        cwd=REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    for line in reversed((proc.stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
                return (proc.returncode, obj.get("mesh"),
                        obj.get("mesh_rebalance"))
            except json.JSONDecodeError:
                break
    return proc.returncode or 1, None, None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--record", metavar="FILE", default=None,
                    help="write the MULTICHIP schema-v3 record here "
                    "(QoS dryrun block + mesh throughput trajectory "
                    "+ rebalance A/B block under --rebalance on)")
    ap.add_argument("--n-devices", type=int, default=8)
    ap.add_argument("--clients", type=int, default=100_000)
    ap.add_argument("--counter-sync-every", type=int, default=1)
    ap.add_argument("--fault-plan", default="none",
                    help="forwarded to the bench mesh run: a "
                    "parseable spec makes the recorded trajectory a "
                    "CHAOS session (mesh block carries fault_plan + "
                    "per-shard dropout/resync counts)")
    ap.add_argument("--rebalance", choices=["off", "on"],
                    default="off",
                    help="forwarded to the bench mesh run: 'on' adds "
                    "the shard-rebalancing A/B row to the record's "
                    "v3 rebalance block (placement mode, migrations, "
                    "shard skew before/after, dec/s recovered)")
    args, extra = ap.parse_known_args()

    env = dict(os.environ, DMCLOCK_FULLSCALE="1")
    cmd = [sys.executable, "-m", "pytest",
           os.path.join(REPO, "tests", "test_sim_tpu_fullscale.py"),
           os.path.join(REPO, "tests", "test_cluster_realism.py"),
           "-q", *extra]
    rc = subprocess.call(cmd, cwd=REPO, env=env)

    if args.record:
        d_rc, tail = _dryrun(args.n_devices)
        m_rc, mesh, rebal = _mesh_trajectory(
            args.n_devices, args.clients, args.counter_sync_every,
            args.fault_plan, args.rebalance)
        record = {
            "schema": MULTICHIP_SCHEMA,
            "n_devices": args.n_devices,
            "rc": rc or d_rc or m_rc,
            "ok": rc == 0 and d_rc == 0 and m_rc == 0
            and mesh is not None
            and (args.rebalance == "off" or rebal is not None),
            "skipped": False,
            "tail": tail,
            "mesh": mesh,
            "rebalance": rebal,
        }
        with open(args.record, "w") as fh:
            json.dump(record, fh, indent=1)
        print(f"# multichip v3 record -> {args.record} "
              f"(dryrun rc={d_rc}, mesh rc={m_rc}, "
              f"aggregate {0 if not mesh else mesh.get('dps', 0)/1e6:.1f}M dec/s"
              + ("" if not rebal else
                 f", rebalance skew {rebal.get('shard_skew_before', 0):.2f}"
                 f"->{rebal.get('shard_skew_after', 0):.2f} "
                 f"{rebal.get('migrations', 0)} migration(s)") + ")",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
