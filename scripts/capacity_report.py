#!/usr/bin/env python
"""Capacity report: the bench JSON line's capacity record as a table.

Renders the capacity plane's per-workload record (docs/OBSERVABILITY.md
"Capacity plane") from either input shape:

- a **bench output file** (the line ``bench.py`` prints; stderr noise
  and non-JSON lines are skipped, the last JSON object wins) -- reads
  the ``capacity`` / ``compile`` / ``cost_analysis`` / ``spans``
  blocks;
- a **benchmark/history record** (``benchmark/history/bench_*.json``)
  -- reads the per-workload scalars directly.

Columns: compile wall + retraces the workload added, projected
resident HBM, cost_analysis flops / bytes accessed, arithmetic
intensity, measured dispatch share (when spans ran), and the roofline
``bound_class``.  ``--diff BASELINE`` prints per-workload deltas --
the before/after instrument for a compile-time or footprint
regression, same contract as ``trace_report.py --diff``.

Usage:
    python scripts/capacity_report.py BENCH.json [--diff BASELINE.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional


def load_line(path: str) -> dict:
    """Last parseable JSON object in the file (the bench emits one
    line, but logs may surround it; history records are one
    pretty-printed object, so the whole-file parse is tried first)."""
    with open(path) as fh:
        text = fh.read()
    try:
        whole = json.loads(text)
        if isinstance(whole, dict):
            return whole
    except json.JSONDecodeError:
        pass
    obj = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            cand = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(cand, dict):
            obj = cand
    if obj is None:
        raise ValueError(f"{path}: no JSON object found")
    return obj


def workload_rows(obj: dict) -> Dict[str, dict]:
    """Normalize either input shape to {workload: scalars}."""
    if "workloads" in obj:          # a history record
        return {wl: dict(row) for wl, row in obj["workloads"].items()}
    rows: Dict[str, dict] = {}
    cap = obj.get("capacity") or {}
    for field in ("projected_hbm_bytes", "bound_class",
                  "compile_ms_total", "retraces"):
        for wl, v in (cap.get(field) or {}).items():
            rows.setdefault(wl, {})[field] = v
    for wl, ca in (obj.get("cost_analysis") or {}).items():
        if isinstance(ca, dict):
            rows.setdefault(wl, {}).update(
                {k: v for k, v in ca.items()
                 if k in ("flops", "bytes_accessed")})
    for wl, sp in (obj.get("spans") or {}).items():
        if isinstance(sp, dict):
            row = rows.setdefault(wl, {})
            d = sp.get("dispatch_ms_per_launch")
            dev = sp.get("device_ms_per_launch")
            if d is not None and dev is not None and (d + dev) > 0:
                row["dispatch_share"] = d / (d + dev)
    return rows


def _mib(v) -> str:
    return f"{v / 2**20:.1f}M" if v is not None else "-"


def _num(v, fmt="{:.0f}") -> str:
    return fmt.format(v) if v is not None else "-"


def render(rows: Dict[str, dict], totals: Optional[dict],
           budget: Optional[int], out=sys.stdout) -> None:
    hdr = (f"{'workload':<24} {'compile_ms':>10} {'retrace':>7} "
           f"{'proj_hbm':>9} {'flops':>10} {'bytes':>10} "
           f"{'AI':>6} {'disp%':>6}  bound_class")
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    for wl in sorted(rows):
        r = rows[wl]
        flops = r.get("flops")
        byts = r.get("bytes_accessed")
        ai = (flops / byts) if flops and byts else None
        share = r.get("dispatch_share")
        print(f"{wl:<24} "
              f"{_num(r.get('compile_ms_total')):>10} "
              f"{_num(r.get('retraces')):>7} "
              f"{_mib(r.get('projected_hbm_bytes')):>9} "
              f"{_num(flops, '{:.2e}'):>10} "
              f"{_num(byts, '{:.2e}'):>10} "
              f"{_num(ai, '{:.2f}'):>6} "
              f"{_num(share * 100 if share is not None else None):>6} "
              f" {r.get('bound_class', '-')}"
              + ("  [CAPACITY-SKIPPED]"
                 if r.get("capacity_skipped") else ""),
              file=out)
    if totals:
        print(f"\ncompile totals: {totals.get('entries', 0)} cache "
              f"entries, {totals.get('compiles', 0)} compiles "
              f"({totals.get('retraces', 0)} retraces), "
              f"{totals.get('compile_ms_total', 0):.0f}ms compile + "
              f"{totals.get('lower_ms_total', 0):.0f}ms lower, "
              f"{totals.get('dispatch_fallbacks', 0)} dispatch "
              "fallbacks", file=out)
    if budget is not None:
        print(f"device HBM budget: {budget / 2**30:.2f} GiB",
              file=out)


def render_diff(rows: Dict[str, dict], base: Dict[str, dict],
                out=sys.stdout) -> None:
    hdr = (f"{'workload':<24} {'d compile_ms':>12} {'d retrace':>9} "
           f"{'d proj_hbm':>11}  bound_class")
    print("\n-- diff vs baseline --", file=out)
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    for wl in sorted(set(rows) | set(base)):
        a, b = rows.get(wl), base.get(wl)
        if a is None or b is None:
            print(f"{wl:<24} {'(only in ' + ('new' if b is None else 'baseline') + ')':>12}",
                  file=out)
            continue

        def delta(key):
            x, y = a.get(key), b.get(key)
            if x is None or y is None:
                return None
            return x - y

        dc = delta("compile_ms_total")
        dr = delta("retraces")
        dh = delta("projected_hbm_bytes")
        bc_a = a.get("bound_class", "-")
        bc_b = b.get("bound_class", "-")
        bc = bc_a if bc_a == bc_b else f"{bc_b} -> {bc_a}"
        print(f"{wl:<24} "
              f"{_num(dc, '{:+.0f}'):>12} "
              f"{_num(dr, '{:+.0f}'):>9} "
              f"{(_num(dh / 2**20, '{:+.1f}M') if dh is not None else '-'):>11}"
              f"  {bc}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="capacity-plane report from a bench JSON line or "
                    "history record")
    ap.add_argument("bench", help="bench output file or "
                    "benchmark/history record")
    ap.add_argument("--diff", metavar="BASELINE", default=None,
                    help="baseline file to diff against")
    args = ap.parse_args(argv)

    obj = load_line(args.bench)
    rows = workload_rows(obj)
    if not rows:
        print(f"{args.bench}: no capacity record (run bench.py with "
              "the capacity plane on)", file=sys.stderr)
        return 1
    render(rows, obj.get("compile"),
           (obj.get("capacity") or {}).get("budget_bytes"))
    if args.diff:
        render_diff(rows, workload_rows(load_line(args.diff)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
