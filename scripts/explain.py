#!/usr/bin/env python
"""explain.py -- offline "why" forensics for one client's SLO windows.

Joins the three decision-provenance artifacts a run leaves behind
(docs/OBSERVABILITY.md "Provenance plane"):

- the **SLO window log** (``--slo``): judged closed-window JSONL rows
  from ``SloPlane.export_jsonl`` (the supervisor's ``slo_log``, the
  bench's per-chain rolls, or ``scripts/slo_report.py``'s input);
- the **flight ring dump** (``--flight``, optional): the HBM black
  box's last-R commit records (``obs.flight``), now carrying the
  provenance ``margin``/``gate`` columns;
- the **decision trace** (``--trace``, optional): schema-v2 JSONL
  (``obs.trace``; v1 rows load with nulls).

and answers ``--client C [--window W]`` with a RANKED causal
attribution of the client's delivered-vs-contract behavior:

    limit_capped        delivered rate pinned at the limit ceiling
                        while demand remained (backlog/tardiness)
    out_competed        eligible and backlogged, but the delivered
                        cost share fell short of the weight
                        entitlement -- lost the proportional race
    reservation_tardy   constraint-phase serves landed past their
                        reservation deadlines / the floor ran a
                        deficit with demand present
    no_demand           nothing delivered because nothing was asked
                        (zero ops AND zero backlog): not a violation

Each cause gets a [0, 1] score from the window rows, with the flight
ring and trace contributing corroborating evidence (limit-gate
pressure, margin tightness).  ``--diff BASELINE`` re-runs the
attribution against a baseline run's window log and prints the score
deltas -- "what changed between these two runs for this client".

Exit status: 0 on success, 2 when the client has no windows in the
log (nothing to explain).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

CAUSES = ("limit_capped", "out_competed", "reservation_tardy",
          "no_demand")


def load_jsonl(path: str) -> List[dict]:
    rows = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                obj = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                rows.append(obj)
    return rows


def client_windows(rows: List[dict], client: int,
                   window: Optional[int] = None) -> List[dict]:
    out = [r for r in rows if r.get("client") == client
           and "ops" in r]
    if window is not None:
        out = [r for r in out if r.get("seq") == window]
    return out


def _mean(vals):
    vals = list(vals)
    return sum(vals) / len(vals) if vals else 0.0


def flight_evidence(rows: List[dict], client: int) -> dict:
    """Corroborating signals from the flight ring: the client's own
    commit records (phase mix, margin tightness) and the global
    limit-gate pressure the ring observed."""
    mine = [r for r in rows if r.get("client") == client]
    margins = [r["margin"] for r in rows
               if r.get("margin", -1) is not None
               and r.get("margin", -1) >= 0]
    my_margins = [r["margin"] for r in mine
                  if r.get("margin", -1) is not None
                  and r.get("margin", -1) >= 0]
    gates = [r["gate"] for r in rows if r.get("gate") is not None]
    return {
        "records": len(rows), "client_records": len(mine),
        "client_resv_frac": _mean(1.0 if r.get("cls") == 0 else 0.0
                                  for r in mine),
        "margin_mean_ns": _mean(margins),
        "client_margin_mean_ns": _mean(my_margins),
        "gate_mean": _mean(gates),
        "gate_max": max(gates) if gates else 0,
    }


def trace_evidence(rows: List[dict], client: int) -> dict:
    mine = [r for r in rows if r.get("client") == client]
    gates = [r["gate"] for r in rows if r.get("gate") is not None]
    depths = [r["eligible_depth"] for r in rows
              if r.get("eligible_depth") is not None]
    return {
        "rows": len(rows), "client_rows": len(mine),
        "client_resv_frac": _mean(
            1.0 if r.get("phase") == "reservation" else 0.0
            for r in mine),
        "gate_mean": _mean(gates),
        "eligible_depth_mean": _mean(depths),
    }


def attribute(wins: List[dict], flight: Optional[dict] = None,
              trace: Optional[dict] = None) -> dict:
    """Score the four causes over one client's window rows (see
    module doc); returns ``{"scores", "ranked", "cause",
    "evidence"}``."""
    ops = _mean(w["ops"] for w in wins)
    backlog = _mean(w.get("backlog", 0) for w in wins)
    rate = _mean(w.get("rate", 0.0) for w in wins)
    limit = _mean(w.get("limit", 0.0) for w in wins)
    resv = _mean(w.get("reservation", 0.0) for w in wins)
    share_err = _mean(w.get("share_err", 0.0) for w in wins)
    entitled = _mean(w.get("entitled_share", 0.0) for w in wins)
    resv_ops = sum(w.get("resv_ops", 0) for w in wins)
    tardy_ops = sum(w.get("tardy_ops", 0) for w in wins)
    resv_deficit = _mean(w.get("resv_deficit", 0.0) for w in wins)
    any_miss = any(w.get("resv_miss") for w in wins)
    demand = backlog > 0 or tardy_ops > 0

    evidence: List[str] = []
    scores = {c: 0.0 for c in CAUSES}

    share = _mean(w.get("share", 0.0) for w in wins)
    # the client's own row carries enough to reconstruct the window's
    # delivered total (rate / share), hence its ENTITLED absolute
    # rate -- the counterfactual the limit is capping
    total_rate = rate / share if share > 0 else 0.0
    entitled_abs = entitled * total_rate

    if ops == 0 and backlog == 0:
        scores["no_demand"] = 1.0
        evidence.append("zero delivered ops AND zero backlog at "
                        "every close: the client asked for nothing")
    if limit > 0 and rate >= 0.4 * limit and demand:
        base = min(rate / limit, 1.0)
        if entitled_abs > limit:
            # the weight entitlement EXCEEDS the ceiling: whatever the
            # tag-spacing quantization delivered, the limit -- not the
            # proportional race -- is the binding constraint
            base = max(base, 0.8)
            evidence.append(
                f"entitled rate {entitled_abs:.1f}/s exceeds the "
                f"{limit:.1f}/s limit ceiling: the limit binds")
        scores["limit_capped"] = base
        evidence.append(
            f"delivered rate {rate:.1f}/s against a {limit:.1f}/s "
            f"limit ceiling with demand remaining "
            f"(backlog {backlog:.1f})")
        if flight and flight["gate_mean"] > 0:
            scores["limit_capped"] = min(
                scores["limit_capped"] + 0.1, 1.0)
            evidence.append(
                f"flight ring corroborates: {flight['gate_mean']:.1f}"
                " clients limit-gated per recorded batch "
                f"(max {flight['gate_max']})")
    if resv > 0:
        tardy_frac = tardy_ops / max(resv_ops, 1)
        deficit_frac = min(resv_deficit / resv, 1.0)
        s = max(deficit_frac, tardy_frac)
        if s > 0:
            scores["reservation_tardy"] = s * (1.0 if any_miss
                                               else 0.6)
            evidence.append(
                f"{tardy_ops}/{max(resv_ops, 1)} constraint serves "
                f"landed past their reservation deadline; floor "
                f"deficit {resv_deficit:.2f}/s of {resv:.1f}/s"
                + (" (judged resv_miss)" if any_miss else ""))
    if entitled > 0 and share_err < -0.05 and \
            scores["limit_capped"] < 0.5:
        scores["out_competed"] = min(-share_err, 1.0) * \
            (1.0 if backlog > 0 else 0.4)
        evidence.append(
            f"delivered cost share ran {-100 * share_err:.0f}% below "
            f"the weight entitlement ({entitled:.3f}) with "
            + ("backlog queued" if backlog > 0 else "no backlog"))
        if flight and 0 < flight["client_margin_mean_ns"] \
                < flight["margin_mean_ns"]:
            evidence.append(
                "flight ring corroborates: the client's own wins "
                f"were tight (mean margin "
                f"{flight['client_margin_mean_ns']:.0f} ns vs "
                f"{flight['margin_mean_ns']:.0f} ns overall) -- a "
                "contested proportional race")
    if trace and trace["rows"]:
        resv_pct = 100 * trace["client_resv_frac"]
        evidence.append(
            f"trace: {trace['client_rows']}/{trace['rows']} decisions"
            f" were this client's ({resv_pct:.0f}% constraint-phase)")

    order = {c: i for i, c in enumerate(CAUSES)}
    ranked = sorted(scores, key=lambda c: (-scores[c], order[c]))
    # an honest null: when no cause scores, the windows are conforming
    # (delivered ~ entitled, floor met, limit respected) -- reporting
    # a tie-broken cause here would invent a violation
    cause = ranked[0] if scores[ranked[0]] > 0 else "conforming"
    if cause == "conforming" and not evidence:
        evidence.append("no cause scored: delivered tracked the "
                        "contract in every window examined")
    return {"scores": {c: round(scores[c], 4) for c in CAUSES},
            "ranked": ranked, "cause": cause,
            "windows": len(wins), "evidence": evidence}


def explain(slo_path: str, client: int, *,
            window: Optional[int] = None,
            flight_path: Optional[str] = None,
            trace_path: Optional[str] = None) -> Optional[dict]:
    """The full join for one run; None when the client has no
    windows in the log."""
    wins = client_windows(load_jsonl(slo_path), client, window)
    if not wins:
        return None
    fl = flight_evidence(load_jsonl(flight_path), client) \
        if flight_path else None
    tr = None
    if trace_path:
        from dmclock_tpu.obs.trace import load_trace
        tr = trace_evidence(load_trace(trace_path), client)
    out = attribute(wins, fl, tr)
    out["client"] = client
    out["window"] = window
    if fl:
        out["flight"] = fl
    if tr:
        out["trace"] = tr
    return out


def _fmt(res: dict) -> str:
    lines = [f"client {res['client']}"
             + (f" window {res['window']}" if res["window"] is not None
                else f" ({res['windows']} windows)")
             + f": {res['cause']}"]
    for c in res["ranked"]:
        bar = "#" * int(20 * res["scores"][c])
        lines.append(f"  {c:<18} {res['scores'][c]:6.3f} {bar}")
    lines.append("evidence:")
    for e in res["evidence"]:
        lines.append(f"  - {e}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="explain", description=__doc__.splitlines()[0])
    ap.add_argument("--slo", required=True, metavar="JSONL",
                    help="judged closed-window log "
                    "(SloPlane.export_jsonl / supervisor slo_log)")
    ap.add_argument("--client", required=True, type=int)
    ap.add_argument("--window", type=int, default=None, metavar="SEQ",
                    help="restrict to one roll seq (default: "
                    "aggregate every window of the client)")
    ap.add_argument("--flight", metavar="JSONL", default=None,
                    help="flight ring dump (obs.flight.flight_dump)")
    ap.add_argument("--trace", metavar="JSONL", default=None,
                    help="decision trace (obs.trace, v1 or v2)")
    ap.add_argument("--diff", metavar="BASELINE_SLO", default=None,
                    help="baseline run's window log: print score "
                    "deltas vs it")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    res = explain(args.slo, args.client, window=args.window,
                  flight_path=args.flight, trace_path=args.trace)
    if res is None:
        print(f"explain: client {args.client} has no windows in "
              f"{args.slo}", file=sys.stderr)
        return 2
    if args.diff:
        base = explain(args.diff, args.client, window=args.window)
        res["diff"] = None
        if base is not None:
            res["diff"] = {
                "baseline_cause": base["cause"],
                "deltas": {c: round(res["scores"][c]
                                    - base["scores"][c], 4)
                           for c in CAUSES}}
    if args.json:
        print(json.dumps(res, indent=1))
        return 0
    print(_fmt(res))
    if args.diff:
        if res.get("diff") is None:
            print(f"diff vs baseline: client {args.client} absent "
                  "from the baseline log")
        else:
            d = res["diff"]
            print(f"diff vs baseline (was: {d['baseline_cause']}):")
            for c in CAUSES:
                delta = d["deltas"][c]
                if delta:
                    print(f"  {c:<18} {delta:+.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
