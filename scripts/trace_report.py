#!/usr/bin/env python
"""Span-trace attribution report + before/after diff.

Turns a span file (Chrome trace-event JSON from ``--trace-out``, or
the tracer's raw JSONL, e.g. a supervisor ``span_log``) into the
PROFILE.md-style attribution table the hand-run dispatch-tax
experiments (findings 17-18) produced manually:

    python scripts/trace_report.py trace.json

prints per-(name, category) rows -- count, total / self time, mean,
and per-span duration percentiles -- sorted by self time, the
per-category rollup, and the **dispatch-vs-compute ratio** (host
``dispatch`` self-time over ``device_compute`` self-time: how many
seconds of launching the run paid per second of device work).

    python scripts/trace_report.py after.json --diff before.json

diffs two trace files by (name, category): delta count / total / self
/ mean per row plus the ratio shift -- the before/after tool for the
streaming-serve-loop refactor (ROADMAP #1): run the same bench with
``--trace-out`` on both sides and the diff prices exactly what the
restructuring bought.

Exit status: 0 ok, 2 bad input.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Dict, List, Tuple

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dmclock_tpu.obs.spans import CATEGORIES                 # noqa: E402
from dmclock_tpu.obs.trace_export import (load_rows,         # noqa: E402
                                          rows_self_times)


def _percentile(sorted_vals: List[int], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return float(sorted_vals[idx])


def aggregate(rows: List[dict]) -> Dict[Tuple[str, str], dict]:
    """(name, cat) -> {count, total_ns, self_ns, durs (sorted)};
    self time from the canonical ``trace_export.rows_self_times``
    sweep (recorded ``self`` fields trusted, Chrome rows swept)."""
    selfs = rows_self_times(rows)
    agg: Dict[Tuple[str, str], dict] = {}
    for r, self_ns in zip(rows, selfs):
        key = (r["name"], r.get("cat", "?"))
        a = agg.setdefault(key, {"count": 0, "total_ns": 0,
                                 "self_ns": 0, "durs": []})
        a["count"] += 1
        a["total_ns"] += r["dur"]
        a["self_ns"] += self_ns
        a["durs"].append(r["dur"])
    for a in agg.values():
        a["durs"].sort()
    return agg


def cat_rollup(agg) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for (_name, cat), a in agg.items():
        c = out.setdefault(cat, {"count": 0, "self_ns": 0})
        c["count"] += a["count"]
        c["self_ns"] += a["self_ns"]
    return out


def dispatch_ratio(cats: Dict[str, dict]) -> float:
    """dispatch self-time per unit of device_compute self-time; inf
    (represented as -1) when no device time was observed."""
    dev = cats.get("device_compute", {}).get("self_ns", 0)
    disp = cats.get("dispatch", {}).get("self_ns", 0)
    return disp / dev if dev else (-1.0 if disp else 0.0)


def _ms(ns: float) -> str:
    return f"{ns / 1e6:.2f}"


def print_report(path: str, agg, cats, top: int,
                 decisions: int = 0) -> None:
    """``decisions`` > 0 adds the per-decision AMORTIZED column
    (self_ns / decisions): under the streaming serve loop one launch
    covers a whole chunk of rounds, so per-LAUNCH dispatch numbers
    stop being comparable across loop modes -- per-decision cost is
    the loop-structure-independent currency
    (docs/OBSERVABILITY.md)."""
    print(f"== span attribution: {path} ==")
    amort = f" {'ns/dec':>8}" if decisions else ""
    print(f"{'name':<28} {'cat':<14} {'count':>8} {'total ms':>10} "
          f"{'self ms':>10} {'mean us':>9} {'p50 us':>8} {'p90 us':>8} "
          f"{'p99 us':>8}" + amort)
    ranked = sorted(agg.items(), key=lambda kv: -kv[1]["self_ns"])
    for (name, cat), a in ranked[:top]:
        durs = a["durs"]
        mean_us = a["total_ns"] / max(a["count"], 1) / 1e3
        row = (f"{name:<28} {cat:<14} {a['count']:>8} "
               f"{_ms(a['total_ns']):>10} {_ms(a['self_ns']):>10} "
               f"{mean_us:>9.1f} "
               f"{_percentile(durs, 0.50) / 1e3:>8.1f} "
               f"{_percentile(durs, 0.90) / 1e3:>8.1f} "
               f"{_percentile(durs, 0.99) / 1e3:>8.1f}")
        if decisions:
            row += f" {a['self_ns'] / decisions:>8.1f}"
        print(row)
    if len(ranked) > top:
        print(f"  ... {len(ranked) - top} more rows (--top)")
    print("-- categories (self time) --")
    total = sum(c["self_ns"] for c in cats.values()) or 1
    for cat in CATEGORIES:
        if cat in cats:
            c = cats[cat]
            line = (f"  {cat:<16} {_ms(c['self_ns']):>10} ms "
                    f"({100.0 * c['self_ns'] / total:5.1f}%)  "
                    f"{c['count']} spans")
            if decisions:
                line += f"  {c['self_ns'] / decisions:.1f} ns/dec"
            print(line)
    ratio = dispatch_ratio(cats)
    label = "inf (no device spans)" if ratio < 0 else f"{ratio:.3f}"
    print(f"dispatch-vs-compute ratio: {label} "
          "(host dispatch self-time / device_compute self-time)")
    if decisions:
        disp = cats.get("dispatch", {}).get("self_ns", 0)
        print(f"dispatch amortized: {disp / decisions:.1f} "
              f"ns/decision over {decisions} decisions (one launch "
              "may cover a whole stream chunk; per-decision cost is "
              "the loop-independent comparison)")


def print_diff(path_a: str, path_b: str, agg_a, agg_b, top: int,
               decisions: int = 0) -> None:
    """``path_a`` is the AFTER file, ``path_b`` the baseline.
    ``decisions`` > 0 adds the per-decision amortized delta column --
    the round-vs-stream A/B covers the SAME decision count on both
    sides by construction (stream is digest-pinned to round), so one
    N amortizes both."""
    print(f"== span diff: {path_a} vs baseline {path_b} ==")
    keys = set(agg_a) | set(agg_b)
    zero = {"count": 0, "total_ns": 0, "self_ns": 0, "durs": []}
    rows = []
    for k in keys:
        a, b = agg_a.get(k, zero), agg_b.get(k, zero)
        rows.append((k, a["self_ns"] - b["self_ns"], a, b))
    rows.sort(key=lambda r: -abs(r[1]))
    amort = f" {'d ns/dec':>9}" if decisions else ""
    print(f"{'name':<28} {'cat':<14} {'d count':>8} {'d total ms':>11} "
          f"{'d self ms':>10} {'d mean us':>10}" + amort)
    for (name, cat), dself, a, b in rows[:top]:
        mean_a = a["total_ns"] / max(a["count"], 1) / 1e3
        mean_b = b["total_ns"] / max(b["count"], 1) / 1e3
        row = (f"{name:<28} {cat:<14} {a['count'] - b['count']:>+8} "
               f"{(a['total_ns'] - b['total_ns']) / 1e6:>+11.2f} "
               f"{dself / 1e6:>+10.2f} {mean_a - mean_b:>+10.1f}")
        if decisions:
            row += f" {dself / decisions:>+9.1f}"
        print(row)
    ca, cb = cat_rollup(agg_a), cat_rollup(agg_b)
    ra, rb = dispatch_ratio(ca), dispatch_ratio(cb)
    fmt = lambda r: "inf" if r < 0 else f"{r:.3f}"  # noqa: E731
    print(f"dispatch-vs-compute ratio: {fmt(rb)} -> {fmt(ra)}")
    if decisions:
        da = ca.get("dispatch", {}).get("self_ns", 0) / decisions
        db = cb.get("dispatch", {}).get("self_ns", 0) / decisions
        print(f"dispatch amortized: {db:.1f} -> {da:.1f} ns/decision "
              f"over {decisions} decisions")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="span-trace attribution report "
                    "(Chrome trace JSON or span JSONL)")
    ap.add_argument("trace", help="trace file (--trace-out JSON or "
                    "span_log JSONL)")
    ap.add_argument("--diff", metavar="BASELINE", default=None,
                    help="diff against a baseline trace (before/after "
                    "tool: TRACE is the after side)")
    ap.add_argument("--top", type=int, default=20,
                    help="rows to print (default 20)")
    ap.add_argument("--decisions", type=int, default=0, metavar="N",
                    help="decisions the trace covers: adds the "
                    "per-decision amortized column (self_ns / N) -- "
                    "the loop-structure-independent cost view when "
                    "one stream launch covers a whole chunk of "
                    "rounds (docs/OBSERVABILITY.md).  With --diff, N "
                    "amortizes BOTH sides, so the two traces must "
                    "cover the same decision count (true for the "
                    "digest-pinned round-vs-stream A/B; meaningless "
                    "for runs of different lengths)")
    args = ap.parse_args(argv)

    try:
        rows = load_rows(args.trace)
        if not rows:
            print(f"trace_report: {args.trace}: no spans",
                  file=sys.stderr)
            return 2
        agg = aggregate(rows)
        if args.diff:
            base = aggregate(load_rows(args.diff))
            print_diff(args.trace, args.diff, agg, base, args.top,
                       decisions=max(args.decisions, 0))
        else:
            print_report(args.trace, agg, cat_rollup(agg), args.top,
                         decisions=max(args.decisions, 0))
        return 0
    except (OSError, ValueError, KeyError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
