#!/usr/bin/env python
"""Offline SLO conformance report from a closed-window JSONL export.

Reads the judged closed-window stream a supervised run appends via
``EpochJob(slo_log=...)`` (or ``SloPlane.export_jsonl``) and renders
the delivered-vs-contract verdict per ``(client, contract_epoch)``
series -- the trace_report of the SLO plane:

    python scripts/slo_report.py RUN.slo.jsonl
    python scripts/slo_report.py RUN.slo.jsonl --diff BASELINE.jsonl
    python scripts/slo_report.py RUN.slo.jsonl --client 7 --limit 40

Per series the table shows windows, delivered ops/rate, reservation
misses, worst/mean share error, limit excess, and mean reservation
tardiness.  ``--diff`` prints per-series deltas of the violation
counts and share errors against a baseline export (e.g. before/after
a scheduler change, or --slo runs of two engine loops).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dmclock_tpu.obs.slo import load_windows_jsonl  # noqa: E402


def _series(rows):
    """Group judged rows by (client, contract_epoch), in first-seen
    order, computing the per-series aggregate the table prints."""
    out = {}
    for r in rows:
        key = (int(r.get("client", -1)),
               int(r.get("contract_epoch", 0)))
        s = out.setdefault(key, {
            "windows": 0, "ops": 0, "cost": 0, "resv_ops": 0,
            "tardy_ops": 0, "lb_ops": 0, "resv_miss": 0,
            "share_errs": [], "limit_excess": 0.0, "tard_means": [],
            "reservation": r.get("reservation", 0.0),
            "weight": r.get("weight", 0.0),
            "limit": r.get("limit", 0.0),
            "rate_sum": 0.0,
        })
        s["windows"] += 1
        s["ops"] += int(r.get("ops", 0))
        s["cost"] += int(r.get("cost", 0))
        s["resv_ops"] += int(r.get("resv_ops", 0))
        s["tardy_ops"] += int(r.get("tardy_ops", 0))
        s["lb_ops"] += int(r.get("lb_ops", 0))
        s["resv_miss"] += int(bool(r.get("resv_miss")))
        s["rate_sum"] += float(r.get("rate", 0.0))
        if r.get("entitled_share", 0) > 0:
            s["share_errs"].append(abs(float(r.get("share_err", 0.0))))
        s["limit_excess"] = max(s["limit_excess"],
                                float(r.get("limit_excess", 0.0)))
        if r.get("resv_ops", 0):
            s["tard_means"].append(float(
                r.get("tardiness_mean_ns", 0.0)))
    return out


def _fmt_row(key, s):
    cid, ce = key
    share = max(s["share_errs"], default=0.0)
    tard = (sum(s["tard_means"]) / len(s["tard_means"]) / 1e6) \
        if s["tard_means"] else 0.0
    return (f"{cid:>7} {ce:>3} {s['windows']:>5} {s['ops']:>9} "
            f"{s['rate_sum'] / max(s['windows'], 1):>10.1f} "
            f"{s['reservation']:>8.1f} {s['weight']:>6.1f} "
            f"{s['resv_miss']:>5} {s['tardy_ops']:>6} "
            f"{share:>9.3f} {s['lb_ops']:>6} "
            f"{s['limit_excess']:>8.1f} {tard:>9.2f}")


_HDR = (f"{'client':>7} {'ce':>3} {'win':>5} {'ops':>9} "
        f"{'rate/s':>10} {'resv/s':>8} {'weight':>6} {'miss':>5} "
        f"{'tardy':>6} {'|shr err|':>9} {'lb':>6} {'lim xs':>8} "
        f"{'tard ms':>9}")


def _totals(series):
    return {
        "series": len(series),
        "windows": sum(s["windows"] for s in series.values()),
        "ops": sum(s["ops"] for s in series.values()),
        "resv_miss": sum(s["resv_miss"] for s in series.values()),
        "tardy_ops": sum(s["tardy_ops"] for s in series.values()),
        "lb_ops": sum(s["lb_ops"] for s in series.values()),
        "worst_share_err": max(
            (max(s["share_errs"], default=0.0)
             for s in series.values()), default=0.0),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="windowed SLO conformance report "
                    "(docs/OBSERVABILITY.md 'SLO plane')")
    ap.add_argument("jsonl", help="closed-window JSONL export "
                    "(EpochJob slo_log / SloPlane.export_jsonl)")
    ap.add_argument("--diff", metavar="BASELINE",
                    help="second export; print per-series deltas")
    ap.add_argument("--client", type=int, default=None,
                    help="restrict to one client id")
    ap.add_argument("--limit", type=int, default=60,
                    help="max series rows printed (most-violating "
                    "first; 0 = all)")
    args = ap.parse_args(argv)

    rows = load_windows_jsonl(args.jsonl)
    if not rows:
        print(f"slo_report: no rows in {args.jsonl}", file=sys.stderr)
        return 1
    skipped = rows[0].pop("_skipped", 0) if rows else 0
    if skipped:
        print(f"slo_report: skipped {skipped} malformed line(s)",
              file=sys.stderr)
    if args.client is not None:
        rows = [r for r in rows if r.get("client") == args.client]
    series = _series(rows)

    def badness(item):
        _key, s = item
        return (s["resv_miss"], s["tardy_ops"],
                max(s["share_errs"], default=0.0), s["lb_ops"])

    ordered = sorted(series.items(), key=badness, reverse=True)
    shown = ordered if not args.limit else ordered[:args.limit]
    print(f"# SLO windowed conformance: {args.jsonl} "
          f"({len(rows)} windows, {len(series)} "
          f"(client, contract-epoch) series)")
    print(_HDR)
    for key, s in shown:
        print(_fmt_row(key, s))
    if len(ordered) > len(shown):
        print(f"... {len(ordered) - len(shown)} more series "
              f"(--limit 0 for all)")
    t = _totals(series)
    print(f"# totals: {t['ops']} ops over {t['windows']} windows; "
          f"{t['resv_miss']} resv-miss windows, "
          f"{t['tardy_ops']} tardy ops, {t['lb_ops']} limit breaks, "
          f"worst |share err| {t['worst_share_err']:.3f}")

    if args.diff:
        base_rows = load_windows_jsonl(args.diff)
        if not base_rows:
            print(f"slo_report: no rows in baseline {args.diff}",
                  file=sys.stderr)
            return 1
        if args.client is not None:
            base_rows = [r for r in base_rows
                         if r.get("client") == args.client]
        base = _series(base_rows)
        tb = _totals(base)
        print(f"\n# diff vs {args.diff} ({tb['windows']} baseline "
              f"windows)")
        for name in ("resv_miss", "tardy_ops", "lb_ops"):
            print(f"#   {name}: {tb[name]} -> {t[name]} "
                  f"({t[name] - tb[name]:+d})")
        print(f"#   worst |share err|: {tb['worst_share_err']:.3f} "
              f"-> {t['worst_share_err']:.3f} "
              f"({t['worst_share_err'] - tb['worst_share_err']:+.3f})")
        both = sorted(set(series) & set(base))
        moved = []
        for key in both:
            d = series[key]["resv_miss"] - base[key]["resv_miss"]
            if d:
                moved.append((abs(d), key, d))
        for _a, key, d in sorted(moved, reverse=True)[:20]:
            print(f"#   client {key[0]} ce {key[1]}: "
                  f"resv-miss windows {d:+d}")
        only_new = sorted(set(series) - set(base))
        if only_new:
            print(f"#   {len(only_new)} series only in {args.jsonl} "
                  f"(new clients / new contract epochs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
