#!/usr/bin/env python
"""On-silicon parity gate: oracle vs TPU-engine traces on the REAL chip.

Every parity suite under tests/ pins jax to the virtual CPU backend
(tests/conftest.py), so the bit-exactness story there is program-level.
This script closes the gap demanded by BASELINE.json's north-star
clause: it runs scaled dmc_sim acceptance shapes through BOTH the
oracle scheduler and the TPU engine ON WHATEVER PLATFORM JAX BOOTS
(the axon-tunneled TPU chip in this image), requires the full service
traces -- (virtual time, server, client, phase, cost) per op -- to
match exactly, and records the evidence in SILICON_PARITY.json.

Run directly or via scripts/ci.sh:
    python scripts/silicon_parity.py
Exits 0 with {"skipped": true} when no accelerator platform is
available (nothing to prove beyond what the CPU-pinned tests pin).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
ARTIFACT = REPO / "SILICON_PARITY.json"


def make_shapes():
    from dmclock_tpu.sim.config import (ClientGroup, ServerGroup,
                                        SimConfig)

    def cfg(clients, servers, **kw):
        return SimConfig(client_groups=len(clients),
                         server_groups=len(servers),
                         cli_group=clients, srv_group=servers, **kw)

    # scaled dmc_sim_example.conf: 4 QoS groups incl. limited and
    # weighted clients (reference sim/dmc_sim_example.conf)
    example = cfg([
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=0,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=1,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=2,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=50.0,
                    client_weight=2.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40, client_wait_s=0,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_req_cost=3,
                    client_server_select_range=1),
    ], [ServerGroup(server_count=1, server_iops=160, server_threads=1)],
        server_soft_limit=False)

    # scaled dmc_sim_100th.conf: reservation-heavy with a cost-3
    # client, soft limit (AtLimit.ALLOW)
    hundredth = cfg([
        ClientGroup(client_count=2, client_total_ops=50,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=10.0, client_limit=0.0,
                    client_weight=2.0, client_req_cost=3,
                    client_server_select_range=1),
    ], [ServerGroup(server_count=1, server_iops=120, server_threads=1)],
        server_soft_limit=True)

    # wider weighted mix to push the total past 1k decisions
    wide = cfg([
        ClientGroup(client_count=4, client_total_ops=100,
                    client_iops_goal=300, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=2),
        ClientGroup(client_count=4, client_total_ops=100,
                    client_iops_goal=300, client_outstanding_ops=32,
                    client_reservation=5.0, client_limit=0.0,
                    client_weight=3.0, client_server_select_range=2),
    ], [ServerGroup(server_count=2, server_iops=400, server_threads=1)],
        server_soft_limit=False)

    return [("example", example), ("100th", hundredth), ("wide", wide)]


def _calendar_silicon_check() -> int:
    """The round-5 headline path on real silicon: calendar batches on
    a mixed-QoS deep state must commit exactly the serial engine's
    next `count` decisions -- per-client decision/phase counts AND the
    full final state, both computed on the accelerator."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dmclock_tpu.core import ClientInfo, ReqParams
    from dmclock_tpu.core.timebase import NS_PER_SEC as S
    from dmclock_tpu.engine import TpuPullPriorityQueue, kernels
    from dmclock_tpu.engine.fastpath import calendar_batch

    rng = __import__("random").Random(17)
    infos = {}
    for c in range(48):
        kind = c % 4
        if kind == 0:
            infos[c] = ClientInfo(1.5, 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, 1.0 + c % 3, 0)
        elif kind == 2:
            infos[c] = ClientInfo(1.0, 2.0, 6.0)
        else:
            infos[c] = ClientInfo(0.5, 1.0, 0)
    q = TpuPullPriorityQueue(lambda c: infos[c], capacity=64,
                             ring_capacity=64)
    t = 1 * S
    for i in range(900):
        c = rng.randrange(48)
        t += rng.randint(0, S // 8)
        delta = rng.randint(1, 4)
        q.add_request(("r", i), c, ReqParams(delta,
                                             rng.randint(1, delta)),
                      time_ns=t, cost=rng.randint(1, 3))
    with q.data_mtx:
        q._flush()
    state = q.state
    total = 0
    now = t + 2 * S
    import functools
    cal = jax.jit(functools.partial(calendar_batch, steps=8,
                                    anticipation_ns=0))
    runs = {p: jax.jit(functools.partial(
        kernels.engine_run, steps=p, allow_limit_break=False,
        anticipation_ns=0, advance_now=False))
        for p in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)}
    for _ in range(30):
        b = cal(state, jnp.int64(now))
        assert bool(b.progress_ok), "calendar stalled on silicon"
        cnt = int(b.count)
        if cnt == 0:
            now += 2 * S
            continue
        # serial replay in power-of-two chunks (engine_run at fixed
        # now composes exactly; one compiled program per chunk size
        # instead of one per distinct count)
        ser_state = state
        ds = []
        n = cnt
        while n:
            p = 1 << (n.bit_length() - 1)
            ser_state, _, decs = runs[p](ser_state, jnp.int64(now))
            ds.append(jax.device_get(decs))
            n -= p
        d_slot = np.concatenate([x.slot for x in ds])
        d_phase = np.concatenate([x.phase for x in ds])
        d_type = np.concatenate([x.type for x in ds])
        assert (d_type == kernels.RETURNING).all()
        served = np.zeros(64, np.int32)
        np.add.at(served, d_slot, 1)
        assert np.array_equal(served, jax.device_get(b.served)), \
            "calendar per-client counts diverge from serial on device"
        resv = np.zeros(64, np.int32)
        np.add.at(resv, d_slot[d_phase == 0], 1)
        assert np.array_equal(resv, jax.device_get(b.served_resv)), \
            "calendar phase counts diverge from serial on device"
        for name, a, bb in zip(state._fields,
                               jax.device_get(b.state),
                               jax.device_get(ser_state)):
            assert np.array_equal(a, bb), \
                f"calendar state field {name} diverges on device"
        state = b.state
        total += cnt
    assert total > 500, f"calendar silicon check too shallow: {total}"
    return total


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu":
        ARTIFACT.write_text(json.dumps({
            "skipped": True,
            "reason": "no accelerator platform; CPU parity is already "
                      "pinned by tests/",
            "platform": platform}, indent=1))
        print("silicon parity: skipped (cpu-only environment)")
        return 0

    from dmclock_tpu.sim.dmc_sim import run_sim

    report = {"platform": platform,
              "device": str(jax.devices()[0]),
              "shapes": [], "total_decisions": 0, "match": True}
    t0 = time.perf_counter()
    # a stale artifact claiming success must be impossible whatever
    # happens below: mark it in-progress BEFORE the first shape runs,
    # and the except arm below catches every failure mode (run_sim
    # crashes and JAX runtime errors included, not just asserts)
    ARTIFACT.write_text(json.dumps({**report, "match": False,
                                    "running": True}, indent=1))
    try:
        for name, cfg in make_shapes():
            oracle = run_sim(cfg, model="dmclock-delayed", seed=7,
                             record_trace=True)
            tpu = run_sim(cfg, model="dmclock-tpu", seed=7,
                          record_trace=True)
            n = len(oracle.trace)
            assert n == len(tpu.trace) > 0, \
                f"{name}: trace lengths differ ({n} vs {len(tpu.trace)})"
            for i, (a, b) in enumerate(zip(oracle.trace, tpu.trace)):
                assert a == b, (f"{name}: trace diverges at op {i}: "
                                f"oracle={a} tpu={b}")
            for cid in oracle.clients:
                ca = oracle.clients[cid].stats
                cb = tpu.clients[cid].stats
                assert (ca.reservation_ops, ca.priority_ops) == \
                    (cb.reservation_ops, cb.priority_ops), \
                    f"{name}: phase split differs for client {cid}"
            report["shapes"].append({"name": name, "decisions": n})
            report["total_decisions"] += n
            print(f"silicon parity: {name}: {n} decisions bit-exact")
        n = _calendar_silicon_check()
        report["shapes"].append({"name": "calendar-vs-serial",
                                 "decisions": n})
        report["total_decisions"] += n
        print(f"silicon parity: calendar-vs-serial: {n} decisions "
              "set+state exact on device")
    except BaseException as e:
        # the artifact must never keep claiming success after ANY
        # failure -- assertion, run_sim crash, JAX runtime error, or
        # interrupt: record the evidence, then fail the gate
        report["match"] = False
        report["error"] = f"{type(e).__name__}: {e}"
        report["wall_s"] = round(time.perf_counter() - t0, 1)
        ARTIFACT.write_text(json.dumps(report, indent=1))
        raise
    report["wall_s"] = round(time.perf_counter() - t0, 1)
    ARTIFACT.write_text(json.dumps(report, indent=1))
    print(f"silicon parity: OK -- {report['total_decisions']} decisions "
          f"bit-exact on {platform} ({report['wall_s']}s); "
          f"wrote {ARTIFACT.name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
