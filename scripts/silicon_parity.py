#!/usr/bin/env python
"""On-silicon parity gate: oracle vs TPU-engine traces on the REAL chip.

Every parity suite under tests/ pins jax to the virtual CPU backend
(tests/conftest.py), so the bit-exactness story there is program-level.
This script closes the gap demanded by BASELINE.json's north-star
clause: it runs scaled dmc_sim acceptance shapes through BOTH the
oracle scheduler and the TPU engine ON WHATEVER PLATFORM JAX BOOTS
(the axon-tunneled TPU chip in this image), requires the full service
traces -- (virtual time, server, client, phase, cost) per op -- to
match exactly, and records the evidence in SILICON_PARITY.json.

Run directly or via scripts/ci.sh:
    python scripts/silicon_parity.py
Exits 0 with {"skipped": true} when no accelerator platform is
available (nothing to prove beyond what the CPU-pinned tests pin).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
ARTIFACT = REPO / "SILICON_PARITY.json"


def make_shapes():
    from dmclock_tpu.sim.config import (ClientGroup, ServerGroup,
                                        SimConfig)

    def cfg(clients, servers, **kw):
        return SimConfig(client_groups=len(clients),
                         server_groups=len(servers),
                         cli_group=clients, srv_group=servers, **kw)

    # scaled dmc_sim_example.conf: 4 QoS groups incl. limited and
    # weighted clients (reference sim/dmc_sim_example.conf)
    example = cfg([
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=0,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=1,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=2,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=50.0,
                    client_weight=2.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40, client_wait_s=0,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_req_cost=3,
                    client_server_select_range=1),
    ], [ServerGroup(server_count=1, server_iops=160, server_threads=1)],
        server_soft_limit=False)

    # scaled dmc_sim_100th.conf: reservation-heavy with a cost-3
    # client, soft limit (AtLimit.ALLOW)
    hundredth = cfg([
        ClientGroup(client_count=2, client_total_ops=50,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=10.0, client_limit=0.0,
                    client_weight=2.0, client_req_cost=3,
                    client_server_select_range=1),
    ], [ServerGroup(server_count=1, server_iops=120, server_threads=1)],
        server_soft_limit=True)

    # wider weighted mix to push the total past 1k decisions
    wide = cfg([
        ClientGroup(client_count=4, client_total_ops=100,
                    client_iops_goal=300, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=2),
        ClientGroup(client_count=4, client_total_ops=100,
                    client_iops_goal=300, client_outstanding_ops=32,
                    client_reservation=5.0, client_limit=0.0,
                    client_weight=3.0, client_server_select_range=2),
    ], [ServerGroup(server_count=2, server_iops=400, server_threads=1)],
        server_soft_limit=False)

    return [("example", example), ("100th", hundredth), ("wide", wide)]


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu":
        ARTIFACT.write_text(json.dumps({
            "skipped": True,
            "reason": "no accelerator platform; CPU parity is already "
                      "pinned by tests/",
            "platform": platform}, indent=1))
        print("silicon parity: skipped (cpu-only environment)")
        return 0

    from dmclock_tpu.sim.dmc_sim import run_sim

    report = {"platform": platform,
              "device": str(jax.devices()[0]),
              "shapes": [], "total_decisions": 0, "match": True}
    t0 = time.perf_counter()
    # a stale artifact claiming success must be impossible whatever
    # happens below: mark it in-progress BEFORE the first shape runs,
    # and the except arm below catches every failure mode (run_sim
    # crashes and JAX runtime errors included, not just asserts)
    ARTIFACT.write_text(json.dumps({**report, "match": False,
                                    "running": True}, indent=1))
    try:
        for name, cfg in make_shapes():
            oracle = run_sim(cfg, model="dmclock-delayed", seed=7,
                             record_trace=True)
            tpu = run_sim(cfg, model="dmclock-tpu", seed=7,
                          record_trace=True)
            n = len(oracle.trace)
            assert n == len(tpu.trace) > 0, \
                f"{name}: trace lengths differ ({n} vs {len(tpu.trace)})"
            for i, (a, b) in enumerate(zip(oracle.trace, tpu.trace)):
                assert a == b, (f"{name}: trace diverges at op {i}: "
                                f"oracle={a} tpu={b}")
            for cid in oracle.clients:
                ca = oracle.clients[cid].stats
                cb = tpu.clients[cid].stats
                assert (ca.reservation_ops, ca.priority_ops) == \
                    (cb.reservation_ops, cb.priority_ops), \
                    f"{name}: phase split differs for client {cid}"
            report["shapes"].append({"name": name, "decisions": n})
            report["total_decisions"] += n
            print(f"silicon parity: {name}: {n} decisions bit-exact")
    except BaseException as e:
        # the artifact must never keep claiming success after ANY
        # failure -- assertion, run_sim crash, JAX runtime error, or
        # interrupt: record the evidence, then fail the gate
        report["match"] = False
        report["error"] = f"{type(e).__name__}: {e}"
        report["wall_s"] = round(time.perf_counter() - t0, 1)
        ARTIFACT.write_text(json.dumps(report, indent=1))
        raise
    report["wall_s"] = round(time.perf_counter() - t0, 1)
    ARTIFACT.write_text(json.dumps(report, indent=1))
    print(f"silicon parity: OK -- {report['total_decisions']} decisions "
          f"bit-exact on {platform} ({report['wall_s']}s); "
          f"wrote {ARTIFACT.name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
