#!/usr/bin/env python
"""Drift-aware benchmark regression guard.

The shared-tunnel TPU runtime drifts by the hour (RESULTS.md quotes
31-49M for one shape across sessions, ~±30%), so a naive
newest-vs-previous comparison would flap.  Instead every `bench.py`
run appends its per-workload rates to `benchmark/history/` (one JSON
per session), and this guard compares the NEWEST record of each
workload against the MEDIAN of the prior records: a drop past the
tolerance factor (default 2×, chosen to clear the observed ±30%
session noise with margin while still catching the order-of-magnitude
regressions that matter, e.g. a fastpath falling back to the serial
scan) fails CI.

Usage:
    python scripts/bench_guard.py [--tolerance 2.0] [--min-records 2]

Exit 0 when there is not enough history yet (the guard cannot judge a
first session), when every workload's newest rate clears
median/tolerance, or when run on a box with no history at all; exit 1
on a regression.

p99 reservation tardiness (the device-ledger QoS column bench.py
records since the telemetry plane landed) is tracked as its own
history series per workload and WARNED on -- tail QoS regressions
surface even when throughput held, but the log2-quantized octaves
and calibration-dependent equilibria make a hard gate flap.

dispatch_ms_per_launch (the span-tracer dispatch-tax column bench.py
records under --spans) gets the same treatment: its own per-workload
series, warn-only on >tolerance regressions -- the dispatch tax can
regress structurally (a lost fusion, an extra sync) while dec/s holds
because the chains amortize it, and it is the before/after currency
of the streaming-serve-loop work (ROADMAP #1).

Churn workloads (bench.py --mode churn; docs/LIFECYCLE.md) form
their own per-workload series keyed additionally by scenario +
scripted population size (total_ids): the population is DYNAMIC, so
the record carries peak/live client counts next to the rate and a
session against a different id space never enters the medians.  The
p99-tardiness warn thresholds apply to churn series like any other.

compile_ms_total and retraces (the capacity plane's per-workload
compile record, docs/OBSERVABILITY.md "Capacity plane") are tracked
the same warn-only way: a compile-time regression or a retrace storm
can eat a whole silicon session (PROFILE.md records a >15-minute
Mosaic compile) while dec/s of the epochs that DID run holds.  Both
medians are floored (100ms / 1 retrace) so clean histories never flap
on jitter or a first stray retrace.  Workload rows the capacity gate
skipped (projected HBM over budget; "capacity_skipped": true) are
excluded from every median and never judged -- a skip is a capacity
verdict, not a rate.

margin_p99_ns and starvation_max_ns (the provenance plane's
per-workload scalars, docs/OBSERVABILITY.md "Provenance plane") are
warn-only series too: a COLLAPSING margin p99 means decisions got
contested (the proportional race tightened -- a QoS-fragility signal
even when dec/s held), and a GROWING starvation watermark means some
backlogged client sat unserved longer.  Both medians are floored (1ms
margin / 100ms starvation, one epoch of virtual time) so log2-bucket
quantization and calibration shifts never flap a clean history.
Provenance-off sessions ("provenance_on": false) form their own
series identity and are never compared against provenance-on records
in either direction.

Controller sessions (bench.py --mode controller; docs/CONTROLLER.md)
carry the `controller` tag ("on"/"both") in the series identity --
a closed-loop A/B row is never median-compared against a bare row --
and a record whose controller actually ACTUATED (>= 1 journaled
decision) joins the clean-median exclusion set the same way chaos
and restart-bearing records do: the on-twin's wall time includes
actuation recompiles, so it extends the trajectory but never seeds
nor is judged against the clean medians.  The actuation count is
printed next to the rate so a knob-thrashing session is visible at
a glance.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
HISTORY = REPO / "benchmark" / "history"


def load_records():
    """History records sorted oldest -> newest (filename carries the
    timestamp; bench.py writes bench_<unix_ts>.json)."""
    if not HISTORY.is_dir():
        return []
    recs = []
    for p in sorted(HISTORY.glob("bench_*.json")):
        try:
            recs.append((p.name, json.loads(p.read_text())))
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_guard: unreadable {p.name}: {e}",
                  file=sys.stderr)
    return recs


def median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def is_fallback(rec: dict) -> bool:
    """A backend-fallback session: bench.py could not initialize the
    accelerator and ran (a reduced shape) on cpu.  Such records keep
    the trajectory unbroken (BENCH_r05 was a null round) but their
    rates are not comparable to accelerator sessions -- the guard
    annotates them and keeps them out of the medians."""
    return bool(rec.get("fallback")) or rec.get("platform") == "cpu"


def is_chaos(rec: dict) -> bool:
    """A fault-injection session (bench.py --fault-plan != "none"):
    its rates reflect injected dropouts/skew, not the engine, so it
    never enters the clean-run medians and is never judged against
    them (docs/ROBUSTNESS.md).  Records predating the field are
    clean runs."""
    return rec.get("fault_plan", "none") != "none"


def is_restarted(rec: dict) -> bool:
    """A supervised session that actually restarted (bench.py under
    robust.supervisor with DMCLOCK_RESTARTS > 0): its wall time
    includes resume + replay recovery work, so like a chaos session
    it extends the trajectory but never enters -- and is never judged
    against -- the clean-run medians.  A supervised run with ZERO
    restarts is a clean run (the zero-host-fault gate pins it
    bit-identical to the bare runner)."""
    return bool(rec.get("supervised")) and int(rec.get("restarts",
                                                       0) or 0) > 0


def is_controller_actuated(rec: dict) -> bool:
    """A closed-loop controller session that actually ACTUATED
    (bench.py --mode controller with >= 1 journaled decision): the
    on-twin's wall time includes knob transitions and their
    recompiles, so like a chaos session it extends the trajectory
    but never enters -- and is never judged against -- the clean-run
    medians.  A controller session with ZERO decisions is a clean
    run (the PR-18 digest gate pins controller=off -- and an
    actuation-free controller=on -- bit-identical to the bare
    runner).  Records predating the field are bare runs."""
    if rec.get("controller", "off") == "off":
        return False
    return any(int(row.get("controller_decisions", 0) or 0) > 0
               for row in rec.get("workloads", {}).values())


def is_degraded(rec: dict) -> bool:
    """A session where the degradation ladder stepped a fast path
    down mid-run (bench.py records the step list): the rates are
    honest for the EFFECTIVE impl, but the step itself means
    something failed -- the record must neither seed clean-run
    medians nor pass silently as a normal session, or a real
    fast-path regression could masquerade as a benign step-down."""
    return bool(rec.get("degradation_ladder"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tolerance", type=float, default=2.0,
                    help="fail when newest < median(prior)/tolerance")
    ap.add_argument("--min-records", type=int, default=2,
                    help="prior records needed before judging")
    args = ap.parse_args()

    recs = load_records()
    if not recs:
        print("bench_guard: no history yet -- pass (bench.py appends "
              "benchmark/history/ records on real hardware)")
        return 0

    n_fb = sum(1 for _, r in recs if is_fallback(r))
    if n_fb:
        print(f"bench_guard: {n_fb} backend-fallback record(s) in "
              "history -- annotated, excluded from medians")
    n_chaos = sum(1 for _, r in recs if is_chaos(r))
    if n_chaos:
        print(f"bench_guard: {n_chaos} chaos (fault-injection) "
              "record(s) in history -- excluded from clean-run "
              "medians")
    n_restarted = sum(1 for _, r in recs if is_restarted(r))
    if n_restarted:
        print(f"bench_guard: {n_restarted} restart-bearing "
              "supervised record(s) in history -- excluded from "
              "clean-run medians")
    n_ctl = sum(1 for _, r in recs if is_controller_actuated(r))
    if n_ctl:
        print(f"bench_guard: {n_ctl} controller-actuated record(s) "
              "in history -- excluded from clean-run medians")
    n_degraded = sum(1 for _, r in recs if is_degraded(r))
    if n_degraded:
        print(f"bench_guard: {n_degraded} ladder-degraded record(s) "
              "in history -- excluded from clean-run medians")

    newest_name, newest = recs[-1]
    if is_degraded(newest):
        steps = newest.get("degradation_ladder")
        print(f"bench_guard: newest record {newest_name} stepped the "
              f"degradation ladder ({steps}) -- a fast path FAILED "
              "mid-session and was retried on its exact twin; "
              "investigate the step reason before trusting this "
              "session; not judged against clean-run history; pass",
              file=sys.stderr)
        return 0
    if is_restarted(newest):
        print(f"bench_guard: newest record {newest_name} is a "
              f"supervised session with "
              f"{newest.get('restarts')} restart(s) -- its rates "
              "include resume/replay recovery; recorded for the "
              "trajectory, not judged against clean-run history; "
              "pass")
        return 0
    if is_controller_actuated(newest):
        n_dec = sum(int(row.get("controller_decisions", 0) or 0)
                    for row in newest.get("workloads", {}).values())
        print(f"bench_guard: newest record {newest_name} is a "
              f"controller-actuated session ({n_dec} journaled "
              "decision(s)) -- its on-twin wall time includes "
              "actuation recompiles; recorded for the trajectory, "
              "not judged against clean-run history; pass")
        return 0
    if is_chaos(newest):
        print(f"bench_guard: newest record {newest_name} is a chaos "
              f"session (fault_plan "
              f"{newest.get('fault_plan')!r}) -- recorded for the "
              "trajectory, not judged against clean-run history; pass")
        return 0
    if is_fallback(newest):
        err = newest.get("backend_error") or newest.get("error") or ""
        print(f"bench_guard: newest record {newest_name} is a "
              f"backend-fallback (cpu) session"
              + (f" [{err}]" if err else "")
              + " -- not judged against accelerator history; pass")
        return 0
    # only same-device sessions are comparable: the tunnel serves
    # whatever chip generation is attached that day, and a device swap
    # would read as a phantom regression (or hide a real one)
    dev = newest.get("device")
    prior = [(n, r) for n, r in recs[:-1]
             if r.get("device") == dev and not is_fallback(r)
             and not is_chaos(r) and not is_restarted(r)
             and not is_controller_actuated(r)
             and not is_degraded(r)]
    def series(wl, key, impl, cal, loop, scen=None, pop=None,
               provon=True, shards=None, sync=None, wk="xla",
               ctl="off", rebal="off", placement="static",
               rpcw=None):
        """Prior values of one per-workload scalar column, filtered to
        the same fast-path identity (select_impl + calendar_impl +
        engine_loop + provenance_on) the throughput series uses.
        Churn workloads add scenario + scripted population
        (total_ids) to the identity: the POPULATION IS DYNAMIC, so a
        record against a different id space is a different workload,
        not a comparable session.  Mesh workloads (engine_loop=mesh)
        add n_shards + counter_sync_every: an 8-shard aggregate rate
        and a 1-shard rate are different machines, and a stale-view
        (K>1) session exchanges fewer counters per epoch -- neither
        may enter the other's medians in either direction.
        Controller rows (bench.py --mode controller) add the
        ``controller`` tag the same way: a closed-loop A/B row never
        median-compares against a bare row.  RPC rows (bench.py
        --mode rpc) add scenario + worker count: a 2-worker loopback
        session and an 8-worker one drive different arrival
        concurrency, and a chaos scenario's rates reflect injected
        faults -- neither may enter the other's medians.  Rows
        predating the provenance knob count as provenance-on (the
        default)."""
        return [r["workloads"][wl][key] for _, r in prior
                if wl in r.get("workloads", {})
                and key in r["workloads"][wl]
                and not r["workloads"][wl].get("capacity_skipped")
                and r["workloads"][wl].get("fault_plan",
                                           "none") == "none"
                and r["workloads"][wl].get("select_impl",
                                           "sort") == impl
                and r["workloads"][wl].get("calendar_impl",
                                           "minstop") == cal
                and r["workloads"][wl].get("engine_loop",
                                           "round") == loop
                and r["workloads"][wl].get("scenario") == scen
                and (r["workloads"][wl].get("total_ids")
                     or r["workloads"][wl].get("clients_total")) == pop
                and r["workloads"][wl].get("n_shards") == shards
                and r["workloads"][wl].get("counter_sync_every")
                == sync
                and r["workloads"][wl].get("wheel_kernel_effective",
                                           "xla") == wk
                and r["workloads"][wl].get("controller",
                                           "off") == ctl
                # the rebalance plane splits mesh series exactly like
                # the controller tag: a migrating session's rates
                # include the host-side handoffs, and a p2c-placed
                # population is a different machine than cid % S --
                # rows predating the knob == off/static
                and r["workloads"][wl].get("rebalance",
                                           "off") == rebal
                and r["workloads"][wl].get("placement",
                                           "static") == placement
                # rpc rows carry their loadgen worker count; only
                # they have the key, so non-rpc rows pass with None
                and r["workloads"][wl].get("workers") == rpcw
                and bool(r["workloads"][wl].get("provenance_on",
                                                True)) == provon]

    status = 0
    for wl, row in sorted(newest.get("workloads", {}).items()):
        dps = row.get("dps")
        if dps is None:
            continue
        if row.get("capacity_skipped"):
            # the capacity gate downgraded this workload before launch
            # (projected HBM over the device budget): a deliberate
            # skip, not a rate -- never judged, never in the medians
            print(f"bench_guard: {wl}: SKIPPED by the capacity gate "
                  f"(projected "
                  f"{row.get('projected_hbm_bytes', 0)/2**30:.2f} GiB"
                  f" vs budget "
                  f"{row.get('hbm_budget_bytes', 0)/2**30:.2f} GiB) "
                  "-- not judged")
            continue
        # the selection backend is part of the series identity: sort
        # and radix epochs are bit-identical in DECISIONS but not in
        # cost, so their rates form separate histories (a radix session
        # judged against sort medians would flap in both directions).
        # Rows without the tag predate the knob == "sort".  The
        # calendar commit scheme splits the series the same way:
        # bucketed sessions must not pollute minstop medians (rows
        # without the tag predate the knob == "minstop").
        impl = row.get("select_impl", "sort")
        cal = row.get("calendar_impl", "minstop")
        # the engine loop splits the series exactly like the fast-path
        # knobs do: a stream session's rates (one launch per chunk of
        # rounds) must NEVER be median-compared against round records
        # -- the workload keys already differ (cfg4 vs cfg4_stream),
        # and the tag filter makes it robust even if a key collides.
        # Rows without the tag predate the knob == "round".
        loop = row.get("engine_loop", "round")
        # churn rows (open population, docs/LIFECYCLE.md) carry
        # scenario + scripted id-space size; both join the series
        # identity and the tag
        scen = row.get("scenario")
        pop = row.get("total_ids")
        provon = bool(row.get("provenance_on", True))
        # mesh rows carry shard count + counter-sync cadence + the
        # client population; all three join the series identity AND
        # the tag, so an S=8 aggregate never median-compares against
        # S=1, K=1 against K=4, or a 100k-client session against a
        # 1M-client one (the churn total_ids precedent: a different
        # population is a different workload, not a comparable
        # session -- per-epoch work grows with N while decisions per
        # epoch stay bounded by m*k).  The population rides the same
        # `pop` filter column the churn rows use.
        shards = row.get("n_shards")
        sync = row.get("counter_sync_every")
        if shards is not None and pop is None:
            pop = row.get("clients_total")
        # wheel rows carry the EFFECTIVE bucket kernel (xla vs
        # pallas; "effective" because an unsupported shape falls
        # back): decisions are bit-identical across kernels but the
        # rates are the whole A/B, so they form separate histories.
        # Rows predating the knob (and every non-wheel row) == xla.
        wk = row.get("wheel_kernel_effective", "xla")
        # controller rows (closed-loop A/B, docs/CONTROLLER.md) carry
        # which twin(s) ran; the tag joins the series identity so an
        # A/B session never median-compares against a bare one
        ctl = row.get("controller", "off")
        # rebalance rows (bench.py --mode mesh --rebalance on) carry
        # the placement mode; both join the series identity and the
        # mesh tag (P=) -- a migrating A/B row never median-compares
        # against a static mesh session
        rebal = row.get("rebalance", "off")
        placement = row.get("placement", "static")
        # rpc rows (bench.py --mode rpc) carry the loadgen worker
        # count and a chaos-scenario tag; both join the series
        # identity -- only rpc rows have the key, so everything else
        # filters on None
        rpcw = row.get("workers")
        tag = f"{wl}[{impl}]" if impl != "sort" else wl
        if cal != "minstop":
            tag += f"[{cal}]"
        if wk != "xla":
            tag += f"[{wk}]"
        if loop != "round" and loop not in wl:
            tag += f"[{loop}]"
        if scen is not None and rpcw is None:
            tag += f"[N={pop}]"
        if rpcw is not None:
            tag += f"[{scen},W={rpcw}]"
        if shards is not None:
            tag += f"[S={shards},K={sync},N={pop},P={placement}]"
        if rebal != "off":
            tag += f"[rebal={rebal}]"
        if ctl != "off":
            tag += f"[ctl={ctl}]"
        if not provon:
            tag += "[prov-off]"
        # a fault-bearing WORKLOAD ROW (bench.py --mode mesh
        # --fault-plan <spec>): its rates reflect injected dropouts
        # and skew, not the engine -- the record-level is_chaos()
        # exclusion extended to the mesh series identity, so a chaos
        # mesh row in an otherwise clean record neither seeds nor is
        # judged against the clean medians
        if row.get("fault_plan", "none") != "none":
            print(f"bench_guard: {tag}: chaos (fault-injection) row "
                  f"(fault_plan {row.get('fault_plan')!r}, "
                  f"dropouts {row.get('fault_dropouts_per_shard')}) "
                  "-- recorded for the trajectory, not judged "
                  "against clean-run medians")
            continue
        hist = series(wl, "dps", impl, cal, loop, scen, pop, provon,
                      shards, sync, wk, ctl, rebal, placement, rpcw)
        if len(hist) < args.min_records:
            print(f"bench_guard: {tag}: {dps/1e6:.1f}M "
                  f"({len(hist)} prior record(s) -- not judged)")
            continue
        med = median(hist)
        floor = med / args.tolerance
        verdict = "OK" if dps >= floor else "REGRESSION"
        # a load-generator-capped run under-reports the engine: worth
        # seeing next to any REGRESSION verdict before panicking; for
        # calendar workloads decisions-per-pass is the per-launch
        # commit depth the bucketed ladder exists to raise
        bb = row.get("bounded_by")
        dpp = row.get("decisions_per_pass")
        # decisions-per-LAUNCH is the streaming loop's acceptance
        # currency (one stream launch covers a whole chunk of rounds)
        dpl = row.get("decisions_per_launch")
        # churn sessions print their population next to the rate: a
        # dynamic population's dec/s is meaningless without it
        peak = row.get("peak_clients")
        print(f"bench_guard: {tag}: newest {dps/1e6:.1f}M vs median "
              f"{med/1e6:.1f}M over {len(hist)} sessions "
              f"(floor {floor/1e6:.1f}M at tolerance "
              f"{args.tolerance:g}x) -- {verdict}"
              + (f" [bounded by {bb}]" if bb else "")
              + (f" [{dpp:.0f} dec/pass]" if dpp else "")
              + (f" [{dpl:.0f} dec/launch]" if dpl else "")
              + (f" [peak {peak} / live {row.get('live_clients')} "
                 "clients]" if peak is not None else "")
              + (f" [{row.get('dps_per_shard_mean', 0)/1e6:.2f}M"
                 "/shard aggregate-of-"
                 f"{shards}]" if shards is not None else "")
              + (f" [{row.get('controller_decisions', 0)} "
                 "controller actuation(s)]"
                 if ctl != "off" else ""))
        if dps < floor:
            status = 1
        # per-shard dec/s (mesh rows) as its own warn-only series:
        # the AGGREGATE can hold while per-shard throughput collapses
        # (e.g. a session quietly ran more shards of a slower
        # engine), and the scaling shape -- aggregate ~ S x per-shard
        # -- is the mesh plane's whole claim, so both are tracked.
        psm = row.get("dps_per_shard_mean")
        if psm is not None:
            p_hist = series(wl, "dps_per_shard_mean", impl, cal,
                            loop, scen, pop, provon, shards, sync,
                            wk, ctl, rebal, placement)
            if len(p_hist) < args.min_records:
                print(f"bench_guard: {tag}: per-shard "
                      f"{psm/1e6:.2f}M ({len(p_hist)} prior "
                      "record(s) -- not judged)")
            else:
                p_med = median(p_hist)
                if psm < p_med / args.tolerance:
                    print(f"bench_guard: {tag}: WARNING per-shard "
                          f"dec/s {psm/1e6:.2f}M vs median "
                          f"{p_med/1e6:.2f}M over {len(p_hist)} "
                          f"sessions (< 1/{args.tolerance:g}x) -- "
                          "per-shard throughput regressed even "
                          "though the aggregate held; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: per-shard "
                          f"{psm/1e6:.2f}M vs median "
                          f"{p_med/1e6:.2f}M -- OK")
        # final shard skew (rebalance rows: max/mean of the per-shard
        # completion totals; 1.0 = level) as its own warn-only series:
        # the migration plane's whole claim is that skew comes DOWN,
        # so a session ending more skewed than tolerance x the median
        # is worth a warning even when the aggregate rate held.
        # Warn-only: skew depends on how many migrations the
        # controller authorized before the run ended, and a hard gate
        # on a ratio of counters would flap.  Median floored at 1.0
        # (perfectly level) so a history of near-level finals never
        # warns on noise.
        sk = row.get("shard_skew_final")
        if sk is not None:
            k_hist = series(wl, "shard_skew_final", impl, cal, loop,
                            scen, pop, provon, shards, sync, wk,
                            ctl, rebal, placement)
            if len(k_hist) < args.min_records:
                print(f"bench_guard: {tag}: final shard skew "
                      f"{sk:.2f} ({len(k_hist)} prior record(s) -- "
                      "not judged)")
            else:
                k_med = max(median(k_hist), 1.0)
                if sk > k_med * args.tolerance:
                    print(f"bench_guard: {tag}: WARNING final shard "
                          f"skew {sk:.2f} vs median {k_med:.2f} over "
                          f"{len(k_hist)} sessions "
                          f"(> {args.tolerance:g}x) -- the rebalance "
                          "plane left the mesh more skewed than its "
                          "history; investigate", file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: final shard skew "
                          f"{sk:.2f} vs median {k_med:.2f} -- OK")
        # p99 reservation tardiness rides the same per-workload
        # history as its own series: a QoS regression (tail tardiness
        # UP past tolerance x the median) is worth a warning even
        # when throughput held -- the paper's contract is per-client
        # QoS, not just decisions/sec.  Warn-only: the log2 buckets
        # quantize to octaves, and tardiness equilibria legitimately
        # shift with calibration; a hard gate would flap.
        p99 = row.get("tardiness_p99_ns")
        if p99 is not None:
            t_hist = series(wl, "tardiness_p99_ns", impl, cal, loop,
                            scen, pop, provon, shards, sync, wk, ctl)
            if len(t_hist) < args.min_records:
                print(f"bench_guard: {tag}: p99 tardiness "
                      f"{p99/1e6:.2f}ms ({len(t_hist)} prior "
                      "record(s) -- not judged)")
            else:
                t_med = median(t_hist)
                # floor the median at 1ms: a perfectly-conformant
                # history (median ~0) must not warn on nanosecond
                # tails -- sub-ms p99 tardiness is octave-quantized
                # noise, not a QoS regression
                ceil = max(t_med, 1e6) * args.tolerance
                if p99 > ceil:
                    print(f"bench_guard: {tag}: WARNING p99 "
                          f"tardiness {p99/1e6:.2f}ms vs median "
                          f"{t_med/1e6:.2f}ms over {len(t_hist)} "
                          f"sessions (> {args.tolerance:g}x) -- "
                          "tail QoS regressed; investigate even "
                          "though throughput held", file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: p99 tardiness "
                          f"{p99/1e6:.2f}ms vs median "
                          f"{t_med/1e6:.2f}ms -- OK")
        # dispatch tax per launch (bench.py --spans) as its own
        # series: the chains amortize dispatch, so dec/s can hold
        # while the per-launch tax regresses structurally -- and the
        # streaming-loop PR's win must show up HERE.  Warn-only: the
        # shared tunnel's dispatch cost drifts by the hour like the
        # rates do, and a hard gate would flap.
        disp = row.get("dispatch_ms_per_launch")
        if disp is not None:
            d_hist = series(wl, "dispatch_ms_per_launch", impl, cal,
                            loop, scen, pop, provon, shards, sync, wk, ctl)
            if len(d_hist) < args.min_records:
                print(f"bench_guard: {tag}: dispatch "
                      f"{disp:.2f}ms/launch ({len(d_hist)} prior "
                      "record(s) -- not judged)")
            else:
                d_med = median(d_hist)
                # floor the median at 1ms: sub-ms dispatch medians
                # (cpu boxes) would make µs jitter read as a 2x
                # regression
                ceil = max(d_med, 1.0) * args.tolerance
                if disp > ceil:
                    print(f"bench_guard: {tag}: WARNING dispatch "
                          f"{disp:.2f}ms/launch vs median "
                          f"{d_med:.2f}ms over {len(d_hist)} "
                          f"sessions (> {args.tolerance:g}x) -- the "
                          "per-launch dispatch tax regressed; "
                          "throughput may still hold because the "
                          "chains amortize it; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: dispatch "
                          f"{disp:.2f}ms/launch vs median "
                          f"{d_med:.2f}ms -- OK")
        # SLO plane verdicts (bench.py --slo; docs/OBSERVABILITY.md
        # "SLO plane") as their own per-workload warn-only series:
        # burn-rate episodes and the worst-window share error measure
        # delivered-vs-contract QoS, which can regress while dec/s
        # holds -- and, like tardiness, their equilibria shift with
        # calibration, so a hard gate would flap.
        viol = row.get("slo_violations_total")
        if viol is not None:
            v_hist = series(wl, "slo_violations_total", impl, cal,
                            loop, scen, pop, provon, shards, sync, wk, ctl)
            if len(v_hist) < args.min_records:
                print(f"bench_guard: {tag}: slo violations {viol} "
                      f"({len(v_hist)} prior record(s) -- not "
                      "judged)")
            else:
                v_med = median(v_hist)
                # floor the median at 1: a historically-clean series
                # must not warn on the first stray episode
                ceil = max(v_med, 1.0) * args.tolerance
                if viol > ceil:
                    print(f"bench_guard: {tag}: WARNING slo "
                          f"violations {viol} vs median {v_med:g} "
                          f"over {len(v_hist)} sessions "
                          f"(> {args.tolerance:g}x) -- burn-rate "
                          "episodes up; the QoS contract regressed "
                          "even if throughput held; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: slo violations "
                          f"{viol} vs median {v_med:g} -- OK")
        serr = row.get("slo_worst_share_err")
        if serr is not None:
            s_hist = series(wl, "slo_worst_share_err", impl, cal,
                            loop, scen, pop, provon, shards, sync, wk, ctl)
            if len(s_hist) < args.min_records:
                print(f"bench_guard: {tag}: worst-window share err "
                      f"{serr:.3f} ({len(s_hist)} prior record(s) "
                      "-- not judged)")
            else:
                s_med = median(s_hist)
                # floor at 0.05: a 5% relative share error is inside
                # windowing noise on any population
                ceil = max(s_med, 0.05) * args.tolerance
                if serr > ceil:
                    print(f"bench_guard: {tag}: WARNING worst-window "
                          f"share error {serr:.3f} vs median "
                          f"{s_med:.3f} over {len(s_hist)} sessions "
                          f"(> {args.tolerance:g}x) -- proportional "
                          "share drifted from the weight "
                          "entitlement; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: worst-window share "
                          f"err {serr:.3f} vs median {s_med:.3f} "
                          "-- OK")
        # compile wall per workload (the capacity plane's compile
        # record) as its own warn-only series: a compile-time
        # regression (a fusion pass giving up, a program blowup)
        # lands BEFORE the timed chains, so dec/s holds while the
        # session's setup cost explodes -- the >15-min-Mosaic-compile
        # failure mode.  Warn-only: compile time on the shared tunnel
        # drifts like everything else.
        cms = row.get("compile_ms_total")
        if cms is not None:
            c_hist = series(wl, "compile_ms_total", impl, cal, loop,
                            scen, pop, provon, shards, sync, wk, ctl)
            if len(c_hist) < args.min_records:
                print(f"bench_guard: {tag}: compile {cms:.0f}ms "
                      f"({len(c_hist)} prior record(s) -- not "
                      "judged)")
            else:
                c_med = median(c_hist)
                # floor the median at 100ms: sub-100ms compiles are
                # cache-hit noise, not a regression signal
                ceil = max(c_med, 100.0) * args.tolerance
                if cms > ceil:
                    print(f"bench_guard: {tag}: WARNING compile "
                          f"{cms:.0f}ms vs median {c_med:.0f}ms "
                          f"over {len(c_hist)} sessions "
                          f"(> {args.tolerance:g}x) -- the workload's "
                          "compile wall regressed; a retrace storm "
                          "or program blowup can eat a whole "
                          "silicon session; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: compile {cms:.0f}ms "
                          f"vs median {c_med:.0f}ms -- OK")
        # retraces as their own series, floored at 1: a clean history
        # (median 0) must not flap on one stray retrace, but a
        # retrace count past tolerance x max(median, 1) means an
        # argument signature is churning (the watchdog's
        # retrace_storm warning is the live view of the same signal)
        rt = row.get("retraces")
        if rt is not None:
            r_hist = series(wl, "retraces", impl, cal, loop, scen,
                            pop, provon, shards, sync, wk, ctl)
            if len(r_hist) < args.min_records:
                print(f"bench_guard: {tag}: retraces {rt} "
                      f"({len(r_hist)} prior record(s) -- not "
                      "judged)")
            else:
                r_med = median(r_hist)
                ceil = max(r_med, 1.0) * args.tolerance
                if rt > ceil:
                    print(f"bench_guard: {tag}: WARNING retraces "
                          f"{rt} vs median {r_med:g} over "
                          f"{len(r_hist)} sessions "
                          f"(> {args.tolerance:g}x) -- an argument "
                          "signature is churning; every retrace "
                          "pays a full XLA compile; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: retraces {rt} vs "
                          f"median {r_med:g} -- OK")
        # provenance margin p99 (docs/OBSERVABILITY.md "Provenance
        # plane") as a warn-only series in the COLLAPSE direction: a
        # p99 winner margin falling past tolerance BELOW the median
        # means the proportional race tightened -- decisions that used
        # to win comfortably are now contested, the QoS-fragility
        # precursor to share skew.  Median floored at 1ms: histories
        # whose margins are already octave-noise never judge.
        mp99 = row.get("margin_p99_ns")
        if mp99 is not None:
            m_hist = series(wl, "margin_p99_ns", impl, cal, loop,
                            scen, pop, provon, shards, sync, wk, ctl)
            if len(m_hist) < args.min_records:
                print(f"bench_guard: {tag}: margin p99 "
                      f"{mp99/1e6:.2f}ms ({len(m_hist)} prior "
                      "record(s) -- not judged)")
            else:
                m_med = median(m_hist)
                if m_med >= 1e6 and mp99 < m_med / args.tolerance:
                    print(f"bench_guard: {tag}: WARNING margin p99 "
                          f"{mp99/1e6:.2f}ms vs median "
                          f"{m_med/1e6:.2f}ms over {len(m_hist)} "
                          f"sessions (< 1/{args.tolerance:g}x) -- "
                          "decision margins collapsed; the "
                          "proportional race tightened even though "
                          "throughput held; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: margin p99 "
                          f"{mp99/1e6:.2f}ms vs median "
                          f"{m_med/1e6:.2f}ms -- OK")
        # starvation watermark as a warn-only series in the GROWTH
        # direction (the tardiness rule's shape): median floored at
        # 100ms -- one round of virtual time -- so an always-served
        # history never flaps on scheduling jitter
        sv = row.get("starvation_max_ns")
        if sv is not None:
            s_hist2 = series(wl, "starvation_max_ns", impl, cal,
                             loop, scen, pop, provon, shards, sync, wk, ctl)
            if len(s_hist2) < args.min_records:
                print(f"bench_guard: {tag}: starvation max "
                      f"{sv/1e6:.0f}ms ({len(s_hist2)} prior "
                      "record(s) -- not judged)")
            else:
                s_med = median(s_hist2)
                ceil = max(s_med, 1e8) * args.tolerance
                if sv > ceil:
                    print(f"bench_guard: {tag}: WARNING starvation "
                          f"max {sv/1e6:.0f}ms vs median "
                          f"{s_med/1e6:.0f}ms over {len(s_hist2)} "
                          f"sessions (> {args.tolerance:g}x) -- a "
                          "backlogged client sat unserved longer; "
                          "run scripts/explain.py on the slo_log "
                          "before trusting this session",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: starvation max "
                          f"{sv/1e6:.0f}ms vs median "
                          f"{s_med/1e6:.0f}ms -- OK")
        # rpc rows (bench.py --mode rpc; docs/RPC.md): the digest
        # gate already ran inside the bench (live vs journaled-trace
        # replay) -- surface a MISMATCH loudly even though the rate
        # held, since a serving plane that admits differently than
        # its journal replays is broken regardless of throughput
        if row.get("digest_match") is False:
            print(f"bench_guard: {tag}: WARNING rpc digest MISMATCH "
                  "-- the live serve and its journaled-trace replay "
                  "disagreed; the admission plane is not "
                  "crash-equivalent; investigate before trusting "
                  "this session", file=sys.stderr)
        # ingest drops (device-side clamp discards) as a warn-only
        # series in the GROWTH direction, median floored at 1: a
        # clean history must not flap on one stray clamp, but drops
        # past tolerance x the median mean the coalesce window is
        # overrunning wave capacity -- admitted work silently
        # discarded on device.  Warn-only: drops depend on arrival
        # timing over real sockets, which drifts with box load.
        idrops = row.get("ingest_drops")
        if idrops is not None and rpcw is not None:
            i_hist = series(wl, "ingest_drops", impl, cal, loop,
                            scen, pop, provon, shards, sync, wk,
                            ctl, rebal, placement, rpcw)
            if len(i_hist) < args.min_records:
                print(f"bench_guard: {tag}: ingest drops {idrops} "
                      f"({len(i_hist)} prior record(s) -- not "
                      "judged)")
            else:
                i_med = median(i_hist)
                ceil = max(i_med, 1.0) * args.tolerance
                if idrops > ceil:
                    print(f"bench_guard: {tag}: WARNING ingest "
                          f"drops {idrops} vs median {i_med:g} over "
                          f"{len(i_hist)} sessions "
                          f"(> {args.tolerance:g}x) -- the device "
                          "admission clamp is discarding more "
                          "coalesced ops; the ingest window is "
                          "overrunning wave capacity; investigate",
                          file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: ingest drops "
                          f"{idrops} vs median {i_med:g} -- OK")
        # p99 admission-to-commit latency as a warn-only series in
        # the GROWTH direction, median floored at 50ms: the serving
        # plane's end-to-end tail (socket arrival -> device commit)
        # can regress while dec/s holds (e.g. a longer coalesce
        # stall or a slower journal fsync path sits outside the
        # timed chunk).  Warn-only: wall-clock tails on a shared box
        # drift with load, and a hard gate would flap.
        lat99 = row.get("lat_p99_ms")
        if lat99 is not None and rpcw is not None:
            l_hist = series(wl, "lat_p99_ms", impl, cal, loop, scen,
                            pop, provon, shards, sync, wk, ctl,
                            rebal, placement, rpcw)
            if len(l_hist) < args.min_records:
                print(f"bench_guard: {tag}: admit->commit p99 "
                      f"{lat99:.0f}ms ({len(l_hist)} prior "
                      "record(s) -- not judged)")
            else:
                l_med = median(l_hist)
                ceil = max(l_med, 50.0) * args.tolerance
                if lat99 > ceil:
                    print(f"bench_guard: {tag}: WARNING "
                          f"admit->commit p99 {lat99:.0f}ms vs "
                          f"median {l_med:.0f}ms over {len(l_hist)} "
                          f"sessions (> {args.tolerance:g}x) -- the "
                          "serving plane's end-to-end tail "
                          "regressed even though throughput held; "
                          "investigate", file=sys.stderr)
                else:
                    print(f"bench_guard: {tag}: admit->commit p99 "
                          f"{lat99:.0f}ms vs median {l_med:.0f}ms "
                          "-- OK")
    if status:
        print(f"bench_guard: FAILED on {newest_name} -- a >"
              f"{args.tolerance:g}x drop survived the drift margin; "
              "investigate before shipping", file=sys.stderr)
    return status


if __name__ == "__main__":
    sys.exit(main())
