#!/usr/bin/env python
"""Multi-process load generator for the RPC ingest plane
(docs/RPC.md "Quickstart").

N worker PROCESSES (real sockets, real concurrency -- not asyncio
simulation) each drive a :class:`dmclock_tpu.net.client.RpcClient`
through a SEEDED, byte-identical request schedule:

- worker ``w`` owns the client ids with ``cid % workers == w``
  (disjoint (cid, seq) spaces -- exactly-once accounting needs no
  cross-process coordination);
- the schedule is a pure function of ``(seed, worker, requests,
  n_clients, max_nops, workers)`` via a dedicated PCG64 stream, so
  the same flags always produce the same frames in the same order
  (``--schedule-only`` prints it; the determinism test and the
  chaos oracle both consume it);
- ``--fault-spec`` draws the PR-3-style slow-sender stalls
  client-side (``stall_ms``/``p_stall``); drops/dups/reorders are
  server-side ingress faults and need nothing here beyond honest
  timeout retry.

Prints one JSON summary line (merged worker stats) and exits 0 when
every request admitted, 1 when any was abandoned after retries.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
from typing import List, Tuple

import numpy as np

# spawn workers re-execute this file with sys.path[0] = scripts/,
# so the repo root must be pinned for run_worker's dmclock_tpu
# imports to resolve inside the children
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def worker_schedule(seed: int, worker: int, *, workers: int,
                    requests: int, n_clients: int,
                    max_nops: int) -> List[Tuple[int, int, int]]:
    """The deterministic per-worker schedule: ``requests`` tuples of
    ``(cid, seq, nops)`` over the worker's own cid partition.  Pure
    function of its arguments -- the determinism gate asserts two
    evaluations are byte-identical."""
    own = [c for c in range(int(n_clients))
           if c % int(workers) == int(worker)]
    if not own:
        return []
    rng = np.random.Generator(np.random.PCG64(
        [int(seed), int(worker), int(requests), int(n_clients)]))
    picks = rng.integers(0, len(own), size=int(requests))
    nops = rng.integers(1, int(max_nops) + 1, size=int(requests))
    seqs = {c: 0 for c in own}
    out = []
    for i in range(int(requests)):
        cid = own[int(picks[i])]
        out.append((cid, seqs[cid], int(nops[i])))
        seqs[cid] += 1
    return out


def full_schedule(seed: int, *, workers: int, requests: int,
                  n_clients: int, max_nops: int
                  ) -> List[List[Tuple[int, int, int]]]:
    return [worker_schedule(seed, w, workers=workers,
                            requests=requests, n_clients=n_clients,
                            max_nops=max_nops)
            for w in range(int(workers))]


def schedule_blob(schedules) -> bytes:
    """Canonical bytes of a schedule (what 'byte-identical' means)."""
    return json.dumps(schedules, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def run_worker(host: str, port: int, schedule, *,
               timeout_s: float = 0.5, max_attempts: int = 8,
               fault_spec=None) -> dict:
    """Drive one worker's schedule to completion; returns its client
    stats (importable -- the in-process bench mode and the tests run
    workers as threads through this same function)."""
    from dmclock_tpu.net import faults as faults_mod
    from dmclock_tpu.net.client import RpcClient, RpcError

    spec = faults_mod.parse_net_fault_spec(fault_spec)
    import time as _time

    with RpcClient(host, port, timeout_s=timeout_s,
                   max_attempts=max_attempts) as cli:
        for cid, seq, nops in schedule:
            stall = faults_mod.stall_ms(spec, cid, seq, 0)
            if stall:
                _time.sleep(stall / 1000.0)
            try:
                cli.request(cid, seq, nops)
            except RpcError:
                pass            # counted in stats["failed"]
        return dict(cli.stats)


def _worker_main(args, w: int, q) -> None:
    sched = worker_schedule(args.seed, w, workers=args.workers,
                            requests=args.requests,
                            n_clients=args.n_clients,
                            max_nops=args.max_nops)
    try:
        stats = run_worker(args.host, args.port, sched,
                           timeout_s=args.timeout_s,
                           max_attempts=args.max_attempts,
                           fault_spec=args.fault_spec)
    except Exception as e:      # a worker crash is a failed leg,
        stats = {"error": f"{type(e).__name__}: {e}",
                 "failed": len(sched)}     # not a hung one
    q.put((w, stats))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="loadgen")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per worker")
    ap.add_argument("--n-clients", type=int, default=16)
    ap.add_argument("--max-nops", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--timeout-s", type=float, default=0.5)
    ap.add_argument("--max-attempts", type=int, default=8)
    ap.add_argument("--fault-spec", default=None,
                    help="client-side stalls (net.faults grammar)")
    ap.add_argument("--schedule-only", action="store_true",
                    help="print the schedule JSON and exit (the "
                    "determinism gate / chaos oracle feed)")
    args = ap.parse_args(argv)

    scheds = full_schedule(args.seed, workers=args.workers,
                           requests=args.requests,
                           n_clients=args.n_clients,
                           max_nops=args.max_nops)
    if args.schedule_only:
        sys.stdout.write(schedule_blob(scheds).decode("utf-8") + "\n")
        return 0
    if not args.port:
        ap.error("--port is required (unless --schedule-only)")

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker_main, args=(args, w, q),
                         daemon=True)
             for w in range(args.workers)]
    for p in procs:
        p.start()
    merged: dict = {}
    for _ in procs:
        w, stats = q.get()
        for k, v in stats.items():
            if isinstance(v, (int, float)):
                merged[k] = merged.get(k, 0) + v
            else:
                merged.setdefault("errors", []).append(v)
    for p in procs:
        p.join(timeout=30)
    merged["workers"] = args.workers
    merged["requests_per_worker"] = args.requests
    merged["schedule_sha"] = __import__("hashlib").sha256(
        schedule_blob(scheds)).hexdigest()
    sys.stdout.write(json.dumps(merged, sort_keys=True) + "\n")
    return 1 if merged.get("failed", 0) or "errors" in merged else 0


if __name__ == "__main__":
    raise SystemExit(main())
