#!/usr/bin/env bash
# One-command CI: python tests, native build+tests, CLI/bench smoke.
# (The role of the reference's .travis.yml:9-26 build matrix.)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== python test suite (per-file process isolation) =="
bash scripts/run_tests.sh

echo "== native build + ctest =="
cmake -S native -B native/build >/dev/null
cmake --build native/build -j >/dev/null
ctest --test-dir native/build --output-on-failure

echo "== simulator smoke =="
# the python sim boots jax (axon platform on this image), so it gets
# a timeout too -- see the tunnel-wedge note below
timeout -k 30 900 python -m dmclock_tpu.sim.dmc_sim -c configs/dmc_sim_example.conf | tail -3
native/build/dmc_sim_native -c configs/dmc_sim_example.conf | tail -3

echo "== observability smoke (trace schema + conformance cross-check) =="
timeout -k 30 900 python - <<'EOF'
import io, re, sys, tempfile
from contextlib import redirect_stdout
from dmclock_tpu.obs import validate_trace_file
from dmclock_tpu.sim import dmc_sim

trace = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False).name
buf = io.StringIO()
with redirect_stdout(buf):
    rc = dmc_sim.main(["-c", "configs/dmc_sim_example.conf",
                       "--trace", trace, "--conformance"])
assert rc == 0, f"dmc_sim exited {rc}"
out = buf.getvalue()
stats = validate_trace_file(trace)        # raises on any bad row
m = re.search(r"total ops (\d+)", out)
assert m, "conformance table missing from sim output"
total = int(m.group(1))
assert stats["rows"] == total, \
    f"trace rows {stats['rows']} != conformance total ops {total}"
assert sum(stats["per_client"].values()) == total
print(f"observability smoke ok ({total} decisions traced, schema "
      f"valid, conformance table sums match)")
EOF

echo "== full-scale TPU parity (100x100 acceptance config) =="
timeout -k 30 1800 python scripts/run_fullscale.py

# TPU legs get hard timeouts: the shared axon tunnel can WEDGE (a
# trivial device op hangs indefinitely -- observed round 5); a hung
# gate is worse than a failed one
echo "== on-silicon parity gate (skips on cpu-only boxes) =="
timeout -k 30 1800 python scripts/silicon_parity.py

echo "== bench history regression guard (drift-aware) =="
python scripts/bench_guard.py

echo "== graft entry compile check =="
timeout -k 30 1200 python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== radix/sort selection parity smoke (cpu backend) =="
# one epoch under BOTH select_impl values must produce identical
# decision digests -- the bit-exactness contract the radix fast path
# ships under (tests/test_radix.py pins the full matrix; this is the
# cheap always-on gate).  Forced to cpu the same way conftest.py does:
# the image's boot shim pre-selects its platform via jax.config, so
# env vars alone don't stick.
timeout -k 30 900 python - <<'EOF'
import functools, hashlib
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from __graft_entry__ import _preloaded_state
from dmclock_tpu.engine.fastpath import scan_prefix_epoch

digests = {}
for impl in ("sort", "radix"):
    state = _preloaded_state(2048, 16, ring=16)
    ep = jax.jit(functools.partial(
        scan_prefix_epoch, m=4, k=256, anticipation_ns=0,
        select_impl=impl))(state, jnp.int64(0))
    assert bool(jax.device_get(ep.guards_ok).all()), \
        f"{impl}: rebase guards failed"
    h = hashlib.sha256()
    for arr in (ep.count, ep.slot, ep.phase, ep.cost, ep.lb):
        h.update(jax.device_get(arr).tobytes())
    digests[impl] = h.hexdigest()
    print(f"{impl}: digest {digests[impl][:16]} "
          f"({int(jax.device_get(ep.count).sum())} decisions)")
assert digests["sort"] == digests["radix"], \
    f"decision digests diverged: {digests}"
print("radix/sort parity smoke ok")
EOF

echo "== calendar minstop/bucketed digest gate (cpu backend) =="
# the bucketed stop-key ladder's exactness currency: (1) ladder_levels=1
# must be BIT-IDENTICAL to the minstop path (same boundary, same ops on
# the same values); (2) a ladder of L levels must equal the COMPOSITION
# of L sequential minstop batches exactly (committed set + final state
# digest) while committing strictly more per launch than one minstop
# batch on the seeded Zipf-skewed cfg4-like workload.
timeout -k 30 900 python - <<'EOF'
import functools, hashlib
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np, jax.numpy as jnp
from __graft_entry__ import _preloaded_state
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.engine.fastpath import (calendar_batch,
                                         calendar_batch_bucketed,
                                         scan_calendar_epoch)
from profile_util import state_digest

N = 2048
st = _preloaded_state(N, 24, ring=32)
w = np.clip(1.0 / np.arange(1, N + 1) ** 1.1
            / (1.0 / (N // 2) ** 1.1), 0.5, 64.0)
rng = np.random.default_rng(7); rng.shuffle(w)
winv = np.asarray([rate_to_inv_ns(x) for x in w], np.int64)
st = st._replace(weight_inv=jnp.asarray(winv),
                 head_prop=jnp.asarray(winv))
now = jnp.int64(0)

def digest(ep):
    h = hashlib.sha256()
    for arr in (ep.count, ep.resv_count, ep.served, ep.progress_ok):
        h.update(jax.device_get(arr).tobytes())
    h.update(jax.device_get(state_digest(ep.state)).tobytes())
    return h.hexdigest()

eps = {}
for impl, lv in (("minstop", 1), ("bucketed", 1)):
    eps[impl] = jax.jit(functools.partial(
        scan_calendar_epoch, m=3, steps=8, anticipation_ns=0,
        calendar_impl=impl, ladder_levels=lv))(st, now)
d_min, d_b1 = digest(eps["minstop"]), digest(eps["bucketed"])
assert d_min == d_b1, f"L=1 ladder != minstop: {d_min[:16]} vs {d_b1[:16]}"
print(f"L=1 ladder bit-identical to minstop ({d_min[:16]}, "
      f"{int(jax.device_get(eps['minstop'].count).sum())} decisions)")

L = 4
bb = jax.jit(functools.partial(
    calendar_batch_bucketed, steps=8, levels=L))(st, now)
s, served = st, np.zeros(N, np.int32)
tot = 0; first = None
for _ in range(L):
    b = jax.jit(functools.partial(calendar_batch, steps=8))(s, now)
    if first is None:
        first = int(b.count)
    tot += int(b.count); served += np.asarray(jax.device_get(b.served))
    s = b.state
assert tot == int(bb.count), (tot, int(bb.count))
assert np.array_equal(served, np.asarray(jax.device_get(bb.served)))
assert bool(jax.device_get(state_digest(bb.state)
                           == state_digest(s))), "final state diverged"
assert int(bb.count) > first, \
    f"ladder committed no more per launch ({int(bb.count)} vs {first})"
print(f"bucketed L={L} == {L}x minstop composition "
      f"({int(bb.count)} decisions/launch vs minstop {first})")
print("calendar digest gate ok")
EOF

echo "== wheel smoke (maintained-calendar digest gate + pallas interpret parity) =="
# the timer-wheel calendar (docs/ENGINE.md "Timer wheel"): (1) the
# wheel at L=1 must be BIT-IDENTICAL to the minstop path AND to the
# bucketed ladder at L=1 (three programs, one decision stream); (2) a
# wheel ladder of L levels must equal the COMPOSITION of L sequential
# minstop batches exactly (committed set + final state digest) while
# committing strictly more per launch; (3) DMCLOCK_WHEEL_INTERPRET=1
# must run the Pallas bucket-scan kernel in interpret mode
# BIT-IDENTICALLY to the XLA reference on any backend -- the
# off-silicon parity pin for the repo's first Pallas kernel; (4) a
# wheel EpochJob must be digest-identical to the bucketed ladder
# under the round, stream, and 4-shard mesh loops, with the wheel
# metric rows (occupancy hwm / re-slots) live and the fallback row
# zero on the XLA path.
timeout -k 30 1200 python - <<'EOF'
import os
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)
import dataclasses, functools, hashlib
import numpy as np, jax.numpy as jnp
from __graft_entry__ import _preloaded_state
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.engine import fastpath
from dmclock_tpu.engine.fastpath import (calendar_batch,
                                         calendar_batch_wheel,
                                         scan_calendar_epoch)
from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.robust import supervisor as SV
from profile_util import state_digest

N = 2048
st = _preloaded_state(N, 24, ring=32)
w = np.clip(1.0 / np.arange(1, N + 1) ** 1.1
            / (1.0 / (N // 2) ** 1.1), 0.5, 64.0)
rng = np.random.default_rng(7); rng.shuffle(w)
winv = np.asarray([rate_to_inv_ns(x) for x in w], np.int64)
st = st._replace(weight_inv=jnp.asarray(winv),
                 head_prop=jnp.asarray(winv))
now = jnp.int64(0)

def digest(ep):
    h = hashlib.sha256()
    for arr in (ep.count, ep.resv_count, ep.served, ep.progress_ok):
        h.update(jax.device_get(arr).tobytes())
    h.update(jax.device_get(state_digest(ep.state)).tobytes())
    return h.hexdigest()

# (1) wheel L=1 == minstop == bucketed L=1, bit-identical
eps = {}
for impl in ("minstop", "bucketed", "wheel"):
    eps[impl] = jax.jit(functools.partial(
        scan_calendar_epoch, m=3, steps=8, anticipation_ns=0,
        calendar_impl=impl, ladder_levels=1))(st, now)
d = {impl: digest(ep) for impl, ep in eps.items()}
assert d["wheel"] == d["minstop"] == d["bucketed"], d
print(f"wheel L=1 bit-identical to minstop + bucketed ({d['wheel'][:16]}, "
      f"{int(jax.device_get(eps['wheel'].count).sum())} decisions)")

# (2) wheel L=4 == 4x minstop composition, strictly more per launch
L = 4
wb = jax.jit(functools.partial(
    calendar_batch_wheel, steps=8, levels=L))(st, now)
s, served = st, np.zeros(N, np.int32)
tot = 0; first = None
for _ in range(L):
    b = jax.jit(functools.partial(calendar_batch, steps=8))(s, now)
    if first is None:
        first = int(b.count)
    tot += int(b.count); served += np.asarray(jax.device_get(b.served))
    s = b.state
assert tot == int(wb.count), (tot, int(wb.count))
assert np.array_equal(served, np.asarray(jax.device_get(wb.served)))
assert bool(jax.device_get(state_digest(wb.state)
                           == state_digest(s))), "final state diverged"
assert int(wb.count) > first, \
    f"wheel ladder committed no more per launch ({int(wb.count)} vs {first})"
print(f"wheel L={L} == {L}x minstop composition "
      f"({int(wb.count)} decisions/launch vs minstop {first})")

# (3) pallas interpret mode bit-identical to the XLA bucket scan
_, fb = fastpath._wheel_resolve("pallas", N)
assert fb, "cpu backend should fall back without the interpret pin"
os.environ["DMCLOCK_WHEEL_INTERPRET"] = "1"
try:
    _, fb = fastpath._wheel_resolve("pallas", N)
    assert not fb, "interpret pin did not engage the pallas kernel"
    pair = {}
    for wk in ("xla", "pallas"):
        pair[wk] = jax.jit(functools.partial(
            calendar_batch_wheel, steps=8, levels=2,
            wheel_kernel=wk))(st, now)
finally:
    del os.environ["DMCLOCK_WHEEL_INTERPRET"]
for f in ("count", "resv_count", "units", "served", "served_resv",
          "lb", "progress_ok", "level_count", "level_bound",
          "level_stall", "served_cost"):
    assert np.array_equal(
        np.asarray(jax.device_get(getattr(pair["xla"], f))),
        np.asarray(jax.device_get(getattr(pair["pallas"], f)))), f
assert bool(jax.device_get(state_digest(pair["xla"].state) ==
                           state_digest(pair["pallas"].state)))
print(f"pallas interpret bit-identical to xla "
      f"({int(jax.device_get(pair['pallas'].count))} decisions)")

# (4) wheel EpochJob == bucketed on round, stream, and 4-shard mesh
base = dict(n=96, depth=6, ring=12, epochs=4, m=2, k=4, seed=9,
            arrival_lam=1.5, waves=3, ckpt_every=2)
WROWS = (obsdev.MET_WHEEL_OCC_HWM, obsdev.MET_WHEEL_RESLOTS,
         obsdev.MET_PALLAS_FALLBACKS)
for loop in ("round", "stream", "mesh"):
    extra = {"n_shards": 4} if loop == "mesh" else {}
    rb = SV.run_job(SV.EpochJob(engine="calendar",
                                calendar_impl="bucketed",
                                ladder_levels=2, engine_loop=loop,
                                **extra, **base))
    rw = SV.run_job(SV.EpochJob(engine="calendar",
                                calendar_impl="wheel",
                                ladder_levels=2, engine_loop=loop,
                                **extra, **base))
    assert rw.decisions == rb.decisions > 0, loop
    assert rw.digest == rb.digest, f"{loop}: wheel digest diverged"
    assert rw.state_digest == rb.state_digest, loop
    mb, mw = np.asarray(rb.metrics).copy(), np.asarray(rw.metrics).copy()
    assert mw[obsdev.MET_WHEEL_OCC_HWM] > 0, \
        f"{loop}: wheel occupancy hwm never observed"
    assert mw[obsdev.MET_PALLAS_FALLBACKS] == 0, \
        f"{loop}: xla path counted pallas fallbacks"
    mb[list(WROWS)] = 0; mw[list(WROWS)] = 0
    assert np.array_equal(mw, mb), f"{loop}: non-wheel metrics diverged"
    print(f"{loop}: wheel == bucketed ({rw.decisions} decisions, "
          f"digest {rw.digest[:16]})")
print("wheel smoke ok")
EOF

echo "== telemetry smoke (histogram/ledger digest gate + scrape) =="
# the device telemetry plane (docs/OBSERVABILITY.md): (1) enabling
# histograms + ledger + flight recorder must leave the decision digest
# BIT-IDENTICAL on a prefix epoch and a bucketed calendar epoch;
# (2) the accumulated telemetry must be self-consistent with the
# decision stream (ledger ops == decisions, commit-size sum ==
# decisions); (3) one histogram family must scrape as a proper
# Prometheus histogram (_bucket/_sum/_count) from the HTTP endpoint,
# and /healthz must answer.
timeout -k 30 900 python - <<'EOF'
import functools, hashlib, json, urllib.request
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np, jax.numpy as jnp
from __graft_entry__ import _preloaded_state
from dmclock_tpu.engine.fastpath import scan_calendar_epoch, scan_prefix_epoch
from dmclock_tpu.obs import MetricsRegistry, MetricsHTTPServer
from dmclock_tpu.obs import flight as obsflight
from dmclock_tpu.obs import histograms as obshist

now = jnp.int64(0)
def digest(ep, fields):
    h = hashlib.sha256()
    for f in fields:
        h.update(jax.device_get(getattr(ep, f)).tobytes())
    return h.hexdigest()

kit = dict(hists=obshist.hist_zero(), ledger=obshist.ledger_zero(2048),
           flight=obsflight.flight_init(256))
runs = {
    "prefix": (functools.partial(scan_prefix_epoch, m=4, k=256,
                                 anticipation_ns=0),
               ("count", "slot", "phase", "cost", "lb")),
    "calendar-bucketed": (functools.partial(
        scan_calendar_epoch, m=2, steps=8, calendar_impl="bucketed",
        ladder_levels=4), ("count", "resv_count", "served")),
}
hist_total = None
for name, (fn, fields) in runs.items():
    st = _preloaded_state(2048, 16, ring=16)
    ep_off = jax.jit(fn)(st, now)
    ep_on = jax.jit(lambda s, t: fn(s, t, **kit))(st, now)
    d0, d1 = digest(ep_off, fields), digest(ep_on, fields)
    assert d0 == d1, f"{name}: digest diverged with telemetry on"
    total = int(jax.device_get(ep_on.count).sum())
    led = np.asarray(jax.device_get(ep_on.ledger))
    hd = obshist.hist_dict(ep_on.hists)
    assert led[:, obshist.LED_OPS].sum() == total, name
    assert hd["commit_size"]["sum"] == total, name
    assert int(jax.device_get(ep_on.flight.seq)) > 0, name
    if hist_total is None:
        hist_total = ep_on.hists
    print(f"{name}: telemetry digest gate ok ({total} decisions, "
          f"digest {d0[:16]})")

reg = MetricsRegistry()
obshist.publish_hists(reg, hist_total, prefix="dmclock")
with MetricsHTTPServer(reg, port=0) as srv:
    with urllib.request.urlopen(srv.url, timeout=10) as resp:
        text = resp.read().decode()
    assert "# TYPE dmclock_decision_latency_ns histogram" in text
    assert 'dmclock_decision_latency_ns_bucket{le="+Inf"}' in text
    assert "dmclock_decision_latency_ns_sum" in text
    assert "dmclock_decision_latency_ns_count" in text
    with urllib.request.urlopen(srv.healthz_url, timeout=10) as resp:
        assert json.loads(resp.read()) == {"status": "ok"}
print("telemetry smoke ok (bit-identical digests; scrape serves "
      "histogram families; /healthz answers)")
EOF

echo "== tracing smoke (span schema + tracing on/off digest gate) =="
# the time-domain tracing plane (docs/OBSERVABILITY.md): (1) tracing
# on vs off must leave decisions BIT-IDENTICAL on all three epoch
# engines (spans are host-side only, never in-graph); (2) the off
# path's per-call cost is a None check -- bound it, so the <=1%
# wall-overhead contract cannot silently rot; (3) a sim run with
# --trace-out must export a chrome://tracing-loadable trace that
# passes schema validation (monotonic ts, matched begin/end nesting,
# category taxonomy) with category self-time sums ~= the spanned wall;
# (4) scripts/trace_report.py must reproduce the attribution table.
timeout -k 30 900 python - <<'EOF'
import hashlib, io, json, re, sys, tempfile, time
from contextlib import redirect_stdout
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from __graft_entry__ import _preloaded_state
from dmclock_tpu.obs import SpanTracer, validate_chrome_trace
from dmclock_tpu.obs import spans as obsspans
from dmclock_tpu.robust.guarded import run_epoch_guarded

# (1) tracing on/off decision digests, all three epoch engines
def digest(ep):
    h = hashlib.sha256()
    for r in ep.results:
        for name in ("count", "slot", "phase", "cost", "served",
                     "length"):
            if hasattr(r, name):
                h.update(np.asarray(
                    jax.device_get(getattr(r, name))).tobytes())
    return h.hexdigest()

tracer = SpanTracer()
for engine in ("prefix", "chain", "calendar"):
    eps = {}
    # the calendar engine reads k as its per-client serve-step budget,
    # bounded by the ring window
    k = 8 if engine == "calendar" else 64
    for tr in (None, tracer):
        st = _preloaded_state(1024, 8, ring=16)
        eps[tr is None] = run_epoch_guarded(
            st, 10 ** 9, engine=engine, m=2, k=k, tracer=tr)
    d_off, d_on = digest(eps[True]), digest(eps[False])
    assert d_off == d_on, f"{engine}: tracing changed decisions"
    print(f"{engine}: tracing on/off digest gate ok ({d_off[:16]})")

# (2) tracing-off per-call cost: spans.span(None, ...) is one None
# check; a generous 20us/call bound catches gross regressions without
# flapping on a loaded CI box
t0 = time.perf_counter_ns()
N = 20000
for _ in range(N):
    with obsspans.span(None, "x", "dispatch"):
        pass
per_call = (time.perf_counter_ns() - t0) / N
assert per_call < 20_000, f"tracing-off path costs {per_call:.0f}ns/call"
print(f"tracing-off path: {per_call:.0f} ns/call (bound 20us)")

# (3) sim --trace-out export + schema validation
from dmclock_tpu.sim import dmc_sim
trace_out = tempfile.mktemp(suffix=".json")
buf = io.StringIO()
t0 = time.perf_counter_ns()
with redirect_stdout(buf):
    rc = dmc_sim.main(["-c", "configs/dmc_sim_example.conf",
                       "--trace-out", trace_out])
wall_ns = time.perf_counter_ns() - t0
assert rc == 0, f"dmc_sim exited {rc}"
stats = validate_chrome_trace(trace_out)   # raises on any violation
assert stats["events"] > 100, stats
assert set(stats["cat_count"]) <= set(obsspans.CATEGORIES)
# spanned self-time can never exceed the run's wall; it must also be
# a real share of it (the sim's event loop is ingest+dispatch-bound)
assert stats["span_ns"] <= 1.05 * wall_ns, (stats["span_ns"], wall_ns)
assert stats["span_ns"] >= 0.10 * wall_ns, (stats["span_ns"], wall_ns)
print(f"sim trace-out ok ({stats['events']} events, "
      f"{stats['span_ns']/1e6:.0f}ms spanned of "
      f"{wall_ns/1e6:.0f}ms wall, schema valid)")

# (4) the attribution report reproduces from the export
import subprocess
out = subprocess.run(
    [sys.executable, "scripts/trace_report.py", trace_out],
    capture_output=True, text=True)
assert out.returncode == 0, out.stderr
assert "dispatch-vs-compute ratio" in out.stdout
assert re.search(r"sim\.pull\s+dispatch", out.stdout), out.stdout
print("trace_report attribution table ok")
print("tracing smoke ok")
EOF

echo "== chaos smoke (seeded dropout+restart; zero-fault digest gate) =="
# the robustness spine (docs/ROBUSTNESS.md): (1) an all-benign
# FaultPlan must be BIT-IDENTICAL to running with no fault plumbing at
# all; (2) a seeded one-dropout-one-restart plan must complete, keep
# surviving servers' reservation conformance within contract, and
# surface the injected events EXACTLY in the fault metric rows.
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    import os
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)
import numpy as np, jax.numpy as jnp
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.parallel import cluster as CL
from dmclock_tpu.robust import cluster as RC, faults as F

S, C, T, K = 4, 8, 6, 16
ADV = 10 ** 8
QOS = [(10.0, 1.0 + (i % 3), 0.0) for i in range(C)]
mesh = CL.make_mesh(4)

def fresh():
    cl = CL.init_cluster(S, C)
    cl = CL.install_clients(
        cl, jnp.asarray([rate_to_inv_ns(r) for r, _, _ in QOS], jnp.int64),
        jnp.asarray([rate_to_inv_ns(w) for _, w, _ in QOS], jnp.int64),
        jnp.asarray([rate_to_inv_ns(l) for _, _, l in QOS], jnp.int64))
    return RC.shard_robust(RC.init_robust(CL.shard_cluster(cl, mesh)), mesh)

arrivals = np.ones((T, S, C), dtype=np.int32)

# (1) zero-fault bit-identity digest gate
_, seq_none = RC.run_with_plan(fresh(), arrivals, 1, mesh, None,
                               decisions_per_step=K, advance_ns=ADV)
_, seq_zero = RC.run_with_plan(fresh(), arrivals, 1, mesh,
                               F.zero_plan(T, S),
                               decisions_per_step=K, advance_ns=ADV)
d0, d1 = RC.decision_digest(seq_none), RC.decision_digest(seq_zero)
assert d0 == d1, f"zero-fault digest diverged: {d0[:16]} vs {d1[:16]}"
print(f"zero-fault digest gate ok ({d0[:16]})")

# (2) seeded chaos run: one dropout + one restart
plan = F.single_outage_plan(T, S, server=2, down_from=2, down_until=4)
rc, seq = RC.run_with_plan(fresh(), arrivals, 1, mesh, plan,
                           decisions_per_step=K, advance_ns=ADV)
totals = RC.metrics_totals(rc)
ev = F.plan_events(plan)
assert totals["server_dropouts"] == ev["server_dropouts"] == 1, totals
assert totals["tracker_resyncs"] == ev["tracker_resyncs"] == 1, totals
assert totals["faults_injected"] == ev["faults_injected"], totals
rows = RC.cluster_conformance(seq, arrivals, plan, QOS, ADV)
survivors = [r for r in rows if r["live_steps"] == T]
assert survivors and all(r["resv_met"] for r in survivors), \
    "surviving servers missed reservation conformance"
print(RC.format_cluster_conformance(rows).splitlines()[-1])
print(f"chaos smoke ok (plan {F.describe(plan)}; fault counters match "
      "the injected plan exactly; surviving servers within contract)")
EOF

echo "== crash smoke (supervised SIGKILL + resume; crash-equivalence digest gate) =="
# the host-fault spine (docs/ROBUSTNESS.md): (1) the zero-host-fault
# gate -- a supervisor-wrapped run with an empty HostFaultPlan and the
# ladder disabled must be BIT-IDENTICAL to the bare runner (digest,
# final state, metric vector, ladder rows zero); (2) the
# crash-equivalence gate -- a child-process run REALLY SIGKILLed at a
# fixed decision count and resumed from the rotation checkpoint must
# match the uninterrupted reference bit-for-bit (modulo the resume
# metric row).
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import os, tempfile
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"   # inherited by the spawn child
from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.robust import host_faults as HF, supervisor as SV

# cfg4-flavored short run: calendar engine, bucketed stop-key ladder
job = SV.EpochJob(engine="calendar", calendar_impl="bucketed",
                  ladder_levels=2, n=512, depth=10, ring=16, epochs=6,
                  m=2, k=8, seed=17, arrival_lam=2.0, waves=4,
                  ckpt_every=2)
ref = SV.run_job(job)
print(f"reference: {ref.decisions} decisions, digest {ref.digest[:16]}")

with tempfile.TemporaryDirectory() as wd:
    r0 = SV.run_supervised(job, wd, HF.zero_host_plan())
SV.assert_crash_equivalent(r0, ref)
assert r0.restarts == 0 and np.array_equal(r0.metrics, ref.metrics)
assert r0.metrics[obsdev.MET_LADDER_STEPS] == 0
assert r0.metrics[obsdev.MET_SUPERVISOR_RESUMES] == 0
print("zero-host-fault gate ok (supervisor-wrapped == bare runner, "
      "bit-identical; ladder rows zero)")

# kill at the FULL decision count: fires at the last epoch boundary,
# after two rotation snapshots exist -- the resume must come from one
kill_at = ref.decisions
with tempfile.TemporaryDirectory() as wd:
    plan = HF.HostFaultPlan(kill_at_decisions=(kill_at,))
    r1 = SV.run_supervised(job, wd, plan, mode="spawn")
SV.assert_crash_equivalent(r1, ref)
assert r1.restarts == 1
assert r1.metrics[obsdev.MET_SUPERVISOR_RESUMES] == 1
assert r1.resumed_from is not None, \
    "resume must land on a rotation snapshot, not replay from scratch"
print(f"crash smoke ok (child SIGKILLed at {kill_at} decisions, "
      f"resumed from {os.path.basename(r1.resumed_from)}; digest + "
      "final state + metrics bit-identical modulo resume rows)")
EOF

echo "== streaming smoke (stream == round decision-digest gate) =="
# the always-on streaming serve loop (docs/ENGINE.md "engine_loop"):
# (1) the fused ingest+serve+commit stream chunks must produce the
# EXACT decision digest, final state, and metric totals of the
# round-based engine on all three epoch engines x {sort,radix} x
# {minstop,bucketed}; (2) the zero-host-fault supervised stream gate:
# a supervisor-wrapped stream run with an empty HostFaultPlan must be
# bit-identical to the bare stream runner INCLUDING the telemetry
# plane (histograms + ledger + flight ring).
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import dataclasses, tempfile
import numpy as np
from dmclock_tpu.robust import host_faults as HF, supervisor as SV

base = dict(n=160, depth=6, ring=12, epochs=4, m=2, seed=9,
            arrival_lam=1.5, waves=3, ckpt_every=2)
matrix = {
    "prefix/sort": SV.EpochJob(engine="prefix", k=16,
                               select_impl="sort", **base),
    "prefix/radix": SV.EpochJob(engine="prefix", k=16,
                                select_impl="radix", **base),
    "chain/sort": SV.EpochJob(engine="chain", chain_depth=3, k=8,
                              select_impl="sort", **base),
    "chain/radix": SV.EpochJob(engine="chain", chain_depth=3, k=8,
                               select_impl="radix", **base),
    "calendar/minstop": SV.EpochJob(engine="calendar", k=4,
                                    calendar_impl="minstop", **base),
    "calendar/bucketed": SV.EpochJob(engine="calendar", k=4,
                                     calendar_impl="bucketed",
                                     ladder_levels=2, **base),
}
for name, jr in matrix.items():
    js = dataclasses.replace(jr, engine_loop="stream")
    r, s = SV.run_job(jr), SV.run_job(js)
    assert r.decisions > 0, name
    assert s.digest == r.digest, \
        f"{name}: stream digest diverged from round"
    assert s.state_digest == r.state_digest, name
    assert np.array_equal(s.metrics, r.metrics), name
    print(f"{name}: stream == round ({r.decisions} decisions, "
          f"digest {r.digest[:16]})")

# zero-host-fault supervised stream gate, telemetry included
job = dataclasses.replace(
    matrix["calendar/bucketed"], engine_loop="stream",
    with_hists=True, with_ledger=True, flight_records=16)
ref = SV.run_job(job)
with tempfile.TemporaryDirectory() as wd:
    sup = SV.run_supervised(job, wd, HF.zero_host_plan())
SV.assert_crash_equivalent(sup, ref)
assert sup.restarts == 0 and np.array_equal(sup.metrics, ref.metrics)
print("zero-host-fault supervised stream gate ok (stream-wrapped + "
      "empty plan == bare stream, bit-identical incl. telemetry)")
print("streaming smoke ok")
EOF

echo "== churn smoke (dynamic == static decision-digest gate) =="
# the client lifecycle plane (docs/LIFECYCLE.md): a seeded
# register/evict/update/compact churn run -- clients arriving through
# the lifecycle plane, idle slots recycled, capacity geometrically
# doubled, live clients repacked by compaction epochs -- must produce
# a BIT-IDENTICAL canonical (client-id-space) decision stream to a
# statically pre-registered population serving the same arrival
# trace, on the serial oracle and on all three epoch engines under
# both the round and the stream loop.
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from dmclock_tpu.lifecycle import (make_spec, run_serial_churn,
                                   static_variant)
from dmclock_tpu.robust import supervisor as SV

# growth (capacity0=4) + eviction (2-epoch generations) + recycling
# (gen2 lands on gen0's freed slots) + compaction (every boundary)
spec = make_spec("churn_storm", total_ids=16, base_lam=1.5,
                 compact_every=1, gens=4, stride=4, life=2,
                 capacity0=4)
static = static_variant(spec)

d_dyn, plane, n_dyn = run_serial_churn(spec, epochs=16, every=2)
d_st, _, n_st = run_serial_churn(static, epochs=16, every=2)
assert d_dyn == d_st, "serial: dynamic digest diverged from static"
assert n_dyn == n_st > 0
snap = plane.snapshot()
for key in ("grows", "evictions", "slot_recycles", "compactions"):
    assert snap[key] > 0, f"churn mechanics never fired: {key}"
print(f"serial: dynamic == static ({n_dyn} decisions, "
      f"{snap['evictions']} evictions, {snap['slot_recycles']} "
      f"recycles, {snap['compactions']} compactions, "
      f"{snap['grows']} grows)")

for engine in ("prefix", "chain", "calendar"):
    jobs = {(tag, loop): SV.EpochJob(
                engine=engine, churn=sp, epochs=12, m=2, k=8,
                ring=16, waves=4, ckpt_every=2, seed=11,
                engine_loop=loop)
            for tag, sp in (("dyn", spec), ("static", static))
            for loop in ("round", "stream")}
    res = {key: SV.run_job(job) for key, job in jobs.items()}
    ref = res[("static", "round")]
    assert ref.decisions > 0, engine
    for key, r in res.items():
        assert r.digest == ref.digest, \
            f"{engine}/{key}: digest diverged from static/round"
        assert r.decisions == ref.decisions, f"{engine}/{key}"
    dyn = res[("dyn", "round")].lifecycle
    assert dyn["compactions"] > 0 and dyn["grows"] > 0, engine
    print(f"{engine}: dyn == static on round + stream "
          f"({ref.decisions} decisions, digest {ref.digest[:16]})")
print("churn smoke ok")
EOF

echo "== slo smoke (window digest gate + windowed==cumulative + scrape) =="
# the SLO plane (docs/OBSERVABILITY.md "SLO plane"): (1) the windowed
# conformance block must leave decisions BIT-IDENTICAL with --slo
# on/off on all three epoch engines under BOTH the round and the
# stream loop; (2) over a contract-stable run, the closed windows plus
# the open block must sum to the cumulative ledger exactly (windowed
# totals == cumulative totals); (3) a dmclock_slo_* family must scrape
# from the HTTP endpoint and GET /slo must answer live.
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import dataclasses, json, urllib.request
import numpy as np
from dmclock_tpu.obs import MetricsHTTPServer, MetricsRegistry
from dmclock_tpu.obs import slo as obsslo, histograms as obshist
from dmclock_tpu.obs.alerts import SloEvaluator, mount_slo_api
from dmclock_tpu.robust import supervisor as SV

base = dict(n=128, depth=6, ring=12, epochs=6, m=2, seed=9,
            arrival_lam=1.5, waves=3, ckpt_every=2, with_ledger=True)
matrix = {
    "prefix": SV.EpochJob(engine="prefix", k=16, **base),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=8, **base),
    "calendar": SV.EpochJob(engine="calendar", k=4,
                            calendar_impl="bucketed",
                            ladder_levels=2, **base),
}
for name, j_off in matrix.items():
    refs = {}
    for loop in ("round", "stream"):
        r_off = SV.run_job(dataclasses.replace(j_off,
                                               engine_loop=loop))
        r_on = SV.run_job(dataclasses.replace(j_off, with_slo=True,
                                              engine_loop=loop))
        assert r_on.digest == r_off.digest, f"{name}/{loop}"
        assert r_on.state_digest == r_off.state_digest, f"{name}/{loop}"
        assert np.array_equal(r_on.metrics, r_off.metrics)
        refs[loop] = r_on
        # windowed == cumulative: ring + open block vs the ledger
        ring = np.asarray(r_on.slo_ring)
        win = np.asarray(r_on.slo_window)
        led = np.asarray(r_on.ledger)
        for wcol, lcol in ((5, obshist.LED_OPS),
                           (7, obshist.LED_RESV_OPS),
                           (9, obshist.LED_LIMIT_BREAKS),
                           (10, obshist.LED_TARD_SUM)):
            got = ring[:, wcol].sum() + win[:, wcol - 5].sum()
            assert got == led[:, lcol].sum(), (name, loop, wcol)
        # delivered COST: these jobs ingest unit costs, so the
        # windowed cost total must equal the ops total exactly
        # (per-client non-unit-cost exactness is pinned per engine
        # in tests/test_slo.py)
        got_cost = ring[:, 6].sum() + win[:, 1].sum()
        assert got_cost == led[:, obshist.LED_OPS].sum(), (name, loop)
    assert refs["round"].slo == refs["stream"].slo, name
    assert np.array_equal(np.asarray(refs["round"].slo_ring),
                          np.asarray(refs["stream"].slo_ring)), name
    print(f"{name}: slo on/off digest gate + windowed==cumulative ok "
          f"(round & stream, {refs['round'].slo['windows_closed']} "
          "windows)")

# scrape: dmclock_slo_* family + live GET /slo
plane = obsslo.SloPlane(4, dt_epoch_ns=10**8)
plane.register(0, 100.0, 1.0, 0.0)
ev = SloEvaluator(plane, log=lambda _l: None)
reg = MetricsRegistry()
with MetricsHTTPServer(reg, port=0) as srv:
    mount_slo_api(srv, ev)
    blk, closed = plane.roll(obsslo.window_zero(4), 0, 2)
    ev.observe_roll(closed)
    with urllib.request.urlopen(srv.url, timeout=10) as resp:
        text = resp.read().decode()
    assert "dmclock_slo_violations_total" in text, text[:400]
    assert "dmclock_slo_windows_closed_total" in text
    with urllib.request.urlopen(srv.url.replace("/metrics", "/slo"),
                                timeout=10) as resp:
        out = json.loads(resp.read())
    assert out["windows_closed"] == len(closed), out
print("slo smoke ok (digest gates green; dmclock_slo_* scrapes; "
      "GET /slo live)")
EOF

echo "== capacity smoke (plane on/off digest gate + planner round-trip + 10% projection gate) =="
# the capacity plane (docs/OBSERVABILITY.md "Capacity plane"): (1) the
# compile/retrace observatory must leave decisions BIT-IDENTICAL with
# the plane on or off, on the serial engine and on all three epoch
# engines under BOTH the round and the stream loop (the wrapper
# dispatches the exact program jax.jit would); (2) plan_capacity()
# must invert the HBM ledger exactly -- the planned N fits the budget
# and N+eps refuses; (3) the ledger's projection for the cfg4 STATE
# shape (100k clients, ring 128, calendar m=3 steps=64, telemetry+slo
# on) must be within 10% of the real compiled program's
# memory_analysis() argument bytes on the CPU backend.
timeout -k 30 1200 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import dataclasses, functools, hashlib
import numpy as np, jax.numpy as jnp
from dmclock_tpu.obs import capacity as CAP, compile_plane as CP
from dmclock_tpu.robust import supervisor as SV
from dmclock_tpu.robust.guarded import _jit_serial
from __graft_entry__ import _preloaded_state
from profile_util import state_digest

# (1a) serial engine: instrumented jit on/off, byte-identical
def serial_digest():
    st = _preloaded_state(512, 6, ring=8)
    run = _jit_serial(64, False, 0)
    s, _, dec = run(st, jnp.int64(10 ** 9))
    h = hashlib.sha256()
    for arr in jax.tree_util.tree_leaves(dec):
        h.update(np.asarray(jax.device_get(arr)).tobytes())
    h.update(np.asarray(jax.device_get(state_digest(s))).tobytes())
    return h.hexdigest()

digs = {}
for on in (True, False):
    CP.plane().enable(on)
    digs[on] = serial_digest()
assert digs[True] == digs[False], "serial digest diverged with the plane"
print(f"serial: capacity plane on/off digest gate ok ({digs[True][:16]})")

# (1b) three epoch engines x round/stream
base = dict(n=160, depth=6, ring=12, epochs=4, m=2, seed=9,
            arrival_lam=1.5, waves=3, ckpt_every=2)
matrix = {
    "prefix": SV.EpochJob(engine="prefix", k=16, **base),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=8, **base),
    "calendar": SV.EpochJob(engine="calendar", k=4,
                            calendar_impl="bucketed",
                            ladder_levels=2, **base),
}
for name, job in matrix.items():
    for loop in ("round", "stream"):
        j = dataclasses.replace(job, engine_loop=loop)
        res = {}
        for on in (True, False):
            CP.plane().enable(on)
            res[on] = SV.run_job(j)
        assert res[True].decisions > 0, (name, loop)
        assert res[True].digest == res[False].digest, (name, loop)
        assert res[True].state_digest == res[False].state_digest
        assert np.array_equal(res[True].metrics, res[False].metrics)
    print(f"{name}: plane on/off digest gate ok on round + stream "
          f"({res[True].decisions} decisions, "
          f"digest {res[True].digest[:16]})")
CP.plane().enable(True)
t = CP.plane().totals()
assert t["compiles"] > 0, "the plane recorded no compiles"
print(f"compile plane: {t['entries']} entries, {t['compiles']} "
      f"compiles, {t['retraces']} retraces, "
      f"{t['compile_ms_total']:.0f}ms compile wall")

# (2) plan_capacity round-trip: planned N fits, N+eps refuses
cfg = dict(ring=128, engine="calendar", m=3, k=64, telemetry=True,
           slo=True)
budget = 16 << 30    # a v5e-sized 16 GiB budget
plan = CAP.plan_capacity(budget, **cfg)
n_max = plan["max_clients"]
assert n_max > 0
assert CAP.fits(n_max, budget, **cfg)
assert not CAP.fits(n_max + 1024, budget, **cfg)
print(f"plan_capacity round-trip ok: {n_max} clients fit a 16 GiB "
      f"budget at the cfg4 knobs ({plan['bytes_per_client']:.0f} "
      f"B/client); N+1024 refuses")

# (3) projected vs measured at the cfg4 STATE shape (abstract
# lowering -- no 100k-client buffers are allocated)
from dmclock_tpu.engine import fastpath
from dmclock_tpu.obs import histograms as obshist, slo as obsslo
n, ring, m, steps = 100_000, 128, 3, 64
st = CAP.abstract_state(n, ring)
comp = jax.jit(functools.partial(
    fastpath.scan_calendar_epoch, m=m, steps=steps,
    anticipation_ns=0, with_metrics=True,
    calendar_impl="minstop")).lower(
        st, jax.ShapeDtypeStruct((), np.dtype(np.int64)),
        hists=jax.eval_shape(obshist.hist_zero),
        ledger=jax.eval_shape(functools.partial(obshist.ledger_zero,
                                                n)),
        slo=jax.eval_shape(functools.partial(obsslo.window_zero,
                                             n))).compile()
mem = CP.memory_analysis_dict(comp)
proj = sum(CAP.hbm_ledger(n, ring=ring, telemetry=True,
                          slo=True).values())
measured = mem["argument_bytes"]
rel = abs(proj - measured) / measured
assert rel <= 0.10, (proj, measured, rel)
print(f"cfg4-shape projection ok: projected {proj/2**20:.1f} MiB vs "
      f"memory_analysis {measured/2**20:.1f} MiB "
      f"(rel err {rel:.2e}, gate 10%; XLA:CPU advisory -- PROFILE.md)")
print("capacity smoke ok")
EOF

echo "== capacity report reproduction (real bench line) =="
# scripts/capacity_report.py must reproduce the capacity table from a
# real recorded bench line (benchmark/history carries the capacity
# scalars since the capacity plane landed) and --diff must render
timeout -k 30 300 python - <<'EOF'
import json, subprocess, sys
from pathlib import Path
hist = sorted(Path("benchmark/history").glob("bench_*.json"))
rec = None
for p in reversed(hist):
    wl = json.loads(p.read_text()).get("workloads", {})
    if any("compile_ms_total" in row for row in wl.values()):
        rec = p
        break
if rec is None:
    print("no capacity-bearing history record yet -- skip "
          "(bench.py records one per session)")
    sys.exit(0)
out = subprocess.run(
    [sys.executable, "scripts/capacity_report.py", str(rec),
     "--diff", str(rec)], capture_output=True, text=True)
assert out.returncode == 0, out.stderr
assert "bound_class" in out.stdout and "compile_ms" in out.stdout
assert "diff vs baseline" in out.stdout
print(f"capacity_report ok on {rec.name}:")
print("\n".join(out.stdout.splitlines()[:3]))
EOF

echo "== provenance smoke (plane on/off digest gate + explain attribution + scrape) =="
# the decision provenance plane (docs/OBSERVABILITY.md "Provenance
# plane"): (1) the provenance block must leave decisions, final state,
# and metric totals BIT-IDENTICAL with the plane on or off, on all
# three epoch engines under BOTH the round and the stream loop (the
# block is pure reductions over arrays the batches already
# materialize); (2) the seeded limit-starvation scenario -- one
# over-limit client + one competitor -- must be attributed to
# limit_capped by scripts/explain.py on both loops, from the slo_log +
# flight dump the run leaves behind; (3) a dmclock_starvation_* family
# must scrape from the HTTP endpoint.
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import dataclasses, json, os, subprocess, sys, tempfile, urllib.request
import numpy as np
from dmclock_tpu.obs import MetricsHTTPServer, MetricsRegistry
from dmclock_tpu.obs import provenance as obsprov
from dmclock_tpu.robust import supervisor as SV

# (1) plane on/off digest gate: three engines x round/stream
base = dict(n=128, depth=6, ring=12, epochs=4, m=2, seed=9,
            arrival_lam=1.5, waves=3, ckpt_every=2)
matrix = {
    "prefix": SV.EpochJob(engine="prefix", k=16, **base),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=8, **base),
    "calendar": SV.EpochJob(engine="calendar", k=4,
                            calendar_impl="bucketed",
                            ladder_levels=2, **base),
}
for name, j_off in matrix.items():
    refs = {}
    for loop in ("round", "stream"):
        r_off = SV.run_job(dataclasses.replace(j_off,
                                               engine_loop=loop))
        r_on = SV.run_job(dataclasses.replace(j_off, with_prov=True,
                                              engine_loop=loop))
        assert r_on.decisions > 0, (name, loop)
        assert r_on.digest == r_off.digest, f"{name}/{loop}"
        assert r_on.state_digest == r_off.state_digest, f"{name}/{loop}"
        assert np.array_equal(r_on.metrics, r_off.metrics)
        assert r_on.prov_scal is not None and r_off.prov_scal is None
        refs[loop] = r_on
    # the block's CONTENTS are loop-invariant too (stream == round)
    for f in ("prov_margin_hist", "prov_scal", "prov_last_served"):
        assert np.array_equal(getattr(refs["round"], f),
                              getattr(refs["stream"], f)), (name, f)
    scal = refs["round"].prov_scal
    print(f"{name}: provenance on/off digest gate ok on round + "
          f"stream ({refs['round'].decisions} decisions, "
          f"{int(scal[obsprov.PS_BATCHES])} batches observed, "
          f"digest {refs['round'].digest[:16]})")

# (2) seeded starvation scenario -> explain.py attribution, both loops
sys.path.insert(0, os.getcwd())
from tests.engine_helpers import starvation_scenario
for loop in ("round", "stream"):
    with tempfile.TemporaryDirectory() as d:
        slo_log = os.path.join(d, "slo.jsonl")
        fldump = os.path.join(d, "flight.jsonl")
        prov, plane, st, now = starvation_scenario(
            "prefix", loop, slo_log=slo_log, flight_dump=fldump)
        pd = obsprov.prov_dict(prov)
        assert pd["gated_batches"] > 0, \
            "the over-limit client was never limit-gated"
        out = subprocess.run(
            [sys.executable, "scripts/explain.py", "--slo", slo_log,
             "--client", "0", "--flight", fldump, "--json"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        res = json.loads(out.stdout)
        assert res["cause"] == "limit_capped", (loop, res)
        assert res["scores"]["limit_capped"] > 0.5, (loop, res)
        # the competitor must NOT read as limit-capped
        out1 = subprocess.run(
            [sys.executable, "scripts/explain.py", "--slo", slo_log,
             "--client", "1", "--json"],
            capture_output=True, text=True)
        assert json.loads(out1.stdout)["cause"] != "limit_capped"
    print(f"{loop}: explain.py attributes the seeded scenario to "
          f"limit_capped (score "
          f"{res['scores']['limit_capped']:.2f}, gate share "
          f"{pd['limit_gate_share']:.2f})")

# (3) dmclock_starvation_* + dmclock_provenance_* scrape
reg = MetricsRegistry()
obsprov.publish_provenance(reg, prov)
mon = obsprov.StarvationMonitor(10 ** 8, registry=reg,
                                log=lambda _l: None)
mon.observe(prov, now, backlog=st.depth)
with MetricsHTTPServer(reg, port=0) as srv:
    with urllib.request.urlopen(srv.url, timeout=10) as resp:
        text = resp.read().decode()
    assert "dmclock_starvation_max_ns" in text, text[:400]
    assert "dmclock_provenance_margin_p99_ns" in text
print("provenance smoke ok (bit-identical digests on both loops; "
      "explain attribution correct; dmclock_starvation_* scrapes)")
EOF

echo "== mesh smoke (S-shard fused launch == host loop; S=1 == stream) =="
# the mesh serving plane (docs/ENGINE.md "Mesh serving"), on an
# 8-device forced host mesh (jax_num_cpu_devices, with the
# --xla_force_host_platform_device_count fallback -- the conftest.py
# discipline): (1) ONE fused shard_map launch of E whole cluster
# rounds with the delta/rho counter psum batched to round boundaries
# must equal E host-driven robust_cluster_steps under a zero-fault
# plan -- decision digest, held counter views, tracker state; with
# counter_sync_every=K>1 it must equal the host loop under a
# delay_counters plan on exactly the non-sync rounds (the staleness
# knob IS the paper's stale-view tolerance); (2) an
# EpochJob(engine_loop="mesh", n_shards=1) run must be bit-identical
# to the stream loop (digest + final state + metrics); (3) an S=4
# mesh job's counter plane must account every decision and the
# in-graph window_mesh_reduce merge must equal the host combine.
timeout -k 30 1200 python - <<'EOF'
import jax, os
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)
import dataclasses
import numpy as np
import jax.numpy as jnp
from dmclock_tpu.core import ClientInfo
from dmclock_tpu.obs import device as obsdev, slo as obsslo
from dmclock_tpu.parallel import cluster as CL
from dmclock_tpu.robust import cluster as RC, faults as F
from dmclock_tpu.robust import supervisor as SV

S, C, E, k, adv = 8, 12, 5, 16, 10 ** 8
mesh = CL.make_mesh(S)
infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0) for c in range(C)]

def fresh():
    cl = CL.init_cluster(S, C)
    cl = CL.install_clients(
        cl,
        jnp.asarray([i.reservation_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64))
    return CL.shard_cluster(cl, mesh)

rng = np.random.Generator(np.random.PCG64(7))
arrivals = rng.integers(0, 3, size=(E, S, C)).astype(np.int32)
for K in (1, 2):
    plan = F.zero_plan(E, S)
    plan.delay_counters[:] = (np.arange(E) % K != 0)[:, None]
    rc = RC.shard_robust(RC.init_robust(fresh()), mesh)
    rc, decs_seq = RC.run_with_plan(
        rc, arrivals, 1, mesh, plan=plan, decisions_per_step=k,
        max_arrivals=2, advance_ns=adv)
    out = CL.run_mesh_rounds(
        fresh(), arrivals, 1, mesh, decisions_per_step=k,
        max_arrivals=2, advance_ns=adv, counter_sync_every=K)
    assert RC.decision_digest(CL.mesh_decs_seq(out.decs)) == \
        RC.decision_digest(decs_seq), f"K={K}: decisions diverged"
    assert np.array_equal(np.asarray(out.view_delta),
                          np.asarray(rc.view_delta)), f"K={K}: views"
    for a, b in zip(jax.tree.leaves(out.cluster.tracker),
                    jax.tree.leaves(rc.cluster.tracker)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"K={K}: tracker diverged"
    print(f"mesh smoke: K={K} fused launch == host loop "
          f"({int((np.asarray(out.decs.type) == 0).sum())} decisions)")

base = dict(n=96, depth=6, ring=10, epochs=5, m=2, k=16, seed=5,
            arrival_lam=1.0, waves=2, ckpt_every=2)
s = SV.run_job(SV.EpochJob(engine="prefix", engine_loop="stream",
                           **base))
m1 = SV.run_job(SV.EpochJob(engine="prefix", engine_loop="mesh",
                            n_shards=1, **base))
assert m1.digest == s.digest and \
    m1.state_digest == s.state_digest and \
    np.array_equal(m1.metrics, s.metrics), "S=1 mesh != stream"
m8 = SV.run_job(SV.EpochJob(engine="prefix", engine_loop="mesh",
                            n_shards=8, counter_sync_every=2,
                            with_slo=True, **base))
assert int(m8.mesh_counters[0].sum()) == m8.decisions, \
    "counter plane lost completions"
assert (m8.mesh_views[0] == m8.mesh_views[0][0]).all(), \
    "shards disagree on the synced view"
print(f"mesh smoke: S=1 bit-identical to stream "
      f"({m1.decisions} decisions); S=8 aggregate {m8.decisions} "
      f"decisions, every completion accounted")
EOF

echo "== mesh chaos smoke (fault plane inside the fused chunk; degraded-mode serving) =="
# the degraded-mode mesh (docs/ROBUSTNESS.md "Degraded-mode mesh"), on
# an 8-device forced host mesh: (1) a CHAOS-CAPABLE chunk under an
# all-benign plan must be BIT-IDENTICAL to the plain mesh chunk
# (decisions, counters, views, state digest); (2) a seeded
# dropout+restart chunk must equal the host robust loop
# (mesh_chunk_host_replay) decision-for-decision and
# counter-view-for-counter-view, with the fault metric rows equal to
# the plan_events oracle EXACTLY; (3) the cluster-model chaos rounds
# (run_mesh_rounds_with_plan) must equal the host robust_cluster_step
# loop at K in {1,2,4}; (4) EpochJob(engine_loop="mesh", churn=...)
# at S>1 must pass the dynamic==static canonical-digest gate.
timeout -k 30 1200 python - <<'EOF'
import jax, os
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)
import dataclasses, hashlib
import numpy as np
import jax.numpy as jnp
from dmclock_tpu.core import ClientInfo
from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.parallel import cluster as CL, mesh as M
from dmclock_tpu.robust import cluster as RC, faults as F
from dmclock_tpu.robust import supervisor as SV
from dmclock_tpu.robust.guarded import (mesh_chunk_host_replay,
                                        run_mesh_chunk_guarded)
from dmclock_tpu.lifecycle import churn as churn_mod

S, E, N = 8, 6, 48
job = SV.EpochJob(engine="prefix", k=16, n=N, depth=6, ring=10,
                  epochs=E, m=2, seed=5, arrival_lam=1.0, waves=2,
                  ckpt_every=E, engine_loop="mesh", n_shards=S)
mesh = M.make_mesh(S)
state = M.stack_shards(
    SV._job_state(dataclasses.replace(job, engine_loop="stream")),
    S, mesh)
cd, cr, vd, vr = M.counter_init(S, N)
rng = np.random.Generator(np.random.PCG64(9))
counts = rng.poisson(1.0, (S, E, N)).astype(np.int32)
kw = dict(engine="prefix", epochs=E, m=2, k=16,
          dt_epoch_ns=job.dt_epoch_ns, waves=2, with_metrics=True,
          counter_sync_every=2)

def digest_of(g):
    d = b"\x00" * 32
    for i in range(E):
        d = SV._digest_update(
            d, tuple(r for grp in g.epochs[i] for r in grp))
    return hashlib.sha256(d).hexdigest()

# (1) zero-fault chaos-capable chunk == plain chunk, bit-identical
plain = run_mesh_chunk_guarded(state, cd, cr, vd, vr, 0, counts,
                               mesh=mesh, **kw)
zero = run_mesh_chunk_guarded(state, cd, cr, vd, vr, 0, counts,
                              mesh=mesh,
                              faults=F.plan_chunk(F.zero_plan(E, S),
                                                  0, E), **kw)
assert digest_of(plain) == digest_of(zero), "zero-fault digest"
for f in ("cd", "cr", "view_d", "view_r"):
    assert np.array_equal(np.asarray(jax.device_get(getattr(plain, f))),
                          np.asarray(jax.device_get(getattr(zero, f)))), f
assert SV._tree_digest(plain.state) == SV._tree_digest(zero.state)
print(f"mesh chaos smoke: zero-fault chaos chunk bit-identical "
      f"({digest_of(plain)[:16]})")

# (2) seeded dropout+restart chunk == host robust loop + exact counters
plan = F.sample_plan(11, E, S, p_dropout=0.3, mean_outage_steps=2.0,
                     p_delay=0.2, p_dup=0.2, max_skew_ns=1000)
ev = F.plan_events(plan)
assert ev["server_dropouts"] > 0 and ev["tracker_resyncs"] > 0, ev
fc = F.plan_chunk(plan, 0, E)
fused = run_mesh_chunk_guarded(state, cd, cr, vd, vr, 0, counts,
                               mesh=mesh, faults=fc, **kw)
host = mesh_chunk_host_replay(state, cd, cr, vd, vr, 0, counts,
                              faults=fc, **kw)
assert fused.mesh_fallback == 0 and host.mesh_fallback == 1
assert digest_of(fused) == digest_of(host), "chaos digest diverged"
for f in ("cd", "cr", "view_d", "view_r"):
    assert np.array_equal(np.asarray(jax.device_get(getattr(fused, f))),
                          np.asarray(jax.device_get(getattr(host, f)))), f
met = np.zeros(obsdev.NUM_METRICS, np.int64)
for i in range(E):
    for grp in fused.epochs[i]:
        for r in grp:
            met = obsdev.metrics_combine_np(met,
                                            jax.device_get(r.metrics))
md = obsdev.metrics_dict(met)
for key in ("server_dropouts", "tracker_resyncs", "faults_injected"):
    assert md[key] == ev[key], (key, md[key], ev[key])
print(f"mesh chaos smoke: seeded chunk == host robust loop "
      f"(plan {F.describe(plan)}; fault counters exact)")

# (3) cluster-model chaos rounds == host loop at K in {1, 2, 4}
C = 10
infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0) for c in range(C)]
def fresh():
    cl = CL.init_cluster(S, C)
    cl = CL.install_clients(
        cl,
        jnp.asarray([i.reservation_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64))
    return RC.shard_robust(RC.init_robust(CL.shard_cluster(cl, mesh)),
                           mesh)
arrivals = rng.integers(0, 3, size=(E, S, C)).astype(np.int32)
cplan = F.sample_plan(13, E, S, p_dropout=0.3, p_delay=0.2,
                      p_dup=0.2, max_skew_ns=500)
for K in (1, 2, 4):
    rc_h, seq = RC.run_with_plan(fresh(), arrivals, 1, mesh,
                                 RC.effective_plan(cplan, K),
                                 decisions_per_step=16,
                                 max_arrivals=2, advance_ns=10 ** 8)
    rc_m, decs = RC.run_mesh_rounds_with_plan(
        fresh(), arrivals, 1, mesh, cplan, decisions_per_step=16,
        max_arrivals=2, advance_ns=10 ** 8, counter_sync_every=K)
    assert RC.decision_digest(CL.mesh_decs_seq(decs)) == \
        RC.decision_digest(seq), f"K={K} cluster chaos digest"
    assert np.array_equal(np.asarray(rc_m.metrics),
                          np.asarray(rc_h.metrics)), f"K={K} metrics"
print("mesh chaos smoke: cluster-model chaos rounds == host loop "
      "at K in {1,2,4}")

# (4) S>1 churn: dynamic == static canonical digest
spec = churn_mod.make_spec("churn_storm", total_ids=32, seed=3)
base = dict(engine="prefix", k=16, n=N, depth=6, ring=10, epochs=8,
            m=2, seed=5, arrival_lam=1.0, waves=2, ckpt_every=2,
            engine_loop="mesh", n_shards=4)
dyn = SV.run_job(SV.EpochJob(churn=spec, **base))
st = SV.run_job(SV.EpochJob(churn=churn_mod.static_variant(spec),
                            **base))
assert dyn.digest == st.digest, "S=4 churn dynamic != static"
assert dyn.lifecycle["registrations"] > 0
print(f"mesh chaos smoke: S=4 churn dynamic == static canonical "
      f"digest ({dyn.digest[:16]}; "
      f"{dyn.lifecycle['registrations']} registrations, "
      f"{dyn.lifecycle['grows']} grows)")
EOF

echo "== controller smoke (off==bare gate + forced-burn actuation + WAL replay) =="
# the closed-loop controller (docs/CONTROLLER.md): (1) the off gate --
# EpochJob(controller=False) is bit-identical to the bare runner
# (digest, final state, metric vector); (2) seeded forced-burn
# limit_thrash: backlog pressure fires the expected protective rule
# (clamp_down, at the FIRST checkpoint boundary) and the journal
# trajectory is run-to-run deterministic; (3) a run SIGKILLed
# mid-actuation (after the journal write, before the apply) resumes
# by REPLAYING the WAL instead of re-deciding -- same digest, same
# knob trajectory, replays >= 1.
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import dataclasses, os, tempfile
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
from dmclock_tpu.lifecycle import make_spec
from dmclock_tpu.robust import host_faults as HF, supervisor as SV

spec = make_spec("limit_thrash", total_ids=12, base_lam=1.5,
                 capacity0=12)
job = SV.EpochJob(engine="prefix", churn=spec, epochs=12, m=2, k=8,
                  ring=16, waves=4, ckpt_every=2, seed=13,
                  with_slo=True)

bare = SV.run_job(job)
off = SV.run_job(dataclasses.replace(job, controller=False))
assert off.digest == bare.digest, "controller=off diverged from bare"
assert off.state_digest == bare.state_digest
assert np.array_equal(np.asarray(off.metrics), np.asarray(bare.metrics))
assert off.controller_decisions == 0 and off.controller_knobs is None
print(f"controller-off gate ok (== bare runner, digest "
      f"{bare.digest[:16]})")

# forced burn: backlog_hi=1 pressures every boundary
forced = dataclasses.replace(job, controller={"backlog_hi": 1})
on = SV.run_job(forced)
assert on.controller_decisions > 0, "forced burn fired no rules"
rules = [row[2] for row in on.controller_trajectory]
assert rules[0] == "clamp_down", rules
assert on.controller_trajectory[0][1] == job.ckpt_every, \
    "first decision must land on the first boundary"
assert on.controller_knobs[2] < 100, "clamp knob never actuated"
on2 = SV.run_job(forced)
assert on2.controller_trajectory == on.controller_trajectory, \
    "controller trajectory is not run-to-run deterministic"
print(f"forced-burn actuation ok ({on.controller_decisions} "
      f"decision(s), rule sequence {rules}, clamp "
      f"{on.controller_knobs[2]}%)")

# kill mid-actuation around the LAST journaled decision: the entry is
# durable before the kill, so the resumed run must REPLAY it
kill_epoch = on.controller_trajectory[-1][1]
plan = HF.HostFaultPlan(
    kill_at_controller=((kill_epoch, "after_journal"),))
with tempfile.TemporaryDirectory() as wd:
    res = SV.run_supervised(forced, wd, plan)
SV.assert_crash_equivalent(res, on)
assert res.restarts == 1
assert res.controller_replays >= 1, \
    "post-write kill must replay the journal, not re-decide"
print(f"controller replay smoke ok (killed at epoch {kill_epoch} "
      f"after_journal; {res.controller_replays} replay(s), "
      f"trajectory bit-identical)")
EOF

echo "== migration smoke (p2c neutrality + twin digest gate) =="
# the shard rebalancing plane (lifecycle/placement.py;
# docs/LIFECYCLE.md "Placement and migration"), on the 8-device
# forced host mesh: (1) S=1 p2c loop neutrality PER ENGINE
# (prefix/chain/calendar-wheel) -- placement="p2c" over one shard is
# bit-identical to the static path (digest + state digest + metrics);
# combined with the earlier mesh (S=1 == stream) and streaming
# (stream == round) gates this carries the placed-there digest across
# round/stream/mesh; (2) the S=4 TWIN GATE on prefix, chain AND the
# wheel calendar: after the controller's migrate rule moves
# quiet-since-start clients off the hot shard, the canonical digest
# equals the run that had them placed on the destination from epoch 0
# (overrides from run A's migration log, migrate rule disarmed) --
# migration is placement-equivalent, not just plausible.  Calendar
# engines drain state.depth at every deadline commit, so the
# boundary-time depth read is structurally zero there; the mid-epoch
# pressure peaks (MeshGuarded.press -> ControlSignals.press_peak) are
# what arm the rule on calendar meshes.
timeout -k 30 1200 python - <<'EOF'
import jax, os
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)
import dataclasses
import numpy as np
from dmclock_tpu.lifecycle import make_spec
from dmclock_tpu.robust import supervisor as SV

GATE_CTL = dict(sync_max=1, backlog_hi=10**9, occ_lo=0.0,
                hysteresis=1, cooldown=8,
                migrate_skew_hi=1.5, migrate_pick="cold",
                migrate_max=4)

def base_job(**over):
    kw = dict(engine="prefix", k=16, select_impl="sort",
              n=96, depth=6, ring=10, epochs=8, m=2, seed=5,
              arrival_lam=1.0, waves=2, ckpt_every=2,
              engine_loop="mesh", n_shards=1)
    kw.update(over)
    return SV.EpochJob(**kw)

def skew_job(**over):
    spec = make_spec("shard_skew", total_ids=64, seed=3,
                     cold_frac=0.5, cold_until=10**9)
    return base_job(n_shards=4, churn=spec, placement="p2c",
                    controller=GATE_CTL, **over)

ENGINES = (dict(engine="prefix"),
           dict(engine="chain"),
           dict(engine="calendar", k=4, calendar_impl="wheel",
                ladder_levels=2))

# (1) S=1 p2c loop neutrality per engine
flash = make_spec("flash_crowd", total_ids=32)
for kw in ENGINES:
    a = SV.run_job(base_job(churn=flash, **kw))
    b = SV.run_job(base_job(churn=flash, placement="p2c", **kw))
    name = kw.get("calendar_impl", kw["engine"])
    assert a.digest == b.digest, f"{name}: S=1 p2c digest diverged"
    assert a.state_digest == b.state_digest, f"{name}: state digest"
    assert np.array_equal(np.asarray(a.metrics),
                          np.asarray(b.metrics)), f"{name}: metrics"
    print(f"migration smoke: S=1 p2c == static on {name} "
          f"(digest {a.digest[:16]})")

# (2) the S=4 twin gate: the depth trigger fires on prefix/chain,
# the mid-epoch pressure-peak trigger fires on the wheel calendar
# (boundary-time depth is structurally zero there)
for kw in ENGINES:
    a = SV.run_job(skew_job(**kw))
    name = kw.get("calendar_impl", kw["engine"])
    assert a.migrations > 0, f"{name}: migrate never fired"
    assert all(src == 0 for _b, _c, src, _d in a.migration_log), \
        f"{name}: a move left a non-hot shard"
    ov = {str(cid): dst for _b, cid, _s, dst in a.migration_log}
    off = dict(GATE_CTL, migrate_skew_hi=0.0)
    b = SV.run_job(dataclasses.replace(
        skew_job(**kw), placement={"mode": "p2c", "overrides": ov},
        controller=off))
    assert b.migrations == 0
    assert a.digest == b.digest, \
        f"{name}: post-migration digest != placed-there-from-start"
    print(f"migration smoke: S=4 twin gate on {name} "
          f"({a.migrations} move(s), digest {a.digest[:16]})")
print("migration smoke ok (twin gates green on prefix+chain+wheel; "
      "calendar armed by mid-epoch pressure peaks)")
EOF

echo "== rpc smoke (loopback serve + loadgen processes; digest + chaos gates) =="
# the serving-plane spine (docs/RPC.md): (1) a REAL loopback serve --
# `python -m dmclock_tpu.net.serve` as a subprocess, driven by 4
# loadgen worker PROCESSES racing over real sockets -- journals its
# admitted-counts trace, and a socketless replay of that trace
# through the same loop must land on the IDENTICAL chain digest;
# (2) the seeded chaos leg (drops + dups) must report fault counters
# EXACTLY equal to the host oracle's plan over the loadgen schedules
# -- equality, not "roughly behaved".
timeout -k 30 900 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import dataclasses, importlib.util, json, os, pathlib, subprocess
import sys, tempfile, time
os.environ["JAX_PLATFORMS"] = "cpu"   # inherited by the subprocesses
from dmclock_tpu.net import faults
from dmclock_tpu.net.journal import ArrivalJournal
from dmclock_tpu.net.serve import RpcServeConfig, run_serve

spec_l = importlib.util.spec_from_file_location(
    "loadgen", pathlib.Path("scripts/loadgen.py").resolve())
loadgen = importlib.util.module_from_spec(spec_l)
spec_l.loader.exec_module(loadgen)

WORKERS, REQUESTS, NCLIENTS, SEED, ATTEMPTS = 4, 16, 16, 7, 8
scheds = loadgen.full_schedule(SEED, workers=WORKERS,
                               requests=REQUESTS,
                               n_clients=NCLIENTS, max_nops=3)

def admitted_ops(fault_spec):
    """Ops the server will admit under this spec -- what wait_ops
    must hold the first boundary take for (the oracle walks fates
    per request; ops weight each admitted request by its nops)."""
    spec = faults.parse_net_fault_spec(fault_spec)
    tot = 0
    for sched in scheds:
        for cid, seq, nops in sched:
            for a in range(ATTEMPTS):
                drop, _, _ = faults.decide(spec, cid, seq, a)
                if not drop:
                    tot += nops
                    break
    return tot

def serve_leg(wd, fault_spec, timeout_s):
    cfg = RpcServeConfig(
        engine="prefix", n=NCLIENTS, depth=2, ring=8, epochs=4,
        m=2, k=8, chain_depth=2, waves=2, ckpt_every=2, seed=11,
        wait_ops=admitted_ops(fault_spec), wait_timeout_s=240.0,
        high_watermark=4096, fault_spec=fault_spec, workdir=wd)
    cfgp, outp, portp = (os.path.join(wd, f)
                         for f in ("cfg.json", "out.json", "port"))
    with open(cfgp, "w") as f:
        json.dump(dataclasses.asdict(cfg), f)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dmclock_tpu.net.serve",
         "--config", cfgp, "--out", outp, "--port-file", portp],
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(portp):
            assert proc.poll() is None, "serve subprocess died early"
            assert time.monotonic() < deadline, "port file never came"
            time.sleep(0.05)
        port = int(open(portp).read())
        lg = subprocess.run(
            [sys.executable, "scripts/loadgen.py", "--port",
             str(port), "--workers", str(WORKERS), "--requests",
             str(REQUESTS), "--n-clients", str(NCLIENTS), "--seed",
             str(SEED), "--timeout-s", str(timeout_s),
             "--max-attempts", str(ATTEMPTS)],
            capture_output=True, text=True, timeout=600)
        assert lg.returncode == 0, f"loadgen failed: {lg.stderr}"
        merged = json.loads(lg.stdout)
        assert proc.wait(timeout=600) == 0, "serve subprocess rc != 0"
    finally:
        if proc.poll() is None:
            proc.kill()
    with open(outp) as f:
        return cfg, merged, json.load(f)

# (1) clean leg + the digest gate vs the journaled-trace replay
with tempfile.TemporaryDirectory() as wd:
    cfg, merged, out = serve_leg(wd, None, 0.5)
    total = sum(n for s in scheds for _, _, n in s)
    assert out["admitted_ops_traced"] + out["carry_ops"] == total, \
        (out, total)
    trace = ArrivalJournal(wd).counts_trace()
replay = run_serve(dataclasses.replace(cfg, workdir=None,
                                       wait_ops=0), trace=trace)
assert out["digest"] == replay["digest"], \
    f"rpc digest gate: live {out['digest'][:16]} != " \
    f"replay {replay['digest'][:16]}"
assert out["trace_sha"] == replay["trace_sha"]
print(f"rpc digest gate ok ({WORKERS} worker processes, "
      f"{merged['workers'] * merged['requests_per_worker']} requests,"
      f" {total} ops; live == journaled-trace replay, "
      f"digest {out['digest'][:16]})")

# (2) seeded chaos leg: drops + dups, EXACT oracle accounting
CHAOS = "seed=5,p_drop=0.25,p_dup=0.2"
oracle = faults.plan_schedule_events(
    faults.parse_net_fault_spec(CHAOS),
    [[(c, s) for c, s, _ in sc] for sc in scheds],
    max_attempts=ATTEMPTS)
assert oracle["lost"] == 0, \
    "chaos leg wants a seed where every request eventually admits"
with tempfile.TemporaryDirectory() as wd:
    _, merged, out = serve_leg(wd, CHAOS, 0.25)
ev = out["events"]
for srv_key, orc_key in (("drops_injected", "drops"),
                         ("dup_frames", "dups"),
                         ("reordered", "reorders"),
                         ("admitted_reqs", "admitted")):
    assert ev[srv_key] == oracle[orc_key], \
        f"chaos {srv_key}: server {ev[srv_key]} != " \
        f"oracle {oracle[orc_key]}"
assert out["admitted_ops_traced"] + out["carry_ops"] \
    == admitted_ops(CHAOS)
print(f"rpc chaos gate ok ({CHAOS}: {ev['drops_injected']} drops, "
      f"{ev['dup_frames']} dups injected across {WORKERS} racing "
      "processes; server counters == host oracle exactly)")
print("rpc smoke ok (loopback digest gate + exact chaos accounting)")
EOF

echo "== bench smoke (one small epoch) =="
timeout -k 30 900 python - <<'EOF'
import functools, jax, jax.numpy as jnp
from __graft_entry__ import _preloaded_state
from dmclock_tpu.engine.fastpath import scan_prefix_epoch
state = _preloaded_state(4096, 16, ring=16)
ep = jax.jit(functools.partial(scan_prefix_epoch, m=4, k=256,
                               anticipation_ns=0))(state, jnp.int64(0))
assert bool(jax.device_get(ep.guards_ok).all()), "rebase guards failed"
n = int(jax.device_get(ep.count).sum())
assert n == 4 * 256, f"bench smoke: only {n}/{4*256} decisions committed"
print(f"bench smoke ok ({n} decisions committed over 4 batches)")
EOF

echo "CI PASSED"
