#!/usr/bin/env python
"""Plot the recorded sweeps (the reference ``plot_gen.sh`` analog,
matplotlib instead of gnuplot): parses benchmark/RESULTS.md and writes
PNGs next to it.

Colors are the validated reference categorical palette (slots 1-2) from
the dataviz method; single-series charts use one hue and no legend.
"""

from __future__ import annotations

import re
from pathlib import Path

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

HERE = Path(__file__).resolve().parent
RESULTS = HERE / "RESULTS.md"

INK = "#1a1a19"
MUTED = "#6b6a5f"
GRID = "#e5e4dc"
SERIES = ["#2a78d6", "#eb6834"]  # validated categorical slots 1-2


def parse_tables(text: str):
    """section-title -> list of row tuples (strings)."""
    tables = {}
    section = None
    rows: list[tuple[str, ...]] = []
    for line in text.splitlines():
        if line.startswith("## "):
            if section and rows:
                tables[section] = rows
            section, rows = line[3:].strip(), []
        elif line.startswith("|") and not set(line) <= {"|", "-", " "}:
            cells = [c.strip() for c in line.strip("|").split("|")]
            if cells and not cells[0].startswith("---"):
                rows.append(tuple(cells))
    if section and rows:
        tables[section] = rows
    return tables


def style(ax):
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(GRID)
    ax.tick_params(colors=MUTED, labelsize=9)
    ax.yaxis.grid(True, color=GRID, linewidth=0.8)
    ax.set_axisbelow(True)


def plot_k_sweep(rows, out: Path):
    data = [(int(r[0]), float(r[1])) for r in rows[1:]]
    ks = [str(k) for k, _ in data]
    ns = [v for _, v in data]
    fig, ax = plt.subplots(figsize=(6, 3.2), dpi=150)
    ax.bar(ks, ns, width=0.62, color=SERIES[0], edgecolor="none")
    style(ax)
    ax.set_xlabel("heap branching factor K", color=MUTED, fontsize=9)
    ax.set_ylabel("add_request ns", color=MUTED, fontsize=9)
    ax.set_title("Native heap K-sweep (dmc_sim_100_100.conf)",
                 color=INK, fontsize=11, loc="left")
    lo = min(ns)
    i = ns.index(lo)
    ax.annotate(f"{lo:.0f} ns", (i, lo), textcoords="offset points",
                xytext=(0, 4), ha="center", color=INK, fontsize=9)
    fig.tight_layout()
    fig.savefig(out)
    plt.close(fig)


def plot_km_sweep(rows, out: Path):
    """The focused round-4 grid: the m axis at k=65536 (dispatch
    amortization) and the k axis at m=64 (batch-size scaling)."""
    data = [(int(r[0]), int(r[1]), float(r[2])) for r in rows[1:]]
    m_axis = sorted((m, v) for k, m, v in data if k == 65536)
    k_axis = sorted((k, v) for k, m, v in data if m == 64)
    if not m_axis or not k_axis:
        raise SystemExit(
            "RESULTS.md k/m table lacks the k=65536 / m=64 axes: "
            "refusing to plot empty charts for unmeasured data")
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(7.5, 3.2), dpi=150)
    ax1.bar([str(m) for m, _ in m_axis], [v for _, v in m_axis],
            width=0.62, color=SERIES[0], edgecolor="none")
    style(ax1)
    ax1.set_xlabel("epoch length m (k=65536)", color=MUTED, fontsize=9)
    ax1.set_ylabel("M decisions/sec", color=MUTED, fontsize=9)
    ax2.bar([str(k) for k, _ in k_axis], [v for _, v in k_axis],
            width=0.62, color=SERIES[1], edgecolor="none")
    style(ax2)
    ax2.set_xlabel("batch size k (m=64)", color=MUTED, fontsize=9)
    ax2.tick_params(axis="x", labelrotation=30)
    fig.suptitle("TPU prefix-epoch k/m sweep (100k clients, one chip, "
                 "medians)", color=INK, fontsize=11, x=0.02,
                 ha="left")
    fig.tight_layout(rect=(0, 0, 1, 0.93))
    fig.savefig(out)
    plt.close(fig)


def main():
    tables = parse_tables(RESULTS.read_text())
    wrote = []
    for title, rows in tables.items():
        if title.startswith("Native heap K-sweep"):
            plot_k_sweep(rows, HERE / "k_sweep.png")
            wrote.append("k_sweep.png")
        elif title.startswith("TPU prefix-epoch k/m sweep"):
            plot_km_sweep(rows, HERE / "tpu_km_sweep.png")
            wrote.append("tpu_km_sweep.png")
    print(f"wrote {', '.join(wrote) or 'nothing (no known sections)'}")


if __name__ == "__main__":
    main()
