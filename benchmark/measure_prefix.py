#!/usr/bin/env python
"""Quick TPU measurement: prefix-commit epoch vs the all-or-nothing
fastpath on the headline, transition, and past-the-cliff shapes."""
import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from __graft_entry__ import _preloaded_state
from dmclock_tpu.engine.fastpath import scan_prefix_epoch
from profile_util import scalar_latency, state_digest


def resv_state(n, depth):
    st = _preloaded_state(n, depth, ring=depth)
    c = np.arange(n)
    phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
    rinv = np.asarray(st.resv_inv)
    jit = (phase * 2.0 * rinv).astype(np.int64)
    return st._replace(head_resv=jnp.asarray(rinv + jit))


def run_case(name, state, now_ns, k, m, epochs, lat):
    run = jax.jit(functools.partial(
        scan_prefix_epoch, m=m, k=k, anticipation_ns=0),
        donate_argnums=(0,))
    ep = run(state, jnp.int64(now_ns))
    jax.device_get(state_digest(ep.state))      # warm/compile
    state = ep.state
    t0 = time.perf_counter()
    counts = []
    for _ in range(epochs):
        ep = run(state, jnp.int64(now_ns))
        state = ep.state
        counts.append(ep.count)
    jax.device_get(state_digest(state))
    t = time.perf_counter() - t0 - lat
    total = int(sum(int(jax.device_get(c).sum()) for c in counts))
    full = epochs * m * k
    print(f"{name}: {total/t/1e6:8.2f} M dec/s  "
          f"({total} dec in {t*1e3:.0f} ms, fill {total/full:.3f})")
    return total / t


def main():
    n, depth = 100_000, 128
    lat = scalar_latency()
    print(f"latency {lat*1e3:.1f} ms")

    # headline: weight steady state
    run_case("weight steady (k=32768,m=32)",
             _preloaded_state(n, depth, ring=depth), 0, 32768, 32, 6,
             lat)
    # reservation backlog
    run_case("resv backlog (k=32768,m=32)", resv_state(n, depth),
             10**15, 32768, 32, 4, lat)
    # transition: only ~3 batches of resv eligible then weight
    st = resv_state(n, depth)
    now = int(np.asarray(st.head_resv).min()) + 2 * 10**7
    run_case("resv->weight transition", st, now, 32768, 32, 4, lat)
    # past the old cliff
    run_case("k=49152 (old cliff)",
             _preloaded_state(n, depth, ring=depth), 0, 49152, 21, 4,
             lat)
    run_case("k=65536", _preloaded_state(n, depth, ring=depth), 0,
             65536, 16, 4, lat)


if __name__ == "__main__":
    main()
