#!/usr/bin/env python
"""Benchmark sweeps (the reference benchmark/ pipeline analog,
``benchmark/data_gen.sh:28-38`` + ``plot_gen.sh``):

1. K-sweep: native ``dmc_sim_native --k-way K`` for K=2..10 over the
   acceptance config, harvesting the mean ns-per-call numbers the
   reference pipeline greps (``simulate.h:306-349``).  The reference's
   rule of thumb ("<= 6 elements: K small; otherwise K=3",
   benchmark/README.md:17-19) is what this reproduces with runtime K.
2. TPU k/m sweep: ``scan_prefix_epoch`` decisions/sec at 100k clients
   across batch size k and epoch length m (the analog of the
   K_WAY_HEAP study for the batch engine: k amortizes the selection
   sort; prefix-commit makes k past the re-entry distance a fill
   degradation instead of a cliff).

Writes benchmark/RESULTS.md.  Usage:
    python benchmark/run_sweeps.py [--skip-native] [--skip-tpu]
        [--repeat N]
"""

from __future__ import annotations

import argparse
import functools
import re
import statistics
import subprocess
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "native" / "build"
RESULTS = Path(__file__).resolve().parent / "RESULTS.md"


def build_native() -> Path:
    exe = BUILD / "dmc_sim_native"
    subprocess.run(["cmake", "-S", str(REPO / "native"), "-B",
                    str(BUILD)], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", str(BUILD), "-j", "--target",
                    "dmc_sim_native"], check=True, capture_output=True)
    return exe


def native_k_sweep(repeat: int):
    exe = build_native()
    # the reference sweep's workload (benchmark/configs/
    # dmc_sim_100_100.conf): 100 servers x 100 clients, 1M ops
    conf = REPO / "configs" / "dmc_sim_100_100.conf"
    rows = []
    for k in range(2, 11):
        add_ns, wall = [], []
        for r in range(repeat):
            t0 = time.perf_counter()
            out = subprocess.run(
                [str(exe), "-c", str(conf), "--k-way", str(k),
                 "--seed", str(12345 + r)],
                check=True, capture_output=True, text=True,
                timeout=600).stdout
            wall.append(time.perf_counter() - t0)
            m = re.search(r"average add_request:\s+(\d+) ns", out)
            add_ns.append(int(m.group(1)))
        rows.append((k, statistics.mean(add_ns),
                     statistics.mean(wall)))
        print(f"K={k}: add_request {rows[-1][1]:.0f} ns "
              f"(wall {rows[-1][2]:.2f}s)")
    return rows


def _timed_prefix_epochs(make_state, now_ns, epochs_hi, k, m,
                         epochs_lo=None, reps=3):
    """Differenced-chain timing on the prefix-commit engine (matches
    bench.py's protocol): a short chain of ``epochs_hi // 4`` epochs
    and a long one of ``epochs_hi``, each chained async with ONE digest
    sync; ``(D_hi - D_lo) / (T_hi - T_lo)`` cancels the fixed per-chain
    dispatch/sync overhead exactly.  (Round 3 subtracted one measured
    scalar latency instead, which left chain-length-dependent overhead
    in the result -- the 50M-vs-103M protocol discrepancy of VERDICT r3
    weak #3.)

    Backlog bounds keep the chains short (tens to hundreds of ms of
    device work), so one differenced pair still carries tunnel jitter
    of the same order -- single-shot rates at the big-k shapes spread
    41-71M run to run.  The reported rate is the MEDIAN over ``reps``
    fresh-state repetitions.

    BOTH chains must be device-bound: wall = max(device, sync RTT), so
    a lo chain under the ~100ms RTT floor truncates the difference's
    denominator and the rate explodes.  The lo chain is sized to hold
    >= 2^22 decisions (~150ms+ of device work at the plateau rates)
    and reps whose lo wall still sits at the floor are discarded.
    Returns (decisions/sec, fill)."""
    import jax
    import jax.numpy as jnp
    from dmclock_tpu.engine.fastpath import scan_prefix_epoch
    from profile_util import scalar_latency, state_digest

    run = jax.jit(functools.partial(
        scan_prefix_epoch, m=m, k=k, anticipation_ns=0),
        donate_argnums=(0,))
    if epochs_lo is None:
        # >= 3*2^21 decisions ~= 160ms+ of device work at the plateau
        # rates (matches bench.py's serve-only lo-chain sizing)
        epochs_lo = max(1, epochs_hi // 4,
                        -((3 << 21) // -(m * k)))      # ceil div
    epochs_hi = max(epochs_hi, epochs_lo + 1)
    lat = scalar_latency()

    def chain(state, n):
        t0 = time.perf_counter()
        counts, guards = [], []
        for _ in range(n):
            ep = run(state, jnp.int64(now_ns))
            state = ep.state
            counts.append(ep.count)
            guards.append(ep.guards_ok)
        jax.device_get(state_digest(state))
        wall = time.perf_counter() - t0
        assert all(bool(jax.device_get(g).all()) for g in guards), \
            "rebase guards tripped -- counts are not trustworthy"
        total = int(sum(int(jax.device_get(c).sum()) for c in counts))
        return state, total, wall

    rates, d_all, pot_all = [], 0, 0
    for rep in range(max(reps, 1)):
        state = make_state()
        # the tunneled remote-compile endpoint occasionally drops a
        # response mid-read; one retry covers it (the cache makes the
        # second attempt cheap).  Only runtime/transport errors are
        # retried -- a trace-time programming error must fail fast.
        # Retry ONLY if the donated input buffer survived: a post-
        # dispatch failure consumes it, and retrying would mask the
        # original error with a deleted-buffer error.
        for attempt in (0, 1):
            try:
                ep = run(state, jnp.int64(now_ns))   # warm/compile
                break
            except jax.errors.JaxRuntimeError:
                if attempt or any(
                        getattr(x, "is_deleted", lambda: False)()
                        for x in jax.tree_util.tree_leaves(state)):
                    raise
                time.sleep(2)
                state = make_state()
        jax.device_get(state_digest(ep.state))
        state = ep.state
        if rep == 0:
            # backlog sufficiency with the 1.5x heavy-class margin
            # (bench.py's rule: weights 1..4 serve the heaviest class
            # ~1.6x the mean; chains sized to the MEAN backlog drain
            # heavy clients mid-chain and deflate the rate)
            backlog = int(jax.device_get(
                state.depth.astype(jnp.int64).sum()))
            assert (epochs_lo + epochs_hi) * m * k * 3 // 2 <= backlog, \
                f"backlog {backlog} cannot feed chains at k={k} " \
                f"m={m} with heavy-class margin"
        state, d_lo, t_lo = chain(state, epochs_lo)
        state, d_hi, t_hi = chain(state, epochs_hi)
        d_all += d_lo + d_hi
        pot_all += (epochs_lo + epochs_hi) * m * k
        if t_hi <= t_lo or t_lo < 1.2 * lat:
            continue    # jitter-inverted or RTT-floor-bound lo chain
        rates.append((d_hi - d_lo) / (t_hi - t_lo))
    assert rates, \
        "no valid pair: chains too short for the tunnel RTT floor"
    import statistics
    return statistics.median(rates), d_all / pot_all


def _timed_transient_chain(state, now_ns, epochs, k, m):
    """Single measured chain for NON-stationary shapes (a transition
    is consumed once, so chain differencing cannot apply): compile on
    a disposable copy of the state, then time one chain from the
    intact original, subtracting one measured scalar round-trip.
    Transient rates carry the tunnel noise the differenced protocol
    cancels -- treat them as approximate."""
    import jax
    import jax.numpy as jnp
    from dmclock_tpu.engine.fastpath import scan_prefix_epoch
    from profile_util import scalar_latency, state_digest

    run = jax.jit(functools.partial(
        scan_prefix_epoch, m=m, k=k, anticipation_ns=0),
        donate_argnums=(0,))
    warm = run(jax.tree.map(jnp.copy, state), jnp.int64(now_ns))
    jax.device_get(state_digest(warm.state))
    del warm
    lat = scalar_latency()
    t0 = time.perf_counter()
    counts, guards = [], []
    for _ in range(epochs):
        ep = run(state, jnp.int64(now_ns))
        state = ep.state
        counts.append(ep.count)
        guards.append(ep.guards_ok)
    jax.device_get(state_digest(state))
    t = time.perf_counter() - t0 - lat
    assert all(bool(jax.device_get(g).all()) for g in guards), \
        "rebase guards tripped -- counts are not trustworthy"
    total = int(sum(int(jax.device_get(c).sum()) for c in counts))
    assert t > 0, f"timing underflow: {t:.4f}s"
    return total / t, total / (epochs * m * k)


def tpu_km_sweep():
    import sys
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state

    n, depth = 100_000, 256
    rows = []
    # focused grid: the m axis at the argmax k (dispatch-amortization
    # story) plus the k axis at the argmax m; 3 fresh-state reps per
    # point (median) keep the short-chain shapes jitter-stable.  The
    # largest shapes need deeper rings for the heavy-class backlog
    # margin (ring width itself costs; keep the smallest that fits).
    grid = [(65536, m, 320) for m in (8, 21, 32)] + \
        [(65536, 64, 384),
         (16384, 64, 256), (32768, 64, 256), (49152, 64, 384),
         (98304, 64, 384)]
    for k, m, d in grid:
        hi = max(2, (1 << 23) // (m * k))

        def mk(depth=d):
            return _preloaded_state(n, depth, ring=depth)

        dps, fill = _timed_prefix_epochs(mk, 0, hi, k, m)
        rows.append((k, m, dps, fill))
        print(f"k={k} m={m}: {dps/1e6:.2f} M dec/s "
              f"(fill {fill:.3f})", flush=True)
    return rows


def tpu_regime_sweep():
    """Decisions/sec by REGIME on the prefix-commit engine: pure
    reservation backlog (constraint phase every decision), a
    reservation->weight transition mid-run (the prefix stops exactly at
    the flip and the next batch switches regime -- formerly the serial-
    recovery cliff), the weight steady state, and the exact serial
    engine as the floor."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import sys
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine import kernels
    from profile_util import scalar_latency, state_digest

    n, depth, k, m = 100_000, 256, 49152, 21
    rows = []

    def resv_state():
        st = _preloaded_state(n, depth, ring=depth)
        # stagger reservation phases over the serve period (2*rinv)
        c = np.arange(n)
        phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
        rinv = np.asarray(st.resv_inv)
        jit = (phase * 2.0 * rinv).astype(np.int64)
        return st._replace(head_resv=jnp.asarray(rinv + jit))

    # pure reservation regime: now far beyond every reservation tag
    dps, fill = _timed_prefix_epochs(resv_state, 10**15, 8, k, m)
    rows.append(("reservation backlog", dps, fill))
    print(f"reservation: {dps/1e6:.2f} M dec/s fill {fill:.3f}")

    # transition: only a few batches of reservation serves are
    # eligible, then the regime flips to weight mid-epoch.  The flip is
    # consumed once, so this row uses the single-chain transient
    # protocol (approximate), not chain differencing.
    st = resv_state()
    now = int(np.asarray(st.head_resv).min()) + 2 * 10**7
    dps, fill = _timed_transient_chain(st, now, 8, k, m)
    rows.append(("resv->weight transition (transient)", dps, fill))
    print(f"transition: {dps/1e6:.2f} M dec/s fill {fill:.3f}")

    # weight regime baseline at the same epoch budget
    dps, fill = _timed_prefix_epochs(
        lambda: _preloaded_state(n, depth, ring=depth), 0, 8, k, m)
    rows.append(("weight steady state", dps, fill))
    print(f"weight: {dps/1e6:.2f} M dec/s fill {fill:.3f}")

    # exact serial engine floor (single-chain, lat-corrected: the
    # serial scan is minutes-per-epoch slow, so chain differencing is
    # unnecessary -- overhead is < 1% here)
    lat = scalar_latency()
    state = _preloaded_state(n, depth, ring=depth)
    serial = jax.jit(lambda s, t: kernels.engine_run(
        s, t, 4096, allow_limit_break=False, anticipation_ns=0,
        advance_now=False))
    state, _, decs = serial(state, jnp.int64(0))
    jax.device_get(state_digest(state))
    t0 = time.perf_counter()
    state, _, decs = serial(state, jnp.int64(0))
    jax.device_get(state_digest(state))
    t = time.perf_counter() - t0 - lat
    rows.append(("exact serial engine", 4096 / t, 1.0))
    print(f"serial exact: {4096/t/1e3:.1f} k dec/s")
    return rows


def tpu_sustained_sweep():
    """BASELINE configs #3/#4: the sustained closed loop (Poisson
    superwave ingest + prefix epochs) as measured by bench.py."""
    import sys
    sys.path.insert(0, str(REPO))
    from bench import CFG4_RESV_RATE, bench_sustained

    rows = []
    r3 = bench_sustained(10_000, 4096, 32, 60, zipf=False,
                         resv_rate=100.0, dt_round_ns=100_000_000,
                         ring=256, depth0=128, rounds_lo=20)
    rows.append(("cfg3: 10k clients, uniform QoS, Poisson", r3))
    print(f"cfg3: {r3['dps']/1e6:.2f} M dec/s")
    r4 = bench_sustained(100_000, 49152, 21, 24, zipf=True,
                         resv_rate=CFG4_RESV_RATE,
                         dt_round_ns=50_000_000, rounds_lo=8)
    rows.append(("cfg4: 100k clients, Zipf weights, resv-constrained",
                 r4))
    print(f"cfg4: {r4['dps']/1e6:.2f} M dec/s")
    return rows


def cfg4_calibration_sweep():
    """The cfg4 reservation-rate calibration study: constraint-phase
    share and throughput vs reservation rate, for three population
    designs.  Mixed-QoS clients pin the share high at any realistic
    rate (weight serves' reservation-debt reduction re-arms the
    constraint phase, reference reduce_reservation_tags :1077-1111);
    cohort alignment and split populations were the candidate
    mitigations -- neither beats the simple mixed design at the target
    share, so cfg4 ships mixed with CFG4_RESV_RATE."""
    import sys
    sys.path.insert(0, str(REPO))
    from bench import bench_sustained

    rows = []
    cases = [
        ("mixed staggered", {}, (25.0, 50.0, 100.0, 200.0)),
        ("mixed aligned", {"resv_aligned": True}, (100.0, 200.0)),
        ("split 50/50", {"split_resv": 0.5}, (60.0, 90.0, 140.0)),
    ]
    for name, kw, rates in cases:
        for r in rates:
            out = bench_sustained(100_000, 49152, 21, 16, zipf=True,
                                  resv_rate=r, dt_round_ns=50_000_000,
                                  rounds_lo=8, **kw)
            rows.append((name, r, out))
            print(f"{name} r={r}: resv_phase="
                  f"{out['resv_phase_frac']:.3f} "
                  f"fill={out['fill']:.3f} "
                  f"dps={out['dps']/1e6:.1f}M", flush=True)
    return rows


def device_sim_headline():
    """Closed-loop ops/sec of the device-resident simulator at 100k
    clients -- the reference's system test (sim/src/simulate.h:159-178)
    run as ONE compiled program per launch: load generation, delta/rho
    piggybacking, dmClock scheduling, service, completion feedback all
    on device.  Prefix serve mode (q=4096 per slice), random server
    selection, 2-thread servers."""
    import dataclasses
    import functools
    import sys
    sys.path.insert(0, str(REPO))
    import jax
    import numpy as np
    from dmclock_tpu.sim import device_sim as DS
    from dmclock_tpu.sim.config import (ClientGroup, ServerGroup,
                                        SimConfig)

    n = 100_000
    groups = [
        ClientGroup(client_count=n // 2, client_total_ops=10**9,
                    client_iops_goal=80.0, client_outstanding_ops=32,
                    client_reservation=2.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=8),
        ClientGroup(client_count=n // 2, client_total_ops=10**9,
                    client_iops_goal=80.0, client_outstanding_ops=32,
                    client_reservation=2.0, client_limit=0.0,
                    client_weight=3.0, client_server_select_range=8),
    ]
    cfg = SimConfig(client_groups=2, server_groups=1,
                    server_random_selection=True,
                    server_soft_limit=False, cli_group=groups,
                    srv_group=[ServerGroup(server_count=8,
                                           server_iops=500_000.0,
                                           server_threads=2)])
    sim, _ = DS.init_device_sim(cfg, ring_capacity=64)
    # rebuild the spec at the throughput slice size through _make_spec
    # so max_sends is re-derived for the longer slice (a stale
    # max_sends would silently clamp offered load below the goal --
    # the misreporting _make_spec's assert exists to refuse)
    spec = DS._make_spec(cfg, q_per_slice=4096)
    assert spec.q_per_slice >= 256 and not spec.force_scan
    mesh = DS.make_mesh(1)
    sim = DS.shard_device_sim(sim, mesh)
    # slices=2 + per-launch syncs: longer launches of this program
    # (vmap x while_loop x shard_map over 8 servers) reliably fault
    # the tunneled TPU worker; 2-slice launches ran 14+ consecutive
    # times without incident.  Donation keeps one ~1GB state resident.
    slices = 2
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh, slices=slices),
                   donate_argnums=(0,))

    def served(s):
        return int(np.asarray(s.served_resv).sum()
                   + np.asarray(s.served_prop).sum())

    def chain(launches, s):
        # served_resv/served_prop are CUMULATIVE counters: take the
        # per-chain delta so the differenced rate's numerator and
        # denominator cover the same launches.  Launches are sync'd
        # INDIVIDUALLY: queueing several multi-second device_sim
        # launches asynchronously reliably crashed the tunneled TPU
        # worker ("kernel fault").  Differencing cancels only the
        # fixed per-CHAIN offset; each launch's ~110ms sync round-trip
        # stays in the denominator, so the reported wall rate is a
        # tunnel-inclusive, conservative figure.
        before = served(s)          # syncs the previous chain, untimed
        t0 = time.perf_counter()
        for _ in range(launches):
            s = step(s)
            jax.block_until_ready(s.served_resv)
        n_served = served(s) - before
        return s, n_served, time.perf_counter() - t0

    sim, _, _ = chain(1, sim)                      # warm/compile
    sim, d_lo, t_lo = chain(4, sim)
    sim, d_hi, t_hi = chain(10, sim)
    dps = (d_hi - d_lo) / (t_hi - t_lo)
    virt_s = int(np.asarray(sim.t)) / 1e9
    per_client = (np.asarray(sim.served_resv)
                  + np.asarray(sim.served_prop)).sum(axis=0)
    g2 = per_client[n // 2:].sum() / max(per_client[:n // 2].sum(), 1)
    row = {"ops_per_sec": dps, "total_ops": served(sim),
           "virtual_s": virt_s, "weight_ratio_3_1": float(g2)}
    print(f"device_sim closed loop: {dps/1e6:.2f} M ops/s wall "
          f"(weight 3:1 ratio {g2:.2f}, {d_hi} ops, "
          f"{virt_s:.1f}s virtual)")
    return row


def tpu_calendar_sweep():
    """Round-5 calendar engine rows: serve-only drain throughput by
    (m, steps) over the 100k-client weight steady state (single-chain,
    latency-corrected; chains sized to consume well under the 32M
    backlog so per-epoch commits stay representative).  The calendar
    batch has no [k] sort cap: per-batch commits are bounded by the
    per-client step budget x the population (~500k at steps=8 on
    weights 1..4) instead of the sorted engine's ~62k."""
    import functools
    import sys
    import time

    import jax
    import jax.numpy as jnp
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine.fastpath import scan_calendar_epoch
    from profile_util import scalar_latency, state_digest

    lat = scalar_latency()
    rows = []
    for m, steps, epochs in ((4, 8, 10), (8, 8, 5), (8, 16, 4)):
        run = jax.jit(functools.partial(
            scan_calendar_epoch, m=m, steps=steps, anticipation_ns=0),
            donate_argnums=(0,))
        st = _preloaded_state(100_000, 320, ring=320)
        ep = run(st, jnp.int64(0))
        jax.device_get(state_digest(ep.state))        # warm
        st = _preloaded_state(100_000, 320, ring=320)
        t0 = time.perf_counter()
        counts = []
        for _ in range(epochs):
            ep = run(st, jnp.int64(0))
            st = ep.state
            counts.append(ep.count)
        jax.device_get(state_digest(st))
        wall = time.perf_counter() - t0 - lat
        total = sum(int(jax.device_get(c).sum()) for c in counts)
        rows.append((m, steps, total / wall, total))
        print(f"calendar m={m} steps={steps}: {total/wall/1e6:.1f} "
              f"M dec/s ({total} decisions, {wall:.2f}s)")
    return rows


def tpu_allow_regime_row():
    """AtLimit::Allow on the fast paths (VERDICT r4 weak #3: the Allow
    regime ran at 0.01M on the serial scan).  A limited population
    (weights > 0, tight limits, now past every limit) serves purely
    via limit-break: measured on the flat sorted batch and the
    calendar batch."""
    import functools
    import sys
    import time

    import numpy as np
    import jax
    import jax.numpy as jnp
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                             scan_prefix_epoch)
    from profile_util import scalar_latency, state_digest

    lat = scalar_latency()

    def limited_state():
        st = _preloaded_state(100_000, 256, ring=256)
        n = 100_000
        # tight limits: limit tags already past `now`, so Wait would
        # park everyone and Allow limit-breaks every serve
        return st._replace(
            limit_inv=jnp.full((n,), 10**6, dtype=jnp.int64),
            head_limit=jnp.full((n,), 10**12, dtype=jnp.int64),
            head_ready=jnp.zeros((n,), dtype=bool))

    rows = []
    # sorted flat epochs, Allow
    run = jax.jit(functools.partial(
        scan_prefix_epoch, m=21, k=49152, anticipation_ns=0,
        allow_limit_break=True), donate_argnums=(0,))
    st = limited_state()
    ep = run(st, jnp.int64(0))
    jax.device_get(state_digest(ep.state))
    lb = bool(jax.device_get(ep.lb).any())
    st = limited_state()
    t0 = time.perf_counter()
    total = 0
    for _ in range(3):
        ep = run(st, jnp.int64(0))
        st = ep.state
        total += int(jax.device_get(ep.count).sum())
    jax.device_get(state_digest(st))
    wall = time.perf_counter() - t0 - lat
    rows.append(("Allow limit-break (sorted flat epochs)",
                 total / wall, lb))
    print(f"allow sorted: {total/wall/1e6:.1f} M dec/s (lb={lb})")

    # calendar epochs, Allow.  The epoch output has no lb aggregate,
    # so verify limit-breaks actually fire via one calendar_batch on
    # the same state (a classification regression must not let this
    # row silently measure something else).
    from dmclock_tpu.engine.fastpath import calendar_batch
    b = calendar_batch(limited_state(), jnp.int64(0), steps=8,
                       anticipation_ns=0, allow_limit_break=True)
    lb_cal = bool(jax.device_get(b.lb).sum() > 0)
    assert lb_cal, "calendar Allow row: no limit-breaks fired"
    run = jax.jit(functools.partial(
        scan_calendar_epoch, m=8, steps=8, anticipation_ns=0,
        allow_limit_break=True), donate_argnums=(0,))
    st = limited_state()
    ep = run(st, jnp.int64(0))
    jax.device_get(state_digest(ep.state))
    st = limited_state()
    t0 = time.perf_counter()
    total = 0
    for _ in range(6):
        ep = run(st, jnp.int64(0))
        st = ep.state
        total += int(jax.device_get(ep.count).sum())
    jax.device_get(state_digest(st))
    wall = time.perf_counter() - t0 - lat
    rows.append(("Allow limit-break (calendar epochs)",
                 total / wall, lb_cal))
    print(f"allow calendar: {total/wall/1e6:.1f} M dec/s")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-native", action="store_true")
    ap.add_argument("--skip-tpu", action="store_true")
    ap.add_argument("--regimes", action="store_true",
                    help="also run the regime-coverage sweep")
    ap.add_argument("--devsim", action="store_true",
                    help="also run the device-sim closed-loop headline")
    ap.add_argument("--calibrate", action="store_true",
                    help="also run the cfg4 reservation calibration "
                    "study (slow: ~9 sustained runs)")
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--calendar", action="store_true",
                    help="round-5 calendar-engine + Allow-regime rows "
                         "(prints; paste into RESULTS.md)")
    args = ap.parse_args()

    if args.calendar:
        tpu_calendar_sweep()
        tpu_allow_regime_row()
        return

    here = Path(__file__).resolve().parent
    native_part = here / ".native_section.md"
    tpu_part = here / ".tpu_section.md"
    regime_part = here / ".regime_section.md"
    sustained_part = here / ".sustained_section.md"
    devsim_part = here / ".devsim_section.md"
    calib_part = here / ".calib_section.md"

    if not args.skip_native:
        lines = ["## Native heap K-sweep (dmc_sim_100_100.conf, "
                 f"mean of {args.repeat} runs)", "",
                 "| K | add_request ns | sim wall s |", "|---|---|---|"]
        for k, add, wall in native_k_sweep(args.repeat):
            lines.append(f"| {k} | {add:.0f} | {wall:.2f} |")
        lines.append("")
        native_part.write_text("\n".join(lines))
    if not args.skip_tpu:
        import jax
        plat = jax.devices()[0].platform
        lines = [f"## TPU prefix-epoch k/m sweep (100k clients, "
                 f"platform={plat})", "",
                 "| k | m | M dec/s | fill |", "|---|---|---|---|"]
        for k, m, dps, fill in tpu_km_sweep():
            lines.append(f"| {k} | {m} | {dps/1e6:.2f} | {fill:.3f} |")
        lines.append("")
        tpu_part.write_text("\n".join(lines))
    if args.regimes:
        lines = ["## Regime coverage (prefix engine, 100k clients, "
                 "k=49152, m=21)", "",
                 "| scenario | M dec/s | fill |", "|---|---|---|"]
        for name, dps, fill in tpu_regime_sweep():
            lines.append(f"| {name} | {dps/1e6:.2f} | {fill:.3f} |")
        lines.append("")
        regime_part.write_text("\n".join(lines))
        lines = ["## Sustained closed loop, arrivals included "
                 "(BASELINE configs #3/#4)", "",
                 "| workload | M dec/s | fill | resv phase | mean "
                 "depth |", "|---|---|---|---|---|"]
        for name, r in tpu_sustained_sweep():
            lines.append(
                f"| {name} | {r['dps']/1e6:.2f} | {r['fill']:.3f} | "
                f"{r['resv_phase_frac']:.2f} | {r['mean_depth']:.0f} |")
        lines.append("")
        sustained_part.write_text("\n".join(lines))

    if args.calibrate:
        lines = ["## cfg4 reservation calibration (100k clients, Zipf, "
                 "k=49152 m=21, dt=50ms)", "",
                 "| design | resv rate /s | resv phase | fill | "
                 "M dec/s |", "|---|---|---|---|---|"]
        for name, r, out in cfg4_calibration_sweep():
            lines.append(
                f"| {name} | {r:.0f} | {out['resv_phase_frac']:.3f} | "
                f"{out['fill']:.3f} | {out['dps']/1e6:.1f} |")
        lines.append("")
        lines.append(
            "The share is monotone in the rate for every design: "
            "weight serves' reservation-debt reduction keeps mixed "
            "clients' reservation tags at the eligibility boundary, "
            "so the phases interleave per decision; the shipped cfg4 "
            "is mixed-staggered at the rate whose share is ~0.5 "
            "(bench.CFG4_RESV_RATE).")
        lines.append("")
        calib_part.write_text("\n".join(lines))

    if args.devsim:
        row = device_sim_headline()
        lines = ["## Device-sim closed loop (100k clients, prefix "
                 "serve q=4096, random selection, 2-thread servers, "
                 "one chip)", "",
                 "| M ops/s (wall) | total ops | virtual s | "
                 "weight 3:1 ratio |", "|---|---|---|---|",
                 f"| {row['ops_per_sec']/1e6:.2f} | "
                 f"{row['total_ops']} | {row['virtual_s']:.1f} | "
                 f"{row['weight_ratio_3_1']:.2f} |", ""]
        devsim_part.write_text("\n".join(lines))

    head = ["# Benchmark sweeps", "",
            "Produced by `python benchmark/run_sweeps.py` "
            "(see its docstring).", ""]
    body = [p.read_text() for p in (native_part, tpu_part, regime_part,
                                    sustained_part, devsim_part,
                                    calib_part)
            if p.exists()]
    RESULTS.write_text("\n".join(head + body))
    print(f"wrote {RESULTS}")


if __name__ == "__main__":
    main()
