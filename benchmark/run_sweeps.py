#!/usr/bin/env python
"""Benchmark sweeps (the reference benchmark/ pipeline analog,
``benchmark/data_gen.sh:28-38`` + ``plot_gen.sh``):

1. K-sweep: native ``dmc_sim_native --k-way K`` for K=2..10 over the
   acceptance config, harvesting the mean ns-per-call numbers the
   reference pipeline greps (``simulate.h:306-349``).  The reference's
   rule of thumb ("<= 6 elements: K small; otherwise K=3",
   benchmark/README.md:17-19) is what this reproduces with runtime K.
2. TPU k/m sweep: ``scan_fast_epoch`` decisions/sec at 100k clients
   across speculative batch size k and epoch length m (the analog of
   the K_WAY_HEAP study for the batch engine: k trades selection-sort
   amortization against speculation-window validity).

Writes benchmark/RESULTS.md.  Usage:
    python benchmark/run_sweeps.py [--skip-native] [--skip-tpu]
        [--repeat N]
"""

from __future__ import annotations

import argparse
import functools
import re
import statistics
import subprocess
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "native" / "build"
RESULTS = Path(__file__).resolve().parent / "RESULTS.md"


def build_native() -> Path:
    exe = BUILD / "dmc_sim_native"
    subprocess.run(["cmake", "-S", str(REPO / "native"), "-B",
                    str(BUILD)], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", str(BUILD), "-j", "--target",
                    "dmc_sim_native"], check=True, capture_output=True)
    return exe


def native_k_sweep(repeat: int):
    exe = build_native()
    # the reference sweep's workload (benchmark/configs/
    # dmc_sim_100_100.conf): 100 servers x 100 clients, 1M ops
    conf = REPO / "configs" / "dmc_sim_100_100.conf"
    rows = []
    for k in range(2, 11):
        add_ns, wall = [], []
        for r in range(repeat):
            t0 = time.perf_counter()
            out = subprocess.run(
                [str(exe), "-c", str(conf), "--k-way", str(k),
                 "--seed", str(12345 + r)],
                check=True, capture_output=True, text=True,
                timeout=600).stdout
            wall.append(time.perf_counter() - t0)
            m = re.search(r"average add_request:\s+(\d+) ns", out)
            add_ns.append(int(m.group(1)))
        rows.append((k, statistics.mean(add_ns),
                     statistics.mean(wall)))
        print(f"K={k}: add_request {rows[-1][1]:.0f} ns "
              f"(wall {rows[-1][2]:.2f}s)")
    return rows


def _timed_epochs(state, now_ns, epochs, k, m, lat, *, recovery=False):
    """Shared per-epoch-sync timing harness for the sweeps: warm one
    epoch, time ``epochs`` more with a per-epoch ok readback (latency-
    corrected), optionally recovering stalls with one exact serial
    4096-batch.  bench.py's async-chained headline protocol is kept
    separate by design (see its docstring).  Returns (decisions/sec,
    fallback_rate, serial_recoveries)."""
    import jax
    import jax.numpy as jnp
    from dmclock_tpu.engine import kernels
    from dmclock_tpu.engine.fastpath import scan_fast_epoch
    from profile_util import state_digest

    run = jax.jit(functools.partial(
        scan_fast_epoch, m=m, k=k, anticipation_ns=0),
        donate_argnums=(0,))
    serial = jax.jit(lambda s, t: kernels.engine_run(
        s, t, 4096, allow_limit_break=False, anticipation_ns=0,
        advance_now=False))
    if recovery:
        _ = serial(state, jnp.int64(now_ns))       # compile
    ep = run(state, jnp.int64(now_ns))
    jax.device_get(state_digest(ep.state))         # warm
    state = ep.state

    t0 = time.perf_counter()
    committed = serial_dec = recoveries = trips = 0
    for _ in range(epochs):
        ep = run(state, jnp.int64(now_ns))
        state = ep.state
        ok = jax.device_get(ep.ok)
        trips += 1
        committed += int(ok.sum())
        if recovery and not ok.all():
            state, _, decs = serial(state, jnp.int64(now_ns))
            serial_dec += int(jax.device_get(
                (decs.type == kernels.RETURNING).sum()))
            trips += 1
            recoveries += 1
    jax.device_get(state_digest(state))
    trips += 1
    t = time.perf_counter() - t0 - lat * trips
    total = committed * k + serial_dec
    return total / t, 1 - committed / (epochs * m), recoveries


def tpu_km_sweep():
    import sys
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from profile_util import scalar_latency

    n, depth = 100_000, 128
    rows = []
    lat = scalar_latency()
    for k in (8192, 16384, 32768, 49152):
        for m in (8, 32):
            state = _preloaded_state(n, depth, ring=depth)
            epochs = max(1, (1 << 21) // (m * k))
            dps, fb, _rec = _timed_epochs(state, 0, epochs, k, m, lat)
            rows.append((k, m, dps, fb))
            print(f"k={k} m={m}: {dps/1e6:.2f} M dec/s "
                  f"(fallback {fb:.3f})")
    return rows


def tpu_regime_sweep():
    """Decisions/sec by REGIME, beyond the headline's weight-only
    steady state: pure reservation backlog (constraint phase every
    decision), a reservation->weight transition (forces speculation
    failures + serial recovery at the boundary), and the exact serial
    engine as the floor."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import sys
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine import kernels
    from profile_util import scalar_latency, state_digest

    n, depth, k, m = 100_000, 128, 32768, 32
    lat = scalar_latency()
    rows = []

    def run_epochs(state, now_ns, epochs):
        return _timed_epochs(state, now_ns, epochs, k, m, lat,
                             recovery=True)

    def resv_state():
        st = _preloaded_state(n, depth, ring=depth)
        # stagger reservation phases over the serve period (2*rinv)
        c = np.arange(n)
        phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
        rinv = np.asarray(st.resv_inv)
        jit = (phase * 2.0 * rinv).astype(np.int64)
        return st._replace(head_resv=jnp.asarray(rinv + jit))

    # pure reservation regime: now far beyond every reservation tag
    dps, fb, rec = run_epochs(resv_state(), 10**15, 4)
    rows.append(("reservation backlog", dps, fb, rec))
    print(f"reservation: {dps/1e6:.2f} M dec/s fallback {fb:.3f}")

    # transition: only ~3 batches of reservation serves are eligible,
    # then the regime flips to weight mid-run (speculation must fail
    # and serially recover at the boundary)
    st = resv_state()
    now = int(np.asarray(st.head_resv).min()) + 2 * 10**7
    dps, fb, rec = run_epochs(st, now, 4)
    rows.append(("resv->weight transition", dps, fb, rec))
    print(f"transition: {dps/1e6:.2f} M dec/s fallback {fb:.3f} "
          f"recoveries {rec}")

    # weight regime baseline at the same epoch budget
    dps, fb, rec = run_epochs(_preloaded_state(n, depth, ring=depth),
                              0, 4)
    rows.append(("weight steady state", dps, fb, rec))
    print(f"weight: {dps/1e6:.2f} M dec/s fallback {fb:.3f}")

    # exact serial engine floor
    state = _preloaded_state(n, depth, ring=depth)
    serial = jax.jit(lambda s, t: kernels.engine_run(
        s, t, 4096, allow_limit_break=False, anticipation_ns=0,
        advance_now=False))
    state, _, decs = serial(state, jnp.int64(0))
    jax.device_get(state_digest(state))
    t0 = time.perf_counter()
    state, _, decs = serial(state, jnp.int64(0))
    jax.device_get(state_digest(state))
    t = time.perf_counter() - t0 - lat
    rows.append(("exact serial engine", 4096 / t, 0.0, 0))
    print(f"serial exact: {4096/t/1e3:.1f} k dec/s")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-native", action="store_true")
    ap.add_argument("--skip-tpu", action="store_true")
    ap.add_argument("--regimes", action="store_true",
                    help="also run the regime-coverage sweep")
    ap.add_argument("--repeat", type=int, default=3)
    args = ap.parse_args()

    here = Path(__file__).resolve().parent
    native_part = here / ".native_section.md"
    tpu_part = here / ".tpu_section.md"
    regime_part = here / ".regime_section.md"

    if not args.skip_native:
        lines = ["## Native heap K-sweep (dmc_sim_100_100.conf, "
                 f"mean of {args.repeat} runs)", "",
                 "| K | add_request ns | sim wall s |", "|---|---|---|"]
        for k, add, wall in native_k_sweep(args.repeat):
            lines.append(f"| {k} | {add:.0f} | {wall:.2f} |")
        lines.append("")
        native_part.write_text("\n".join(lines))
    if not args.skip_tpu:
        import jax
        plat = jax.devices()[0].platform
        lines = [f"## TPU epoch k/m sweep (100k clients, platform="
                 f"{plat})", "",
                 "| k | m | M dec/s | fallback rate |", "|---|---|---|---|"]
        for k, m, dps, fb in tpu_km_sweep():
            lines.append(f"| {k} | {m} | {dps/1e6:.2f} | {fb:.3f} |")
        lines.append("")
        tpu_part.write_text("\n".join(lines))
    if args.regimes:
        lines = ["## Regime coverage (100k clients, k=32768, m=32)", "",
                 "| scenario | M dec/s | fallback rate | serial "
                 "recoveries |", "|---|---|---|---|"]
        for name, dps, fb, rec in tpu_regime_sweep():
            lines.append(f"| {name} | {dps/1e6:.2f} | {fb:.3f} | "
                         f"{rec} |")
        lines.append("")
        regime_part.write_text("\n".join(lines))

    head = ["# Benchmark sweeps", "",
            "Produced by `python benchmark/run_sweeps.py` "
            "(see its docstring).", ""]
    body = [p.read_text() for p in (native_part, tpu_part, regime_part)
            if p.exists()]
    RESULTS.write_text("\n".join(head + body))
    print(f"wrote {RESULTS}")


if __name__ == "__main__":
    main()
