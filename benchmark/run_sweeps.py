#!/usr/bin/env python
"""Benchmark sweeps (the reference benchmark/ pipeline analog,
``benchmark/data_gen.sh:28-38`` + ``plot_gen.sh``):

1. K-sweep: native ``dmc_sim_native --k-way K`` for K=2..10 over the
   acceptance config, harvesting the mean ns-per-call numbers the
   reference pipeline greps (``simulate.h:306-349``).  The reference's
   rule of thumb ("<= 6 elements: K small; otherwise K=3",
   benchmark/README.md:17-19) is what this reproduces with runtime K.
2. TPU k/m sweep: ``scan_prefix_epoch`` decisions/sec at 100k clients
   across batch size k and epoch length m (the analog of the
   K_WAY_HEAP study for the batch engine: k amortizes the selection
   sort; prefix-commit makes k past the re-entry distance a fill
   degradation instead of a cliff).

Writes benchmark/RESULTS.md.  Usage:
    python benchmark/run_sweeps.py [--skip-native] [--skip-tpu]
        [--repeat N]
"""

from __future__ import annotations

import argparse
import functools
import re
import statistics
import subprocess
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "native" / "build"
RESULTS = Path(__file__).resolve().parent / "RESULTS.md"


def build_native() -> Path:
    exe = BUILD / "dmc_sim_native"
    subprocess.run(["cmake", "-S", str(REPO / "native"), "-B",
                    str(BUILD)], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", str(BUILD), "-j", "--target",
                    "dmc_sim_native"], check=True, capture_output=True)
    return exe


def native_k_sweep(repeat: int):
    exe = build_native()
    # the reference sweep's workload (benchmark/configs/
    # dmc_sim_100_100.conf): 100 servers x 100 clients, 1M ops
    conf = REPO / "configs" / "dmc_sim_100_100.conf"
    rows = []
    for k in range(2, 11):
        add_ns, wall = [], []
        for r in range(repeat):
            t0 = time.perf_counter()
            out = subprocess.run(
                [str(exe), "-c", str(conf), "--k-way", str(k),
                 "--seed", str(12345 + r)],
                check=True, capture_output=True, text=True,
                timeout=600).stdout
            wall.append(time.perf_counter() - t0)
            m = re.search(r"average add_request:\s+(\d+) ns", out)
            add_ns.append(int(m.group(1)))
        rows.append((k, statistics.mean(add_ns),
                     statistics.mean(wall)))
        print(f"K={k}: add_request {rows[-1][1]:.0f} ns "
              f"(wall {rows[-1][2]:.2f}s)")
    return rows


def _timed_prefix_epochs(state, now_ns, epochs, k, m, lat):
    """Per-epoch-sync timing on the prefix-commit engine: every batch
    commits its longest exact serial prefix, so there is no fallback or
    recovery path -- the decision count is the sum of per-batch commit
    counts.  Returns (decisions/sec, fill)."""
    import jax
    import jax.numpy as jnp
    from dmclock_tpu.engine.fastpath import scan_prefix_epoch
    from profile_util import state_digest

    run = jax.jit(functools.partial(
        scan_prefix_epoch, m=m, k=k, anticipation_ns=0),
        donate_argnums=(0,))
    # the tunneled remote-compile endpoint occasionally drops a
    # response mid-read; one retry covers it (the cache makes the
    # second attempt cheap).  Only runtime/transport errors are
    # retried -- a trace-time programming error (TypeError, shape
    # mismatch) must fail fast with its original traceback.  Retry
    # ONLY if the donated input buffer survived: a post-dispatch
    # failure consumes it, and retrying would mask the original error
    # with a deleted-buffer error.
    for attempt in (0, 1):
        try:
            ep = run(state, jnp.int64(now_ns))
            break
        except jax.errors.JaxRuntimeError:
            if attempt or any(
                    getattr(x, "is_deleted", lambda: False)()
                    for x in jax.tree_util.tree_leaves(state)):
                raise
            time.sleep(2)
    jax.device_get(state_digest(ep.state))
    state = ep.state

    # epochs chained ASYNC (no mid-run readback): one digest sync is
    # timed and one latency subtracted; commit counts are fetched
    # untimed afterwards.  (A per-epoch sync'd variant subtracted
    # lat*trips, which overwhelms short chains through the ~110ms
    # tunnel and can go negative.)
    t0 = time.perf_counter()
    counts = []
    for _ in range(epochs):
        ep = run(state, jnp.int64(now_ns))
        state = ep.state
        counts.append(ep.count)
    jax.device_get(state_digest(state))
    t = time.perf_counter() - t0 - lat
    total = int(sum(int(jax.device_get(c).sum()) for c in counts))
    assert t > 0, f"timing underflow: {t:.4f}s for {epochs} epochs"
    return total / t, total / (epochs * m * k)


def tpu_km_sweep():
    import sys
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from profile_util import scalar_latency

    n, depth = 100_000, 128
    rows = []
    lat = scalar_latency()
    for k in (8192, 16384, 32768, 49152, 65536, 98304):
        for m in (8, 32):
            state = _preloaded_state(n, depth, ring=depth)
            epochs = max(2, (1 << 23) // (m * k))
            dps, fill = _timed_prefix_epochs(state, 0, epochs, k, m, lat)
            rows.append((k, m, dps, fill))
            print(f"k={k} m={m}: {dps/1e6:.2f} M dec/s "
                  f"(fill {fill:.3f})")
    return rows


def tpu_regime_sweep():
    """Decisions/sec by REGIME on the prefix-commit engine: pure
    reservation backlog (constraint phase every decision), a
    reservation->weight transition mid-run (the prefix stops exactly at
    the flip and the next batch switches regime -- formerly the serial-
    recovery cliff), the weight steady state, and the exact serial
    engine as the floor."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import sys
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _preloaded_state
    from dmclock_tpu.engine import kernels
    from profile_util import scalar_latency, state_digest

    n, depth, k, m = 100_000, 128, 49152, 21
    lat = scalar_latency()
    rows = []

    def resv_state():
        st = _preloaded_state(n, depth, ring=depth)
        # stagger reservation phases over the serve period (2*rinv)
        c = np.arange(n)
        phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
        rinv = np.asarray(st.resv_inv)
        jit = (phase * 2.0 * rinv).astype(np.int64)
        return st._replace(head_resv=jnp.asarray(rinv + jit))

    # pure reservation regime: now far beyond every reservation tag
    dps, fill = _timed_prefix_epochs(resv_state(), 10**15, 8, k, m, lat)
    rows.append(("reservation backlog", dps, fill))
    print(f"reservation: {dps/1e6:.2f} M dec/s fill {fill:.3f}")

    # transition: only a few batches of reservation serves are
    # eligible, then the regime flips to weight mid-epoch
    st = resv_state()
    now = int(np.asarray(st.head_resv).min()) + 2 * 10**7
    dps, fill = _timed_prefix_epochs(st, now, 8, k, m, lat)
    rows.append(("resv->weight transition", dps, fill))
    print(f"transition: {dps/1e6:.2f} M dec/s fill {fill:.3f}")

    # weight regime baseline at the same epoch budget
    dps, fill = _timed_prefix_epochs(
        _preloaded_state(n, depth, ring=depth), 0, 8, k, m, lat)
    rows.append(("weight steady state", dps, fill))
    print(f"weight: {dps/1e6:.2f} M dec/s fill {fill:.3f}")

    # exact serial engine floor
    state = _preloaded_state(n, depth, ring=depth)
    serial = jax.jit(lambda s, t: kernels.engine_run(
        s, t, 4096, allow_limit_break=False, anticipation_ns=0,
        advance_now=False))
    state, _, decs = serial(state, jnp.int64(0))
    jax.device_get(state_digest(state))
    t0 = time.perf_counter()
    state, _, decs = serial(state, jnp.int64(0))
    jax.device_get(state_digest(state))
    t = time.perf_counter() - t0 - lat
    rows.append(("exact serial engine", 4096 / t, 1.0))
    print(f"serial exact: {4096/t/1e3:.1f} k dec/s")
    return rows


def tpu_sustained_sweep():
    """BASELINE configs #3/#4: the sustained closed loop (Poisson
    superwave ingest + prefix epochs) as measured by bench.py."""
    import sys
    sys.path.insert(0, str(REPO))
    from bench import bench_sustained

    rows = []
    r3 = bench_sustained(10_000, 4096, 32, 20, zipf=False,
                         resv_rate=100.0, dt_round_ns=100_000_000,
                         ring=256, depth0=128)
    rows.append(("cfg3: 10k clients, uniform QoS, Poisson", r3))
    print(f"cfg3: {r3['dps']/1e6:.2f} M dec/s")
    r4 = bench_sustained(100_000, 49152, 21, 10, zipf=True,
                         resv_rate=100.0, dt_round_ns=50_000_000)
    rows.append(("cfg4: 100k clients, Zipf weights, resv-constrained",
                 r4))
    print(f"cfg4: {r4['dps']/1e6:.2f} M dec/s")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-native", action="store_true")
    ap.add_argument("--skip-tpu", action="store_true")
    ap.add_argument("--regimes", action="store_true",
                    help="also run the regime-coverage sweep")
    ap.add_argument("--repeat", type=int, default=3)
    args = ap.parse_args()

    here = Path(__file__).resolve().parent
    native_part = here / ".native_section.md"
    tpu_part = here / ".tpu_section.md"
    regime_part = here / ".regime_section.md"
    sustained_part = here / ".sustained_section.md"

    if not args.skip_native:
        lines = ["## Native heap K-sweep (dmc_sim_100_100.conf, "
                 f"mean of {args.repeat} runs)", "",
                 "| K | add_request ns | sim wall s |", "|---|---|---|"]
        for k, add, wall in native_k_sweep(args.repeat):
            lines.append(f"| {k} | {add:.0f} | {wall:.2f} |")
        lines.append("")
        native_part.write_text("\n".join(lines))
    if not args.skip_tpu:
        import jax
        plat = jax.devices()[0].platform
        lines = [f"## TPU prefix-epoch k/m sweep (100k clients, "
                 f"platform={plat})", "",
                 "| k | m | M dec/s | fill |", "|---|---|---|---|"]
        for k, m, dps, fill in tpu_km_sweep():
            lines.append(f"| {k} | {m} | {dps/1e6:.2f} | {fill:.3f} |")
        lines.append("")
        tpu_part.write_text("\n".join(lines))
    if args.regimes:
        lines = ["## Regime coverage (prefix engine, 100k clients, "
                 "k=49152, m=21)", "",
                 "| scenario | M dec/s | fill |", "|---|---|---|"]
        for name, dps, fill in tpu_regime_sweep():
            lines.append(f"| {name} | {dps/1e6:.2f} | {fill:.3f} |")
        lines.append("")
        regime_part.write_text("\n".join(lines))
        lines = ["## Sustained closed loop, arrivals included "
                 "(BASELINE configs #3/#4)", "",
                 "| workload | M dec/s | fill | resv phase | mean "
                 "depth |", "|---|---|---|---|---|"]
        for name, r in tpu_sustained_sweep():
            lines.append(
                f"| {name} | {r['dps']/1e6:.2f} | {r['fill']:.3f} | "
                f"{r['resv_phase_frac']:.2f} | {r['mean_depth']:.0f} |")
        lines.append("")
        sustained_part.write_text("\n".join(lines))

    head = ["# Benchmark sweeps", "",
            "Produced by `python benchmark/run_sweeps.py` "
            "(see its docstring).", ""]
    body = [p.read_text() for p in (native_part, tpu_part, regime_part,
                                    sustained_part)
            if p.exists()]
    RESULTS.write_text("\n".join(head + body))
    print(f"wrote {RESULTS}")


if __name__ == "__main__":
    main()
