"""Distributed layer: psum ServiceTracker parity + sharded cluster step.

The device tracker must reproduce the host ``OrigTracker`` delta/rho
sequences exactly (reference ``test/test_dmclock_client.cc:231-304``
pins the same algebra), and the cluster step must run sharded over the
virtual 8-device CPU mesh with its psum collective.
"""

import functools
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import Phase
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.core.tracker import (BorrowingTracker, OrigTracker,
                                      ServiceTracker)
from dmclock_tpu.parallel import (cluster as CL, borrow_tracker_prepare,
                                  borrow_tracker_track,
                                  init_borrow_tracker, init_tracker,
                                  tracker_prepare, tracker_track)


def test_device_tracker_matches_orig_tracker():
    """Random interleaving of requests/responses across servers: the
    device algebra must equal host OrigTracker's ReqParams stream."""
    rng = random.Random(7)
    n_servers, n_steps = 3, 300

    host = ServiceTracker(run_gc_thread=False)
    # device trackers: one TrackerState per server, single client slot 0
    dev = [init_tracker(1) for _ in range(n_servers)]

    def dev_global():
        d = 1 + sum(int(t.completed_delta[0]) for t in dev)
        r = 1 + sum(int(t.completed_rho[0]) for t in dev)
        return d, r

    outstanding = []
    for _ in range(n_steps):
        if outstanding and rng.random() < 0.5:
            s, phase, cost = outstanding.pop(rng.randrange(len(outstanding)))
            host.track_resp(s, phase, cost)
            dev[s] = tracker_track(
                dev[s], jnp.zeros(1, jnp.int32),
                jnp.full(1, cost, jnp.int64),
                jnp.full(1, int(phase), jnp.int32),
                jnp.ones(1, bool))
        else:
            s = rng.randrange(n_servers)
            rp = host.get_req_params(s)
            gd, gr = dev_global()
            dev[s], d_out, r_out = tracker_prepare(
                dev[s], jnp.ones(1, bool),
                jnp.full(1, gd, jnp.int64), jnp.full(1, gr, jnp.int64))
            assert (int(d_out[0]), int(r_out[0])) == (rp.delta, rp.rho), \
                f"server {s}: device ({int(d_out[0])},{int(r_out[0])}) " \
                f"!= host ({rp.delta},{rp.rho})"
            phase = Phase.RESERVATION if rng.random() < 0.5 \
                else Phase.PRIORITY
            outstanding.append((s, phase, rng.randint(1, 3)))


def test_device_tracker_matches_borrowing_tracker():
    """Same interleaving gate for the BorrowingTracker variant
    (reference dmclock_client.h:90-154; host parity pinned by
    test_tracker.py against test_dmclock_client.cc:108-225)."""
    rng = random.Random(11)
    n_servers, n_steps = 3, 300

    host = ServiceTracker(tracker_cls=BorrowingTracker,
                          run_gc_thread=False)
    dev = [init_borrow_tracker(1) for _ in range(n_servers)]

    def dev_global():
        d = 1 + sum(int(t.completed_delta[0]) for t in dev)
        r = 1 + sum(int(t.completed_rho[0]) for t in dev)
        return d, r

    outstanding = []
    for _ in range(n_steps):
        if outstanding and rng.random() < 0.5:
            s, phase, cost = outstanding.pop(rng.randrange(len(outstanding)))
            host.track_resp(s, phase, cost)
            dev[s] = borrow_tracker_track(
                dev[s], jnp.zeros(1, jnp.int32),
                jnp.full(1, cost, jnp.int64),
                jnp.full(1, int(phase), jnp.int32),
                jnp.ones(1, bool))
        else:
            s = rng.randrange(n_servers)
            rp = host.get_req_params(s)
            gd, gr = dev_global()
            dev[s], d_out, r_out = borrow_tracker_prepare(
                dev[s], jnp.ones(1, bool),
                jnp.full(1, gd, jnp.int64), jnp.full(1, gr, jnp.int64))
            assert (int(d_out[0]), int(r_out[0])) == (rp.delta, rp.rho), \
                f"server {s}: device ({int(d_out[0])},{int(r_out[0])}) " \
                f"!= host ({rp.delta},{rp.rho})"
            # borrowing guarantees strictly positive params
            assert int(d_out[0]) >= 1 and int(r_out[0]) >= 1
            phase = Phase.RESERVATION if rng.random() < 0.5 \
                else Phase.PRIORITY
            outstanding.append((s, phase, rng.randint(1, 3)))


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return CL.make_mesh(8)


def _make_cluster(n_servers, n_clients, reservation=10.0):
    cl = CL.init_cluster(n_servers, n_clients)
    rinv = jnp.full((n_clients,), rate_to_inv_ns(reservation),
                    dtype=jnp.int64)
    winv = jnp.asarray([rate_to_inv_ns(1.0 + (i % 3))
                        for i in range(n_clients)], dtype=jnp.int64)
    linv = jnp.zeros((n_clients,), dtype=jnp.int64)
    return CL.install_clients(cl, rinv, winv, linv)


def test_cluster_step_sharded(mesh8):
    n_servers, n_clients = 8, 16
    cl = _make_cluster(n_servers, n_clients)
    cl = CL.shard_cluster(cl, mesh8)
    step = jax.jit(functools.partial(
        CL.cluster_step, mesh=mesh8, cost=1, decisions_per_step=16))
    arrivals = jnp.ones((n_servers, n_clients), dtype=jnp.int32)

    cl, decs = step(cl, arrivals)
    served = np.asarray(decs.type) == 0
    assert served.sum() == n_servers * n_clients  # all requests served
    # every server served every client exactly once
    slots = np.asarray(decs.slot)
    for s in range(n_servers):
        assert sorted(slots[s][served[s]]) == list(range(n_clients))
    # completion counters: each server recorded one completion/client
    assert np.asarray(cl.tracker.completed_delta).sum() \
        == n_servers * n_clients

    # second round: ReqParams now flow from the psum'd counters
    cl, decs = step(cl, arrivals)
    assert (np.asarray(decs.type) == 0).sum() == n_servers * n_clients
    # rho/delta reached the engine: cur_delta holds last ReqParams.delta,
    # which after round 2 must reflect the other servers' traffic
    cur_delta = np.asarray(cl.engine.cur_delta)
    assert cur_delta.max() > 1


@pytest.mark.parametrize("tracker_kind", ["orig", "borrowing"])
def test_cluster_step_matches_independent_host_sims(mesh8, tracker_kind):
    """The whole cluster step equals S independent host oracle queues +
    per-client host ServiceTrackers (Orig or Borrowing accounting) fed
    the same arrival schedule: per round, every server's full
    k-decision stream (type/slot/phase/cost/when), its virtual clock,
    and the ReqParams flowing into every ingest must match the host
    composition exactly."""
    from dmclock_tpu.core import ClientInfo, PullPriorityQueue, ReqParams
    from dmclock_tpu.core.scheduler import NextReqType

    n_servers, n_clients, rounds, k, max_arr = 8, 12, 3, 16, 3
    infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
             for c in range(n_clients)]

    # --- device cluster
    cl = CL.init_cluster(n_servers, n_clients,
                         tracker_kind=tracker_kind)
    cl = CL.install_clients(
        cl,
        jnp.asarray([i.reservation_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64))
    cl = CL.shard_cluster(cl, mesh8)
    step = jax.jit(functools.partial(
        CL.cluster_step, mesh=mesh8, cost=1, decisions_per_step=k,
        max_arrivals=max_arr))

    # --- host composition: S oracle queues + C ServiceTrackers
    queues = [PullPriorityQueue(lambda c, i=s: infos[c],
                                delayed_tag_calc=True,
                                run_gc_thread=False)
              for s in range(n_servers)]
    host_cls = {"orig": OrigTracker,
                "borrowing": BorrowingTracker}[tracker_kind]
    trackers = [ServiceTracker(tracker_cls=host_cls, run_gc_thread=False)
                for _ in range(n_clients)]
    host_now = [0] * n_servers

    rng = random.Random(23)
    for rnd in range(rounds + 1):
        if rnd == 0:
            # Warmup: every client contacts every server once.  The
            # cluster's tie-break convention is order == client slot
            # (install_clients); the host oracle assigns creation order
            # at first contact, so first contacts must happen in client
            # index order for the two compositions to share a tie rank.
            arrivals = np.ones((n_servers, n_clients), dtype=np.int32)
        else:
            arrivals = np.asarray(
                [[rng.randint(0, max_arr) for _ in range(n_clients)]
                 for _ in range(n_servers)], dtype=np.int32)

        # device round
        cl, decs = step(cl, jnp.asarray(arrivals))
        d_type = np.asarray(decs.type)
        d_slot = np.asarray(decs.slot)
        d_phase = np.asarray(decs.phase)
        d_cost = np.asarray(decs.cost)
        d_when = np.asarray(decs.when)
        d_now = np.asarray(cl.now)

        # host round, replicating the cluster's phase structure: ALL
        # servers ingest against the pre-round tracker state (the psum
        # is computed once per round), THEN every server pulls, THEN
        # responses fold -- interleaving per server would let server 0's
        # completions leak into server 1's ReqParams mid-round
        for s in range(n_servers):
            # phase A: wave-major ingest with tracker-derived params
            for wave in range(max_arr):
                for c in range(n_clients):
                    if arrivals[s][c] > wave:
                        rp = trackers[c].get_req_params(s)
                        queues[s].add_request(
                            (rnd, wave, c), c,
                            ReqParams(rp.delta, rp.rho),
                            time_ns=host_now[s], cost=1)
        for s in range(n_servers):
            # phase B: k pulls with advance-on-FUTURE clock semantics
            responses = []
            for i in range(k):
                pr = queues[s].pull_request(host_now[s])
                if pr.type is NextReqType.RETURNING:
                    assert (d_type[s][i], d_slot[s][i], d_phase[s][i],
                            d_cost[s][i]) == \
                        (0, pr.client, int(pr.phase is Phase.PRIORITY),
                         pr.cost), \
                        f"round {rnd} server {s} step {i}"
                    responses.append((pr.client, pr.phase, pr.cost))
                elif pr.type is NextReqType.FUTURE:
                    assert (d_type[s][i], d_when[s][i]) == \
                        (1, pr.when_ready), \
                        f"round {rnd} server {s} step {i} FUTURE"
                    host_now[s] = pr.when_ready
                else:
                    assert d_type[s][i] == 2, \
                        f"round {rnd} server {s} step {i} NONE"
            assert host_now[s] == d_now[s], f"round {rnd} server {s} now"
            # phase C: responses fold into the client trackers
            for client, phase, cost in responses:
                trackers[client].track_resp(s, phase, cost)


def test_cluster_counters_match_protocol(mesh8):
    """delta seen by a server == completions that client got everywhere
    since its previous request to that server (the dmClock invariant)."""
    n_servers, n_clients = 8, 4
    cl = _make_cluster(n_servers, n_clients)
    cl = CL.shard_cluster(cl, mesh8)
    step = jax.jit(functools.partial(
        CL.cluster_step, mesh=mesh8, cost=1, decisions_per_step=8))
    arrivals = jnp.ones((n_servers, n_clients), dtype=jnp.int32)
    cl, _ = step(cl, arrivals)
    cl, _ = step(cl, arrivals)
    # after round 1 each client completed once on each of 8 servers; a
    # round-2 request to server s sees delta = 1 (global start) ... plus
    # 8 completions minus bookkeeping; just pin the exact invariant:
    # all servers saw the same delta for a given client
    cur_delta = np.asarray(cl.engine.cur_delta)  # [S, C]
    assert (cur_delta == cur_delta[0]).all()
    # OrigTracker algebra: completions everywhere since the previous
    # request to this server, MINUS own completions there -> S - 1
    assert cur_delta[0, 0] == n_servers - 1
