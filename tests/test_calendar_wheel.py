"""Differential tests for the timer-wheel calendar engine.

``calendar_batch_wheel`` promises: the committed set, counters, and
final state are BIT-identical to ``calendar_batch_bucketed`` at the
same ``levels`` (and therefore to the serial engine -- the bucketed
suite pins that leg), with the ladder boundaries read from a
maintained [3, B] bucket-min index instead of dense [N] rebuilds.
The wheel-specific contracts pinned here:

- **adjust == rebuild**: ``wheel_adjust`` over exactly the clients
  whose (class, key) changed -- a fixed-now commit's served set, a
  live QoS update's target, an idle re-entry, a churn boundary
  re-slot -- equals ``wheel_build`` of the new state bit for bit;
- **first-occupied-bucket min == dense masked min** for entry packs
  (``wheel_origins``) and stop packs (``_wheel_stop_min``), the
  exactness identity the whole engine rests on (the bucket index is
  monotone in the key, so geometry affects discrimination only);
- **Pallas parity**: ``wheel_kernel="pallas"`` under
  ``DMCLOCK_WHEEL_INTERPRET=1`` is bit-identical to the XLA kernel,
  and off-TPU without interpret mode falls back cleanly and counts
  ``wheel_pallas_fallbacks``.

Compile-heavy shapes carry ``@pytest.mark.slow`` (the tier-1 budget
discipline of test_calendar_bucketed.py); scripts/run_tests.sh and
the ci.sh wheel smoke run everything.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import fastpath as FP
from dmclock_tpu.engine import kernels

from engine_helpers import assert_states_equal, deep_state
from test_calendar_bucketed import (_JIT, ladder_batch, minstop_batch,
                                    zipf64_state)
from test_prefix import mixed_qos_state, serial_run_lb

S = NS_PER_SEC


def wheel_batch(state, now, steps, levels, *, allow=False,
                wheel_kernel="xla"):
    key = ("wheel", state.capacity, state.ring_capacity, steps,
           levels, allow, wheel_kernel)
    if key not in _JIT:
        _JIT[key] = jax.jit(functools.partial(
            FP.calendar_batch_wheel, steps=steps, levels=levels,
            anticipation_ns=0, allow_limit_break=allow,
            wheel_kernel=wheel_kernel))
    return _JIT[key](state, jnp.int64(now))


_BATCH_FIELDS = ("count", "resv_count", "units", "served",
                 "served_resv", "lb", "progress_ok", "level_count",
                 "level_bound", "level_stall", "served_cost")


def assert_batches_equal(a, b):
    for f in _BATCH_FIELDS:
        assert bool(jnp.array_equal(getattr(a, f), getattr(b, f))), \
            f"wheel batch field {f} diverged"
    assert_states_equal(a.state, b.state)


def check_wheel_vs_serial(state, now, steps, levels, *, allow=False):
    """One wheel batch vs the serial engine for ``count`` steps (the
    test_calendar_bucketed differential, on the wheel path)."""
    b = wheel_batch(state, now, steps, levels, allow=allow)
    c = int(b.count)
    if c == 0:
        assert_states_equal(b.state, state)
        return b.state, 0
    ser_state, ser = serial_run_lb(state, now, c, allow)
    assert (ser.type == kernels.RETURNING).all()
    served = np.zeros(state.capacity, np.int32)
    np.add.at(served, ser.slot, 1)
    assert np.array_equal(served, jax.device_get(b.served))
    assert_states_equal(b.state, ser_state)
    return b.state, c


# ----------------------------------------------------------------------
# batch differentials: wheel == bucketed == serial
# ----------------------------------------------------------------------

def test_wheel_matches_bucketed_bitwise():
    """The headline batch gate: wheel == bucketed on every output
    field and the full state, driven over successive batches of the
    cfg4 cutter shape."""
    st_w = st_b = zipf64_state(n=10, depth=32)
    committed = 0
    for _ in range(3):
        bw = wheel_batch(st_w, 500 * S, 8, 3)
        bb = ladder_batch(st_b, 500 * S, 8, 3)
        assert_batches_equal(bw, bb)
        committed += int(bw.count)
        st_w, st_b = bw.state, bb.state
    assert committed > 0


def test_wheel_matches_serial():
    st, c = check_wheel_vs_serial(zipf64_state(n=10, depth=32),
                                  500 * S, 8, 2)
    assert c > 0
    check_wheel_vs_serial(st, 500 * S, 8, 2)


@pytest.mark.slow
def test_wheel_l1_bit_identical_to_minstop():
    """levels=1 wheel == the minstop calendar batch bit for bit (the
    ci.sh wheel-L1 composition gate's unit form)."""
    for state, now in ((zipf64_state(n=8, depth=16), 500 * S),
                       mixed_qos_state(n=8, depth=10)):
        st_m, st_w = state, state
        for _ in range(3):
            bm = minstop_batch(st_m, now, 6)
            bw = wheel_batch(st_w, now, 6, 1)
            assert int(bm.count) == int(bw.count)
            for f in ("units", "served", "served_resv", "lb"):
                assert np.array_equal(
                    jax.device_get(getattr(bm, f)),
                    jax.device_get(getattr(bw, f))), f
            assert_states_equal(bm.state, bw.state)
            st_m, st_w = bm.state, bw.state


@pytest.mark.slow
def test_wheel_mixed_regimes_and_allow():
    """Interleaved constraint/weight regimes and AtLimit::Allow ride
    the wheel exactly (vs serial AND vs bucketed)."""
    state, now = mixed_qos_state(n=8, depth=12)
    st = state
    for _ in range(4):
        st, c = check_wheel_vs_serial(st, now, 6, 3)
        if c == 0:
            break
    st_w = st_b = state
    for _ in range(3):
        bw = wheel_batch(st_w, now, 6, 3, allow=True)
        bb = ladder_batch(st_b, now, 6, 3, allow=True)
        assert_batches_equal(bw, bb)
        st_w, st_b = bw.state, bb.state


# ----------------------------------------------------------------------
# in-place adjust == rebuild (the wheel's whole perf claim is that
# these are interchangeable; exactness says they must be IDENTICAL)
# ----------------------------------------------------------------------

def _assert_wheel_equal(a: FP.WheelIndex, b: FP.WheelIndex):
    """Index equality modulo the observability counters (reslots/hwm
    deliberately differ: adjust counts movement, build starts
    fresh)."""
    for f in ("origin", "cnt", "bmin", "slot", "key"):
        assert bool(jnp.array_equal(getattr(a, f), getattr(b, f))), \
            f"wheel field {f} diverged from rebuild"


def test_adjust_equals_rebuild_served_commit():
    """Fixed-now commit: re-slotting exactly the served clients
    reproduces the full rebuild of the committed state."""
    state = zipf64_state(n=10, depth=32)
    now = jnp.int64(500 * S)
    w = FP.wheel_build(state, now, False)
    b = wheel_batch(state, 500 * S, 8, 2)
    assert int(b.count) > 0
    moved = b.served > 0
    adj = FP.wheel_adjust(w, b.state, now, False, moved)
    _assert_wheel_equal(adj, FP.wheel_build(b.state, now, False))
    assert int(adj.reslots) > 0
    assert int(adj.hwm) >= int(w.hwm)


def test_adjust_equals_rebuild_live_qos_update():
    """A live PUT /clients/{id}/qos rewrites one client's rate
    params and head tags at the boundary; adjusting that client alone
    must equal the rebuild."""
    state = zipf64_state(n=10, depth=32)
    now = jnp.int64(500 * S)
    w = FP.wheel_build(state, now, False)
    c = 3
    onehot = jnp.arange(state.capacity) == c
    new_state = state._replace(
        weight_inv=state.weight_inv.at[c].set(
            state.weight_inv[c] // 4),
        head_prop=state.head_prop.at[c].set(
            state.head_prop[c] // 2))
    adj = FP.wheel_adjust(w, new_state, now, False, onehot)
    _assert_wheel_equal(adj, FP.wheel_build(new_state, now, False))


def test_adjust_equals_rebuild_idle_reentry():
    """A client departing (CLS_NONE, unslotted) and re-entering must
    round-trip through the adjust in both directions."""
    state = zipf64_state(n=10, depth=32)
    now = jnp.int64(500 * S)
    c = 5
    onehot = jnp.arange(state.capacity) == c
    idle = state._replace(active=state.active.at[c].set(False))
    w = FP.wheel_build(state, now, False)
    adj_out = FP.wheel_adjust(w, idle, now, False, onehot)
    _assert_wheel_equal(adj_out, FP.wheel_build(idle, now, False))
    # unslotted rows park at 3B
    assert int(adj_out.slot[c]) == 3 * FP._WHEEL_BUCKETS
    # ... and back in
    adj_in = FP.wheel_adjust(adj_out, state, now, False, onehot)
    _assert_wheel_equal(adj_in, w)


def test_adjust_equals_rebuild_churn_boundary_reslot():
    """Churn boundary at fixed now: one slot evicted and recycled
    for a fresh registration with different QoS/tags; adjusting the
    recycled slot equals the rebuild."""
    state = zipf64_state(n=10, depth=32)
    now = jnp.int64(500 * S)
    w = FP.wheel_build(state, now, False)
    c = 7
    onehot = jnp.arange(state.capacity) == c
    evicted = state._replace(
        active=state.active.at[c].set(False),
        depth=state.depth.at[c].set(0))
    adj = FP.wheel_adjust(w, evicted, now, False, onehot)
    _assert_wheel_equal(adj, FP.wheel_build(evicted, now, False))
    recycled = evicted._replace(
        active=evicted.active.at[c].set(True),
        depth=state.depth.at[c].set(2),
        weight_inv=evicted.weight_inv.at[c].set(
            evicted.weight_inv[c] * 3),
        head_prop=evicted.head_prop.at[c].set(
            jnp.int64(now + 1_000_000)))
    adj2 = FP.wheel_adjust(adj, recycled, now, False, onehot)
    _assert_wheel_equal(adj2, FP.wheel_build(recycled, now, False))


# ----------------------------------------------------------------------
# the exactness identity: first occupied bucket's min == dense min
# ----------------------------------------------------------------------

def test_wheel_origins_match_dense_min():
    for state, now in ((zipf64_state(n=12, depth=16), 500 * S),
                       mixed_qos_state(n=8, depth=10)):
        now = jnp.int64(now)
        for allow in (False, True):
            w = FP.wheel_build(state, now, allow)
            kresv, kprop1, kprop2, any_c = FP.wheel_origins(w)
            cls, key = FP._classify(state, now, allow)
            for c, got in ((FP.CLS_RESV, kresv),
                           (FP.CLS_WEIGHT, kprop1),
                           (FP.CLS_LB, kprop2)):
                want = jnp.min(jnp.where(cls == c, key, FP.KEY_INF))
                assert int(got) == int(want), (allow, int(c))
            assert bool(any_c) == bool((cls != FP.CLS_NONE).any())


def test_wheel_stop_min_matches_dense_min():
    rng = np.random.default_rng(23)
    for _ in range(4):
        stops = rng.integers(0, 1 << 60, size=64, dtype=np.int64)
        inf_mask = rng.random(64) < 0.3
        stops = np.where(inf_mask, kernels.KEY_INF, stops)
        got = FP._wheel_stop_min(jnp.asarray(stops),
                                 kernels.wheel_scan)
        assert int(got) == int(stops.min())
    # all-INF distributions return KEY_INF like the dense min
    all_inf = jnp.full((16,), jnp.int64(kernels.KEY_INF))
    assert int(FP._wheel_stop_min(all_inf, kernels.wheel_scan)) \
        == kernels.KEY_INF


# ----------------------------------------------------------------------
# Pallas kernel parity + fallback accounting
# ----------------------------------------------------------------------

def test_pallas_interpret_bit_identical(monkeypatch):
    """DMCLOCK_WHEEL_INTERPRET=1 resolves wheel_kernel="pallas" to
    the interpret-mode Pallas kernel (no fallback); the batch must be
    bit-identical to the XLA kernel -- the ci.sh parity pin."""
    monkeypatch.setenv("DMCLOCK_WHEEL_INTERPRET", "1")
    _, fb = FP._wheel_resolve("pallas", 16)
    assert not fb, "interpret mode must not fall back"
    state = zipf64_state(n=10, depth=16)
    bx = FP.calendar_batch_wheel(state, jnp.int64(500 * S), steps=6,
                                 levels=2, wheel_kernel="xla")
    bp = FP.calendar_batch_wheel(state, jnp.int64(500 * S), steps=6,
                                 levels=2, wheel_kernel="pallas")
    assert_batches_equal(bx, bp)
    assert int(bx.count) > 0


def test_pallas_unsupported_shape_falls_back(monkeypatch):
    monkeypatch.setenv("DMCLOCK_WHEEL_INTERPRET", "1")
    # > 2^19 padded lanes: resolver must decline the kernel
    _, fb = FP._wheel_resolve("pallas", 1 << 20)
    assert fb
    with pytest.raises(ValueError, match="wheel_kernel"):
        FP._wheel_resolve("mosaic", 16)


def test_pallas_fallback_counted_in_metrics():
    """Off-TPU without interpret mode the pallas request falls back
    to the XLA kernel: decisions bit-identical, fallbacks counted per
    live batch (fleet visibility for a silently-degraded kernel)."""
    from dmclock_tpu.obs import device as obsdev

    if jax.default_backend() == "tpu":
        pytest.skip("fallback accounting is the off-TPU path")
    state = zipf64_state(n=8, depth=16)
    now = jnp.int64(500 * S)
    kw = dict(steps=6, anticipation_ns=0, calendar_impl="wheel",
              ladder_levels=2, with_metrics=True)
    ex = FP.scan_calendar_epoch(state, now, 2, wheel_kernel="xla",
                                **kw)
    ep = FP.scan_calendar_epoch(state, now, 2, wheel_kernel="pallas",
                                **kw)
    for f in ("count", "resv_count", "served", "level_count"):
        assert bool(jnp.array_equal(getattr(ex, f), getattr(ep, f)))
    assert_states_equal(ex.state, ep.state)
    mx = obsdev.metrics_dict(ex.metrics)
    mp = obsdev.metrics_dict(ep.metrics)
    assert mx["wheel_pallas_fallbacks"] == 0
    assert mp["wheel_pallas_fallbacks"] > 0


# ----------------------------------------------------------------------
# epoch plumbing: scan_calendar_epoch(calendar_impl="wheel")
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_wheel_epoch_matches_batches():
    state, now = mixed_qos_state(n=8, depth=10)
    m, steps, levels = 4, 6, 2
    ep = FP.scan_calendar_epoch(state, jnp.int64(now), m,
                                steps=steps, anticipation_ns=0,
                                calendar_impl="wheel",
                                ladder_levels=levels)
    st = state
    total_served = np.zeros(state.capacity, np.int32)
    for i in range(m):
        b = wheel_batch(st, now, steps, levels)
        assert int(b.count) == int(jax.device_get(ep.count)[i])
        total_served += jax.device_get(b.served)
        st = b.state
    assert np.array_equal(total_served, jax.device_get(ep.served))
    assert_states_equal(ep.state, st)


def test_wheel_epoch_metrics():
    """with_metrics invisible to the wheel decision stream; the three
    new rows account the index's work: occupancy HWM > 0 on any
    non-empty build, re-slots > 0 once commits move clients."""
    from dmclock_tpu.obs import device as obsdev

    state = zipf64_state(n=8, depth=16)
    now = jnp.int64(500 * S)
    kw = dict(steps=6, anticipation_ns=0, calendar_impl="wheel",
              ladder_levels=3)
    ep_off = FP.scan_calendar_epoch(state, now, 2, **kw)
    ep_on = FP.scan_calendar_epoch(state, now, 2, with_metrics=True,
                                   **kw)
    for f in ("count", "resv_count", "progress_ok", "served",
              "level_count"):
        assert bool(jnp.array_equal(getattr(ep_off, f),
                                    getattr(ep_on, f))), \
            f"wheel epoch field {f} diverged with metrics on"
    assert_states_equal(ep_off.state, ep_on.state)
    m = obsdev.metrics_dict(ep_on.metrics)
    assert m["decisions_total"] == \
        int(np.asarray(ep_on.level_count).sum())
    assert m["wheel_bucket_occupancy_hwm"] > 0
    assert m["wheel_reslots_total"] > 0
    assert m["wheel_pallas_fallbacks"] == 0
    assert m["calendar_ladder_fallbacks"] == 0


@pytest.mark.slow
def test_wheel_epoch_tag32_exact():
    """The int32 tag carry composes with the wheel exactly as with
    the bucketed path (window-fitting high-rate shape)."""
    infos = {c: ClientInfo(0, 1000.0 + 500 * (c % 3), 0)
             for c in range(6)}
    state = deep_state(infos, depth=12)
    kw = dict(steps=4, anticipation_ns=0, calendar_impl="wheel",
              ladder_levels=2)
    now = jnp.int64(2 * S)
    e64 = FP.scan_calendar_epoch(state, now, 2, tag_width=64, **kw)
    e32 = FP.scan_calendar_epoch(state, now, 2, tag_width=32, **kw)
    assert bool(jax.device_get(e32.progress_ok).all())
    for f in ("count", "resv_count", "progress_ok", "served",
              "level_count"):
        assert bool(jnp.array_equal(getattr(e64, f),
                                    getattr(e32, f))), f
    assert_states_equal(e64.state, e32.state)


# ----------------------------------------------------------------------
# live PUT mid-epoch-stream: the lifecycle plane drives the wheel
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_wheel_churn_stream_equals_bucketed():
    """Scripted QoS updates (limit_thrash's PUT /clients/{id}/qos
    script) applied at boundaries MID-STREAM, plus registrations and
    evictions (churn_storm), must leave wheel == bucketed digests on
    the streaming loop -- the lifecycle plane's state rewrites hit
    the wheel's rebuild/adjust paths, not just steady serving."""
    import dataclasses

    from dmclock_tpu.lifecycle import make_spec
    from dmclock_tpu.robust import supervisor as SV

    for spec in (make_spec("limit_thrash", total_ids=12,
                           base_lam=1.5),
                 make_spec("churn_storm", total_ids=16, base_lam=1.5,
                           compact_every=1, gens=4, stride=4, life=2,
                           capacity0=4)):
        base = SV.EpochJob(engine="calendar", churn=spec, epochs=12,
                           m=2, k=8, ring=16, waves=4, ckpt_every=2,
                           seed=11, engine_loop="stream",
                           calendar_impl="wheel", ladder_levels=2)
        w = SV.run_job(base)
        b = SV.run_job(dataclasses.replace(
            base, calendar_impl="bucketed"))
        assert w.decisions == b.decisions > 0, spec["scenario"]
        assert w.digest == b.digest, spec["scenario"]
        assert w.state_digest == b.state_digest, spec["scenario"]
        if spec["scenario"] == "limit_thrash":
            assert w.lifecycle["qos_updates"] > 0
