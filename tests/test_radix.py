"""Differential tests for the radix-select backend and the int32 tag
rebase.

Two contracts pinned here:

1. ``select_impl="radix"`` (histogram k-selection + [k]-sized sort)
   produces BIT-IDENTICAL decision ordering and post-state to
   ``select_impl="sort"`` (the original full sort) on every shape the
   selection can see: uniform and Zipf-skewed weights, all-ties,
   single-client, k past the live count, both dmClock regimes, and
   re-entry boundaries (driving a workload to exhaustion batch by
   batch).  The sort path itself is pinned to the serial engine by
   tests/test_prefix.py, so radix == sort == serial transitively; the
   direct radix-vs-serial check rides along anyway.

2. ``kernels.rebase32``/``restore64`` round-trip int64 tags bit-exactly
   within the +-(2^31 - 8) window (sentinels MAX_TAG/MIN_TAG map to
   reserved codes), report ``ok=False`` past it, and the
   ``tag_width=32`` epoch carry built on them is bit-identical to
   ``tag_width=64`` when the window holds -- and falls back EXACTLY
   (commits nothing, keeps the input state, bumps ``rebase_fallbacks``)
   when it does not.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo
from dmclock_tpu.core.timebase import MAX_TAG, MIN_TAG, NS_PER_SEC
from dmclock_tpu.engine import kernels
from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                         scan_chain_epoch,
                                         scan_prefix_epoch,
                                         speculate_chain_batch,
                                         speculate_prefix_batch)
from dmclock_tpu.engine.kernels import rebase32, restore64

from engine_helpers import assert_states_equal, build_state, deep_state

S = NS_PER_SEC


def assert_batches_identical(a, b):
    """Sort-backend batch vs radix-backend batch: every caller-visible
    output must match bitwise (padding included -- the radix compaction
    promises sentinel-identical padding)."""
    assert int(a.count) == int(b.count)
    assert bool(a.guards_ok) == bool(b.guards_ok)
    da, db = jax.device_get(a.decisions), jax.device_get(b.decisions)
    for f in da._fields:
        assert np.array_equal(getattr(da, f), getattr(db, f)), \
            f"decision field {f} diverged"
    assert_states_equal(a.state, b.state)


def both_impls(state, now, k, **kw):
    a = speculate_prefix_batch(state, jnp.int64(now), k,
                               anticipation_ns=0, select_impl="sort",
                               **kw)
    b = speculate_prefix_batch(state, jnp.int64(now), k,
                               anticipation_ns=0, select_impl="radix",
                               **kw)
    assert_batches_identical(a, b)
    return b


def drive_both_to_exhaustion(state, now, k, *, max_batches=100, **kw):
    """Radix batch == sort batch == serial prefix at EVERY re-entry
    boundary until the workload drains."""
    allow = kw.get("allow_limit_break", False)
    st, total = state, 0
    for _ in range(max_batches):
        batch = both_impls(st, now, k, **kw)
        c = int(batch.count)
        if c:
            ser_state, _, ser = kernels.engine_run(
                st, jnp.int64(now), c, allow_limit_break=allow,
                anticipation_ns=0, advance_now=False)
            ser = jax.device_get(ser)
            d = jax.device_get(batch.decisions)
            assert np.array_equal(d.slot[:c], ser.slot)
            assert np.array_equal(d.phase[:c], ser.phase)
            assert_states_equal(batch.state, ser_state)
        st, total = batch.state, total + c
        if c == 0:
            break
    return st, total


# ----------------------------------------------------------------------
# radix vs sort: the differential shapes
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_radix_uniform_weights():
    infos = {c: ClientInfo(0, 1 + (c % 4), 0) for c in range(16)}
    state = deep_state(infos, depth=4)
    _, total = drive_both_to_exhaustion(state, 50 * S, 8)
    assert total == 16 * 4


@pytest.mark.slow
def test_radix_zipf_weights():
    """Zipf-skewed weights: the packed keys spread over decades, so
    every histogram round sees non-trivial digit distributions."""
    w = np.clip(64.0 / np.arange(1, 25) ** 1.1, 0.5, 64.0)
    rng = np.random.default_rng(3)
    rng.shuffle(w)
    infos = {c: ClientInfo(0, float(w[c]), 0) for c in range(24)}
    state = deep_state(infos, depth=3)
    _, total = drive_both_to_exhaustion(state, 200 * S, 16)
    assert total == 24 * 3


@pytest.mark.slow
def test_radix_all_ties():
    """Equal weights + equal arrivals: every selection boundary is a
    pure creation-order tie group -- the low 28 order bits decide."""
    infos = {c: ClientInfo(0, 2, 0) for c in range(12)}
    state = deep_state(infos, depth=6)
    _, total = drive_both_to_exhaustion(state, 8 * S, 8)
    assert total == 12 * 6


@pytest.mark.slow
def test_radix_single_client():
    infos = {0: ClientInfo(0, 1, 0)}
    adds = [(0, 1 * S, 1, 1, 1) for _ in range(10)]
    state = build_state(infos, adds, capacity=8)
    _, total = drive_both_to_exhaustion(state, 100 * S, 8)
    assert total == 10


def test_radix_k_past_live_count():
    """kk > live candidates: the KEY_INF exclusion must drop sentinel
    rows and pad the compaction identically to the trimmed sort."""
    infos = {c: ClientInfo(0, 1, 0) for c in range(3)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(3)]
    state = build_state(infos, adds, capacity=8)
    batch = both_impls(state, 1000 * S, 64)
    assert int(batch.count) == 3
    both_impls(batch.state, 1000 * S, 64)   # empty follow-up


@pytest.mark.slow
def test_radix_both_regimes():
    """Reservation backlog drains mid-run: batches cross the
    constraint->weight boundary; classes 0 and 1 both populated."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(8)}
    state = deep_state(infos, depth=8)
    _, total = drive_both_to_exhaustion(state, 4 * S, 16)
    assert total == 8 * 8


@pytest.mark.slow
def test_radix_limit_break_class():
    """AtLimit::Allow adds class 2: limit-capped clients selected by
    effective proportion with the limit_break flag."""
    infos = {c: ClientInfo(0, 1, 0.5) for c in range(6)}
    state = deep_state(infos, depth=4)
    _, total = drive_both_to_exhaustion(state, 2 * S, 8,
                                        allow_limit_break=True)
    assert total == 6 * 4


def test_radix_chain_batch():
    """Chained units (chain_depth > 1): the lens column rides the small
    sort as a payload; unit stream must match bitwise."""
    infos = {c: ClientInfo(1, 2, 0) for c in range(6)}
    state = deep_state(infos, depth=10)
    now = jnp.int64(3 * S)
    a = speculate_chain_batch(state, now, 8, chain_depth=4,
                              anticipation_ns=0, select_impl="sort")
    b = speculate_chain_batch(state, now, 8, chain_depth=4,
                              anticipation_ns=0, select_impl="radix")
    assert int(a.count) == int(b.count)
    assert int(a.unit_count) == int(b.unit_count)
    for f in ("slot", "cls", "length"):
        assert np.array_equal(jax.device_get(getattr(a, f)),
                              jax.device_get(getattr(b, f))), f
    assert_states_equal(a.state, b.state)


@pytest.mark.slow
def test_radix_epoch_stream_identical():
    """Whole epochs under both backends: decision stream, guards, and
    final state bit-identical (the A/B contract benches rely on)."""
    infos = {c: ClientInfo(0, 1 + (c % 2), 0) for c in range(8)}
    state = deep_state(infos, depth=5)
    now = jnp.int64(30 * S)
    es = scan_prefix_epoch(state, now, 10, 8, anticipation_ns=0,
                           select_impl="sort")
    er = scan_prefix_epoch(state, now, 10, 8, anticipation_ns=0,
                           select_impl="radix")
    for f in ("count", "guards_ok", "slot", "phase", "cost", "lb"):
        assert np.array_equal(jax.device_get(getattr(es, f)),
                              jax.device_get(getattr(er, f))), f
    assert_states_equal(es.state, er.state)


def test_radix_kth_key_property():
    """_radix_kth_key == the kk-th smallest element of the array, over
    random non-negative int64 populations with duplicates."""
    from dmclock_tpu.engine.fastpath import _radix_kth_key

    rng = np.random.default_rng(11)
    for trial in range(4):
        n = int(rng.integers(5, 200))
        # mix magnitudes so high and low digit rounds both matter
        vals = rng.integers(0, 1 << int(rng.integers(4, 62)), size=n)
        vals = jnp.asarray(vals, dtype=jnp.int64)
        kk = int(rng.integers(1, n + 1))
        got = int(_radix_kth_key(vals, kk))
        want = int(np.sort(np.asarray(vals))[kk - 1])
        assert got == want, (trial, n, kk, got, want)


# ----------------------------------------------------------------------
# int32 rebase: round-trip property + epoch carry
# ----------------------------------------------------------------------

def test_rebase32_roundtrip_in_window():
    rng = np.random.default_rng(5)
    origin = jnp.int64(123_456_789_000)
    win = (1 << 31) - 8
    vals = rng.integers(-win + 1, win, size=256) + 123_456_789_000
    vals = np.concatenate([vals, [MAX_TAG, MIN_TAG,
                                  123_456_789_000 + win - 1,
                                  123_456_789_000 - win + 1]])
    v = jnp.asarray(vals, dtype=jnp.int64)
    v32, ok = rebase32(v, origin)
    assert bool(ok)
    assert v32.dtype == jnp.int32
    back = restore64(v32, origin)
    assert np.array_equal(np.asarray(back), vals)


def test_rebase32_out_of_window_flags():
    origin = jnp.int64(0)
    win = (1 << 31) - 8
    for bad in (win, -win, win + 12345, -(win + 99)):
        v = jnp.asarray([0, bad], dtype=jnp.int64)
        _, ok = rebase32(v, origin)
        assert not bool(ok), bad
    # sentinels alone never trip the window
    v = jnp.asarray([MAX_TAG, MIN_TAG], dtype=jnp.int64)
    _, ok = rebase32(v, origin)
    assert bool(ok)


def _high_rate_state(n=12, depth=6):
    """Per-serve tag advance ~1e6 ns: a whole small epoch drifts well
    inside the +-2^31 ns rebase window."""
    infos = {c: ClientInfo(2000, 1000 * (1 + c % 3), 0)
             for c in range(n)}
    return deep_state(infos, depth=depth)


def _low_rate_state(n=12, depth=6):
    """Per-serve tag advance ~1e9 ns: one batch of serves exits the
    window -- the fallback shape."""
    infos = {c: ClientInfo(2, 1 + (c % 3), 0) for c in range(n)}
    return deep_state(infos, depth=depth)


@pytest.mark.parametrize("select_impl", ["sort", "radix"])
@pytest.mark.slow
def test_tag32_epoch_bit_identical_in_window(select_impl):
    state = _high_rate_state()
    now = jnp.int64(4 * S)
    e64 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=64, select_impl=select_impl)
    e32 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=32, select_impl=select_impl)
    assert jax.device_get(e32.guards_ok).all()
    for f in ("count", "slot", "phase", "cost", "lb"):
        assert np.array_equal(jax.device_get(getattr(e64, f)),
                              jax.device_get(getattr(e32, f))), f
    assert_states_equal(e64.state, e32.state)


@pytest.mark.slow
def test_tag32_chain_and_calendar_epochs():
    state = _high_rate_state()
    now = jnp.int64(4 * S)
    c64 = scan_chain_epoch(state, now, 3, 8, chain_depth=4,
                           anticipation_ns=0, tag_width=64)
    c32 = scan_chain_epoch(state, now, 3, 8, chain_depth=4,
                           anticipation_ns=0, tag_width=32)
    for f in ("count", "unit_count", "slot", "cls", "length"):
        assert np.array_equal(jax.device_get(getattr(c64, f)),
                              jax.device_get(getattr(c32, f))), f
    assert_states_equal(c64.state, c32.state)

    k64 = scan_calendar_epoch(state, now, 2, steps=8,
                              anticipation_ns=0, tag_width=64)
    k32 = scan_calendar_epoch(state, now, 2, steps=8,
                              anticipation_ns=0, tag_width=32)
    assert np.array_equal(jax.device_get(k64.served),
                          jax.device_get(k32.served))
    assert jax.device_get(k32.progress_ok).all()
    assert_states_equal(k64.state, k32.state)


@pytest.mark.slow
def test_tag32_window_trip_falls_back_exactly():
    """The fallback contract: a mid-epoch window trip zeroes that batch
    and every later one, keeps the carry at the last good state, and
    bumps rebase_fallbacks ONCE; the caller reruns on tag_width=64 from
    the returned state and loses nothing."""
    state = _low_rate_state()
    now = jnp.int64(4 * S)
    e32 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=32, with_metrics=True)
    counts = jax.device_get(e32.count)
    guards = jax.device_get(e32.guards_ok)
    # once a batch trips, everything from it on is zeroed / not ok
    first_bad = int(np.argmax(~guards)) if not guards.all() \
        else len(guards)
    assert first_bad < len(guards), "shape was supposed to trip"
    assert (counts[first_bad:] == 0).all()
    assert not guards[first_bad:].any()
    assert (jax.device_get(e32.slot)[first_bad:] == -1).all()
    met = jax.device_get(e32.metrics)
    from dmclock_tpu.obs import device as obsdev
    assert met[obsdev.MET_REBASE_FALLBACKS] == 1
    # the returned state is the last good state: rerunning the epoch on
    # the int64 path from it must continue the EXACT serial stream
    e64_ref = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                                tag_width=64)
    ref_counts = jax.device_get(e64_ref.count)
    # batches before the trip match the int64 epoch bitwise
    assert np.array_equal(counts[:first_bad], ref_counts[:first_bad])
    st_resume = scan_prefix_epoch(e32.state, now, 4 - first_bad, 8,
                                  anticipation_ns=0, tag_width=64)
    assert np.array_equal(
        jax.device_get(st_resume.slot),
        jax.device_get(e64_ref.slot)[first_bad:])
    assert_states_equal(st_resume.state, e64_ref.state)


@pytest.mark.slow
def test_tag32_ignores_stale_inactive_lanes():
    """A stale lane (inactive, or active but empty) whose ancient tag
    sits far outside any window must NOT trip the int32 carry: it
    cannot serve this epoch, its fields are excluded from the fit, and
    the exit state carries its exact entry values."""
    state = _high_rate_state()
    n = state.capacity
    far = jnp.int64(1) << 40          # ~18 minutes of virtual time away
    state = state._replace(
        active=state.active.at[n - 1].set(False),
        head_prop=state.head_prop.at[n - 1].set(far),
        prev_prop=state.prev_prop.at[n - 1].set(-far),
        # an ACTIVE but drained lane is equally dead for the epoch
        depth=state.depth.at[n - 2].set(0),
        head_resv=state.head_resv.at[n - 2].set(far))
    now = jnp.int64(4 * S)
    e64 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=64)
    e32 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=32, with_metrics=True)
    assert jax.device_get(e32.guards_ok).all()
    from dmclock_tpu.obs import device as obsdev
    assert jax.device_get(e32.metrics)[obsdev.MET_REBASE_FALLBACKS] == 0
    for f in ("count", "slot", "phase", "cost"):
        assert np.array_equal(jax.device_get(getattr(e64, f)),
                              jax.device_get(getattr(e32, f))), f
    assert_states_equal(e64.state, e32.state)
    assert int(e32.state.head_prop[n - 1]) == int(far)
    assert int(e32.state.prev_prop[n - 1]) == -int(far)
    assert int(e32.state.head_resv[n - 2]) == int(far)


def test_tag32_dead_batches_do_not_pollute_metrics():
    """Post-trip dead batches force their counts to zero by contract;
    those zeros are a fallback artifact and must not read as
    limit_stalls, and the discarded speculative states must not feed
    the ring high-water mark."""
    state = _low_rate_state()
    now = jnp.int64(4 * S)
    e32 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=32, with_metrics=True)
    guards = jax.device_get(e32.guards_ok)
    assert not guards.all(), "shape was supposed to trip"
    met = jax.device_get(e32.metrics)
    from dmclock_tpu.obs import device as obsdev
    assert met[obsdev.MET_STALLS] == 0
    # hwm comes only from LIVE batches; the committed prefix of the
    # int64 reference epoch bounds it
    e64 = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                            tag_width=64, with_metrics=True)
    assert met[obsdev.MET_RING_HWM] <= \
        jax.device_get(e64.metrics)[obsdev.MET_RING_HWM]


def test_tag32_initial_misfit_returns_input_state():
    """An epoch whose ENTRY state already cannot narrow must return the
    input state untouched with zero commits and one fallback bump."""
    state = _low_rate_state()
    # spread head_prop past the whole window so entry narrowing fails
    n = state.capacity
    spread = (jnp.arange(n, dtype=jnp.int64) * jnp.int64(1 << 28))
    state = state._replace(head_prop=state.head_prop + spread)
    now = jnp.int64(4 * S)
    e32 = scan_prefix_epoch(state, now, 3, 8, anticipation_ns=0,
                            tag_width=32, with_metrics=True)
    assert (jax.device_get(e32.count) == 0).all()
    assert not jax.device_get(e32.guards_ok).any()
    assert_states_equal(e32.state, state)
    from dmclock_tpu.obs import device as obsdev
    assert jax.device_get(e32.metrics)[obsdev.MET_REBASE_FALLBACKS] == 1


# ----------------------------------------------------------------------
# window_m chunked prefetch
# ----------------------------------------------------------------------

@pytest.mark.parametrize("window_m", [1, 2, 4])
def test_window_m_chunking_is_invisible(window_m):
    """m=64-style wide epochs chunk the ring prefetch; the decision
    stream and final state must not depend on the chunking."""
    infos = {c: ClientInfo(0, 1 + (c % 3), 0) for c in range(10)}
    state = deep_state(infos, depth=8)
    now = jnp.int64(20 * S)
    ref = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0)
    ch = scan_prefix_epoch(state, now, 4, 8, anticipation_ns=0,
                           window_m=window_m)
    for f in ("count", "guards_ok", "slot", "phase", "cost", "lb"):
        assert np.array_equal(jax.device_get(getattr(ref, f)),
                              jax.device_get(getattr(ch, f))), f
    assert_states_equal(ref.state, ch.state)


def test_window_m_must_divide_m():
    infos = {0: ClientInfo(0, 1, 0)}
    state = build_state(infos, [(0, 1 * S, 1, 1, 1)], capacity=8)
    with pytest.raises(AssertionError):
        scan_prefix_epoch(state, jnp.int64(S), 4, 8,
                          anticipation_ns=0, window_m=3)
