"""Tests for the RPC ingest front-end (docs/RPC.md): wire framing,
the seeded network fault plane and its exact host oracle, the fsync'd
arrival journal (torn tails, sequence gaps), exactly-once admission
(dedup watermarks, reorder holds, backpressure), loadgen schedule
determinism, the live-vs-replay digest gate, and crash-equivalent
admission across a SIGKILL landed between the journal fsync and the
boundary apply."""

import dataclasses
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from dmclock_tpu.net import faults, framing
from dmclock_tpu.net.client import RpcClient, drain_notifies
from dmclock_tpu.net.journal import ArrivalJournal
from dmclock_tpu.net.server import IngestServer

REPO = Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "loadgen", REPO / "scripts" / "loadgen.py")
loadgen = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(loadgen)


# ----------------------------------------------------------------------
# framing
# ----------------------------------------------------------------------

class TestFraming:
    def test_req_ack_roundtrip(self):
        t, f = framing.unpack(framing.pack_req(7, 123, 3, attempt=2))
        assert t == framing.T_REQ and f == (7, 123, 3, 2)
        t, f = framing.unpack(framing.pack_ack(7, 123,
                                               framing.ST_BUSY, 50))
        assert t == framing.T_ACK and f == (7, 123, framing.ST_BUSY,
                                            50)

    def test_notify_sub_roundtrip(self):
        obj = {"b": 4, "verdicts": [[0, "conformant"]]}
        t, f = framing.unpack(framing.pack_notify(obj))
        assert t == framing.T_NOTIFY and f[0] == obj
        t, f = framing.unpack(framing.pack_sub())
        assert t == framing.T_SUB and f == ()

    def test_framer_reassembles_byte_at_a_time(self):
        payloads = [framing.pack_req(1, 0, 2),
                    framing.pack_ack(1, 0, framing.ST_OK),
                    framing.pack_notify({"k": 1})]
        stream = b"".join(framing.frame(p) for p in payloads)
        fr = framing.Framer()
        got = []
        for i in range(len(stream)):
            got.extend(fr.feed(stream[i:i + 1]))
        assert got == payloads
        assert fr.pending() == 0

    def test_framer_rejects_oversized_prefix(self):
        fr = framing.Framer()
        bad = (framing.MAX_FRAME + 1).to_bytes(4, "big")
        with pytest.raises(framing.ProtocolError):
            fr.feed(bad)

    def test_unknown_type_and_bad_body_raise(self):
        with pytest.raises(framing.ProtocolError):
            framing.unpack(bytes([99]) + b"x")
        with pytest.raises(framing.ProtocolError):
            framing.unpack(bytes([framing.T_REQ]) + b"\x01\x02")
        with pytest.raises(framing.ProtocolError):
            framing.unpack(b"")


# ----------------------------------------------------------------------
# the fault plane + its exact oracle
# ----------------------------------------------------------------------

class TestFaults:
    def test_parse_grammar(self):
        spec = faults.parse_net_fault_spec(
            "seed=9, p_drop=0.25, stall_ms=40, p_stall=0.5")
        assert spec["seed"] == 9 and spec["p_drop"] == 0.25
        assert spec["stall_ms"] == 40
        assert faults.parse_net_fault_spec(None) is None
        assert faults.parse_net_fault_spec("") is None
        # all-zero probabilities == fault plane off
        assert faults.parse_net_fault_spec("seed=3") is None

    def test_parse_rejects_typos_and_ranges(self):
        with pytest.raises(ValueError):
            faults.parse_net_fault_spec("p_dorp=0.1")
        with pytest.raises(ValueError):
            faults.parse_net_fault_spec({"p_drop": 0.1, "wat": 1})
        with pytest.raises(ValueError):
            faults.parse_net_fault_spec("p_drop=1.5")

    def test_decide_is_pure_and_attempt_sensitive(self):
        spec = faults.parse_net_fault_spec(
            "seed=5,p_drop=0.3,p_dup=0.2,p_reorder=0.1")
        fates = [faults.decide(spec, c, s, a)
                 for c in range(8) for s in range(8)
                 for a in range(3)]
        again = [faults.decide(spec, c, s, a)
                 for c in range(8) for s in range(8)
                 for a in range(3)]
        assert fates == again
        # attempts draw fresh fates (a retried frame is a new frame)
        assert any(faults.decide(spec, c, s, 0)
                   != faults.decide(spec, c, s, 1)
                   for c in range(8) for s in range(8))

    def test_oracle_order_independent(self):
        spec = faults.parse_net_fault_spec(
            "seed=5,p_drop=0.3,p_dup=0.2,p_reorder=0.1")
        sched = [(c, s) for c in range(16) for s in range(8)]
        fwd = faults.plan_events(spec, sched)
        rev = faults.plan_events(spec, list(reversed(sched)))
        assert fwd == rev
        assert fwd["admitted"] + fwd["lost"] == len(sched)

    def test_oracle_extremes(self):
        sched = [(c, s) for c in range(4) for s in range(4)]
        none = faults.plan_events(None, sched)
        assert none == {"drops": 0, "dups": 0, "reorders": 0,
                        "lost": 0, "admitted": len(sched)}
        all_drop = faults.plan_events(
            {"seed": 1, "p_drop": 1.0, "p_dup": 0.0,
             "p_reorder": 0.0, "p_stall": 0.0, "stall_ms": 0},
            sched, max_attempts=3)
        assert all_drop["lost"] == len(sched)
        assert all_drop["drops"] == len(sched) * 3

    def test_schedule_oracle_flattens_workers(self):
        spec = faults.parse_net_fault_spec("seed=2,p_drop=0.5")
        scheds = [[(0, 0), (0, 1)], [(1, 0)]]
        assert faults.plan_schedule_events(spec, scheds) \
            == faults.plan_events(spec, [(0, 0), (0, 1), (1, 0)])


# ----------------------------------------------------------------------
# arrival journal (WAL discipline)
# ----------------------------------------------------------------------

class TestJournal:
    def _entry(self, seq):
        return {"seq": seq, "counts": [[seq, 1]], "carry": [0, 0],
                "marks": {"0": [seq, []]}, "events": {}}

    def test_append_reload_roundtrip(self, tmp_path):
        j = ArrivalJournal(str(tmp_path))
        for k in range(3):
            j.append(self._entry(k))
        j2 = ArrivalJournal(str(tmp_path))
        assert len(j2) == 3
        assert j2.counts_trace() == [[[k, 1]] for k in range(3)]
        assert j2.last_marks() == {"0": [2, []]}
        assert j2.entry_at(1)["counts"] == [[1, 1]]
        assert j2.entry_at(7) is None

    def test_torn_tail_truncated_on_disk(self, tmp_path):
        j = ArrivalJournal(str(tmp_path))
        j.append(self._entry(0))
        j.append(self._entry(1))
        with open(j.path, "ab") as f:
            f.write(b'{"seq": 2, "counts": [[')   # crash mid-append
        j2 = ArrivalJournal(str(tmp_path))
        assert len(j2) == 2
        # the torn suffix is gone ON DISK: the next append starts at
        # a clean line boundary and a third load agrees
        ent = j2.append(self._entry(2))
        assert ent["seq"] == 2
        assert len(ArrivalJournal(str(tmp_path))) == 3

    def test_sequence_gap_refused(self, tmp_path):
        j = ArrivalJournal(str(tmp_path))
        j.append(self._entry(0))
        with open(j.path, "ab") as f:
            f.write(json.dumps(self._entry(5)).encode() + b"\n")
        assert len(ArrivalJournal(str(tmp_path))) == 1

    def test_memory_journal_never_touches_disk(self, tmp_path):
        j = ArrivalJournal(None)
        j.append(self._entry(0))
        assert j.path is None and len(j) == 1


# ----------------------------------------------------------------------
# admission core (no event loop: direct calls under the lock)
# ----------------------------------------------------------------------

class TestAdmission:
    def _server(self, **kw):
        kw.setdefault("datagram", False)
        return IngestServer(4, waves=2, port=0, **kw)

    def test_exactly_once_under_reordered_seqs(self):
        srv = self._server()
        try:
            assert srv.admit_frame(1, 2, 1, 0)[0] == framing.ST_OK
            assert srv.admit_frame(1, 0, 1, 0)[0] == framing.ST_OK
            # retry of an out-of-order admit: refused via extras
            assert srv.admit_frame(1, 2, 1, 1)[0] == framing.ST_DUP
            assert srv.admit_frame(1, 1, 1, 0)[0] == framing.ST_OK
            # mark advanced to 2; extras drained
            assert srv._marks[1] == [2, set()]
            assert srv.admit_frame(1, 1, 1, 3)[0] == framing.ST_DUP
            assert srv.counters["deduped"] == 2
            assert srv.counters["admitted_reqs"] == 3
        finally:
            srv.stop()

    def test_backpressure_busy_and_device_pressure(self):
        srv = self._server(high_watermark=4, retry_after_ms=30)
        try:
            assert srv.admit_frame(0, 0, 4, 0)[0] == framing.ST_OK
            st, hint = srv.admit_frame(1, 0, 1, 0)
            assert st == framing.ST_BUSY and hint == 30
            assert srv.counters["busy"] == 1
            # a device admission-clamp signal halves the watermark
            # and doubles the hint until a clean chunk clears it
            srv.note_device_drops(3)
            st, hint = srv.admit_frame(1, 0, 1, 1)
            assert st == framing.ST_BUSY and hint == 60
            assert srv.counters["device_drop_signals"] == 1
            srv.note_device_drops(0)
            srv.take_chunk(2)            # drain
            assert srv.admit_frame(1, 0, 1, 2)[0] == framing.ST_OK
        finally:
            srv.stop()

    def test_take_chunk_waves_cap_and_carry(self):
        srv = self._server()
        try:
            srv.admit_frame(0, 0, 5, 0)      # slot 0: 5 ops, waves=2
            t = srv.take_chunk(2)
            assert t.counts.tolist()[0][0] == 2
            assert t.counts.tolist()[1][0] == 2
            # the 5th op is admitted-but-queued: in carry, journaled,
            # never lost and never double-counted
            assert t.carry[0] == 1
            assert int(t.counts.sum()) + sum(t.carry) == 5
            t2 = srv.take_chunk(1)
            assert t2.counts.tolist()[0][0] == 1
            assert sum(t2.carry) == 0
        finally:
            srv.stop()

    def test_reordered_admit_lands_one_take_late(self):
        srv = self._server(fault_spec="seed=1,p_reorder=1.0")
        try:
            assert srv.admit_frame(2, 0, 3, 0)[0] == framing.ST_OK
            assert srv.counters["reordered"] == 1
            t = srv.take_chunk(2)
            assert int(t.counts.sum()) == 0
            assert t.carry[2 % 4] == 3       # poured after the matrix
            t2 = srv.take_chunk(2)
            assert int(t2.counts.sum()) == 3
        finally:
            srv.stop()

    def test_restore_marks_refuses_dead_incarnations_admits(self):
        srv = self._server()
        try:
            srv.restore_marks({"3": [4, [7]]})
            assert srv.admit_frame(3, 2, 1, 0)[0] == framing.ST_DUP
            assert srv.admit_frame(3, 7, 1, 0)[0] == framing.ST_DUP
            assert srv.admit_frame(3, 5, 1, 0)[0] == framing.ST_OK
        finally:
            srv.stop()

    def test_status_and_http_handler(self):
        srv = self._server(shard_of=lambda cid: cid % 2)
        try:
            srv.admit_frame(1, 0, 2, 0)
            st, ctype, body = srv.http_handler("GET", "/rpc/status",
                                               None)
            assert st == 200 and ctype == "application/json"
            doc = json.loads(body)
            assert doc["queue_depth"] == 2
            assert doc["shard_rx"] == {"1": 2}
            assert doc["counters"]["admitted_ops"] == 2
            assert srv.http_handler("POST", "/rpc/status",
                                    b"")[0] == 405
        finally:
            srv.stop()


# ----------------------------------------------------------------------
# loopback: real sockets, chaos accounting, notify plane
# ----------------------------------------------------------------------

class TestLoopback:
    def test_client_retry_and_idempotent_resend(self):
        with IngestServer(4, waves=4, port=0) as srv:
            with RpcClient("127.0.0.1", srv.port,
                           timeout_s=1.0) as cli:
                assert cli.request(2, 0, 3) == framing.ST_OK
                # resend of an admitted frame is success, not a
                # double admission
                assert cli.request(2, 0, 3) == framing.ST_DUP
            assert srv.counters["admitted_ops"] == 3
            assert srv.counters["deduped"] == 1

    def test_datagram_transport_admits(self):
        with IngestServer(4, waves=4, port=0) as srv:
            with socket.socket(socket.AF_INET,
                               socket.SOCK_DGRAM) as s:
                s.settimeout(2.0)
                s.sendto(framing.pack_req(1, 0, 2, 0),
                         ("127.0.0.1", srv.port))
                t, f = framing.unpack(s.recv(4096))
            assert t == framing.T_ACK
            assert f[:3] == (1, 0, framing.ST_OK)
            assert srv.counters["datagrams"] == 1

    def test_chaos_accounting_is_exact(self):
        spec_str = "seed=5,p_drop=0.3,p_dup=0.2,p_reorder=0.1"
        scheds = loadgen.full_schedule(11, workers=2, requests=30,
                                       n_clients=8, max_nops=3)
        oracle = faults.plan_schedule_events(
            faults.parse_net_fault_spec(spec_str), [
                [(c, s) for c, s, _ in sc] for sc in scheds])
        with IngestServer(8, waves=4, port=0,
                          high_watermark=4096,
                          fault_spec=spec_str) as srv:
            threads = [threading.Thread(
                target=loadgen.run_worker,
                args=("127.0.0.1", srv.port, sc),
                kwargs=dict(timeout_s=0.15)) for sc in scheds]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            c = srv.counters
            # EXACT equality against the host oracle -- the whole
            # point of hashing (seed, cid, seq, attempt): socket
            # interleaving and retry timing cannot skew the counts
            assert c["drops_injected"] == oracle["drops"]
            assert c["dup_frames"] == oracle["dups"]
            assert c["reordered"] == oracle["reorders"]
            assert c["admitted_reqs"] == oracle["admitted"]
            assert c["deduped"] >= oracle["dups"]
            # conservation: every admitted op is queued exactly once
            assert srv.queue_depth() == c["admitted_ops"]

    def test_notify_reaches_subscribers(self):
        with IngestServer(4, waves=4, port=0) as srv:
            got = []
            t = threading.Thread(
                target=lambda: got.extend(drain_notifies(
                    "127.0.0.1", srv.port, timeout_s=2.0,
                    max_batches=1)))
            t.start()
            time.sleep(0.4)          # let the SUB frame register
            srv.publish({"boundary": 0, "decisions": 12})
            t.join(timeout=10)
            assert got and got[0]["decisions"] == 12


# ----------------------------------------------------------------------
# loadgen determinism
# ----------------------------------------------------------------------

class TestLoadgen:
    KW = dict(workers=3, requests=20, n_clients=10, max_nops=3)

    def test_same_seed_byte_identical(self):
        a = loadgen.full_schedule(7, **self.KW)
        b = loadgen.full_schedule(7, **self.KW)
        assert loadgen.schedule_blob(a) == loadgen.schedule_blob(b)

    def test_seed_and_worker_sensitivity(self):
        a = loadgen.full_schedule(7, **self.KW)
        b = loadgen.full_schedule(8, **self.KW)
        assert loadgen.schedule_blob(a) != loadgen.schedule_blob(b)
        assert loadgen.worker_schedule(7, 0, **self.KW) \
            != loadgen.worker_schedule(7, 1, **self.KW)

    def test_partitions_disjoint_and_seqs_dense(self):
        scheds = loadgen.full_schedule(7, **self.KW)
        for w, sched in enumerate(scheds):
            assert all(c % 3 == w for c, _, _ in sched)
            per = {}
            for c, s, n in sched:
                assert s == per.get(c, 0)    # per-cid seqs 0,1,2,...
                per[c] = s + 1
                assert 1 <= n <= 3

    def test_schedule_only_cli_matches_library(self, capsys):
        rc = loadgen.main(["--schedule-only", "--seed", "7",
                           "--workers", "3", "--requests", "20",
                           "--n-clients", "10", "--max-nops", "3"])
        assert rc == 0
        printed = json.loads(capsys.readouterr().out)
        lib = json.loads(loadgen.schedule_blob(
            loadgen.full_schedule(7, **self.KW)))
        assert printed == lib

    def test_cli_spawn_workers_admit_over_sockets(self):
        # the REAL process path: spawn children re-execute
        # loadgen.py with sys.path[0] = scripts/, so this guards the
        # repo-root pin that makes dmclock_tpu importable in them
        srv = IngestServer(8, waves=4, high_watermark=4096,
                           datagram=False).start()
        try:
            lg = subprocess.run(
                [sys.executable, str(REPO / "scripts/loadgen.py"),
                 "--port", str(srv.port), "--workers", "2",
                 "--requests", "8", "--n-clients", "8",
                 "--seed", "3", "--timeout-s", "2.0"],
                capture_output=True, text=True, timeout=120)
            assert lg.returncode == 0, (lg.stdout, lg.stderr)
            merged = json.loads(lg.stdout)
            assert merged["ok"] == 16 and merged["failed"] == 0
            assert srv.counters["admitted_reqs"] == 16
        finally:
            srv.stop()


# ----------------------------------------------------------------------
# obs: dmclock_rpc_* families
# ----------------------------------------------------------------------

class TestObsRpc:
    def test_publish_families_and_latency(self):
        from dmclock_tpu.obs import rpc as obsrpc
        from dmclock_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry()
        obsrpc.publish_rpc(reg, {
            "queue_depth": 5, "connections": 2,
            "device_pressure": True, "shard_rx": {"0": 7, "1": 3},
            "counters": {"requests": 40, "admitted_ops": 33,
                         "busy": 4}})
        snap = reg.snapshot()
        assert snap["dmclock_rpc_requests_total"][0]["value"] == 40
        assert snap["dmclock_rpc_admitted_ops_total"][0]["value"] \
            == 33
        assert snap["dmclock_rpc_queue_depth"][0]["value"] == 5
        assert snap["dmclock_rpc_backpressure_engaged"][0]["value"] \
            == 1
        shards = {m["labels"]["shard"]: m["value"]
                  for m in snap["dmclock_rpc_shard_routed_ops_total"]}
        assert shards == {"0": 7, "1": 3}

        empty = obsrpc.latency_summary([])
        assert empty["samples"] == 0 and empty["p99_ms"] == 0.0
        summ = obsrpc.latency_summary([10 ** 6] * 99 + [10 ** 9])
        assert summ["samples"] == 100
        assert summ["max_ms"] == pytest.approx(1000.0)
        obsrpc.publish_rpc_latency(reg, summ)
        snap = reg.snapshot()
        assert snap["dmclock_rpc_admit_to_commit_max_ms"][0][
            "value"] == pytest.approx(1000.0)


# ----------------------------------------------------------------------
# the serving loop: digest gate + SIGKILL crash equivalence
# ----------------------------------------------------------------------

def _small_cfg(**over):
    from dmclock_tpu.net.serve import RpcServeConfig

    base = dict(engine="prefix", n=8, depth=2, ring=8, epochs=4,
                m=2, k=8, chain_depth=2, waves=2, ckpt_every=2,
                seed=11, with_slo=True, wait_ops=0, port=0)
    base.update(over)
    return RpcServeConfig(**base)


def _drive(scheds, port):
    threads = [threading.Thread(
        target=loadgen.run_worker,
        args=("127.0.0.1", port, sc),
        kwargs=dict(timeout_s=2.0)) for sc in scheds]
    for t in threads:
        t.start()
    return threads


class TestServeLoop:
    def test_digest_gate_live_vs_replay(self, tmp_path):
        from dmclock_tpu.net.serve import (make_server, run_serve,
                                           trace_sha)

        scheds = loadgen.full_schedule(13, workers=2, requests=10,
                                       n_clients=8, max_nops=2)
        total = sum(n for sc in scheds for _, _, n in sc)
        cfg = _small_cfg(workdir=str(tmp_path), wait_ops=total)
        server = make_server(cfg).start()
        try:
            threads = _drive(scheds, server.port)
            live = run_serve(cfg, server=server)
            for t in threads:
                t.join(timeout=60)
        finally:
            server.stop()
        assert live["mode"] == "rpc-serve" and not live["resumed"]
        assert live["decisions"] > 0
        # conservation: every op the workers sent is traced or
        # carried, exactly once (no chaos in this leg)
        assert live["admitted_ops_traced"] + live["carry_ops"] \
            == total
        trace = ArrivalJournal(str(tmp_path)).counts_trace()
        assert trace_sha(trace) == live["trace_sha"]
        replay = run_serve(
            dataclasses.replace(cfg, workdir=None, wait_ops=0),
            trace=trace)
        assert replay["mode"] == "rpc-replay"
        assert replay["digest"] == live["digest"]
        assert replay["trace_sha"] == live["trace_sha"]
        assert replay["decisions"] == live["decisions"]

    def test_sigkill_between_fsync_and_apply_is_crash_equivalent(
            self, tmp_path):
        from dmclock_tpu.net.serve import run_serve

        scheds = loadgen.full_schedule(29, workers=2, requests=12,
                                       n_clients=8, max_nops=2)
        total = sum(n for sc in scheds for _, _, n in sc)
        cfg = _small_cfg(epochs=8, workdir=str(tmp_path),
                         wait_ops=total)
        cfg_json = tmp_path / "cfg.json"
        cfg_json.write_text(json.dumps(dataclasses.asdict(cfg)))
        out_json = tmp_path / "out.json"
        port_file = tmp_path / "port"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dmclock_tpu.net.serve",
             "--config", str(cfg_json), "--out", str(out_json),
             "--port-file", str(port_file),
             "--crash-after-fsync", "3"],
            cwd=str(REPO), env=env)
        try:
            deadline = time.monotonic() + 120
            while not port_file.exists():
                assert time.monotonic() < deadline, "no port file"
                assert proc.poll() is None, "server died early"
                time.sleep(0.05)
            port = int(port_file.read_text())
            threads = _drive(scheds, port)
            for t in threads:
                t.join(timeout=120)
            proc.wait(timeout=300)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        # SIGKILL'd in the exact window: record 3 durable, chunk 3
        # never applied, no result record written
        assert proc.returncode == -signal.SIGKILL
        assert not out_json.exists()
        journal = ArrivalJournal(str(tmp_path))
        assert len(journal) == 4
        trace = journal.counts_trace()
        # nothing journaled was lost and nothing admits twice: the
        # trace + final carry account for every op the workers sent
        traced = int(sum(np.asarray(c).sum() for c in trace))
        carry = int(np.asarray(
            journal.entries[-1]["carry"]).sum())
        assert traced + carry == total
        # the resumed incarnation (journal alone, no live server)
        resumed = run_serve(cfg)
        assert resumed["resumed"] is True
        assert resumed["boundaries"] == 4
        assert resumed["trace_sha"] == \
            __import__("dmclock_tpu.net.serve",
                       fromlist=["trace_sha"]).trace_sha(trace)
        # ... lands on the digest of an uninterrupted run fed the
        # same admitted-counts trace: crash equivalence
        twin = run_serve(
            dataclasses.replace(cfg, workdir=None, wait_ops=0),
            trace=trace)
        assert resumed["digest"] == twin["digest"]
        assert resumed["decisions"] == twin["decisions"]
        # the journal is a replay source, not re-taken: unchanged
        assert len(ArrivalJournal(str(tmp_path))) == 4
