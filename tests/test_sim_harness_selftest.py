"""Harness self-test: the load generator against a fake server.

The reference tests its simulator's own client with a lambda standing
in for the server (test_test_client.cc:51-134 -- instant and delayed
responders, asserting op counts and the time envelope).  Same pattern
here, on virtual time instead of wall sleeps: the SimulatedClient's
``submit_f`` seam is bound to hand-written responders and the client's
rate limiting, outstanding-window blocking, completion accounting and
finish detection are pinned without any queue or tracker in the loop.
"""

from dmclock_tpu.core import NS_PER_SEC, Phase
from dmclock_tpu.sim.config import ClientGroup
from dmclock_tpu.sim.harness import EventLoop, SimulatedClient
from dmclock_tpu.sim.ssched import NullServiceTracker

S = NS_PER_SEC


def make_client(loop, group, submit_f, done):
    return SimulatedClient(
        0, group, NullServiceTracker(), loop,
        server_select_f=lambda seq: "srv",
        submit_f=submit_f,
        on_done=lambda cid: done.append(cid))


def test_instant_responder_rate_limited():
    """An instantly-responding fake server: the client is limited only
    by its own iops goal, so the run spans (N-1) inter-request gaps
    (reference test_client_full_bore_timing :51-73)."""
    loop = EventLoop()
    group = ClientGroup(client_count=1, client_total_ops=100,
                        client_iops_goal=1000, client_wait_s=0,
                        client_outstanding_ops=10)
    done = []
    served = []

    def submit_f(server, request, client_id, rp, cost):
        served.append(request)
        # respond within the same virtual instant
        loop.after(0, lambda: client.receive_response(
            request, Phase.PRIORITY, cost, server))

    client = make_client(loop, group, submit_f, done)
    loop.run()
    assert done == [0]
    assert client.stats.ops_requested == 100
    assert client.stats.ops_completed == 100
    assert client.stats.priority_ops == 100
    # rate envelope: 99 gaps of 1ms (gap rounds to whole us)
    assert client.stats.finish_time_ns == 99 * (S // 1000)


def test_paused_responder_blocks_at_window():
    """A responder that holds replies: the client must stop at its
    outstanding window, then finish after the server releases
    (reference test_client_paused_timing :93-134)."""
    loop = EventLoop()
    group = ClientGroup(client_count=1, client_total_ops=50,
                        client_iops_goal=100000, client_wait_s=0,
                        client_outstanding_ops=8)
    done = []
    held = []

    def submit_f(server, request, client_id, rp, cost):
        held.append((request, cost, server))

    client = make_client(loop, group, submit_f, done)
    # release replies only after 1s of virtual time
    pending_checked = {}

    def check_blocked():
        pending_checked["outstanding"] = client.outstanding
        pending_checked["sent"] = client.sent

    loop.at(S // 2, check_blocked)

    def release_all():
        while held:
            request, cost, server = held.pop(0)
            client.receive_response(request, Phase.RESERVATION, cost,
                                    server)

    def drain():
        release_all()
        if client.sent < group.client_total_ops or held:
            loop.after(1000, drain)

    loop.at(S, drain)
    loop.run()
    # at 0.5s the window was saturated: exactly 8 in flight, 8 sent
    assert pending_checked == {"outstanding": 8, "sent": 8}
    assert done == [0]
    assert client.stats.ops_completed == 50
    assert client.stats.reservation_ops == 50
    assert client.stats.finish_time_ns >= S


def test_initial_wait_defers_first_request():
    """client_wait_s delays the first send (reference CliInst wait,
    sim_client.h:40-70)."""
    loop = EventLoop()
    group = ClientGroup(client_count=1, client_total_ops=3,
                        client_iops_goal=1000, client_wait_s=2.0,
                        client_outstanding_ops=4)
    done = []
    first_send_ns = []

    def submit_f(server, request, client_id, rp, cost):
        if not first_send_ns:
            first_send_ns.append(loop.now_ns)
        loop.after(0, lambda: client.receive_response(
            request, Phase.PRIORITY, cost, server))

    client = make_client(loop, group, submit_f, done)
    loop.run()
    assert first_send_ns == [2 * S]
    assert done == [0]
