"""Push-driven simulation: the queue dispatches via handle_f (the mode
the reference's dmc_sim actually runs, test_dmclock.h:38-56).

With single-thread servers the push flow's dispatch instants coincide
with the pull server's polling instants, so the full service trace
must match the pull-mode sim EXACTLY -- a strong gate that the push
path's scheduling decisions (including sched-ahead timed wakeups) are
the same do_next_request stream."""

import os

import pytest

from dmclock_tpu.sim.config import parse_config_file
from dmclock_tpu.sim.dmc_sim import run_sim

CONFIGS = os.path.join(os.path.dirname(__file__), "..", "configs")


@pytest.mark.parametrize("model", ["dmclock", "dmclock-delayed",
                                   "ssched"])
def test_push_trace_matches_pull(model):
    cfg = parse_config_file(
        os.path.join(CONFIGS, "dmc_sim_example.conf"))
    pull = run_sim(cfg, model=model, seed=7, record_trace=True)
    push = run_sim(cfg, model=model, seed=7, record_trace=True,
                   server_mode="push")
    assert len(pull.trace) == len(push.trace) > 0
    for i, (a, b) in enumerate(zip(pull.trace, push.trace)):
        assert a == b, f"{model}: trace diverges at op {i}: " \
                       f"pull={a} push={b}"
    for cid in pull.clients:
        ca, cb = pull.clients[cid].stats, push.clients[cid].stats
        assert (ca.reservation_ops, ca.priority_ops) == \
            (cb.reservation_ops, cb.priority_ops)


def test_push_sched_ahead_wakeup_fires():
    """A hard-limited workload must progress purely on sched-ahead
    wakeups (no pending adds or completions to re-trigger dispatch)."""
    from dmclock_tpu.sim.config import ClientGroup, ServerGroup, SimConfig

    cfg = SimConfig(
        client_groups=1, server_groups=1,
        server_random_selection=False, server_soft_limit=False,
        cli_group=[ClientGroup(client_count=1, client_total_ops=40,
                               client_wait_s=0, client_iops_goal=200,
                               client_outstanding_ops=40,
                               client_reservation=0.0,
                               client_limit=20.0, client_weight=1.0,
                               client_server_select_range=1)],
        srv_group=[ServerGroup(server_count=1, server_iops=400,
                               server_threads=1)])
    sim = run_sim(cfg, model="dmclock-delayed", seed=3,
                  server_mode="push")
    st = sim.clients[0].stats
    assert st.ops_completed == 40
    # limit 20/s: 40 ops take ~2s of virtual time
    assert st.finish_time_ns >= int(1.8e9)


@pytest.mark.slow
def test_tpu_push_trace_matches_pull():
    """The TPU engine behind the push surface, in virtual time: the
    push-mode sim trace must equal the pull-mode TPU sim trace (scaled
    example shape; the full configs are covered for the pull path by
    test_sim_tpu_fullscale.py)."""
    from dmclock_tpu.sim.config import ClientGroup, ServerGroup, SimConfig

    groups = [
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=0,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=1,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40, client_wait_s=0,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=2.0, client_req_cost=3,
                    client_server_select_range=1),
    ]
    cfg = SimConfig(client_groups=len(groups), server_groups=1,
                    server_random_selection=False,
                    server_soft_limit=False, cli_group=groups,
                    srv_group=[ServerGroup(server_count=1,
                                           server_iops=160,
                                           server_threads=1)])
    pull = run_sim(cfg, model="dmclock-tpu", seed=7, record_trace=True)
    push = run_sim(cfg, model="dmclock-tpu", seed=7, record_trace=True,
                   server_mode="push")
    assert len(pull.trace) == len(push.trace) > 0
    for i, (a, b) in enumerate(zip(pull.trace, push.trace)):
        assert a == b, f"tpu trace diverges at op {i}: pull={a} push={b}"
