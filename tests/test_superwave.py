"""ingest_superwave must be bit-equivalent to sequential ingest_wave.

The superwave fuses W arrival waves into one ring pass; its contract is
exact equality with W sequential ``ingest_wave`` calls where wave w's
requesting set is ``counts > w`` -- across empty queues (head install),
idle clients (reactivation at wave 0), deep queues, and ring
wrap-around.  These tests drive both paths over randomized states
(including states mutated by serves, so q_head wraps) and compare every
state field.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import kernels

from engine_helpers import assert_states_equal, build_state, serial_run

S = NS_PER_SEC


def random_state(rng, n_clients, ring=16, serve_some=True):
    infos = {}
    for c in range(n_clients):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 4), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4), rng.uniform(3, 8))
        else:
            infos[c] = ClientInfo(0, 2, 0)
    adds = []
    t = S
    for _ in range(rng.randint(0, n_clients * 6)):
        c = rng.randrange(n_clients)
        t += rng.randint(0, S // 8)
        delta = rng.randint(1, 4)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=n_clients, ring=ring)
    if serve_some and adds:
        # advance q_head (ring wrap-around coverage) via real serves
        n_serve = rng.randint(0, len(adds) // 2)
        if n_serve:
            state, _ = serial_run(state, t + 100 * S, n_serve)
    # some idle clients with empty queues
    idle_extra = jnp.asarray(
        [rng.random() < 0.3 for _ in range(n_clients)])
    state = state._replace(
        idle=state.idle | (idle_extra & (state.depth == 0)))
    return state, t


def apply_sequential(state, counts, wave_times, cost, rho, delta):
    st = state
    for w in range(len(wave_times)):
        st = kernels.ingest_wave(
            st, jnp.asarray(counts > w), jnp.int64(wave_times[w]),
            cost, rho, delta, anticipation_ns=0)
    return st


@pytest.mark.parametrize("seed", [
    pytest.param(1, marks=pytest.mark.slow), 2,
    pytest.param(3, marks=pytest.mark.slow), 4, 5,
    pytest.param(6, marks=pytest.mark.slow)])
def test_superwave_equals_sequential_waves(seed):
    rng = random.Random(seed)
    n = rng.randint(3, 24)
    ring = rng.choice([8, 16, 32])
    state, t = random_state(rng, n, ring=ring)
    w = rng.randint(1, 6)
    headroom = ring - np.asarray(state.depth)
    counts = np.asarray(
        [rng.randint(0, min(w, int(headroom[i]))) for i in range(n)],
        dtype=np.int32)
    # inactive slots must not receive arrivals
    counts = np.where(np.asarray(state.active), counts, 0)
    dt = rng.randint(1, S // 4)
    wave_times = np.asarray([t + S + i * dt for i in range(w)],
                            dtype=np.int64)
    cost = jnp.asarray(rng.choices(range(1, 4), k=n), dtype=jnp.int64)
    rho = jnp.ones((n,), dtype=jnp.int64)
    delta = jnp.asarray(rng.choices(range(1, 4), k=n), dtype=jnp.int64)

    a = kernels.ingest_superwave(
        state, jnp.asarray(counts), jnp.asarray(wave_times), cost, rho,
        delta, anticipation_ns=0)
    b = apply_sequential(state, counts, wave_times, cost, rho, delta)
    assert_states_equal(a, b)


def test_superwave_then_serve_matches_serial():
    """After a superwave, the serial engine must produce a coherent
    decision stream that serves the ingested arrivals in tag order
    (end-to-end ingest+serve sanity, not just state equality)."""
    rng = random.Random(99)
    state, t = random_state(rng, 8, ring=16, serve_some=False)
    counts = np.minimum(
        16 - np.asarray(state.depth),
        np.asarray([rng.randint(1, 4) for _ in range(8)]))
    counts = np.where(np.asarray(state.active), counts, 0)
    wave_times = np.asarray([t + S + i * (S // 8) for i in range(4)],
                            dtype=np.int64)
    cost = jnp.ones((8,), dtype=jnp.int64)
    st = kernels.ingest_superwave(
        state, jnp.asarray(counts, dtype=jnp.int32),
        jnp.asarray(wave_times), cost, cost, cost, anticipation_ns=0)
    total = int(np.asarray(st.depth).sum())
    st2, decs = serial_run(st, int(wave_times[-1]) + 1000 * S, total)
    assert (decs.type == kernels.RETURNING).all()
    assert int(np.asarray(st2.depth).sum()) == 0


@pytest.mark.slow
def test_superwave_zero_counts_is_identity():
    rng = random.Random(7)
    state, t = random_state(rng, 6, ring=8)
    z = jnp.zeros((6,), dtype=jnp.int32)
    ones = jnp.ones((6,), dtype=jnp.int64)
    out = kernels.ingest_superwave(
        state, z, jnp.asarray([t + S], dtype=np.int64), ones, ones,
        ones, anticipation_ns=0)
    assert_states_equal(out, state)
