"""Capacity plane tests (docs/OBSERVABILITY.md "Capacity plane"):

- the compile/retrace observatory: instrumented jit caches compile
  once per signature, dispatch bit-identical results with the plane
  on/off, attribute retraces to the arg-signature diff that caused
  them, survive AOT-executable rejections by falling back to plain
  dispatch, and emit ``compile``-category spans + ``dmclock_compile_*``
  families;
- the HBM ledger + planner: exact linearity, plan_capacity round-trip
  (planned N fits, N+eps refuses), projection within 10% of the real
  compiled program's ``memory_analysis()`` argument bytes;
- roofline classification rules (dispatch-/compute-/memory-bound);
- the watchdog's retrace-storm warning: deterministic ``poll_once``
  coverage — fires once per episode, re-arms on a quiet window, and
  never fires on the legitimate first-compiles of an AOT pre-compile
  loop (the PR-8 chunk-length pattern);
- the doc-drift gate: every Prometheus family the code registers
  matches a docs/OBSERVABILITY.md metric-family-index row, and every
  index row matches something in the code.
"""

import re
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.obs import capacity as obscap
from dmclock_tpu.obs import compile_plane as cplane
from dmclock_tpu.obs import spans as obsspans
from dmclock_tpu.obs.registry import MetricsRegistry
from dmclock_tpu.obs.watchdog import Watchdog

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def plane():
    pl = cplane.plane()
    pl.reset()
    pl.enable(True)
    tracer0 = pl.tracer
    pl.set_tracer(None)
    yield pl
    pl.reset()
    pl.enable(True)
    pl.set_tracer(tracer0)


class TestCompilePlane:
    def test_compiles_once_per_signature(self, plane):
        j = cplane.instrumented_jit(lambda a, b: a * b + 1,
                                    cache="t", entry=("e", 1))
        a = jnp.arange(8, dtype=jnp.int64)
        r1 = j(a, jnp.int64(2))
        r2 = j(a, jnp.int64(5))       # same signature: no new compile
        assert np.array_equal(np.asarray(r1),
                              np.asarray(a) * 2 + 1)
        assert np.array_equal(np.asarray(r2),
                              np.asarray(a) * 5 + 1)
        t = plane.totals()
        assert t["compiles"] == 1 and t["retraces"] == 0

    def test_retrace_records_signature_diff(self, plane):
        j = cplane.instrumented_jit(lambda a: a + 1, cache="t",
                                    entry="e")
        j(jnp.arange(8, dtype=jnp.int64))
        j(jnp.arange(16, dtype=jnp.int64))
        t = plane.totals()
        assert t["compiles"] == 2 and t["retraces"] == 1
        (e,) = plane.entries()
        assert e["retraces"] == 1
        assert e["last_retrace_diff"], "retrace must carry its diff"
        assert "(8,)" in e["last_retrace_diff"][0]
        assert "(16,)" in e["last_retrace_diff"][0]
        assert len(plane.retrace_events()) == 1

    def test_results_match_plain_jit_and_plane_off(self, plane):
        def fn(s, t):
            return {"x": s["x"] * t, "y": s["y"].sum()}

        j = cplane.instrumented_jit(fn, cache="t", entry="e")
        args = ({"x": jnp.arange(6, dtype=jnp.int64),
                 "y": jnp.ones((3,), jnp.float64)}, jnp.int64(3))
        on = j(*args)
        plane.enable(False)
        off = j(*args)
        ref = jax.jit(fn)(*args)
        for k in ref:
            assert np.array_equal(np.asarray(on[k]),
                                  np.asarray(ref[k]))
            assert np.array_equal(np.asarray(off[k]),
                                  np.asarray(ref[k]))

    def test_cost_and_memory_analysis_recorded(self, plane):
        j = cplane.instrumented_jit(lambda a: (a * 2).sum(),
                                    cache="t", entry="e")
        j(jnp.arange(64, dtype=jnp.int64))
        (e,) = plane.entries()
        assert e["compile_ms"] > 0 and e["lower_ms"] > 0
        assert e["cost_analysis"].get("flops", 0) > 0
        assert e["memory_analysis"].get("argument_bytes") == 64 * 8

    def test_dispatch_fallback_on_rejected_executable(self, plane):
        j = cplane.instrumented_jit(lambda a: a + 1, cache="t",
                                    entry="e")
        a8 = jnp.arange(8, dtype=jnp.int64)
        a16 = jnp.arange(16, dtype=jnp.int64)
        j(a8)
        # poison: route a16's signature at a8's executable -- the AOT
        # call must reject (TypeError) and the wrapper must fall back
        # to plain jit dispatch with the CORRECT result, permanently
        sig16 = cplane._signature((a16,), {})
        j._compiled[sig16] = j._compiled[cplane._signature((a8,), {})]
        out = j(a16)
        assert np.array_equal(np.asarray(out), np.arange(16) + 1)
        assert plane.totals()["dispatch_fallbacks"] == 1
        out2 = j(a16)   # permanently routed; no second fallback count
        assert np.array_equal(np.asarray(out2), np.arange(16) + 1)
        assert plane.totals()["dispatch_fallbacks"] == 1

    def test_tracer_args_route_to_plain_jit(self, plane):
        inner = cplane.instrumented_jit(lambda a: a * 2, cache="t",
                                        entry="inner")

        @jax.jit
        def outer(a):
            return inner(a) + 1     # traced arg: must inline cleanly

        out = outer(jnp.arange(4, dtype=jnp.int64))
        assert np.array_equal(np.asarray(out), np.arange(4) * 2 + 1)

    def test_compile_spans_ride_attached_tracer(self, plane):
        tr = obsspans.SpanTracer()
        plane.set_tracer(tr)
        j = cplane.instrumented_jit(lambda a: a + 1, cache="spanned",
                                    entry="e")
        j(jnp.arange(4, dtype=jnp.int64))
        cats = tr.category_counts()
        assert cats.get("compile", 0) >= 1
        names = {n for (n, c) in tr.name_stats() if c == "compile"}
        assert "compile.spanned" in names

    def test_clear_compiled_recompiles(self, plane):
        j = cplane.instrumented_jit(lambda a: a + 1, cache="t",
                                    entry="e")
        a = jnp.arange(4, dtype=jnp.int64)
        j(a)
        cplane.clear_compiled()
        j(a)
        t = plane.totals()
        assert t["compiles"] == 2   # re-lowered after the clear

    def test_aot_record(self, plane):
        comp = cplane.aot_record(
            "bench.test", ("e", 1), jax.jit(lambda a: a * 3),
            jnp.arange(8, dtype=jnp.int64))
        out = comp(jnp.arange(8, dtype=jnp.int64))
        assert np.array_equal(np.asarray(out), np.arange(8) * 3)
        (e,) = plane.entries()
        assert e["cache"] == "bench.test" and e["compiles"] == 1
        # same entry compiled again = a retrace (bench chunk lengths
        # are DIFFERENT entries, so the pre-compile loop records none)
        cplane.aot_record("bench.test", ("e", 1),
                          jax.jit(lambda a: a * 3),
                          jnp.arange(8, dtype=jnp.int64))
        assert plane.totals()["retraces"] == 1

    def test_publish_compile_metrics(self, plane):
        j = cplane.instrumented_jit(lambda a: a + 1, cache="fam",
                                    entry="e")
        j(jnp.arange(4, dtype=jnp.int64))
        reg = MetricsRegistry()
        cplane.publish_compile_metrics(reg, plane)
        text = reg.prometheus()
        for fam in ("dmclock_compile_events_total",
                    "dmclock_compile_retraces_total",
                    "dmclock_compile_ms_total",
                    "dmclock_compile_lower_ms_total",
                    "dmclock_compile_cache_entries",
                    "dmclock_compile_flops",
                    "dmclock_compile_bytes_accessed",
                    "dmclock_compile_hbm_bytes"):
            assert fam in text, fam
        assert 'cache="fam"' in text

    def test_guarded_epoch_digest_identical_plane_on_off(self, plane):
        from __graft_entry__ import _preloaded_state
        from dmclock_tpu.robust.guarded import run_epoch_guarded

        def digest(ep):
            import hashlib
            h = hashlib.sha256()
            for r in ep.results:
                for name in ("count", "slot", "phase", "cost"):
                    if hasattr(r, name):
                        h.update(np.asarray(jax.device_get(
                            getattr(r, name))).tobytes())
            return h.hexdigest()

        digs = {}
        for on in (True, False):
            plane.enable(on)
            st = _preloaded_state(256, 6, ring=8)
            ep = run_epoch_guarded(st, 10 ** 9, engine="prefix", m=2,
                                   k=32)
            digs[on] = digest(ep)
        assert digs[True] == digs[False]


class TestSupervisedCompileRecords:
    def test_compile_spans_ride_span_log_and_crash_gate_holds(
            self, plane, tmp_path):
        """The supervisor attaches its per-incarnation tracer to the
        compile plane, so compile records flush with the span_log at
        checkpoint boundaries (the rotation checkpoints' durability
        window) -- and the PR-5 crash-equivalence gate is unaffected
        by the plane being on."""
        from dmclock_tpu.obs.spans import load_jsonl
        from dmclock_tpu.robust import host_faults as HF
        from dmclock_tpu.robust import supervisor as SV

        job = SV.EpochJob(engine="prefix", n=96, depth=5, ring=8,
                          epochs=4, m=2, k=16, seed=7,
                          arrival_lam=1.0, waves=3, ckpt_every=2,
                          span_log=str(tmp_path / "spans.jsonl"))
        ref = SV.run_job(dataclasses_replace_no_log(job))
        # drop the executables the reference run compiled, so the
        # supervised incarnation re-compiles (and its span stream
        # carries the compile records)
        cplane.clear_compiled()
        sup = SV.run_supervised(job, str(tmp_path / "wd"),
                                HF.zero_host_plan())
        SV.assert_crash_equivalent(sup, ref)
        rows = load_jsonl(job.span_log)
        comp = [r for r in rows if r["cat"] == "compile"]
        assert comp, "compile spans must ride the span_log stream"
        assert any(r["name"].startswith("compile.") for r in comp)
        # the record instants carry the compile payload
        recs = [r for r in comp if r["name"].endswith(".record")]
        assert recs and "compile_ms" in (recs[0].get("args") or {})


def dataclasses_replace_no_log(job):
    import dataclasses

    return dataclasses.replace(job, span_log=None)


class TestLedgerAndPlanner:
    CFG = dict(ring=16, engine="prefix", m=2, k=64, telemetry=True,
               slo=True, flight_records=32)

    def test_ledger_matches_real_state_bytes(self):
        from dmclock_tpu.engine.state import init_state

        led = obscap.hbm_ledger(128, ring=16)
        st = init_state(128, 16)
        real = sum(np.asarray(x).nbytes
                   for x in jax.tree_util.tree_leaves(st))
        assert led["client_state"] + led["rings"] == real

    def test_model_linearity_exact(self):
        model = obscap.capacity_model(**self.CFG)
        direct = obscap.hbm_ledger(4096, **self.CFG)
        assert model.ledger(4096) == direct

    def test_plan_capacity_round_trip(self):
        budget = 1 << 30
        plan = obscap.plan_capacity(budget, **self.CFG)
        n = plan["max_clients"]
        assert n > 0
        assert obscap.fits(n, budget, **self.CFG)
        assert not obscap.fits(n + 1024, budget, **self.CFG)
        assert plan["projected_bytes"] <= plan["usable_bytes"]

    def test_stream_chunk_multiplies_outputs(self):
        l1 = obscap.hbm_ledger(512, **self.CFG)
        l8 = obscap.hbm_ledger(512, stream_chunk=8, **self.CFG)
        assert l8["epoch_outputs"] == 8 * l1["epoch_outputs"]
        for k in l1:
            if k != "epoch_outputs":
                assert l8[k] == l1[k]

    def test_projection_within_10pct_of_memory_analysis(self, plane):
        """The acceptance gate's small twin (ci.sh runs the cfg4
        shape): the ledger's resident-argument projection vs the real
        compiled epoch program's memory_analysis argument bytes."""
        import functools

        from __graft_entry__ import _preloaded_state
        from dmclock_tpu.engine import fastpath
        from dmclock_tpu.obs import histograms as obshist
        from dmclock_tpu.obs import slo as obsslo

        n, ring, m, k = 512, 16, 2, 64
        st = _preloaded_state(n, 6, ring=ring)
        comp = cplane.aot_record(
            "test.capacity", "proj-gate",
            jax.jit(functools.partial(
                fastpath.scan_prefix_epoch, m=m, k=k,
                anticipation_ns=0, with_metrics=True)),
            st, jnp.int64(0), hists=obshist.hist_zero(),
            ledger=obshist.ledger_zero(n), slo=obsslo.window_zero(n))
        mem = cplane.memory_analysis_dict(comp)
        assert mem.get("argument_bytes", 0) > 0
        led = obscap.hbm_ledger(n, ring=ring, telemetry=True,
                                slo=True)
        projected_args = sum(led.values())
        measured = mem["argument_bytes"]
        assert abs(projected_args - measured) <= 0.10 * measured, \
            (projected_args, measured)

    def test_device_budget_env_override(self, monkeypatch):
        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "987654")
        assert obscap.device_hbm_budget() == 987654
        # 0 = detection disabled (not a zero-byte budget that would
        # gate every workload)
        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "0")
        assert obscap.device_hbm_budget() is None
        monkeypatch.delenv("DMCLOCK_HBM_BUDGET_BYTES")
        # cpu backend: no memory_stats -> None (host RAM is not HBM)
        assert obscap.device_hbm_budget() is None


class TestRoofline:
    PK = dict(peak_flops=1e12, peak_bytes_per_s=1e11)  # balance 10

    def test_dispatch_bound_wins(self):
        out = obscap.classify(flops=1e12, bytes_accessed=1e9,
                              device_time_s=0.001,
                              dispatch_time_s=0.01, **self.PK)
        assert out["bound_class"] == "dispatch_bound"
        assert out["dispatch_share"] > 0.9

    def test_memory_vs_compute_ridge(self):
        lo = obscap.classify(flops=1e9, bytes_accessed=1e9, **self.PK)
        hi = obscap.classify(flops=1e11, bytes_accessed=1e9,
                             **self.PK)
        assert lo["bound_class"] == "memory_bound"
        assert hi["bound_class"] == "compute_bound"
        assert lo["arithmetic_intensity"] == 1.0

    def test_unknown_without_cost_data(self):
        out = obscap.classify(flops=0.0, bytes_accessed=0.0,
                              **self.PK)
        assert out["bound_class"] == "unknown"

    def test_classify_bench_row_joins_spans(self):
        row = {"cost_analysis": {"flops": 1e9,
                                 "bytes_accessed": 1e9},
               "spans": {"dispatch_ms_per_launch": 20.0,
                         "device_ms_per_launch": 1.0}}
        out = obscap.classify_bench_row(row, peaks=self.PK)
        assert out["bound_class"] == "dispatch_bound"
        row["spans"]["dispatch_ms_per_launch"] = 0.1
        out = obscap.classify_bench_row(row, peaks=self.PK)
        assert out["bound_class"] == "memory_bound"


class TestRetraceStormWatchdog:
    def _setup(self, k=3, window_s=100.0):
        clock = {"t": 1_000_000_000}

        def clock_ns():
            return clock["t"]

        pl = cplane.CompilePlane(clock_ns=clock_ns)
        tr = obsspans.SpanTracer(clock_ns=clock_ns)
        wd = Watchdog(tr, compile_plane=pl, retrace_storm_k=k,
                      retrace_window_s=window_s, stall_after_s=1e9,
                      log=lambda _line: None, clock_ns=clock_ns)
        return clock, pl, wd

    def _retrace(self, pl, entry="queue:('run', 1)"):
        # a compile event on an entry that already compiled = retrace
        pl.record_compile(entry.split(":")[0], entry.split(":")[1],
                          lower_ns=1, compile_ns=1, cost={}, hbm={})

    def test_fires_once_per_episode_and_rearms(self):
        clock, pl, wd = self._setup(k=3, window_s=100.0)
        for _ in range(4):          # 1 first compile + 3 retraces
            self._retrace(pl)
        warns = wd.poll_once()
        assert [w["kind"] for w in warns] == ["retrace_storm"]
        assert warns[0]["retraces"] == 3
        # same storm still in window: once per episode, no repeat
        assert wd.poll_once() == []
        # quiet window re-arms ...
        clock["t"] += int(200e9)
        assert wd.poll_once() == []
        # ... and a NEW storm fires again
        for _ in range(3):
            self._retrace(pl)
        warns = wd.poll_once()
        assert [w["kind"] for w in warns] == ["retrace_storm"]

    def test_distinct_entries_below_threshold_never_fire(self):
        clock, pl, wd = self._setup(k=3)
        # the PR-8 AOT pre-compile pattern: one FIRST compile per
        # chunk length -- distinct entries, zero retraces
        for c in (1, 2, 4, 8, 16, 32):
            pl.record_compile("bench.chunk", f"(cfg, {c})",
                              lower_ns=1, compile_ns=1, cost={},
                              hbm={})
        assert pl.totals()["retraces"] == 0
        assert wd.poll_once() == []
        # and 2 retraces each on two DIFFERENT entries stay below k=3
        for entry in ("queue:a", "queue:b"):
            self._retrace(pl, entry)
            self._retrace(pl, entry)
            self._retrace(pl, entry)  # 3rd compile = 2nd retrace
        assert wd.poll_once() == []

    def test_real_aot_precompile_loop_never_warns(self):
        """End-to-end twin of the bench's chunk pre-compile: real
        jits, one entry per chunk length, watchdog polling after."""
        clock, pl, wd = self._setup(k=2)
        for c in (1, 2, 4):
            compiled = jax.jit(lambda a, c=c: a * c).lower(
                jnp.arange(4, dtype=jnp.int64)).compile()
            pl.record_compile("bench.chunk", f"(shape, {c})",
                              lower_ns=1, compile_ns=1,
                              cost=cplane.cost_analysis_dict(compiled),
                              hbm=cplane.memory_analysis_dict(
                                  compiled))
        assert wd.poll_once() == []
        assert pl.totals()["compiles"] == 3
        assert pl.totals()["retraces"] == 0

    def test_watchdog_without_plane_unaffected(self):
        tr = obsspans.SpanTracer()
        wd = Watchdog(tr, log=lambda _line: None)
        assert wd.poll_once() == []


class TestDocDrift:
    """The metric-family index in docs/OBSERVABILITY.md is a contract:
    families the code registers must appear in it, and index rows must
    point at something real."""

    @staticmethod
    def _doc_patterns():
        text = (REPO / "docs" / "OBSERVABILITY.md").read_text()
        start = text.index("## Metric-family index")
        end = text.index("\n## ", start + 10)
        pats = []
        for tok in re.findall(r"`([A-Za-z0-9_*]+)`", text[start:end]):
            if tok.startswith(("dmclock_", "sim_")):
                pats.append(tok)
        assert pats, "metric-family index table not found"
        return pats

    @staticmethod
    def _matches(name: str, pat: str) -> bool:
        if "*" in pat:
            prefix = pat.split("*", 1)[0]
            return name.startswith(prefix) or prefix.startswith(name)
        return name == pat or name.startswith(pat) \
            or pat.startswith(name)

    def _registered_names(self):
        """Exercise every cheaply-runnable publisher into one registry
        and return the family names it holds."""
        from dmclock_tpu.control import Controller, as_spec
        from dmclock_tpu.lifecycle import make_spec
        from dmclock_tpu.lifecycle.placement import PlacementMap
        from dmclock_tpu.lifecycle.plane import LifecyclePlane
        from dmclock_tpu.obs import device as obsdev
        from dmclock_tpu.obs import histograms as obshist
        from dmclock_tpu.obs import provenance as obsprov
        from dmclock_tpu.obs import slo as obsslo
        from dmclock_tpu.obs.alerts import SloEvaluator
        from dmclock_tpu.obs.registry import publish_span_gauges

        reg = MetricsRegistry()
        obsprov.publish_provenance(reg, obsprov.prov_init(2))
        obsprov.publish_shard_pressure(
            reg, np.zeros((1, obsprov.PRESS_FIELDS), dtype=np.int64),
            np.zeros(obsprov.PRESS_FIELDS, dtype=np.int64))
        obsprov.StarvationMonitor(10 ** 9, registry=reg,
                                  log=lambda _l: None)
        obsdev.publish(reg, np.zeros(obsdev.NUM_METRICS,
                                     dtype=np.int64))
        obsdev.publish_shard_faults(
            reg, np.zeros((2, 3), dtype=np.int64))
        obshist.publish_hists(reg, obshist.hist_zero())
        obshist.publish_ledger(reg, np.zeros((4, obshist.LED_COLS),
                                             dtype=np.int64))
        obsslo.publish_shard_windows(
            reg, np.zeros((2, 2, obsslo.W_FIELDS), dtype=np.int64))
        publish_span_gauges(reg, {"dispatch_ms_per_launch": 1.0,
                                  "device_ms_per_launch": 1.0,
                                  "host_overhead_frac": 0.1})
        Watchdog(obsspans.SpanTracer(), registry=reg,
                 log=lambda _l: None)
        SloEvaluator(obsslo.SloPlane(2, dt_epoch_ns=10 ** 8),
                     registry=reg, log=lambda _l: None)
        pl = cplane.CompilePlane()
        pl.record_compile("t", "e", lower_ns=1, compile_ns=1,
                          cost={"flops": 1.0, "bytes_accessed": 1.0},
                          hbm={"total_bytes": 1})
        cplane.publish_compile_metrics(reg, pl)
        obscap.publish_capacity_metrics(reg, projected_bytes=1,
                                        budget_bytes=1, max_clients=1,
                                        workload="t")
        LifecyclePlane(make_spec("flash_crowd", total_ids=8)) \
            .publish(reg)
        PlacementMap(2, 8).publish(reg)
        Controller(as_spec(True), n=4, ring=4, registry=reg)
        from dmclock_tpu.obs import rpc as obsrpc
        obsrpc.publish_rpc(reg, {"queue_depth": 0, "connections": 0,
                                 "device_pressure": False,
                                 "shard_rx": {"0": 0},
                                 "counters": {}})
        obsrpc.publish_rpc_latency(reg,
                                   obsrpc.latency_summary([10 ** 6]))
        return sorted({m.name for m in reg.metrics()})

    @staticmethod
    def _static_names():
        """Family-name literals at registration call sites
        (.counter/.gauge/.histogram/.timer first args), normalized to
        prefixes at the first f-string hole."""
        rx = re.compile(
            r"\.(?:counter|gauge|histogram|timer)\(\s*f?[\"']"
            r"((?:dmclock|sim)_[A-Za-z0-9_{}]*)", re.S)
        names = set()
        files = list((REPO / "dmclock_tpu").rglob("*.py")) \
            + [REPO / "bench.py"] \
            + list((REPO / "scripts").glob("*.py"))
        for p in files:
            for m in rx.finditer(p.read_text()):
                name = m.group(1).split("{", 1)[0].rstrip("_")
                if name.count("_") >= 1:
                    names.add(name)
        assert names, "no registration sites found"
        return sorted(names)

    def test_registered_families_are_documented(self):
        pats = self._doc_patterns()
        missing = [n for n in self._registered_names()
                   if not any(self._matches(n, p) for p in pats)]
        assert not missing, \
            (f"families registered by code but absent from the "
             f"docs/OBSERVABILITY.md metric-family index: {missing}")

    def test_static_registration_sites_are_documented(self):
        pats = self._doc_patterns()
        missing = [n for n in self._static_names()
                   if not any(self._matches(n, p) for p in pats)]
        assert not missing, \
            (f"registration-site names absent from the metric-family "
             f"index: {missing}")

    def test_documented_families_exist_in_code(self):
        registered = self._registered_names()
        static = self._static_names()
        src = "\n".join(p.read_text() for p in
                        list((REPO / "dmclock_tpu").rglob("*.py"))
                        + [REPO / "bench.py"]
                        + list((REPO / "scripts").glob("*.py")))
        rotted = []
        for pat in self._doc_patterns():
            prefix = pat.split("*", 1)[0].rstrip("_")
            hit = any(self._matches(n, pat)
                      for n in registered + static) \
                or prefix in src
            if not hit:
                rotted.append(pat)
        assert not rotted, \
            (f"metric-family index rows pointing at nothing in the "
             f"code: {rotted}")

    def test_new_capacity_families_bidirectional(self):
        """The strong form for the families this plane adds: exactly
        what publish_* registers must be indexed, and every indexed
        dmclock_compile_*/dmclock_capacity_* token must be
        registered."""
        reg = MetricsRegistry()
        pl = cplane.CompilePlane()
        pl.record_compile("t", "e", lower_ns=1, compile_ns=1,
                          cost={"flops": 1.0, "bytes_accessed": 1.0},
                          hbm={"total_bytes": 1})
        cplane.publish_compile_metrics(reg, pl)
        obscap.publish_capacity_metrics(reg, projected_bytes=1,
                                        budget_bytes=1, max_clients=1,
                                        workload="t")
        names = {m.name for m in reg.metrics()}
        pats = self._doc_patterns()
        for n in names:
            assert any(self._matches(n, p) for p in pats), n
        doc_new = [p for p in pats
                   if p.startswith(("dmclock_compile_",
                                    "dmclock_capacity_"))
                   and "*" not in p]
        for p in doc_new:
            assert p in names, \
                f"indexed family {p} is not registered by the " \
                "capacity-plane publishers"


class TestBenchCapacityGate:
    def test_gate_skips_over_budget_and_passes_under(self,
                                                     monkeypatch):
        import bench

        cfg = dict(n=4096, ring=64, engine="prefix", m=4, k=256,
                   telemetry=True, slo=True)
        need = obscap.projected_hbm(4096, **{k: v for k, v in
                                             cfg.items() if k != "n"})
        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES",
                           str(int(need * 0.5)))
        row = bench._capacity_gate(cfg, engine_loop="stream")
        assert row is not None and row["capacity_skipped"]
        assert row["dps"] == 0.0
        assert row["engine_loop"] == "stream"
        assert row["projected_hbm_bytes"] > row["hbm_budget_bytes"] \
            * 0.9
        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES",
                           str(int(need * 10)))
        assert bench._capacity_gate(cfg) is None

    def test_gate_never_raises_on_garbage(self, monkeypatch):
        import bench

        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "1000000")
        assert bench._capacity_gate({"n": 64, "engine": "nonsense",
                                     "bogus_knob": 1}) is None
