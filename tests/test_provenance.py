"""Decision provenance plane tests (obs.provenance; docs/OBSERVABILITY.md
"Provenance plane").

The load-bearing contracts:

1. **On/off bit-identity** -- the provenance block must not perturb
   the decision stream or final state on any epoch engine or fast
   path (pure reductions over arrays the batches already
   materialize).
2. **Margin exactness** -- the sorted engines' per-decision margin is
   the EXACT runner-up distance (next sorted entry vs the served
   prefix's re-entry keys), pinned on a hand-built two-client race.
3. **Cross-loop exactness** -- the block's contents are bit-identical
   between the round and the stream loop, and crash equivalence
   extends to it (tests via robust.supervisor).
4. **Starvation detector** -- the last_served watermark and the
   once-per-episode client_starved warnings (fire on rising edge,
   re-arm on service).
5. **Flight overflow at stream-chunk boundaries** -- the newest-R
   contract holds when a single FUSED chunk commits more than R
   records, on all three engines (previously only exercised via the
   round loop).
6. **Trace schema v2** -- margin/eligible_depth/gate columns, the
   backward-compatible v1 reader, and the per-phase-vs-device-counters
   hard cross-check.
7. **explain.py** -- the seeded limit-starvation scenario attributes
   to limit_capped; synthetic window rows hit each cause.
"""

import functools
import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import NS_PER_SEC
from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                         scan_chain_epoch,
                                         scan_prefix_epoch,
                                         speculate_prefix_batch)
from dmclock_tpu.obs import flight as obsflight
from dmclock_tpu.obs import histograms as obshist
from dmclock_tpu.obs import provenance as obsprov
from dmclock_tpu.obs import MetricsRegistry

from engine_helpers import deep_state, starvation_scenario

REPO = Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "explain", REPO / "scripts" / "explain.py")
explain_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(explain_mod)

S = NS_PER_SEC

from dmclock_tpu.core import ClientInfo

INFOS = {
    0: ClientInfo(10.0, 2.0, 50.0),
    1: ClientInfo(5.0, 1.0, 40.0),
    2: ClientInfo(0.0, 3.0, 0.0),
}


def _mixed_state(depth=6):
    return deep_state(INFOS, depth)


def _digest(ep, fields):
    import hashlib

    h = hashlib.sha256()
    for f in fields:
        h.update(np.asarray(jax.device_get(getattr(ep, f))).tobytes())
    h.update(np.asarray(jax.device_get(
        jax.tree.leaves(ep.state)[0])).tobytes())
    return h.hexdigest()


ENGINES = {
    "prefix": (functools.partial(scan_prefix_epoch, m=3, k=16,
                                 anticipation_ns=0),
               ("count", "slot", "phase", "cost", "lb")),
    "chain": (functools.partial(scan_chain_epoch, m=3, k=8,
                                chain_depth=3, anticipation_ns=0),
              ("count", "slot", "cls", "length")),
    "calendar": (functools.partial(scan_calendar_epoch, m=2, steps=4,
                                   calendar_impl="minstop"),
                 ("count", "resv_count", "served")),
    "calendar-bucketed": (functools.partial(
        scan_calendar_epoch, m=2, steps=4, calendar_impl="bucketed",
        ladder_levels=3), ("count", "resv_count", "served")),
}


class TestBitIdentity:
    @pytest.mark.parametrize("name", sorted(ENGINES))
    def test_prov_on_off(self, name):
        fn, fields = ENGINES[name]
        off = jax.jit(fn)(_mixed_state(), jnp.int64(S))
        prov = obsprov.prov_init(64)
        on = jax.jit(lambda s, t: fn(s, t, prov=prov))(
            _mixed_state(), jnp.int64(S))
        assert _digest(off, fields) == _digest(on, fields)
        assert on.prov is not None and off.prov is None
        scal = np.asarray(jax.device_get(on.prov.scal))
        assert scal[obsprov.PS_BATCHES] > 0

    @pytest.mark.slow
    @pytest.mark.parametrize("kw", [
        dict(select_impl="radix"), dict(tag_width=32)])
    def test_prov_on_off_fastpaths(self, kw):
        fn = functools.partial(scan_prefix_epoch, m=3, k=16,
                               anticipation_ns=0, **kw)
        off = jax.jit(fn)(_mixed_state(), jnp.int64(S))
        prov = obsprov.prov_init(64)
        on = jax.jit(lambda s, t: fn(s, t, prov=prov))(
            _mixed_state(), jnp.int64(S))
        fields = ("count", "slot", "phase", "cost", "lb")
        assert _digest(off, fields) == _digest(on, fields)

    def test_contents_equal_across_select_impls(self):
        """sort and radix commit identical decisions, so the
        provenance observations must be bit-identical too."""
        blocks = {}
        for impl in ("sort", "radix"):
            fn = functools.partial(scan_prefix_epoch, m=3, k=16,
                                   anticipation_ns=0,
                                   select_impl=impl)
            prov = obsprov.prov_init(64)
            ep = jax.jit(lambda s, t, fn=fn: fn(s, t, prov=prov))(
                _mixed_state(), jnp.int64(S))
            blocks[impl] = jax.device_get(ep.prov)
        for a, b in zip(blocks["sort"], blocks["radix"]):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestMarginExactness:
    def test_two_client_race(self):
        """Two weight-only clients with distinct proportion tags: the
        first decision's margin is the runner-up's tag distance."""
        infos = {0: ClientInfo(0.0, 4.0, 0.0),
                 1: ClientInfo(0.0, 1.0, 0.0)}
        st = deep_state(infos, 4)
        b = speculate_prefix_batch(st, jnp.int64(S), 8,
                                   anticipation_ns=0)
        margins = np.asarray(jax.device_get(b.margins))
        slots = np.asarray(jax.device_get(b.decisions.slot))
        count = int(jax.device_get(b.count))
        assert count >= 1
        # recompute the unified entry keys on the host: eff prop tag
        eff = np.asarray(jax.device_get(
            st.head_prop + st.prop_delta))
        # winner = lower eff tag; its exact runner-up is min(next
        # entry, its own... ) -- for two clients entering at distinct
        # tags, decision 0's runner-up is the OTHER client's entry
        # (both weight class), so margin0 == |eff delta| up to the
        # packed order bits (< 1 ns truncation)
        want = abs(int(eff[0]) - int(eff[1]))
        assert margins[0] >= 0
        assert abs(int(margins[0]) - want) <= 1, \
            (margins[:4].tolist(), want, slots[:4].tolist())

    def test_no_runner_up_records_nothing(self):
        """A sole candidate has no runner-up: margin -1, and the
        histogram stays empty."""
        infos = {0: ClientInfo(0.0, 4.0, 0.0)}
        st = deep_state(infos, 1)
        prov = obsprov.prov_init(64)
        ep = scan_prefix_epoch(st, jnp.int64(S), 1, 4,
                               anticipation_ns=0, prov=prov)
        h = np.asarray(jax.device_get(ep.prov.margin_hist))
        assert h[:obshist.NUM_BUCKETS].sum() == 0
        assert int(jax.device_get(ep.count).sum()) >= 1


class TestProvAlgebra:
    def test_combine_and_select(self):
        a = obsprov.prov_init(4)
        b = obsprov.ProvBlock(
            margin_hist=jnp.ones_like(a.margin_hist),
            scal=jnp.arange(obsprov.PS_FIELDS, dtype=jnp.int64),
            last_served=jnp.asarray([5, 0, 7, 0], jnp.int64))
        c = obsprov.prov_combine(a, b)
        assert np.array_equal(np.asarray(c.scal), np.asarray(b.scal))
        assert np.array_equal(np.asarray(c.last_served),
                              np.asarray(b.last_served))
        # max rows max, counter rows add
        d = obsprov.prov_combine(b, b)
        scal = np.asarray(d.scal)
        for i in range(obsprov.PS_FIELDS):
            want = i if i in (obsprov.PS_GATE_MAX,
                              obsprov.PS_ELIG_MAX,
                              obsprov.PS_STARVE_MAX) else 2 * i
            assert scal[i] == want, (i, scal[i], want)
        # liveness select: dead keeps OLD, live takes NEW
        dead = obsprov.prov_select(jnp.bool_(False), b, a)
        assert np.asarray(dead.scal).sum() == 0
        live = obsprov.prov_select(jnp.bool_(True), b, a)
        assert np.array_equal(np.asarray(live.scal),
                              np.asarray(b.scal))

    def test_init_baseline(self):
        """A block armed mid-run measures staleness from its own
        creation time, not from virtual t=0 (the bench's
        post-calibration reset must not read continuously-served
        clients as starved since the beginning of the run)."""
        prov = obsprov.prov_init(3, now_ns=500)
        assert np.asarray(prov.last_served).tolist() == [500] * 3
        newp = obsprov.prov_observe(
            prov, now=jnp.int64(700),
            elig=jnp.asarray([True, True, False]),
            gated=jnp.zeros(3, bool), win_cls=jnp.int32(1),
            served_pc=jnp.zeros(3, jnp.int32))
        scal = np.asarray(jax.device_get(newp.scal))
        assert scal[obsprov.PS_STARVE_MAX] == 200   # 700 - 500, not 700
        rows = obsprov.stale_clients(prov, 700, 100,
                                     backlog=np.asarray([1, 0, 0]))
        assert [r["client"] for r in rows] == [0]
        assert rows[0]["stale_ns"] == 200

    def test_dict_and_publish(self):
        prov = obsprov.prov_init(4)
        d = obsprov.prov_dict(prov)
        assert d["batches"] == 0 and d["margin_p50_ns"] == 0.0
        reg = MetricsRegistry()
        obsprov.publish_provenance(reg, prov)
        names = {m.name for m in reg.metrics()}
        assert "dmclock_provenance_margin_p99_ns" in names
        assert "dmclock_starvation_max_ns" in names


class TestStarvation:
    def test_last_served_watermark(self):
        """Clients served this epoch stamp now; unserved keep their
        old watermark and grow the starvation max."""
        st = _mixed_state()
        prov = obsprov.prov_init(64)
        ep = scan_prefix_epoch(st, jnp.int64(S), 2, 8,
                               anticipation_ns=0, prov=prov)
        last = np.asarray(jax.device_get(ep.prov.last_served))
        slots = np.asarray(jax.device_get(ep.slot)).ravel()
        served = set(int(s) for s in slots if s >= 0)
        for c in served:
            assert last[c] == S
        assert int(np.asarray(jax.device_get(
            ep.prov.scal))[obsprov.PS_STARVE_MAX]) == S

    def test_monitor_once_per_episode(self):
        fired_log = []
        mon = obsprov.StarvationMonitor(100, log=fired_log.append)
        prov = obsprov.prov_init(3)
        backlog = np.asarray([1, 1, 0])
        # client 0 and 1 backlogged and stale; 2 idle-stale (ignored)
        w1 = mon.observe(prov, 500, backlog=backlog)
        assert {w["client"] for w in w1} == {0, 1}
        # same stale set: no re-fire
        assert mon.observe(prov, 600, backlog=backlog) == []
        # client 0 served (watermark catches up): episode re-arms
        prov2 = prov._replace(
            last_served=jnp.asarray([590, 0, 0], jnp.int64))
        assert mon.observe(prov2, 600, backlog=backlog) == []
        w3 = mon.observe(prov2, 800, backlog=backlog)
        assert {w["client"] for w in w3} == {0}
        assert mon.episodes_total == 3
        assert len(fired_log) == 3

    def test_monitor_routes_through_watchdog(self):
        class FakeWd:
            def __init__(self):
                self.warnings = []

            def external_warning(self, obj):
                self.warnings.append(obj)

        wd = FakeWd()
        mon = obsprov.StarvationMonitor(10, watchdog=wd)
        mon.observe(obsprov.prov_init(2), 100,
                    backlog=np.asarray([1, 0]))
        assert len(wd.warnings) == 1
        assert wd.warnings[0]["kind"] == "client_starved"


class TestFlightChunkOverflow:
    """Satellite: the newest-R-on-overflow contract when a single
    FUSED stream chunk commits more than R records, on all three
    engines (previously only exercised via the round loop)."""

    @pytest.mark.parametrize("engine,kw", [
        ("prefix", dict(k=8)),
        ("chain", dict(k=8, chain_depth=2)),
        ("calendar", dict(k=3)),
    ])
    def test_one_chunk_overflow_keeps_newest(self, engine, kw):
        from dmclock_tpu.robust.guarded import run_stream_chunk_guarded

        R = 4
        st = _mixed_state(depth=8)
        fl = obsflight.flight_init(R)
        g = run_stream_chunk_guarded(
            st, 0, None, engine=engine, epochs=3, m=2,
            dt_epoch_ns=S, waves=2, flight=fl, **kw)
        assert g.stream_fallback == 0
        seq = int(jax.device_get(g.flight.seq))
        total = sum(g.counts) if engine == "prefix" else seq
        assert seq > R, (engine, seq)
        recs = obsflight.flight_drain(g.flight)
        assert len(recs) == R
        # newest R, contiguous, ending at the final record
        assert [r["seq"] for r in recs] == list(range(seq - R, seq))
        if engine == "prefix":
            assert seq == total   # one record per decision
        # the provenance columns ride every record
        assert all("margin" in r and "gate" in r for r in recs)

    def test_chunk_overflow_matches_round_loop(self):
        """The ring after one fused chunk == the ring after the same
        epochs on the round loop (newest-R is loop-invariant)."""
        from dmclock_tpu.robust.guarded import (run_epoch_guarded,
                                                run_stream_chunk_guarded)

        R = 4
        g = run_stream_chunk_guarded(
            _mixed_state(depth=8), 0, None, engine="prefix",
            epochs=3, m=2, k=8, dt_epoch_ns=S, waves=2,
            flight=obsflight.flight_init(R))
        st = _mixed_state(depth=8)
        fl = obsflight.flight_init(R)
        for e in range(3):
            ep = run_epoch_guarded(st, (e + 1) * S, engine="prefix",
                                   m=2, k=8, flight=fl)
            st, fl = ep.state, ep.flight
        assert np.array_equal(
            np.asarray(jax.device_get(g.flight.buf)),
            np.asarray(jax.device_get(fl.buf)))
        assert int(jax.device_get(g.flight.seq)) == \
            int(jax.device_get(fl.seq))


class TestTraceV2:
    def test_writer_reader_round_trip(self, tmp_path):
        from dmclock_tpu.obs.trace import (DecisionTrace, load_trace,
                                           validate_trace_file)

        p = tmp_path / "t.jsonl"
        with DecisionTrace(str(p)) as tr:
            tr.record(1, 0, 7, 0, 2, tag=(1, 2, 3), margin=100,
                      eligible_depth=5, gate=1)
            tr.record(2, 0, 8, 1, 1)
        stats = validate_trace_file(str(p))
        assert stats["rows"] == 2 and stats["v2_rows"] == 2
        assert stats["margin"] == {"count": 1, "max_ns": 100}
        rows = load_trace(str(p))
        assert rows[0]["margin"] == 100 and rows[1]["margin"] is None

    def test_v1_rows_load_with_nulls(self, tmp_path):
        p = tmp_path / "v1.jsonl"
        p.write_text(json.dumps(
            {"t": 1, "server": 0, "client": 3,
             "phase": "priority", "cost": 1, "tag": None}) + "\n")
        from dmclock_tpu.obs.trace import load_trace, validate_trace_file

        stats = validate_trace_file(str(p))
        assert stats["v1_rows"] == 1 and stats["v2_rows"] == 0
        rows = load_trace(str(p))
        assert rows[0]["margin"] is None
        assert rows[0]["eligible_depth"] is None

    def test_bad_provenance_type_rejected(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text(json.dumps(
            {"t": 1, "server": 0, "client": 3, "phase": "priority",
             "cost": 1, "tag": None, "margin": "fast",
             "eligible_depth": None, "gate": None}) + "\n")
        from dmclock_tpu.obs.trace import validate_trace_file

        with pytest.raises(ValueError, match="margin"):
            validate_trace_file(str(p))

    def test_summarize_device_cross_check(self, tmp_path):
        from dmclock_tpu.obs.trace import DecisionTrace, summarize

        p = tmp_path / "t.jsonl"
        with DecisionTrace(str(p)) as tr:
            tr.record(1, 0, 0, 0, 1)   # reservation
            tr.record(2, 0, 1, 1, 1)   # priority
        assert summarize(str(p), (1, 1))["per_phase"] == \
            {"reservation": 1, "priority": 1}
        with pytest.raises(ValueError, match="diverge"):
            summarize(str(p), (2, 0))


def _win(client=0, **kw):
    base = dict(seq=0, client=client, contract_epoch=1, e0=0, e1=2,
                ops=4, cost=4, resv_ops=0, tardy_ops=0, lb_ops=0,
                tardiness_sum_ns=0, backlog=0, window_s=0.2,
                rate=20.0, reservation=0.0, weight=1.0, limit=0.0,
                share=0.25, entitled_share=0.25, share_err=0.0,
                resv_deficit=0.0, resv_miss=False, limit_excess=0.0,
                tardiness_mean_ns=0.0)
    base.update(kw)
    return base


class TestExplain:
    def test_no_demand(self):
        res = explain_mod.attribute([_win(ops=0, rate=0.0, backlog=0,
                                          share=0.0)])
        assert res["cause"] == "no_demand"

    def test_limit_capped(self):
        res = explain_mod.attribute([_win(limit=20.0, rate=18.0,
                                          backlog=9, share=0.2,
                                          entitled_share=0.5)])
        assert res["cause"] == "limit_capped"
        assert res["scores"]["limit_capped"] >= 0.8

    def test_out_competed(self):
        res = explain_mod.attribute([_win(share=0.1,
                                          entitled_share=0.4,
                                          share_err=-0.75,
                                          backlog=12)])
        assert res["cause"] == "out_competed"

    def test_reservation_tardy(self):
        res = explain_mod.attribute([_win(reservation=50.0,
                                          resv_ops=10, tardy_ops=8,
                                          resv_deficit=30.0,
                                          resv_miss=True, backlog=3)])
        assert res["cause"] == "reservation_tardy"

    def test_conforming_null(self):
        res = explain_mod.attribute([_win()])
        assert res["cause"] == "conforming"

    def test_scenario_round(self, tmp_path):
        slo_log = str(tmp_path / "slo.jsonl")
        fl = str(tmp_path / "flight.jsonl")
        prov, plane, st, now = starvation_scenario(
            "prefix", "round", slo_log=slo_log, flight_dump=fl)
        res = explain_mod.explain(slo_log, 0, flight_path=fl)
        assert res["cause"] == "limit_capped"
        assert res["scores"]["limit_capped"] > 0.5
        # the competitor is NOT limit-capped
        res1 = explain_mod.explain(slo_log, 1)
        assert res1["cause"] != "limit_capped"
        # the plane saw the gate pressure live
        pd = obsprov.prov_dict(prov)
        assert pd["limit_gate_share"] > 0.25
        assert pd["gated_batches"] > 0

    @pytest.mark.slow
    @pytest.mark.parametrize("engine", ["chain", "calendar"])
    def test_scenario_engines(self, engine, tmp_path):
        slo_log = str(tmp_path / "slo.jsonl")
        starvation_scenario(engine, "round", slo_log=slo_log)
        res = explain_mod.explain(slo_log, 0)
        assert res["cause"] == "limit_capped"

    @pytest.mark.slow
    def test_scenario_stream_and_diff(self, tmp_path):
        a = str(tmp_path / "a.jsonl")
        b = str(tmp_path / "b.jsonl")
        starvation_scenario("prefix", "stream", slo_log=a)
        starvation_scenario("prefix", "round", slo_log=b)
        res = explain_mod.explain(a, 0)
        assert res["cause"] == "limit_capped"
        # identical runs under --diff: zero score deltas
        base = explain_mod.explain(b, 0)
        assert base["scores"] == res["scores"]


class TestSupervisorProv:
    def test_round_equals_stream(self):
        import dataclasses

        from dmclock_tpu.robust import supervisor as SV

        job = SV.EpochJob(engine="prefix", k=16, n=96, depth=6,
                          ring=12, epochs=4, m=2, seed=9,
                          arrival_lam=1.5, waves=3, ckpt_every=2,
                          with_prov=True)
        r = SV.run_job(job)
        s = SV.run_job(dataclasses.replace(job, engine_loop="stream"))
        assert r.digest == s.digest
        for f in ("prov_margin_hist", "prov_scal",
                  "prov_last_served"):
            assert np.array_equal(getattr(r, f), getattr(s, f)), f

    @pytest.mark.slow
    def test_crash_equivalence(self, tmp_path):
        from dmclock_tpu.robust import host_faults as HF
        from dmclock_tpu.robust import supervisor as SV

        job = SV.EpochJob(engine="calendar", calendar_impl="bucketed",
                          ladder_levels=2, k=4, n=96, depth=6,
                          ring=12, epochs=4, m=2, seed=9,
                          arrival_lam=1.5, waves=3, ckpt_every=2,
                          with_prov=True, flight_records=16)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(ref.decisions // 2,))
        got = SV.run_supervised(job, str(tmp_path), plan)
        SV.assert_crash_equivalent(got, ref)

    def test_prov_off_side_none(self):
        from dmclock_tpu.robust import supervisor as SV

        job = SV.EpochJob(engine="prefix", k=16, n=64, depth=4,
                          ring=8, epochs=2, m=2, seed=9,
                          arrival_lam=1.0, waves=2, ckpt_every=2)
        r = SV.run_job(job)
        assert r.prov_scal is None

    def test_prov_survives_with_slo(self):
        """Regression: a job running BOTH planes must report both --
        the slo branch of _build_result once rebound the kwargs dict
        and silently dropped the prov_* fields (which would make the
        crash-equivalence comparison vacuous for the combination)."""
        from dmclock_tpu.robust import supervisor as SV

        job = SV.EpochJob(engine="prefix", k=16, n=64, depth=4,
                          ring=8, epochs=4, m=2, seed=9,
                          arrival_lam=1.0, waves=2, ckpt_every=2,
                          with_prov=True, with_slo=True)
        r = SV.run_job(job)
        assert r.prov_scal is not None and r.slo is not None
        assert r.prov_margin_hist is not None
        assert r.prov_last_served is not None
        import dataclasses

        s = SV.run_job(dataclasses.replace(job, engine_loop="stream"))
        assert np.array_equal(r.prov_scal, s.prov_scal)

    def test_churn_plus_prov_composes(self):
        """The lifecycle boundary carries the provenance watermark
        through grow/compact/evict as a boundary ``extras`` rider
        (the lifted PR-12 rejection): the combination runs, reports
        the prov arrays, and stays loop-identical.  The deeper
        churn-storm + crash-equivalence gates live in
        tests/test_controller.py::TestChurnProvComposition."""
        import dataclasses

        from dmclock_tpu.lifecycle import make_spec
        from dmclock_tpu.robust import supervisor as SV

        spec = make_spec("flash_crowd", total_ids=8)
        job = SV.EpochJob(engine="prefix", k=8, churn=spec,
                          epochs=4, m=2, ckpt_every=2,
                          with_prov=True)
        r = SV.run_job(job)
        assert r.prov_scal is not None
        s = SV.run_job(dataclasses.replace(job, engine_loop="stream"))
        assert r.digest == s.digest
        assert np.array_equal(r.prov_scal, s.prov_scal)


class TestShardPressure:
    def test_pressure_vec_semantics(self):
        st = _mixed_state(depth=6)
        vec = np.asarray(jax.device_get(
            obsprov.pressure_vec(st, jnp.int64(S))))
        assert vec[obsprov.PRESS_BACKLOG] == \
            int(np.asarray(jax.device_get(st.depth)).sum())
        assert vec[obsprov.PRESS_ELIG] == vec[obsprov.PRESS_ELIG_PEAK]
        assert vec[obsprov.PRESS_WAIT_WM] >= 0

    def test_combine_axis_and_publish(self):
        mat = jnp.asarray([[4, 10, 4, 100], [2, 6, 2, 300]],
                          jnp.int64)
        red = np.asarray(jax.device_get(
            obsprov.pressure_combine_axis(mat)))
        assert red.tolist() == [6, 16, 4, 300]
        reg = MetricsRegistry()
        obsprov.publish_shard_pressure(reg, np.asarray(mat), red)
        names = {m.name for m in reg.metrics()}
        assert "dmclock_shard_pressure_eligible_live" in names
        assert "dmclock_shard_pressure_head_wait_max_ns" in names

    def test_cluster_step_pressure(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 (virtual) devices")
        from dmclock_tpu.core.timebase import rate_to_inv_ns
        from dmclock_tpu.parallel import cluster as CL

        S_, C = 4, 8
        mesh = CL.make_mesh(4)
        cl = CL.init_cluster(S_, C)
        cl = CL.install_clients(
            cl, jnp.asarray([rate_to_inv_ns(10.0)] * C, jnp.int64),
            jnp.asarray([rate_to_inv_ns(1.0 + (i % 3))
                         for i in range(C)], jnp.int64),
            jnp.zeros((C,), jnp.int64))
        cl = CL.shard_cluster(cl, mesh)
        arr = jnp.ones((S_, C), jnp.int32)
        out = CL.cluster_step(cl, arr, 1, mesh, decisions_per_step=4,
                              advance_ns=10 ** 8, with_pressure=True)
        cl2, decs, press, merged = out
        press = np.asarray(jax.device_get(press))
        merged = np.asarray(jax.device_get(merged))
        assert press.shape == (S_, obsprov.PRESS_FIELDS)
        assert merged[obsprov.PRESS_BACKLOG] == \
            press[:, obsprov.PRESS_BACKLOG].sum()
        assert merged[obsprov.PRESS_WAIT_WM] == \
            press[:, obsprov.PRESS_WAIT_WM].max()
        # decisions identical to the no-pressure step
        cl3, decs2 = CL.cluster_step(cl, arr, 1, mesh,
                                     decisions_per_step=4,
                                     advance_ns=10 ** 8)
        for a, b in zip(jax.tree.leaves(decs),
                        jax.tree.leaves(decs2)):
            assert np.array_equal(np.asarray(jax.device_get(a)),
                                  np.asarray(jax.device_get(b)))
