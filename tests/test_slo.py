"""SLO plane tests (docs/OBSERVABILITY.md "SLO plane").

Pins:
- the device window block leaves decisions bit-identical on/off and
  its counters match the cumulative ledger exactly (windowed totals ==
  cumulative totals over a contract-stable run), incl. non-unit costs
  and the tag32 dead-batch gate;
- host SloPlane contract-epoch attribution (register/update/evict
  bumps, closed windows report against their OWN version), the
  checkpoint round-trip, and the conformance math;
- burn-rate alerting fires exactly once per episode and re-arms on a
  clean fast window (the seeded resv-starvation scenario);
- supervisor integration: round == stream incl. the slo artifacts,
  crash equivalence (SIGKILL + resume bit-identical), churn
  attribution across a live QoS update (no smearing);
- the MetricsHTTPServer.mount dispatch edges the SLO/admin APIs ride
  on (unknown prefix, wrong method, duplicate prefix, handler
  exception), and the pull queue's host window mirror.
"""

import dataclasses
import json
import tempfile
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.engine import TpuPullPriorityQueue
from dmclock_tpu.engine.fastpath import (scan_chain_epoch,
                                         scan_prefix_epoch)
from dmclock_tpu.obs import histograms as obshist
from dmclock_tpu.obs import slo as obsslo
from dmclock_tpu.obs.alerts import RULES, SloEvaluator, mount_slo_api
from dmclock_tpu.obs.registry import MetricsHTTPServer, MetricsRegistry
from dmclock_tpu.obs.slo import ClosedWindow, SloPlane
from engine_helpers import build_state

S = 10 ** 9


def _digest(ep, fields):
    import hashlib

    h = hashlib.sha256()
    for f in fields:
        h.update(np.asarray(jax.device_get(getattr(ep, f))).tobytes())
    return h.hexdigest()


# ----------------------------------------------------------------------
# device window block
# ----------------------------------------------------------------------

class TestWindowBlock:
    def _state(self, n=48, depth=5):
        infos = {c: ClientInfo(10.0, 1.0 + c % 3, 0.0)
                 for c in range(n)}
        adds = [(c, 1 * S, 1 + (c + d) % 4, 1, 1)
                for d in range(depth) for c in range(n)]
        return build_state(infos, adds, capacity=n, ring=16)

    @pytest.mark.parametrize("tag_width", [64, 32])
    def test_prefix_digest_and_cost_exact(self, tag_width):
        st = self._state()
        now = jnp.int64(3 * S)
        fn = lambda s, t, **kw: scan_prefix_epoch(
            s, t, m=3, k=32, anticipation_ns=0,
            tag_width=tag_width, **kw)
        off = jax.jit(fn)(st, now)
        on = jax.jit(lambda s, t: fn(
            s, t, slo=obsslo.window_zero(48),
            ledger=obshist.ledger_zero(48)))(st, now)
        flds = ("count", "slot", "phase", "cost", "lb")
        assert _digest(off, flds) == _digest(on, flds)
        w = np.asarray(jax.device_get(on.slo))
        led = np.asarray(jax.device_get(on.ledger))
        total = int(jax.device_get(on.count).sum())
        assert w[:, obsslo.W_OPS].sum() == total
        assert np.array_equal(w[:, obsslo.W_RESV_OPS],
                              led[:, obshist.LED_RESV_OPS])
        assert np.array_equal(w[:, obsslo.W_TARD_SUM],
                              led[:, obshist.LED_TARD_SUM])
        # delivered cost is EXACT per client: sum the committed
        # decision costs by slot from the epoch's own output
        slots = np.asarray(jax.device_get(on.slot)).ravel()
        costs = np.asarray(jax.device_get(on.cost)).ravel()
        expect = np.zeros(48, dtype=np.int64)
        ok = slots >= 0
        np.add.at(expect, slots[ok], costs[ok])
        assert np.array_equal(w[:, obsslo.W_COST], expect)

    def test_chain_cost_exact(self):
        st = self._state()
        now = jnp.int64(3 * S)
        ep = jax.jit(lambda s, t: scan_chain_epoch(
            s, t, m=2, k=16, chain_depth=3, anticipation_ns=0,
            slo=obsslo.window_zero(48)))(st, now)
        w = np.asarray(jax.device_get(ep.slo))
        # per-client ops from the unit lengths must match W_OPS
        slots = np.asarray(jax.device_get(ep.slot)).ravel()
        lens = np.asarray(jax.device_get(ep.length)).ravel()
        expect = np.zeros(48, dtype=np.int64)
        ok = slots >= 0
        np.add.at(expect, slots[ok], lens[ok])
        assert np.array_equal(w[:, obsslo.W_OPS], expect)
        assert w[:, obsslo.W_COST].sum() > 0

    def test_calendar_cost_exact(self):
        """The calendar engine's delivered-cost threading (dense pass
        cost carry -> ladder accumulation -> served_cost masking) must
        match the decision stream exactly: serves pop each client's
        ring in FIFO order, so the expected cost is the sum of the
        first served[c] queued costs."""
        from dmclock_tpu.engine.fastpath import scan_calendar_epoch

        n, depth = 24, 6
        st = self._state(n=n, depth=depth)
        costs = {c: [1 + (c + d) % 4 for d in range(depth)]
                 for c in range(n)}
        now = jnp.int64(3 * S)
        for impl, lv in (("minstop", 1), ("bucketed", 3)):
            ep = jax.jit(lambda s, t, impl=impl, lv=lv:
                         scan_calendar_epoch(
                             s, t, m=2, steps=4, calendar_impl=impl,
                             ladder_levels=lv,
                             slo=obsslo.window_zero(n)))(st, now)
            w = np.asarray(jax.device_get(ep.slo))
            served = np.asarray(jax.device_get(ep.served))
            expect = np.asarray(
                [sum(costs[c][:served[c]]) for c in range(n)],
                dtype=np.int64)
            assert np.array_equal(w[:, obsslo.W_COST], expect), impl
            assert np.array_equal(w[:, obsslo.W_OPS], served), impl

    def test_combine_and_mask(self):
        a = np.zeros((4, obsslo.W_FIELDS), dtype=np.int64)
        b = a.copy()
        a[:, obsslo.W_OPS] = 2
        a[:, obsslo.W_CEPOCH] = 3
        b[:, obsslo.W_OPS] = 5
        b[:, obsslo.W_CEPOCH] = 1
        m = np.asarray(obsslo.window_combine(jnp.asarray(a),
                                             jnp.asarray(b)))
        assert (m[:, obsslo.W_OPS] == 7).all()      # counters add
        assert (m[:, obsslo.W_CEPOCH] == 3).all()   # cepoch maxes
        # a delta fold gated dead contributes nothing
        f = np.asarray(obsslo.window_fold(jnp.asarray(a),
                                          jnp.asarray(b), False))
        assert np.array_equal(f, a)


# ----------------------------------------------------------------------
# host plane: attribution, conformance, round-trip
# ----------------------------------------------------------------------

class TestSloPlane:
    def test_contract_epoch_bumps(self):
        p = SloPlane(4, dt_epoch_ns=10 ** 8)
        assert p.register(1, 10.0, 2.0, 0.0) == 1
        assert p.update(1, 10.0, 4.0, 0.0) == 2
        p.evict(1)
        assert 1 not in p.contracts
        # re-registration continues the monotone counter
        assert p.register(1, 5.0, 1.0, 0.0) == 3
        assert p.contract_of(1, 2) == (10.0, 4.0, 0.0)
        assert p.contract_of(1, 3) == (5.0, 1.0, 0.0)

    def test_roll_attribution_and_fresh_block(self):
        p = SloPlane(2, dt_epoch_ns=10 ** 8)
        p.register(0, 10.0, 1.0, 0.0)
        p.register(1, 0.0, 3.0, 0.0)
        blk = p.stamp(obsslo.window_zero(2))
        blk = blk.at[0, obsslo.W_OPS].set(7)
        blk = blk.at[0, obsslo.W_COST].set(7)
        blk = blk.at[1, obsslo.W_OPS].set(3)
        blk = blk.at[1, obsslo.W_COST].set(3)
        fresh, closed = p.roll(blk, 0, 2)
        assert [w.cid for w in closed] == [0, 1]
        assert all(w.cepoch == 1 for w in closed)
        assert closed[0].ops == 7
        f = np.asarray(jax.device_get(fresh))
        assert f[:, :obsslo.W_CEPOCH].sum() == 0
        assert (f[:, obsslo.W_CEPOCH] == 1).all()
        rows = p.conformance_rows(closed)
        # shares: 0.7 vs 0.3 delivered; entitlements 0.25 vs 0.75
        assert rows[0]["share"] == pytest.approx(0.7)
        assert rows[0]["entitled_share"] == pytest.approx(0.25)
        assert rows[1]["entitled_share"] == pytest.approx(0.75)
        # client 0 delivered 35/s against a 10/s floor: no miss
        assert not rows[0]["resv_miss"]

    def test_starved_window_is_a_miss(self):
        p = SloPlane(1, dt_epoch_ns=10 ** 8)
        p.register(0, 100.0, 1.0, 0.0)
        blk = p.stamp(obsslo.window_zero(1))
        _, closed = p.roll(blk, 0, 2,
                           depth=np.asarray([5]))   # backlogged
        rows = p.conformance_rows(closed)
        assert rows[0]["ops"] == 0 and rows[0]["resv_miss"]
        # same window with no backlog: idle, not starved
        p2 = SloPlane(1, dt_epoch_ns=10 ** 8)
        p2.register(0, 100.0, 1.0, 0.0)
        _, closed2 = p2.roll(p2.stamp(obsslo.window_zero(1)), 0, 2)
        assert not p2.conformance_rows(closed2)[0]["resv_miss"]

    def test_encode_load_roundtrip(self):
        p = SloPlane(3, dt_epoch_ns=10 ** 8, ring_depth=4)
        for c in range(3):
            p.register(c, 1.0, 1.0 + c, 0.0)
        p.update(2, 1.0, 9.0, 0.0)
        blk = p.stamp(obsslo.window_zero(3))
        blk = blk.at[:, obsslo.W_OPS].set(4)
        _, _ = p.roll(blk, 0, 2)
        q = SloPlane.load(p.encode(), capacity=3, dt_epoch_ns=10 ** 8)
        assert q.cepoch == p.cepoch
        assert q.contracts == p.contracts
        assert q.contract_log == p.contract_log
        assert [w.row() for w in q.ring_rows()] == \
            [w.row() for w in p.ring_rows()]
        assert q.window_seq == p.window_seq

    def test_export_jsonl_and_report(self, tmp_path, capsys):
        p = SloPlane(2, dt_epoch_ns=10 ** 8)
        p.register(0, 10.0, 1.0, 0.0)
        p.register(1, 0.0, 1.0, 0.0)
        blk = p.stamp(obsslo.window_zero(2))
        blk = blk.at[:, obsslo.W_OPS].set(5)
        blk = blk.at[:, obsslo.W_COST].set(5)
        _, closed = p.roll(blk, 0, 2)
        path = str(tmp_path / "w.jsonl")
        assert p.export_jsonl(path, closed) == 2
        rows = obsslo.load_windows_jsonl(path)
        assert len(rows) == 2 and rows[0]["client"] == 0
        # the offline tool reproduces a table (+ --diff) from it
        import importlib.util
        import pathlib
        spec = importlib.util.spec_from_file_location(
            "slo_report", pathlib.Path(__file__).parent.parent
            / "scripts" / "slo_report.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([path]) == 0
        out = capsys.readouterr().out
        assert "SLO windowed conformance" in out
        assert "totals:" in out
        assert mod.main([path, "--diff", path]) == 0
        out = capsys.readouterr().out
        assert "diff vs" in out and "(+0)" in out


# ----------------------------------------------------------------------
# burn-rate alerting: exactly once per episode
# ----------------------------------------------------------------------

def _mk_windows(plane, seq, e0, rows):
    """Append synthetic closed windows (cid, ops, cost, resv, tardy,
    backlog) for one roll into the plane's ring."""
    out = []
    for cid, ops, cost, resv, tardy, backlog in rows:
        w = ClosedWindow(seq=seq, cid=cid,
                         cepoch=plane.cepoch.get(cid, 1),
                         e0=e0, e1=e0 + 2, ops=ops, cost=cost,
                         resv_ops=resv, tardy_ops=tardy,
                         tard_sum_ns=tardy * 10 ** 6, lb_ops=0,
                         backlog=backlog)
        out.append(w)
        from collections import deque
        plane.rings.setdefault(cid, deque(maxlen=plane.ring_depth)) \
            .append(w)
    plane.window_seq = seq + 1
    plane.windows_closed += len(out)
    return out


class TestBurnRate:
    def _plane(self):
        p = SloPlane(2, dt_epoch_ns=10 ** 9)   # 2s windows
        p.register(0, 50.0, 1.0, 0.0)   # the starved victim
        p.register(1, 0.0, 1.0, 0.0)
        return p

    def test_resv_starvation_fires_once_per_episode(self):
        p = self._plane()
        ev = SloEvaluator(p, slow_windows=2, log=lambda _l: None)
        starved = (0, 0, 0, 0, 0, 9)        # backlogged, undelivered
        healthy0 = (0, 120, 120, 60, 0, 0)  # floor met
        fired = []
        for i, row in enumerate([starved, starved, starved,
                                 healthy0, starved, starved]):
            closed = _mk_windows(p, i, i * 2,
                                 [row, (1, 30, 30, 0, 0, 0)])
            fired += [w for w in ev.observe_roll(closed)
                      if w["kind"] == "slo_resv_miss"]
        # episode 1: rolls 0-2 violate -> ONE alert (at roll 1, when
        # the slow horizon confirms); roll 3 is clean and re-arms;
        # episode 2: rolls 4-5 -> ONE more
        assert len(fired) == 2, fired
        assert ev.fired_counts["resv_miss"] == 2

    def test_share_skew_and_limit_rules(self):
        p = SloPlane(2, dt_epoch_ns=10 ** 9)
        p.register(0, 0.0, 1.0, 10.0)
        p.register(1, 0.0, 1.0, 0.0)
        ev = SloEvaluator(p, slow_windows=1, share_tol=0.5,
                          log=lambda _l: None)
        # equal weights, 90/10 delivered split -> skew both sides of
        # tolerance; client 0 also delivers 45/s over a 10/s limit
        closed = _mk_windows(p, 0, 0, [(0, 90, 90, 0, 0, 1),
                                       (1, 10, 10, 0, 0, 1)])
        kinds = sorted(w["kind"] for w in ev.observe_roll(closed))
        assert "slo_share_skew" in kinds
        assert "slo_limit_break" in kinds
        assert ev.worst_share_err == pytest.approx(0.8)

    def test_eviction_ends_the_episode(self):
        """A re-registered client's fresh tenancy must fire its own
        episode: eviction ends the old one (the once-per-EPISODE
        contract is per tenancy, not per client id forever)."""
        p = self._plane()
        ev = SloEvaluator(p, slow_windows=1, log=lambda _l: None)
        starved = (0, 0, 0, 0, 0, 9)
        def misses(warns):
            return [w for w in warns
                    if w["kind"] == "slo_resv_miss"]

        closed = _mk_windows(p, 0, 0, [starved])
        assert len(misses(ev.observe_roll(closed))) == 1  # episode 1
        closed = _mk_windows(p, 1, 2, [starved])
        assert misses(ev.observe_roll(closed)) == []      # damped
        p.evict(0)                                   # tenancy ends
        p.register(0, 50.0, 1.0, 0.0)                # fresh contract
        closed = _mk_windows(p, 2, 4, [starved])
        fired = misses(ev.observe_roll(closed))
        assert len(fired) == 1, fired                # episode 2 fires

    def test_evaluator_checkpoint_roundtrip(self):
        p = self._plane()
        ev = SloEvaluator(p, slow_windows=2, log=lambda _l: None)
        for i in range(3):
            closed = _mk_windows(p, i, i * 2, [(0, 0, 0, 0, 0, 9)])
            ev.observe_roll(closed)
        enc = {**ev.encode(), **p.encode()}
        p2 = SloPlane.load(enc, capacity=2, dt_epoch_ns=10 ** 9)
        ev2 = SloEvaluator(p2, slow_windows=2, log=lambda _l: None)
        ev2.load(enc)
        assert ev2.summary() == ev.summary()
        # the restored evaluator is mid-episode: more violating
        # windows must NOT re-fire
        closed = _mk_windows(p2, 3, 6, [(0, 0, 0, 0, 0, 9)])
        assert ev2.observe_roll(closed) == []


# ----------------------------------------------------------------------
# supervisor integration
# ----------------------------------------------------------------------

def _base_job(**over):
    from dmclock_tpu.robust.supervisor import EpochJob

    kw = dict(engine="prefix", k=16, n=48, depth=6, ring=12, epochs=6,
              m=2, seed=9, arrival_lam=1.5, waves=3, ckpt_every=2,
              with_slo=True, with_ledger=True)
    kw.update(over)
    return EpochJob(**kw)


class TestSupervisorSlo:
    def test_round_stream_parity_and_log(self, tmp_path):
        from dmclock_tpu.robust import supervisor as SV

        log = str(tmp_path / "run.slo.jsonl")
        job = _base_job(slo_log=log)
        r = SV.run_job(job)
        s = SV.run_job(dataclasses.replace(job, slo_log=None,
                                           engine_loop="stream"))
        assert s.digest == r.digest
        assert s.slo == r.slo
        assert np.array_equal(np.asarray(s.slo_ring),
                              np.asarray(r.slo_ring))
        assert np.array_equal(np.asarray(s.slo_window),
                              np.asarray(r.slo_window))
        rows = obsslo.load_windows_jsonl(log)
        assert len(rows) == r.slo["windows_closed"]

    @pytest.mark.slow
    def test_crash_equivalence(self):
        from dmclock_tpu.robust import host_faults as HF
        from dmclock_tpu.robust import supervisor as SV

        job = _base_job(engine="calendar", k=4,
                        calendar_impl="bucketed", ladder_levels=2)
        ref = SV.run_job(job)
        with tempfile.TemporaryDirectory() as wd:
            r0 = SV.run_supervised(job, wd, HF.zero_host_plan())
        SV.assert_crash_equivalent(r0, ref)
        kill_at = ref.decisions * 2 // 3
        with tempfile.TemporaryDirectory() as wd:
            r1 = SV.run_supervised(
                job, wd, HF.HostFaultPlan(
                    kill_at_decisions=(kill_at,)))
        assert r1.restarts == 1
        SV.assert_crash_equivalent(r1, ref)

    def test_churn_update_lands_in_fresh_epoch(self):
        from dmclock_tpu.lifecycle import make_spec
        from dmclock_tpu.robust import supervisor as SV

        spec = make_spec("limit_thrash", total_ids=12, base_lam=1.5,
                         capacity0=12)
        job = _base_job(engine="prefix", k=8, churn=spec, epochs=8,
                        ring=16, waves=4, seed=11, n=12)
        r = SV.run_job(job)
        ring = np.asarray(r.slo_ring)
        victim = 11        # limit_thrash victims: top quarter of ids
        rows = ring[ring[:, 1] == victim]
        assert len(rows) >= 3
        # every window reports exactly one version, versions ascend
        # across the per-boundary updates -- no smearing
        epochs = [int(x) for x in rows[:, 2]]
        assert epochs == sorted(epochs) and len(set(epochs)) > 1
        # crash equivalence under churn + slo
        from dmclock_tpu.robust import host_faults as HF
        with tempfile.TemporaryDirectory() as wd:
            r1 = SV.run_supervised(
                job, wd, HF.HostFaultPlan(
                    kill_at_decisions=(r.decisions * 2 // 3,)))
        SV.assert_crash_equivalent(r1, r)

    def test_conformance_http_endpoints(self):
        """GET /slo + GET /clients/{id}/conformance live on the
        supervised churn run's own scrape endpoint."""
        from dmclock_tpu.lifecycle import make_spec
        from dmclock_tpu.robust import supervisor as SV

        spec = make_spec("flash_crowd", total_ids=8, base_lam=1.5,
                         capacity0=8, crowd_at=2, crowd_len=4)
        job = _base_job(engine="prefix", k=8, churn=spec, epochs=6,
                        ring=16, waves=4, seed=11, n=8,
                        metrics_port=0)
        # run via the bare loop but with a scrape port: the on_bind
        # mount serves /slo and /clients/{id}/conformance.  Probe
        # from a sibling thread mid-run via the plane's own port is
        # racy; instead re-create the mount standalone.
        r = SV.run_job(job)
        assert r.slo["windows_closed"] > 0

        plane = SloPlane(4, dt_epoch_ns=10 ** 8)
        plane.register(3, 10.0, 1.0, 0.0)
        ev = SloEvaluator(plane, log=lambda _l: None)
        blk, closed = plane.roll(plane.stamp(obsslo.window_zero(4)),
                                 0, 2)
        ev.observe_roll(closed)
        from dmclock_tpu.lifecycle.api import mount_admin_api
        from dmclock_tpu.lifecycle.plane import LifecyclePlane
        lp = LifecyclePlane(spec)
        lp.attach_slo(plane)
        with MetricsHTTPServer(MetricsRegistry(), port=0) as srv:
            mount_slo_api(srv, ev)
            mount_admin_api(srv, lp, slo=plane)
            base = f"http://{srv.host}:{srv.port}"
            with urllib.request.urlopen(base + "/slo",
                                        timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["windows_closed"] == len(closed)
            with urllib.request.urlopen(
                    base + "/clients/3/conformance",
                    timeout=10) as resp:
                view = json.loads(resp.read())
            assert view["contract_epoch"] == 1
            try:
                urllib.request.urlopen(base + "/clients/7/conformance",
                                       timeout=10)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404


# ----------------------------------------------------------------------
# MetricsHTTPServer.mount dispatch edges (satellite)
# ----------------------------------------------------------------------

class TestMountEdges:
    def _srv(self):
        return MetricsHTTPServer(MetricsRegistry(), port=0)

    def _req(self, srv, method, path, body=b""):
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}{path}",
            data=body or None, method=method)
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def test_unknown_prefix_404(self):
        with self._srv() as srv:
            srv.mount("/api", lambda m, p, b: (200, "text/plain",
                                               b"ok"))
            assert self._req(srv, "GET", "/nope")[0] == 404
            assert self._req(srv, "POST", "/other", b"{}")[0] == 404
            # prefix match must be path-segment exact: /apiX is NOT
            # under /api
            assert self._req(srv, "GET", "/apix")[0] == 404
            assert self._req(srv, "GET", "/api")[0] == 200
            assert self._req(srv, "GET", "/api/sub")[0] == 200

    def test_wrong_method_on_mounted_prefix_405(self):
        def handler(method, path, body):
            if method != "GET":
                return (405, "application/json",
                        json.dumps({"error": "nope"}).encode())
            return (200, "application/json", b"{}")

        with self._srv() as srv:
            srv.mount("/ro", handler)
            assert self._req(srv, "GET", "/ro")[0] == 200
            status, body = self._req(srv, "POST", "/ro", b"{}")
            assert status == 405, body
            assert self._req(srv, "PUT", "/ro", b"{}")[0] == 405
            assert self._req(srv, "DELETE", "/ro")[0] == 405

    def test_duplicate_prefix_mount_rejected(self):
        with self._srv() as srv:
            srv.mount("/x", lambda m, p, b: (200, "text/plain", b"1"))
            with pytest.raises(ValueError, match="already mounted"):
                srv.mount("/x", lambda m, p, b: (200, "text/plain",
                                                 b"2"))
            # the original handler still serves
            assert self._req(srv, "GET", "/x")[1] == b"1"

    def test_handler_exception_is_500_not_crash(self):
        def boom(method, path, body):
            raise RuntimeError("kaboom")

        with self._srv() as srv:
            srv.mount("/boom", boom)
            status, body = self._req(srv, "GET", "/boom")
            assert status == 500
            assert b"kaboom" in body
            # the scrape endpoint survives the handler exception
            status, body = self._req(srv, "GET", "/metrics")
            assert status == 200
            status, body = self._req(srv, "GET", "/healthz")
            assert status == 200 and b"ok" in body


# ----------------------------------------------------------------------
# pull-queue host window mirror
# ----------------------------------------------------------------------

class TestQueueMirror:
    def test_mirror_counts_and_roll(self):
        infos = {c: ClientInfo(0.0, 1.0, 0.0) for c in range(3)}
        q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                                 ring_capacity=8)
        for i in range(4):
            for c in range(3):
                q.add_request(("r", c, i), c, ReqParams(1, 1),
                              time_ns=i * S, cost=2)
        pulls = 0
        while True:
            r = q.pull_request(now_ns=10 * S)
            if r.type.name != "RETURNING":
                break
            pulls += 1
        assert pulls == 12
        rows = q.slo_window_rows()
        assert sum(int(r[obsslo.W_OPS]) for r in rows.values()) == 12
        assert sum(int(r[obsslo.W_COST]) for r in rows.values()) == 24
        assert all(int(r[obsslo.W_CEPOCH]) == 1
                   for r in rows.values())
        closed = q.roll_slo_windows()
        assert sum(r["ops"] for r in closed) == 12
        assert q.roll_slo_windows() == []     # counters zeroed
        # a live ClientInfo update bumps the contract epoch
        infos[1] = ClientInfo(0.0, 5.0, 0.0)
        q.update_client_info(1)
        assert int(q.slo_window_rows()[1][obsslo.W_CEPOCH]) == 2
        # an UNCHANGED refresh sweep must not fragment the version
        # series (the reference's update_client_infos() pattern)
        q.update_client_infos()
        rows = q.slo_window_rows()
        assert int(rows[1][obsslo.W_CEPOCH]) == 2
        assert int(rows[0][obsslo.W_CEPOCH]) == 1


# ----------------------------------------------------------------------
# sim cross-check: window mirror == ledger through a full sim
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_sim_slo_window_check():
    from dmclock_tpu.sim import ClientGroup, ServerGroup, SimConfig
    from dmclock_tpu.sim.dmc_sim import run_sim

    cfg = SimConfig(
        client_groups=1, server_groups=1,
        cli_group=[ClientGroup(client_count=3, client_total_ops=30,
                               client_wait_s=0, client_iops_goal=200,
                               client_outstanding_ops=16,
                               client_reservation=0.0,
                               client_limit=0.0, client_weight=1.0,
                               client_server_select_range=1)],
        srv_group=[ServerGroup(server_count=1, server_iops=160,
                               server_threads=1)])
    sim = run_sim(cfg, model="dmclock-tpu", seed=7)
    chk = sim.report().slo_window_check()
    assert chk is not None and chk["clients"] == 3
    assert chk["windows_ops"] == 90
    assert chk["mismatches"] == []
