"""Tests for scripts/trace_diff.py (the first-divergence decision-
trace triage tool from PR-2, previously untested): identical streams,
a single mid-stream divergence, truncated files, malformed input, and
the --ignore/--limit knobs."""

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "trace_diff", REPO / "scripts" / "trace_diff.py")
trace_diff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(trace_diff)


def row(t, client, phase="priority", cost=1, server=0, tag=None):
    return {"t": t, "server": server, "client": client,
            "phase": phase, "cost": cost, "tag": tag}


def write(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    return str(path)


def stream(n, start=0):
    return [row(10 ** 9 + i * 10 ** 6, i % 3) for i in range(start, n)]


def test_identical_traces(tmp_path, capsys):
    a = write(tmp_path / "a.jsonl", stream(50))
    b = write(tmp_path / "b.jsonl", stream(50))
    assert trace_diff.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "identical (50 decisions)" in out


def test_single_divergence_reports_field_and_both_rows(tmp_path,
                                                       capsys):
    rows_a = stream(50)
    rows_b = stream(50)
    rows_b[17] = dict(rows_b[17], client=99, cost=7)
    a = write(tmp_path / "a.jsonl", rows_a)
    b = write(tmp_path / "b.jsonl", rows_b)
    assert trace_diff.main([a, b]) == 1
    out = capsys.readouterr().out
    assert "divergence at decision 17" in out
    assert "client" in out and "cost" in out
    assert "client=99" in out         # both rows printed
    assert out.count(a) == 1 and out.count(b) == 1


def test_truncated_stream_is_divergence(tmp_path, capsys):
    a = write(tmp_path / "a.jsonl", stream(30))
    b = write(tmp_path / "b.jsonl", stream(40))
    assert trace_diff.main([a, b]) == 1
    out = capsys.readouterr().out
    assert "ended after 30 decisions" in out
    assert "<stream ended>" in out


def test_empty_vs_nonempty(tmp_path, capsys):
    a = write(tmp_path / "a.jsonl", [])
    b = write(tmp_path / "b.jsonl", stream(3))
    assert trace_diff.main([a, b]) == 1
    assert "ended after 0 decisions" in capsys.readouterr().out


def test_null_tag_vs_triple_not_divergent(tmp_path, capsys):
    # backends that materialize no host-side tags emit null; a
    # null-vs-triple pair is NOT a divergence (schema contract)
    rows_a = [row(1, 0, tag=[5, 6, 7]), row(2, 1, tag=[8, 9, 10])]
    rows_b = [row(1, 0, tag=None), row(2, 1, tag=None)]
    a = write(tmp_path / "a.jsonl", rows_a)
    b = write(tmp_path / "b.jsonl", rows_b)
    assert trace_diff.main([a, b]) == 0
    # but two PRESENT, differing triples are
    rows_b2 = [row(1, 0, tag=[5, 6, 7]), row(2, 1, tag=[8, 9, 999])]
    b2 = write(tmp_path / "b2.jsonl", rows_b2)
    assert trace_diff.main([a, b2]) == 1
    assert "tag" in capsys.readouterr().out


def test_malformed_input_exits_2(tmp_path, capsys):
    a = write(tmp_path / "a.jsonl", stream(2))
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"t": 1, "server": 0}\nnot json\n')
    assert trace_diff.main([a, str(bad)]) == 2
    assert "trace_diff:" in capsys.readouterr().err


def test_missing_file_exits_2(tmp_path, capsys):
    a = write(tmp_path / "a.jsonl", stream(2))
    assert trace_diff.main([a, str(tmp_path / "nope.jsonl")]) == 2


def test_ignore_and_limit_flags(tmp_path, capsys):
    # server differs everywhere (the cross-backend default ignores
    # it); --ignore '' makes it count
    rows_a = stream(10)
    rows_b = [dict(r, server=1) for r in rows_a]
    a = write(tmp_path / "a.jsonl", rows_a)
    b = write(tmp_path / "b.jsonl", rows_b)
    assert trace_diff.main([a, b]) == 0
    assert trace_diff.main([a, b, "--ignore", ""]) == 1
    capsys.readouterr()
    # --limit stops before a late divergence
    rows_b2 = stream(10)
    rows_b2[8] = dict(rows_b2[8], cost=5)
    b2 = write(tmp_path / "b2.jsonl", rows_b2)
    assert trace_diff.main([a, b2, "--limit", "5"]) == 0
    assert "--limit reached" in capsys.readouterr().out
    assert trace_diff.main([a, b2]) == 1
