"""Speculative decision buffer: bit-exact vs the launch-per-pull queue.

``TpuPullPriorityQueue(speculative_batch=k)`` prefetches a batch of
decisions with a validity horizon and serves later pulls from it
launch-free; adds invalidate unless provably non-interfering.  These
tests drive random interleavings of adds and pulls (monotone now) on a
buffered queue and an unbuffered twin and require the full decision
stream -- client, phase, cost, FUTURE times -- to match, including
around idle-marking, client creation mid-run, head installs, and
update_client_info.
"""

import random

import pytest

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue

S = NS_PER_SEC


def pull_to_tuple(pr):
    if pr.is_retn():
        return ("RETN", pr.client, pr.request, pr.phase.name, pr.cost)
    if pr.is_future():
        return ("FUTURE", pr.when_ready)
    return ("NONE",)


def run_interleaving(seed, spec, n_clients=8, steps=300,
                     infos=None):
    rng = random.Random(seed)
    if infos is None:
        infos = {}
        for c in range(n_clients):
            kind = rng.randrange(4)
            if kind == 0:
                infos[c] = ClientInfo(rng.uniform(0.5, 3), 0, 0)
            elif kind == 1:
                infos[c] = ClientInfo(0, rng.uniform(0.5, 3), 0)
            elif kind == 2:
                infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                      rng.uniform(0.5, 3),
                                      rng.uniform(2, 6))
            else:
                infos[c] = ClientInfo(0, 2, 0)
    q = TpuPullPriorityQueue(lambda c: infos[c], capacity=16,
                             ring_capacity=16,
                             speculative_batch=spec)
    out = []
    t = S
    seq = 0
    for _ in range(steps):
        t += rng.randint(0, S // 3)
        op = rng.random()
        if op < 0.45:
            c = rng.randrange(n_clients)
            delta = rng.randint(1, 5)
            q.add_request(("r", c, seq), c,
                          ReqParams(delta, rng.randint(1, delta)),
                          time_ns=t, cost=rng.randint(1, 3))
            seq += 1
        elif op < 0.95:
            out.append(pull_to_tuple(q.pull_request(t)))
        else:
            q.update_client_info(rng.randrange(n_clients))
    # drain what's left at a far-future now
    t += 10_000 * S
    for _ in range(n_clients * 40):
        pr = q.pull_request(t)
        out.append(pull_to_tuple(pr))
        if not pr.is_retn():
            break
    counters = (q.reserv_sched_count, q.prop_sched_count,
                q.limit_break_sched_count)
    counts = (q.client_count(), q.request_count(), q.empty())
    return out, counters, counts


# seeds 41/44 draw the deep-backlog interleavings (~20-40s each on
# the CPU box): slow-marked for the tier-1 wall budget, still run by
# scripts/run_tests.sh; the other six seeds keep the quick coverage
@pytest.mark.parametrize("seed", [
    pytest.param(41, marks=pytest.mark.slow), 42, 43,
    pytest.param(44, marks=pytest.mark.slow), 45, 46, 47, 48])
def test_spec_buffer_stream_matches_unbuffered(seed):
    a = run_interleaving(seed, spec=0)
    b = run_interleaving(seed, spec=8)
    assert a == b, f"seed {seed}: buffered stream diverges"


@pytest.mark.slow
def test_spec_buffer_heavy_single_client():
    """Single deep client: every buffered serve retags the same client,
    so the one-client interleavings stress consumed-prefix settling."""
    infos = {0: ClientInfo(0, 1, 0), 1: ClientInfo(0, 3, 0)}
    runs = []
    for spec in (0, 8):
        q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                                 ring_capacity=32,
                                 speculative_batch=spec)
        out = []
        t = S
        for i in range(20):
            q.add_request(("r", 0, i), 0, ReqParams(1, 1),
                          time_ns=t, cost=1)
        for i in range(30):
            t += S // 10
            if i == 10:
                # mid-stream add for the OTHER client: new head install
                # must invalidate the buffer
                q.add_request(("r", 1, 0), 1, ReqParams(1, 1),
                              time_ns=t, cost=1)
            out.append(pull_to_tuple(q.pull_request(t)))
        runs.append(out)
    assert runs[0] == runs[1]


@pytest.mark.slow
def test_spec_buffer_idle_reactivation():
    """do_clean idle-marks a client; its next add reactivates with a
    prop_delta shift -- the buffer must not serve stale decisions."""
    infos = {c: ClientInfo(0, 1 + c % 2, 0) for c in range(4)}
    runs = []
    for spec in (0, 8):
        clock = [0.0]
        q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                                 ring_capacity=16,
                                 speculative_batch=spec,
                                 idle_age_s=10.0, erase_age_s=1e6,
                                 monotonic_clock=lambda: clock[0])
        out = []
        t = S
        for i in range(6):
            for c in range(4):
                q.add_request(("r", c, i), c, ReqParams(1, 1),
                              time_ns=t, cost=1)
        # drain client tags apart, then idle-mark via aged mark points
        for _ in range(12):
            t += S // 5
            out.append(pull_to_tuple(q.pull_request(t)))
        q.do_clean()
        clock[0] += 20.0
        q.do_clean()          # marks everything idle
        t += 100 * S
        q.add_request(("r", 0, 99), 0, ReqParams(1, 1), time_ns=t,
                      cost=1)
        for _ in range(16):
            t += S // 5
            pr = q.pull_request(t)
            out.append(pull_to_tuple(pr))
        runs.append(out)
    assert runs[0] == runs[1]


def test_spec_buffer_mixed_batch_settle_state_parity():
    """A MIXED prefetch batch (RETURNING prefix then FUTURE steps) that
    drains fully: settle() must leave the device state BIT-IDENTICAL to
    the launch-per-pull twin's.  The trailing never-handed-out FUTURE
    steps promote head_ready for the limited zero-weight client Z
    (limit <= t0, proportion pinned MAX_TAG so it is never served);
    the twin's pulls are all reservation-phase serves, which skip the
    promote loop entirely -- so keeping the post-batch state would leak
    a promotion no handed-out decision performed."""
    from engine_helpers import assert_states_equal

    infos = {
        "Z": ClientInfo(0.1, 0, 10),   # resv-only, limited
        "A": ClientInfo(1, 0, 0),
        "B": ClientInfo(1, 0, 0),
    }
    t0 = 5 * S
    results = []
    for spec in (0, 8):
        q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                                 ring_capacity=16,
                                 speculative_batch=spec)
        for c in ("Z", "A", "B"):          # creation order: Z first
            for i in range(2):
                q.add_request(("r", c, i), c, ReqParams(1, 1),
                              time_ns=S, cost=1)
        # adaptive refills at fixed t0=5s (A/B resv tags 3s,5s; Z resv
        # 21s, Z limit ~1s): size 1 [A], size 2 [B, A], then the MIXED
        # size-4 batch [B, FUTURE, FUTURE, FUTURE] whose trailing
        # FUTURE steps promote Z.  Settle right after its RETURNING
        # prefix is consumed -- a further pull would launch another
        # promoting step in both queues and mask the divergence.
        out = [pull_to_tuple(q.pull_request(t0)) for _ in range(4)]
        q.settle()
        results.append((q.state, out, q._slot_of["Z"]))
    (state_a, out_a, slot_a), (state_b, out_b, slot_b) = results
    assert out_a == out_b
    assert [o[1] for o in out_a] == ["A", "B", "A", "B"]
    # the twin never promotes Z (every handed-out pull is a resv serve)
    assert not bool(state_a.head_ready[slot_a])
    assert_states_equal(state_a, state_b)


def test_spec_buffer_checkpoint_settles():
    """queue_state_dict mid-buffer must produce a consistent snapshot
    (payload FIFOs == logical device depths)."""
    from dmclock_tpu.utils.checkpoint import queue_state_dict

    infos = {c: ClientInfo(0, 1, 0) for c in range(4)}
    q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                             ring_capacity=16, speculative_batch=8)
    t = S
    for i in range(5):
        for c in range(4):
            q.add_request(("r", c, i), c, ReqParams(1, 1), time_ns=t,
                          cost=1)
    q.pull_request(2 * S)          # primes the buffer
    st = queue_state_dict(q)
    import numpy as np
    depth = np.asarray(q.state.depth)
    for s, d in st["payloads"].items():
        assert len(d) == int(depth[s])
