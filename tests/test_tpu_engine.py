"""TPU batched engine: behavior + golden parity vs the oracle scheduler.

The differential tests are the heart: identical workloads driven through
the oracle ``PullPriorityQueue`` and ``TpuPullPriorityQueue`` must yield
bit-identical decision streams (client, phase, future times), since both
implement the same int64 total order (SURVEY.md section 7, 'exact
ordering parity').  Behavioral cases mirror the reference's server tests
(``/root/reference/test/test_dmclock_server.cc``).
"""

import random

import pytest

from dmclock_tpu.core import ClientInfo, Phase, ReqParams
from dmclock_tpu.core.scheduler import (AtLimit, NextReqType,
                                        PullPriorityQueue)
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue

S = NS_PER_SEC


def make_pair(info_map, at_limit=AtLimit.WAIT, anticipation_ns=0,
              ring_capacity=64, capacity=64):
    """Oracle (delayed-calc) + TPU queues over the same ClientInfo."""

    def info_f(c):
        return info_map[c]

    oracle = PullPriorityQueue(info_f, delayed_tag_calc=True,
                               at_limit=at_limit,
                               anticipation_timeout_ns=anticipation_ns,
                               run_gc_thread=False)
    tpu = TpuPullPriorityQueue(info_f, at_limit=at_limit,
                               anticipation_timeout_ns=anticipation_ns,
                               capacity=capacity,
                               ring_capacity=ring_capacity)
    return oracle, tpu


def pull_compare(oracle, tpu, now_ns):
    po = oracle.pull_request(now_ns)
    pt = tpu.pull_request(now_ns)
    assert po.type == pt.type, (po, pt)
    if po.type is NextReqType.RETURNING:
        assert po.client == pt.client
        assert po.phase == pt.phase
        assert po.cost == pt.cost
        assert po.request == pt.request
    elif po.type is NextReqType.FUTURE:
        assert po.when_ready == pt.when_ready
    return po, pt


# ----------------------------------------------------------------------
# behavioral cases (reference test_dmclock_server.cc)
# ----------------------------------------------------------------------

def test_pull_weight_ratio():
    """Weight 1:2 serves 1:2 (reference pull_weight :822-874)."""
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 2, 0)}
    _, q = make_pair(infos)
    t = 1 * S
    for i in range(6):
        q.add_request(("r", 1, i), 1, ReqParams(), time_ns=t)
        q.add_request(("r", 2, i), 2, ReqParams(), time_ns=t)
    counts = {1: 0, 2: 0}
    for _ in range(6):
        pr = q.pull_request(t + S)
        assert pr.is_retn() and pr.phase is Phase.PRIORITY
        counts[pr.client] += 1
    assert counts == {1: 2, 2: 4}


def test_pull_reservation_ratio():
    """Reservation 2:1 serves 2:1 (reference pull_reservation :877-929)."""
    infos = {1: ClientInfo(2, 0, 0), 2: ClientInfo(1, 0, 0)}
    _, q = make_pair(infos)
    t = 100 * S
    for i in range(6):
        q.add_request(("r", 1, i), 1, ReqParams(), time_ns=t)
        q.add_request(("r", 2, i), 2, ReqParams(), time_ns=t)
    counts = {1: 0, 2: 0}
    for _ in range(6):
        # pull far in the future so every reservation tag is eligible
        # (the reference test backdates adds the same way, :902-908)
        pr = q.pull_request(t + 100 * S)
        assert pr.is_retn() and pr.phase is Phase.RESERVATION
        counts[pr.client] += 1
    assert counts == {1: 4, 2: 2}


def test_pull_none_and_future():
    infos = {1: ClientInfo(1, 1, 1)}
    _, q = make_pair(infos)
    pr = q.pull_request(1 * S)
    assert pr.is_none()
    q.add_request("a", 1, ReqParams(), time_ns=10 * S)
    # queue head is eligible at its arrival
    pr = q.pull_request(10 * S)
    assert pr.is_retn()
    # second request is limited 1/s away
    q.add_request("b", 1, ReqParams(), time_ns=10 * S)
    pr = q.pull_request(10 * S)
    assert pr.is_future()
    assert pr.when_ready == 11 * S


def test_allow_limit_break():
    """AtLimit.ALLOW serves over-limit work when nothing is eligible
    (reference :1239-1298)."""
    infos = {1: ClientInfo(0, 1, 1)}
    _, q = make_pair(infos, at_limit=AtLimit.ALLOW)
    t = 50 * S
    q.add_request("a", 1, ReqParams(), time_ns=t)
    q.add_request("b", 1, ReqParams(), time_ns=t)
    first = q.pull_request(t)
    second = q.pull_request(t)  # over limit, served via limit-break
    assert first.is_retn() and second.is_retn()
    assert q.limit_break_sched_count == 1


def test_batch_equals_sequential():
    """pull_batch(k) must equal k sequential pulls."""
    infos = {1: ClientInfo(1, 1, 0), 2: ClientInfo(0, 3, 0)}
    oracle, tpu = make_pair(infos)
    t = 7 * S
    for i in range(5):
        for c in (1, 2):
            oracle.add_request(("r", c, i), c, ReqParams(), time_ns=t)
            tpu.add_request(("r", c, i), c, ReqParams(), time_ns=t)
    now = t + 3 * S
    seq = [oracle.pull_request(now) for _ in range(12)]
    batch = tpu.pull_batch(now, 12)
    seq_retn = [p for p in seq if p.is_retn()]
    batch_retn = [p for p in batch if p.is_retn()]
    assert len(seq_retn) == len(batch_retn) == 10
    for a, b in zip(seq_retn, batch_retn):
        assert (a.client, a.phase, a.request) == (b.client, b.phase,
                                                 b.request)
    assert batch[-1].type == seq[10].type


def test_idle_reactivation_prop_delta():
    """A long-idle client must not replay a stale low proportion tag
    (reference :937-985)."""
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
    oracle, tpu = make_pair(infos)
    for q in (oracle, tpu):
        # client 1 builds up virtual time early
        for i in range(4):
            q.add_request(("a", i), 1, ReqParams(), time_ns=1 * S)
        for _ in range(4):
            q.pull_request(2 * S)
        # much later, client 2 starts, then 1 returns from idle
        q.add_request(("b", 0), 2, ReqParams(), time_ns=1000 * S)
        q.add_request(("b", 1), 2, ReqParams(), time_ns=1000 * S)
    # NOTE: client 1 is only "idle" after GC marks it; without GC the
    # oracle treats it as active.  Exercise both backends identically:
    for now in (1000 * S, 1000 * S, 1000 * S):
        pull_compare(oracle, tpu, now)


def test_remove_by_client_and_filter():
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
    oracle, tpu = make_pair(infos)
    t = 3 * S
    for q in (oracle, tpu):
        for i in range(4):
            q.add_request(("x", 1, i), 1, ReqParams(), time_ns=t)
            q.add_request(("y", 2, i), 2, ReqParams(), time_ns=t)
    got_o, got_t = [], []
    oracle.remove_by_client(1, accum=got_o.append)
    tpu.remove_by_client(1, accum=got_t.append)
    assert got_o == got_t and len(got_o) == 4
    removed_o = oracle.remove_by_req_filter(lambda r: r[2] % 2 == 0)
    removed_t = tpu.remove_by_req_filter(lambda r: r[2] % 2 == 0)
    assert removed_o and removed_t
    assert oracle.request_count() == tpu.request_count() == 2
    for _ in range(3):
        pull_compare(oracle, tpu, t + S)


def test_update_client_info_before_first_flush():
    """Regression: update_client_info must flush buffered creates first,
    else the stale OP_CREATE replays over the new inverses."""
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
    oracle, tpu = make_pair(infos)
    t = 5 * S
    for q in (oracle, tpu):
        for i in range(5):
            q.add_request(("r", 1, i), 1, ReqParams(), time_ns=t)
            q.add_request(("r", 2, i), 2, ReqParams(), time_ns=t)
    # no pull yet: the TPU queue still holds OP_CREATE rows buffered
    infos[2].update(0, 4, 0)
    oracle.update_client_info(2)
    tpu.update_client_info(2)
    for _ in range(10):
        pull_compare(oracle, tpu, t + S)


def test_gc_idle_and_erase():
    """Host-driven GC mirrors the oracle: long-idle clients are marked
    idle then erased, freeing their slots."""
    infos = {1: ClientInfo(1, 1, 0), 2: ClientInfo(1, 1, 0)}
    fake = [0.0]
    tpu = TpuPullPriorityQueue(
        lambda c: infos[c], capacity=8, idle_age_s=10.0, erase_age_s=20.0,
        monotonic_clock=lambda: fake[0])
    t = 1 * S
    tpu.add_request("a", 1, ReqParams(), time_ns=t)
    assert tpu.pull_request(2 * S).is_retn()
    assert tpu.client_count() == 1
    for i in range(31):
        fake[0] = float(i)
        tpu.do_clean()
    assert tpu.client_count() == 0
    # slot got recycled: a new client lands on the freed slot
    tpu.add_request("b", 2, ReqParams(), time_ns=40 * S)
    assert tpu.pull_request(41 * S).client == 2


def test_update_client_info():
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
    oracle, tpu = make_pair(infos)
    t = 5 * S
    for q in (oracle, tpu):
        for i in range(6):
            q.add_request(("r", 1, i), 1, ReqParams(), time_ns=t)
            q.add_request(("r", 2, i), 2, ReqParams(), time_ns=t)
        q.pull_request(t + 1)
    infos[2].update(0, 4, 0)
    oracle.update_client_info(2)
    tpu.update_client_info(2)
    for _ in range(8):
        pull_compare(oracle, tpu, t + S)


# ----------------------------------------------------------------------
# differential fuzzing: the golden-parity gate
# ----------------------------------------------------------------------

# the heaviest random-workload cells (~18-27s each on the CPU box)
# are slow-marked for the tier-1 wall budget; one WAIT and one ALLOW
# cell keep the quick sweep's differential coverage
@pytest.mark.parametrize("seed,at_limit,anticipation_s", [
    (1, AtLimit.WAIT, 0.0),
    (2, AtLimit.WAIT, 0.0),
    pytest.param(3, AtLimit.ALLOW, 0.0, marks=pytest.mark.slow),
    (4, AtLimit.ALLOW, 0.0),
    pytest.param(5, AtLimit.WAIT, 0.1, marks=pytest.mark.slow),
    pytest.param(6, AtLimit.ALLOW, 0.05, marks=pytest.mark.slow),
])
def test_differential_random_workload(seed, at_limit, anticipation_s):
    rng = random.Random(seed)
    n_clients = rng.randint(2, 12)
    infos = {}
    for c in range(n_clients):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 4), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2), rng.uniform(0.5, 4),
                                  rng.uniform(3, 8))
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 2), rng.uniform(0.5, 4),
                                  0)
    oracle, tpu = make_pair(infos, at_limit=at_limit,
                            anticipation_ns=int(anticipation_s * S))

    now = 1 * S
    n_retn = [0]

    def do_pull():
        po, _ = pull_compare(oracle, tpu, now)
        if po.is_retn():
            n_retn[0] += 1

    for step in range(200):
        now += rng.randint(0, S // 2)
        r = rng.random()
        if r < 0.55:
            c = rng.randrange(n_clients)
            delta = rng.randint(1, 5)
            rho = rng.randint(1, delta)
            cost = rng.randint(1, 3)
            req = ("req", c, step)
            assert oracle.add_request(req, c, ReqParams(delta, rho),
                                      time_ns=now, cost=cost) == 0
            assert tpu.add_request(req, c, ReqParams(delta, rho),
                                   time_ns=now, cost=cost) == 0
        else:
            do_pull()
    # drain (advance generously: reservation spacing can reach
    # inv * charge-units ~ 16s per request for the slowest QoS draws)
    for _ in range(800):
        now += 4 * S
        do_pull()
        if oracle.request_count() == 0:
            break
    assert oracle.request_count() == tpu.request_count() == 0
    assert n_retn[0] > 50
    assert oracle.reserv_sched_count == tpu.reserv_sched_count
    assert oracle.prop_sched_count == tpu.prop_sched_count
    assert oracle.limit_break_sched_count == tpu.limit_break_sched_count


def test_differential_ring_growth():
    """Force tail-ring overflow -> growth mid-workload; parity must hold."""
    infos = {0: ClientInfo(1, 1, 0), 1: ClientInfo(0, 2, 0)}
    oracle, tpu = make_pair(infos, ring_capacity=4)
    t = 2 * S
    for i in range(40):
        for c in (0, 1):
            oracle.add_request((c, i), c, ReqParams(), time_ns=t + i)
            tpu.add_request((c, i), c, ReqParams(), time_ns=t + i)
    assert tpu.state.ring_capacity >= 40
    served = 0
    now = t
    while served < 80:
        now += S
        po, _ = pull_compare(oracle, tpu, now)
        if po.is_retn():
            served += 1
        elif po.is_none():
            break
    assert served == 80


def test_capacity_growth():
    infos = {c: ClientInfo(0, 1 + (c % 3), 0) for c in range(40)}
    oracle, tpu = make_pair(infos, capacity=8)
    t = 1 * S
    for c in range(40):
        oracle.add_request(("r", c), c, ReqParams(), time_ns=t)
        tpu.add_request(("r", c), c, ReqParams(), time_ns=t)
    assert tpu.state.capacity >= 40
    for _ in range(41):
        pull_compare(oracle, tpu, t + S)


def test_display_queues_dump():
    """Device-state debug dump: three sections in the oracle's
    RESER/LIMIT/READY layout, selection order = (tag, creation order),
    requestless clients last."""
    from dmclock_tpu.core import ClientInfo, ReqParams
    from dmclock_tpu.engine import TpuPullPriorityQueue

    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 2, 0)}
    q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                             ring_capacity=8)
    q.add_request("a", 1, ReqParams(), time_ns=0)
    q.add_request("b", 2, ReqParams(), time_ns=0)
    dump = q.display_queues()
    lines = dump.splitlines()
    assert [ln.split(":")[0] for ln in lines] == ["RESER", "LIMIT",
                                                 "READY"]
    ready = lines[2]
    # client 1 leads READY: its eff tag is 1e9; client 2's smaller raw
    # prop tag (5e8) is shifted past it by idle-reactivation prop_delta
    # (it was created while client 1 was already active)
    assert ready.startswith("READY: 1:")
    assert "2:" in ready
    # the displayed proportion tag is the RAW head tag (5e8), not the
    # prop_delta-shifted effective sort key -- so dumps diff cleanly
    # against the oracle/native dumps, which print the raw tag
    assert f"P{5 * 10**8}/" in ready.split("2:")[1]
    # draining client 1 leaves it 'noreq', sorted last in every section
    pr = q.pull_request(now_ns=10**9)
    assert pr.client == 1
    dump = q.display_queues()
    for ln in dump.splitlines():
        assert ln.endswith("1:noreq")


@pytest.mark.slow
def test_ingest_wave_matches_sequential_scan():
    """ingest_wave == the sequential ingest scan for distinct-slot
    waves, bit for bit, whenever at most one client reactivates from
    idle per wave (with more, only the reactivation prop_delta may
    differ -- the documented batch-model semantic)."""
    import numpy as np
    import random as pyrandom
    import jax.numpy as jnp
    from dmclock_tpu.engine import init_state, kernels
    from dmclock_tpu.engine.kernels import (OP_ADD, OP_NOP, IngestOps,
                                            ingest, ingest_wave)

    rng = pyrandom.Random(3)
    n = 16
    state = init_state(n, 8)
    state = state._replace(
        active=jnp.ones((n,), bool),
        order=jnp.arange(n, dtype=jnp.int64),
        resv_inv=jnp.asarray([10**7 * (1 + i % 3) for i in range(n)],
                             jnp.int64),
        weight_inv=jnp.asarray([10**9 // (1 + i % 4) for i in range(n)],
                               jnp.int64),
        limit_inv=jnp.zeros((n,), jnp.int64),
    )
    seq_state = wave_state = state
    t = 10**9
    for wave in range(12):
        # at most one currently-idle client per wave (checked below)
        idle = np.asarray(wave_state.idle)
        mask = np.zeros(n, dtype=bool)
        idle_choices = [c for c in range(n) if idle[c]]
        lo = 0
        if idle_choices and rng.random() < 0.7:
            c0 = rng.choice(idle_choices)
            mask[c0] = True
            lo = c0 + 1          # reactivator must be the lowest slot
        for c in rng.sample(range(n), rng.randint(1, n)):
            if c >= lo and not idle[c]:
                mask[c] = True
        if not mask.any():
            continue
        cost = np.asarray([rng.randint(1, 3) for _ in range(n)],
                          dtype=np.int64)
        delta = np.asarray([rng.randint(1, 6) for _ in range(n)],
                           dtype=np.int64)
        rho = np.minimum(delta,
                         [rng.randint(1, 4) for _ in range(n)])
        ops = IngestOps(
            kind=jnp.asarray(np.where(mask, OP_ADD, OP_NOP),
                             jnp.int32),
            slot=jnp.arange(n, dtype=jnp.int32),
            time=jnp.full((n,), t, jnp.int64),
            cost=jnp.asarray(cost), rho=jnp.asarray(rho),
            delta=jnp.asarray(delta),
            resv_inv=jnp.zeros((n,), jnp.int64),
            weight_inv=jnp.zeros((n,), jnp.int64),
            limit_inv=jnp.zeros((n,), jnp.int64),
            order=jnp.zeros((n,), jnp.int64))
        seq_state = ingest(seq_state, ops, anticipation_ns=0)
        wave_state = ingest_wave(
            wave_state, jnp.asarray(mask), jnp.int64(t),
            jnp.asarray(cost), jnp.asarray(rho), jnp.asarray(delta),
            anticipation_ns=0)
        for f in seq_state._fields:
            a, b = getattr(seq_state, f), getattr(wave_state, f)
            assert (np.asarray(a) == np.asarray(b)).all(), \
                f"wave {wave}: field {f} diverges"
        # pop a few heads so queues/depths vary across waves
        st, _, _ = kernels.engine_run(seq_state, jnp.int64(t + 10**9),
                                      3, allow_limit_break=False,
                                      anticipation_ns=0)
        seq_state = wave_state = st
        t += 10**9


# ----------------------------------------------------------------------
# AtLimit::Reject -- host immediate-mode limit mirror
# ----------------------------------------------------------------------

class TestTpuReject:
    """The TPU queue's Reject admission must be bit-identical to the
    oracle's immediate-mode Reject queue (the reference cannot even
    express Reject+delayed; here admission runs on a host mirror of
    the immediate limit recurrence, queue.py module docstring)."""

    def test_reject_at_limit(self):
        import errno
        q = TpuPullPriorityQueue(lambda c: ClientInfo(0, 1, 1),
                                 at_limit=AtLimit.REJECT)
        assert q.add_request("a", 52, ReqParams(), time_ns=1 * S) == 0
        assert q.add_request("b", 52, ReqParams(), time_ns=2 * S) == 0
        assert q.add_request("c", 52, ReqParams(), time_ns=3 * S) == 0
        assert q.add_request("d", 52, ReqParams(),
                             time_ns=int(3.9 * S)) == errno.EAGAIN
        # the rejected request still advanced the limit mirror
        assert q.add_request("e", 52, ReqParams(),
                             time_ns=4 * S) == errno.EAGAIN
        assert q.add_request("f", 52, ReqParams(), time_ns=6 * S) == 0
        # admitted requests actually get served
        served = 0
        for _ in range(8):
            pr = q.pull_request(now_ns=100 * S)
            if pr.type is not NextReqType.RETURNING:
                break
            served += 1
        assert served == 4

    def test_reject_threshold_number_implies_reject(self):
        import errno
        q = TpuPullPriorityQueue(lambda c: ClientInfo(0, 1, 1),
                                 at_limit=3 * S)
        assert q.at_limit is AtLimit.REJECT
        assert q.reject_threshold_ns == 3 * S
        for _ in range(4):
            assert q.add_request("x", 52, ReqParams(),
                                 time_ns=1 * S) == 0
        assert q.add_request("x", 52, ReqParams(),
                             time_ns=1 * S) == errno.EAGAIN
        assert q.add_request("x", 52, ReqParams(), time_ns=3 * S) == 0

    @pytest.mark.parametrize("seed", [5, 6, 7])
    @pytest.mark.parametrize("threshold_s", [0, 2])
    def test_reject_admission_matches_oracle(self, seed, threshold_s):
        """Random add sequences: the EAGAIN pattern must equal the
        oracle immediate-mode queue's, add for add."""
        rng = random.Random(seed)
        infos = {c: ClientInfo(0, 1.0 + c % 2,
                               rng.choice([0.5, 1.0, 2.0]))
                 for c in range(6)}
        at = AtLimit.REJECT if threshold_s == 0 else threshold_s * S

        oracle = PullPriorityQueue(lambda c: infos[c],
                                   delayed_tag_calc=False,
                                   at_limit=at, run_gc_thread=False)
        tpu = TpuPullPriorityQueue(lambda c: infos[c], at_limit=at)
        t = 1 * S
        outcomes = []
        for i in range(200):
            c = rng.randrange(6)
            t += rng.randint(0, S // 3)
            delta = rng.randint(1, 3)
            rho = rng.randint(1, delta)
            cost = rng.randint(1, 2)
            ro = oracle.add_request(("r", i), c, ReqParams(delta, rho),
                                    time_ns=t, cost=cost)
            rt = tpu.add_request(("r", i), c, ReqParams(delta, rho),
                                 time_ns=t, cost=cost)
            assert ro == rt, \
                f"add {i} (t={t}): oracle {ro} vs tpu {rt}"
            outcomes.append(ro)
            # occasional pulls: serves must not perturb admission
            # (the immediate limit recurrence is add-only)
            if rng.random() < 0.2:
                oracle.pull_request(now_ns=t)
                tpu.pull_request(now_ns=t)
        assert any(o != 0 for o in outcomes), "no rejects exercised"
        assert any(o == 0 for o in outcomes)
