"""Host fault plans (robust.host_faults): deterministic schedules,
the exactly-once write-ahead injector, checkpoint corruption during
save, and the scrape endpoint's restart resilience
(docs/ROBUSTNESS.md)."""

import os
import urllib.request

import pytest

from dmclock_tpu.engine import init_state
from dmclock_tpu.obs import MetricsRegistry, start_http_server
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.utils import checkpoint as ckpt_mod


class TestPlans:
    def test_zero_plan_describes_none(self):
        assert HF.describe_host(HF.zero_host_plan()) == "none"
        assert HF.describe_host(None) == "none"
        assert HF.host_plan_events(None)["restarts"] == 0

    def test_sample_deterministic_across_calls(self):
        kw = dict(epochs=8, est_decisions=1000, kills=2,
                  save_kills=1, corrupt_saves=1, scrape_drops=1)
        assert HF.sample_host_plan(7, **kw) == \
            HF.sample_host_plan(7, **kw)
        assert HF.sample_host_plan(7, **kw) != \
            HF.sample_host_plan(8, **kw)

    def test_sample_targets_checkpointing_epochs(self):
        plan = HF.sample_host_plan(3, epochs=8, est_decisions=500,
                                   save_kills=4, corrupt_saves=4,
                                   ckpt_every=2)
        for e, stage in plan.kill_at_save:
            assert (e + 1) % 2 == 0, "save kill on a non-ckpt epoch"
            assert stage in ckpt_mod.SAVE_STAGES
        for e in plan.corrupt_save_at:
            assert (e + 1) % 2 == 0

    def test_events_oracle_and_describe(self):
        plan = HF.HostFaultPlan(kill_at_decisions=(10, 20),
                                kill_at_save=((1, "data_renamed"),),
                                corrupt_save_at=(3,),
                                drop_scrape_at=(0, 2))
        ev = HF.host_plan_events(plan)
        assert ev == {"kills": 2, "save_kills": 1,
                      "corrupt_saves": 1, "scrape_drops": 2,
                      "ctl_kills": 0, "restarts": 3}
        assert HF.describe_host(plan) == \
            "host:kill2+savekill1+corrupt1+scrape2"

    def test_controller_kills_count_as_restarts(self):
        plan = HF.HostFaultPlan(
            kill_at_controller=((2, "after_journal"),
                                (4, "after_apply")))
        ev = HF.host_plan_events(plan)
        assert ev["ctl_kills"] == 2 and ev["restarts"] == 2
        assert HF.describe_host(plan) == \
            "host:kill0+savekill0+corrupt0+scrape0+ctlkill2"
        for _e, stage in plan.kill_at_controller:
            assert stage in HF.CONTROLLER_STAGES

    def test_json_round_trip(self):
        plan = HF.sample_host_plan(5, epochs=6, est_decisions=300,
                                   kills=2, save_kills=1,
                                   corrupt_saves=1, scrape_drops=1)
        assert HF.plan_from_json(HF.plan_to_json(plan)) == plan
        assert HF.plan_from_json(HF.plan_to_json(None)) == \
            HF.zero_host_plan()


class TestInjector:
    def test_kill_fires_exactly_once_across_restarts(self, tmp_path):
        plan = HF.HostFaultPlan(kill_at_decisions=(100,))
        inj = HF.HostFaultInjector(plan, tmp_path)
        inj.after_decisions(50)          # below the point: no fire
        with pytest.raises(HF.HostKill):
            inj.after_decisions(150)
        # a restarted incarnation (fresh injector, same workdir)
        # replays past the same threshold without dying again
        inj2 = HF.HostFaultInjector(plan, tmp_path)
        inj2.after_decisions(150)
        inj2.after_decisions(10 ** 9)
        assert "dec:0" in inj2.fired

    def test_fired_journal_is_durable_before_the_kill(self, tmp_path):
        inj = HF.HostFaultInjector(
            HF.HostFaultPlan(kill_at_decisions=(1,)), tmp_path)
        with pytest.raises(HF.HostKill):
            inj.after_decisions(5)
        # the write-ahead journal already names the point (a SIGKILL
        # right after would still leave it on disk)
        fired = (tmp_path / HF.HostFaultInjector.FIRED_NAME).read_text()
        assert "dec:0" in fired

    def test_save_stage_kill_uninstalls_the_hook(self, tmp_path):
        plan = HF.HostFaultPlan(kill_at_save=((0, "data_renamed"),))
        inj = HF.HostFaultInjector(plan, tmp_path)
        rot = tmp_path / "rot"
        st = init_state(8, 4)
        with pytest.raises(HF.HostKill):
            inj.around_save(
                0, lambda: ckpt_mod.save_pytree_rotating(rot, st))
        assert ckpt_mod._crash_hook is None
        assert ckpt_mod._post_commit_hook is None
        # the torn entry is not restorable, and a retried save (the
        # point is spent) commits cleanly
        inj.around_save(
            0, lambda: ckpt_mod.save_pytree_rotating(rot, st))
        _, path = ckpt_mod.restore_pytree_rotating(rot, init_state(8, 4))
        assert path == ckpt_mod.rotation_paths(rot)[-1]

    def test_corrupt_save_pair_fails_verification(self, tmp_path):
        plan = HF.HostFaultPlan(corrupt_save_at=(0,))
        inj = HF.HostFaultInjector(plan, tmp_path)
        rot = tmp_path / "rot"
        st = init_state(8, 4)
        ckpt_mod.save_pytree_rotating(rot, st)      # intact predecessor
        inj.around_save(
            0, lambda: ckpt_mod.save_pytree_rotating(rot, st))
        paths = ckpt_mod.rotation_paths(rot)
        assert len(paths) == 2
        with pytest.raises(ckpt_mod.CheckpointCorruptError):
            ckpt_mod.restore_pytree(paths[-1], init_state(8, 4))
        # rotation restore walks back to the intact predecessor
        _, path = ckpt_mod.restore_pytree_rotating(rot, init_state(8, 4))
        assert path == paths[0]


class TestScrapeEndpointResilience:
    def test_repeated_start_on_taken_port_fails_soft(self, capsys):
        reg = MetricsRegistry()
        srv = start_http_server(reg, port=0)
        assert srv is not None
        try:
            dup = start_http_server(MetricsRegistry(), port=srv.port)
            assert dup is None, "second bind on a live port must " \
                "fail soft, not raise"
            assert "scrape endpoint disabled" in \
                capsys.readouterr().err
        finally:
            srv.close()

    def test_rebind_same_port_after_close(self):
        """The supervisor-restart scenario: the old incarnation's
        server is gone, the new one takes the same port immediately
        (SO_REUSEADDR -- no TIME_WAIT stall) and serves scrapes."""
        reg = MetricsRegistry()
        reg.counter("dmclock_test_total", "t").inc(3)
        srv = start_http_server(reg, port=0)
        port = srv.port
        srv.close()
        srv2 = start_http_server(reg, port=port)
        assert srv2 is not None and srv2.port == port
        try:
            body = urllib.request.urlopen(srv2.url,
                                          timeout=5).read().decode()
            assert "dmclock_test_total 3" in body
        finally:
            srv2.close()

    def test_fail_soft_off_raises(self):
        srv = start_http_server(MetricsRegistry(), port=0)
        try:
            with pytest.raises(OSError):
                start_http_server(MetricsRegistry(), port=srv.port,
                                  fail_soft=False)
        finally:
            srv.close()
