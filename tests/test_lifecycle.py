"""Client lifecycle plane (dmclock_tpu.lifecycle; docs/LIFECYCLE.md).

The headline gate: a run that registers clients dynamically -- with
idle eviction, slot recycling, grow-on-demand capacity, and at least
one compaction epoch -- produces a BIT-IDENTICAL canonical decision
stream to a statically pre-registered run over the same arrival
trace, on the serial engine and on prefix/chain/calendar under both
the round and the stream loop.  Plus the slot-map/op-vector unit
contracts, the admin control API (one validation path with init-time
construction), the WAL acceptance journal, the queue's
departed-clients report, and the grow-on-demand checkpoint shapes.
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core.qos import ClientInfo, validate_client_info
from dmclock_tpu.engine.state import (_FRESH_FILLS, EngineState,
                                      grow_state, init_state)
from dmclock_tpu.lifecycle import (SCENARIOS, LifecyclePlane, SlotMap,
                                   make_spec, run_serial_churn,
                                   static_variant, wal_append)
from dmclock_tpu.lifecycle import churn as churn_mod
from dmclock_tpu.lifecycle.api import AdminAPI, mount_admin_api
from dmclock_tpu.lifecycle.plane import (LC_EVICT, LC_IDLE, LC_NOP,
                                         LC_REGISTER, LC_UPDATE,
                                         apply_op_vector)
from dmclock_tpu.lifecycle.slots import compact_tree
from dmclock_tpu.robust import supervisor as SV


def np_state(st: EngineState) -> dict:
    return {f: np.asarray(jax.device_get(getattr(st, f)))
            for f in EngineState._fields}


# ----------------------------------------------------------------------
# slot map
# ----------------------------------------------------------------------

class TestSlotMap:
    def test_allocate_lowest_first_and_recycle(self):
        m = SlotMap(4)
        assert [m.allocate(c) for c in (10, 11, 12)] == [0, 1, 2]
        for s in range(3):
            m.was_used(s)                 # mark, as the plane does
        assert m.release(11) == 1
        # the freed slot is the LOWEST free one -> reused next
        assert m.allocate(13) == 1
        assert m.was_used(1) is True      # second tenant = a recycle
        assert m.allocate(14) == 3
        assert m.was_used(3) is False
        assert m.allocate(15) == -1       # full -> caller grows
        assert m.live_count == 4

    def test_grow_extends_free_list(self):
        m = SlotMap(2)
        m.allocate(0), m.allocate(1)
        m.grow(4)
        assert m.capacity == 4
        assert m.allocate(2) == 2
        assert np.array_equal(m.cid_of_slot, [0, 1, 2, -1])

    def test_compaction_perm_none_when_dense(self):
        m = SlotMap(4)
        m.allocate(0), m.allocate(1)
        assert m.compaction_perm() is None     # already a dense prefix
        m.release(0)
        perm = m.compaction_perm()
        assert perm is not None
        assert perm.tolist() == [1, 0, 2, 3]   # stable: live first

    def test_apply_perm_remaps_everything(self):
        m = SlotMap(4)
        for c in (7, 8, 9):
            m.allocate(c)
        m.release(8)
        perm = m.compaction_perm()
        m.apply_perm(perm)
        assert np.array_equal(m.cid_of_slot, [7, 9, -1, -1])
        assert m.slot_of == {7: 0, 9: 1}
        assert m.allocate(20) == 2             # free list rebuilt

    def test_translate_and_scatter(self):
        m = SlotMap(3)
        m.allocate(5), m.allocate(6)
        out = m.translate(np.asarray([[1, 0], [-1, 2]]))
        # -1 pads pass through; slot 2 is free -> -1
        assert out.tolist() == [[6, 5], [-1, -1]]
        sc = m.scatter_by_cid(np.asarray([10, 20, 30]), total=8)
        assert sc.tolist() == [0, 0, 0, 0, 0, 10, 20, 0]

    def test_encode_load_round_trip(self):
        m = SlotMap(4)
        for c in (3, 1, 2):
            m.allocate(c)
        m.take_order(), m.take_order()
        m.release(1)
        m2 = SlotMap.load(m.encode())
        assert np.array_equal(m2.cid_of_slot, m.cid_of_slot)
        assert m2.slot_of == m.slot_of
        assert m2.next_order == m.next_order
        # derived free list rebuilt lowest-first (the resume contract)
        assert m2.allocate(99) == 1


# ----------------------------------------------------------------------
# validation: ONE path shared by init-time and live updates
# ----------------------------------------------------------------------

class TestValidation:
    def test_same_error_as_init_time_construction(self):
        with pytest.raises(ValueError) as init_err:
            ClientInfo(-1.0, 1.0, 0.0, client="g0")
        with pytest.raises(ValueError) as live_err:
            validate_client_info((-1.0, 1.0, 0.0), name="g0")
        assert str(init_err.value) == str(live_err.value)
        assert "g0" in str(live_err.value)

    def test_limit_below_reservation_matches_too(self):
        with pytest.raises(ValueError) as init_err:
            ClientInfo(100.0, 1.0, 50.0, client=7)
        with pytest.raises(ValueError) as live_err:
            validate_client_info((100.0, 1.0, 50.0), name=7)
        assert str(init_err.value) == str(live_err.value)

    def test_object_form_uses_own_client_name(self):
        info = ClientInfo(0.0, 1.0, 0.0, client="ok")
        validate_client_info(info)            # valid passes
        bad = ClientInfo(0.0, 1.0, 0.0, client="bad")
        bad.weight = float("nan")
        with pytest.raises(ValueError, match="bad"):
            validate_client_info(bad)

    def test_non_numeric_is_a_valueerror_not_a_crash(self):
        with pytest.raises(ValueError, match="numeric"):
            validate_client_info(("abc", 1.0, 0.0), name=3)


# ----------------------------------------------------------------------
# the device op vector
# ----------------------------------------------------------------------

class TestApplyOpVector:
    def _dirty_state(self, n=4, ring=4):
        """A state whose slot 1 carries junk in every field."""
        st = init_state(n, ring)
        return st._replace(
            active=st.active.at[1].set(True),
            idle=st.idle.at[1].set(False),
            order=st.order.at[1].set(9),
            resv_inv=st.resv_inv.at[1].set(11),
            weight_inv=st.weight_inv.at[1].set(12),
            limit_inv=st.limit_inv.at[1].set(13),
            prev_prop=st.prev_prop.at[1].set(14),
            head_prop=st.head_prop.at[1].set(15),
            head_cost=st.head_cost.at[1].set(16),
            head_ready=st.head_ready.at[1].set(True),
            depth=st.depth.at[1].set(2),
            q_head=st.q_head.at[1].set(1),
            q_arrival=st.q_arrival.at[1].set(17),
            q_cost=st.q_cost.at[1].set(18),
        )

    def _apply(self, st, rows):
        arr = np.asarray(rows, dtype=np.int64)
        return apply_op_vector(st, arr[:, 0], arr[:, 1], arr[:, 2],
                               arr[:, 3], arr[:, 4], arr[:, 5])

    def test_evicted_slot_is_byte_identical_to_fresh(self):
        st = self._apply(self._dirty_state(),
                         [(LC_EVICT, 1, 0, 0, 0, 0)])
        fresh = np_state(init_state(4, 4))
        got = np_state(st)
        for f in EngineState._fields:
            assert np.array_equal(got[f], fresh[f]), f

    def test_register_installs_exactly_create_fields(self):
        st = self._apply(self._dirty_state(),
                         [(LC_REGISTER, 1, 100, 200, 300, 5)])
        got = np_state(st)
        fresh = np_state(init_state(4, 4))
        assert got["active"][1] and got["idle"][1]
        assert got["order"][1] == 5
        assert (got["resv_inv"][1], got["weight_inv"][1],
                got["limit_inv"][1]) == (100, 200, 300)
        # every OTHER field of the row reset to the init fill
        for f in EngineState._fields:
            if f in ("active", "order", "resv_inv", "weight_inv",
                     "limit_inv"):
                continue
            assert np.array_equal(got[f][1], fresh[f][1]), f

    def test_update_touches_only_the_three_inverses(self):
        dirty = self._dirty_state()
        st = self._apply(dirty, [(LC_UPDATE, 1, 7, 8, 9, 0)])
        got, before = np_state(st), np_state(dirty)
        assert (got["resv_inv"][1], got["weight_inv"][1],
                got["limit_inv"][1]) == (7, 8, 9)
        for f in EngineState._fields:
            if f in ("resv_inv", "weight_inv", "limit_inv"):
                continue
            assert np.array_equal(got[f], before[f]), f

    def test_idle_mark_touches_only_idle(self):
        dirty = self._dirty_state()
        st = self._apply(dirty, [(LC_IDLE, 1, 0, 0, 0, 0)])
        got, before = np_state(st), np_state(dirty)
        assert got["idle"][1]
        for f in EngineState._fields:
            if f == "idle":
                continue
            assert np.array_equal(got[f], before[f]), f

    def test_rows_compose_in_order_and_nops_pad(self):
        st = self._apply(init_state(4, 4), [
            (LC_REGISTER, 2, 1, 2, 3, 0),
            (LC_UPDATE, 2, 4, 5, 6, 0),      # same boundary, later row
            (LC_NOP, 0, 0, 0, 0, 0),
        ])
        got = np_state(st)
        assert got["active"][2]
        assert (got["resv_inv"][2], got["weight_inv"][2],
                got["limit_inv"][2]) == (4, 5, 6)
        assert not got["active"][0]          # NOP touched nothing

    def test_grow_state_new_rows_match_fresh(self):
        st = grow_state(self._dirty_state(), 8)
        fresh = np_state(init_state(8, 4))
        got = np_state(st)
        for f in EngineState._fields:
            assert np.array_equal(got[f][4:], fresh[f][4:]), f
        assert got["order"][1] == 9          # old rows untouched

    def test_compact_tree_gathers_every_leaf(self):
        st = self._dirty_state()
        led = jnp.arange(8, dtype=jnp.int64).reshape(4, 2)
        perm = np.asarray([1, 0, 2, 3], dtype=np.int32)
        st2, led2 = compact_tree((st, led), perm)
        assert np.asarray(st2.order).tolist() == [9, 0, 0, 0]
        assert np.asarray(led2).tolist() == [[2, 3], [0, 1],
                                             [4, 5], [6, 7]]


# ----------------------------------------------------------------------
# the digest gates
# ----------------------------------------------------------------------

# generations live 2 epochs and start 4 apart: gen0 is evicted (quiet
# streak 2 at boundary 6) before gen2 registers at boundary 8, so
# registrations land on RECYCLED slots; capacity0=4 forces a grow at
# boundary 4; the eviction holes make compaction (every boundary) fire
SPEC = make_spec("churn_storm", total_ids=16, base_lam=1.5,
                 compact_every=1, gens=4, stride=4, life=2,
                 capacity0=4)


class TestSerialDigestGate:
    @pytest.mark.parametrize("scenario", SCENARIOS)
    def test_dynamic_equals_static(self, scenario):
        spec = make_spec(scenario, total_ids=16, base_lam=1.5,
                         compact_every=2)
        d_dyn, plane, n_dyn = run_serial_churn(spec, epochs=16,
                                               every=2)
        d_st, _, n_st = run_serial_churn(static_variant(spec),
                                         epochs=16, every=2)
        assert d_dyn == d_st
        assert n_dyn == n_st > 0
        snap = plane.snapshot()
        if scenario == "churn_storm":
            # later generations start past this short run's horizon;
            # the open-population mechanics still fired
            assert snap["registrations"] >= 8
            assert snap["evictions"] > 0
        else:
            assert snap["registrations"] == 16

    def test_churn_storm_recycles_and_compacts(self):
        d_dyn, plane, _ = run_serial_churn(SPEC, epochs=20, every=2)
        d_st, _, _ = run_serial_churn(static_variant(SPEC),
                                      epochs=20, every=2)
        assert d_dyn == d_st
        snap = plane.snapshot()
        assert snap["evictions"] > 0
        assert snap["slot_recycles"] > 0
        assert snap["compactions"] > 0
        # departed report: one final ledger row per evicted client
        dep = plane.departed_report()
        assert len(dep) == snap["evictions"]
        assert all(row.shape == (5,) for _, row in dep)
        assert plane.departed_report() == []   # drained


_STATIC_REFS: dict = {}


def _churn_job(engine: str, spec: dict, loop: str) -> SV.EpochJob:
    return SV.EpochJob(engine=engine, churn=spec, epochs=12, m=2,
                       k=8, ring=16, waves=4, ckpt_every=2, seed=11,
                       engine_loop=loop)


def _static_ref(engine: str) -> SV.SupervisedResult:
    if engine not in _STATIC_REFS:
        _STATIC_REFS[engine] = SV.run_job(
            _churn_job(engine, static_variant(SPEC), "round"))
    return _STATIC_REFS[engine]


class TestEngineDigestGate:
    @pytest.mark.parametrize("engine", ("prefix", "chain", "calendar"))
    @pytest.mark.parametrize("loop", ("round", "stream"))
    def test_dynamic_equals_static(self, engine, loop):
        """The acceptance gate: dynamic registration + recycling +
        growth + compaction is decision-stream-neutral on every epoch
        engine, round and stream loops."""
        res = SV.run_job(_churn_job(engine, SPEC, loop))
        ref = _static_ref(engine)
        assert res.digest == ref.digest
        assert res.decisions == ref.decisions > 0
        assert res.lifecycle["grows"] >= 1
        assert res.lifecycle["compactions"] >= 1
        assert res.lifecycle["evictions"] >= 1

    def test_static_stream_equals_static_round(self):
        res = SV.run_job(
            _churn_job("prefix", static_variant(SPEC), "stream"))
        assert res.digest == _static_ref("prefix").digest


# ----------------------------------------------------------------------
# admin control API
# ----------------------------------------------------------------------

def _plane(**kw) -> LifecyclePlane:
    spec = make_spec("flash_crowd", total_ids=8, base_lam=1.0, **kw)
    return LifecyclePlane(spec)


class TestAdminAPI:
    def _api(self, plane=None, ledger_rows=None):
        return AdminAPI(plane or _plane(), ledger_rows=ledger_rows)

    def _call(self, api, method, path, body=None):
        status, ctype, out = api.handler(
            method, path,
            json.dumps(body).encode() if body is not None else b"")
        assert ctype == "application/json"
        return status, json.loads(out.decode())

    def test_register_update_get_delete_cycle(self):
        # client 6 is in the flash_crowd cohort scripted for boundary
        # 8 -- these boundaries stop at 2, so every op below is ours;
        # the base cohort (ids 0-3) registers by script at boundary 0
        api = self._api()
        st, obj = self._call(api, "POST", "/clients",
                             {"id": 6, "reservation": 0.0,
                              "weight": 2.0, "limit": 0.0})
        assert st == 202 and obj["accepted"] and obj["seq"] == 0
        # visible as pending before its boundary
        st, obj = self._call(api, "GET", "/clients/6")
        assert st == 200 and obj["pending"] == ["register"]
        assert not obj["registered"]
        st, obj = self._call(api, "PUT", "/clients/6/qos",
                             {"weight": 8.0})
        assert st == 202 and obj["seq"] == 1
        # apply at a boundary, then the slot is live
        plane = api.plane
        state = init_state(plane.spec["capacity0"], 8)
        state, _ = plane.boundary(state, 0, 2)
        st, obj = self._call(api, "GET", "/clients/6")
        assert st == 200 and obj["registered"]
        assert obj["qos"]["weight"] == 8.0
        st, obj = self._call(api, "DELETE", "/clients/6")
        assert st == 202
        state, _ = plane.boundary(state, 2, 2)
        st, obj = self._call(api, "GET", "/clients/6")
        assert st == 404
        snap = plane.snapshot()
        assert snap["registrations"] == 5    # 4 scripted + ours
        assert snap["qos_updates"] == 1
        assert snap["evictions"] == 1

    def test_invalid_qos_is_400_with_init_time_message(self):
        api = self._api()
        st, obj = self._call(api, "POST", "/clients",
                             {"id": 1, "reservation": -5.0})
        assert st == 400
        with pytest.raises(ValueError) as err:
            ClientInfo(-5.0, 1.0, 0.0, client=1)
        assert obj["error"] == str(err.value)

    def test_conflict_unknown_and_method_errors(self):
        api = self._api()
        self._call(api, "POST", "/clients", {"id": 1})
        st, _ = self._call(api, "POST", "/clients", {"id": 1})
        assert st == 409
        st, _ = self._call(api, "PUT", "/clients/9/qos",
                           {"weight": 1.0})
        assert st == 404
        st, _ = self._call(api, "DELETE", "/clients/9")
        assert st == 404
        st, _ = self._call(api, "GET", "/clients/xyz")
        assert st == 404
        st, _ = self._call(api, "PUT", "/clients")
        assert st == 405
        st, obj = self._call(api, "POST", "/clients", "not a dict")
        assert st == 400

    def test_population_summary(self):
        api = self._api()
        st, obj = self._call(api, "GET", "/clients")
        assert st == 200
        assert obj["live_clients"] == 0
        assert obj["pending_ops"] == 0
        assert "registrations" in obj

    def test_ledger_rows_surface_in_get(self):
        plane = _plane()
        api = self._api(plane,
                        ledger_rows=lambda: {2: np.arange(5)})
        self._call(api, "POST", "/clients", {"id": 2})
        state = init_state(plane.spec["capacity0"], 8)
        plane.boundary(state, 0, 2)
        st, obj = self._call(api, "GET", "/clients/2")
        assert st == 200 and obj["ledger"] == [0, 1, 2, 3, 4]

    def test_mounted_over_http(self):
        """End to end through the scrape endpoint: ONE port serves
        Prometheus scrape + lifecycle control."""
        from dmclock_tpu.obs.registry import (MetricsHTTPServer,
                                              MetricsRegistry)

        plane = _plane()
        with MetricsHTTPServer(MetricsRegistry(), port=0) as srv:
            mount_admin_api(srv, plane)
            base = f"http://{srv.host}:{srv.port}"

            def req(method, path, body=None):
                data = json.dumps(body).encode() \
                    if body is not None else None
                r = urllib.request.Request(base + path, data=data,
                                           method=method)
                try:
                    with urllib.request.urlopen(r, timeout=5) as resp:
                        return resp.status, json.loads(resp.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            st, obj = req("POST", "/clients", {"id": 5, "weight": 3.0})
            assert st == 202 and obj["accepted"]
            st, obj = req("GET", "/clients/5")
            assert st == 200 and obj["pending"] == ["register"]
            st, obj = req("POST", "/clients",
                          {"id": 6, "reservation": -1.0})
            assert st == 400 and "client 6" in obj["error"]
            st, obj = req("GET", "/clients")
            assert st == 200 and obj["pending_ops"] == 1
            # the scrape side still serves, counters published
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=5) as resp:
                text = resp.read().decode()
            assert "dmclock_lc_live_clients" in text

    def test_supervised_scrape_remounts_across_rebinds(self):
        """A churn job's scrape endpoint carries the admin control
        API (supervisor wires it through _ScrapeCtl.on_bind), and a
        port-loss rebind re-mounts it -- mounts are per-server, so
        without the re-mount a recovered endpoint would serve scrape
        but 404 the control plane."""
        plane = _plane()
        scr = SV._ScrapeCtl(
            0, 0, lambda srv: mount_admin_api(srv, plane))

        def get_clients():
            url = f"http://127.0.0.1:{scr.port}/clients"
            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.status, json.loads(resp.read())

        try:
            scr.tick(0, None)
            assert scr.scrape is not None
            st, obj = get_clients()
            assert st == 200 and obj["live_clients"] == 0
            # the injector's drop_scrape path: port yanked, next tick
            # rebinds on the pinned port
            scr.scrape.close()
            scr.scrape = None
            scr.tick(1, None)
            assert scr.scrape is not None and scr.rebinds == 1
            st, obj = get_clients()
            assert st == 200 and "registrations" in obj
        finally:
            scr.close()


# ----------------------------------------------------------------------
# WAL acceptance journal
# ----------------------------------------------------------------------

class TestAdminWAL:
    def test_accept_fsyncs_then_boundary_applies_once(self, tmp_path):
        spec = make_spec("flash_crowd", total_ids=8, base_lam=1.0)
        plane = LifecyclePlane(spec, workdir=str(tmp_path))
        seq = plane.accept({"op": "register", "cid": 2, "r": 0.0,
                            "w": 2.0, "l": 0.0, "apply_at": None})
        assert seq == 0
        assert (tmp_path / "admin.wal").exists()
        # accepted ops live in the WAL, not in memory, until a
        # boundary ingests them (crash between accept and apply loses
        # nothing)
        assert plane.pending == []
        state = init_state(spec["capacity0"], 8)
        plane.boundary(state, 0, 2)
        assert plane.wal_seen == 1
        assert 2 in plane.slots.slot_of
        # a resumed plane with the cursor PAST the line replays nothing
        plane2 = LifecyclePlane.load(plane.encode(), spec,
                                     workdir=str(tmp_path))
        plane2._wal_ingest()
        assert plane2.pending == []

    def test_resume_before_ingest_replays_exactly_once(self, tmp_path):
        spec = make_spec("flash_crowd", total_ids=8, base_lam=1.0)
        wal_append(tmp_path, {"op": "update", "cid": 0, "r": 0.0,
                              "w": 9.0, "l": 0.0, "apply_at": 2})
        plane = LifecyclePlane(spec, workdir=str(tmp_path))
        state = init_state(spec["capacity0"], 8)
        state, _ = plane.boundary(state, 0, 2)     # ingests, not due
        assert plane.wal_seen == 1
        assert len(plane.pending) == 1
        # crash here: reload from the encoded snapshot -- the pending
        # op rides it, the WAL line is NOT re-ingested
        plane2 = LifecyclePlane.load(plane.encode(), spec,
                                     workdir=str(tmp_path))
        state, _ = plane2.boundary(state, 2, 2)
        assert plane2.snapshot()["qos_updates"] == 1
        assert plane2.pending == []

    def test_wal_append_validates_like_the_live_path(self, tmp_path):
        with pytest.raises(ValueError, match="client 4"):
            wal_append(tmp_path, {"op": "register", "cid": 4,
                                  "r": -1.0, "w": 1.0, "l": 0.0})
        assert not (tmp_path / "admin.wal").exists()

    def test_out_of_id_space_cid_rejected_at_accept(self):
        """The id space is spec-bounded: arrival draws and the
        canonical digest views are [total_ids]-wide, so an
        out-of-space registration must 400 at accept, not IndexError
        the serving loop at the next ingest."""
        plane = _plane()                      # total_ids=8
        with pytest.raises(ValueError, match=r"outside.*\[0, 8\)"):
            plane.accept({"op": "register", "cid": 8, "r": 0.0,
                          "w": 1.0, "l": 0.0, "apply_at": None})
        api = AdminAPI(plane)
        st, _, out = api.handler("POST", "/clients",
                                 json.dumps({"id": 99}).encode())
        assert st == 400
        assert "outside" in json.loads(out.decode())["error"]

    def test_poisoned_wal_line_dropped_not_fatal(self, tmp_path,
                                                 capsys):
        """A hand-written WAL bypasses accept(); an out-of-space line
        must be dropped deterministically at ingest (every
        incarnation drops the same line), not crash every resume."""
        spec = make_spec("flash_crowd", total_ids=8, base_lam=1.0)
        wal_append(tmp_path, {"op": "register", "cid": 500,
                              "r": 0.0, "w": 1.0, "l": 0.0,
                              "apply_at": None})
        plane = LifecyclePlane(spec, workdir=str(tmp_path))
        state = init_state(spec["capacity0"], 8)
        state, _ = plane.boundary(state, 0, 2)
        assert 500 not in plane.slots.slot_of
        assert plane.wal_seen == 1            # cursor still advances
        assert "dropping WAL line" in capsys.readouterr().err
        # and the serving-loop mapping stays intact
        plane.map_counts(np.zeros(8, dtype=np.int32))

    def test_wal_seq_is_cheap_and_monotone(self, tmp_path):
        """Sequence numbers come from the cached line count (one file
        scan total), not a per-accept re-read of the journal."""
        spec = make_spec("flash_crowd", total_ids=8, base_lam=1.0)
        plane = LifecyclePlane(spec, workdir=str(tmp_path))
        seqs = [plane.accept({"op": "update", "cid": 0, "r": 0.0,
                              "w": float(w), "l": 0.0,
                              "apply_at": None})
                for w in range(1, 5)]
        assert seqs == [0, 1, 2, 3]
        # a fresh plane over the same workdir continues the numbering
        plane2 = LifecyclePlane(spec, workdir=str(tmp_path))
        assert plane2.accept({"op": "update", "cid": 0, "r": 0.0,
                              "w": 9.0, "l": 0.0,
                              "apply_at": None}) == 4

    def test_wal_mode_pending_visible_to_api_checks(self, tmp_path):
        """In WAL mode an accepted op lives only in the file until
        the next boundary -- the API's existence/duplicate checks
        must still see it: POST then PUT is 202/202 (not 404), and a
        duplicate POST is 409 (not a second 202)."""
        spec = make_spec("flash_crowd", total_ids=8, base_lam=1.0)
        plane = LifecyclePlane(spec, workdir=str(tmp_path))
        api = AdminAPI(plane)

        def call(method, path, body):
            st, _, out = api.handler(method, path,
                                     json.dumps(body).encode())
            return st, json.loads(out.decode())

        st, _ = call("POST", "/clients", {"id": 5, "weight": 2.0})
        assert st == 202
        assert plane.pending == []            # journaled, not staged
        st, _ = call("PUT", "/clients/5/qos", {"weight": 8.0})
        assert st == 202
        st, _ = call("POST", "/clients", {"id": 5})
        assert st == 409
        st, _, out = api.handler("GET", "/clients/5", b"")
        obj = json.loads(out.decode())
        assert st == 200 and "register" in obj["pending"]
        # both ops apply exactly once at the boundary
        state = init_state(spec["capacity0"], 8)
        plane.boundary(state, 0, 2)
        assert 5 in plane.slots.slot_of
        assert plane.qos[5][1] == 8.0
        assert plane.snapshot()["qos_updates"] == 1


# ----------------------------------------------------------------------
# queue departed-clients report
# ----------------------------------------------------------------------

class TestQueueDepartedReport:
    def test_erase_folds_final_ledger_row_before_zeroing(self):
        from dmclock_tpu.core import ClientInfo as CI
        from dmclock_tpu.core.recs import ReqParams
        from dmclock_tpu.engine import TpuPullPriorityQueue

        clock = [0.0]
        infos = {c: CI(0.0, 1.0, 0.0, client=c) for c in range(3)}
        q = TpuPullPriorityQueue(lambda c: infos[c], capacity=4,
                                 ring_capacity=8, idle_age_s=5.0,
                                 erase_age_s=10.0,
                                 monotonic_clock=lambda: clock[0])
        t = 10 ** 9
        for i in range(4):
            q.add_request(("r", i), i % 2, ReqParams(1, 1),
                          time_ns=t, cost=1)
        served = 0
        for _ in range(4):
            if q.pull_request(now_ns=t + served * 10).is_retn():
                served += 1
        assert served == 4
        rows_before = q.ledger_rows()
        q.do_clean()                       # mark point at t=0
        clock[0] = 11.0
        q.do_clean()                       # past erase_age -> erase
        assert q.slot_recycles == 2
        dep = dict(q.departed_report(drain=False))
        assert set(dep) == {0, 1}
        for cid, row in dep.items():
            assert np.array_equal(row, rows_before[cid])
            assert int(row[0]) == 2        # LED_OPS: 2 ops each
        # ledger rows zeroed AFTER the fold
        assert all(int(r.sum()) == 0
                   for r in q.ledger_rows().values())
        assert len(q.departed_report()) == 2   # drain clears
        assert q.departed_report() == []

    def test_recycle_counter_is_published(self):
        from dmclock_tpu.core import ClientInfo as CI
        from dmclock_tpu.engine import TpuPullPriorityQueue
        from dmclock_tpu.obs.registry import MetricsRegistry

        q = TpuPullPriorityQueue(
            lambda c: CI(0.0, 1.0, 0.0, client=c), capacity=4,
            ring_capacity=8)
        reg = MetricsRegistry()
        q.register_metrics(reg)
        text = reg.prometheus()
        assert "dmclock_slot_recycles_total" in text


# ----------------------------------------------------------------------
# grow-on-demand checkpoint shapes
# ----------------------------------------------------------------------

class TestGrowableCheckpoints:
    def test_strict_shapes_off_restores_grown_payload(self, tmp_path):
        from dmclock_tpu.utils import checkpoint as ckpt_mod

        small = {"a": np.zeros((2, 3), dtype=np.int64),
                 "n": np.int64(0)}
        grown = {"a": np.arange(12, dtype=np.int64).reshape(4, 3),
                 "n": np.int64(7)}
        path = str(tmp_path / "ck.npz")
        ckpt_mod.save_pytree(path, grown)
        with pytest.raises(ckpt_mod.CheckpointCorruptError):
            ckpt_mod.restore_pytree(path, small)
        out = ckpt_mod.restore_pytree(path, small,
                                      strict_shapes=False)
        assert np.array_equal(out["a"], grown["a"])
        assert int(out["n"]) == 7

    def test_rank_and_dtype_still_gate(self, tmp_path):
        from dmclock_tpu.utils import checkpoint as ckpt_mod

        path = str(tmp_path / "ck.npz")
        ckpt_mod.save_pytree(path, {"a": np.zeros(4, dtype=np.int64)})
        with pytest.raises(ckpt_mod.CheckpointCorruptError):
            ckpt_mod.restore_pytree(
                path, {"a": np.zeros((1, 1), dtype=np.int64)},
                strict_shapes=False)
        with pytest.raises(ckpt_mod.CheckpointCorruptError):
            ckpt_mod.restore_pytree(
                path, {"a": np.zeros(1, dtype=np.float64)},
                strict_shapes=False)

    def test_trailing_dims_still_gate(self, tmp_path):
        """The relaxation is AXIS-0 ONLY: growth and the journals
        vary exactly there, so a fixed trailing width (ring columns,
        histogram buckets, journal row layout) changing between runs
        must still raise, not restore silently wrong-shaped."""
        from dmclock_tpu.utils import checkpoint as ckpt_mod

        path = str(tmp_path / "ck.npz")
        ckpt_mod.save_pytree(
            path, {"q": np.zeros((4, 16), dtype=np.int64)})
        # grown axis 0, same ring width: restores
        out = ckpt_mod.restore_pytree(
            path, {"q": np.zeros((2, 16), dtype=np.int64)},
            strict_shapes=False)
        assert out["q"].shape == (4, 16)
        # same rank, different ring width: still corrupt
        with pytest.raises(ckpt_mod.CheckpointCorruptError):
            ckpt_mod.restore_pytree(
                path, {"q": np.zeros((4, 8), dtype=np.int64)},
                strict_shapes=False)

    def test_plane_encode_load_round_trip(self):
        spec = make_spec("churn_storm", total_ids=8, base_lam=1.0)
        plane = LifecyclePlane(spec)
        state = init_state(spec["capacity0"], 8)
        for b in (0, 2, 4):
            state, _ = plane.boundary(state, b, 2)
        plane.accept({"op": "update", "cid": 0, "r": 0.0, "w": 2.0,
                      "l": 0.0, "apply_at": 99})
        enc = plane.encode()
        plane2 = LifecyclePlane.load(
            {k: np.asarray(v) for k, v in enc.items()}, spec)
        assert plane2.snapshot() == plane.snapshot()
        assert plane2.pending == plane.pending
        assert np.array_equal(plane2.streak, plane.streak)
        assert plane2.qos == plane.qos

    def test_empty_leaves_structure_matches_encode(self):
        empty = LifecyclePlane.empty_leaves()
        enc = _plane().encode()
        assert set(empty) == set(enc)
        for k in empty:
            assert np.asarray(empty[k]).dtype == \
                np.asarray(enc[k]).dtype, k
            assert np.asarray(empty[k]).ndim == \
                np.asarray(enc[k]).ndim, k


# ----------------------------------------------------------------------
# churn spec scripts
# ----------------------------------------------------------------------

class TestChurnSpecs:
    def test_unknown_scenario_and_params_raise(self):
        with pytest.raises(ValueError, match="unknown churn"):
            make_spec("nope", total_ids=4)
        with pytest.raises(ValueError, match="params"):
            make_spec("diurnal", total_ids=4, crowd_at=3)

    def test_lam_shared_between_dynamic_and_static(self):
        spec = make_spec("flash_crowd", total_ids=12, seed=3)
        st = static_variant(spec)
        for e in (0, 7, 8, 15, 16, 30):
            assert np.array_equal(churn_mod.lam_vector(spec, e),
                                  churn_mod.lam_vector(st, e))

    def test_flash_crowd_rates_follow_the_script(self):
        spec = make_spec("flash_crowd", total_ids=12, base_lam=1.0,
                         crowd_at=8, crowd_len=4, crowd_lam_x=4.0)
        lam0 = churn_mod.lam_vector(spec, 0)
        assert lam0[:6].tolist() == [1.0] * 6    # base cohort on
        assert lam0[6:].tolist() == [0.0] * 6    # crowd not started
        lam8 = churn_mod.lam_vector(spec, 8)
        assert lam8[6:].tolist() == [4.0] * 6
        assert churn_mod.lam_vector(spec, 12)[6:].tolist() == [0.0] * 6

    def test_peak_ids(self):
        spec = make_spec("churn_storm", total_ids=12, gens=3,
                         stride=2, life=3)
        assert churn_mod.peak_ids(spec) == 8     # 2 gens overlap
        assert churn_mod.peak_ids(
            make_spec("diurnal", total_ids=12)) == 12

    def test_events_register_in_ascending_cid_order(self):
        spec = make_spec("churn_storm", total_ids=12, gens=3,
                         stride=2, life=4)
        regs = [e["cid"] for e in churn_mod.events(spec, 0, 2)
                if e["op"] == "register"]
        assert regs == sorted(regs) == [0, 1, 2, 3]

    def test_limit_thrash_flips_the_victim_limit(self):
        spec = make_spec("limit_thrash", total_ids=8, victim_frac=0.5,
                         tight_limit=40.0)
        ups = [e for e in churn_mod.events(spec, 2, 2)
               if e["op"] == "update"]
        assert {e["cid"] for e in ups} == {4, 5, 6, 7}
        assert all(e["l"] == 40.0 for e in ups)
        ups2 = [e for e in churn_mod.events(spec, 4, 2)
                if e["op"] == "update"]
        assert all(e["l"] == 0.0 for e in ups2)
