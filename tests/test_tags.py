"""Tag-algebra unit tests (model: reference test_dmclock_server.cc
tag-calculation coverage, e.g. delayed_tag_calc :273-316)."""

import pytest

from dmclock_tpu.core import (ClientInfo, MAX_TAG, MIN_TAG, NS_PER_SEC,
                              RequestTag, ZERO_TAG, rate_to_inv_ns, tag_calc)

S = NS_PER_SEC


class TestTagCalc:
    def test_zero_increment_pins_high(self):
        assert tag_calc(5 * S, 3 * S, 0, 1, True, 1) == MAX_TAG

    def test_zero_increment_pins_low(self):
        assert tag_calc(5 * S, 3 * S, 0, 1, False, 1) == MIN_TAG

    def test_advances_from_prev(self):
        # rate 1 op/s -> 1s per unit; prev 3s + (0 dist + 1 cost) = 4s
        inv = rate_to_inv_ns(1.0)
        assert tag_calc(2 * S, 3 * S, inv, 0, True, 1) == 4 * S

    def test_floors_at_now(self):
        inv = rate_to_inv_ns(1.0)
        assert tag_calc(10 * S, 3 * S, inv, 0, True, 1) == 10 * S

    def test_dist_val_and_cost_both_charge(self):
        inv = rate_to_inv_ns(2.0)  # 0.5s per unit
        # prev 0 + 0.5 * (3 + 2) = 2.5s
        assert tag_calc(0, 0, inv, 3, True, 2) == 2_500_000_000

    def test_rate_inverse_rounding_is_canonical(self):
        # 3 ops/s does not divide 1e9; all backends must round identically
        assert rate_to_inv_ns(3.0) == 333333333
        assert rate_to_inv_ns(0.0) == 0


class TestRequestTagRecurrence:
    def test_axes_use_correct_dist_values(self):
        # reservation uses rho; proportion and limit use delta
        # (reference dmclock_server.h:163-180)
        info = ClientInfo(1.0, 1.0, 1.0)
        tag = RequestTag.from_prev(ZERO_TAG, info, delta=5, rho=2,
                                   time_ns=0, cost=1)
        assert tag.reservation == 3 * S   # (2 + 1) * 1s
        assert tag.proportion == 6 * S    # (5 + 1) * 1s
        assert tag.limit == 6 * S

    def test_zero_rates_pin(self):
        info = ClientInfo(0.0, 1.0, 0.0)
        tag = RequestTag.from_prev(ZERO_TAG, info, 0, 0, time_ns=S, cost=1)
        assert tag.reservation == MAX_TAG
        assert tag.limit == MIN_TAG
        assert tag.proportion == S  # max(1s, 0 + 1s*(0+1)) = 1s

    def test_no_reservation_nor_weight_asserts(self):
        # reference asserts reservation < max || proportion < max (:182)
        info = ClientInfo(0.0, 0.0, 1.0)
        with pytest.raises(AssertionError):
            RequestTag.from_prev(ZERO_TAG, info, 0, 0, time_ns=S, cost=1)

    def test_anticipation_backdates_within_window(self):
        # arrival within timeout of previous arrival is backdated
        # (reference :159-161); weight 100 -> 0.01s increments so the
        # wall-time floor dominates and the backdating is observable
        info = ClientInfo(0.0, 100.0, 0.0)
        prev = RequestTag(reservation=0, proportion=S, limit=0,
                          arrival=1 * S)
        ant = int(0.1 * S)
        t2 = int(1.08 * S)
        with_ant = RequestTag.from_prev(prev, info, 0, 0, t2, 1, ant)
        without = RequestTag.from_prev(prev, info, 0, 0, t2, 1, 0)
        assert with_ant.proportion == int(1.01 * S)  # prev + 0.01s
        assert without.proportion == int(1.08 * S)   # floored at arrival
        # outside the window: no backdating
        t3 = int(2.5 * S)
        far = RequestTag.from_prev(prev, info, 0, 0, t3, 1, ant)
        assert far.proportion == int(2.5 * S)

    def test_cost_scales_increment(self):
        info = ClientInfo(4.0, 0.0, 0.0)  # 0.25s per unit
        tag = RequestTag.from_prev(ZERO_TAG, info, delta=0, rho=0,
                                   time_ns=0, cost=3)
        assert tag.reservation == 750_000_000

    def test_zero_cost_asserts(self):
        info = ClientInfo(1.0, 1.0, 0.0)
        with pytest.raises(AssertionError):
            RequestTag.from_prev(ZERO_TAG, info, 0, 0, 0, cost=0)


def test_proportion_floor_fixup():
    # double-check the max(time, prev+inc) floor on the proportion axis
    info = ClientInfo(0.0, 1.0, 0.0)
    tag = RequestTag.from_prev(ZERO_TAG, info, 0, 0, time_ns=S, cost=1)
    # max(1s, 0 + 1s) = 1s
    assert tag.proportion == S


class TestSaturation:
    # regression (code-review finding): absurd inputs must saturate,
    # never collide with sentinels or overflow int64 backends
    def test_tiny_rate_saturates_not_asserts(self):
        from dmclock_tpu.core.timebase import MAX_INV_NS, ORGANIC_TAG_CAP
        info = ClientInfo(0.0, 1e-10, 0.0)
        assert info.weight_inv_ns == MAX_INV_NS
        tag = RequestTag.from_prev(ZERO_TAG, info, 0, 0, time_ns=0, cost=1)
        assert tag.proportion == MAX_INV_NS < MAX_TAG

    def test_organic_tag_capped_below_sentinel(self):
        from dmclock_tpu.core.timebase import (MAX_INV_NS,
                                               ORGANIC_TAG_CAP)
        prev = ORGANIC_TAG_CAP - 5
        got = tag_calc(0, prev, MAX_INV_NS, 2**31, True, 1)
        assert got == ORGANIC_TAG_CAP < MAX_TAG

    def test_huge_delta_charge_saturates(self):
        from dmclock_tpu.core.timebase import MAX_CHARGE_UNITS
        inv = rate_to_inv_ns(1.0)
        got = tag_calc(0, 0, inv, 2**32 - 1, True, 5)
        assert got == inv * MAX_CHARGE_UNITS
