"""North-star gate: full-simulation request-ordering parity.

BASELINE.json demands the TPU backend reproduce CPU ``dmc_sim`` request
ordering.  Both backends implement the same int64 total order, so the
complete service trace -- (virtual time, server, client, phase, cost)
per op -- must match EXACTLY, not statistically.  Run on scaled-down
versions of the acceptance configs for test-time reasons; ``bench.py``
and the full configs cover scale.
"""

import pytest

from dmclock_tpu.sim import ClientGroup, ServerGroup, SimConfig
from dmclock_tpu.sim.dmc_sim import run_sim


def make_cfg(clients, servers, **kw):
    return SimConfig(client_groups=len(clients), server_groups=len(servers),
                     cli_group=clients, srv_group=servers, **kw)


def trace_of(cfg, model, seed=7):
    sim = run_sim(cfg, model=model, seed=seed, record_trace=True)
    return sim


def assert_traces_equal(cfg, seed=7):
    cpu = trace_of(cfg, "dmclock-delayed", seed)
    tpu = trace_of(cfg, "dmclock-tpu", seed)
    assert len(cpu.trace) == len(tpu.trace) > 0
    for i, (a, b) in enumerate(zip(cpu.trace, tpu.trace)):
        assert a == b, f"trace diverges at op {i}: cpu={a} tpu={b}"
    # aggregate phase split must agree too
    for cid in cpu.clients:
        ca, cb = cpu.clients[cid].stats, tpu.clients[cid].stats
        assert (ca.reservation_ops, ca.priority_ops) == \
            (cb.reservation_ops, cb.priority_ops)


@pytest.mark.slow
def test_trace_parity_example_shape():
    """Scaled-down dmc_sim_example.conf: 4 QoS groups incl. limited and
    weighted clients, one 160-iops server, hard limit."""
    groups = [
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=0,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=1,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=2,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=50.0,
                    client_weight=2.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40, client_wait_s=0,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_req_cost=3,
                    client_server_select_range=1),
    ]
    servers = [ServerGroup(server_count=1, server_iops=160,
                           server_threads=1)]
    assert_traces_equal(make_cfg(groups, servers,
                                 server_soft_limit=False))


@pytest.mark.slow
def test_trace_parity_100th_shape():
    """Scaled-down dmc_sim_100th.conf: reservation-heavy mix with a
    cost-3 client on one server, soft limit (AtLimit.ALLOW)."""
    groups = [
        ClientGroup(client_count=2, client_total_ops=50,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=10.0, client_limit=0.0,
                    client_weight=2.0, client_req_cost=3,
                    client_server_select_range=1),
    ]
    servers = [ServerGroup(server_count=1, server_iops=120,
                           server_threads=2)]
    assert_traces_equal(make_cfg(groups, servers, server_soft_limit=True))


def test_trace_parity_multi_server():
    """Two servers, clients spreading requests: exercises the rho/delta
    protocol feeding different queues."""
    groups = [
        ClientGroup(client_count=3, client_total_ops=60,
                    client_iops_goal=120, client_outstanding_ops=8,
                    client_reservation=15.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=2),
    ]
    servers = [ServerGroup(server_count=2, server_iops=80,
                           server_threads=1)]
    assert_traces_equal(make_cfg(groups, servers,
                                 server_soft_limit=False))
