"""Simulation-harness tests: config parsing, determinism, and
closed-loop QoS behavior (the sim binaries double as integration tests
in the reference; same idea here, but assertable because virtual time
is deterministic)."""

import os
import textwrap

import pytest

from dmclock_tpu import models
from dmclock_tpu.core import NS_PER_SEC
from dmclock_tpu.sim import (ClientGroup, ServerGroup, SimConfig,
                             parse_config_file, Simulation)
from dmclock_tpu.sim.dmc_sim import run_sim

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(clients, servers, **global_kw):
    return SimConfig(client_groups=len(clients), server_groups=len(servers),
                     cli_group=clients, srv_group=servers, **global_kw)


class TestConfig:
    def test_parse_example_conf(self):
        cfg = parse_config_file(os.path.join(REPO, "configs",
                                             "dmc_sim_example.conf"))
        assert cfg.client_groups == 4
        assert cfg.server_groups == 1
        assert not cfg.server_soft_limit
        assert not cfg.server_random_selection
        assert cfg.cli_group[2].client_weight == 2.0
        assert cfg.cli_group[3].client_req_cost == 3
        assert cfg.cli_group[1].client_wait_s == 5.0
        assert cfg.srv_group[0].server_iops == 160.0
        assert cfg.total_clients == 4
        assert cfg.total_servers == 1

    def test_defaults_match_reference(self, tmp_path):
        # a minimal file inherits reference struct defaults
        # (reference config.h:44-53, :92-97)
        p = tmp_path / "min.conf"
        p.write_text(textwrap.dedent("""\
            [global]
            client_groups = 1
            server_groups = 1
        """))
        cfg = parse_config_file(str(p))
        g = cfg.cli_group[0]
        assert (g.client_count, g.client_total_ops, g.client_iops_goal) == \
            (100, 1000, 50.0)
        assert (g.client_reservation, g.client_limit, g.client_weight) == \
            (20.0, 60.0, 1.0)
        s = cfg.srv_group[0]
        assert (s.server_count, s.server_iops, s.server_threads) == \
            (100, 40.0, 1)


class TestSimBehavior:
    def test_weight_share_under_contention(self):
        # one 100-iops server; two greedy clients with weights 1:3 and
        # no reservation/limit -> service split ~1:3
        cfg = make_cfg(
            [ClientGroup(client_count=1, client_total_ops=500,
                         client_iops_goal=200, client_outstanding_ops=32,
                         client_reservation=0, client_limit=0,
                         client_weight=1, client_server_select_range=1),
             ClientGroup(client_count=1, client_total_ops=1500,
                         client_iops_goal=200, client_outstanding_ops=32,
                         client_reservation=0, client_limit=0,
                         client_weight=3, client_server_select_range=1)],
            [ServerGroup(server_count=1, server_iops=100,
                         server_threads=1)])
        sim = run_sim(cfg)
        # while both are active (first ~20s), ratio should be ~1:3;
        # compare ops completed when the faster client finishes
        c0, c1 = sim.clients[0], sim.clients[1]
        t1 = c1.stats.finish_time_ns
        c0_at_t1 = sum(1 for t in c0.stats.completion_times_ns if t <= t1)
        ratio = c1.stats.ops_completed / max(1, c0_at_t1)
        assert 2.4 < ratio < 3.6, f"weight ratio {ratio}"

    def test_limit_caps_throughput(self):
        # client wants 200 iops from a 400-iops server but limit=50;
        # hard limit (soft limit would legitimately break the cap on an
        # idle server via AtLimit.ALLOW)
        cfg = make_cfg(
            [ClientGroup(client_count=1, client_total_ops=400,
                         client_iops_goal=200, client_outstanding_ops=32,
                         client_reservation=0, client_limit=50,
                         client_weight=1, client_server_select_range=1)],
            [ServerGroup(server_count=1, server_iops=400,
                         server_threads=1)],
            server_soft_limit=False)
        sim = run_sim(cfg)
        c = sim.clients[0]
        dur_s = c.stats.finish_time_ns / NS_PER_SEC
        rate = c.stats.ops_completed / dur_s
        assert 45 <= rate <= 55, f"limited rate {rate}"

    def test_reservation_floor_under_contention(self):
        # low-weight client with r=40 keeps >=40 iops against a heavy
        # competitor on a 100-iops server
        cfg = make_cfg(
            [ClientGroup(client_count=1, client_total_ops=400,
                         client_iops_goal=100, client_outstanding_ops=32,
                         client_reservation=40, client_limit=0,
                         client_weight=0.001, client_server_select_range=1),
             ClientGroup(client_count=1, client_total_ops=2000,
                         client_iops_goal=200, client_outstanding_ops=64,
                         client_reservation=0, client_limit=0,
                         client_weight=10, client_server_select_range=1)],
            [ServerGroup(server_count=1, server_iops=100,
                         server_threads=1)])
        sim = run_sim(cfg)
        c0 = sim.clients[0]
        dur_s = c0.stats.finish_time_ns / NS_PER_SEC
        rate = c0.stats.ops_completed / dur_s
        assert rate >= 36, f"reserved client got only {rate} ops/s"
        assert c0.stats.reservation_ops > c0.stats.priority_ops

    def test_trace_determinism(self):
        cfg = make_cfg(
            [ClientGroup(client_count=3, client_total_ops=200,
                         client_iops_goal=100, client_outstanding_ops=16,
                         client_reservation=10, client_limit=60,
                         client_weight=1, client_server_select_range=2)],
            [ServerGroup(server_count=2, server_iops=80,
                         server_threads=1)])
        s1 = run_sim(cfg, record_trace=True, seed=7)
        s2 = run_sim(cfg, record_trace=True, seed=7)
        assert s1.trace == s2.trace
        assert len(s1.trace) == 600

    def test_delayed_model_also_completes(self):
        cfg = make_cfg(
            [ClientGroup(client_count=2, client_total_ops=150,
                         client_iops_goal=100, client_outstanding_ops=8,
                         client_reservation=10, client_limit=0,
                         client_weight=1, client_server_select_range=1)],
            [ServerGroup(server_count=1, server_iops=100,
                         server_threads=2)])
        sim = run_sim(cfg, model="dmclock-delayed")
        assert sum(c.stats.ops_completed
                   for c in sim.clients.values()) == 300

    def test_ssched_fifo_baseline(self):
        cfg = make_cfg(
            [ClientGroup(client_count=2, client_total_ops=100,
                         client_iops_goal=100, client_outstanding_ops=8,
                         client_server_select_range=1)],
            [ServerGroup(server_count=1, server_iops=150,
                         server_threads=1)])
        sim = run_sim(cfg, model="ssched")
        assert sum(c.stats.ops_completed
                   for c in sim.clients.values()) == 200

    def test_report_formats(self):
        cfg = make_cfg(
            [ClientGroup(client_count=1, client_total_ops=50,
                         client_iops_goal=100, client_outstanding_ops=8,
                         client_server_select_range=1)],
            [ServerGroup(server_count=1, server_iops=100)])
        sim = run_sim(cfg)
        text = sim.report().format(show_intervals=True)
        assert "average" in text and "ops" in text


class TestMultiServerTracking:
    def test_rho_delta_flow_across_servers(self):
        # with several servers, delta/rho piggybacking keeps per-server
        # views consistent: every client's tracker has entries for the
        # servers it used, and reservation phases dominate when under
        # reservation
        cfg = make_cfg(
            [ClientGroup(client_count=4, client_total_ops=200,
                         client_iops_goal=80, client_outstanding_ops=16,
                         client_reservation=30, client_limit=0,
                         client_weight=1, client_server_select_range=4)],
            [ServerGroup(server_count=4, server_iops=50,
                         server_threads=1)])
        sim = run_sim(cfg)
        for c in sim.clients.values():
            assert len(c.tracker.server_map) == 4
            assert c.stats.ops_completed == 200


class TestSschedPush:
    def test_push_surface(self):
        """ssched push mode (reference ssched_server.h:184-191): FIFO
        dispatch through handle_f under a can_handle gate."""
        from dmclock_tpu.sim.ssched import SimpleQueue
        handled = []
        gate = {"open": False}
        q = SimpleQueue(can_handle_f=lambda: gate["open"],
                        handle_f=lambda c, r, p, cost:
                        handled.append((c, r, cost)))
        q.add_request("a", 1, cost=2)
        q.add_request("b", 2)
        assert handled == []           # gated
        gate["open"] = True
        q.request_completed()          # server signals capacity
        assert handled == [(1, "a", 2)]   # ONE dispatch per completion
        q.request_completed()
        assert handled == [(1, "a", 2), (2, "b", 1)]  # strict FIFO
        assert q.empty()
