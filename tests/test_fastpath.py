"""Differential tests for the speculative fast path.

``fastpath`` promises bit-identity with the exact serial engine
(``kernels.engine_run`` under AtLimit::Wait, fixed ``now`` per batch):
speculation either commits a batch the serial engine would have produced
verbatim, or fails and leaves state untouched.  These tests pin that
contract -- the same contract the headline benchmark rests on --
including the edge cases speculation is most likely to get wrong:
fewer-than-k eligible clients (underfull), equal-tag ties at the
k-boundary, reservation<->weight regime flips, depth-1 clients, and
commit-prefix semantics of the scanned epoch.

Ordering spec being checked = the oracle's total order
(``core/scheduler.py``), itself pinned to reference
``dmclock_server.h:1115-1186`` by the oracle test suite.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.core.scheduler import AtLimit
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue, kernels
from dmclock_tpu.engine.fastpath import (attempt_fast_batch,
                                         make_fast_runner,
                                         scan_fast_epoch,
                                         speculate_resv_batch,
                                         speculate_weight_batch)
from dmclock_tpu.engine.state import EngineState

S = NS_PER_SEC


def states_equal(a: EngineState, b: EngineState) -> bool:
    return all(bool(jnp.array_equal(x, y))
               for x, y in zip(a, b))


def assert_states_equal(a: EngineState, b: EngineState):
    for name, x, y in zip(EngineState._fields, a, b):
        assert bool(jnp.array_equal(x, y)), \
            f"state field {name} diverged:\n{x}\nvs\n{y}"


def serial_run(state, now, k, anticipation_ns=0):
    st, _, decs = kernels.engine_run(
        state, jnp.int64(now), k, allow_limit_break=False,
        anticipation_ns=anticipation_ns, advance_now=False)
    return st, jax.device_get(decs)


def build_state(infos, adds, *, capacity=64, ring=64,
                anticipation_ns=0) -> EngineState:
    """EngineState populated via the queue's own ingest path.

    ``adds`` = list of (client, time_ns, cost, delta, rho).
    """
    q = TpuPullPriorityQueue(lambda c: infos[c],
                             anticipation_timeout_ns=anticipation_ns,
                             capacity=capacity, ring_capacity=ring)
    for client, t, cost, delta, rho in adds:
        q.add_request(("r", client, t), client, ReqParams(delta, rho),
                      time_ns=t, cost=cost)
    with q.data_mtx:
        q._flush()
    return q.state


def check_fast_vs_serial(state, now, k, *, anticipation_ns=0,
                         expect_fast=None):
    """One batch through the fast runner vs the exact serial engine."""
    run = make_fast_runner(k, anticipation_ns=anticipation_ns)
    fast_state, fast_decs, used_fast = run(state, jnp.int64(now))
    ser_state, ser_decs = serial_run(state, now, k, anticipation_ns)
    if expect_fast is not None:
        assert used_fast == expect_fast, \
            f"expected used_fast={expect_fast}, got {used_fast}"
    fd = jax.device_get(fast_decs)
    assert np.array_equal(fd.slot, ser_decs.slot)
    assert np.array_equal(fd.cost, ser_decs.cost)
    if used_fast:
        # a committed speculation means every serial decision RETURNING
        assert (ser_decs.type == kernels.RETURNING).all()
        assert np.array_equal(fd.phase, ser_decs.phase)
    assert_states_equal(fast_state, ser_state)
    return fast_state, used_fast


# ----------------------------------------------------------------------
# underfull batches (the round-1 advisor bug): fewer real candidates
# than k must fail speculation in BOTH regimes
# ----------------------------------------------------------------------

def test_underfull_weight_regime_falls_back():
    infos = {c: ClientInfo(0, 1, 0) for c in range(3)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(3)]
    state = build_state(infos, adds, capacity=8)
    fb = attempt_fast_batch(state, jnp.int64(1000 * S), 4,
                            anticipation_ns=0)
    assert not bool(fb.ok)
    assert_states_equal(fb.state, state)  # untouched on failure
    # depth must never go negative through the full runner either
    st, _ = check_fast_vs_serial(state, 1000 * S, 4, expect_fast=False)
    assert int(jnp.min(st.depth)) >= 0


def test_underfull_resv_regime_falls_back():
    infos = {c: ClientInfo(10, 0, 0) for c in range(3)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(3)]
    state = build_state(infos, adds, capacity=8)
    fb = speculate_resv_batch(state, jnp.int64(1000 * S), 4,
                              anticipation_ns=0)
    assert not bool(fb.ok)
    assert_states_equal(fb.state, state)
    st, _ = check_fast_vs_serial(state, 1000 * S, 4, expect_fast=False)
    assert int(jnp.min(st.depth)) >= 0


def test_exactly_k_candidates_commits():
    """k real candidates is the boundary case that must still commit."""
    infos = {c: ClientInfo(0, 1 + (c % 2), 0) for c in range(4)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(4)]
    state = build_state(infos, adds, capacity=8)
    check_fast_vs_serial(state, 5 * S, 4, expect_fast=True)


# ----------------------------------------------------------------------
# regime correctness on deep backlogs
# ----------------------------------------------------------------------

def deep_state(infos, depth, t=1 * S, capacity=64):
    adds = [(c, t, 1, 1, 1) for _ in range(depth) for c in infos]
    return build_state(infos, adds, capacity=capacity)


def test_weight_regime_matches_serial():
    """Mixed weights: speculation commits when consecutive winners are
    distinct and legitimately falls back when the serial engine would
    serve one client twice in-batch; parity must hold either way."""
    infos = {c: ClientInfo(0, 1 + (c % 3), 0) for c in range(16)}
    state = deep_state(infos, depth=8)
    st = state
    n_fast = 0
    for _ in range(4):
        st, used = check_fast_vs_serial(st, 10 * S, 8)
        n_fast += used
    assert n_fast >= 1, "speculation never committed -- tests vacuous"


def test_resv_regime_matches_serial():
    infos = {c: ClientInfo(5 + c % 3, 0, 0) for c in range(16)}
    state = deep_state(infos, depth=8)
    # far-future now: every reservation tag eligible (deep constraint
    # backlog)
    st = state
    n_fast = 0
    for _ in range(4):
        st, used = check_fast_vs_serial(st, 10_000 * S, 8)
        n_fast += used
    assert n_fast >= 1, "speculation never committed -- tests vacuous"


def test_equal_tag_ties_at_k_boundary():
    """All clients share one weight and one arrival: every proportion
    tag is equal, so the k-boundary is a pure tie group resolved by
    creation order.  Exactness at the boundary is the hard case."""
    infos = {c: ClientInfo(0, 2, 0) for c in range(12)}
    state = deep_state(infos, depth=6)
    st = state
    for _ in range(6):
        st, _ = check_fast_vs_serial(st, 8 * S, 8, expect_fast=True)


def test_resv_ties_at_k_boundary():
    infos = {c: ClientInfo(3, 0, 0) for c in range(12)}
    state = deep_state(infos, depth=6)
    st = state
    for _ in range(6):
        st, _ = check_fast_vs_serial(st, 9_000 * S, 8, expect_fast=True)


def test_depth_one_clients():
    """Depth-1 clients leave the window by emptying -- the has_more
    branch of the one-serve check."""
    infos = {c: ClientInfo(0, 1, 0) for c in range(10)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(10)]
    state = build_state(infos, adds, capacity=16)
    check_fast_vs_serial(state, 4 * S, 8, expect_fast=True)


def test_single_client_deep_queue_falls_back():
    """One client with many requests violates one-serve-per-client, so
    speculation must fail and the serial engine must take over."""
    infos = {0: ClientInfo(0, 1, 0), 1: ClientInfo(0, 1, 0)}
    adds = [(c, 1 * S, 1, 1, 1) for _ in range(16) for c in (0, 1)]
    state = build_state(infos, adds, capacity=8)
    check_fast_vs_serial(state, 100 * S, 8, expect_fast=False)


def test_limited_clients_excluded():
    """Clients whose head limit is in the future are not ready; with
    too few ready candidates speculation fails; with enough it must
    serve only ready ones, matching serial."""
    infos = {}
    for c in range(16):
        if c < 8:
            infos[c] = ClientInfo(0, 1, 0)          # unlimited
        else:
            infos[c] = ClientInfo(0, 1, 1000.0)     # high limit: ready
    state = deep_state(infos, depth=4)
    check_fast_vs_serial(state, 2 * S, 8)


# ----------------------------------------------------------------------
# regime flips + fallback-resume through the runner
# ----------------------------------------------------------------------

def test_regime_flip_resv_to_weight():
    """Reservation backlog drains at a far-future now, then weight
    phase takes over: the runner must track the flip batch by batch."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(8)}
    state = deep_state(infos, depth=8)
    run = make_fast_runner(4)
    st = state
    # fixed now: the reservation phase drains (~4 eligible serves per
    # client before its tag passes now), then weight takes over
    now = 4 * S
    phases = []
    for i in range(14):
        ser_state, ser_decs = serial_run(st, now, 4)
        st2, decs, used = run(st, jnp.int64(now))
        fd = jax.device_get(decs)
        assert np.array_equal(fd.slot, ser_decs.slot)
        if used:
            assert np.array_equal(fd.phase, ser_decs.phase)
        phases.extend(int(p) for p in jax.device_get(ser_decs.phase)[
            jax.device_get(ser_decs.type) == kernels.RETURNING])
        assert_states_equal(st2, ser_state)
        st = st2
    assert 0 in phases and 1 in phases, \
        "workload never exercised both phases"


def test_fallback_then_resume():
    """A batch that falls back must leave state so the NEXT batch can
    speculate again -- the steady-state recovery path."""
    infos = {c: ClientInfo(0, 1, 0) for c in range(6)}
    # client 0 heavily queued => early batches violate one-serve
    adds = [(0, 1 * S, 1, 1, 1) for _ in range(12)]
    adds += [(c, 1 * S, 1, 1, 1) for _ in range(4) for c in range(1, 6)]
    state = build_state(infos, adds, capacity=8)
    run = make_fast_runner(4)
    st = state
    now = 50 * S
    used_seq = []
    for _ in range(8):
        ser_state, ser_decs = serial_run(st, now, 4)
        st2, decs, used = run(st, jnp.int64(now))
        used_seq.append(used)
        fd = jax.device_get(decs)
        assert np.array_equal(fd.slot, ser_decs.slot)
        assert_states_equal(st2, ser_state)
        st = st2
    assert False in used_seq, "expected at least one fallback"


# ----------------------------------------------------------------------
# scan_fast_epoch: commit-prefix semantics
# ----------------------------------------------------------------------

def test_epoch_commit_prefix_all_ok():
    infos = {c: ClientInfo(0, 1 + (c % 2), 0) for c in range(16)}
    state = deep_state(infos, depth=16)
    m, k = 4, 8
    ep = scan_fast_epoch(state, jnp.int64(20 * S), m, k,
                         anticipation_ns=0)
    ok = jax.device_get(ep.ok)
    assert ok.all()
    # replay serially: epoch output must equal m sequential k-batches
    st = state
    for i in range(m):
        ser_state, ser_decs = serial_run(st, 20 * S, k)
        assert np.array_equal(jax.device_get(ep.slot)[i], ser_decs.slot)
        assert np.array_equal(jax.device_get(ep.phase)[i],
                              ser_decs.phase)
        st = ser_state
    assert_states_equal(ep.state, st)


def test_epoch_commit_prefix_stops_at_failure():
    """Backlog shallower than m*k: the epoch must stop at the first
    failed speculation and the returned state must be the exact serial
    prefix -- later batches must not commit even if they would pass."""
    infos = {c: ClientInfo(0, 1, 0) for c in range(8)}
    state = deep_state(infos, depth=3)   # 24 requests total
    m, k = 8, 8                          # 64 asked
    ep = scan_fast_epoch(state, jnp.int64(5 * S), m, k,
                         anticipation_ns=0)
    ok = jax.device_get(ep.ok)
    n_ok = int(ok.sum())
    assert 0 < n_ok < m
    # prefix property: no commit after the first failure
    first_fail = int(np.argmin(ok))
    assert not ok[first_fail:].any()
    # state equals the serial replay of the committed prefix
    st = state
    for _ in range(n_ok):
        st, _ = serial_run(st, 5 * S, k)
    assert_states_equal(ep.state, st)
    assert int(jnp.min(ep.state.depth)) >= 0


def test_epoch_on_empty_state_commits_nothing():
    infos = {0: ClientInfo(0, 1, 0)}
    state = build_state(infos, [], capacity=8)
    ep = scan_fast_epoch(state, jnp.int64(1 * S), 4, 4,
                         anticipation_ns=0)
    assert not jax.device_get(ep.ok).any()
    assert_states_equal(ep.state, state)


# ----------------------------------------------------------------------
# randomized differential fuzz
# ----------------------------------------------------------------------

@pytest.mark.parametrize("seed", [11, 12, 13, 14, 15])
def test_fuzz_fast_runner_matches_serial(seed):
    rng = random.Random(seed)
    n_clients = rng.randint(4, 24)
    infos = {}
    for c in range(n_clients):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 4), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4),
                                  rng.uniform(3, 8))
        else:
            # equal weights: maximal tie pressure
            infos[c] = ClientInfo(0, 2, 0)
    adds = []
    t = 1 * S
    for step in range(rng.randint(20, 120)):
        c = rng.randrange(n_clients)
        t += rng.randint(0, S // 4)
        delta = rng.randint(1, 5)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=32)

    k = rng.choice([2, 4, 8])
    run = make_fast_runner(k)
    now = t + rng.randint(0, 10) * S
    st = state
    n_fast = 0
    for _ in range(10):
        ser_state, ser_decs = serial_run(st, now, k)
        st2, decs, used = run(st, jnp.int64(now))
        fd = jax.device_get(decs)
        assert np.array_equal(fd.slot, ser_decs.slot), \
            f"seed={seed} now={now} k={k}"
        assert np.array_equal(fd.cost, ser_decs.cost)
        assert_states_equal(st2, ser_state)
        st = st2
        n_fast += used
        now += rng.randint(1, 3) * S
    assert int(jnp.min(st.depth)) >= 0


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_fuzz_epoch_matches_serial(seed):
    rng = random.Random(seed)
    n_clients = rng.randint(8, 20)
    infos = {c: ClientInfo(rng.choice([0, 1, 2]),
                           rng.choice([1, 2, 3]), 0)
             for c in range(n_clients)}
    # ensure every client has either r or w
    for c in range(n_clients):
        if infos[c].reservation == 0 and infos[c].weight == 0:
            infos[c] = ClientInfo(0, 1, 0)
    depth = rng.randint(1, 8)
    state = deep_state(infos, depth=depth, capacity=32)
    m, k = rng.choice([(2, 4), (4, 4), (3, 8)])
    now = rng.randint(2, 2000) * S
    ep = scan_fast_epoch(state, jnp.int64(now), m, k, anticipation_ns=0)
    ok = jax.device_get(ep.ok)
    n_ok = int(ok.sum())
    # prefix property
    if n_ok < m:
        first_fail = int(np.argmin(ok))
        assert not ok[first_fail:].any()
    st = state
    for i in range(n_ok):
        ser_state, ser_decs = serial_run(st, now, k)
        assert np.array_equal(jax.device_get(ep.slot)[i], ser_decs.slot)
        st = ser_state
    assert_states_equal(ep.state, st)
    assert int(jnp.min(ep.state.depth)) >= 0


def test_pallas_rotate_matches_xla():
    """The Pallas ring-rotate kernel (interpret mode off-TPU) must be
    bit-identical to the XLA barrel shift for random rings/offsets."""
    import numpy as np
    from dmclock_tpu.engine.fastpath import (_rotate_rows_pallas,
                                             _rotate_rows_xla)

    rng = np.random.default_rng(9)
    for n, q, w in ((700, 16, 5), (2500, 128, 32), (100, 64, 64)):
        ring = jnp.asarray(rng.integers(-(1 << 50), 1 << 50, (n, q)),
                           jnp.int64)
        q0 = jnp.asarray(rng.integers(0, q, n), jnp.int32)
        a = _rotate_rows_xla(ring, q0, w)
        b = _rotate_rows_pallas(ring, q0, w, interpret=True)
        assert a.shape == b.shape == (w, n)
        assert (np.asarray(a) == np.asarray(b)).all(), (n, q, w)


def test_anticipation_differential():
    """Nonzero anticipation window: arrivals within the window of the
    previous arrival are backdated (reference :159-161) and the fast
    runner must stay bit-identical to the serial engine through the
    backdated tag recurrence."""
    rng = random.Random(17)
    ant = S // 2                     # 0.5 s anticipation window
    infos = {c: ClientInfo(0, 1.0 + c % 3, 0) for c in range(12)}
    adds = []
    t = S
    for i in range(120):
        c = rng.randrange(12)
        # backdating triggers when an arrival lands within `ant` of the
        # SAME client's previous arrival (kernels._make_tag); with 12
        # clients and these global gaps ~16 of the 120 arrivals do
        t += rng.choice([ant // 4, ant // 3, 2 * ant])
        adds.append((c, t, rng.randint(1, 3), rng.randint(1, 4), 1))
    state = build_state(infos, adds, capacity=16, ring=32,
                        anticipation_ns=ant)
    now = t + 1000 * S
    st = state
    n_fast = 0
    for _ in range(6):
        st, used = check_fast_vs_serial(st, now, 8,
                                        anticipation_ns=ant)
        n_fast += int(used)
    # the comparison must not degrade to serial-vs-serial: at least one
    # batch has to commit through the speculative path
    assert n_fast >= 1, "no batch used the fast path"
